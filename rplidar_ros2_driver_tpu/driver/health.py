"""Per-stream health FSM + capped-backoff primitives.

The single-stream driver already has a fault-tolerant scan loop
(node/fsm.py — the reference's 5-state recovery FSM).  This module is
its FLEET-scale counterpart: one :class:`StreamHealth` state machine per
lidar, driven by the per-tick signals the fleet seams already produce
(frame counts, malformed-frame counts, completed revolutions), so a
single wedged or garbage-spewing stream degrades to an idle padding
lane instead of stalling or poisoning the fleet tick.

::

    HEALTHY ──bad──► SUSPECT ──bad×K──► QUARANTINED
       ▲                │                    │ backoff expires
       │◄──clean×P──────┘                    │ + device-health probe OK
       │                                     ▼
       └────────clean×P────────────── RECOVERING
                                             │ bad (relapse)
                                             └──────► QUARANTINED (escalated)

"bad" is a corrupt-frame ratio over a sliding tick window above
threshold, OR a tick-starvation age (frames arriving, or a previously
streaming stream gone silent, with no completed revolution) above
threshold.  Quarantine release is gated on a capped exponential backoff
with deterministic jitter (:class:`BackoffPolicy`) and, when a probe is
wired, on the device answering ``GET_DEVICE_HEALTH`` with OK/WARNING
(protocol/constants.HealthStatus — the reference's CHECK_HEALTH gate,
applied per stream on re-entry).

:class:`FleetHealth` packages N of these behind the two-call tick API
the service seams use (``begin_tick`` masks quarantined streams onto
the existing idle padding lanes — same compiled program, zero
recompiles; ``end_tick`` feeds the observations back), with transition
hooks the service binds to its quarantine-checkpoint / rejoin-restore
machinery (parallel/service.py).

Everything here is host-side bookkeeping: no jax, no device work, and a
``clock`` injection point so tests (and the chaos bench) drive the
backoff deterministically.
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import random
import time
from collections import deque
from typing import Callable, Optional

from rplidar_ros2_driver_tpu.protocol.constants import (
    ANS_PAYLOAD_BYTES,
    HealthStatus,
)

log = logging.getLogger("rplidar_tpu.health")


class BackoffPolicy:
    """Capped exponential backoff with jitter — the ONE retry-delay
    helper (reconnects, quarantine release, probe retries), so no loop
    in this codebase hand-rolls an unbounded ``while True: sleep(k)``
    again (graftlint GL009 flags exactly that shape).

    ``next_delay()`` returns ``min(base * 2**(attempt-1), max) *
    (1 + jitter * u)`` with ``u ∈ [0, 1)`` from a private RNG —
    seedable for deterministic tests, decorrelated across streams in
    production so a fleet-wide outage does not produce a synchronized
    reconnect storm.
    """

    def __init__(
        self,
        base_s: float = 0.5,
        max_s: float = 30.0,
        jitter: float = 0.1,
        seed: Optional[int] = None,
    ) -> None:
        if base_s <= 0 or max_s < base_s:
            raise ValueError("need 0 < base_s <= max_s")
        if not (0.0 <= jitter <= 1.0):
            raise ValueError("jitter must be within [0, 1]")
        self.base_s = float(base_s)
        self.max_s = float(max_s)
        self.jitter = float(jitter)
        self._rng = random.Random(seed)
        self.attempt = 0
        self.last_delay_s = 0.0

    def next_delay(self) -> float:
        self.attempt += 1
        # exponent clamp BEFORE the cap: 2.0**1024 overflows a Python
        # float, and a device that stays dead for hours walks the
        # attempt counter that far — an OverflowError here would crash
        # the retry loop it exists to pace (fleet tick included)
        raw = min(
            self.base_s * (2.0 ** min(self.attempt - 1, 63)), self.max_s
        )
        self.last_delay_s = raw * (1.0 + self.jitter * self._rng.random())
        return self.last_delay_s

    def reset(self) -> None:
        self.attempt = 0
        self.last_delay_s = 0.0


class StreamState(enum.Enum):
    HEALTHY = "healthy"
    SUSPECT = "suspect"
    QUARANTINED = "quarantined"
    RECOVERING = "recovering"


@dataclasses.dataclass(frozen=True)
class HealthConfig:
    """Thresholds (defaults mirror core/config.DriverParams.health_*)."""

    window_ticks: int = 8        # sliding observation window (ticks)
    corrupt_ratio: float = 0.5   # malformed/total over the window -> bad
    starvation_ticks: int = 16   # ticks w/o a completed revolution -> bad
    suspect_ticks: int = 4       # consecutive bad ticks -> QUARANTINED
    probation_ticks: int = 4     # consecutive clean ticks -> HEALTHY
    backoff_base_s: float = 0.5
    backoff_max_s: float = 30.0
    backoff_jitter: float = 0.1
    seed: int = 0                # jitter seed base (stream id mixed in)

    # minimum window frames before the corrupt ratio means anything (a
    # single malformed frame in an otherwise-quiet window is noise, not
    # a sick cable) — internal, not a deployment knob
    MIN_RATIO_FRAMES = 4

    def __post_init__(self) -> None:
        # the same domain DriverParams.validate() enforces, applied at
        # THIS boundary too: direct construction (bench, tests, any
        # embedder wiring FleetHealth by hand) must not silently
        # disable health signals — window_ticks=0 would make the
        # observation deque discard everything, a >1 corrupt_ratio is
        # unreachable, and BackoffPolicy rejects its own domain below
        if self.window_ticks < 1:
            raise ValueError("window_ticks must be >= 1")
        if not (0.0 < self.corrupt_ratio <= 1.0):
            raise ValueError("corrupt_ratio must be within (0, 1]")
        if self.starvation_ticks < 1:
            raise ValueError("starvation_ticks must be >= 1")
        if self.suspect_ticks < 1:
            raise ValueError("suspect_ticks must be >= 1")
        if self.probation_ticks < 1:
            raise ValueError("probation_ticks must be >= 1")
        if self.backoff_base_s <= 0 or self.backoff_max_s < (
            self.backoff_base_s
        ):
            raise ValueError("need 0 < backoff_base_s <= backoff_max_s")
        if not (0.0 <= self.backoff_jitter <= 1.0):
            raise ValueError("backoff_jitter must be within [0, 1]")

    @classmethod
    def from_params(cls, params) -> "HealthConfig":
        """The one params -> HealthConfig mapping (DriverParams carries
        these as ``health_*`` so the YAML stays the deployment source
        of truth)."""
        g = lambda k, d: getattr(params, k, d)  # noqa: E731 - tiny local
        return cls(
            window_ticks=int(g("health_window_ticks", 8)),
            corrupt_ratio=float(g("health_corrupt_ratio", 0.5)),
            starvation_ticks=int(g("health_starvation_ticks", 16)),
            suspect_ticks=int(g("health_suspect_ticks", 4)),
            probation_ticks=int(g("health_probation_ticks", 4)),
            backoff_base_s=float(g("health_backoff_base_s", 0.5)),
            backoff_max_s=float(g("health_backoff_max_s", 30.0)),
            backoff_jitter=float(g("health_backoff_jitter", 0.1)),
        )


def probe_ok(result) -> bool:
    """Interpret a health probe's answer: bools pass through; enums/ints
    follow the reference's CHECK_HEALTH gate (OK/WARNING pass, ERROR and
    silence fail — node/fsm.py:_do_check_health)."""
    if result is None:
        return False
    if isinstance(result, bool):
        return result
    try:
        return int(result) <= int(HealthStatus.WARNING)
    except (TypeError, ValueError):
        return False


def gated_release(clock, release_at: float, probe, backoff) -> tuple:
    """The ONE backoff+probe re-admission gate, shared by
    :meth:`StreamHealth.poll_release` and
    :meth:`ShardHealth.poll_readmit` so the semantics (probe exceptions
    count as failures, :func:`probe_ok` interpretation, escalated — not
    reset — re-arm on failure) cannot drift between the two FSMs.

    Returns ``("wait", None)`` while the backoff has not expired,
    ``("failed", rearm_at)`` when the probe refused (caller records the
    failure + new release time), or ``("pass", None)``.
    """
    if clock() < release_at:
        return "wait", None
    if probe is not None:
        try:
            result = probe()
        except Exception:
            result = None
        if not probe_ok(result):
            return "failed", clock() + backoff.next_delay()
    return "pass", None


class StreamHealth:
    """One stream's health FSM (see module diagram).

    Drive it with one :meth:`observe` per admitted tick and one
    :meth:`poll_release` per tick while quarantined.  Both return the
    ``(old, new)`` state transition when one fired, else None.
    """

    def __init__(
        self,
        cfg: Optional[HealthConfig] = None,
        stream_id: int = 0,
        *,
        clock: Callable[[], float] = time.monotonic,
        probe: Optional[Callable[[], object]] = None,
    ) -> None:
        self.cfg = cfg or HealthConfig()
        self.stream_id = stream_id
        self._clock = clock
        self.probe = probe
        self.state = StreamState.HEALTHY
        self.backoff = BackoffPolicy(
            self.cfg.backoff_base_s,
            self.cfg.backoff_max_s,
            self.cfg.backoff_jitter,
            seed=self.cfg.seed * 65537 + stream_id,
        )
        self.release_at = 0.0
        self._window: deque = deque(maxlen=self.cfg.window_ticks)
        self._bad_streak = 0
        self._clean_streak = 0
        self._starved = 0
        self._streaming = False  # has this stream ever completed a rev?
        # cumulative counters (diagnostics surface)
        self.frames_seen = 0
        self.frames_malformed = 0
        self.completions = 0
        self.quarantines = 0
        self.recoveries = 0
        self.reconnect_failures = 0
        self.last_reason = ""

    # -- signal evaluation ------------------------------------------------

    def _corrupt_ratio(self) -> float:
        frames = sum(f for f, _ in self._window)
        if frames < self.cfg.MIN_RATIO_FRAMES:
            return 0.0
        return sum(m for _, m in self._window) / frames

    def _evaluate(self, frames: int, malformed: int, completed: int) -> bool:
        """Fold one tick's signals in; returns whether the tick is bad."""
        self.frames_seen += frames
        self.frames_malformed += malformed
        self._window.append((frames, malformed))
        if completed > 0:
            self.completions += completed
            self._starved = 0
            self._streaming = True
        elif frames > 0 or self._streaming:
            # frames without revolutions, or a previously streaming
            # stream gone silent: the starvation age ticks up.  A stream
            # that never streamed and sends nothing is idle, not sick.
            self._starved += 1
        ratio = self._corrupt_ratio()
        if ratio > self.cfg.corrupt_ratio:
            self.last_reason = f"corrupt-frame ratio {ratio:.2f}"
            return True
        if self._starved > self.cfg.starvation_ticks:
            self.last_reason = f"starved {self._starved} ticks"
            return True
        return False

    def _clear_signals(self) -> None:
        self._window.clear()
        self._bad_streak = 0
        self._clean_streak = 0
        self._starved = 0

    # -- transitions ------------------------------------------------------

    def _to(self, new: StreamState) -> tuple:
        old, self.state = self.state, new
        log.info(
            "stream %d health: %s -> %s (%s)",
            self.stream_id, old.value, new.value, self.last_reason or "-",
        )
        return (old, new)

    def _enter_quarantine(self) -> tuple:
        self.quarantines += 1
        self.release_at = self._clock() + self.backoff.next_delay()
        self._clear_signals()
        return self._to(StreamState.QUARANTINED)

    def observe(
        self, frames: int, malformed: int, completed: int
    ) -> Optional[tuple]:
        """One admitted tick's signals (quarantined streams are masked
        upstream and must not be fed here)."""
        if self.state is StreamState.QUARANTINED:
            return None  # masked: nothing reaches a quarantined stream
        bad = self._evaluate(frames, malformed, completed)
        if bad:
            self._bad_streak += 1
            self._clean_streak = 0
        else:
            self._clean_streak += 1
            self._bad_streak = 0
        if self.state is StreamState.HEALTHY:
            if bad:
                return self._to(StreamState.SUSPECT)
        elif self.state is StreamState.SUSPECT:
            if self._bad_streak >= self.cfg.suspect_ticks:
                return self._enter_quarantine()
            if self._clean_streak >= self.cfg.probation_ticks:
                self.last_reason = "probation clean"
                return self._to(StreamState.HEALTHY)
        elif self.state is StreamState.RECOVERING:
            if bad:
                # relapse: straight back, with the backoff ESCALATED
                # (the policy was not reset on release)
                return self._enter_quarantine()
            if self._clean_streak >= self.cfg.probation_ticks:
                self.last_reason = "recovered"
                self.recoveries += 1
                self.backoff.reset()
                return self._to(StreamState.HEALTHY)
        return None

    def poll_release(self) -> Optional[tuple]:
        """Quarantine-release gate, called once per tick while
        quarantined: after the backoff expires, the stream must also
        pass its device-health probe (when wired) before it re-enters as
        RECOVERING.  A failed probe re-arms the (escalated) backoff."""
        if self.state is not StreamState.QUARANTINED:
            return None
        verdict, rearm = gated_release(
            self._clock, self.release_at, self.probe, self.backoff
        )
        if verdict == "wait":
            return None
        if verdict == "failed":
            self.reconnect_failures += 1
            self.release_at = rearm
            self.last_reason = (
                f"health probe failed x{self.reconnect_failures}"
            )
            return None
        self._clear_signals()
        self.last_reason = "backoff expired, probe ok"
        return self._to(StreamState.RECOVERING)

    @property
    def admitted(self) -> bool:
        """Whether this stream's bytes enter the fleet tick (quarantined
        streams ride the padding buckets as idle lanes instead)."""
        return self.state is not StreamState.QUARANTINED

    def status(self) -> dict:
        """Host dict for /diagnostics-style reporting."""
        return {
            "state": self.state.value,
            "frames": self.frames_seen,
            "malformed": self.frames_malformed,
            "completions": self.completions,
            "quarantines": self.quarantines,
            "recoveries": self.recoveries,
            "reconnect_failures": self.reconnect_failures,
            "backoff_attempt": self.backoff.attempt,
            "backoff_s": round(self.backoff.last_delay_s, 3),
            "reason": self.last_reason,
        }


# ---------------------------------------------------------------------------
# shard-level health (the fleet-of-fleets layer above the per-stream FSM)
# ---------------------------------------------------------------------------


class ShardState(enum.Enum):
    UP = "up"
    SUSPECT = "suspect"
    LOST = "lost"
    READMITTING = "readmitting"


@dataclasses.dataclass(frozen=True)
class ShardHealthConfig:
    """Thresholds for one SHARD's FSM (defaults mirror
    core/config.DriverParams.shard_*).  A shard is a whole engine pair
    hosting several streams (parallel/service.ElasticFleetService), so
    its failure signals differ from a stream's: a dead dispatch
    (heartbeat) is LOST immediately — there is no "maybe" about an
    exception out of the compiled tick — while fleet-wide tick
    starvation (zero completions anywhere while bytes are offered on
    its lanes — or while a previously streaming shard sits silent,
    like a sick cable: the upstream going quiet is a loss signal too)
    walks UP -> SUSPECT -> LOST like a sick cable would."""

    starvation_ticks: int = 8    # all-lane dry ticks while offered -> bad
    suspect_ticks: int = 4       # consecutive bad ticks -> LOST
    probation_ticks: int = 4     # clean ticks in READMITTING -> UP
    backoff_base_s: float = 1.0  # re-admission probe backoff
    backoff_max_s: float = 60.0
    backoff_jitter: float = 0.1
    seed: int = 0

    def __post_init__(self) -> None:
        if self.starvation_ticks < 1:
            raise ValueError("starvation_ticks must be >= 1")
        if self.suspect_ticks < 1:
            raise ValueError("suspect_ticks must be >= 1")
        if self.probation_ticks < 1:
            raise ValueError("probation_ticks must be >= 1")
        if self.backoff_base_s <= 0 or self.backoff_max_s < (
            self.backoff_base_s
        ):
            raise ValueError("need 0 < backoff_base_s <= backoff_max_s")
        if not (0.0 <= self.backoff_jitter <= 1.0):
            raise ValueError("backoff_jitter must be within [0, 1]")

    @classmethod
    def from_params(cls, params) -> "ShardHealthConfig":
        """The one params -> ShardHealthConfig mapping (DriverParams
        carries these as ``shard_*``, param/rplidar.yaml is the
        deployment source of truth)."""
        g = lambda k, d: getattr(params, k, d)  # noqa: E731 - tiny local
        return cls(
            starvation_ticks=int(g("shard_starvation_ticks", 8)),
            suspect_ticks=int(g("shard_suspect_ticks", 4)),
            probation_ticks=int(g("shard_probation_ticks", 4)),
            backoff_base_s=float(g("shard_backoff_base_s", 1.0)),
            backoff_max_s=float(g("shard_backoff_max_s", 60.0)),
            backoff_jitter=float(g("shard_backoff_jitter", 0.1)),
        )


class ShardHealth:
    """One shard's health FSM::

        UP ──starved×K──► SUSPECT ──bad×S──► LOST ◄────────┐
        ▲        │ clean                        │ backoff   │ relapse
        │◄───────┘                              │ + probe OK│ (escalated)
        │                                       ▼           │
        └──────────clean×P────────────── READMITTING ───────┘

    plus the hard edge every state except LOST has: ``force_lost`` (a
    heartbeat failure — the shard's dispatch raised, or the chaos
    schedule killed it) goes straight to LOST, no probation.

    Drive it with one :meth:`observe` per tick while hosting streams
    and one :meth:`poll_readmit` per tick while LOST.  Host-side only
    (no jax), ``clock``-injected like :class:`StreamHealth`.
    """

    def __init__(
        self,
        cfg: Optional[ShardHealthConfig] = None,
        shard_id: int = 0,
        *,
        clock: Callable[[], float] = time.monotonic,
        probe: Optional[Callable[[], object]] = None,
    ) -> None:
        self.cfg = cfg or ShardHealthConfig()
        self.shard_id = shard_id
        self._clock = clock
        self.probe = probe
        self.state = ShardState.UP
        self.backoff = BackoffPolicy(
            self.cfg.backoff_base_s,
            self.cfg.backoff_max_s,
            self.cfg.backoff_jitter,
            seed=self.cfg.seed * 131071 + shard_id,
        )
        self.release_at = 0.0
        self._starved = 0
        self._bad_streak = 0
        self._clean_streak = 0
        self._streaming = False  # any lane ever completed a revolution?
        # cumulative counters (diagnostics surface)
        self.losses = 0
        self.readmissions = 0
        self.probe_failures = 0
        self.last_reason = ""

    def _to(self, new: ShardState) -> tuple:
        old, self.state = self.state, new
        log.info(
            "shard %d health: %s -> %s (%s)",
            self.shard_id, old.value, new.value, self.last_reason or "-",
        )
        return (old, new)

    def _enter_lost(self) -> tuple:
        self.losses += 1
        self.release_at = self._clock() + self.backoff.next_delay()
        self._starved = 0
        self._bad_streak = 0
        self._clean_streak = 0
        # the loss wipes the shard's engines (cold_reset): it is
        # factually a fresh shard, so "has it ever streamed" restarts
        # too.  Carrying _streaming across the loss would make an
        # empty re-admitted shard (rebalance found no stream to give
        # it) starve on silence and relapse forever — a permanent
        # LOST/READMITTING flap on healthy hardware
        self._streaming = False
        return self._to(ShardState.LOST)

    def force_lost(self, reason: str = "heartbeat failure") -> Optional[tuple]:
        """Hard kill: dispatch raised / chaos schedule / operator drain.
        No probation — the shard's device state is gone either way."""
        if self.state is ShardState.LOST:
            return None
        self.last_reason = reason
        return self._enter_lost()

    def observe(self, offered: bool, completed: int) -> Optional[tuple]:
        """One hosted tick's aggregate signals: whether any lane was
        offered bytes, and how many revolutions completed across all
        lanes.  LOST shards host nothing and must not be fed here."""
        if self.state is ShardState.LOST:
            return None
        if completed > 0:
            self._starved = 0
            self._streaming = True
            bad = False
        elif offered or self._streaming:
            self._starved += 1
            # READMITTING gets ONE extra starvation window: the
            # migrate-back reset every victim's decode carries, so the
            # first revolution structurally needs up to a full window
            # of dry ticks before silence is evidence of anything — a
            # healthy shard must not be condemned to relapse on every
            # re-admission.  A dead shard still relapses (promotion
            # needs PRODUCTIVE ticks), one window later.
            limit = self.cfg.starvation_ticks * (
                2 if self.state is ShardState.READMITTING else 1
            )
            bad = self._starved > limit
            if bad:
                self.last_reason = f"shard starved {self._starved} ticks"
        else:
            bad = False  # nothing offered, never streamed: idle shard
        if bad:
            self._bad_streak += 1
            self._clean_streak = 0
        elif completed > 0 or not (offered or self._streaming):
            self._clean_streak += 1
            self._bad_streak = 0
        else:
            # offered but dry, below the starvation threshold: neither
            # clean nor bad.  A clean streak must be PRODUCTIVE ticks
            # (or true idle) — otherwise a probe-passing-but-dead shard
            # fills probation_ticks of silence before starvation can
            # fire, gets promoted with its backoff reset, and flaps
            # forever at the base delay with streams migrated onto it
            # each cycle (the relapse edge below would be dead code
            # whenever probation_ticks <= starvation_ticks)
            self._bad_streak = 0
        if self.state is ShardState.UP:
            if bad:
                return self._to(ShardState.SUSPECT)
        elif self.state is ShardState.SUSPECT:
            if self._bad_streak >= self.cfg.suspect_ticks:
                return self._enter_lost()
            if self._clean_streak >= self.cfg.probation_ticks:
                self.last_reason = "probation clean"
                return self._to(ShardState.UP)
        elif self.state is ShardState.READMITTING:
            if bad:
                # relapse: straight back, backoff ESCALATED (not reset)
                return self._enter_lost()
            if self._clean_streak >= self.cfg.probation_ticks:
                self.last_reason = "readmitted"
                self.readmissions += 1
                self.backoff.reset()
                return self._to(ShardState.UP)
        return None

    def poll_readmit(self) -> Optional[tuple]:
        """Re-admission gate, once per tick while LOST: after the
        capped backoff expires the shard must also pass its probe (when
        wired — the pod wires the chaos schedule's liveness there, a
        real deployment wires a device/host health check) before it
        re-enters as READMITTING.  A failed probe re-arms the
        escalated backoff."""
        if self.state is not ShardState.LOST:
            return None
        verdict, rearm = gated_release(
            self._clock, self.release_at, self.probe, self.backoff
        )
        if verdict == "wait":
            return None
        if verdict == "failed":
            self.probe_failures += 1
            self.release_at = rearm
            self.last_reason = (
                f"readmission probe failed x{self.probe_failures}"
            )
            return None
        self._starved = 0
        self._bad_streak = 0
        self._clean_streak = 0
        self.last_reason = "backoff expired, probe ok"
        return self._to(ShardState.READMITTING)

    @property
    def hosting(self) -> bool:
        """Whether this shard can host streams (LOST shards host
        nothing; their lanes were evacuated)."""
        return self.state is not ShardState.LOST

    def status(self) -> dict:
        """Host dict for /diagnostics-style reporting."""
        return {
            "state": self.state.value,
            "losses": self.losses,
            "readmissions": self.readmissions,
            "probe_failures": self.probe_failures,
            "backoff_attempt": self.backoff.attempt,
            "backoff_s": round(self.backoff.last_delay_s, 3),
            "reason": self.last_reason,
        }


def _count_item(item) -> tuple[int, int]:
    """(frames, malformed) of one per-stream tick item — the SAME
    length-based malformed test every ingest backend applies
    (ANS_PAYLOAD_BYTES), so the health view matches what the engines
    will actually drop."""
    if not item:
        return 0, 0
    ans, frames = item
    expect = ANS_PAYLOAD_BYTES.get(ans)
    n = len(frames)
    if expect is None:
        return n, n  # unknown answer type: every frame is garbage
    bad = sum(1 for f, _ts in frames if len(f) != expect)
    return n, bad


def _count_completed(out) -> int:
    """Completions in one per-stream tick result (the seams return
    either one Optional[FilterOutput] or a list of revolutions)."""
    if out is None:
        return 0
    if isinstance(out, (list, tuple)):
        return len(out)
    return 1


class FleetHealth:
    """N per-stream FSMs behind the fleet tick seam.

    Usage (parallel/service.py wires this automatically)::

        masked = health.begin_tick(items)   # release polls + masking
        outs = <dispatch masked tick>
        health.end_tick(outs)               # observations + transitions

    ``on_quarantine(i)`` fires when stream i enters QUARANTINED (the
    service snapshots that stream's filter+map state there);
    ``on_recover(i)`` fires when its backoff+probe gate releases it into
    RECOVERING (the service restores the checkpoint there, BEFORE the
    tick's bytes flow again).  ``mask`` is the observation-free variant
    for backlog drains (catch-up is not steady ticking).
    """

    def __init__(
        self,
        streams: int,
        cfg: Optional[HealthConfig] = None,
        *,
        clock: Callable[[], float] = time.monotonic,
        probes: Optional[dict] = None,
        on_quarantine: Optional[Callable[[int], None]] = None,
        on_recover: Optional[Callable[[int], None]] = None,
        record_masks: bool = False,
    ) -> None:
        if streams < 1:
            raise ValueError("need at least one stream")
        cfg = cfg or HealthConfig()
        probes = probes or {}
        self.cfg = cfg
        self.health = [
            StreamHealth(cfg, i, clock=clock, probe=probes.get(i))
            for i in range(streams)
        ]
        self.on_quarantine = on_quarantine
        self.on_recover = on_recover
        self.tick_no = 0
        # transition log: (tick_no, stream, old.value, new.value)
        self.events: list[tuple] = []
        # per-tick admitted-mask log (opt-in: tests + chaos parity
        # harnesses replay the exact masked stream into the golden path)
        self.mask_log: Optional[list] = [] if record_masks else None
        self._pending_obs: Optional[list] = None

    @property
    def streams(self) -> int:
        return len(self.health)

    def set_probe(self, i: int, probe: Optional[Callable]) -> None:
        self.health[i].probe = probe

    def admitted(self) -> list[bool]:
        return [h.admitted for h in self.health]

    def _record(self, i: int, tr: Optional[tuple]) -> Optional[tuple]:
        if tr is not None:
            self.events.append((self.tick_no, i, tr[0].value, tr[1].value))
        return tr

    def begin_tick(self, items: list) -> list:
        """Release polls, then mask quarantined streams' items to None
        (the idle-lane encoding the padding buckets already compile
        for).  Stashes the admitted streams' (frames, malformed) counts
        for :meth:`end_tick`."""
        if len(items) != self.streams:
            raise ValueError(
                f"expected {self.streams} per-stream items, got {len(items)}"
            )
        for i, h in enumerate(self.health):
            tr = self._record(i, h.poll_release())
            if tr is not None and self.on_recover is not None:
                # restore BEFORE this tick's bytes flow into the engine
                self.on_recover(i)
        masked, obs = [], []
        for i, h in enumerate(self.health):
            if not h.admitted:
                masked.append(None)
                obs.append(None)
            else:
                masked.append(items[i])
                obs.append(_count_item(items[i]))
        self._pending_obs = obs
        if self.mask_log is not None:
            self.mask_log.append([h.admitted for h in self.health])
        return masked

    def end_tick(self, outs: Optional[list]) -> None:
        """Feed the tick's per-stream results back and run transitions.
        ``outs`` follows the seam's shape (Optional[FilterOutput] or a
        revolution list per stream); None means the tick produced no
        result vector (treated as zero completions everywhere)."""
        obs, self._pending_obs = self._pending_obs, None
        if obs is None:
            obs = [
                (0, 0) if h.admitted else None for h in self.health
            ]
        for i, h in enumerate(self.health):
            if obs[i] is None:
                continue  # was quarantined this tick: masked, unobserved
            frames, malformed = obs[i]
            completed = _count_completed(outs[i]) if outs is not None else 0
            tr = self._record(i, h.observe(frames, malformed, completed))
            if (
                tr is not None
                and tr[1] is StreamState.QUARANTINED
                and self.on_quarantine is not None
            ):
                self.on_quarantine(i)
        self.tick_no += 1

    def mask(self, items: list) -> list:
        """Masking WITHOUT observation — the backlog-drain seam's
        variant (a catch-up drain is one event, not len(ticks) of
        steady-state evidence; the FSM advances on live ticks only)."""
        return [
            items[i] if h.admitted else None
            for i, h in enumerate(self.health)
        ]

    def status(self) -> list[dict]:
        return [h.status() for h in self.health]
