"""FusedIngest — the device-resident ingest engine (``ingest_backend=fused``).

Drop-in producer twin of driver/decode.BatchScanDecoder (same
``on_measurement_batch`` interface, so protocol/engine.py's pump feeds it
unchanged) that replaces the decode -> host-assembly -> re-pack ->
``device_put`` round-trip with ONE staged upload and ONE fused dispatch
per frame run (ops/ingest.fused_ingest_step): unpack, revolution
segmentation and the donated filter step all execute in a single compiled
program on the filter device.  The consumer side replaces
ScanAssembler.wait_and_grab_host + ScanFilterChain.process_raw with
:meth:`wait_and_grab_outputs`, which collects a previously dispatched
batch's single-fetch wire (its device->host copy started at dispatch
time — the same pipelined-collect discipline as
filters/chain.process_raw_pipelined) and returns the completed
revolutions' FilterOutputs with their back-dated timestamps.

The host path (decoder + assembler + chain) stays the golden reference;
bit-exact parity between the two backends is pinned by
tests/test_fused_ingest.py.

What the fused backend does NOT do:
  * feed a RawNodeHolder (interval grabs need host-side nodes — use the
    host backend for ``grab_scan_data_with_interval`` consumers);
  * expose the chain's snapshot/restore surface (the FilterState lives
    inside the fused program's donated state; checkpointing the fused
    path is future work, see ROADMAP).
"""

from __future__ import annotations

import logging
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES
from rplidar_ros2_driver_tpu.protocol import crc as crcmod
from rplidar_ros2_driver_tpu.protocol import timing as timingmod
from rplidar_ros2_driver_tpu.protocol.constants import ANS_PAYLOAD_BYTES, Ans

log = logging.getLogger("rplidar_tpu.ingest")

# frame-run bucket sizes (padded up, like driver/decode._BUCKETS — fewer
# buckets here: every extra bucket is one more compile of the big fused
# program).  The engine caps runs at 64 (protocol/engine.py).
_FUSED_BUCKETS = (4, 64)


class FusedIngest:
    """Producer/consumer engine around ops/ingest.fused_ingest_step."""

    def __init__(
        self,
        params,
        beams: Optional[int] = None,
        *,
        capacity: Optional[int] = None,
        max_revs: int = 2,
        max_queue: int = 32,
        emit_nodes: bool = False,
        buckets: tuple = _FUSED_BUCKETS,
        slot_impl: str = "auto",
    ) -> None:
        import jax

        from rplidar_ros2_driver_tpu.filters.chain import (
            DEFAULT_BEAMS,
            config_from_params,
            pick_device,
        )

        self.device = pick_device(params.filter_backend)
        self.cfg = config_from_params(
            params, beams or DEFAULT_BEAMS, platform=self.device.platform
        )
        self.max_nodes = capacity or MAX_SCAN_NODES
        self.max_revs = max_revs
        self.emit_nodes = emit_nodes
        # per-revolution slot lowering ("auto" | "cond" | "fori") —
        # bit-exact either way, see ops/ingest._slot_impl_for
        self.slot_impl = slot_impl
        self._buckets = tuple(sorted(buckets))
        self._jax = jax
        # producer-facing decoder interface (driver/real.py wires these)
        self.timing = timingmod.TimingDesc()
        self.recorder = None
        # streaming state
        self._active_ans: Optional[int] = None
        self._icfg = None
        self._state = None
        self._filter_state = None  # survives answer-type switches
        self._lock = threading.Lock()
        # timestamp base of the most recent dispatch (f64, host-side):
        # every batch ships offsets from ITS OWN first rx stamp plus the
        # base delta that re-bases the carried partial, so the f32
        # on-device offsets stay bounded by one revolution's span no
        # matter how long the session runs (a single session epoch
        # drifts to ~ms f32 ulp after hours of streaming)
        self._base: Optional[float] = None
        # pipelined collect seam: dispatched-but-unfetched wires
        self._pending: deque = deque()
        self._max_queue = max_queue
        self._event = threading.Event()
        # statistics (host path parity: decode.py counters + assembler's)
        self.frames_decoded = 0
        self.nodes_decoded = 0
        self.scans_completed = 0
        self.revs_dropped = 0
        self.wires_dropped = 0

    # -- stream state ------------------------------------------------------

    def _fresh_filter_state(self):
        from rplidar_ros2_driver_tpu.ops.filters import FilterState

        return self._jax.device_put(
            FilterState.for_config(self.cfg), self.device
        )

    def _activate(self, ans_type: int) -> None:
        """Answer type changed: new scan mode — reset decode/assembly
        state, carry the filter window (the host path's chain survives a
        mode switch too; only decoder + assembler reset)."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            create_ingest_state,
            ingest_config_for,
        )

        self._active_ans = ans_type
        self._icfg = ingest_config_for(
            ans_type, self.timing, self.cfg,
            max_nodes=self.max_nodes, max_revs=self.max_revs,
            emit_nodes=self.emit_nodes, slot_impl=self.slot_impl,
        )
        filt = (
            self._state.filter if self._state is not None
            else self._filter_state
            if self._filter_state is not None
            else self._fresh_filter_state()
        )
        self._state = self._jax.device_put(
            create_ingest_state(self._icfg, filter_state=filt), self.device
        )

    def reset(self) -> None:
        """Stream-state reset (scan stop/start, driver reconnect): clears
        the partial revolution, carries and pending wires; the filter
        window survives, like the host chain across _begin_streaming."""
        with self._lock:
            if self._state is not None:
                self._filter_state = self._state.filter
            self._state = None
            self._active_ans = None
            self._icfg = None
            self._base = None
            self._pending.clear()
            self._event.clear()

    def reset_filter(self) -> None:
        """Cold filter reset (the chain.reset() analog)."""
        with self._lock:
            self._filter_state = self._fresh_filter_state()
            if self._state is not None and self._active_ans is not None:
                ans = self._active_ans
                self._active_ans = None
                self._state = None
                self._activate(ans)

    # -- producer side (the engine pump's callback) ------------------------

    def on_measurement(self, ans_type: int, payload: bytes) -> None:
        """Single-frame compatibility shim (tests / non-batching engines)."""
        self.on_measurement_batch(ans_type, [(payload, time.monotonic())])

    def on_measurement_batch(self, ans_type: int, items: list) -> None:
        """Stage one run of ``(payload, rx_monotonic_ts)`` frames to the
        device and dispatch the fused step — the whole decode+assemble+
        filter pipeline is inside that one dispatch."""
        rec = self.recorder
        if rec is not None:
            for data, ts in items:
                rec.write(ans_type, data, ts)
        expect = ANS_PAYLOAD_BYTES.get(ans_type)
        if expect is None:
            return
        items = [it for it in items if len(it[0]) == expect]
        if not items:
            return
        with self._lock:
            if ans_type != self._active_ans:
                self._activate(ans_type)
            self.frames_decoded += len(items)
            cap = self._buckets[-1]
            for i in range(0, len(items), cap):
                self._dispatch(ans_type, expect, items[i : i + cap])
        self._event.set()

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _dispatch(self, ans_type: int, expect: int, chunk: list) -> None:
        from rplidar_ros2_driver_tpu.ops.ingest import fused_ingest_step

        m = len(chunk)
        mb = self._bucket(m)
        base = chunk[0][1]
        buf = np.zeros((mb, expect), np.uint8)
        buf[:m] = np.frombuffer(
            b"".join(d for d, _ in chunk), np.uint8
        ).reshape(m, expect)
        aux = np.zeros((2 * mb + 2,), np.float32)
        aux[:m] = [ts - base for _, ts in chunk]
        if ans_type == Ans.MEASUREMENT_HQ:
            aux[mb : mb + m] = [
                float(
                    crcmod.crc32_padded(d[:-4])
                    == int.from_bytes(d[-4:], "little")
                )
                for d, _ in chunk
            ]
        aux[-2] = 0.0 if self._base is None else self._base - base
        aux[-1] = m
        self._base = base
        # numpy args go straight into the dispatch: the jit places
        # uncommitted arrays on the (committed, donated) state's device,
        # and the explicit pytree device_put it replaces measured ~0.5 ms
        # per call on the CPU backend — pure staging overhead
        self._state, *res = fused_ingest_step(
            self._state, buf, aux, cfg=self._icfg
        )
        for arr in res:
            try:
                arr.copy_to_host_async()
            except Exception:
                pass  # backend without async D2H: the later fetch blocks
        self._pending.append((tuple(res), self._icfg, base))
        while len(self._pending) > self._max_queue:
            # consumer lagging: oldest result dropped (the assembler's
            # newest-wins double buffer, at batch granularity)
            self._pending.popleft()
            self.wires_dropped += 1

    def precompile(self, ans_type: int) -> None:
        """Warm the jit cache for this format's buckets on a throwaway
        state (motor-warmup analog of BatchScanDecoder.precompile)."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            create_ingest_state,
            fused_ingest_step,
            ingest_config_for,
        )

        expect = ANS_PAYLOAD_BYTES.get(ans_type)
        if expect is None:
            return
        icfg = ingest_config_for(
            ans_type, self.timing, self.cfg,
            max_nodes=self.max_nodes, max_revs=self.max_revs,
            emit_nodes=self.emit_nodes, slot_impl=self.slot_impl,
        )
        for b in self._buckets:
            st = self._jax.device_put(create_ingest_state(icfg), self.device)
            # frames/aux stay numpy, matching the live _dispatch call
            # exactly: a committed-device warmup arg compiles a separate
            # executable, and the first live (numpy-arg) dispatch then
            # pays a full in-loop recompile (~600 ms measured on CPU)
            aux = np.zeros((2 * b + 2,), np.float32)
            aux[-1] = 1.0
            fused_ingest_step(
                st, np.zeros((b, expect), np.uint8), aux, cfg=icfg
            )

    # -- consumer side -----------------------------------------------------

    def _parse(self, entry) -> list:
        from rplidar_ros2_driver_tpu.ops.ingest import unpack_ingest_result

        arrays, icfg, base = entry
        res = unpack_ingest_result(arrays, icfg)
        self.nodes_decoded += res.nodes_appended
        self.scans_completed += res.n_completed
        self.revs_dropped += res.revs_dropped
        out = []
        for k in range(res.n_completed):
            ts0 = base + float(res.ts0[k])
            duration = max(float(res.end_ts[k]) - float(res.ts0[k]), 0.0)
            out.append((res.outputs[k], ts0, duration))
        return out

    def _pop(self):
        with self._lock:
            if not self._pending:
                self._event.clear()
                return None
            entry = self._pending.popleft()
            if not self._pending:
                self._event.clear()
            return entry

    def wait_and_grab_outputs(self, timeout_s: float = 2.0) -> Optional[list]:
        """Block for the next dispatched batch's wire; returns its
        completed revolutions as ``[(FilterOutput, ts0, duration), ...]``
        (possibly empty — a mid-revolution batch), or None on timeout.
        The fetch touches an already-dispatched step whose D2H copy
        started at dispatch time, so in steady state it does not wait on
        device compute."""
        if not self._event.wait(timeout_s):
            return None
        entry = self._pop()
        if entry is None:
            return None
        return self._parse(entry)

    def collect_nowait(self) -> Optional[list]:
        """Non-blocking variant of :meth:`wait_and_grab_outputs`."""
        entry = self._pop()
        if entry is None:
            return None
        return self._parse(entry)

    def collect_pipelined(self) -> list:
        """Drain every pending result EXCEPT the newest: the just-
        dispatched batch keeps computing on the device while its
        predecessors — whose results already landed during earlier
        dispatch gaps — are parsed on the host.  This is the engine-level
        mirror of ScanFilterChain.process_raw_pipelined's collect-before-
        dispatch discipline (one batch of bounded staleness, no blocking
        on in-flight device compute); pair with :meth:`flush` at stream
        end to drain the last batch."""
        out = []
        while True:
            with self._lock:
                if len(self._pending) <= 1:
                    return out
                entry = self._pending.popleft()
            out.extend(self._parse(entry))

    def flush(self) -> list:
        """Drain every pending wire (stream stop): flat list of
        ``(FilterOutput, ts0, duration)`` in dispatch order."""
        out = []
        while True:
            entry = self._pop()
            if entry is None:
                return out
            out.extend(self._parse(entry))
