"""FusedIngest — the device-resident ingest engine (``ingest_backend=fused``).

Drop-in producer twin of driver/decode.BatchScanDecoder (same
``on_measurement_batch`` interface, so protocol/engine.py's pump feeds it
unchanged) that replaces the decode -> host-assembly -> re-pack ->
``device_put`` round-trip with ONE staged upload and ONE fused dispatch
per frame run (ops/ingest.fused_ingest_step): unpack, revolution
segmentation and the donated filter step all execute in a single compiled
program on the filter device.  The consumer side replaces
ScanAssembler.wait_and_grab_host + ScanFilterChain.process_raw with
:meth:`wait_and_grab_outputs`, which collects a previously dispatched
batch's single-fetch wire (its device->host copy started at dispatch
time — the same pipelined-collect discipline as
filters/chain.process_raw_pipelined) and returns the completed
revolutions' FilterOutputs with their back-dated timestamps.

The host path (decoder + assembler + chain) stays the golden reference;
bit-exact parity between the two backends is pinned by
tests/test_fused_ingest.py.

What the fused backend does NOT do:
  * feed a RawNodeHolder (interval grabs need host-side nodes — use the
    host backend for ``grab_scan_data_with_interval`` consumers);
  * expose the chain's snapshot/restore surface (the FilterState lives
    inside the fused program's donated state; checkpointing the fused
    path is future work, see ROADMAP).
"""

from __future__ import annotations

import dataclasses
import logging
import threading
import time
from collections import deque
from typing import Optional

import numpy as np

from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES
from rplidar_ros2_driver_tpu.protocol import crc as crcmod
from rplidar_ros2_driver_tpu.protocol import timing as timingmod
from rplidar_ros2_driver_tpu.protocol.constants import ANS_PAYLOAD_BYTES, Ans

log = logging.getLogger("rplidar_tpu.ingest")

# frame-run bucket sizes (padded up, like driver/decode._BUCKETS — fewer
# buckets here: every extra bucket is one more compile of the big fused
# program).  The engine caps runs at 64 (protocol/engine.py).
_FUSED_BUCKETS = (4, 64)

# schema version of the PER-STREAM fleet snapshot (snapshot_stream /
# restore_stream) — the quarantine/rejoin checkpoint and the unit of
# cross-host stream migration.  Bump on layout changes; restore rejects
# a mismatched version instead of misreading it (the PR 4 mapper-
# checkpoint discipline).
#   v2: optional de-skew/reconstruction planes (recon_ring, recon_pos,
#       deskew_prof, deskew_motion) join the ingest.* key space when
#       ``deskew_enable`` is set; None leaves are omitted, so a
#       deskew-off snapshot still carries exactly the v1 keys.
#   v3: optional in-program mapping planes (map_log_odds, map_pose,
#       map_origin_xy, map_revision) join the ingest.* key space when
#       the fused mapping route is active (fused_mapping_backend) —
#       the MapState rides the ingest carry, so the per-stream
#       failover/quarantine transport now moves the map rows WITH the
#       decode/filter rows.  Same omit-when-None discipline; the bump
#       keeps a v2 restore from silently installing a snapshot whose
#       key-space contract predates the carry layout.
INGEST_STREAM_SNAPSHOT_VERSION = 3


class StagingPool:
    """Recycled host-side (frames, aux) staging pairs, keyed by staging
    key — the free list the PR 16 double buffer drew from, split out of
    the engines so it can be owned PER HOST rather than per shard.

    The ownership split is the pod-of-pods enabler: staging planes are
    host-local state (pinned numpy feeding the H2D link of whichever
    process runs the shard), while everything else an engine carries is
    device state plus per-lane scalars that travel in the per-stream
    snapshot.  With the pool outside the engine, re-homing a shard to
    another process moves only device rows — the destination host's own
    pool supplies staging — and sibling shards on one host share a
    single allocation pool instead of each holding private ping/pong
    pairs per (rung, bucket).

    Reuse safety is the caller's completion-barrier contract, unchanged
    from the in-engine free lists: a pair is ``give``-n back only after
    its dispatch's RESULTS were fetched, proving the device consumed
    the staged inputs, so reuse can never race an in-flight dispatch
    even on a PJRT client with zero-copy host-buffer semantics.  Pairs
    dropped unfetched (queue overflow, reset) just release to the GC.
    Thread-safe: shards on one host stage concurrently.
    """

    def __init__(self) -> None:
        self._free: dict = {}
        self._lock = threading.Lock()

    def take(self, key: tuple, shape_b: tuple, shape_a: tuple) -> tuple:
        """A zeroed (frames, aux) pair for ``key`` — recycled when a
        pooled pair matches the requested shapes (shapes go stale when
        the active format set's payload width moves; stale pairs under
        the key are simply dropped), freshly allocated otherwise.  The
        zero fill happens OUTSIDE the lock: it is the dominant cost at
        big buckets and must not serialize sibling shards' staging."""
        entry = None
        with self._lock:
            free = self._free.setdefault(key, [])
            while free:
                cand = free.pop()
                if cand[0].shape == shape_b and cand[1].shape == shape_a:
                    entry = cand
                    break
        if entry is not None:
            entry[0].fill(0)
            entry[1].fill(0)
            return entry
        return (np.zeros(shape_b, np.uint8), np.zeros(shape_a, np.float32))

    def give(self, key: tuple, pair: tuple) -> None:
        """Return a pair whose dispatch results were fetched (the
        completion barrier) to the free list."""
        with self._lock:
            self._free.setdefault(key, []).append(pair)

    def pooled(self) -> int:
        """Pairs currently pooled (diagnostics)."""
        with self._lock:
            return sum(len(v) for v in self._free.values())


class FusedIngest:
    """Producer/consumer engine around ops/ingest.fused_ingest_step."""

    def __init__(
        self,
        params,
        beams: Optional[int] = None,
        *,
        capacity: Optional[int] = None,
        max_revs: int = 2,
        max_queue: int = 32,
        emit_nodes: bool = False,
        buckets: tuple = _FUSED_BUCKETS,
        slot_impl: str = "auto",
    ) -> None:
        import jax

        from rplidar_ros2_driver_tpu.filters.chain import (
            DEFAULT_BEAMS,
            config_from_params,
            pick_device,
        )
        from rplidar_ros2_driver_tpu.utils.backend import (
            maybe_enable_compilation_cache,
        )

        maybe_enable_compilation_cache(
            getattr(params, "compilation_cache_dir", None)
        )
        self.device = pick_device(params.filter_backend)
        self.cfg = config_from_params(
            params, beams or DEFAULT_BEAMS, platform=self.device.platform
        )
        # fixed-point de-skew + sweep reconstruction (ops/deskew.py):
        # rides inside the fused program when params enable it
        from rplidar_ros2_driver_tpu.ops.deskew import (
            deskew_config_from_params,
        )

        self._deskew = deskew_config_from_params(
            params, self.cfg.beams, platform=self.device.platform
        )
        # newest reconstructed sweep surfaced by _parse (per dispatch
        # that pushed a sub-sweep): (recon_plane (B,) i32, recon_pts
        # (B, 3) f32).  ``recon_log=True`` additionally appends every
        # pushed reconstruction to ``recon_history`` (offline parity /
        # replay tooling; unbounded, so live engines leave it off).
        self.last_recon = None
        self.recon_log = False
        self.recon_history: list = []
        self.max_nodes = capacity or MAX_SCAN_NODES
        self.max_revs = max_revs
        self.emit_nodes = emit_nodes
        # per-revolution slot lowering ("auto" | "cond" | "fori") —
        # bit-exact either way, see ops/ingest._slot_impl_for
        self.slot_impl = slot_impl
        self._buckets = tuple(sorted(buckets))
        self._jax = jax
        # producer-facing decoder interface (driver/real.py wires these)
        self.timing = timingmod.TimingDesc()
        self.recorder = None
        # streaming state
        self._active_ans: Optional[int] = None
        self._icfg = None
        self._state = None
        self._filter_state = None  # survives answer-type switches
        self._lock = threading.Lock()
        # timestamp base of the most recent dispatch (f64, host-side):
        # every batch ships offsets from ITS OWN first rx stamp plus the
        # base delta that re-bases the carried partial, so the f32
        # on-device offsets stay bounded by one revolution's span no
        # matter how long the session runs (a single session epoch
        # drifts to ~ms f32 ulp after hours of streaming)
        self._base: Optional[float] = None
        # recycled staging pairs per (bucket, frame_bytes): each dispatch
        # takes a (frames, aux) numpy pair from the pool (zeroed — the
        # fused program's contract is zero-padding past the live count)
        # and the pair rides its pending entry until that dispatch's
        # results are fetched (StagingPool's completion-barrier
        # contract).  Private pool: the single-stream engine has no
        # host-sharing story.
        self.staging = StagingPool()
        # pipelined collect seam: dispatched-but-unfetched wires
        self._pending: deque = deque()
        self._max_queue = max_queue
        self._event = threading.Event()
        # statistics (host path parity: decode.py counters + assembler's)
        self.frames_decoded = 0
        self.nodes_decoded = 0
        self.scans_completed = 0
        self.revs_dropped = 0
        self.wires_dropped = 0

    # -- stream state ------------------------------------------------------

    def _fresh_filter_state(self):
        from rplidar_ros2_driver_tpu.ops.filters import FilterState

        return self._jax.device_put(
            FilterState.for_config(self.cfg), self.device
        )

    def _activate(self, ans_type: int) -> None:
        """Answer type changed: new scan mode — reset decode/assembly
        state, carry the filter window (the host path's chain survives a
        mode switch too; only decoder + assembler reset)."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            create_ingest_state,
            ingest_config_for,
        )

        self._active_ans = ans_type
        self._icfg = ingest_config_for(
            ans_type, self.timing, self.cfg,
            max_nodes=self.max_nodes, max_revs=self.max_revs,
            emit_nodes=self.emit_nodes, slot_impl=self.slot_impl,
            deskew=self._deskew,
        )
        filt = (
            self._state.filter if self._state is not None
            else self._filter_state
            if self._filter_state is not None
            else self._fresh_filter_state()
        )
        self._state = self._jax.device_put(
            create_ingest_state(self._icfg, filter_state=filt), self.device
        )

    def reset(self) -> None:
        """Stream-state reset (scan stop/start, driver reconnect): clears
        the partial revolution, carries and pending wires; the filter
        window survives, like the host chain across _begin_streaming."""
        with self._lock:
            if self._state is not None:
                self._filter_state = self._state.filter
            self._state = None
            self._active_ans = None
            self._icfg = None
            self._base = None
            self._pending.clear()
            self._event.clear()
            self.last_recon = None

    def reset_filter(self) -> None:
        """Cold filter reset (the chain.reset() analog)."""
        with self._lock:
            self._filter_state = self._fresh_filter_state()
            if self._state is not None and self._active_ans is not None:
                ans = self._active_ans
                self._active_ans = None
                self._state = None
                self._activate(ans)

    # -- producer side (the engine pump's callback) ------------------------

    def on_measurement(self, ans_type: int, payload: bytes) -> None:
        """Single-frame compatibility shim (tests / non-batching engines)."""
        self.on_measurement_batch(ans_type, [(payload, time.monotonic())])

    def on_measurement_batch(self, ans_type: int, items: list) -> None:
        """Stage one run of ``(payload, rx_monotonic_ts)`` frames to the
        device and dispatch the fused step — the whole decode+assemble+
        filter pipeline is inside that one dispatch."""
        rec = self.recorder
        if rec is not None:
            for data, ts in items:
                rec.write(ans_type, data, ts)
        expect = ANS_PAYLOAD_BYTES.get(ans_type)
        if expect is None:
            return
        items = [it for it in items if len(it[0]) == expect]
        if not items:
            return
        with self._lock:
            if ans_type != self._active_ans:
                self._activate(ans_type)
            self.frames_decoded += len(items)
            cap = self._buckets[-1]
            for i in range(0, len(items), cap):
                self._dispatch(ans_type, expect, items[i : i + cap])
        self._event.set()

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def _staging_buffers(self, mb: int, expect: int) -> tuple:
        """A recycled (frames, aux) staging pair, zeroed for reuse;
        freshly allocated on first contact with a (bucket, payload
        width).  Unlike the fleet engine's keys, this one pins BOTH
        dimensions, so any pooled pair already has the right shape."""
        return self.staging.take(
            (mb, expect), (mb, expect), (2 * mb + 2,)
        )

    # graftlint: hot-loop
    def _dispatch(self, ans_type: int, expect: int, chunk: list) -> None:
        from rplidar_ros2_driver_tpu.ops.ingest import fused_ingest_step

        m = len(chunk)
        mb = self._bucket(m)
        base = chunk[0][1]
        pair = self._staging_buffers(mb, expect)
        buf, aux = pair
        buf[:m] = np.frombuffer(
            b"".join(d for d, _ in chunk), np.uint8
        ).reshape(m, expect)
        aux[:m] = [ts - base for _, ts in chunk]
        if ans_type == Ans.MEASUREMENT_HQ:
            aux[mb : mb + m] = [
                float(crcmod.frame_crc_ok(d)) for d, _ in chunk
            ]
        aux[-2] = 0.0 if self._base is None else self._base - base
        aux[-1] = m
        self._base = base
        # EXPLICIT H2D staging (device_put), not numpy args into the
        # jit: under the runtime transfer sentinel
        # (utils/guards.no_implicit_transfers — jax_transfer_guard=
        # "disallow") an implicit numpy->jit transfer raises, so the
        # steady-state hot loop performs exactly two declared puts per
        # dispatch; precompile commits its warmup args the same way so
        # the executable is shared (a committed-vs-uncommitted arg
        # mismatch compiles twice and recompiles in-loop, ~600 ms
        # measured on CPU)
        dbuf, daux = self._jax.device_put((buf, aux), self.device)
        self._state, *res = fused_ingest_step(
            self._state, dbuf, daux, cfg=self._icfg
        )
        for arr in res:
            try:
                arr.copy_to_host_async()
            except Exception:
                pass  # backend without async D2H: the later fetch blocks
        self._pending.append((tuple(res), self._icfg, base, (mb, expect), pair))
        while len(self._pending) > self._max_queue:
            # consumer lagging: oldest result dropped (the assembler's
            # newest-wins double buffer, at batch granularity)
            self._pending.popleft()
            self.wires_dropped += 1

    def precompile(self, ans_type: int) -> None:
        """Warm the jit cache for this format's buckets on a throwaway
        state (motor-warmup analog of BatchScanDecoder.precompile)."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            create_ingest_state,
            fused_ingest_step,
            ingest_config_for,
        )

        expect = ANS_PAYLOAD_BYTES.get(ans_type)
        if expect is None:
            return
        icfg = ingest_config_for(
            ans_type, self.timing, self.cfg,
            max_nodes=self.max_nodes, max_revs=self.max_revs,
            emit_nodes=self.emit_nodes, slot_impl=self.slot_impl,
            deskew=self._deskew,
        )
        for b in self._buckets:
            st = self._jax.device_put(create_ingest_state(icfg), self.device)
            # frames/aux committed via device_put, matching the live
            # _dispatch call exactly: warmup and live args must share a
            # commit pattern or the first live dispatch pays a full
            # in-loop recompile (~600 ms measured on CPU)
            aux = np.zeros((2 * b + 2,), np.float32)
            aux[-1] = 1.0
            dbuf, daux = self._jax.device_put(
                (np.zeros((b, expect), np.uint8), aux), self.device
            )
            fused_ingest_step(st, dbuf, daux, cfg=icfg)

    # -- consumer side -----------------------------------------------------

    def _parse(self, entry) -> list:
        from rplidar_ros2_driver_tpu.ops.ingest import unpack_ingest_result

        arrays, icfg, base, skey, pair = entry
        res = unpack_ingest_result(arrays, icfg)
        # the unpack fetched this dispatch's results, proving its staged
        # inputs consumed: the staging pair is safe to recycle
        self.staging.give(skey, pair)
        if res.recon_pushed:
            self.last_recon = (res.recon_plane, res.recon_pts)
            if self.recon_log:
                self.recon_history.append(self.last_recon)
        self.nodes_decoded += res.nodes_appended
        self.scans_completed += res.n_completed
        self.revs_dropped += res.revs_dropped
        out = []
        for k in range(res.n_completed):
            ts0 = base + float(res.ts0[k])
            duration = max(float(res.end_ts[k]) - float(res.ts0[k]), 0.0)
            out.append((res.outputs[k], ts0, duration))
        return out

    def _pop(self):
        with self._lock:
            if not self._pending:
                self._event.clear()
                return None
            entry = self._pending.popleft()
            if not self._pending:
                self._event.clear()
            return entry

    def wait_and_grab_outputs(self, timeout_s: float = 2.0) -> Optional[list]:
        """Block for the next dispatched batch's wire; returns its
        completed revolutions as ``[(FilterOutput, ts0, duration), ...]``
        (possibly empty — a mid-revolution batch), or None on timeout.
        The fetch touches an already-dispatched step whose D2H copy
        started at dispatch time, so in steady state it does not wait on
        device compute."""
        if not self._event.wait(timeout_s):
            return None
        entry = self._pop()
        if entry is None:
            return None
        return self._parse(entry)

    def collect_nowait(self) -> Optional[list]:
        """Non-blocking variant of :meth:`wait_and_grab_outputs`."""
        entry = self._pop()
        if entry is None:
            return None
        return self._parse(entry)

    def collect_pipelined(self) -> list:
        """Drain every pending result EXCEPT the newest: the just-
        dispatched batch keeps computing on the device while its
        predecessors — whose results already landed during earlier
        dispatch gaps — are parsed on the host.  This is the engine-level
        mirror of ScanFilterChain.process_raw_pipelined's collect-before-
        dispatch discipline (one batch of bounded staleness, no blocking
        on in-flight device compute); pair with :meth:`flush` at stream
        end to drain the last batch."""
        out = []
        while True:
            with self._lock:
                if len(self._pending) <= 1:
                    return out
                entry = self._pending.popleft()
            out.extend(self._parse(entry))

    def flush(self) -> list:
        """Drain every pending wire (stream stop): flat list of
        ``(FilterOutput, ts0, duration)`` in dispatch order."""
        out = []
        while True:
            entry = self._pop()
            if entry is None:
                return out
            out.extend(self._parse(entry))


class FleetFusedIngest:
    """Fleet-scale producer/consumer engine around
    ops/ingest.fleet_fused_ingest_step: one staged upload and ONE fused
    dispatch per fleet tick, whatever the fleet size.

    Each tick the caller hands every stream's newest raw frame bytes
    (``items[i] = (ans_type, [(payload, rx_monotonic_ts), ...])``, None
    for an idle stream); the engine stacks them into one zero-padded
    ``(streams, M, frame_bytes)`` buffer (M picked from the padding
    ``buckets``), threads per-stream format branches / decode-state reset
    flags / timestamp re-bases through ``aux``, and dispatches the one
    vmapped program.  Per-stream decode carries live entirely on the
    device; the host tracks only each stream's active format and
    timestamp base.

    Semantics per stream are EXACTLY the single-stream fused engine's
    (bit-exact against N independent BatchScanDecoder + ScanAssembler +
    ScanFilterChain paths — tests/test_fleet_fused_ingest.py): a stream
    advances its rolling filter window only on its own completed
    revolutions.  This differs from ShardedFilterService.submit's
    lockstep contract, where an idle stream's window absorbs an
    all-masked scan; the fleet-fused backend is the scale-out of N
    independent chains, not of the lockstep tick.

    Structural counters (``dispatch_count``, ``h2d_transfers``) exist so
    the bench decomposition can assert the O(N) -> O(1) per-tick claim
    rather than infer it from wall time.

    ``super_tick_max`` (default from ``params.super_tick_max``, 1 =
    disabled) enables the T-tick super-step lowering
    (ops/ingest.super_fleet_ingest_step): whenever more than one tick
    slice is queued — a backlog handed to :meth:`submit_backlog` after a
    link stall, or one oversized tick split across bucket slices — up to
    ``super_tick_max`` slices are staged as one (T, N, M, frame_bytes)
    plane and drained in ONE compiled dispatch instead of T.  Short
    groups are padded with all-idle tick planes (carries pass through
    untouched) so each (T, bucket) pair compiles exactly once.
    """

    def __init__(
        self,
        params,
        streams: int,
        *,
        mesh=None,
        beams: Optional[int] = None,
        capacity: Optional[int] = None,
        max_revs: int = 2,
        max_queue: int = 32,
        emit_nodes: bool = False,
        buckets: tuple = _FUSED_BUCKETS,
        slot_impl: str = "fori",
        super_tick_max: Optional[int] = None,
        rungs: Optional[tuple] = None,
        staging_pool: Optional[StagingPool] = None,
    ) -> None:
        import jax

        from rplidar_ros2_driver_tpu.filters.chain import (
            DEFAULT_BEAMS,
            config_from_params,
            pick_device,
        )
        from rplidar_ros2_driver_tpu.ops.ingest import (
            create_fleet_ingest_state,
            fleet_ingest_config_for,
        )
        from rplidar_ros2_driver_tpu.utils.backend import (
            maybe_enable_compilation_cache,
        )

        maybe_enable_compilation_cache(
            getattr(params, "compilation_cache_dir", None)
        )
        if streams < 1:
            raise ValueError("fleet ingest needs at least one stream")
        self.streams = streams
        self.mesh = mesh
        if mesh is not None:
            platform = mesh.devices.flat[0].platform
            self.device = None
        else:
            self.device = pick_device(params.filter_backend)
            platform = self.device.platform
        self.cfg = config_from_params(
            params, beams or DEFAULT_BEAMS, platform=platform
        )
        from rplidar_ros2_driver_tpu.ops.deskew import (
            deskew_config_from_params,
        )

        self._deskew = deskew_config_from_params(
            params, self.cfg.beams, platform=platform
        )
        # in-program SLAM front-end (ops/ingest cfg.mapping): when the
        # fused mapping route is active the per-stream MapState rides
        # the ingest carry and the map update runs INSIDE the one fleet
        # program — the engine surfaces the per-tick pose wires here
        # (mapping/mapper.CarriedFleetMapper is the host-facing view)
        from rplidar_ros2_driver_tpu.mapping.mapper import (
            fused_mapping_map_config,
        )

        self._mapping = fused_mapping_map_config(
            params, self.cfg.beams, platform
        )
        if self._mapping is not None and self._deskew is None:
            # the validator only sees the fields; THIS seam knows the
            # reconstruction stage is absent — refuse loudly instead of
            # building a program with no sweep for the mapper to absorb
            raise ValueError(
                "fused_mapping_backend='fused' requires deskew_enable "
                "(the in-program mapper consumes the reconstructed "
                "sweep the de-skew stage emits every tick)"
            )
        # newest per-stream (7,) int32 map wires from parsed dispatches
        # ([live, tx, ty, θidx, score, n_valid, revision]);
        # ``take_map_wires()`` drains the FRESH ones for the service's
        # mapping seam, exactly like ``take_recon()``
        self.last_map_wires: list = [None] * streams
        self._map_fresh: list = [False] * streams
        # per-stream reconstructed-sweep surface (see FusedIngest):
        # ``last_recon[i]`` holds stream i's newest (plane, pts) pair,
        # ``take_recon()`` drains the ticks' FRESH reconstructions for
        # the mapper seam, ``recon_log=True`` appends every pushed
        # reconstruction to ``recon_history[i]`` (offline parity only)
        self.last_recon: list = [None] * streams
        self._recon_fresh: list = [False] * streams
        self.recon_log = False
        self.recon_history: list = [[] for _ in range(streams)]
        self.max_nodes = capacity or MAX_SCAN_NODES
        self.max_revs = max_revs
        self.emit_nodes = emit_nodes
        self.slot_impl = slot_impl
        if super_tick_max is None:
            super_tick_max = getattr(params, "super_tick_max", 1)
        if super_tick_max < 1:
            raise ValueError("super_tick_max must be >= 1")
        self.super_tick_max = int(super_tick_max)
        # super-tick RUNG ladder: the set of backlog-drain depths this
        # engine pre-warms, so a scheduler (parallel/scheduler.py) can
        # pick a different T per drain with every rung already in the
        # compile cache — a mid-run rung switch is a cache hit by
        # construction.  Depth 1 (the per-tick program) is always a
        # rung; ``super_tick_max`` stays the default drain depth for
        # unscheduled callers.  Every rung > 1 costs one compiled
        # super-step program per padding bucket at precompile.
        self.rungs = tuple(sorted(
            {1, self.super_tick_max}
            | {int(r) for r in (rungs or ())}
        ))
        if self.rungs[0] < 1:
            raise ValueError("super-tick rungs must be >= 1")
        # compiled drains per rung depth (the bench's per-rung
        # dispatch accounting; depth 1 counts per-tick dispatches)
        self.rung_dispatches: dict = {r: 0 for r in self.rungs}
        # set once precompile has warmed the ladder: extending the
        # rung set after that would hand out depths precompile never
        # compiled (ensure_rungs refuses); cold-drain warnings fire
        # once per depth
        self._rungs_warmed = False
        self._cold_rungs_warned: set = set()
        self._buckets = tuple(sorted(buckets))
        self._jax = jax
        self.timing = timingmod.TimingDesc()
        self.recorder = None
        self._lock = threading.Lock()
        # recycled staging planes per (kind, bucket): each dispatch
        # takes a (frames, aux) numpy pair from the StagingPool instead
        # of allocating fresh, and the pair rides its pending entry
        # until that dispatch's RESULTS have been fetched (the pool's
        # completion-barrier contract).  Steady state (pipelined depth
        # ~2) holds two pairs per bucket and allocates nothing per
        # tick.  The pool is INJECTED by the elastic pod (one per host,
        # shared across its shards) so this engine carries only device
        # state and per-lane scalars — the re-homing unit; standalone
        # engines own a private pool.
        self.staging = staging_pool if staging_pool is not None else (
            StagingPool()
        )
        # double-buffered async H2D staging: within a multi-group drain
        # the NEXT group's staging planes are filled and device_put
        # while the previous group's compute is still in flight — the
        # free list then holds a ping/pong PAIR of planes per staging
        # key (the in-flight dispatch owns one half, the overlap stage
        # fills the other), recycled at result fetch as before.  Off
        # reproduces the PR 14 serialized stage->compute order exactly
        # (same ticks, same contents — the A/B arm of bench --config 20)
        self.double_buffer = bool(
            getattr(params, "staging_double_buffer", True)
        )
        # dispatches whose H2D staging overlapped an in-flight compute
        # (the /diagnostics staging-overlap hit counter)
        self.staging_overlap_hits = 0
        # adaptive padding-bucket ladder seam: when set (a warmed
        # bucket), _tick_slices caps frame runs at THIS bucket instead
        # of the largest — the scheduler's BucketLadder drops it on
        # occupancy collapse so dispatches ride a cheaper executable.
        # The cap only re-slices future ticks: contents and order never
        # change, so any cap sequence is byte-equal by construction
        # (same argument as the rung ladder) and per-stream snapshots
        # round-trip across a switch untouched.
        self.active_bucket: Optional[int] = None
        self.bucket_switches = 0
        # per-(rung, bucket) dispatch accounting (sums to
        # dispatch_count; marginal over buckets reproduces
        # rung_dispatches — bench --config 20 asserts both identities)
        self.rung_bucket_dispatches: dict = {}
        # precompile's timed re-runs of each warmed (rung, bucket)
        # program (compile excluded): the LatencyModel seeds
        # (parallel/scheduler.py) so the first live drain is priced
        # before any traffic
        self.warmup_costs: dict = {}
        # per-stream host trackers (everything else lives on device)
        self._stream_fmt: list = [None] * streams   # active ans type
        self._bases: list = [None] * streams        # f64 timestamp base
        self._reset_next: list = [False] * streams  # decode-state reset flags
        self._icfg = None                           # active FleetIngestConfig
        # the carried state's SHAPE is format-independent (prev plane at
        # the global max payload width), so it is created once here and
        # survives every format-set recompile untouched.  The cold_reset
        # host template is NOT captured here: only the elastic pod ever
        # cold-resets, and the capture costs a D2H fetch plus a
        # permanently retained host copy of the whole fleet state —
        # single-shard deployments skip both (capture_cold_template).
        self._fresh_host = None
        self._state = self._fresh_fleet_state()
        self._pending: deque = deque()
        self._max_queue = max_queue
        # structural counters (the bench decomposition's O(1) assertion)
        self.ticks = 0
        self.dispatch_count = 0
        self.h2d_transfers = 0
        # super-step lowering counters: compiled super dispatches issued
        # and how many real (un-padded) tick slices they drained
        self.super_dispatches = 0
        self.ticks_super_fused = 0
        # statistics, host-path parity
        self.frames_decoded = 0
        self.nodes_decoded = 0
        self.scans_completed = 0
        self.revs_dropped = 0
        self.wires_dropped = 0
        # per-stream cumulative counters — the latent health signals
        # surfaced (driver/health.py consumers read deltas): frames
        # offered, revolutions completed, revolution syncs observed,
        # and max_revs overflow drops, per lane
        self.stream_frames = [0] * streams
        self.stream_scans = [0] * streams
        self.stream_syncs = [0] * streams
        self.stream_revs_dropped = [0] * streams

    # -- placement ---------------------------------------------------------

    def _place(self, state):
        """Put a stream-batched pytree on the mesh (stream axis sharded,
        everything else replicated per shard) or the single device."""
        if self.mesh is None:
            return self._jax.device_put(state, self.device)
        from rplidar_ros2_driver_tpu.parallel.sharding import (
            place_fleet_ingest_state,
        )

        return place_fleet_ingest_state(self.mesh, state)

    def _fresh_fleet_state(self):
        """A placed all-fresh fleet state — the __init__ construction,
        shared with :meth:`cold_reset` so the two can never drift.  The
        shape is format-independent, so the baseline single-format
        config describes every lane."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            create_fleet_ingest_state,
            fleet_ingest_config_for,
        )

        return self._place(create_fleet_ingest_state(
            fleet_ingest_config_for(
                (Ans.MEASUREMENT,), self.timing, self.cfg,
                max_nodes=self.max_nodes, max_revs=self.max_revs,
                deskew=self._deskew, mapping=self._mapping,
            ),
            self.streams,
        ))

    def capture_cold_template(self) -> None:
        """Capture the host-side :meth:`cold_reset` template (one D2H
        fetch of a fresh state, retained for the engine's lifetime).
        The elastic pod calls this at precompile — before traffic, so
        the fetch never lands inside a guarded loop — and it is the
        only cold_reset caller; everyone else skips the cost."""
        if self._fresh_host is None:
            self._fresh_host = self._jax.device_get(
                self._fresh_fleet_state()
            )

    def cold_reset(self) -> None:
        """Device-loss reinitialization — the elastic fleet's shard-kill
        / re-admission entry point (parallel/service.ElasticFleetService):
        every lane's device state is replaced with a fresh one and every
        host tracker cleared, exactly as if this engine had just been
        constructed on a rebooted chip.  Unlike :meth:`reset` (scan
        stop/start — filter windows survive) nothing survives here: the
        pod wipes a lost shard the moment it dies so a later re-admission
        provably rebuilds from per-stream snapshots, never from stale
        device state.  The fresh state is an explicit placement of the
        host template (guard-safe: one declared device_put, no compiles,
        inside a guarded steady-state loop) — re-creating the jnp state
        here instead would trip the transfer sentinel on its fill-value
        scalar uploads."""
        if self._fresh_host is None:
            raise RuntimeError(
                "capture_cold_template() must run before cold_reset() "
                "(the elastic pod captures it at precompile, before "
                "traffic)"
            )
        fresh = self._place(self._fresh_host)
        with self._lock:
            self._state = fresh
            self._stream_fmt = [None] * self.streams
            self._bases = [None] * self.streams
            self._reset_next = [False] * self.streams
            self._pending.clear()
            # the sub-sweep cache dies with the engines (the PR 9
            # `_streaming`-flag discipline: host mirrors of wiped
            # device state restart with it) — and so do the map wires:
            # the in-carry MapState was just wiped with everything else
            self.last_recon = [None] * self.streams
            self._recon_fresh = [False] * self.streams
            self.last_map_wires = [None] * self.streams
            self._map_fresh = [False] * self.streams

    def _put_staging(self, buf, aux, *, super_step: bool = False) -> tuple:
        """EXPLICIT H2D staging of one dispatch's input planes — the
        declared transfers the runtime sentinel counts (utils/guards.
        no_implicit_transfers disallows implicit numpy->jit staging).
        Stream-sharded on a mesh (the state's own layout: each stream's
        bytes land on the shard holding its carries), device-committed
        otherwise; ``super_step`` shifts the stream axis behind the
        leading tick axis.  Warmup (precompile) and the live dispatch
        both route through here so they share one commit pattern — and
        therefore one compiled executable."""
        if self.mesh is None:
            return self._jax.device_put((buf, aux), self.device)
        from jax.sharding import NamedSharding, PartitionSpec as P

        lead = (None,) if super_step else ()
        return (
            self._jax.device_put(buf, NamedSharding(
                self.mesh, P(*lead, "stream", None, None)
            )),
            self._jax.device_put(aux, NamedSharding(
                self.mesh, P(*lead, "stream", None)
            )),
        )

    # -- configuration -----------------------------------------------------

    def _ensure_cfg(self, formats) -> None:
        """(Re)build the static config when the needed format set is not
        covered by the active one.  State is untouched — only the program
        recompiles (format-set changes are scan-mode events, not per-tick
        traffic)."""
        from rplidar_ros2_driver_tpu.ops.ingest import fleet_ingest_config_for

        need = tuple(sorted({int(f) for f in formats if f is not None}))
        if not need:
            return
        if self._icfg is not None and set(need) <= set(self._icfg.formats):
            return
        have = set(self._icfg.formats) if self._icfg is not None else set()
        self._icfg = fleet_ingest_config_for(
            tuple(sorted(have | set(need))), self.timing, self.cfg,
            max_nodes=self.max_nodes, max_revs=self.max_revs,
            emit_nodes=self.emit_nodes, slot_impl=self.slot_impl,
            deskew=self._deskew, mapping=self._mapping,
        )

    def ensure_rungs(self, rungs) -> None:
        """Extend the warmed rung ladder (a scheduler attaching to an
        already-constructed engine).  Must happen BEFORE precompile /
        traffic: a new depth on a live engine would pay its compile
        inside the serving loop, exactly what the ladder exists to
        forbid."""
        need = {1} | {int(r) for r in rungs}
        if need <= set(self.rungs):
            return
        if self.ticks > 0 or self._rungs_warmed:
            # after precompile the new depths would pass the
            # `depth in self.rungs` check without any compiled
            # executable behind them — the first deep drain would pay
            # its compile inside the serving loop
            raise RuntimeError(
                f"cannot extend the rung ladder {self.rungs} with "
                f"{sorted(need - set(self.rungs))} on an engine that "
                "has already "
                + ("ticked" if self.ticks > 0 else "precompiled")
                + " — attach the scheduler BEFORE precompile/traffic"
            )
        if min(need) < 1:
            raise ValueError("super-tick rungs must be >= 1")
        self.rungs = tuple(sorted(set(self.rungs) | need))
        self.rung_dispatches = {
            r: self.rung_dispatches.get(r, 0) for r in self.rungs
        }

    def precompile(self, formats, buckets: Optional[tuple] = None) -> None:
        """Warm the jit cache for EVERY padding bucket of the given format
        set on a throwaway state (motor-warmup analog of the single-stream
        engine's precompile), so first contact with an off-bucket chunk —
        or the first tick itself — never stalls the live loop on a
        compile.  Frames/aux are committed through the same
        ``_put_staging`` path as the live dispatch: warmup and live args
        must share one commit pattern or the first live tick pays an
        in-loop recompile (see FusedIngest.precompile; pinned by the
        tests/test_guards.py steady-state sentinels)."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            create_fleet_ingest_state,
            fleet_aux_len,
            fleet_fused_ingest_step,
            super_fleet_ingest_step,
        )

        with self._lock:
            self._ensure_cfg(formats)
            icfg = self._icfg
        if icfg is None:
            return
        self._rungs_warmed = True

        def timed_seed(rung, bucket, run, st):
            # warm (pays the compile), then time a SECOND run of the
            # now-cached executable end to end — the LatencyModel seed
            # for this (rung, bucket) program.  Compile time must stay
            # out of the seed or the deadline cap would price every
            # rung at its one-off warmup cost and pin the ladder to the
            # floor for the first hundreds of drains.  The state arg is
            # donated, so the timed re-run threads the returned carry.
            out = run(st)
            self._jax.block_until_ready(out)
            t0 = time.perf_counter()
            self._jax.block_until_ready(run(out[0]))
            self.warmup_costs[(rung, bucket)] = time.perf_counter() - t0

        for b in buckets or self._buckets:
            st = self._place(create_fleet_ingest_state(icfg, self.streams))
            aux = np.zeros((self.streams, fleet_aux_len(b)), np.float32)
            aux[:, 2 * b + 1] = 1.0  # m=1: the live-lane trace
            dbuf, daux = self._put_staging(
                np.zeros((self.streams, b, icfg.frame_bytes), np.uint8),
                aux,
            )
            timed_seed(
                1, b,
                # graftlint: disable=GL003 — timed_seed threads the
                # RETURNED carry into its second call; the donated
                # handle is never re-read (each invocation gets a
                # fresh state, see the docstring above)
                lambda s, u=dbuf, a=daux: fleet_fused_ingest_step(
                    s, u, a, cfg=icfg
                ),
                st,
            )
            for T in self.rungs:
                if T <= 1:
                    continue  # the per-tick program above IS rung 1
                # the backlog-drain programs: one compile per
                # (rung, bucket) — EVERY ladder depth is warmed here,
                # so a scheduler switching rungs mid-run stays in the
                # compile cache (tests/test_guards.py pins it)
                st = self._place(
                    create_fleet_ingest_state(icfg, self.streams)
                )
                saux = np.zeros(
                    (T, self.streams, fleet_aux_len(b)), np.float32
                )
                saux[:, :, 2 * b + 1] = 1.0
                dbuf, daux = self._put_staging(
                    np.zeros(
                        (T, self.streams, b, icfg.frame_bytes), np.uint8
                    ),
                    saux,
                    super_step=True,
                )
                timed_seed(
                    T, b,
                    # graftlint: disable=GL003 — timed_seed threads
                    # the RETURNED carry into its second call; the
                    # donated handle is never re-read
                    lambda s, u=dbuf, a=daux: super_fleet_ingest_step(
                        s, u, a, cfg=icfg
                    ),
                    st,
                )

    # -- producer side -----------------------------------------------------

    def _bucket(self, n: int) -> int:
        for b in self._buckets:
            if n <= b:
                return b
        return self._buckets[-1]

    def set_active_bucket(self, bucket: int) -> None:
        """Move the frame-run slicing cap to ``bucket`` (the scheduler's
        BucketLadder pick — occupancy collapse drops it, recovery
        raises it).  The bucket must be warmed: every listed bucket got
        its own compiled program per rung at precompile, so a mid-run
        switch is a compile-cache hit by construction.  The cap only
        re-slices FUTURE ticks — contents and order never change, so
        any cap sequence lands byte-equal trajectories and per-stream
        snapshots round-trip across the switch untouched (the PR 9
        migration-relabel argument; tests/test_guards.py pins the
        zero-recompile half, bench --config 20 the byte-equality)."""
        b = int(bucket)
        if b not in self._buckets:
            raise ValueError(
                f"bucket {b} is not a warmed padding bucket "
                f"{self._buckets} — list it in bucket_rungs (every "
                "ladder bucket is compiled per rung at precompile)"
            )
        prev = self.active_bucket or self._buckets[-1]
        self.active_bucket = b
        if b != prev:
            self.bucket_switches += 1

    @property
    def slicing_bucket(self) -> int:
        """The active slicing-cap bucket: the bucket ladder's pick, or
        the top warmed bucket when no ladder has spoken (the pre-PR-16
        static behaviour)."""
        return self.active_bucket or self._buckets[-1]

    def _normalize_tick(self, items) -> list:
        """Validate one tick's per-stream byte runs: payload-size filter
        (the single-stream engine's), recorder tee, format bookkeeping
        (a per-stream answer-type change resets THAT stream's decode
        state, filter window carried — host-path semantics)."""
        if len(items) != self.streams:
            raise ValueError(
                f"expected {self.streams} per-stream byte runs, got {len(items)}"
            )
        rec = self.recorder
        runs: list = [None] * self.streams
        for i, item in enumerate(items):
            if not item:
                continue
            ans, frames = item
            expect = ANS_PAYLOAD_BYTES.get(ans)
            if expect is None:
                continue
            if rec is not None:
                for data, ts in frames:
                    rec.write(ans, data, ts)
            frames = [it for it in frames if len(it[0]) == expect]
            if not frames:
                continue
            if self._stream_fmt[i] != ans:
                self._stream_fmt[i] = ans
                # the timestamp base is NOT cleared here: normalize runs
                # for every backlog tick before any is staged, and a
                # later tick's switch must not corrupt an earlier tick's
                # re-base.  The reset travels in the slice snapshot and
                # _stage_slice clears the base when it lands, in slice
                # order (per-tick mode is equivalent: nothing reads the
                # base between normalize and stage).
                self._reset_next[i] = True
            runs[i] = (int(ans), frames)
            self.frames_decoded += len(frames)
            self.stream_frames[i] += len(frames)
        return runs

    def _tick_slices(self, items) -> list:
        """Normalize one tick into its bucket-capped lockstep slices
        (several when a stream delivered more frames than the largest
        bucket), advancing the per-tick counters; [] for a pure idle
        tick with no pending resets.

        Each slice is ``(chunk, fmts, resets)`` with the per-stream
        format snapshot and (first slice only) the consumed decode-reset
        flags BAKED IN at normalize time: a backlog normalizes every
        tick before any is staged, so stage-time engine state (a later
        tick's format switch) must never leak into an earlier tick's
        staging planes."""
        runs = self._normalize_tick(items)
        self._ensure_cfg([self._stream_fmt[i] for i in range(self.streams)])
        if self._icfg is None:
            return []  # nothing ever streamed
        longest = max((len(r[1]) for r in runs if r), default=0)
        if longest == 0 and not any(self._reset_next):
            return []  # pure idle tick: nothing to stage, nothing to reset
        self.ticks += 1
        fmts = list(self._stream_fmt)
        resets = self._reset_next
        self._reset_next = [False] * self.streams
        no_reset = [False] * self.streams
        # the bucket ladder's slicing cap: a collapsed fleet slices at
        # a small pre-warmed bucket (cheap executable, a couple more
        # dispatches), a full fleet at the largest (one padded plane)
        cap = self.active_bucket or self._buckets[-1]
        slices = []
        off = 0
        while True:
            chunk = [
                (r[0], r[1][off : off + cap]) if r else None for r in runs
            ]
            if off and not any(c and c[1] for c in chunk):
                break
            slices.append((chunk, fmts, resets if off == 0 else no_reset))
            off += cap
            if off >= longest:
                break
        return slices

    def _dispatch_tick(self, items) -> None:
        """Stage and dispatch one tick (its slices grouped into T-tick
        super-steps whenever more than one is queued and the super-step
        lowering is enabled)."""
        self._dispatch_slices(self._tick_slices(items))

    def _dispatch_slices(self, slices, depth: Optional[int] = None) -> None:
        """Dispatch a queue of tick slices: one per-tick program each
        at depth 1 (or for a single queued slice), else groups of up to
        ``depth`` slices per ONE compiled super-step dispatch.  The
        default depth is ``super_tick_max``; a scheduler picks a
        different WARMED rung per drain — an unwarmed depth is refused
        loudly, because it would pay its compile inside the serving
        loop.

        With ``staging_double_buffer`` on and more than one group
        queued, staging runs one group AHEAD of compute: group t's
        dispatch is issued (async), THEN group t+1's planes are filled
        and ``device_put`` while t computes — the H2D link transfer of
        drain t+1 hides under the compute of drain t.  Staging order is
        unchanged (groups stage strictly in tick order, so the
        timestamp-base walk and the pending queue see the exact PR 14
        sequence), only the interleaving with compute dispatch moves —
        byte-equal trajectories by construction."""
        if depth is None:
            depth = self.super_tick_max
        elif depth not in self.rungs:
            raise ValueError(
                f"drain depth {depth} is not a warmed rung "
                f"{self.rungs} — extend sched_rungs (ensure_rungs) "
                "before traffic"
            )
        elif (
            depth > 1 and not self._rungs_warmed
            and depth not in self._cold_rungs_warned
        ):
            # a LISTED rung on a never-precompiled engine still pays
            # its compile here — fine for offline tools and parity
            # tests, a latency spike in a serving loop, so say so
            # (once per depth; the jit cache holds it afterwards)
            self._cold_rungs_warned.add(depth)
            log.warning(
                "rung-%d drain on an engine precompile() never warmed "
                "— this dispatch compiles in-line", depth,
            )
        if depth <= 1:
            groups = [[sl] for sl in slices]
        else:
            groups = [
                slices[off : off + depth]
                for off in range(0, len(slices), depth)
            ]

        def stage(group):
            if len(group) == 1:
                return self._stage_tick(group[0])
            return self._stage_super(group, depth)

        if not self.double_buffer or len(groups) < 2:
            # PR 14 order: stage -> compute, serialized per group
            for group in groups:
                self._launch(stage(group))
            return
        staged = stage(groups[0])
        for group in groups[1:]:
            self._launch(staged)   # async dispatch: drain t computes
            staged = stage(group)  # drain t+1's H2D overlaps drain t
            self.staging_overlap_hits += 1
        self._launch(staged)

    def _staging_buffers(self, skey: tuple) -> tuple:
        """A (frames, aux) staging pair for one staging key —
        ``("tick", bucket)`` or ``("super", T, bucket)``, the rung depth
        part of the key because each rung's planes carry a different
        leading tick axis: recycled from the free list when a fetched
        dispatch has returned one of the right shape (zeroed for
        reuse), freshly allocated otherwise — shapes go stale when the
        active format set's payload width moves, and stale pairs are
        simply not reused."""
        from rplidar_ros2_driver_tpu.ops.ingest import fleet_aux_len

        fb = self._icfg.frame_bytes
        mb = skey[-1]
        al = fleet_aux_len(mb)
        if skey[0] == "super":
            T = skey[1]
            shape_b = (T, self.streams, mb, fb)
            shape_a = (T, self.streams, al)
        else:
            shape_b = (self.streams, mb, fb)
            shape_a = (self.streams, al)
        return self.staging.take(skey, shape_b, shape_a)

    @property
    def _staging_free(self) -> dict:
        """The pool's raw free-list dict (test/diagnostic seam kept
        from the in-engine free-list era)."""
        return self.staging._free

    def _recycle_staging(self, skey: tuple, pair) -> None:
        """Return a fetched entry's staging pair to the pool (its
        dispatch's results are host-side, so the inputs are provably
        consumed)."""
        self.staging.give(skey, pair)

    # graftlint: hot-loop
    def _stage_slice(self, sl, mb: int, buf, aux) -> None:
        """Fill one tick slice's staging planes (``buf``: (streams, mb,
        frame_bytes) uint8, ``aux``: (streams, 2mb+4) f32, both
        pre-zeroed) from the slice's baked-in format/reset snapshots,
        advancing the per-stream timestamp bases."""
        icfg = self._icfg
        chunk, fmts, resets = sl
        for i, c in enumerate(chunk):
            fmt = fmts[i]
            if fmt is not None:
                aux[i, 2 * mb + 2] = icfg.formats.index(int(fmt))
            if resets[i]:
                aux[i, 2 * mb + 3] = 1.0
                # decode reset => fresh timestamp base for this stream,
                # applied HERE so it lands at its own slice (see
                # _normalize_tick)
                self._bases[i] = None
            if not c or not c[1]:
                continue  # idle this slice: m=0, carries pass through
            ans, frames = c
            m = len(frames)
            ebytes = ANS_PAYLOAD_BYTES[Ans(ans)]
            base = frames[0][1]
            buf[i, :m, :ebytes] = np.frombuffer(
                b"".join(d for d, _ in frames), np.uint8
            ).reshape(m, ebytes)
            aux[i, :m] = [ts - base for _, ts in frames]
            if ans == Ans.MEASUREMENT_HQ:
                aux[i, mb : mb + m] = [
                    float(crcmod.frame_crc_ok(d)) for d, _ in frames
                ]
            aux[i, 2 * mb] = (
                0.0 if self._bases[i] is None else self._bases[i] - base
            )
            aux[i, 2 * mb + 1] = m
            self._bases[i] = base

    def _append_pending(self, res, entry) -> None:
        for arr in res:
            try:
                arr.copy_to_host_async()
            except Exception:
                pass  # backend without async D2H: the later fetch blocks
        self._pending.append(entry)
        while len(self._pending) > self._max_queue:
            self._pending.popleft()
            self.wires_dropped += 1

    # graftlint: hot-loop
    def _stage_tick(self, sl) -> tuple:
        """Fill and ``device_put`` ONE per-tick dispatch's staging
        planes — 2 DECLARED host->device transfers per fleet tick
        slice, independent of fleet size; the runtime transfer sentinel
        forbids the implicit numpy->jit alternative.  Returns the
        staged descriptor :meth:`_launch` consumes (the stage/compute
        split is what lets the double buffer issue drain t+1's H2D
        while drain t computes)."""
        icfg = self._icfg
        mb = self._bucket(max(
            (len(c[1]) for c in sl[0] if c), default=1
        ))
        skey = ("tick", mb)
        pair = self._staging_buffers(skey)
        buf, aux = pair
        self._stage_slice(sl, mb, buf, aux)
        dbuf, daux = self._put_staging(buf, aux)
        self.h2d_transfers += 2
        return (
            "tick", 1, 1, icfg, list(self._bases), skey, pair, dbuf, daux
        )

    # graftlint: hot-loop
    def _stage_super(self, group, T: int) -> tuple:
        """Fill and ``device_put`` one super-step dispatch's staging
        planes: up to ``T`` tick slices (a warmed rung depth) as one
        (T, streams, M, frame_bytes) plane.  The group is padded to the
        full T with all-idle tick planes — zeroed staging rows are
        exactly the idle-lane encoding (m=0, base_shift=0, no reset),
        which pass every carry through — so each (rung, bucket) pair
        compiles once, whatever the backlog length, and any rung
        SEQUENCE lands byte-identical state (the pad ticks are no-ops
        by construction)."""
        icfg = self._icfg
        mb = self._bucket(max(
            (len(c[1]) for sl in group for c in sl[0] if c), default=1
        ))
        skey = ("super", T, mb)
        pair = self._staging_buffers(skey)
        buf, aux = pair
        bases_per_tick = []
        for t, sl in enumerate(group):
            self._stage_slice(sl, mb, buf[t], aux[t])
            bases_per_tick.append(list(self._bases))
        # the idle pad ticks (t >= len(group)) stay all-zero; their meta
        # rows come back all-zero and the parse skips them.  Staging is
        # an explicit device_put, like the per-tick path.
        dbuf, daux = self._put_staging(buf, aux, super_step=True)
        self.h2d_transfers += 2
        return (
            "super", T, len(group), icfg, bases_per_tick, skey, pair,
            dbuf, daux,
        )

    # graftlint: hot-loop
    def _launch(self, staged) -> None:
        """Issue the compiled dispatch for one staged descriptor and
        append its pending entry — the compute half of the
        stage/compute split (dispatch is async: this returns as soon as
        the program is enqueued, which is what the double buffer's
        overlap stage hides behind)."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            fleet_fused_ingest_step,
            super_fleet_ingest_step,
        )

        kind, T, n, icfg, bases, skey, pair, dbuf, daux = staged
        if kind == "super":
            self._state, *res = super_fleet_ingest_step(
                self._state, dbuf, daux, cfg=icfg
            )
            self.super_dispatches += 1
            self.ticks_super_fused += n
        else:
            self._state, *res = fleet_fused_ingest_step(
                self._state, dbuf, daux, cfg=icfg
            )
        self.dispatch_count += 1
        self.rung_dispatches[T] = self.rung_dispatches.get(T, 0) + 1
        rb = (T, skey[-1])
        self.rung_bucket_dispatches[rb] = (
            self.rung_bucket_dispatches.get(rb, 0) + 1
        )
        self._append_pending(
            res, (kind, tuple(res), icfg, bases, skey, pair)
        )

    def _dispatch_slice(self, sl) -> None:
        self._launch(self._stage_tick(sl))

    def _dispatch_super(self, group, T: int) -> None:
        self._launch(self._stage_super(group, T))

    # -- consumer side -----------------------------------------------------

    def _parse_entries(self, entries) -> list:
        """Per-stream accumulated ``(FilterOutput, ts0, duration)`` lists
        across the given dispatch entries, in dispatch order.  A "tick"
        entry carries one tick's result planes and per-stream bases; a
        "super" entry carries T stacked tick planes with per-tick base
        snapshots (the idle pad ticks parse to all-zero rows)."""
        from rplidar_ros2_driver_tpu.ops.ingest import (
            unpack_fleet_ingest_result,
            unpack_super_fleet_ingest_result,
        )

        out: list = [[] for _ in range(self.streams)]

        def absorb(results, bases):
            for i, res in enumerate(results):
                if res.recon_pushed:
                    self.last_recon[i] = (res.recon_plane, res.recon_pts)
                    self._recon_fresh[i] = True
                    if self.recon_log:
                        self.recon_history[i].append(self.last_recon[i])
                if res.map_wire is not None:
                    # every in-program mapping tick emits a wire (an
                    # idle tick's carries live=0): newest wins, the
                    # freshness flag gates take_map_wires
                    self.last_map_wires[i] = res.map_wire
                    self._map_fresh[i] = True
                self.nodes_decoded += res.nodes_appended
                self.scans_completed += res.n_completed
                self.revs_dropped += res.revs_dropped
                self.stream_scans[i] += res.n_completed
                self.stream_syncs[i] += res.syncs
                self.stream_revs_dropped[i] += res.revs_dropped
                base = bases[i]
                for k in range(res.n_completed):
                    ts0 = (base or 0.0) + float(res.ts0[k])
                    dur = max(float(res.end_ts[k]) - float(res.ts0[k]), 0.0)
                    out[i].append((res.outputs[k], ts0, dur))

        for kind, arrays, icfg, bases, skey, pair in entries:
            if kind == "super":
                ticks = unpack_super_fleet_ingest_result(arrays, icfg)
                for t, results in enumerate(ticks):
                    # bases beyond the staged group are pad ticks: no
                    # completions there, the last snapshot covers them
                    absorb(results, bases[min(t, len(bases) - 1)])
            else:
                absorb(unpack_fleet_ingest_result(arrays, icfg), bases)
            # the unpack above fetched this dispatch's results, proving
            # its staged inputs consumed: the pair is safe to reuse
            self._recycle_staging(skey, pair)
        return out

    def take_recon(self) -> list:
        """Drain the FRESH reconstructed sweeps since the last call: one
        ``(recon_plane, recon_pts)`` or None per stream.  Fresh means a
        parsed dispatch actually pushed a sub-sweep for that stream —
        an idle tick re-emits nothing, so a mapper fed from this seam
        updates exactly once per data tick (the R× update-rate claim of
        bench --config 16), never on stale cache re-reads."""
        out = []
        with self._lock:
            for i in range(self.streams):
                out.append(
                    self.last_recon[i] if self._recon_fresh[i] else None
                )
                self._recon_fresh[i] = False
        return out

    def take_map_wires(self) -> list:
        """Drain the FRESH in-program map wires since the last call:
        one (7,) int32 ``[live, tx_sub, ty_sub, theta_idx, score,
        n_valid, revision]`` or None per stream (None = no mapping tick
        parsed since — distinct from a parsed tick whose ``live`` flag
        is 0, which the service must surface as "no pose this tick"
        rather than republishing a stale one).  The mapping analog of
        :meth:`take_recon`."""
        out = []
        with self._lock:
            for i in range(self.streams):
                out.append(
                    self.last_map_wires[i] if self._map_fresh[i] else None
                )
                self._map_fresh[i] = False
        return out

    def submit(self, items) -> list:
        """One blocking fleet tick: dispatch this tick's bytes and return
        every pending revolution, as per-stream lists of
        ``(FilterOutput, ts0, duration)`` (empty list = no revolution
        completed for that stream).  Includes revolutions from earlier
        pipelined ticks still in flight, in dispatch order."""
        with self._lock:
            self._dispatch_tick(items)
            entries = list(self._pending)
            self._pending.clear()
            return self._parse_entries(entries)

    def submit_backlog(
        self,
        ticks,
        *,
        rung: Optional[int] = None,
        overlap_work=None,
    ) -> list:
        """Drain a BACKLOG of queued fleet ticks — frames that piled up
        behind a link stall or a slow consumer — in
        ``ceil(len(ticks)/T)`` compiled dispatches instead of one per
        tick (one per tick when the super-step is disabled).  ``T`` is
        ``super_tick_max`` by default; ``rung`` overrides it with
        another WARMED ladder depth (parallel/scheduler.py picks it per
        drain from measured backlog — an unwarmed depth is refused).
        ``ticks`` is a list of per-tick item lists, each with the
        :meth:`submit` layout; ticks are normalized IN ORDER (recorder
        tee, per-stream format switches and resets land at their own
        tick) and the whole queue is staged into T-tick super-step
        planes.  Returns every pending revolution as per-stream
        ``(FilterOutput, ts0, duration)`` lists, in tick order —
        bit-exact against submitting the same ticks one by one, for ANY
        rung sequence (the scheduler chooses when, never what).

        ``overlap_work`` (optional zero-arg callable) runs AFTER every
        dispatch is issued and BEFORE their results are fetched — the
        idle half of the double buffer: work queued there (the elastic
        pod's failover snapshot pulls and quarantine checkpoints — row
        gathers the device executes after the in-flight drain, in
        order) hides its latency under the drain's compute instead of
        extending the critical path.  It runs OUTSIDE the engine lock,
        so it may call the snapshot surface (snapshot_stream)."""
        with self._lock:
            slices = []
            for items in ticks:
                slices.extend(self._tick_slices(items))
            self._dispatch_slices(slices, depth=rung)
        if overlap_work is not None:
            overlap_work()
        with self._lock:
            entries = list(self._pending)
            self._pending.clear()
            return self._parse_entries(entries)

    def submit_pipelined(self, items) -> list:
        """Pipelined fleet tick (the ShardedFilterService.submit_pipelined
        discipline): collect the PREVIOUS ticks' landed wires first — their
        device->host copies started at their own dispatch time — then
        dispatch THIS tick's bytes and return the previous outputs.  One
        tick of declared staleness; the publish never waits on this tick's
        device compute.  Returns all-empty lists on the first tick;
        :meth:`flush` drains the last tick when the fleet stops."""
        with self._lock:
            entries = list(self._pending)
            self._pending.clear()
            out = self._parse_entries(entries)
            self._dispatch_tick(items)
            return out

    def flush(self) -> list:
        """Drain every pending wire (fleet stop): per-stream lists of
        ``(FilterOutput, ts0, duration)`` in dispatch order."""
        with self._lock:
            entries = list(self._pending)
            self._pending.clear()
            return self._parse_entries(entries)

    def reset(self) -> None:
        """Fleet stream-state reset (scan stop/start): every stream's
        decode/assembly carries reset at the next dispatch, pending wires
        dropped; the rolling filter windows survive (host-path
        semantics: _begin_streaming resets decoder+assembler, the chain
        persists)."""
        with self._lock:
            self._pending.clear()
            self._stream_fmt = [None] * self.streams
            self._bases = [None] * self.streams
            self._reset_next = [True] * self.streams
            self.last_recon = [None] * self.streams
            self._recon_fresh = [False] * self.streams
            # the in-carry maps SURVIVE a stream reset (host-route
            # semantics: scan stop/start resets decode, not the map) —
            # only the stale wire stash is dropped
            self.last_map_wires = [None] * self.streams
            self._map_fresh = [False] * self.streams

    # -- checkpoint surface ------------------------------------------------

    def snapshot(self) -> dict:
        """Host snapshot of the WHOLE per-stream ingest state — decode
        carries, partial revolutions, rolling filter windows — plus the
        host-side trackers (active formats, timestamp bases).  The
        single-stream engine has no checkpoint surface (its FilterState
        hides inside the donated program state); the fleet engine is the
        one that restarts with a fleet attached, so it gets one.

        Keys: ``ingest.*`` / ``filter.*`` device planes (stream-batched
        numpy), ``formats`` (int32, -1 = never streamed), ``bases``
        (f64, nan = none).  ``median_sorted`` is derived and excluded
        (restore recomputes it), like every other snapshot format."""
        jnp = self._jax.numpy
        with self._lock:
            state = self._jax.tree_util.tree_map(jnp.copy, self._state)
            formats = np.asarray(
                [-1 if f is None else int(f) for f in self._stream_fmt],
                np.int32,
            )
            bases = np.asarray(
                [np.nan if b is None else float(b) for b in self._bases],
                np.float64,
            )
        snap = {
            f"ingest.{k}": np.asarray(v)
            for k, v in vars(state).items()
            if k != "filter" and v is not None
        }
        snap.update({
            f"filter.{k}": np.asarray(v)
            for k, v in vars(state.filter).items()
            if v is not None and k != "median_sorted"
        })
        snap["formats"] = formats
        snap["bases"] = bases
        return snap

    def restore(self, snap: dict) -> bool:
        """Restore a :meth:`snapshot`.  Stream-count or geometry mismatch
        is rejected with the current state untouched; pending wires are
        dropped on success (pre-restore outputs must never publish)."""
        from rplidar_ros2_driver_tpu.ops.filters import (
            FilterState,
            recompute_median_sorted,
        )
        from rplidar_ros2_driver_tpu.ops.ingest import IngestState

        try:
            formats = np.asarray(snap["formats"])
            bases = np.asarray(snap["bases"])
            ing = {
                k[len("ingest."):]: np.asarray(v)
                for k, v in snap.items() if k.startswith("ingest.")
            }
            filt = {
                k[len("filter."):]: np.asarray(v)
                for k, v in snap.items() if k.startswith("filter.")
            }
        except KeyError:
            return False
        if formats.shape != (self.streams,) or ing.get(
            "partial", np.empty(0)
        ).shape != (self.streams, self.max_nodes, 4):
            log.warning(
                "rejecting incompatible fleet ingest snapshot "
                "(streams/geometry mismatch)"
            )
            return False
        # the ingest key space must match this engine's state EXACTLY —
        # including the optional de-skew/reconstruction planes: a
        # deskew-off snapshot installed into a deskew-on engine (or a
        # ring of the wrong geometry) would desync the donated program's
        # state structure at the next dispatch, after the old state was
        # already replaced
        expected_ing = {
            k: tuple(v.shape)
            for k, v in vars(self._state).items()
            if k != "filter" and v is not None
        }
        got_ing = {k: tuple(v.shape) for k, v in ing.items()}
        if expected_ing != got_ing:
            log.warning(
                "rejecting incompatible fleet ingest snapshot "
                "(ingest planes %s != %s)", got_ing, expected_ing,
            )
            return False
        # the filter planes must match this engine's chain geometry too —
        # installing a mismatched window/beams/grid would crash (or
        # silently recompile) the next dispatch AFTER the old state was
        # already replaced (same pre-validation the chain's restore does)
        expected_filter = {
            k: (self.streams, *v)
            for k, v in FilterState.shapes(
                self.cfg.window, self.cfg.beams, self.cfg.grid
            ).items()
        }
        got_filter = {k: tuple(v.shape) for k, v in filt.items()}
        if expected_filter != got_filter:
            log.warning(
                "rejecting incompatible fleet ingest snapshot "
                "(filter geometry %s != %s)", got_filter, expected_filter
            )
            return False
        fstate = FilterState(
            **filt,
            median_sorted=(
                recompute_median_sorted(filt["range_window"])
                if self.cfg.median_backend.startswith("inc") else None
            ),
        )
        state = self._place(IngestState(filter=fstate, **ing))
        with self._lock:
            self._state = state
            self._stream_fmt = [
                None if f < 0 else int(f) for f in formats
            ]
            self._bases = [
                None if np.isnan(b) else float(b) for b in bases
            ]
            self._reset_next = [False] * self.streams
            self._pending.clear()
        return True

    # -- per-stream checkpoint surface (quarantine/rejoin + migration) ----

    def _row_ops(self) -> tuple:
        """The shared dynamic-index row gather/scatter
        (utils/rowops.make_row_ops) with this engine's derived-state
        fixup: the restored window row invalidates its sorted median
        view, so the scatter re-sorts ONLY that row — a whole-fleet
        recompute here measurably dented healthy-lane throughput at
        full geometry (bench --config 13)."""
        ops = getattr(self, "_row_ops_cache", None)
        if ops is not None:
            return ops
        from jax import lax

        from rplidar_ros2_driver_tpu.ops.filters import (
            recompute_median_sorted,
        )
        from rplidar_ros2_driver_tpu.utils.rowops import make_row_ops

        def fixup(new, row, idx):
            if new.filter.median_sorted is None:
                return new
            return dataclasses.replace(
                new,
                filter=dataclasses.replace(
                    new.filter,
                    median_sorted=lax.dynamic_update_index_in_dim(
                        new.filter.median_sorted,
                        recompute_median_sorted(row.filter.range_window),
                        idx, 0,
                    ),
                ),
            )

        ops = make_row_ops(self._jax, fixup=fixup)
        self._row_ops_cache = ops
        return ops

    def _put_row_index(self, i: int):
        """The dynamic stream index as an explicitly placed device
        scalar — committed to the engine's device (or replicated on its
        mesh): an implicit numpy->jit or device->device relayout would
        trip the runtime transfer sentinel."""
        arr = np.asarray(i, np.int32)
        if self.mesh is None:
            return self._jax.device_put(arr, self.device)
        from jax.sharding import NamedSharding, PartitionSpec as P

        return self._jax.device_put(arr, NamedSharding(self.mesh, P()))

    def snapshot_stream(self, i: int) -> dict:
        """One stream's rows of the fleet state, schema-versioned — the
        quarantine checkpoint (parallel/service.py snapshots a stream
        here the moment its health FSM quarantines it) and the unit of
        cross-host stream migration (ROADMAP item 1).

        Device traffic is one row gather (a single compiled program,
        dynamic stream index) plus one explicit ``jax.device_get`` of
        that ROW — O(1/streams) of the fleet state, so a quarantine
        event inside a guarded steady-state loop costs zero recompiles,
        declared transfers only, and no whole-fleet fetch."""
        if not (0 <= i < self.streams):
            raise IndexError(f"stream {i} out of range [0, {self.streams})")
        gather, _ = self._row_ops()
        with self._lock:
            row = self._jax.device_get(
                gather(self._state, self._put_row_index(i))
            )
            fmt = self._stream_fmt[i]
            base = self._bases[i]
        snap = {
            f"ingest.{k}": np.array(v)
            for k, v in vars(row).items()
            if k != "filter" and v is not None
        }
        snap.update({
            f"filter.{k}": np.array(v)
            for k, v in vars(row.filter).items()
            if v is not None and k != "median_sorted"
        })
        snap["format"] = np.asarray(-1 if fmt is None else int(fmt), np.int32)
        snap["base"] = np.asarray(
            np.nan if base is None else float(base), np.float64
        )
        snap["version"] = np.asarray(INGEST_STREAM_SNAPSHOT_VERSION, np.int32)
        return snap

    def restore_stream(
        self, i: int, snap: dict, *, restore_decode: bool = False
    ) -> bool:
        """Install a :meth:`snapshot_stream` into lane ``i`` with every
        OTHER stream's state — and the pending pipelined wires —
        untouched (a rejoining stream must not cost its healthy
        neighbors an in-flight revolution, unlike the whole-fleet
        :meth:`restore`).

        By default the rolling filter window is restored and the decode
        /assembly carries are RESET (``_reset_next``), because a rejoin
        after quarantine re-enters the byte stream at an arbitrary
        capsule boundary — exactly the host path's decoder+assembler
        reset with the chain carried through.  ``restore_decode=True``
        additionally restores the decode rows (same-stream resume, e.g.
        migration of a live stream between hosts).

        Version or geometry mismatch is rejected with the state
        untouched.  Device traffic is row-sized and fully declared: one
        row gather, explicit puts of the snapshot rows, one row scatter
        (dynamic-index programs shared across streams and warmed by
        ``attach_health``-style callers before steady state)."""
        from rplidar_ros2_driver_tpu.ops.filters import FilterState

        if not (0 <= i < self.streams):
            raise IndexError(f"stream {i} out of range [0, {self.streams})")
        ver = int(np.asarray(snap.get("version", -1)))
        if ver != INGEST_STREAM_SNAPSHOT_VERSION:
            log.warning(
                "rejecting stream snapshot with schema version %s (want %d)",
                snap.get("version"), INGEST_STREAM_SNAPSHOT_VERSION,
            )
            return False
        expected_filter = FilterState.shapes(
            self.cfg.window, self.cfg.beams, self.cfg.grid
        )
        got_filter = {
            k[len("filter."):]: tuple(np.asarray(v).shape)
            for k, v in snap.items() if k.startswith("filter.")
        }
        if expected_filter != got_filter:
            log.warning(
                "rejecting incompatible stream snapshot "
                "(filter geometry %s != %s)", got_filter, expected_filter,
            )
            return False
        gather, scatter = self._row_ops()
        with self._lock:
            idx = self._put_row_index(i)
            cur = gather(self._state, idx)  # current row: the template
            filt_rows = {}
            for k, v in vars(cur.filter).items():
                if v is None or k == "median_sorted":
                    continue
                row = np.asarray(snap[f"filter.{k}"])
                # the template leaf's own sharding: an unplaced put
                # would force a device->device relayout inside the
                # scatter jit, which the transfer sentinel forbids
                filt_rows[k] = self._jax.device_put(
                    row.astype(v.dtype, copy=False), v.sharding
                )
            new_row = dataclasses.replace(
                cur, filter=dataclasses.replace(cur.filter, **filt_rows)
            )
            if restore_decode:
                # same-stream resume: the snapshot's ingest key space
                # must cover THIS engine's state exactly — a deskew-off
                # snapshot silently skipped here would leave the lane's
                # previous occupant's recon_ring/profile/motion in place
                # (and restore_decode suppresses the reset that would
                # otherwise clear them), attributing another stream's
                # sub-sweep cache to the migrated stream
                expected_keys = {
                    f"ingest.{k}" for k, v in vars(cur).items()
                    if k != "filter" and v is not None
                }
                got_keys = {k for k in snap if k.startswith("ingest.")}
                if expected_keys != got_keys:
                    log.warning(
                        "rejecting incompatible stream snapshot "
                        "(ingest keys %s != %s)",
                        sorted(got_keys), sorted(expected_keys),
                    )
                    return False
                ing_rows = {}
                for k, v in vars(cur).items():
                    if k == "filter" or v is None:
                        continue
                    key = f"ingest.{k}"
                    row = np.asarray(snap[key])
                    if row.shape != tuple(v.shape):
                        log.warning(
                            "rejecting incompatible stream snapshot "
                            "(ingest %s row %s != %s)",
                            k, row.shape, tuple(v.shape),
                        )
                        return False
                    ing_rows[k] = self._jax.device_put(
                        row.astype(v.dtype, copy=False), v.sharding
                    )
                new_row = dataclasses.replace(new_row, **ing_rows)
            self._state = scatter(self._state, new_row, idx)
            fmt = int(np.asarray(snap.get("format", -1)))
            self._stream_fmt[i] = None if fmt < 0 else fmt
            if restore_decode:
                base = float(np.asarray(snap.get("base", np.nan)))
                self._bases[i] = None if np.isnan(base) else base
                self._reset_next[i] = False
            else:
                self._bases[i] = None
                self._reset_next[i] = True
        return True

    # -- in-program map surface (mapping/mapper.CarriedFleetMapper) --------

    _MAP_KEYS = ("log_odds", "pose", "origin_xy", "revision")

    def _require_mapping(self) -> None:
        if self._mapping is None:
            raise RuntimeError(
                "this engine carries no in-program map (the fused "
                "mapping route is off — fused_mapping_backend)"
            )

    def map_snapshot(self) -> dict:
        """Host copy of every stream's in-carry MapState, in the
        FleetMapper snapshot key space (stream-batched ``log_odds`` /
        ``pose`` / ``origin_xy`` / ``revision``) so carried and
        host-route map checkpoints interoperate."""
        self._require_mapping()
        with self._lock:
            st = self._state
            return {
                k: np.asarray(getattr(st, f"map_{k}"))
                for k in self._MAP_KEYS
            }

    def map_restore(self, core: dict) -> None:
        """Install stream-batched MapState planes into the carry (shape
        pre-validated by the caller — the carried mapper view mirrors
        FleetMapper's reject-don't-crash contract).  Each leaf is an
        explicit put at the live leaf's own sharding."""
        self._require_mapping()
        with self._lock:
            st = self._state
            leaves = {}
            for k in self._MAP_KEYS:
                cur = getattr(st, f"map_{k}")
                leaves[f"map_{k}"] = self._jax.device_put(
                    np.asarray(core[k]).astype(cur.dtype, copy=False),
                    cur.sharding,
                )
            self._state = dataclasses.replace(st, **leaves)

    def map_snapshot_stream(self, i: int) -> dict:
        """One stream's in-carry MapState row (FleetMapper key space) —
        one row gather + one explicit row fetch, the quarantine-
        checkpoint discipline."""
        self._require_mapping()
        if not (0 <= i < self.streams):
            raise IndexError(f"stream {i} out of range [0, {self.streams})")
        gather, _ = self._row_ops()
        with self._lock:
            row = self._jax.device_get(
                gather(self._state, self._put_row_index(i))
            )
        return {
            k: np.array(getattr(row, f"map_{k}")) for k in self._MAP_KEYS
        }

    def map_restore_stream(self, i: int, core: dict) -> None:
        """Install one stream's MapState row into the carry with every
        other stream — and the decode/filter rows of THIS stream —
        untouched (row gather, explicit row puts, row scatter: the same
        warmed programs the per-stream checkpoint path runs)."""
        self._require_mapping()
        if not (0 <= i < self.streams):
            raise IndexError(f"stream {i} out of range [0, {self.streams})")
        gather, scatter = self._row_ops()
        with self._lock:
            idx = self._put_row_index(i)
            cur = gather(self._state, idx)
            rows = {}
            for k in self._MAP_KEYS:
                leaf = getattr(cur, f"map_{k}")
                rows[f"map_{k}"] = self._jax.device_put(
                    np.asarray(core[k]).astype(leaf.dtype, copy=False),
                    leaf.sharding,
                )
            self._state = scatter(
                self._state, dataclasses.replace(cur, **rows), idx
            )

    def map_reanchor_stream(self, i: int, pose: np.ndarray) -> None:
        """Rewrite one stream's in-carry front-end pose (the loop-
        closure re-anchor path, FleetMapper.reanchor_stream's carried
        twin): row gather, one explicit (3,) put, row scatter."""
        self._require_mapping()
        if not (0 <= i < self.streams):
            raise IndexError(f"stream {i} out of range [0, {self.streams})")
        gather, scatter = self._row_ops()
        with self._lock:
            idx = self._put_row_index(i)
            cur = gather(self._state, idx)
            row = dataclasses.replace(
                cur,
                map_pose=self._jax.device_put(
                    np.asarray(pose, np.int32), cur.map_pose.sharding
                ),
            )
            self._state = scatter(self._state, row, idx)
