"""RealLidarDriver — hardware backend over the native I/O runtime.

Equivalent of the reference's ``RealLidarDriver`` wrapper plus the driver
core it delegates to (src/lidar_driver_wrapper.cpp:97-405 over
sl_lidar_driver.cpp), re-composed for this framework:

  * transport: native C++ channel + transceiver (native/src/*.cc) selected
    by ``channel_type`` (serial/tcp/udp — the reference's channel factories,
    sl_lidar_driver.h:260-274)
  * request plane: CommandEngine (protocol/engine.py) + conf protocol
    (protocol/conf.py)
  * scan plane: measurement frames stream off the pump thread in natural
    runs into the vectorized batch decoder (driver/decode.BatchScanDecoder
    over ops/unpack.py, CPU-pinned jit; ops/unpack_ref.py is the scalar
    golden oracle) and assemble into revolutions
    (driver/assembly.ScanAssembler, the ScanDataHolder equivalent)
  * strategy: model detection via models/tables.detect_profile; start_motor
    follows the reference's two strategies (src/lidar_driver_wrapper.cpp:
    193-268): NEW_TYPE = RPM control + mode enumeration with
    user-pref → DenseBoost → Sensitivity fallback + express scan;
    OLD_TYPE = 600 RPM default + startScan(0, 1)'s typical-mode path —
    conf-resolved typical mode when the firmware speaks the conf
    protocol, hardwired EXPRESS fallback when it predates it
    (sl_lidar_driver.cpp:577-580).  Every conf query is gated on
    checkSupportConfigCommands semantics (:1176-1196).
"""

from __future__ import annotations

import logging
import struct
import threading
import time
from typing import Callable, Optional

from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.core.types import ScanBatch
from rplidar_ros2_driver_tpu.driver.assembly import RawNodeHolder, ScanAssembler
from rplidar_ros2_driver_tpu.driver.decode import BatchScanDecoder
from rplidar_ros2_driver_tpu.driver.interface import LidarDriverInterface
from rplidar_ros2_driver_tpu.models.tables import (
    A2A3_MINUM_MAJOR_ID,
    DeviceInfo,
    DriverProfile,
    MajorType,
    MotorCtrlSupport,
    ProtocolType,
    ScanMode,
    detect_profile,
    has_builtin_motor_ctrl,
    major_type,
    native_baudrate,
    supports_conf_commands,
)
from rplidar_ros2_driver_tpu.protocol import conf as confproto
from rplidar_ros2_driver_tpu.protocol.constants import (
    ACC_BOARD_FLAG_MOTOR_CTRL_SUPPORT_MASK,
    Ans,
    AUTOBAUD_CONFIRM_FLAG,
    AUTOBAUD_MAGICBYTE,
    Cmd,
    SCAN_COMMAND_EXPRESS,
    SCAN_COMMAND_STD,
)
from rplidar_ros2_driver_tpu.protocol import timing as timingmod
from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine, TransceiverLike

log = logging.getLogger("rplidar_tpu.real")

DEFAULT_RPM = 600  # src/lidar_driver_wrapper.cpp:187,262
LEGACY_MAX_DISTANCE = 12.0
NEW_TYPE_MAX_DISTANCE = 40.0


def _default_transceiver_factory(
    channel_type: str, port: str, baudrate: int, host: str, net_port: int
) -> TransceiverLike:
    """Native C++ transport when the library builds/loads; otherwise the
    pure-Python twin (protocol/pytransport.py) with a one-time notice —
    same contracts, no SCHED_RR rx elevation."""
    if channel_type not in ("serial", "tcp", "udp"):
        raise ValueError(f"unsupported channel_type {channel_type!r}")

    def make_channel(channel_cls):
        # NativeChannel and PyChannel are deliberate duck-type twins
        if channel_type == "serial":
            return channel_cls("serial", port, baud=baudrate)
        return channel_cls(channel_type, host, port=net_port)

    try:
        from rplidar_ros2_driver_tpu.native.runtime import NativeChannel, NativeTransceiver

        return NativeTransceiver(make_channel(NativeChannel))
    except Exception as e:
        from rplidar_ros2_driver_tpu.native import NativeUnavailable

        if not isinstance(e, NativeUnavailable):
            raise
        log.warning("native I/O library unavailable (%s); using the "
                    "pure-Python transport fallback", e)
        from rplidar_ros2_driver_tpu.protocol.pytransport import PyChannel, PyTransceiver

        return PyTransceiver(make_channel(PyChannel))


class RealLidarDriver(LidarDriverInterface):
    """Hardware driver: native transport + command engine + scan decode."""

    def __init__(
        self,
        channel_type: str = "serial",
        *,
        tcp_host: str = "192.168.0.7",
        tcp_port: int = 20108,
        udp_host: str = "192.168.11.2",
        udp_port: int = 8089,
        transceiver_factory: Optional[Callable[..., TransceiverLike]] = None,
        motor_warmup_s: float = 1.0,   # ref waits 1 s after setMotorSpeed (:197)
        legacy_warmup_s: float = 0.2,  # ref waits 200 ms on OLD_TYPE (:264)
        ingest_sink=None,
    ) -> None:
        self._channel_type = channel_type
        self._tcp = (tcp_host, tcp_port)
        self._udp = (udp_host, udp_port)
        self._tx_factory = transceiver_factory or _default_transceiver_factory
        self._motor_warmup_s = motor_warmup_s
        self._legacy_warmup_s = legacy_warmup_s

        self._engine: Optional[CommandEngine] = None
        self._assembler = ScanAssembler()
        self._raw_holder = RawNodeHolder()
        # the ingest seam: the measurement-frame consumer wired into the
        # engine pump.  Default: the host golden path (BatchScanDecoder
        # -> ScanAssembler -> grab_scan_host).  A fused sink
        # (driver/ingest.FusedIngest, ingest_backend="fused") implements
        # the same producer interface but runs decode + revolution
        # assembly + the filter step device-resident; revolutions are
        # then consumed via grab_filtered, not grab_scan_*.
        self._scan_decoder = ingest_sink or BatchScanDecoder(
            self._assembler, self._raw_holder
        )
        self._fused_ingest = ingest_sink
        self._lock = threading.RLock()
        self._connected = False
        self._scanning = False
        self._baudrate = 0
        self._angle_compensate = True
        self.device_info: Optional[DeviceInfo] = None
        self.profile = DriverProfile()
        self.scan_modes: list = []
        self.motor_ctrl = MotorCtrlSupport.NONE
        # conf-protocol gate (checkSupportConfigCommands): set on connect;
        # every GET/SET_LIDAR_CONF path checks it so a pre-conf device is
        # never sent a query it would silently time out on
        self.conf_supported = False
        # connect/reconnect observability (/diagnostics): how many
        # connect calls this driver object has made and how many failed.
        # The retry PACING lives in the scan-loop FSM's capped-backoff
        # policy (node/fsm.py); these counters survive only as long as
        # the driver object, so the FSM's cumulative count is the
        # session-level truth
        self.connect_attempts = 0
        self.connect_failures = 0

    # ------------------------------------------------------------------
    # connection
    # ------------------------------------------------------------------

    def connect(self, port: str, baudrate: int, use_geometric_compensation: bool) -> bool:
        with self._lock:
            if self._connected:
                return True
            self.connect_attempts += 1
            ok = self._connect_locked(port, baudrate, use_geometric_compensation)
            if not ok:
                self.connect_failures += 1
            return ok

    def _connect_locked(
        self, port: str, baudrate: int, use_geometric_compensation: bool
    ) -> bool:
        self._angle_compensate = use_geometric_compensation
        self._baudrate = baudrate
        try:
            tx = self._tx_factory(
                self._channel_type, port, baudrate, *self._net_target()
            )
        except Exception as e:
            log.error("channel creation failed: %s", e)
            return False
        engine = CommandEngine(
            tx, on_measurement_batch=self._scan_decoder.on_measurement_batch
        )
        if not engine.start():
            log.warning("could not open %s channel on %s", self._channel_type, port)
            return False
        # quiesce any previous streaming, then identify the device
        engine.send_only(Cmd.STOP)
        time.sleep(0.01)
        engine.reset_decoder()
        info_payload = engine.request(
            Cmd.GET_DEVICE_INFO, Ans.DEVINFO, timeout_s=1.0
        )
        if info_payload is None or len(info_payload) < 20:
            log.warning("device did not answer GET_DEVICE_INFO")
            engine.stop()
            return False
        self.device_info = DeviceInfo.from_payload(info_payload)
        self.conf_supported = supports_conf_commands(self.device_info)
        self._engine = engine
        self._connected = True
        self.motor_ctrl = self._check_motor_ctrl_support()
        log.info(
            "connected: %s (motor ctrl: %s)",
            self.device_info.summary(),
            self.motor_ctrl.value,
        )
        return True

    def _net_target(self) -> tuple[str, int]:
        return self._tcp if self._channel_type == "tcp" else self._udp

    def disconnect(self) -> None:
        with self._lock:
            if self._engine is not None:
                if self._scanning:
                    try:
                        self.stop_motor()
                    except Exception:
                        pass
                self._engine.stop()
                self._engine = None
            self._connected = False
            self._scanning = False
            self._assembler.reset()
            self._raw_holder.reset()
            self._scan_decoder.reset()

    def is_connected(self) -> bool:
        with self._lock:
            if self._engine is not None and not self._engine.healthy:
                return False  # hot-unplug detected by the pump thread
            return self._connected

    # ------------------------------------------------------------------
    # strategy detection (src/lidar_driver_wrapper.cpp:145-178)
    # ------------------------------------------------------------------

    def detect_and_init_strategy(self) -> None:
        with self._lock:
            if self.device_info is None:
                return
            self.profile = detect_profile(self.device_info, self._angle_compensate)

    # ------------------------------------------------------------------
    # motor + scan startup (src/lidar_driver_wrapper.cpp:180-268)
    # ------------------------------------------------------------------

    def start_motor(self, scan_mode: str, rpm: int) -> bool:
        with self._lock:
            if self._engine is None:
                return False
            if self.profile.protocol is ProtocolType.NEW_TYPE:
                return self._start_new_type(scan_mode, rpm)
            return self._start_old_type(rpm)

    def _start_new_type(self, scan_mode: str, rpm: int) -> bool:
        target_rpm = rpm if rpm > 0 else DEFAULT_RPM
        if not self.set_motor_speed(target_rpm):
            return False
        time.sleep(self._motor_warmup_s)
        if not self.conf_supported:
            # cannot happen for a genuine NEW_TYPE unit (ND magic implies
            # conf support) — but if a device misreports, degrade the way
            # a pre-conf triangle would rather than fire doomed queries
            log.warning("device reports no conf support; using the legacy "
                        "Express fallback")
            return self._start_legacy_express(target_rpm)
        self.scan_modes = confproto.enumerate_scan_modes(self._engine)
        mode = self._select_mode(scan_mode)
        if mode is None:
            log.error("no usable scan mode enumerated")
            return False
        return self._start_express(mode, target_rpm)

    def _select_mode(self, preferred: str):
        """user pref -> 'DenseBoost' -> 'Sensitivity' -> typical/first
        (src/lidar_driver_wrapper.cpp:207-245)."""
        if not self.scan_modes:
            return None
        by_name = {m.name: m for m in self.scan_modes}
        if preferred and preferred in by_name:
            return by_name[preferred]
        if preferred:
            log.warning("scan mode %r not supported; falling back to auto", preferred)
        for fallback in ("DenseBoost", "Sensitivity"):
            if fallback in by_name:
                return by_name[fallback]
        typical = confproto.get_typical_mode(self._engine)
        if typical is not None:
            for m in self.scan_modes:
                if m.id == typical:
                    return m
        return self.scan_modes[0]

    def _start_express(
        self, mode, target_rpm: int, *, wire_mode: Optional[int] = None,
        update_hw_max: bool = True,
    ) -> bool:
        # EXPRESS_SCAN payload: u8 mode, u16 flags, u16 reserved
        # (startScanExpress, sl_lidar_driver.cpp:745-758).  working_flags
        # stays 0 like the reference wrapper's startScanExpress(false, id, 0)
        # call (src/lidar_driver_wrapper.cpp:249): the mode id alone selects
        # boost variants; setting EXPRESS_FLAG_BOOST here could make real
        # firmware stream a format that mismatches the enumerated ans_type.
        # ``wire_mode`` overrides the payload mode byte — pre-conf firmware
        # expects 0 there (startScanExpress :748-750) while the metadata
        # mode id stays SCAN_COMMAND_EXPRESS.
        self._update_timing_desc(mode.us_per_sample)
        # warm the decode-kernel jit cache for this mode's wire format before
        # the stream starts, so the pump thread never stalls on a compile
        self._scan_decoder.precompile(mode.ans_type)
        self._begin_streaming()
        payload = struct.pack(
            "<BHH", mode.id if wire_mode is None else wire_mode, 0, 0
        )
        if not self._engine.send_only(Cmd.EXPRESS_SCAN, payload):
            return False
        # graftlint: disable=GL012 — helper reached only from start_scan/
        # _start_old_type, whose public entries hold self._lock (RLock)
        self._scanning = True
        self.profile.active_mode = mode.name
        self.profile.active_rpm = target_rpm
        if update_hw_max:
            self.profile.hw_max_distance = mode.max_distance or NEW_TYPE_MAX_DISTANCE
        return True

    def force_scan(self, rpm: int = 0) -> bool:
        """FORCE_SCAN (cmd 0x21): start streaming regardless of the
        device-side health gate (startScan(force=true),
        sl_lidar_driver.cpp:586-616).  Legacy wire format."""
        with self._lock:
            if self._engine is None:
                return False
            target_rpm = rpm if rpm > 0 else DEFAULT_RPM
            self.set_motor_speed(target_rpm)
            time.sleep(self._legacy_warmup_s)
            self._update_timing_desc(self._legacy_sample_durations()[0])
            self._scan_decoder.precompile(Ans.MEASUREMENT)
            self._begin_streaming()
            if not self._engine.send_only(Cmd.FORCE_SCAN):
                return False
            self._scanning = True
            self.profile.active_mode = "Standard (forced)"
            self.profile.active_rpm = target_rpm
            return True

    def _start_old_type(self, rpm: int) -> bool:
        # legacy strategy: fixed 600 RPM, brief spin-up, then the
        # reference wrapper's startScan(0, 1) — useTypicalScan
        # (src/lidar_driver_wrapper.cpp:262-268 -> sl_lidar_driver.cpp:
        # 586-616): the typical mode comes from the conf protocol when the
        # firmware speaks it, and is hardwired to the EXPRESS scan command
        # on pre-conf triangle units (getTypicalScanMode :577-580) — those
        # must never be sent a conf query at all.
        self.set_motor_speed(DEFAULT_RPM)
        time.sleep(self._legacy_warmup_s)
        if not self.conf_supported:
            return self._start_legacy_express(DEFAULT_RPM)
        typical = confproto.get_typical_mode(self._engine)
        if typical is not None and typical != SCAN_COMMAND_STD:
            mode = confproto.get_mode_metadata(self._engine, typical)
            if mode is not None and mode.ans_type != Ans.MEASUREMENT:
                return self._start_express(mode, DEFAULT_RPM, update_hw_max=False)
        # typical resolved to Standard (or its metadata didn't): plain scan
        # (startScanNormal_commonpath redirect, sl_lidar_driver.cpp:732-735)
        return self._start_standard_scan()

    def _start_standard_scan(self) -> bool:
        """Plain SCAN startup with device-queried sample duration
        (startScanNormal_commonpath, sl_lidar_driver.cpp:620-661)."""
        std_us, _ = self._legacy_sample_durations()
        self._update_timing_desc(std_us)
        self._scan_decoder.precompile(Ans.MEASUREMENT)
        self._begin_streaming()
        if not self._engine.send_only(Cmd.SCAN):
            return False
        # graftlint: disable=GL012 — helper reached only from start_scan/
        # _start_old_type, whose public entries hold self._lock (RLock)
        self._scanning = True
        self.profile.active_mode = "Standard"
        self.profile.active_rpm = DEFAULT_RPM
        return True

    def _start_legacy_express(self, target_rpm: int) -> bool:
        """Express startup for pre-conf firmware (startScanExpress legacy
        branch, sl_lidar_driver.cpp:716-729): no conf queries — metadata is
        fixed to the GET_SAMPLERATE express duration, 16 m, the capsule
        stream format, name "Express" — and the EXPRESS_SCAN payload's
        working_mode byte stays 0 (:748-750).  hw_max_distance keeps the
        wrapper's 12 m A-series profile value (the 16 m here is SDK mode
        metadata, not the wrapper profile)."""
        _, express_us = self._legacy_sample_durations()
        mode = ScanMode(
            id=SCAN_COMMAND_EXPRESS,
            us_per_sample=express_us,
            max_distance=16.0,
            ans_type=Ans.MEASUREMENT_CAPSULED,
            name="Express",
        )
        return self._start_express(
            mode, target_rpm, wire_mode=0, update_hw_max=False
        )


    def _update_timing_desc(self, us_per_sample: Optional[float]) -> None:
        """Push link+mode timing into the decoder for timestamp back-dating
        (_updateTimingDesc -> unpacker context, sl_lidar_driver.cpp:1538-1554):
        the device model's NATIVE baud (sl_lidar_driver.cpp:1540) drives the
        transmission-delay model, falling back to the link baud, then to the
        per-format defaults; linkage delay is 0 like the reference (:1547)."""
        native = 0
        if self.device_info is not None:
            native = native_baudrate(
                self.device_info.model, self.device_info.hardware_version
            )
        self._scan_decoder.timing = timingmod.TimingDesc(
            sample_duration_us=us_per_sample or timingmod.LEGACY_SAMPLE_DURATION_US,
            native_baudrate=native or self._baudrate,
            is_serial=self._channel_type == "serial",
        )

    def _legacy_sample_durations(self) -> tuple[float, float]:
        """(std, express) sample durations for legacy (non-conf) scan
        startup, queried from the device via GET_SAMPLERATE (cmd 0x59 ->
        ans 0x15, two u16 LE: std/express µs) — _getLegacySampleDuration_uS,
        sl_lidar_driver.cpp:1556-1599.  Very old A-series firmware
        (< 1.17) predates the command and always gets the 476 µs default
        for both (:1559-1567)."""
        default = timingmod.LEGACY_SAMPLE_DURATION_US
        if self.device_info is not None:
            is_a_series = major_type(self.device_info.model) is MajorType.A_SERIES
            if is_a_series and self.device_info.firmware_version < ((0x1 << 8) | 17):
                return default, default
        ans = self._engine.request(
            Cmd.GET_SAMPLERATE, Ans.SAMPLE_RATE, timeout_s=1.0
        )
        if ans is None or len(ans) < 4:
            return default, default
        std_us, express_us = struct.unpack_from("<HH", ans)
        return float(std_us) or default, float(express_us) or default

    def _begin_streaming(self) -> None:
        self._engine.send_only(Cmd.STOP)
        time.sleep(0.002)
        self._engine.reset_decoder()
        self._assembler.reset()
        self._raw_holder.reset()
        self._scan_decoder.reset()

    def stop_motor(self) -> None:
        with self._lock:
            if self._engine is None:
                return
            self._engine.send_only(Cmd.STOP)
            self._scanning = False
            self._engine.reset_decoder()
            # speed 0 stops every motor variant: RPM/PWM command 0, or DTR
            # raised on DTR-driven A-series units
            self.set_motor_speed(0)

    def _check_motor_ctrl_support(self) -> MotorCtrlSupport:
        """3-way capability probe (checkMotorCtrlSupport,
        sl_lidar_driver.cpp:833-878): built-in RPM control for major id
        >= 6; A2/A3-class units ask the accessory board (cmd 0xFF, u32
        reserved payload) and get PWM if bit 0 of the answer is set;
        everything else is DTR-toggled."""
        if self.device_info is None:
            return MotorCtrlSupport.NONE
        if has_builtin_motor_ctrl(self.device_info.model):
            return MotorCtrlSupport.RPM
        major = self.device_info.model >> 4
        if major >= A2A3_MINUM_MAJOR_ID:
            ans = self._engine.request(
                Cmd.GET_ACC_BOARD_FLAG,
                Ans.ACC_BOARD_FLAG,
                struct.pack("<I", 0),
                timeout_s=0.5,
            )
            if ans is not None and len(ans) >= 4:
                flag = struct.unpack_from("<I", ans)[0]
                if flag & ACC_BOARD_FLAG_MOTOR_CTRL_SUPPORT_MASK:
                    return MotorCtrlSupport.PWM
        return MotorCtrlSupport.NONE

    def set_motor_speed(self, rpm: Optional[int] = None) -> bool:
        """3-way motor control (setMotorSpeed, sl_lidar_driver.cpp:968-1021):
        RPM via cmd 0xA8, accessory-board PWM via 0xF0, otherwise the serial
        DTR line (clear = run, set = stop).  ``rpm=None`` asks the device for
        its desired speed (DESIRED_ROT_FREQ), defaulting to 600."""
        with self._lock:
            if self._engine is None:
                return False
            if rpm is None:
                # DTR-driven legacy units can't use a fetched speed (the DTR
                # path only distinguishes stop/run) — skip the blocking conf
                # query there, and on any pre-conf device (the gate).
                desired = (
                    confproto.get_desired_speed(self._engine)
                    if self.conf_supported
                    and self.motor_ctrl is not MotorCtrlSupport.NONE
                    else None
                )
                if desired is not None:
                    rpm_d, pwm_ref = desired
                    rpm = pwm_ref if self.motor_ctrl is MotorCtrlSupport.PWM else rpm_d
                else:
                    rpm = DEFAULT_RPM
            if self.motor_ctrl is MotorCtrlSupport.RPM:
                return self._engine.send_only(
                    Cmd.HQ_MOTOR_SPEED_CTRL, struct.pack("<H", rpm)
                )
            if self.motor_ctrl is MotorCtrlSupport.PWM:
                return self._engine.send_only(
                    Cmd.SET_MOTOR_PWM, struct.pack("<H", rpm)
                )
            # no motor controller: DTR low spins the motor, high stops it
            channel = self._engine.channel
            if channel is not None and getattr(channel, "kind", "") == "serial":
                return bool(channel.set_dtr(rpm == 0))
            return True  # network units have no host-driven motor line

    def _conf_engine(self) -> Optional[CommandEngine]:
        """The engine iff conf queries are allowed — None keeps every
        conf getter a clean miss on pre-conf firmware (the gate)."""
        return self._engine if self.conf_supported else None

    def get_motor_info(self) -> Optional[confproto.MotorInfo]:
        """min/max/desired rotation speed (getMotorInfo :1023-1056)."""
        with self._lock:
            engine = self._conf_engine()
            if engine is None:
                return None
            return confproto.get_motor_info(
                engine, pwm_ctrl=self.motor_ctrl is MotorCtrlSupport.PWM
            )

    def get_mac_addr(self) -> Optional[bytes]:
        with self._lock:
            engine = self._conf_engine()
            return confproto.get_mac_addr(engine) if engine else None

    def get_ip_conf(self) -> Optional[confproto.IpConf]:
        with self._lock:
            engine = self._conf_engine()
            return confproto.get_ip_conf(engine) if engine else None

    def set_ip_conf(self, conf: confproto.IpConf) -> bool:
        with self._lock:
            engine = self._conf_engine()
            return confproto.set_ip_conf(engine, conf) if engine else False

    # ------------------------------------------------------------------
    # serial autobaud negotiation (sl_lidar_driver.cpp:1058-1155)
    # ------------------------------------------------------------------

    def negotiate_serial_baud(self, required_baud: int) -> Optional[int]:
        """Ask the device to measure and switch its UART baud rate.

        Serial-only.  The transceiver is shut down so the raw channel can
        be driven directly: stream 16-byte bursts of the 0x41 magic for up
        to 1.5 s (the device needs >100 B/s to trigger measurement), read
        back the 4-byte detected bps, then restart the transceiver and —
        only when the device measured the ``required_baud`` we are already
        transmitting at — confirm with NEW_BAUDRATE_CONFIRM
        {0x5F5F, required_bps, 0}.  An unconfirmed device reverts, which
        is exactly what we want on a mismatch: confirming a rate different
        from the host channel's would switch the device's UART away from
        the link we keep using.  Returns the detected bps, or None.
        """
        with self._lock:
            if self._engine is None:
                return None
            channel = self._engine.channel
            if channel is None or getattr(channel, "kind", "") != "serial":
                return None
            self._engine.send_only(Cmd.STOP)
            self._scanning = False
            self._engine.stop()  # closes the channel; we reopen it raw
            detected: Optional[int] = None
            try:
                if not channel.open():
                    return None
                magic = bytes([AUTOBAUD_MAGICBYTE]) * 16
                deadline = time.monotonic() + 1.5
                while time.monotonic() < deadline:
                    if channel.write(magic) < 0:
                        break
                    first = channel.read(1, timeout_ms=1)
                    if first:
                        # device replied: collect the 4-byte measured bps
                        raw = bytearray(first)
                        stop_at = time.monotonic() + 0.5
                        while len(raw) < 4 and time.monotonic() < stop_at:
                            more = channel.read(4 - len(raw), timeout_ms=100)
                            if more:
                                raw += more
                        if len(raw) >= 4:
                            detected = struct.unpack_from("<I", raw)[0]
                        break
            finally:
                channel.close()
                restarted = self._engine.start()
            if detected is None or not restarted:
                return None
            if detected == required_baud:
                self._engine.send_only(
                    Cmd.NEW_BAUDRATE_CONFIRM,
                    struct.pack("<HIH", AUTOBAUD_CONFIRM_FLAG, required_baud, 0),
                )
            return detected

    # ------------------------------------------------------------------
    # health / reset / info
    # ------------------------------------------------------------------

    def get_health(self) -> DeviceHealth:
        with self._lock:
            if self._engine is None:
                return DeviceHealth.ERROR
            ans = self._engine.request(Cmd.GET_DEVICE_HEALTH, Ans.DEVHEALTH, timeout_s=1.0)
        if ans is None or len(ans) < 3:
            return DeviceHealth.ERROR
        status = ans[0]
        if status >= 2:
            return DeviceHealth.ERROR
        return DeviceHealth(status)

    def reset(self) -> None:
        with self._lock:
            if self._engine is not None:
                self._engine.send_only(Cmd.RESET)

    def get_device_info_str(self) -> str:
        return self.device_info.summary() if self.device_info else "N/A"

    def rx_scheduling_class(self) -> Optional[int]:
        """Scheduling class the rx thread achieved (2 = SCHED_RR,
        1 = nice boost, 0 = default, -1 = transport without elevation);
        None when disconnected.  Surfaces in /diagnostics and the bench
        artifacts — the observable for the reference's PRIORITY_HIGH
        contract (sl_async_transceiver.cpp:299-409).

        Deliberately lock-free: the driver RLock is held across
        multi-second connect/disconnect/reset sequences, and diagnostics
        must never stall behind them.  One atomic attribute read; a
        mid-teardown engine still answers its (plain-int) property."""
        engine = self._engine
        return engine.rx_priority if engine is not None else None

    def print_summary(self) -> None:
        for line in self.profile.summary_lines():
            log.info("%s", line)

    def get_hw_max_distance(self) -> float:
        return self.profile.hw_max_distance

    def get_frequency(self, node_count: int) -> Optional[float]:
        """Scan rate in Hz derived from the active mode's sample duration
        and the points in one revolution (getFrequency,
        sl_lidar_driver.cpp:880-885).  None before a mode is active."""
        us = self._scan_decoder.timing.sample_duration_us
        if not self._scanning or us <= 0 or node_count <= 0:
            return None
        return 1e6 / (us * node_count)

    def is_new_type(self) -> bool:
        return self.profile.protocol is ProtocolType.NEW_TYPE

    # ------------------------------------------------------------------
    # scan consumption
    # ------------------------------------------------------------------

    def grab_scan_data(self, timeout_s: float = 2.0) -> Optional[ScanBatch]:
        got = self.grab_scan_data_with_timestamp(timeout_s)
        return got[0] if got is not None else None

    def grab_scan_data_with_timestamp(
        self, timeout_s: float = 2.0
    ) -> Optional[tuple[ScanBatch, float, float]]:
        """(batch, back-dated revolution-begin time, measured duration) —
        grabScanDataHqWithTimeStamp parity (sl_lidar_driver.cpp:783-806)."""
        if not self.is_connected() or not self._scanning:
            return None
        got = self._assembler.wait_and_grab_with_timestamp(timeout_s)
        if got is None:
            return None
        batch, ts0, duration = got
        from rplidar_ros2_driver_tpu.ops.ascend import apply_angle_compensation

        return apply_angle_compensation(batch, self._angle_compensate), ts0, duration

    def set_ingest_sink(self, sink) -> None:
        """Install a fused ingest sink BEFORE connect (the engine binds
        the measurement callback at connect time).  The node's seam
        wiring uses this so one FusedIngest (and its rolling filter
        window) survives FSM driver recreation, like the chain does."""
        with self._lock:
            if self._connected:
                raise RuntimeError("ingest sink must be set before connect")
            self._scan_decoder = sink
            self._fused_ingest = sink

    def grab_filtered(self, timeout_s: float = 2.0) -> Optional[list]:
        """Fused-ingest consumer: completed revolutions as
        ``[(FilterOutput, ts0, duration), ...]`` from the next dispatched
        batch (possibly empty — mid-revolution batch), or None on
        timeout / when the host ingest backend is active."""
        if not self.is_connected() or not self._scanning:
            return None
        sink = self._fused_ingest
        if sink is None:
            return None
        return sink.wait_and_grab_outputs(timeout_s)

    def grab_scan_host(
        self, timeout_s: float = 2.0
    ) -> Optional[tuple[dict, float, float]]:
        """Host-native grab: raw numpy arrays straight from the assembler,
        no device work at all.  Angle compensation is NOT applied here —
        the chain's grid resampler is ordering-independent (scatter-min)
        and its clip stage drops invalid nodes, so ascend would only add a
        per-scan device dispatch to the latency path."""
        if not self.is_connected() or not self._scanning:
            return None
        return self._assembler.wait_and_grab_host(timeout_s)

    # ------------------------------------------------------------------
    # capture (replay.py)
    # ------------------------------------------------------------------

    def start_recording(self, path: str) -> None:
        """Tee every measurement frame into a capture file; decode it later
        with replay.decode_recording (batched JAX kernels)."""
        from rplidar_ros2_driver_tpu.replay import FrameRecorder

        self.stop_recording()
        self._scan_decoder.recorder = FrameRecorder(path)

    def stop_recording(self) -> Optional[int]:
        """Returns the number of frames captured, or None if not recording."""
        rec = self._scan_decoder.recorder
        if rec is None:
            return None
        self._scan_decoder.recorder = None
        frames = rec.frames
        rec.close()
        return frames

    def grab_scan_data_with_interval(self, max_nodes: Optional[int] = None):
        """Raw nodes accumulated since the last interval grab, as a (k, 4)
        [angle_q14, dist_q2, quality, flag] array — without waiting for a
        complete revolution (getScanDataWithIntervalHq,
        sl_lidar_driver.cpp:962-966).  None when nothing arrived."""
        if not self.is_connected() or not self._scanning:
            return None
        return self._raw_holder.fetch(max_nodes)
