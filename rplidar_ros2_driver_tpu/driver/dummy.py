"""Hardware-free simulated driver.

Parity with the reference's ``DummyLidarDriver``
(src/lidar_driver_wrapper.cpp:417-471): always connected and healthy,
synthesizes a 360-point ring at 2 m +/- 0.5 m sine with the phase advancing
0.1 rad per scan, quality 200, ~10 Hz.  The synthesis itself is a jitted
JAX kernel — the dummy backend exercises the same device-array path the
real driver uses, so node-layer tests cover the TPU hand-off too.
"""

from __future__ import annotations

import functools
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp

from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES, ScanBatch
from rplidar_ros2_driver_tpu.driver.interface import LidarDriverInterface
from rplidar_ros2_driver_tpu.models.tables import DriverProfile, ProtocolType


@functools.partial(jax.jit, static_argnames=("count", "capacity"))
def synth_scan(phase: jax.Array, count: int = 360, capacity: int = MAX_SCAN_NODES) -> ScanBatch:
    """Synthetic ring scan as a padded ScanBatch (pure, jit-stable)."""
    i = jnp.arange(capacity, dtype=jnp.int32)
    live = i < count
    angle_q14 = (i.astype(jnp.float32) * (16384.0 / 90.0)).astype(jnp.int32) & 0xFFFF
    dist_m = 2.0 + 0.5 * jnp.sin(i.astype(jnp.float32) * (jnp.pi / 180.0) + phase)
    dist_q2 = jnp.where(live, (dist_m * 4000.0).astype(jnp.int32), 0)
    quality = jnp.where(live, 200, 0)
    flag = jnp.where(i == 0, 1, 0)
    return ScanBatch(
        angle_q14=jnp.where(live, angle_q14, 0),
        dist_q2=dist_q2,
        quality=quality,
        flag=flag,
        valid=live,
        count=jnp.asarray(count, jnp.int32),
    )


class DummyLidarDriver(LidarDriverInterface):
    """Simulation/CI backend selected by the ``dummy_mode`` parameter."""

    def __init__(self, scan_rate_hz: float = 10.0, count: int = 360) -> None:
        self._scan_rate_hz = scan_rate_hz
        self._count = count
        self._phase = 0.0
        self._lock = threading.Lock()
        self.profile = DriverProfile(
            protocol=ProtocolType.NEW_TYPE,
            model_name="[Dummy] Virtual RPLIDAR",
            hw_max_distance=40.0,
            active_mode="Simulated",
        )

    # -- trivial lifecycle (dummy is always healthy/connected) --

    def connect(self, port: str, baudrate: int, use_geometric_compensation: bool) -> bool:
        return True

    def disconnect(self) -> None: ...

    def is_connected(self) -> bool:
        return True

    def start_motor(self, scan_mode: str, rpm: int) -> bool:
        return True

    def stop_motor(self) -> None: ...

    def get_health(self) -> DeviceHealth:
        return DeviceHealth.OK

    def reset(self) -> None: ...

    def detect_and_init_strategy(self) -> None: ...

    def print_summary(self) -> None:
        print("[Dummy] Virtual RPLIDAR device ready.")

    def get_hw_max_distance(self) -> float:
        return 40.0

    def set_motor_speed(self, rpm: int) -> bool:
        return True

    def grab_scan_data(self, timeout_s: float = 2.0) -> Optional[ScanBatch]:
        with self._lock:
            self._phase += 0.1
            phase = self._phase
        if self._scan_rate_hz > 0:
            time.sleep(1.0 / self._scan_rate_hz)
        return synth_scan(jnp.float32(phase), count=self._count)
