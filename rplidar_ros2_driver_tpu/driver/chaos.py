"""Deterministic fault injection — the chaos plane of the fleet.

The reference driver's defining property is surviving a hostile wire:
garbage bytes, torn capsules, yanked cables (README.md's community
stress protocol).  This module makes that property TESTABLE at fleet
scale by generating faults from a seeded, schedule-driven program that
is a pure function of ``(seed, frame_index, payload)`` — so the
host-golden decode path and the fused device path can be handed
byte-for-byte the SAME corrupted stream, and the bit-exact parity
contract (tests/test_fused_ingest.py et al.) extends to degraded input.

Three injection points, one schedule:

  * :class:`ChaosStream` — frame-level applier for the fleet tick
    harnesses (tests, bench --config 13): corrupts/truncates/drops the
    ``(payload, rx_ts)`` runs fed to ``submit_bytes``-shaped seams.
  * :class:`ChaosTransport` — a ``TransceiverLike`` wrapper for the live
    driver stack (protocol/engine.py pump): same fault program applied
    to decoded measurement messages, plus stalls (timeout reads) and
    mid-stream disconnects (ChannelError, exactly what a hot-unplug
    produces).
  * ``SimConfig.chaos`` (driver/sim_device.py) — the emulated firmware
    applies the program to its outgoing wire frames, so the whole stack
    (native/py transport -> decoder resync -> assembler -> FSM) chews
    on the corruption, including mid-capsule severs.

Determinism contract: every decision about frame ``i`` comes from
``np.random.default_rng((seed, i))`` — independent of read chunking,
thread timing, or which consumer asks.  Two appliers built from the
same :class:`ChaosConfig` produce identical fault sequences.
"""

from __future__ import annotations

import dataclasses
import logging
from typing import Optional

import numpy as np

log = logging.getLogger("rplidar_tpu.chaos")

# fault kinds, in the order the schedule resolves them (first hit wins)
FAULT_STALL = "stall"
FAULT_DISCONNECT = "disconnect"
FAULT_DROP = "drop"
FAULT_TRUNCATE = "truncate"
FAULT_CORRUPT = "corrupt"
FAULT_DESYNC = "desync"
FAULT_PASS = "pass"


@dataclasses.dataclass(frozen=True)
class ChaosConfig:
    """One seeded fault program (all rates are per-frame probabilities).

    ``start_frame``/``stop_frame`` bound the active window in GLOBAL
    frame indices (stop 0 = never stops), so a schedule can model
    "clean warmup, then a sick cable, then recovery" in one config.
    """

    seed: int = 0
    start_frame: int = 0
    stop_frame: int = 0
    # byte corruption inside the payload (decoder checksum/CRC fodder)
    corrupt_rate: float = 0.0
    corrupt_bytes: int = 4
    # truncated reads: the frame arrives as a strict prefix (the length
    # filter both ingest backends share drops it identically)
    truncate_rate: float = 0.0
    # frames silently swallowed (radio fade / kernel buffer overrun)
    drop_rate: float = 0.0
    # periodic stalls: every ``stall_period`` frames, the next
    # ``stall_frames`` frames are swallowed (a wedged device window)
    stall_period: int = 0
    stall_frames: int = 0
    # absolute frame indices at which the link severs mid-capsule (the
    # transport raises ChannelError / the sim sends a torn frame then
    # unplugs); small repeated indices per session model reconnect storms
    disconnect_frames: tuple = ()
    # descriptor desync: garbage bytes injected AHEAD of the frame on
    # byte-stream transports (sim/transport level; at the frame-run
    # level this degrades to a malformed frame, same as truncate)
    desync_rate: float = 0.0
    desync_bytes: int = 16

    def __post_init__(self) -> None:
        for name in ("corrupt_rate", "truncate_rate", "drop_rate",
                     "desync_rate"):
            v = getattr(self, name)
            if not (0.0 <= v <= 1.0):
                raise ValueError(f"{name} must be within [0, 1], got {v}")
        if self.stall_period < 0 or self.stall_frames < 0:
            raise ValueError("stall_period/stall_frames must be >= 0")
        if self.stall_frames and self.stall_period <= self.stall_frames:
            raise ValueError(
                "stall_period must exceed stall_frames (the window must "
                "re-open between stalls)"
            )


class ChaosSchedule:
    """Stateless per-index fault resolver (the pure core both appliers
    and the sim share)."""

    def __init__(self, cfg: ChaosConfig) -> None:
        self.cfg = cfg
        self._disconnects = frozenset(int(i) for i in cfg.disconnect_frames)

    def active(self, index: int) -> bool:
        cfg = self.cfg
        return index >= cfg.start_frame and (
            cfg.stop_frame == 0 or index < cfg.stop_frame
        )

    def plan(self, index: int) -> str:
        """The fault kind for frame ``index`` — deterministic, chunking-
        independent, identical for every consumer."""
        cfg = self.cfg
        if index in self._disconnects:
            return FAULT_DISCONNECT
        if not self.active(index):
            return FAULT_PASS
        if cfg.stall_period > 0 and cfg.stall_frames > 0:
            if index % cfg.stall_period < cfg.stall_frames:
                return FAULT_STALL
        u = np.random.default_rng((cfg.seed, index)).random(4)
        if u[0] < cfg.drop_rate:
            return FAULT_DROP
        if u[1] < cfg.truncate_rate:
            return FAULT_TRUNCATE
        if u[2] < cfg.corrupt_rate:
            return FAULT_CORRUPT
        if u[3] < cfg.desync_rate:
            return FAULT_DESYNC
        return FAULT_PASS

    def mutate(self, index: int, payload: bytes) -> tuple[str, Optional[bytes]]:
        """Apply frame ``index``'s fault to ``payload``.  Returns
        ``(kind, bytes-or-None)``; None means the frame never arrives
        (drop/stall) or the link severed (disconnect — the CALLER owns
        what severing means for its transport).  A desync fault returns
        the payload with leading garbage; frame-run consumers should
        treat it like truncation (see :class:`ChaosStream`)."""
        kind = self.plan(index)
        if kind in (FAULT_STALL, FAULT_DROP, FAULT_DISCONNECT):
            return kind, None
        if kind == FAULT_PASS:
            return kind, payload
        rng = np.random.default_rng((self.cfg.seed, index, 1))
        if kind == FAULT_TRUNCATE:
            cut = int(rng.integers(1, max(len(payload), 2)))
            return kind, payload[:cut]
        if kind == FAULT_CORRUPT:
            buf = bytearray(payload)
            n = min(self.cfg.corrupt_bytes, len(buf))
            pos = rng.choice(len(buf), size=n, replace=False)
            for p in pos:
                buf[int(p)] ^= int(rng.integers(1, 256))
            return kind, bytes(buf)
        # FAULT_DESYNC: garbage ahead of the frame (byte-stream framing
        # damage; the decoder's resync machinery eats it)
        garbage = bytes(rng.integers(0, 256, self.cfg.desync_bytes,
                                     dtype=np.uint8))
        return kind, garbage + payload


class ChaosStream:
    """Stateful frame-run applier for tick-shaped consumers: carries the
    global frame index across runs and tallies what it did.

    Desync faults degrade to oversized frames here (the run consumers'
    shared length filter drops them, exactly like a host decoder would
    eventually resync past the garbage) — the byte-level form lives in
    the transport/sim injectors.
    """

    def __init__(self, cfg: ChaosConfig) -> None:
        self.schedule = ChaosSchedule(cfg)
        self.index = 0
        self.faults: dict[str, int] = {}
        self.severed = False

    def _tally(self, kind: str) -> None:
        self.faults[kind] = self.faults.get(kind, 0) + 1

    def apply_frame(self, payload: bytes) -> tuple[str, Optional[bytes]]:
        """One frame through the program: advances the global index,
        tallies, latches ``severed`` on a disconnect fault.  Returns
        ``(kind, bytes-or-None)`` — None means the frame never reaches
        the consumer."""
        i = self.index
        self.index += 1
        if self.severed:
            self._tally("severed")
            return "severed", None
        kind, mutated = self.schedule.mutate(i, payload)
        self._tally(kind)
        if kind == FAULT_DISCONNECT:
            self.severed = True
            return kind, None
        return kind, mutated

    def apply_run(self, frames: list) -> list:
        """Map one ``[(payload, rx_ts), ...]`` run through the program.
        Dropped/stalled frames vanish; after a disconnect fault the
        stream goes silent until :meth:`replug`."""
        out = []
        for payload, ts in frames:
            _kind, mutated = self.apply_frame(payload)
            if mutated is not None:
                out.append((mutated, ts))
        return out

    def replug(self) -> None:
        self.severed = False


class ChaosTransport:
    """``TransceiverLike`` wrapper applying the fault program to the live
    rx plane (protocol/engine.py's pump reads through this unchanged).

    Only loop-mode measurement messages are faulted — the request/answer
    plane passes clean, so chaos degrades the STREAM (the thing the
    health FSM supervises) without just breaking connect.  A disconnect
    fault raises ``ChannelError`` out of ``wait_message``, which is
    byte-for-byte the failure the pump sees on a real hot-unplug.
    """

    def __init__(self, inner, cfg: ChaosConfig) -> None:
        self._tx = inner
        self.chaos = ChaosStream(cfg)

    # -- lifecycle / passthrough ----------------------------------------

    def start(self) -> bool:
        return self._tx.start()

    def stop(self) -> None:
        self._tx.stop()

    def send(self, packet: bytes) -> bool:
        return self._tx.send(packet)

    def reset_decoder(self) -> None:
        self._tx.reset_decoder()

    @property
    def had_error(self) -> bool:
        return self.chaos.severed or self._tx.had_error

    @property
    def channel(self):
        return getattr(self._tx, "channel", None)

    @property
    def rx_priority(self) -> int:
        return int(getattr(self._tx, "rx_priority", -1))

    # -- faulted rx plane ------------------------------------------------

    def _filter(self, m):
        """Apply the program to one received message tuple (either the
        3-tuple wait_message shape or the 4-tuple stamped shape)."""
        from rplidar_ros2_driver_tpu.native.runtime import ChannelError
        from rplidar_ros2_driver_tpu.protocol.constants import SCAN_ANS_TYPES

        if m is None:
            return None
        ans_type, data, is_loop = m[0], m[1], m[2]
        if not (is_loop or ans_type in SCAN_ANS_TYPES):
            return m  # request plane: clean
        if self.chaos.severed:
            raise ChannelError("chaos: link severed")
        got = self.chaos.apply_run([(data, 0.0)])
        if self.chaos.severed:
            raise ChannelError("chaos: mid-capsule disconnect")
        if not got:
            return None  # dropped/stalled: reads as an idle timeout
        return (ans_type, got[0][0], is_loop, *m[3:])

    def wait_message(self, timeout_ms: int = 1000):
        return self._filter(self._tx.wait_message(timeout_ms=timeout_ms))

    def __getattr__(self, name):
        # keep optional extras (wait_message_ts, ...) visible only when
        # the wrapped transport has them, with the fault filter applied
        # to the stamped receive path
        if name == "wait_message_ts":
            inner = getattr(self._tx, "wait_message_ts")

            def wait_message_ts(timeout_ms: int = 1000):
                return self._filter(inner(timeout_ms=timeout_ms))

            return wait_message_ts
        return getattr(self._tx, name)


@dataclasses.dataclass(frozen=True)
class ShardChaosConfig:
    """One seeded SHARD-loss fault program — the chaos plane of the
    elastic fleet (parallel/service.ElasticFleetService).  Where
    :class:`ChaosConfig` damages a stream's bytes, this kills whole
    shards: every hosted stream's engine state vanishes at once (a chip
    falling out of the pod), and the pod must evacuate the victims onto
    surviving shards' idle lanes from their last per-stream snapshots.

    Same one-schedule discipline as the frame program: whether shard
    ``s`` is down at tick ``t`` is a pure function of ``(seed, s, t)``,
    so a kill->evacuate->re-admit cycle replays identically in tests,
    the bench, and the host-golden replay harness.

    ``kills`` holds explicit ``(shard, start_tick, stop_tick)`` outages
    (stop 0 = never recovers); ``kill_rate``/``outage_ticks`` add
    seeded random outages on top (an outage of ``outage_ticks`` ticks
    begins at tick ``t0`` iff the per-index draw fires there).
    """

    seed: int = 0
    kills: tuple = ()
    kill_rate: float = 0.0
    outage_ticks: int = 0

    def __post_init__(self) -> None:
        if not (0.0 <= self.kill_rate <= 1.0):
            raise ValueError(
                f"kill_rate must be within [0, 1], got {self.kill_rate}"
            )
        if self.kill_rate > 0.0 and self.outage_ticks < 1:
            raise ValueError(
                "kill_rate needs outage_ticks >= 1 (a zero-length outage "
                "kills nothing)"
            )
        for k in self.kills:
            if len(k) != 3:
                raise ValueError(
                    "kills entries are (shard, start_tick, stop_tick) "
                    f"triples, got {k!r}"
                )
            shard, start, stop = k
            if shard < 0 or start < 0 or stop < 0:
                raise ValueError(f"kills entry {k!r} has negative fields")
            if stop and stop <= start:
                raise ValueError(
                    f"kills entry {k!r}: stop_tick must exceed start_tick "
                    "(0 = never recovers)"
                )


class ShardChaosSchedule:
    """Stateless per-(shard, tick) outage resolver — the pure core the
    pod service, the failover bench and the replay harness all share."""

    def __init__(self, cfg: ShardChaosConfig) -> None:
        self.cfg = cfg

    def down(self, shard: int, tick: int) -> bool:
        """Whether ``shard`` is dead at ``tick`` — deterministic,
        identical for every consumer (the shard-level analog of
        :meth:`ChaosSchedule.plan`)."""
        cfg = self.cfg
        for s, start, stop in cfg.kills:
            if s == shard and start <= tick and (stop == 0 or tick < stop):
                return True
        if cfg.kill_rate > 0.0:
            lo = max(0, tick - cfg.outage_ticks + 1)
            for t0 in range(lo, tick + 1):
                u = np.random.default_rng(
                    (cfg.seed, shard, t0)
                ).random()
                if u < cfg.kill_rate:
                    return True
        return False

    def down_shards(self, tick: int, shards: int) -> frozenset:
        return frozenset(
            s for s in range(shards) if self.down(s, tick)
        )


def chaos_ticks(ticks: list, stream_cfgs: dict) -> list:
    """Apply per-stream fault programs to a whole fleet tick list (the
    ``submit_bytes`` layout: ``ticks[t][i] = (ans, [(payload, ts), ...])``
    or None).  ``stream_cfgs`` maps stream index -> :class:`ChaosConfig`.
    Returns a NEW tick list; the input is untouched.  Because the
    program is deterministic, feeding the returned ticks to the host
    and fused backends hands both the identical corrupted stream."""
    streams = {i: ChaosStream(cfg) for i, cfg in stream_cfgs.items()}
    out = []
    for tick in ticks:
        row = []
        for i, item in enumerate(tick):
            cs = streams.get(i)
            if item is None or cs is None:
                row.append(item)
                continue
            ans, frames = item
            got = cs.apply_run(list(frames))
            row.append((ans, got) if got else None)
        out.append(row)
    return out
