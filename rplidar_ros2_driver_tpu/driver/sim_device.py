"""Protocol-accurate simulated lidar device (software device emulator).

The reference's only hardware-free backend is the node-layer
``DummyLidarDriver`` (src/lidar_driver_wrapper.cpp:417-471), which bypasses
the entire SDK.  This emulator goes further: it speaks the *wire protocol*
over a real TCP socket — request parsing, devinfo/health/conf answers, and
loop-mode measurement streaming built with the ops/wire.py encoders — so
tests (and users without hardware) can exercise the full stack: native
channel -> transceiver -> codec -> command engine -> per-format decoders ->
scan assembly -> FSM -> filter chain.  ``unplug()`` severs the link
mid-stream, automating the reference's manual hot-unplug protocol
(README.md:27-38).

Default identity is an S2-class DTOF unit (model 0x71 -> NEW_TYPE strategy);
pass ``model_id=0x18`` (A1M8) to exercise the legacy path.
"""

from __future__ import annotations

import logging
import math
import os
import select
import socket
import struct
import threading
import time
import tty
from dataclasses import dataclass, field
from typing import Optional

import numpy as np

from rplidar_ros2_driver_tpu.models.tables import DeviceInfo
from rplidar_ros2_driver_tpu.ops import unpack_ref, wire
from rplidar_ros2_driver_tpu.protocol.codec import AnsHeader
from rplidar_ros2_driver_tpu.protocol.constants import (
    Ans,
    Cmd,
    CMDFLAG_HAS_PAYLOAD,
    CMD_SYNC_BYTE,
    ConfKey,
    DENSE_CAPSULE_BYTES,
    CAPSULE_BYTES,
    HQ_CAPSULE_BYTES,
    NORMAL_NODE_BYTES,
    ULTRA_CAPSULE_BYTES,
    ULTRA_DENSE_CAPSULE_BYTES,
)

log = logging.getLogger("rplidar_tpu.sim")


@dataclass
class SimScanMode:
    id: int
    name: str
    ans_type: int
    us_per_sample: float
    max_distance: float


DEFAULT_MODES = [
    SimScanMode(0, "Standard", Ans.MEASUREMENT, 476.0, 12.0),
    SimScanMode(1, "DenseBoost", Ans.MEASUREMENT_DENSE_CAPSULED, 31.25, 40.0),
    SimScanMode(2, "Sensitivity", Ans.MEASUREMENT_CAPSULED, 63.0, 25.0),
    SimScanMode(3, "UltraBoost", Ans.MEASUREMENT_CAPSULED_ULTRA, 42.0, 30.0),
    # us_per_sample must keep the implied spin rate (1e6 / (us * points_per_rev))
    # under the unpacker's 100 Hz angle-jump ceiling for a 32-cabin frame
    # (handler_capsules.cpp:968): with 400 pts/rev, 60 us -> ~42 Hz.
    SimScanMode(4, "UltraDense", Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED, 60.0, 40.0),
    SimScanMode(5, "HQ", Ans.MEASUREMENT_HQ, 32.0, 40.0),
]


# graftlint: disable=GL006 — NOT a jit static: SimConfig is the mutable
# firmware-state holder of the simulated device (tests flip
# health_status live, SET_LIDAR_CONF writes ip_conf); it never crosses
# a jit boundary
@dataclass
class SimConfig:
    model_id: int = 0x71           # S2M1 -> NEW_TYPE
    firmware: int = 0x0105
    hardware: int = 0x12
    serial: bytes = bytes(range(1, 17))  # nonzero first byte: "connected" S/N
    health_status: int = 0         # 0 ok / 1 warning / 2 error
    points_per_rev: int = 400
    dist_base_mm: float = 2000.0
    dist_amp_mm: float = 500.0
    frame_rate_hz: float = 0.0     # 0 = stream as fast as possible (tests)
    modes: list = field(default_factory=lambda: list(DEFAULT_MODES))
    # accessory-board / motor metadata (checkMotorCtrlSupport + getMotorInfo)
    acc_board_pwm: bool = False    # A2/A3 acc-board flag bit 0
    min_rpm: int = 200
    max_rpm: int = 1200
    desired_rpm: int = 600
    desired_pwm: int = 660
    # legacy GET_SAMPLERATE answer (std/express µs)
    std_sample_us: int = 476
    express_sample_us: int = 238
    # network identity (MAC / static-IP conf keys)
    mac: bytes = b"\xaa\xbb\xcc\xdd\xee\xff"
    ip_conf: bytes = bytes([192, 168, 11, 2, 255, 255, 255, 0, 192, 168, 11, 1])
    # deterministic fault program (driver/chaos.ChaosConfig): the
    # emulated firmware mutates its OWN outgoing wire frames — corrupt
    # bytes, truncated/garbage-prefixed frames, stall windows, and
    # mid-capsule severs (half a frame, then unplug) — so the full
    # transport->decoder->assembler->FSM stack chews the damage.  A new
    # scan start restarts the program at frame 0, so small
    # disconnect_frames indices model reconnect storms.  None = clean.
    chaos: object = None
    # procedural world provider (scenarios/foundry.FoundryScene or any
    # object with dist_mm(thetas_deg, revs) -> mm ndarray, 0 = no
    # return): replaces the sinusoid ring for ALL six wire formats via
    # the one _scene_dists seam.  None keeps the default ring on the
    # exact per-beam scalar-math path — byte-identical frames to the
    # pre-scene tree (pinned by tests/test_scenarios.py goldens).
    scene: object = None


class SimulatedDevice:
    """One-client TCP server emulating lidar firmware."""

    TARGET = "127.0.0.1"

    def __init__(self, config: Optional[SimConfig] = None) -> None:
        self.cfg = config or SimConfig()
        self._srv: Optional[socket.socket] = None
        self._conn: Optional[socket.socket] = None
        self._conn_lock = threading.Lock()
        # one frame on the wire at a time: the stream thread and the
        # request-answer path (rx thread) share the transport, and real
        # firmware serializes its UART writes — without this, a
        # GET_DEVICE_HEALTH answer issued mid-stream tears into a
        # measurement frame and the host decoder resyncs past it (the
        # health FSM's quarantine-release probe polls exactly there)
        self._tx_lock = threading.Lock()
        self._accept_thread: Optional[threading.Thread] = None
        self._stream_thread: Optional[threading.Thread] = None
        self._streaming = threading.Event()
        self._running = threading.Event()
        self.port = 0
        # observability for tests
        self.motor_rpm = 0
        # wire format of the most recently started stream — NOT reset on
        # stop/unplug (observability for tests asserting what the last
        # scan start selected, not a liveness signal; use _streaming for
        # that)
        self.active_ans_type = 0
        self.commands: list[int] = []
        # points actually delivered by the stream loop (frames _send
        # confirmed written; resets at each scan start) — under host load
        # the absolute-deadline pacer can fall behind nominal rate, so
        # tests that check "did the consumer keep up" compare against
        # this, not wall-clock * nominal rate
        self.points_emitted = 0
        # when the current stream session began, and how many frame sends
        # blocked hard (>100 ms inside _send): a consumer that stops
        # draining the socket fills the kernel buffer and parks sendall
        # for hundreds of ms, while host-load/GIL scheduling delays stay
        # in the single-ms range — tests use this to tell "consumer
        # can't keep up" apart from "CI host is slow"
        self.stream_t0 = 0.0
        self.stream_send_stalls = 0
        # the live ChaosStream of the current scan session (cfg.chaos
        # set): fault tallies for tests; None on a clean stream
        self.chaos_stream = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    _RX_THREAD_NAME = "sim_accept"

    def start(self) -> "SimulatedDevice":
        """Shared lifecycle: transports implement _open_listener/_rx_loop."""
        self._open_listener()
        self._running.set()
        self.motor_rpm = 0
        self.commands = []
        self._accept_thread = threading.Thread(
            target=self._rx_loop, name=self._RX_THREAD_NAME, daemon=True
        )
        self._accept_thread.start()
        return self

    def _open_listener(self) -> None:
        self._srv = socket.socket()
        self._srv.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._srv.bind((self.TARGET, 0))
        self._srv.listen(1)
        self.port = self._srv.getsockname()[1]

    def _rx_loop(self) -> None:
        self._accept_loop()

    def stop(self) -> None:
        self._running.clear()
        self._streaming.clear()
        self.unplug()
        self._close_listener()
        for t in (self._accept_thread, self._stream_thread):
            if t is not None:
                t.join(3.0)
        self._accept_thread = self._stream_thread = None

    def _close_listener(self) -> None:
        """Transport hook: tear down the accept endpoint."""
        if self._srv is not None:
            try:
                self._srv.close()
            except OSError:
                pass
            self._srv = None

    def unplug(self) -> None:
        """Sever the client link abruptly (hot-unplug fault injection)."""
        self._streaming.clear()
        with self._conn_lock:
            if self._conn is not None:
                try:
                    self._conn.shutdown(socket.SHUT_RDWR)
                except OSError:
                    pass
                try:
                    self._conn.close()
                except OSError:
                    pass
                self._conn = None

    # ------------------------------------------------------------------
    # connection handling
    # ------------------------------------------------------------------

    def _accept_loop(self) -> None:
        while self._running.is_set():
            try:
                conn, _ = self._srv.accept()
            except OSError:
                return
            conn.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
            # timeout BEFORE publishing: _send's whole-frame retry loop
            # relies on send() timing out at 0.2 s — a send grabbing the
            # conn in the publish-to-_serve window must not block forever
            conn.settimeout(0.2)
            with self._conn_lock:
                self._conn = conn
            try:
                self._serve(conn)
            except (OSError, ConnectionError):
                pass
            finally:
                self._streaming.clear()

    def _serve(self, conn: socket.socket) -> None:
        buf = bytearray()
        while self._running.is_set():  # timeout set before conn was published
            try:
                chunk = conn.recv(256)
            except socket.timeout:
                continue
            except OSError:
                return
            if not chunk:
                return
            self._feed(buf, chunk)

    def _feed(self, buf: bytearray, chunk: bytes) -> None:
        """Shared rx helper: append bytes, parse every complete request."""
        buf += chunk
        while True:
            consumed = self._try_parse_request(bytes(buf))
            if consumed == 0:
                return
            del buf[:consumed]

    def _try_parse_request(self, data: bytes) -> int:
        """Parse one request packet; returns bytes consumed (0 = need more)."""
        # resync to A5
        idx = data.find(bytes([CMD_SYNC_BYTE]))
        if idx < 0:
            return len(data)
        if idx > 0:
            return idx
        if len(data) < 2:
            return 0
        cmd = data[1]
        if cmd & CMDFLAG_HAS_PAYLOAD:
            if len(data) < 3:
                return 0
            size = data[2]
            total = 3 + size + 1
            if len(data) < total:
                return 0
            payload = data[3 : 3 + size]
            checksum = 0
            for b in data[: total - 1]:
                checksum ^= b
            if checksum != data[total - 1]:
                log.warning("sim: bad request checksum for cmd %#x", cmd)
                return total
            self._handle(cmd, payload)
            return total
        self._handle(cmd, b"")
        return 2

    # ------------------------------------------------------------------
    # request handlers
    # ------------------------------------------------------------------

    def _send(self, data: bytes) -> bool:
        """Write the WHOLE frame or report failure.  The conn socket
        carries the 0.2 s rx timeout set in _serve, which also applies
        to sends — a backpressured sendall would abort mid-frame after
        0.2 s and tear the byte stream, so partial progress is tracked
        explicitly and timeouts retry until a deadline (same contract as
        the serial transport's _send)."""
        with self._conn_lock:
            conn = self._conn
        if conn is None:
            return False
        view = memoryview(data)
        deadline = time.monotonic() + 0.5
        with self._tx_lock:  # whole-frame atomicity across threads
            while len(view):
                try:
                    n = conn.send(view)
                except socket.timeout:
                    n = 0
                except OSError:
                    return False
                if n:
                    view = view[n:]
                elif time.monotonic() > deadline:
                    return False  # reader is gone; stream is torn anyway
        return True

    def tx_backlog_bytes(self) -> int:
        """Bytes queued in the kernel TX buffer, not yet drained by the
        consumer (Linux SIOCOUTQ).  This is the timing-insensitive
        "is the consumer keeping up" signal: a drain-limited consumer
        pins this near the socket buffer size, while host-load slowness
        (sim thread starved, GIL contention) leaves it near zero.
        Returns 0 when no client is connected or the query fails."""
        import fcntl
        import termios

        with self._conn_lock:
            conn = self._conn
        if conn is None:
            return 0
        try:
            buf = fcntl.ioctl(conn.fileno(), termios.TIOCOUTQ, b"\x00" * 4)
            return struct.unpack("i", buf)[0]
        except (OSError, AttributeError):
            # AttributeError: termios lacks TIOCOUTQ on non-Linux hosts —
            # same "returns 0 on failure" contract as a failed ioctl.
            return 0

    def _answer(self, ans_type: int, payload: bytes, is_loop: bool = False) -> None:
        hdr = AnsHeader(ans_type=ans_type, payload_len=len(payload), is_loop=is_loop)
        self._send(hdr.encode() + payload)

    def _handle(self, cmd: int, payload: bytes) -> None:
        self.commands.append(cmd)
        if cmd == Cmd.STOP:
            self._streaming.clear()
        elif cmd == Cmd.RESET:
            self._streaming.clear()
            self.motor_rpm = 0
        elif cmd == Cmd.GET_DEVICE_INFO:
            info = DeviceInfo(
                model=self.cfg.model_id,
                firmware_version=self.cfg.firmware,
                hardware_version=self.cfg.hardware,
                serialnum=self.cfg.serial,
            )
            self._answer(Ans.DEVINFO, info.to_payload())
        elif cmd == Cmd.GET_DEVICE_HEALTH:
            self._answer(
                Ans.DEVHEALTH, struct.pack("<BH", self.cfg.health_status, 0)
            )
        elif cmd == Cmd.HQ_MOTOR_SPEED_CTRL:
            if len(payload) >= 2:
                self.motor_rpm = struct.unpack_from("<H", payload)[0]
        elif cmd == Cmd.SET_MOTOR_PWM:
            if len(payload) >= 2:
                self.motor_rpm = struct.unpack_from("<H", payload)[0]
        elif cmd == Cmd.GET_ACC_BOARD_FLAG:
            flag = 0x1 if self.cfg.acc_board_pwm else 0x0
            self._answer(Ans.ACC_BOARD_FLAG, struct.pack("<I", flag))
        elif cmd == Cmd.GET_SAMPLERATE:
            # legacy sample-rate query (cmd 0x59 -> ans 0x15): two u16 LE,
            # std/express µs (sl_lidar_driver.cpp:1556-1599)
            self._answer(
                Ans.SAMPLE_RATE,
                struct.pack(
                    "<HH",
                    int(self.cfg.std_sample_us),
                    int(self.cfg.express_sample_us),
                ),
            )
        elif cmd == Cmd.GET_LIDAR_CONF:
            # pre-conf firmware (old triangle, fw < 1.24) does not know the
            # command at all: no answer, the requester times out — the
            # behavior checkSupportConfigCommands exists to avoid
            # (sl_lidar_driver.cpp:1176-1196)
            if self._conf_capable():
                self._handle_conf(payload)
        elif cmd == Cmd.SET_LIDAR_CONF:
            if self._conf_capable():
                self._handle_set_conf(payload)
        elif cmd in (Cmd.SCAN, Cmd.FORCE_SCAN):
            # FORCE_SCAN streams even when health-gated firmware would
            # refuse SCAN (sl_lidar_driver.cpp startScan force path)
            self._start_stream(self.cfg.modes[0])
        elif cmd == Cmd.EXPRESS_SCAN:
            if not self._conf_capable():
                # pre-conf express: working_mode byte is 0 on the wire and
                # the device streams the classic capsule format
                # (startScanExpress legacy branch, sl_lidar_driver.cpp:
                # 716-729, 748-750)
                self._start_stream(SimScanMode(
                    1, "Express", Ans.MEASUREMENT_CAPSULED,
                    float(self.cfg.express_sample_us), 16.0,
                ))
                return
            mode_id = payload[0] if payload else 0
            mode = next((m for m in self.cfg.modes if m.id == mode_id), None)
            if mode is not None:
                self._start_stream(mode)
        # unknown commands are ignored, like real firmware

    def _conf_capable(self) -> bool:
        """Whether the emulated firmware speaks GET/SET_LIDAR_CONF — the
        device-side truth the host's supports_conf_commands gate predicts
        (ND-magic major id >= 4, or triangle firmware >= 1.24).  The
        comparison logic is deliberately written out rather than calling
        supports_conf_commands: the emulator is the independent oracle the
        gate is tested against."""
        from rplidar_ros2_driver_tpu.models.tables import (
            CONF_MIN_FIRMWARE_VERSION,
            NEWDESIGN_MINUM_MAJOR_ID,
        )

        return (
            (self.cfg.model_id >> 4) >= NEWDESIGN_MINUM_MAJOR_ID
            or self.cfg.firmware >= CONF_MIN_FIRMWARE_VERSION
        )

    def _handle_conf(self, payload: bytes) -> None:
        if len(payload) < 4:
            return
        key = struct.unpack_from("<I", payload)[0]
        extra = payload[4:]
        mode_id = struct.unpack_from("<H", extra)[0] if len(extra) >= 2 else 0
        mode = next((m for m in self.cfg.modes if m.id == mode_id), None)
        echo = struct.pack("<I", key)
        if key == ConfKey.SCAN_MODE_COUNT:
            self._answer(Ans.GET_LIDAR_CONF, echo + struct.pack("<H", len(self.cfg.modes)))
        elif key == ConfKey.SCAN_MODE_TYPICAL:
            dense = next(
                (m for m in self.cfg.modes if m.name == "DenseBoost"), self.cfg.modes[0]
            )
            self._answer(Ans.GET_LIDAR_CONF, echo + struct.pack("<H", dense.id))
        elif key == ConfKey.SCAN_MODE_US_PER_SAMPLE and mode:
            self._answer(
                Ans.GET_LIDAR_CONF, echo + struct.pack("<I", int(mode.us_per_sample * 256))
            )
        elif key == ConfKey.SCAN_MODE_MAX_DISTANCE and mode:
            self._answer(
                Ans.GET_LIDAR_CONF, echo + struct.pack("<I", int(mode.max_distance * 256))
            )
        elif key == ConfKey.SCAN_MODE_ANS_TYPE and mode:
            self._answer(Ans.GET_LIDAR_CONF, echo + bytes([mode.ans_type]))
        elif key == ConfKey.SCAN_MODE_NAME and mode:
            self._answer(Ans.GET_LIDAR_CONF, echo + mode.name.encode() + b"\x00")
        elif key == ConfKey.MIN_ROT_FREQ:
            self._answer(Ans.GET_LIDAR_CONF, echo + struct.pack("<H", self.cfg.min_rpm))
        elif key == ConfKey.MAX_ROT_FREQ:
            self._answer(Ans.GET_LIDAR_CONF, echo + struct.pack("<H", self.cfg.max_rpm))
        elif key == ConfKey.DESIRED_ROT_FREQ:
            self._answer(
                Ans.GET_LIDAR_CONF,
                echo + struct.pack("<HH", self.cfg.desired_rpm, self.cfg.desired_pwm),
            )
        elif key == ConfKey.LIDAR_MAC_ADDR:
            self._answer(Ans.GET_LIDAR_CONF, echo + self.cfg.mac)
        elif key == ConfKey.LIDAR_STATIC_IP_ADDR:
            self._answer(Ans.GET_LIDAR_CONF, echo + self.cfg.ip_conf)
        # unknown keys: no answer (requester times out, like a real device)

    def _handle_set_conf(self, payload: bytes) -> None:
        if len(payload) < 4:
            return
        key = struct.unpack_from("<I", payload)[0]
        data = payload[4:]
        if key == ConfKey.LIDAR_STATIC_IP_ADDR and len(data) >= 12:
            self.cfg.ip_conf = bytes(data[:12])
            self._answer(Ans.SET_LIDAR_CONF, struct.pack("<I", 0))
        else:
            # unsupported key: result code 1 (device rejects the set)
            self._answer(Ans.SET_LIDAR_CONF, struct.pack("<I", 1))

    # ------------------------------------------------------------------
    # measurement streaming
    # ------------------------------------------------------------------

    def _start_stream(self, mode: SimScanMode) -> None:
        self._streaming.clear()
        if self._stream_thread is not None:
            self._stream_thread.join(2.0)
        self.active_ans_type = int(mode.ans_type)  # test observability
        self._streaming.set()
        self._stream_thread = threading.Thread(
            target=self._stream_loop, args=(mode,), name="sim_stream", daemon=True
        )
        self._stream_thread.start()

    def _scene_dist_mm(self, theta_deg: float, rev: int) -> float:
        return self.cfg.dist_base_mm + self.cfg.dist_amp_mm * math.sin(
            math.radians(theta_deg) + 0.1 * rev
        )

    def _scene_dists(self, pts: np.ndarray) -> np.ndarray:
        """Ranges (mm, float) for an array of GLOBAL point indices —
        the ONE beam→(theta, rev) contract for every wire format:

            theta = 360 · (p % points_per_rev) / points_per_rev
            rev   = p // points_per_rev

        Each beam is evaluated at its OWN revolution, even mid-frame —
        a capsule frame that straddles a rev boundary mixes two revs,
        which matters because the default ring's phase advances by
        0.1 rad per rev (and a foundry scene's pose advances per rev).
        Pinned by the golden test in tests/test_scenarios.py so scene
        providers cannot silently disagree with the ring.

        With no scene configured the default sinusoid ring keeps the
        historical per-beam SCALAR math.sin path — vectorized libm can
        differ from scalar libm in the last ulp, and the default wire
        bytes are pinned byte-identical across trees."""
        ppr = self.cfg.points_per_rev
        thetas = 360.0 * (pts % ppr) / ppr
        revs = pts // ppr
        if self.cfg.scene is not None:
            return np.asarray(
                self.cfg.scene.dist_mm(thetas, revs), np.float64
            )
        return np.array(
            [self._scene_dist_mm(t, r) for t, r in zip(thetas, revs)]
        )

    # all six measurement wire formats, (frame bytes, points per frame)
    STREAMABLE = {
        Ans.MEASUREMENT: (NORMAL_NODE_BYTES, 1),
        Ans.MEASUREMENT_DENSE_CAPSULED: (DENSE_CAPSULE_BYTES, 40),
        Ans.MEASUREMENT_CAPSULED: (CAPSULE_BYTES, 32),
        Ans.MEASUREMENT_CAPSULED_ULTRA: (ULTRA_CAPSULE_BYTES, 96),
        Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: (ULTRA_DENSE_CAPSULE_BYTES, 64),
        Ans.MEASUREMENT_HQ: (HQ_CAPSULE_BYTES, 96),
    }

    def _stream_loop(self, mode: SimScanMode) -> None:
        if mode.ans_type not in self.STREAMABLE:
            log.error(
                "sim: ans type %#x is not streamable; ignoring scan start",
                mode.ans_type,
            )
            self._streaming.clear()
            return
        frame_bytes, pts_per_frame = self.STREAMABLE[mode.ans_type]
        chaos = None
        if self.cfg.chaos is not None:
            from rplidar_ros2_driver_tpu.driver.chaos import ChaosStream

            chaos = ChaosStream(self.cfg.chaos)
            self.chaos_stream = chaos  # test observability (fault tallies)
        self._send(
            AnsHeader(ans_type=mode.ans_type, payload_len=frame_bytes, is_loop=True).encode()
        )
        period = (
            pts_per_frame / (1e6 / mode.us_per_sample)
            if self.cfg.frame_rate_hz == 0
            else 1.0 / self.cfg.frame_rate_hz
        )
        ppr = self.cfg.points_per_rev
        idx = 0  # global point index
        self.points_emitted = 0
        self.stream_send_stalls = 0
        self.stream_t0 = time.monotonic()
        first = True
        # absolute-deadline pacing: per-frame relative sleeps accumulate
        # scheduler overhead (~0.1-1 ms each), which at 800 fps would run
        # ~10-20% slow — pace against a running deadline instead
        pace = min(period, 0.02) if self.cfg.frame_rate_hz == 0 else period
        next_t = time.monotonic()
        while self._streaming.is_set() and self._running.is_set():
            rev, pos = divmod(idx, ppr)
            theta = 360.0 * pos / ppr
            start_q6 = int(theta * 64) & 0x7FFF
            if mode.ans_type == Ans.MEASUREMENT:
                dist = self._scene_dists(np.arange(1) + idx)[0]
                frame = wire.encode_normal_node(
                    int(theta * 64), int(dist * 4), 0x2F, syncbit=(pos == 0)
                )
            elif mode.ans_type == Ans.MEASUREMENT_DENSE_CAPSULED:
                dists = self._scene_dists(np.arange(40) + idx)
                frame = wire.encode_dense_capsule(start_q6, first, dists.astype(int))
            elif mode.ans_type == Ans.MEASUREMENT_CAPSULED:
                # express capsule: 16 cabins x 2 points
                dists = self._scene_dists(np.arange(32) + idx)
                dist_q2 = (dists.astype(int) * 4) & ~0x3
                frame = wire.encode_capsule(
                    start_q6, first, dist_q2.reshape(16, 2), np.zeros((16, 2), int)
                )
            elif mode.ans_type == Ans.MEASUREMENT_CAPSULED_ULTRA:
                # 32 cabins x 3 points.  The decoder's contract
                # (unpack_ref.UltraCapsuleDecoder): major is the mm-domain
                # varbitscale base of point 0; predict1 applies to THIS
                # cabin's decoded base, predict2 to the NEXT cabin's,
                # both shifted left by the base's scale level; -512/511
                # are reserved invalid markers.  Encode quantization-aware
                # against the decoded bases.
                pts = np.arange(97) + idx  # + first point of the NEXT frame
                mm = self._scene_dists(pts).astype(np.int64)
                bases_mm = mm[0::3]  # 33 cabin bases (incl. next frame's)
                majors = np.array(
                    [wire.varbitscale_encode(int(v)) for v in bases_mm]
                )
                dec = [unpack_ref.varbitscale_decode(int(m)) for m in majors]
                p1 = np.empty(32, np.int64)
                p2 = np.empty(32, np.int64)
                for c in range(32):
                    b1, l1 = dec[c]
                    b2, l2 = dec[c + 1]
                    p1[c] = np.clip((mm[3 * c + 1] - b1) >> l1, -511, 510)
                    p2[c] = np.clip((mm[3 * c + 2] - b2) >> l2, -511, 510)
                frame = wire.encode_ultra_capsule(
                    start_q6, first, majors[:32], p1, p2
                )
            elif mode.ans_type == Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED:
                # 32 cabins x 2 points, 20-bit piecewise-scaled samples
                dists = self._scene_dists(np.arange(64) + idx)
                words = np.array(
                    [
                        wire.ultra_dense_encode_sample(int(d), 0x2F)
                        for d in dists
                    ]
                )
                frame = wire.encode_ultra_dense_capsule(start_q6, first, words)
            else:  # HQ capsule: 96 pre-formatted nodes + CRC32
                pts = np.arange(96) + idx
                thetas = 360.0 * (pts % ppr) / ppr
                dq2 = self._scene_dists(pts).astype(np.int64) * 4
                flags = np.where(pts % ppr == 0, 1, 2)  # bit0 sync else !sync
                frame = wire.encode_hq_capsule(
                    (thetas * (65536.0 / 360.0)).astype(int),
                    dq2,
                    np.full(96, 0x2F, int),
                    flags,
                    timestamp=idx,
                )
            if chaos is not None:
                from rplidar_ros2_driver_tpu.driver.chaos import (
                    FAULT_DISCONNECT,
                )

                kind, mutated = chaos.apply_frame(frame)
                if kind == FAULT_DISCONNECT:
                    # mid-capsule sever: half a frame on the wire, then
                    # the cable is yanked — the consumer's decoder is
                    # left holding a torn capsule, exactly the hot-
                    # unplug shape the reference protocol survives
                    self._send(bytes(frame[: len(frame) // 2]))
                    self.unplug()
                    return
                frame = mutated  # None = swallowed (stall/drop)
            sent = False
            if frame is not None:
                t_send = time.monotonic()
                sent = self._send(frame)
                if time.monotonic() - t_send > 0.1:
                    self.stream_send_stalls += 1
            idx += pts_per_frame
            if sent:
                self.points_emitted += pts_per_frame
            first = False
            if pace > 0:
                next_t += pace
                delay = next_t - time.monotonic()
                if delay > 0:
                    time.sleep(delay)
                elif delay < -1.0:
                    next_t = time.monotonic()  # fell far behind: resync


class SerialSimulatedDevice(SimulatedDevice):
    """The same protocol emulator behind a pty: the driver opens the slave
    end as a real serial device (termios2 path in native/src/channel.cc),
    so the SERIAL transport — not just TCP — is exercisable end-to-end.

    ``port_path`` is the /dev/pts/N to hand to ``connect()``.
    ``unplug()`` closes the master, which surfaces as EIO on the slave —
    the same failure a yanked USB adapter produces.  Unlike the TCP
    emulator (whose listener keeps accepting, so the FSM can reconnect),
    an unplugged pty CANNOT be re-plugged: the kernel owns /dev/pts
    naming, so a fresh master would appear at a different path.  Use the
    TCP emulator for reconnect/recovery scenarios.

    fd discipline: the master is nonblocking and every os.read/os.write
    happens under ``_conn_lock`` after re-checking ``self._master`` is
    still the fd we selected on — a closed-and-reused fd number can never
    be touched (unlike sockets, raw fds are silently recycled).
    """

    def __init__(self, config: Optional[SimConfig] = None) -> None:
        super().__init__(config)
        self._master: Optional[int] = None
        self._slave: Optional[int] = None
        self.port_path = ""

    _RX_THREAD_NAME = "sim_serial"

    def _open_listener(self) -> None:
        self._master, self._slave = os.openpty()
        tty.setraw(self._master)  # no echo/line discipline on the device side
        os.set_blocking(self._master, False)
        self.port_path = os.ttyname(self._slave)

    def _rx_loop(self) -> None:
        self._serial_loop()

    def _close_listener(self) -> None:
        if self._slave is not None:
            try:
                os.close(self._slave)
            except OSError:
                pass
            self._slave = None

    def unplug(self) -> None:
        self._streaming.clear()
        with self._conn_lock:
            if self._master is not None:
                try:
                    os.close(self._master)
                except OSError:
                    pass
                self._master = None

    def _serial_loop(self) -> None:
        buf = bytearray()
        while self._running.is_set():
            with self._conn_lock:
                fd = self._master
            if fd is None:
                return
            try:
                r, _, _ = select.select([fd], [], [], 0.2)
            except OSError:
                continue  # fd closed under us; loop re-checks _master
            if not r:
                continue
            with self._conn_lock:
                if self._master != fd:
                    continue  # unplugged (and fd possibly recycled)
                try:
                    chunk = os.read(fd, 256)
                except BlockingIOError:
                    continue
                except OSError:
                    return
            if not chunk:
                return
            self._feed(buf, chunk)

    def _send(self, data: bytes) -> bool:
        """Write the WHOLE frame or (on sustained backpressure) nothing
        past what's already out: a short nonblocking write must not leave
        a torn frame desyncing the byte stream, so the remainder is
        retried with a writability wait until a deadline."""
        view = memoryview(data)
        deadline = time.monotonic() + 0.5
        with self._tx_lock:  # whole-frame atomicity across threads
            while len(view):
                with self._conn_lock:
                    fd = self._master
                    if fd is None:
                        return False
                    try:
                        n = os.write(fd, view)
                    except BlockingIOError:
                        n = 0
                    except OSError:
                        return False
                if n:
                    view = view[n:]
                    continue
                if time.monotonic() > deadline:
                    return False  # reader is gone; stream is torn anyway
                try:
                    select.select([], [fd], [], 0.05)
                except OSError:
                    return False
        return True

    def tx_backlog_bytes(self) -> int:
        """Undrained bytes in the pty slave's input queue (FIONREAD on
        the retained slave fd) — the serial analog of the TCP SIOCOUTQ
        probe: a drain-limited consumer pins this near the pty buffer
        size while a starved sim thread leaves it near zero.  Returns 0
        on any failure, matching the base contract."""
        import fcntl
        import termios

        with self._conn_lock:
            fd = self._slave
        if fd is None:
            return 0
        try:
            buf = fcntl.ioctl(fd, termios.FIONREAD, b"\x00" * 4)
            return struct.unpack("i", buf)[0]
        except (OSError, AttributeError):
            return 0


class UdpSimulatedDevice(SimulatedDevice):
    """The emulator over UDP with connected-pair semantics: the device
    learns its peer from the first request datagram and streams answers
    back to it (the reference's UDP channel connects to a fixed device
    address the same way, sl_udp_channel.cpp:53-58).  ``unplug()`` goes
    silent (drops the peer) — UDP has no connection to sever, so the
    failure mode a dead radio link produces is timeouts, not errors.

    Keep-up counters are weaker here than over TCP/serial: ``sendto``
    never backpressures, so ``points_emitted`` counts datagrams *fired*
    (not delivered), ``stream_send_stalls`` cannot trigger, and
    ``tx_backlog_bytes`` reads 0.  Consumer keep-up tests should drive
    the TCP or serial emulator instead.
    """

    def __init__(self, config: Optional[SimConfig] = None) -> None:
        super().__init__(config)
        self._sock: Optional[socket.socket] = None
        self._peer = None

    _RX_THREAD_NAME = "sim_udp"

    def _open_listener(self) -> None:
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        self._sock.bind((self.TARGET, 0))
        self._sock.settimeout(0.2)
        self.port = self._sock.getsockname()[1]

    def _rx_loop(self) -> None:
        self._udp_loop()

    def _close_listener(self) -> None:
        if self._sock is not None:
            try:
                self._sock.close()
            except OSError:
                pass
            self._sock = None

    def unplug(self) -> None:
        self._streaming.clear()
        with self._conn_lock:
            self._peer = None

    def _udp_loop(self) -> None:
        buf = bytearray()
        while self._running.is_set():
            sock = self._sock
            if sock is None:
                return
            try:
                chunk, addr = sock.recvfrom(2048)
            except socket.timeout:
                continue
            except OSError:
                return
            with self._conn_lock:
                if self._peer != addr:
                    self._peer = addr
                    buf.clear()  # new client: drop any half-parsed request
            self._feed(buf, chunk)

    def _send(self, data: bytes) -> bool:
        with self._conn_lock:
            sock, peer = self._sock, self._peer
        if sock is None or peer is None:
            return False
        try:
            sock.sendto(data, peer)
            return True
        except OSError:
            return False
