"""Abstract driver contract — the testability seam.

Mirrors the reference's ``LidarDriverInterface``
(include/lidar_driver_wrapper.hpp:139-267): the node layer depends on this
and nothing below it, so the whole node stack (FSM, conversion, filters,
publishing, diagnostics) runs against the dummy backend without hardware.

TPU-native difference: ``grab_scan_data`` returns a :class:`ScanBatch`
(padded SoA arrays ready for device kernels) instead of an
array-of-structs vector.
"""

from __future__ import annotations

import abc
from typing import Optional

from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.core.types import ScanBatch


class LidarDriverInterface(abc.ABC):
    """The 12-method driver contract the node layer programs against."""

    @abc.abstractmethod
    def connect(self, port: str, baudrate: int, use_geometric_compensation: bool) -> bool:
        """Open the transport and fetch device info."""

    @abc.abstractmethod
    def disconnect(self) -> None: ...

    @abc.abstractmethod
    def is_connected(self) -> bool: ...

    @abc.abstractmethod
    def start_motor(self, scan_mode: str, rpm: int) -> bool:
        """Spin up and begin streaming (model-specific strategy)."""

    @abc.abstractmethod
    def stop_motor(self) -> None: ...

    @abc.abstractmethod
    def get_health(self) -> DeviceHealth: ...

    @abc.abstractmethod
    def reset(self) -> None:
        """Device soft reset (cmd 0x40)."""

    @abc.abstractmethod
    def grab_scan_data(self, timeout_s: float = 2.0) -> Optional[ScanBatch]:
        """Block for the next complete revolution; None on timeout/failure."""

    def grab_scan_data_with_timestamp(
        self, timeout_s: float = 2.0
    ) -> Optional[tuple[ScanBatch, float, float]]:
        """(batch, revolution-begin time, duration) — hardware-timestamped
        grab (grabScanDataHqWithTimeStamp, sl_lidar_driver.cpp:783-806).
        Backends without hardware timing inherit this default: grab time and
        zero duration, which consumers treat as 'derive times yourself'."""
        import time

        batch = self.grab_scan_data(timeout_s)
        if batch is None:
            return None
        return batch, time.monotonic(), 0.0

    def force_scan(self, rpm: int = 0) -> bool:
        """FORCE_SCAN (cmd 0x21): stream despite a failed device health
        gate.  Default: unsupported — callers fall back to the normal
        health-gated start (startScan force path, sl_lidar_driver.cpp:586)."""
        return False

    def grab_scan_host(
        self, timeout_s: float = 2.0
    ) -> Optional[tuple[dict, float, float]]:
        """(host arrays, begin time, duration): the revolution as numpy
        angle_q14/dist_q2/quality/flag — the transfer-free form the filter
        chain ingests.  Hardware backends override this to avoid touching
        any device in the grab path; the default pulls from the batch."""
        got = self.grab_scan_data_with_timestamp(timeout_s)
        if got is None:
            return None
        batch, ts0, duration = got
        return batch.to_host(), ts0, duration

    @abc.abstractmethod
    def detect_and_init_strategy(self) -> None:
        """Classify the device (A vs S/C series) and cache a DriverProfile."""

    @abc.abstractmethod
    def print_summary(self) -> None: ...

    @abc.abstractmethod
    def get_hw_max_distance(self) -> float: ...

    @abc.abstractmethod
    def set_motor_speed(self, rpm: int) -> bool: ...

    # -- informational helpers used by the node (non-abstract) --

    def is_new_type(self) -> bool:
        """New-protocol devices publish quality unshifted
        (src/rplidar_node.cpp:589-592)."""
        return False

    def get_device_info_str(self) -> str:
        return "[Dummy] Virtual Driver"

    def rx_scheduling_class(self) -> Optional[int]:
        """Scheduling class of the transport's rx thread (2 = SCHED_RR,
        1 = nice boost, 0 = default, -1 = no elevation support); None for
        drivers without an rx thread (dummy) — /diagnostics omits the
        field then."""
        return None
