"""Pallas TPU kernels for the correlative matcher's two hot loops.

ROADMAP item 4: with ingest fused end to end, the SLAM front-end's dense
(dθ, dx, dy) score evaluation and the log-odds occupancy update are the
fleet tick's dominant compute (ops/scan_match.py) — and exactly the
dense, tiled, int32 workload the FPGA 2D SLAM accelerators (PAPERS.md,
arxiv 2103.09523 / 2006.01050) build custom scoring datapaths for.  On
TPU the same move is a Pallas kernel pair:

  * SCORE VOLUME (``coarse_scores_pallas`` + ``fine_scores_pallas``) —
    the coarse max-pooled translation sweep and the full-resolution
    joint (dθ, dx, dy) refinement.  The XLA arm materializes (T, B, F,
    F) gather planes in HBM per corner; here each candidate tile runs
    rotate → quantize → 4-corner gather → int32 reduce entirely in
    VMEM, and the quantized match map is loaded into VMEM ONCE (its
    block index map is constant) and stays resident across the whole
    θ-candidate grid instead of re-streaming from HBM per (dθ, dx, dy):

        fine grid step t (θ candidate t)
        ┌──────────────────────────────────────────────┐
        │ VMEM: mq (G, G)   ← loaded at t=0, RESIDENT  │
        │       pq, ok      ← constant blocks, resident │
        │       cosθ/sinθ   ← (1,) SMEM block per step  │
        │ rotate(B) → cell/frac split → take ×4 corners │
        │ → (B, F, F) int32 weights·vals → Σ_B → (F, F) │
        └──────────────────────────────────────────────┘

  * LOG-ODDS UPDATE (``log_odds_update_pallas``) — the endpoint-
    histogram hit pass plus the sampled free-space miss pass,
    scatter-free: the same one-hot/matmul tiling as
    ops/scan_match.cell_hits_matmul (bf16 one-hot outer products, f32
    accumulation — exact small integers below 2^24), tiled over map-row
    stripes so the one-hot planes ride the MXU at any grid size, fused
    with the Q10 clamp-accumulate in one pass over the map.

EXACTNESS.  The whole matcher datapath is int32 fixed point (the
scan_match module docstring's contract), and int32 addition is
associative and commutative even at wrap-around — so ANY evaluation
order produces bit-identical scores.  These kernels therefore pin
byte-for-byte against both the XLA lowering and the NumPy
``scan_match_ref`` twin: same quantization, same first-max-wins C-order
argmax (the (T, F, F) volume layout is reproduced exactly, and the
argmax itself runs in shared jnp code outside the kernels), same
``quant_shift`` overflow bound.  Nothing here is "close"; the parity
suite (tests/test_pallas_scan_match.py) asserts equality.

LOWERING.  Every entry point resolves compiled-vs-interpret AT LOWERING
TIME via ops/pallas_kernels._lowering_dispatch (graftlint GL010
enforces this for every pallas_call under ops/): a CPU-traced config
pinned to ``match_backend=pallas`` gets the interpretable lowering, so
CI and the linkless rig run the exact kernel code path.  Per MEMORY and
ROADMAP item 5, the CPU interpret-mode artifact is honesty-only — the
``decide_backends`` ``pallas_match_ab`` key stays clamped until an
on-device capture; Mosaic-side caveats (vector-index gather lowering,
sub-lane tile shapes for small F/U planes) are exactly what that first
on-chip run must shake out.
"""

from __future__ import annotations

import functools
import math

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from rplidar_ros2_driver_tpu.ops.pallas_kernels import _lowering_dispatch
from rplidar_ros2_driver_tpu.ops.scan_match import (
    SUB,
    SUB_BITS,
    MapConfig,
    _bilinear_gather,
    rotate_rows,
)


# ---------------------------------------------------------------------------
# score volume: coarse translation sweep
# ---------------------------------------------------------------------------


def _coarse_kernel(
    gc: int, c: int, clog: int, clamp_q: int, qshift: int, w: int,
    posec_ref, trig_ref, lo_ref, px_ref, py_ref, okm_ref, mq_ref, sc_ref,
):
    """One program: quantize the match map (kept as the ``mq`` output the
    fine stage reuses), max-pool it, rotate the scan to the predicted
    heading and score every coarse (dx, dy) candidate — all in VMEM."""
    cq, sq = trig_ref[0], trig_ref[1]
    ox, oy = posec_ref[0], posec_ref[1]
    px, py = px_ref[0, :], py_ref[0, :]
    okv = okm_ref[0, :] > 0
    rx, ry = rotate_rows(px, py, cq, sq)
    bx, by = rx + ox, ry + oy                                   # world subcells

    mq = jnp.clip(lo_ref[:], 0, clamp_q) >> qshift
    mq_ref[:] = mq
    mc = mq.reshape(gc, c, gc, c).max(axis=(1, 3))

    # coarse-scale subcell coords: SUB subcells per COARSE cell, so only
    # the cell index shifts per candidate and the bilinear fraction is
    # shared (the XLA arm's exact formulation)
    scx, scy = bx >> clog, by >> clog
    ccx, ccy = scx >> SUB_BITS, scy >> SUB_BITS
    cfx, cfy = scx & (SUB - 1), scy & (SUB - 1)
    u = 2 * w + 1
    # iota keeps the shift lattice kernel-local (pallas_call rejects
    # captured host constants)
    iu = jax.lax.broadcasted_iota(jnp.int32, (1, u, 1), 1) - w
    iv = jax.lax.broadcasted_iota(jnp.int32, (1, 1, u), 2) - w
    ix = ccx[:, None, None] + iu                                # (B, U, 1)
    iy = ccy[:, None, None] + iv                                # (B, 1, V)
    vals = _bilinear_gather(
        mc.reshape(-1), gc, ix, iy, cfx[:, None, None], cfy[:, None, None]
    )                                                           # (B, U, V)
    sc_ref[:] = jnp.sum(jnp.where(okv[:, None, None], vals, 0), axis=0)


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def _coarse_call(lo, px, py, okm, posec, trig, cfg: MapConfig, interpret: bool):
    g, c = cfg.grid, cfg.coarse
    gc = g // c
    u = 2 * cfg.window_cells + 1
    b = px.shape[-1]
    kern = functools.partial(
        _coarse_kernel, gc, c, int(math.log2(c)), cfg.clamp_q,
        cfg.quant_shift, cfg.window_cells,
    )
    return pl.pallas_call(
        kern,
        grid=(1,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),              # posec (2,)
            pl.BlockSpec(memory_space=pltpu.SMEM),              # trig (2,)
            pl.BlockSpec((g, g), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=[
            pl.BlockSpec((g, g), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((u, u), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((g, g), jnp.int32),            # mq
            jax.ShapeDtypeStruct((u, u), jnp.int32),            # score_c
        ],
        interpret=interpret,
    )(posec, trig, lo, px, py, okm)


def coarse_scores_pallas(
    log_odds, pq, ok, posec, cos_mid, sin_mid, cfg: MapConfig,
    *, interpret: bool | None = None,
):
    """Coarse translation-only sweep at the predicted heading — Pallas
    backend.  Returns ``(mq, score_c)``: the quantized match map (the
    fine stage's VMEM-resident input) and the (U, V) int32 coarse score
    plane, both bit-identical to the XLA arm's.

    ``interpret=None`` (default) resolves per LOWERING platform
    (``_lowering_dispatch``), so a config pinned to
    ``match_backend=pallas`` traced for a CPU device still compiles."""
    px = pq[:, 0][None]
    py = pq[:, 1][None]
    okm = ok.astype(jnp.int32)[None]
    trig = jnp.stack([cos_mid, sin_mid]).astype(jnp.int32)
    args = (log_odds, px, py, okm, posec.astype(jnp.int32), trig)
    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_coarse_call, cfg=cfg, interpret=False),
            functools.partial(_coarse_call, cfg=cfg, interpret=True),
            *args,
        )
    return _coarse_call(*args, cfg=cfg, interpret=interpret)


# ---------------------------------------------------------------------------
# score volume: joint (dθ, dx, dy) refinement
# ---------------------------------------------------------------------------


def _fine_kernel(
    g: int, csub: int, r: int,
    posec_ref, uv_ref, cos_ref, sin_ref, mq_ref, px_ref, py_ref, okm_ref,
    sf_ref,
):
    """One program per θ candidate: re-rotate the scan, shift by the
    coarse winner, score the ±r full-resolution window.  ``mq_ref``'s
    block index map is constant, so the match map is fetched from HBM
    once and stays VMEM-resident across the whole θ grid."""
    cq, sq = cos_ref[0], sin_ref[0]
    ox, oy = posec_ref[0], posec_ref[1]
    u_best, v_best = uv_ref[0], uv_ref[1]
    px, py = px_ref[0, :], py_ref[0, :]
    okv = okm_ref[0, :] > 0
    rx, ry = rotate_rows(px, py, cq, sq)
    fbx = rx + ox + u_best * csub
    fby = ry + oy + v_best * csub
    fcx, fcy = fbx >> SUB_BITS, fby >> SUB_BITS
    ffx, ffy = fbx & (SUB - 1), fby & (SUB - 1)
    f = 2 * r + 1
    ifu = jax.lax.broadcasted_iota(jnp.int32, (1, f, 1), 1) - r
    ifv = jax.lax.broadcasted_iota(jnp.int32, (1, 1, f), 2) - r
    fix = fcx[:, None, None] + ifu                              # (B, F, 1)
    fiy = fcy[:, None, None] + ifv                              # (B, 1, F)
    fvals = _bilinear_gather(
        mq_ref[:].reshape(-1), g, fix, fiy,
        ffx[:, None, None], ffy[:, None, None],
    )                                                           # (B, F, F)
    sf_ref[0] = jnp.sum(jnp.where(okv[:, None, None], fvals, 0), axis=0)


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def _fine_call(mq, px, py, okm, posec, uv, cos_q, sin_q, cfg, interpret):
    g = cfg.grid
    t = 2 * cfg.theta_window + 1
    f = 2 * cfg.fine_radius + 1
    b = px.shape[-1]
    kern = functools.partial(
        _fine_kernel, g, cfg.coarse * SUB, cfg.fine_radius
    )
    return pl.pallas_call(
        kern,
        grid=(t,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),              # posec (2,)
            pl.BlockSpec(memory_space=pltpu.SMEM),              # uv (2,)
            pl.BlockSpec((1,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((1,), lambda i: (i,), memory_space=pltpu.SMEM),
            # constant index map: the match map block is loaded once and
            # stays resident in VMEM across all T grid steps
            pl.BlockSpec((g, g), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (1, f, f), lambda i: (i, 0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((t, f, f), jnp.int32),
        interpret=interpret,
    )(posec, uv, cos_q, sin_q, mq, px, py, okm)


def fine_scores_pallas(
    mq, pq, ok, posec, cos_q, sin_q, u_best, v_best, cfg: MapConfig,
    *, interpret: bool | None = None,
):
    """Joint (dθ, dx, dy) refinement around the coarse winner — Pallas
    backend.  ``mq`` is the coarse kernel's quantized map output;
    ``cos_q``/``sin_q`` are the (T,) rotation-table rows of the θ
    candidates.  Returns the (T, F, F) int32 score volume in the XLA
    arm's exact C-order layout, so the shared first-max-wins argmax
    downstream cannot diverge."""
    px = pq[:, 0][None]
    py = pq[:, 1][None]
    okm = ok.astype(jnp.int32)[None]
    uv = jnp.stack([u_best, v_best]).astype(jnp.int32)
    args = (
        mq, px, py, okm, posec.astype(jnp.int32), uv,
        cos_q.astype(jnp.int32), sin_q.astype(jnp.int32),
    )
    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_fine_call, cfg=cfg, interpret=False),
            functools.partial(_fine_call, cfg=cfg, interpret=True),
            *args,
        )
    return _fine_call(*args, cfg=cfg, interpret=interpret)


# ---------------------------------------------------------------------------
# log-odds update: one-hot/matmul histogram + clamp-accumulate
# ---------------------------------------------------------------------------


def _update_kernel(
    g: int, hit_q: int, miss_q: int, clamp_q: int, samples: int,
    posec_ref, trig_ref, rows_ref, lo_ref, px_ref, py_ref, okm_ref, out_ref,
):
    """One program per map-row stripe: rotate the scan to the composed
    pose, histogram the endpoint hits and the sampled free-space passes
    for this stripe's rows via one-hot matmuls, apply the Q10
    increments and clamp — one fused pass over the stripe."""
    cq, sq = trig_ref[0], trig_ref[1]
    ox, oy = posec_ref[0], posec_ref[1]
    px, py = px_ref[0, :], py_ref[0, :]
    okv = okm_ref[0, :] > 0
    rx, ry = rotate_rows(px, py, cq, sq)
    wcx, wcy = rx + ox, ry + oy                                 # world subcells
    rows = rows_ref[:, 0]                                       # global row ids
    colg = jax.lax.broadcasted_iota(jnp.int32, (1, g), 1)       # (1, G)

    def hist(hx_sub, hy_sub, mask):
        # cell split + one-hot planes: out-of-map cells match no
        # row/column, which drops them exactly like the scatter arm's
        # flat-index drop (ops/scan_match.cell_hits) — no clipping, no
        # bounds mask needed beyond scan validity
        hx, hy = hx_sub >> SUB_BITS, hy_sub >> SUB_BITS
        ohx = (
            (hx[:, None] == rows[None, :]) & mask[:, None]
        ).astype(jnp.bfloat16)                                  # (B, Gt)
        ohy = (hy[:, None] == colg).astype(jnp.bfloat16)        # (B, G)
        # the one sanctioned float accumulation (ops/scan_match.
        # cell_hits_matmul note): 0/1 one-hot products are exact and f32
        # accumulation is exact below 2^24 counts — consumed only
        # through > 0 predicates, so no float ever reaches the Q10 map
        return jax.lax.dot_general(
            ohx, ohy, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                                       # (Gt, G)

    hits = hist(wcx, wcy, okv)
    free = jnp.zeros_like(hits)
    for k in range(samples):
        sx = ox + ((wcx - ox) * k) // samples
        sy = oy + ((wcy - oy) * k) // samples
        free = free + hist(sx, sy, okv)
    i_hit = hits > 0
    i_miss = (free > 0) & ~i_hit
    delta = jnp.where(i_hit, hit_q, 0) + jnp.where(i_miss, miss_q, 0)
    out_ref[:] = jnp.clip(lo_ref[:] + delta, -clamp_q, clamp_q)


def _row_tile(g: int) -> int:
    """Largest divisor row split keeping a stripe <= 256 rows, so the
    one-hot planes stay comfortably inside VMEM at EVERY permitted grid
    — including awkward ones like 514 = 2·257, whose best qualifying
    stripe is 2 rows (d = g always qualifies, so the search cannot
    fail)."""
    return next(
        g // d for d in range(1, g + 1) if g % d == 0 and g // d <= 256
    )


@functools.partial(jax.jit, static_argnames=("cfg", "interpret"))
def _update_call(lo, px, py, okm, posec, trig, cfg: MapConfig, interpret: bool):
    g = cfg.grid
    gt = _row_tile(g)
    b = px.shape[-1]
    rows = jnp.arange(g, dtype=jnp.int32)[:, None]
    kern = functools.partial(
        _update_kernel, g, cfg.hit_q, cfg.miss_q, cfg.clamp_q,
        cfg.free_samples,
    )
    return pl.pallas_call(
        kern,
        grid=(g // gt,),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.SMEM),              # posec (2,)
            pl.BlockSpec(memory_space=pltpu.SMEM),              # trig (2,)
            pl.BlockSpec((gt, 1), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((gt, g), lambda i: (i, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((1, b), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        out_specs=pl.BlockSpec(
            (gt, g), lambda i: (i, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((g, g), jnp.int32),
        interpret=interpret,
    )(posec, trig, rows, lo, px, py, okm)


def log_odds_update_pallas(
    log_odds, pq, ok, posec, cos_q, sin_q, cfg: MapConfig,
    *, interpret: bool | None = None,
):
    """Fused log-odds occupancy update — Pallas backend.  Drop-in for
    the XLA arm of ops/scan_match.update_map at the composed pose
    (``posec`` = pose[:2] + grid centre, ``cos_q``/``sin_q`` the pose's
    rotation-table entry): endpoint hits + sampled free-space misses
    via the scatter-free one-hot/matmul tiling, Q10 increments, clamp.
    Bit-identical to both XLA voxel-kernel arms and the NumPy
    reference."""
    px = pq[:, 0][None]
    py = pq[:, 1][None]
    okm = ok.astype(jnp.int32)[None]
    trig = jnp.stack([cos_q, sin_q]).astype(jnp.int32)
    args = (log_odds, px, py, okm, posec.astype(jnp.int32), trig)
    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_update_call, cfg=cfg, interpret=False),
            functools.partial(_update_call, cfg=cfg, interpret=True),
            *args,
        )
    return _update_call(*args, cfg=cfg, interpret=interpret)
