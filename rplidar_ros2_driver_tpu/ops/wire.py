"""Wire-frame builders (encoders) for the six measurement formats.

The reference only ever *decodes* these formats (the device firmware is the
encoder).  We need encoders so the framework can (a) golden-test its
decoders against hand-built byte fixtures and (b) run a simulated device
(driver/sim_device.py) that exercises the full
pipeline without hardware — the capability the reference's DummyLidarDriver
only approximates at the node layer.

Layouts follow sl_lidar_cmd.h:189-286; checksums follow the handler
implementations (XOR over bytes after the checksum nibbles,
handler_capsules.cpp:146-153).
"""

from __future__ import annotations

import struct

import numpy as np

from rplidar_ros2_driver_tpu.protocol import crc
from rplidar_ros2_driver_tpu.protocol.constants import (
    CAPSULE_BYTES,
    DENSE_CAPSULE_BYTES,
    EXP_SYNC_1,
    EXP_SYNC_2,
    EXP_SYNCBIT,
    HQ_CAPSULE_BYTES,
    HQ_SYNC,
    ULTRA_CAPSULE_BYTES,
    ULTRA_DENSE_CAPSULE_BYTES,
    VARBITSCALE_X2_DEST_VAL,
    VARBITSCALE_X2_SRC_BIT,
    VARBITSCALE_X4_DEST_VAL,
    VARBITSCALE_X4_SRC_BIT,
    VARBITSCALE_X8_DEST_VAL,
    VARBITSCALE_X8_SRC_BIT,
    VARBITSCALE_X16_DEST_VAL,
    VARBITSCALE_X16_SRC_BIT,
)


def _finish_capsule(body: bytes) -> bytes:
    """Prepend express sync nibbles + split XOR checksum over ``body``."""
    checksum = 0
    for b in body:
        checksum ^= b
    b0 = (EXP_SYNC_1 << 4) | (checksum & 0xF)
    b1 = (EXP_SYNC_2 << 4) | (checksum >> 4)
    return bytes([b0, b1]) + body


def encode_normal_node(
    angle_q6: int, dist_q2: int, quality6: int, syncbit: bool
) -> bytes:
    """5-byte legacy node (sl_lidar_cmd.h:189-194).

    byte0: sync:1 | sync_inverse:1 | quality:6;  byte1..2: checkbit:1 |
    angle_q6:15;  byte3..4: distance_q2.
    """
    s = 1 if syncbit else 0
    b0 = (quality6 & 0x3F) << 2 | (s ^ 1) << 1 | s
    angle_field = ((angle_q6 & 0x7FFF) << 1) | 0x1  # checkbit always set
    return bytes([b0]) + struct.pack("<HH", angle_field, dist_q2 & 0xFFFF)


def encode_capsule(
    start_angle_q6: int,
    syncbit: bool,
    dist_q2: np.ndarray,      # (16, 2) int, low 2 bits must be 0
    offset_q3: np.ndarray,    # (16, 2) int in [0, 63]
) -> bytes:
    """Express capsule: 16 cabins x 2 points, 84 bytes."""
    dist_q2 = np.asarray(dist_q2, np.int64)
    offset_q3 = np.asarray(offset_q3, np.int64)
    assert dist_q2.shape == (16, 2) and offset_q3.shape == (16, 2)
    angle_field = (start_angle_q6 & 0x7FFF) | (EXP_SYNCBIT if syncbit else 0)
    body = bytearray(struct.pack("<H", angle_field))
    for c in range(16):
        # distance_angle fields: dist in bits 2..15, offset bits 4..5 of the
        # q3 offset in the low 2 bits; low nibbles of both offsets packed in
        # the fifth byte (sl_lidar_cmd.h:200-205, decode at
        # handler_capsules.cpp:227-231).
        da1 = (int(dist_q2[c, 0]) & 0xFFFC) | ((int(offset_q3[c, 0]) >> 4) & 0x3)
        da2 = (int(dist_q2[c, 1]) & 0xFFFC) | ((int(offset_q3[c, 1]) >> 4) & 0x3)
        packed = (int(offset_q3[c, 0]) & 0xF) | ((int(offset_q3[c, 1]) & 0xF) << 4)
        body += struct.pack("<HHB", da1, da2, packed)
    out = _finish_capsule(bytes(body))
    assert len(out) == CAPSULE_BYTES
    return out


def encode_dense_capsule(
    start_angle_q6: int, syncbit: bool, dist_mm: np.ndarray
) -> bytes:
    """Dense capsule: 40 u16 raw millimetre distances, 84 bytes."""
    dist_mm = np.asarray(dist_mm, np.int64)
    assert dist_mm.shape == (40,)
    angle_field = (start_angle_q6 & 0x7FFF) | (EXP_SYNCBIT if syncbit else 0)
    body = struct.pack("<H", angle_field) + struct.pack(
        "<40H", *[int(d) & 0xFFFF for d in dist_mm]
    )
    out = _finish_capsule(body)
    assert len(out) == DENSE_CAPSULE_BYTES
    return out


def varbitscale_encode(value: int) -> int:
    """Inverse of the ultra-capsule varbitscale decode
    (handler_capsules.cpp:422-458): map a 16-bit-ish distance back to the
    12-bit scaled field.  Values are quantized by the scale level, so
    decode(encode(v)) == v only when v is representable."""
    bases = (
        (1 << VARBITSCALE_X16_SRC_BIT, VARBITSCALE_X16_DEST_VAL, 4),
        (1 << VARBITSCALE_X8_SRC_BIT, VARBITSCALE_X8_DEST_VAL, 3),
        (1 << VARBITSCALE_X4_SRC_BIT, VARBITSCALE_X4_DEST_VAL, 2),
        (1 << VARBITSCALE_X2_SRC_BIT, VARBITSCALE_X2_DEST_VAL, 1),
        (0, 0, 0),
    )
    for target_base, scaled_base, lvl in bases:
        if value >= target_base:
            return scaled_base + ((value - target_base) >> lvl)
    return 0


def encode_ultra_capsule(
    start_angle_q6: int,
    syncbit: bool,
    major12: np.ndarray,     # (32,) ints in [0, 4095] (varbitscale domain)
    predict1: np.ndarray,    # (32,) ints in [-512, 511] (10-bit signed)
    predict2: np.ndarray,    # (32,) ints in [-512, 511]
) -> bytes:
    """Ultra capsule: 32 cabins x u32 ``| predict2 10b | predict1 10b | major 12b |``."""
    major12 = np.asarray(major12, np.int64)
    predict1 = np.asarray(predict1, np.int64)
    predict2 = np.asarray(predict2, np.int64)
    assert major12.shape == (32,)
    angle_field = (start_angle_q6 & 0x7FFF) | (EXP_SYNCBIT if syncbit else 0)
    body = bytearray(struct.pack("<H", angle_field))
    for c in range(32):
        word = (
            (int(major12[c]) & 0xFFF)
            | ((int(predict1[c]) & 0x3FF) << 12)
            | ((int(predict2[c]) & 0x3FF) << 22)
        )
        body += struct.pack("<I", word)
    out = _finish_capsule(bytes(body))
    assert len(out) == ULTRA_CAPSULE_BYTES
    return out


# Ultra-dense piecewise scale thresholds (handler_capsules.cpp:973-975), in mm.
UD_THRESH_1 = 2046
UD_THRESH_2 = 8187
UD_THRESH_3 = 24567


def ultra_dense_encode_sample(dist_mm: int, quality: int) -> int:
    """Encode one 20-bit ultra-dense quality/distance/scale word.

    Inverse of the 4-level piecewise decode (handler_capsules.cpp:995-1017).
    Quantized: round-trips exactly only for representable distances.
    """
    dist_q2 = dist_mm * 4
    if dist_mm < UD_THRESH_1:
        field = (dist_q2 // 2) & 0xFFC
        return ((quality & 0xFF) << 12) | field | 0
    if dist_mm < UD_THRESH_2:
        field = ((dist_q2 - (UD_THRESH_1 << 2)) // 3) & 0x1FFC
        return (((quality >> 1) & 0x7F) << 13) | field | 1
    if dist_mm < UD_THRESH_3:
        field = ((dist_q2 - (UD_THRESH_2 << 2)) // 4) & 0x3FFC
        return (((quality >> 2) & 0x3F) << 14) | field | 2
    field = ((dist_q2 - (UD_THRESH_3 << 2)) // 5) & 0x7FFC
    return (((quality >> 3) & 0x1F) << 15) | field | 3


def encode_ultra_dense_capsule(
    start_angle_q6: int,
    syncbit: bool,
    words20: np.ndarray,   # (64,) 20-bit encoded samples (2 per cabin)
    timestamp: int = 0,
    dev_status: int = 0,
) -> bytes:
    """Ultra-dense capsule: u32 ts + u16 status + u16 angle + 32 cabins x 5B."""
    words20 = np.asarray(words20, np.int64)
    assert words20.shape == (64,)
    angle_field = (start_angle_q6 & 0x7FFF) | (EXP_SYNCBIT if syncbit else 0)
    body = bytearray(struct.pack("<IHH", timestamp & 0xFFFFFFFF, dev_status & 0xFFFF, angle_field))
    for c in range(32):
        w0 = int(words20[2 * c])
        w1 = int(words20[2 * c + 1])
        # low 16 bits of each sample in two u16s, high nibbles packed in byte 5
        body += struct.pack(
            "<HHB", w0 & 0xFFFF, w1 & 0xFFFF, ((w0 >> 16) & 0xF) | (((w1 >> 16) & 0xF) << 4)
        )
    out = _finish_capsule(bytes(body))
    assert len(out) == ULTRA_DENSE_CAPSULE_BYTES
    return out


def encode_hq_capsule(
    angle_q14: np.ndarray,   # (96,)
    dist_q2: np.ndarray,     # (96,)
    quality: np.ndarray,     # (96,)
    flags: np.ndarray,       # (96,)
    timestamp: int = 0,
) -> bytes:
    """HQ capsule: sync 0xA5 + u64 ts + 96 pre-formatted HQ nodes + CRC32."""
    angle_q14 = np.asarray(angle_q14, np.int64)
    assert angle_q14.shape == (96,)
    body = bytearray([HQ_SYNC])
    body += struct.pack("<Q", timestamp & 0xFFFFFFFFFFFFFFFF)
    for i in range(96):
        body += struct.pack(
            "<HIBB",
            int(angle_q14[i]) & 0xFFFF,
            int(dist_q2[i]) & 0xFFFFFFFF,
            int(quality[i]) & 0xFF,
            int(flags[i]) & 0xFF,
        )
    body += struct.pack("<I", crc.crc32_padded(bytes(body)))
    assert len(body) == HQ_CAPSULE_BYTES
    return bytes(body)
