"""Scalar reference decoders for the six measurement wire formats.

These mirror, sample-for-sample, the C++ unpack arithmetic of the reference
handlers (src/sdk/src/dataunpacker/unpacker/handler_*.cpp) using explicit
C-int32 semantics.  They are the *golden model* the vectorized JAX kernels
(ops/unpack.py) are tested against — and double as a readable specification
of each format.  They are not on the hot path.

Stateful pair logic: every capsule format except HQ interpolates angles
between CONSECUTIVE capsules, so decoders carry the previous capsule and
emit nodes only once its successor arrives (handler_capsules.cpp:206-266).
"""

from __future__ import annotations

import dataclasses
import struct
from typing import List, Optional, Tuple

from rplidar_ros2_driver_tpu.protocol import crc
from rplidar_ros2_driver_tpu.protocol.constants import (
    EXP_SYNC_1,
    EXP_SYNC_2,
    EXP_SYNCBIT,
    HQ_SYNC,
    VARBITSCALE_X2_DEST_VAL,
    VARBITSCALE_X2_SRC_BIT,
    VARBITSCALE_X4_DEST_VAL,
    VARBITSCALE_X4_SRC_BIT,
    VARBITSCALE_X8_DEST_VAL,
    VARBITSCALE_X8_SRC_BIT,
    VARBITSCALE_X16_DEST_VAL,
    VARBITSCALE_X16_SRC_BIT,
)

FULL_TURN_Q6 = 360 << 6
FULL_TURN_Q16 = 360 << 16


def _i32(x: int) -> int:
    """Wrap to C int32 (two's complement)."""
    return ((x + 0x80000000) & 0xFFFFFFFF) - 0x80000000


@dataclasses.dataclass
class HqNode:
    """Decoded HQ node (sl_lidar_cmd.h:272-278)."""

    angle_q14: int
    dist_q2: int
    quality: int
    flag: int


def _wrap_angle_q6(a: int) -> int:
    if a < 0:
        a += FULL_TURN_Q6
    if a >= FULL_TURN_Q6:
        a -= FULL_TURN_Q6
    return a


def _check_capsule_checksum(frame: bytes, payload_from: int = 2) -> bool:
    # low nibble of byte0 = checksum low nibble, low nibble of byte1 = high
    # nibble (sl_lidar_cmd.h capsule struct: s_checksum_1/2 are the :4 low
    # bitfields beside the 0xA/0x5 sync nibbles)
    recv = (frame[0] & 0xF) | ((frame[1] & 0xF) << 4)
    c = 0
    for b in frame[payload_from:]:
        c ^= b
    return recv == c


def _has_exp_sync(frame: bytes) -> bool:
    return (frame[0] >> 4) == EXP_SYNC_1 and (frame[1] >> 4) == EXP_SYNC_2


# ---------------------------------------------------------------------------
# Normal (legacy) 5-byte nodes — handler_normalnode.cpp:87-133
# ---------------------------------------------------------------------------


def decode_normal_node(frame: bytes) -> Optional[HqNode]:
    """Decode one 5-byte node; None if the sync/check bits are invalid."""
    b0 = frame[0]
    if not ((b0 >> 1) ^ b0) & 0x1:
        return None
    angle_field, dist_q2 = struct.unpack_from("<HH", frame, 1)
    if not angle_field & 0x1:
        return None
    return HqNode(
        angle_q14=((angle_field >> 1) << 8) // 90,
        dist_q2=dist_q2,
        quality=(b0 >> 2) << 2,
        flag=b0 & 0x1,
    )


# ---------------------------------------------------------------------------
# Express capsule — handler_capsules.cpp:206-266
# ---------------------------------------------------------------------------


def _start_angle_q6(frame: bytes, offset: int = 2) -> int:
    return struct.unpack_from("<H", frame, offset)[0]


@dataclasses.dataclass
class CapsuleDecoder:
    """Stateful express-capsule (ans 0x82) decoder: 16 cabins x 2 points."""

    prev: Optional[bytes] = None

    def reset(self) -> None:
        self.prev = None

    def decode(self, frame: bytes) -> Tuple[List[HqNode], bool]:
        """Returns (nodes, new_scan_flag).  nodes come from the *previous*
        capsule, interpolated toward this one's start angle."""
        if not _has_exp_sync(frame) or not _check_capsule_checksum(frame):
            self.prev = None
            return [], False
        start = _start_angle_q6(frame)
        new_scan = bool(start & EXP_SYNCBIT)
        if new_scan:
            self.prev = None  # discard cached capsule, scan restarts
        nodes: List[HqNode] = []
        if self.prev is not None:
            nodes = self._decode_pair(self.prev, frame)
        self.prev = frame
        return nodes, new_scan

    @staticmethod
    def _decode_pair(prev: bytes, cur: bytes) -> List[HqNode]:
        cur_q8 = (_start_angle_q6(cur) & 0x7FFF) << 2
        prev_q8 = (_start_angle_q6(prev) & 0x7FFF) << 2
        diff_q8 = cur_q8 - prev_q8
        if prev_q8 > cur_q8:
            diff_q8 += 360 << 8
        angle_inc_q16 = diff_q8 << 3
        angle_raw_q16 = prev_q8 << 8
        nodes = []
        for pos in range(16):
            da1, da2, packed = struct.unpack_from("<HHB", prev, 4 + 5 * pos)
            dist = (da1 & 0xFFFC, da2 & 0xFFFC)
            off_q3 = ((packed & 0xF) | ((da1 & 0x3) << 4), (packed >> 4) | ((da2 & 0x3) << 4))
            for c in range(2):
                angle_q6 = _i32(angle_raw_q16 - (off_q3[c] << 13)) >> 10
                sync = 1 if ((angle_raw_q16 + angle_inc_q16) % FULL_TURN_Q16) < angle_inc_q16 else 0
                angle_raw_q16 += angle_inc_q16
                angle_q6 = _wrap_angle_q6(angle_q6)
                nodes.append(
                    HqNode(
                        angle_q14=(angle_q6 << 8) // 90,
                        dist_q2=dist[c],
                        quality=(0x2F << 2) if dist[c] else 0,
                        flag=sync | ((0 if sync else 1) << 1),
                    )
                )
        return nodes


# ---------------------------------------------------------------------------
# Ultra capsule (varbitscale) — handler_capsules.cpp:422-580
# ---------------------------------------------------------------------------

_VBS = (
    (VARBITSCALE_X16_DEST_VAL, 4, 1 << VARBITSCALE_X16_SRC_BIT),
    (VARBITSCALE_X8_DEST_VAL, 3, 1 << VARBITSCALE_X8_SRC_BIT),
    (VARBITSCALE_X4_DEST_VAL, 2, 1 << VARBITSCALE_X4_SRC_BIT),
    (VARBITSCALE_X2_DEST_VAL, 1, 1 << VARBITSCALE_X2_SRC_BIT),
    (0, 0, 0),
)


def varbitscale_decode(scaled: int) -> Tuple[int, int]:
    """Returns (value, scale_level)."""
    for scaled_base, lvl, target_base in _VBS:
        remain = scaled - scaled_base
        if remain >= 0:
            return target_base + (remain << lvl), lvl
    return 0, 0


# Angle-correction constants (handler_capsules.cpp:547-557).
_ULTRA_OFFSET_DEFAULT_Q16 = int(7.5 * 3.1415926535 * (1 << 16) / 180.0)
_ULTRA_OFFSET_BASE_Q16 = int(8 * 3.1415926535 * (1 << 16) / 180)
_ULTRA_K1 = 98361


def ultra_angle_correction_q16(dist_q2: int) -> int:
    """The distance-dependent angular correction term, in raw-Q16 units."""
    if dist_q2 >= 50 * 4:
        k2 = _ULTRA_K1 // dist_q2
        offset_q16 = _ULTRA_OFFSET_BASE_Q16 - (k2 << 6) - (k2 * k2 * k2) // 98304
    else:
        offset_q16 = _ULTRA_OFFSET_DEFAULT_Q16
    # C: int(offsetAngleMean_q16 * 180 / 3.14159265) — double division then
    # truncation toward zero.
    return int(offset_q16 * 180 / 3.14159265)


@dataclasses.dataclass
class UltraCapsuleDecoder:
    """Stateful ultra-capsule (ans 0x84) decoder: 32 cabins x 3 points."""

    prev: Optional[bytes] = None

    def reset(self) -> None:
        self.prev = None

    def decode(self, frame: bytes) -> Tuple[List[HqNode], bool]:
        if not _has_exp_sync(frame) or not _check_capsule_checksum(frame):
            self.prev = None
            return [], False
        start = _start_angle_q6(frame)
        new_scan = bool(start & EXP_SYNCBIT)
        if new_scan:
            self.prev = None
        nodes: List[HqNode] = []
        if self.prev is not None:
            nodes = self._decode_pair(self.prev, frame)
        self.prev = frame
        return nodes, new_scan

    @staticmethod
    def _decode_pair(prev: bytes, cur: bytes) -> List[HqNode]:
        cur_q8 = (_start_angle_q6(cur) & 0x7FFF) << 2
        prev_q8 = (_start_angle_q6(prev) & 0x7FFF) << 2
        diff_q8 = cur_q8 - prev_q8
        if prev_q8 > cur_q8:
            diff_q8 += 360 << 8
        angle_inc_q16 = (diff_q8 << 3) // 3
        angle_raw_q16 = prev_q8 << 8

        words = list(struct.unpack_from("<32I", prev, 4))
        next_word0 = struct.unpack_from("<I", cur, 4)[0]

        nodes = []
        for pos in range(32):
            w = words[pos]
            dist_major_raw = w & 0xFFF
            # "magic shift" signed extraction of the two 10-bit predicts
            predict1 = _i32((w << 10) & 0xFFFFFFFF) >> 22
            predict2 = _i32(w) >> 22
            next_raw = (words[pos + 1] if pos < 31 else next_word0) & 0xFFF

            dist_major, lvl1 = varbitscale_decode(dist_major_raw)
            dist_major2, lvl2 = varbitscale_decode(next_raw)

            base1, base2 = dist_major, dist_major2
            if (not dist_major) and dist_major2:
                base1, lvl1 = dist_major2, lvl2

            d = [dist_major << 2, 0, 0]
            if predict1 in (-512, 511):
                d[1] = 0
            else:
                d[1] = ((predict1 << lvl1) + base1) << 2
            if predict2 in (-512, 511):
                d[2] = 0
            else:
                d[2] = ((predict2 << lvl2) + base2) << 2

            for c in range(3):
                sync = 1 if ((angle_raw_q16 + angle_inc_q16) % FULL_TURN_Q16) < angle_inc_q16 else 0
                corr = ultra_angle_correction_q16(d[c])
                angle_q6 = _i32(angle_raw_q16 - corr) >> 10
                angle_raw_q16 += angle_inc_q16
                angle_q6 = _wrap_angle_q6(angle_q6)
                nodes.append(
                    HqNode(
                        angle_q14=(angle_q6 << 8) // 90,
                        dist_q2=d[c],
                        quality=(0x2F << 2) if d[c] else 0,
                        flag=sync | ((0 if sync else 1) << 1),
                    )
                )
        return nodes


# ---------------------------------------------------------------------------
# Dense capsule — handler_capsules.cpp:736-791
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class DenseCapsuleDecoder:
    """Stateful dense-capsule (ans 0x85) decoder: 40 raw u16 distances.

    Carries the edge-detection sync state across capsules (the reference
    keeps it in a function-static, handler_capsules.cpp:738 — a latent
    cross-instance hazard we scope per-decoder instead).
    """

    sample_duration_us: float = 476.0
    prev: Optional[bytes] = None
    last_sync_out: int = 0

    def reset(self) -> None:
        self.prev = None
        # NB: the reference does NOT reset the static lastNodeSyncBit.

    def decode(self, frame: bytes) -> Tuple[List[HqNode], bool]:
        if not _has_exp_sync(frame) or not _check_capsule_checksum(frame):
            self.prev = None
            return [], False
        start = _start_angle_q6(frame)
        new_scan = bool(start & EXP_SYNCBIT)
        if new_scan:
            self.prev = None
        nodes: List[HqNode] = []
        if self.prev is not None:
            nodes = self._decode_pair(self.prev, frame)
            if nodes is None:
                # angle-jump discard: keep *current* as prev, emit nothing
                self.prev = frame
                return [], new_scan
        self.prev = frame
        return nodes, new_scan

    def _decode_pair(self, prev: bytes, cur: bytes) -> Optional[List[HqNode]]:
        cur_q8 = (_start_angle_q6(cur) & 0x7FFF) << 2
        prev_q8 = (_start_angle_q6(prev) & 0x7FFF) << 2
        diff_q8 = cur_q8 - prev_q8
        if prev_q8 > cur_q8:
            diff_q8 += 360 << 8
        # discard threshold vs 100 Hz rotation (handler_capsules.cpp:750-754)
        max_diff_q8 = (360 * 100 * 40 // int(1000000 / self.sample_duration_us)) << 8
        if diff_q8 > max_diff_q8:
            return None
        angle_inc_q16 = (diff_q8 << 8) // 40
        angle_raw_q16 = prev_q8 << 8
        dists = struct.unpack_from("<40H", prev, 4)
        nodes = []
        for pos in range(40):
            dist_q2 = dists[pos] << 2
            angle_q6 = angle_raw_q16 >> 10
            sync_raw = 1 if ((angle_raw_q16 + angle_inc_q16) % FULL_TURN_Q16) < (angle_inc_q16 << 1) else 0
            sync = (sync_raw ^ self.last_sync_out) & sync_raw  # rising edge only
            angle_raw_q16 += angle_inc_q16
            angle_q6 = _wrap_angle_q6(angle_q6)
            nodes.append(
                HqNode(
                    angle_q14=(angle_q6 << 8) // 90,
                    dist_q2=dist_q2,
                    quality=(0x2F << 2) if dist_q2 else 0,
                    flag=sync | ((0 if sync else 1) << 1),
                )
            )
            self.last_sync_out = sync
        return nodes


# ---------------------------------------------------------------------------
# Ultra-dense capsule — handler_capsules.cpp:951-1047
# ---------------------------------------------------------------------------

UD_THRESH_1 = 2046
UD_THRESH_2 = 8187
UD_THRESH_3 = 24567


def ultra_dense_decode_sample(word20: int) -> Tuple[int, int]:
    """Decode one 20-bit word -> (dist_q2_raw, quality).  Piecewise 4-level
    distance scale (handler_capsules.cpp:991-1017), smoothing NOT applied."""
    scale = word20 & 0x3
    if scale == 0:
        return (word20 & 0xFFC) * 2, word20 >> 12
    if scale == 1:
        return (word20 & 0x1FFC) * 3 + (UD_THRESH_1 << 2), ((word20 >> 13) << 1) & 0xFF
    if scale == 2:
        return (word20 & 0x3FFC) * 4 + (UD_THRESH_2 << 2), ((word20 >> 14) << 2) & 0xFF
    return (word20 & 0x7FFC) * 5 + (UD_THRESH_3 << 2), ((word20 >> 15) << 3) & 0xFF


@dataclasses.dataclass
class UltraDenseCapsuleDecoder:
    """Stateful ultra-dense (ans 0x86, DenseBoost) decoder: 32 cabins x 2.

    Carries both the sync edge detector and the +/-2 mm smoothing history
    across capsules (handler_capsules.cpp:999-1003,1018-1021).
    """

    sample_duration_us: float = 476.0
    prev: Optional[bytes] = None
    last_sync_out: int = 0
    last_dist_q2: int = 0

    def reset(self) -> None:
        self.prev = None
        self.last_sync_out = 0
        self.last_dist_q2 = 0

    def decode(self, frame: bytes) -> Tuple[List[HqNode], bool]:
        if not _has_exp_sync(frame) or not _check_capsule_checksum(frame, payload_from=2):
            self.prev = None
            return [], False
        start = struct.unpack_from("<H", frame, 8)[0]
        new_scan = bool(start & EXP_SYNCBIT)
        if new_scan:
            self.prev = None
        nodes: List[HqNode] = []
        if self.prev is not None:
            nodes = self._decode_pair(self.prev, frame)
            if nodes is None:
                self.prev = frame
                return [], new_scan
        self.prev = frame
        return nodes, new_scan

    def _decode_pair(self, prev: bytes, cur: bytes) -> Optional[List[HqNode]]:
        cur_q8 = (struct.unpack_from("<H", cur, 8)[0] & 0x7FFF) << 2
        prev_q8 = (struct.unpack_from("<H", prev, 8)[0] & 0x7FFF) << 2
        diff_q8 = cur_q8 - prev_q8
        if prev_q8 > cur_q8:
            diff_q8 += 360 << 8
        max_diff_q8 = (360 * 100 * 32 // int(1000000 / self.sample_duration_us)) << 8
        if diff_q8 > max_diff_q8:
            return None
        angle_inc_q16 = (diff_q8 << 8) // 64
        angle_raw_q16 = prev_q8 << 8
        nodes = []
        for pos in range(64):
            cab = pos >> 1
            w0, w1, hi = struct.unpack_from("<HHB", prev, 10 + 5 * cab)
            if not pos & 1:
                word20 = w0 | ((hi & 0x0F) << 16)
            else:
                word20 = w1 | ((hi >> 4) << 16)
            scale = word20 & 0x3
            dist_q2, quality = ultra_dense_decode_sample(word20)
            if scale == 0 and self.last_dist_q2:
                if abs(dist_q2 - self.last_dist_q2) <= 8:  # 2 mm in Q2
                    dist_q2 = (dist_q2 + self.last_dist_q2) >> 1
            self.last_dist_q2 = dist_q2
            angle_q6 = angle_raw_q16 >> 10
            sync_raw = 1 if ((angle_raw_q16 + angle_inc_q16) % FULL_TURN_Q16) < (angle_inc_q16 << 1) else 0
            sync = (sync_raw ^ self.last_sync_out) & sync_raw
            angle_raw_q16 += angle_inc_q16
            angle_q6 = _wrap_angle_q6(angle_q6)
            nodes.append(
                HqNode(
                    angle_q14=(angle_q6 << 8) // 90,
                    dist_q2=dist_q2,
                    quality=quality,
                    flag=sync | ((0 if sync else 1) << 1),
                )
            )
            self.last_sync_out = sync
        return nodes


# ---------------------------------------------------------------------------
# HQ capsule — handler_hqnode.cpp:92-174
# ---------------------------------------------------------------------------


def decode_hq_capsule(frame: bytes) -> Tuple[List[HqNode], int]:
    """Decode one HQ capsule; returns ([], 0) on CRC mismatch, else the 96
    nodes and the device timestamp."""
    if frame[0] != HQ_SYNC:
        return [], 0
    recv_crc = struct.unpack_from("<I", frame, len(frame) - 4)[0]
    if crc.crc32_padded(frame[:-4]) != recv_crc:
        return [], 0
    ts = struct.unpack_from("<Q", frame, 1)[0]
    nodes = []
    for i in range(96):
        angle_q14, dist_q2, quality, flag = struct.unpack_from("<HIBB", frame, 9 + 8 * i)
        nodes.append(HqNode(angle_q14, dist_q2, quality, flag))
    return nodes, ts
