"""Vectorized JAX decoders for the six measurement wire formats.

TPU-first reformulation of the reference's per-byte handler loops
(src/sdk/src/dataunpacker/unpacker/handler_*.cpp): every capsule format
except HQ is only *sequential* through the previous-capsule angle
interpolation, so a batch of M consecutive capsule frames decodes as M-1
independent (prev, cur) pairs — pure branch-free int32 math over cabins,
ideal for the VPU.  The two genuinely sequential recurrences (dense-format
sync-edge detection and ultra-dense +/-2 mm smoothing) are handled with a
closed-form parallel scan and a fused ``lax.scan`` respectively.

All kernels are shape-stable: M is static per compiled specialization; the
returned ``pair_valid`` / node masks carry the data-dependent validity.
Bit-exactness against the scalar golden model (ops/unpack_ref.py) is
enforced by tests/test_unpack_golden.py.
"""

from __future__ import annotations

import functools
from typing import NamedTuple

import jax
import jax.numpy as jnp
import numpy as np

from rplidar_ros2_driver_tpu.protocol.constants import (
    CAPSULE_BYTES,
    DENSE_CAPSULE_BYTES,
    EXP_SYNC_1,
    EXP_SYNC_2,
    HQ_CAPSULE_BYTES,
    HQ_NODES_PER_CAPSULE,
    ULTRA_CAPSULE_BYTES,
    ULTRA_DENSE_CAPSULE_BYTES,
    VARBITSCALE_X2_DEST_VAL,
    VARBITSCALE_X2_SRC_BIT,
    VARBITSCALE_X4_DEST_VAL,
    VARBITSCALE_X4_SRC_BIT,
    VARBITSCALE_X8_DEST_VAL,
    VARBITSCALE_X8_SRC_BIT,
    VARBITSCALE_X16_DEST_VAL,
    VARBITSCALE_X16_SRC_BIT,
)

FULL_TURN_Q6 = 360 << 6
FULL_TURN_Q16 = 360 << 16
_QUAL_VALID = 0x2F << 2  # synthetic quality for formats without one


class DecodedNodes(NamedTuple):
    """SoA decode result.  Shapes: (pairs, points) unless noted."""

    angle_q14: jax.Array  # int32
    dist_q2: jax.Array    # int32
    quality: jax.Array    # int32
    flag: jax.Array       # int32 (bit0 sync, bit1 = !sync)
    node_valid: jax.Array # bool — node comes from a valid frame pair
    new_scan: jax.Array   # bool (M,) — frame i carries the EXP sync bit
    frame_valid: jax.Array# bool (M,) — sync nibbles + checksum OK


# ---------------------------------------------------------------------------
# byte-array field helpers (frames arrive as uint8 (M, B) -> int32)
# ---------------------------------------------------------------------------


def _u16(f: jax.Array, off: int) -> jax.Array:
    return f[:, off] | (f[:, off + 1] << 8)


def _u32(f: jax.Array, off: int) -> jax.Array:
    # graftlint: disable=GL011 — little-endian u32 assembly: byte<<24
    # wraps int32 by design, consumers read the bit pattern only
    return f[:, off] | (f[:, off + 1] << 8) | (f[:, off + 2] << 16) | (f[:, off + 3] << 24)


def _xor_reduce(x: jax.Array, axis: int) -> jax.Array:
    return jax.lax.reduce(x, np.int32(0), jax.lax.bitwise_xor, (axis,))


def _capsule_frame_valid(frames: jax.Array, payload_from: int = 2) -> jax.Array:
    """Express-style validity: sync nibbles 0xA/0x5 + split XOR checksum
    (handler_capsules.cpp:107-155)."""
    sync_ok = ((frames[:, 0] >> 4) == EXP_SYNC_1) & ((frames[:, 1] >> 4) == EXP_SYNC_2)
    recv = (frames[:, 0] & 0xF) | ((frames[:, 1] & 0xF) << 4)
    calc = _xor_reduce(frames[:, payload_from:], 1)
    return sync_ok & (recv == calc)


def _asi32(frames) -> jax.Array:
    f = jnp.asarray(frames)
    if f.dtype != jnp.int32:
        f = f.astype(jnp.int32)
    return f


def _pair_diff(start_q6: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Shared (prev, cur) start-angle geometry of consecutive capsule
    frames: returns (base_q16, diff_q8) for each of the M-1 pairs, where
    ``base_q16`` is the previous frame's start angle in Q16 degrees and
    ``diff_q8`` the angular span of the pair in Q8 degrees, wrapped to
    one positive turn.  The per-format Q16 sample increment is derived
    from ``diff_q8`` by the ``_*_increment`` constructor below matching
    the wire format — one named fixed-point formula per format, exactly
    mirroring the four interpolations in the reference's capsule
    handlers (see each constructor's citation)."""
    cur_q8 = (start_q6[1:] & 0x7FFF) << 2
    prev_q8 = (start_q6[:-1] & 0x7FFF) << 2
    diff_q8 = cur_q8 - prev_q8
    diff_q8 = jnp.where(prev_q8 > cur_q8, diff_q8 + (360 << 8), diff_q8)
    return prev_q8 << 8, diff_q8


def _express_increment(diff_q8: jax.Array) -> jax.Array:
    """Express capsule: 32 samples/pair — diff_q8/32 in Q16 is a pure
    shift, ``diff_q8 << 3`` (handler_capsules.cpp:206-266)."""
    return diff_q8 << 3


def _ultra_increment(diff_q8: jax.Array) -> jax.Array:
    """Ultra capsule: 96 samples/pair — ``(diff_q8 << 3) // 3``
    (handler_capsules.cpp:522-529; equal to (diff_q8 << 8) // 96)."""
    return (diff_q8 << 3) // 3


def _dense_increment(diff_q8: jax.Array) -> jax.Array:
    """Dense capsule: 40 samples/pair — ``(diff_q8 << 8) // 40``
    (handler_capsules.cpp:741-760)."""
    return (diff_q8 << 8) // 40


def _ultra_dense_increment(diff_q8: jax.Array) -> jax.Array:
    """Ultra-dense (DenseBoost) capsule: 64 samples/pair —
    ``(diff_q8 << 8) // 64`` (handler_capsules.cpp:949-989)."""
    return (diff_q8 << 8) // 64


def _sample_angles(base_q16: jax.Array, inc_q16: jax.Array, npts: int):
    """angle_raw at each sample k and the raw sync predicate inputs."""
    k = jnp.arange(npts, dtype=jnp.int32)
    raw = base_q16[:, None] + k[None, :] * inc_q16[:, None]
    return raw


def _wrap_q6(a: jax.Array) -> jax.Array:
    a = jnp.where(a < 0, a + FULL_TURN_Q6, a)
    return jnp.where(a >= FULL_TURN_Q6, a - FULL_TURN_Q6, a)


def _finish_nodes(angle_q6, dist_q2, sync):
    angle_q6 = _wrap_q6(angle_q6)
    angle_q14 = (angle_q6 << 8) // 90
    quality = jnp.where(dist_q2 != 0, _QUAL_VALID, 0)
    flag = sync | (jnp.where(sync == 0, 1, 0) << 1)
    return angle_q14, quality, flag


# ---------------------------------------------------------------------------
# Normal (legacy) 5-byte nodes — vectorized over a batch of nodes
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=())
def unpack_normal_nodes(frames) -> DecodedNodes:
    """Decode (M, 5) legacy nodes (handler_normalnode.cpp:87-133).

    Each frame is independent; ``node_valid`` folds the sync/inverse-sync
    and angle check bits.
    """
    f = _asi32(frames)
    b0 = f[:, 0]
    sync_ok = (((b0 >> 1) ^ b0) & 0x1) == 1
    angle_field = _u16(f, 1)
    check_ok = (angle_field & 0x1) == 1
    valid = sync_ok & check_ok
    angle_q14 = (((angle_field >> 1) << 8) // 90)[:, None]
    dist_q2 = _u16(f, 3)[:, None]
    quality = ((b0 >> 2) << 2)[:, None]
    sync = (b0 & 0x1)[:, None]
    return DecodedNodes(
        angle_q14=angle_q14,
        dist_q2=dist_q2,
        quality=quality,
        flag=sync,  # legacy path publishes the raw sync bit as the flag
        node_valid=valid[:, None],
        new_scan=(b0 & 0x1).astype(bool),
        frame_valid=valid,
    )


# ---------------------------------------------------------------------------
# Express capsule: 16 cabins x 2 points  (handler_capsules.cpp:206-266)
# ---------------------------------------------------------------------------


@jax.jit
def unpack_capsules(frames) -> DecodedNodes:
    """Decode (M, 84) express capsules into (M-1, 32) nodes."""
    f = _asi32(frames)
    assert f.shape[1] == CAPSULE_BYTES
    frame_valid = _capsule_frame_valid(f)
    start_q6 = _u16(f, 2)
    new_scan = ((start_q6 & 0x8000) != 0) & frame_valid

    base_q16, diff_q8 = _pair_diff(start_q6)
    inc_q16 = _express_increment(diff_q8)
    raw = _sample_angles(base_q16, inc_q16, 32)  # (M-1, 32)

    # cabin fields from the PREV frame of each pair
    p = f[:-1]
    cab_off = 4 + 5 * jnp.arange(16, dtype=jnp.int32)
    da1 = p[:, cab_off] | (p[:, cab_off + 1] << 8)
    da2 = p[:, cab_off + 2] | (p[:, cab_off + 3] << 8)
    packed = p[:, cab_off + 4]
    dist = jnp.stack([da1 & 0xFFFC, da2 & 0xFFFC], -1).reshape(p.shape[0], 32)
    off_q3 = jnp.stack(
        [(packed & 0xF) | ((da1 & 0x3) << 4), (packed >> 4) | ((da2 & 0x3) << 4)], -1
    ).reshape(p.shape[0], 32)

    angle_q6 = (raw - (off_q3 << 13)) >> 10
    sync = (((raw + inc_q16[:, None]) % FULL_TURN_Q16) < inc_q16[:, None]).astype(jnp.int32)
    angle_q14, quality, flag = _finish_nodes(angle_q6, dist, sync)

    pair_valid = frame_valid[:-1] & frame_valid[1:] & ~new_scan[1:]
    return DecodedNodes(
        angle_q14, dist, quality, flag, pair_valid[:, None] & jnp.ones((1, 32), bool),
        new_scan, frame_valid,
    )


# ---------------------------------------------------------------------------
# Ultra capsule: varbitscale, 32 cabins x 3 points
# ---------------------------------------------------------------------------

# Branch-free varbitscale decode (handler_capsules.cpp:422-458): pick the
# largest base <= scaled.
_VBS_SCALED = np.array(
    [0, VARBITSCALE_X2_DEST_VAL, VARBITSCALE_X4_DEST_VAL, VARBITSCALE_X8_DEST_VAL,
     VARBITSCALE_X16_DEST_VAL], np.int32)
_VBS_TARGET = np.array(
    [0, 1 << VARBITSCALE_X2_SRC_BIT, 1 << VARBITSCALE_X4_SRC_BIT,
     1 << VARBITSCALE_X8_SRC_BIT, 1 << VARBITSCALE_X16_SRC_BIT], np.int32)


def _varbitscale_decode(scaled: jax.Array):
    lvl = jnp.sum(scaled[..., None] >= jnp.asarray(_VBS_SCALED)[None, :], -1) - 1
    # graftlint: disable=GL011 — lvl in [0, 4] by construction (sum over
    # the 5-entry threshold axis), so the shift is <= 4 bits on a 12-bit
    # residual; the interpreter over-approximates the axis sum
    value = jnp.asarray(_VBS_TARGET)[lvl] + ((scaled - jnp.asarray(_VBS_SCALED)[lvl]) << lvl)
    return value, lvl


def _build_ultra_corr_lut() -> np.ndarray:
    """k2 -> int(offsetAngleMean_q16 * 180 / pi) lookup.

    The C path (handler_capsules.cpp:547-557) computes the correction with
    double arithmetic; k2 = 98361 // dist_q2 <= 491 for dist_q2 >= 200, so
    the full function fits a 492-entry table evaluated here in float64 —
    bit-exact without needing f64 on the TPU.
    """
    base = int(8 * 3.1415926535 * (1 << 16) / 180)
    k2 = np.arange(492, dtype=np.int64)
    off = base - (k2 << 6) - (k2 * k2 * k2) // 98304
    return np.trunc(off.astype(np.float64) * 180 / 3.14159265).astype(np.int32)


_ULTRA_CORR_LUT = _build_ultra_corr_lut()
_ULTRA_CORR_DEFAULT = int(
    np.trunc(int(7.5 * 3.1415926535 * (1 << 16) / 180.0) * 180 / 3.14159265)
)


@jax.jit
def unpack_ultra_capsules(frames) -> DecodedNodes:
    """Decode (M, 132) ultra capsules into (M-1, 96) nodes."""
    f = _asi32(frames)
    assert f.shape[1] == ULTRA_CAPSULE_BYTES
    frame_valid = _capsule_frame_valid(f)
    start_q6 = _u16(f, 2)
    new_scan = ((start_q6 & 0x8000) != 0) & frame_valid

    base_q16, diff_q8 = _pair_diff(start_q6)
    inc_q16 = _ultra_increment(diff_q8)
    raw = _sample_angles(base_q16, inc_q16, 96)  # (M-1, 96)

    p = f[:-1]
    cab_off = 4 + 4 * jnp.arange(32, dtype=jnp.int32)
    w = (
        p[:, cab_off]
        | (p[:, cab_off + 1] << 8)
        | (p[:, cab_off + 2] << 16)
        # graftlint: disable=GL011 — u32 cabin assembly: byte<<24 wraps
        # int32 BY DESIGN; only the bit pattern is consumed below
        | (p[:, cab_off + 3] << 24)
    )  # int32, may be "negative" — bit pattern is what matters

    major_raw = w & 0xFFF
    # graftlint: disable=GL011 — (w<<10)>>22 is the sign-extending field
    # extract from the C decoder: the left shift wraps deliberately so
    # the arithmetic right shift reproduces the 10-bit two's complement
    predict1 = (w << 10) >> 22   # arithmetic shifts reproduce the C magic
    predict2 = w >> 22
    # next cabin's major: shift within frame; last cabin takes cabin 0 of cur
    next_first = (_u32(f, 4)[1:]) & 0xFFF
    next_raw = jnp.concatenate([major_raw[:, 1:], next_first[:, None]], axis=1)

    major, lvl1 = _varbitscale_decode(major_raw)
    major2, lvl2 = _varbitscale_decode(next_raw)
    swap = (major == 0) & (major2 != 0)
    base1 = jnp.where(swap, major2, major)
    lvl1 = jnp.where(swap, lvl2, lvl1)

    d0 = major << 2
    inval1 = (predict1 == -512) | (predict1 == 511)
    # graftlint: disable=GL011 — predict is a 10-bit two's-complement
    # field (|predict| <= 512) and lvl <= 4 by varbitscale construction:
    # (512<<4 + 28656) << 2 < 2^18, but the interpreter cannot see the
    # data-dependent lvl cap
    d1 = jnp.where(inval1, 0, ((predict1 << lvl1) + base1) << 2)
    inval2 = (predict2 == -512) | (predict2 == 511)
    # graftlint: disable=GL011 — same 10-bit predict / lvl<=4 argument
    d2 = jnp.where(inval2, 0, ((predict2 << lvl2) + major2) << 2)
    # graftlint: disable=GL011 — |dist| <= (512<<4 + 28656) << 2 < 2^18
    # by the predict/varbitscale widths above; clipping here would break
    # bit-parity with unpack_ref.UltraCapsuleDecoder on garbage cabins
    dist = jnp.stack([d0, d1, d2], -1).reshape(p.shape[0], 96)

    k2 = jnp.asarray(98361, jnp.int32) // jnp.maximum(dist, 1)
    corr = jnp.where(
        dist >= 200,
        jnp.asarray(_ULTRA_CORR_LUT)[jnp.clip(k2, 0, 491)],
        _ULTRA_CORR_DEFAULT,
    )
    angle_q6 = (raw - corr) >> 10
    sync = (((raw + inc_q16[:, None]) % FULL_TURN_Q16) < inc_q16[:, None]).astype(jnp.int32)
    angle_q14, quality, flag = _finish_nodes(angle_q6, dist, sync)

    pair_valid = frame_valid[:-1] & frame_valid[1:] & ~new_scan[1:]
    return DecodedNodes(
        angle_q14, dist, quality, flag, pair_valid[:, None] & jnp.ones((1, 96), bool),
        new_scan, frame_valid,
    )


# ---------------------------------------------------------------------------
# Sync-edge recurrence  o_k = s_k & ~o_{k-1}  in closed form
# ---------------------------------------------------------------------------


def _sync_edge(s: jax.Array, carry: jax.Array) -> jax.Array:
    """Parallel form of the reference's rising-edge filter
    (``syncBit = (syncBit ^ last) & syncBit``, handler_capsules.cpp:766-767).

    Within a run of raw sync bits the output alternates starting with 1, so
    o_k = s_k & odd(k - last_zero_index); ``carry`` is o_{-1} from the
    previous batch (affects only a run that starts at k=0).
    """
    n = s.shape[0]
    idx = jnp.arange(n, dtype=jnp.int32)
    zpos = jnp.where(s == 0, idx, -1)
    last_zero = jax.lax.associative_scan(jnp.maximum, zpos)
    adj = jnp.where(last_zero == -1, carry.astype(jnp.int32), 0)
    return s & ((idx - last_zero + adj) & 1)


# ---------------------------------------------------------------------------
# Dense capsule: 40 raw u16 distances
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, static_argnames=("sample_duration_us",))
def unpack_dense_capsules(frames, last_sync_out=0, sample_duration_us: int = 476) -> DecodedNodes:
    """Decode (M, 84) dense capsules into (M-1, 40) nodes.

    ``last_sync_out`` carries the sync edge detector across batches.
    Pairs whose start-angle jump exceeds the 100 Hz threshold are masked
    (the reference discards them, handler_capsules.cpp:750-754).
    """
    f = _asi32(frames)
    assert f.shape[1] == DENSE_CAPSULE_BYTES
    frame_valid = _capsule_frame_valid(f)
    start_q6 = _u16(f, 2)
    new_scan = ((start_q6 & 0x8000) != 0) & frame_valid

    base_q16, diff_q8 = _pair_diff(start_q6)
    inc_q16 = _dense_increment(diff_q8)
    max_diff_q8 = (360 * 100 * 40 // (1000000 // sample_duration_us)) << 8
    jump_ok = diff_q8 <= max_diff_q8

    raw = _sample_angles(base_q16, inc_q16, 40)
    p = f[:-1]
    off = 4 + 2 * jnp.arange(40, dtype=jnp.int32)
    dist = (p[:, off] | (p[:, off + 1] << 8)) << 2

    pair_valid = frame_valid[:-1] & frame_valid[1:] & ~new_scan[1:] & jump_ok
    angle_q6 = raw >> 10
    s_raw = (((raw + inc_q16[:, None]) % FULL_TURN_Q16) < (inc_q16[:, None] << 1)).astype(jnp.int32)
    # samples of discarded pairs never reach the reference's edge filter;
    # zeroing them keeps the carry chain aligned (runs crossing a dropped
    # capsule — sync fires ~once/rev — may differ by one flag; the <= 1
    # flag/dropped-frame bound is pinned by
    # tests/test_unpack_golden.py::TestSyncEdgeDivergenceBound).
    s_raw = s_raw * pair_valid[:, None].astype(jnp.int32)
    sync = _sync_edge(s_raw.reshape(-1), jnp.asarray(last_sync_out)).reshape(s_raw.shape)
    angle_q14, quality, flag = _finish_nodes(angle_q6, dist, sync)

    return DecodedNodes(
        angle_q14, dist, quality, flag, pair_valid[:, None] & jnp.ones((1, 40), bool),
        new_scan, frame_valid,
    )


# ---------------------------------------------------------------------------
# Ultra-dense capsule (DenseBoost): 32 cabins x 2 points, 20-bit words
# ---------------------------------------------------------------------------

_UD_T1, _UD_T2, _UD_T3 = 2046, 8187, 24567


def _ud_decode_words(w20: jax.Array):
    """(raw dist_q2, quality) from 20-bit words — branchless 4-level scale
    (handler_capsules.cpp:991-1017)."""
    scale = w20 & 0x3
    d0 = (w20 & 0xFFC) * 2
    d1 = (w20 & 0x1FFC) * 3 + (_UD_T1 << 2)
    d2 = (w20 & 0x3FFC) * 4 + (_UD_T2 << 2)
    d3 = (w20 & 0x7FFC) * 5 + (_UD_T3 << 2)
    dist = jnp.select([scale == 0, scale == 1, scale == 2], [d0, d1, d2], d3)
    q0 = w20 >> 12
    q1 = ((w20 >> 13) << 1) & 0xFF
    q2 = ((w20 >> 14) << 2) & 0xFF
    q3 = ((w20 >> 15) << 3) & 0xFF
    qual = jnp.select([scale == 0, scale == 1, scale == 2], [q0, q1, q2], q3)
    return dist, qual, scale


def _ud_smooth(
    dist_raw: jax.Array, scale: jax.Array, skip: jax.Array, last_dist: jax.Array
) -> jax.Array:
    """Exact +/-2 mm temporal smoothing (sequential; scale-0 samples only).

    o_k = (d_k + o_{k-1}) >> 1  when scale_k == 0, o_{k-1} != 0 and
    |d_k - o_{k-1}| <= 8, else d_k — a genuine recurrence, run as a fused
    ``lax.scan`` over the flattened sample stream.  ``skip`` marks samples
    of discarded pairs: they pass through without touching the carry (the
    reference never sees them).
    """

    def step(carry, x):
        d, sc, sk = x
        cond = (sc == 0) & (carry != 0) & (jnp.abs(d - carry) <= 8)
        out = jnp.where(cond, (d + carry) >> 1, d)
        new_carry = jnp.where(sk, carry, out)
        return new_carry, out

    # unroll=8 beats 32 on both compile time (~15x) and CPU runtime (~10x)
    # for the 64-sample-per-frame stream shapes the live decoder feeds
    _, out = jax.lax.scan(step, last_dist, (dist_raw, scale, skip), unroll=8)
    return out


@functools.partial(jax.jit, static_argnames=("sample_duration_us",))
def unpack_ultra_dense_capsules(
    frames, last_sync_out=0, last_dist_q2=0, sample_duration_us: int = 476
) -> DecodedNodes:
    """Decode (M, 172) ultra-dense capsules into (M-1, 64) nodes."""
    f = _asi32(frames)
    assert f.shape[1] == ULTRA_DENSE_CAPSULE_BYTES
    frame_valid = _capsule_frame_valid(f, payload_from=2)
    start_q6 = _u16(f, 8)
    new_scan = ((start_q6 & 0x8000) != 0) & frame_valid

    base_q16, diff_q8 = _pair_diff(start_q6)
    inc_q16 = _ultra_dense_increment(diff_q8)
    max_diff_q8 = (360 * 100 * 32 // (1000000 // sample_duration_us)) << 8
    jump_ok = diff_q8 <= max_diff_q8
    pair_valid = frame_valid[:-1] & frame_valid[1:] & ~new_scan[1:] & jump_ok

    raw = _sample_angles(base_q16, inc_q16, 64)
    p = f[:-1]
    cab_off = 10 + 5 * jnp.arange(32, dtype=jnp.int32)
    w0 = p[:, cab_off] | (p[:, cab_off + 1] << 8) | ((p[:, cab_off + 4] & 0x0F) << 16)
    w1 = p[:, cab_off + 2] | (p[:, cab_off + 3] << 8) | ((p[:, cab_off + 4] >> 4) << 16)
    words = jnp.stack([w0, w1], -1).reshape(p.shape[0], 64)

    dist_raw, quality, scale = _ud_decode_words(words)
    skip = jnp.broadcast_to(~pair_valid[:, None], dist_raw.shape)
    dist = _ud_smooth(
        dist_raw.reshape(-1), scale.reshape(-1), skip.reshape(-1),
        jnp.asarray(last_dist_q2, jnp.int32),
    ).reshape(dist_raw.shape)

    angle_q6 = raw >> 10
    s_raw = (((raw + inc_q16[:, None]) % FULL_TURN_Q16) < (inc_q16[:, None] << 1)).astype(jnp.int32)
    s_raw = s_raw * pair_valid[:, None].astype(jnp.int32)
    sync = _sync_edge(s_raw.reshape(-1), jnp.asarray(last_sync_out)).reshape(s_raw.shape)

    angle_q6 = _wrap_q6(angle_q6)
    angle_q14 = (angle_q6 << 8) // 90
    flag = sync | (jnp.where(sync == 0, 1, 0) << 1)

    return DecodedNodes(
        angle_q14, dist, quality, flag, pair_valid[:, None] & jnp.ones((1, 64), bool),
        new_scan, frame_valid,
    )


# ---------------------------------------------------------------------------
# HQ capsule: 96 pre-formatted nodes (CRC checked host-side)
# ---------------------------------------------------------------------------


@jax.jit
def unpack_hq_capsules(frames, crc_ok=None) -> DecodedNodes:
    """Decode (M, 777) HQ capsules into (M, 96) nodes.

    CRC32 runs on the host (protocol/crc.py) — pass the per-frame verdicts
    in ``crc_ok``; in-kernel we only check the 0xA5 sync byte.
    """
    f = _asi32(frames)
    assert f.shape[1] == HQ_CAPSULE_BYTES
    sync_ok = f[:, 0] == 0xA5
    frame_valid = sync_ok if crc_ok is None else sync_ok & jnp.asarray(crc_ok)
    off = 9 + 8 * jnp.arange(HQ_NODES_PER_CAPSULE, dtype=jnp.int32)
    angle_q14 = f[:, off] | (f[:, off + 1] << 8)
    # graftlint: disable=GL011 — u32 dist field assembly: byte<<24 wraps
    # int32 by design (the wire field is 32-bit little-endian)
    dist = f[:, off + 2] | (f[:, off + 3] << 8) | (f[:, off + 4] << 16) | (f[:, off + 5] << 24)
    quality = f[:, off + 6]
    flag = f[:, off + 7]
    return DecodedNodes(
        angle_q14, dist, quality, flag,
        frame_valid[:, None] & jnp.ones((1, HQ_NODES_PER_CAPSULE), bool),
        (flag[:, 0] & 1).astype(bool), frame_valid,
    )
