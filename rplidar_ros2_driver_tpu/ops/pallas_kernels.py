"""Pallas TPU kernels for the filter chain's hot ops.

The rolling temporal median is the chain's dominant cost (SURVEY.md §7
"hard parts": a W x B median per revolution).  The XLA path sorts the
whole (W, B) window in HBM via ``jnp.sort``; this kernel instead runs a
fully vectorized bitonic sorting network over the window axis inside
VMEM, tiled over beams, so each (W, TB) tile is read from HBM exactly
once and the median selection fuses with the sort.

The network is expressed with static reshapes + min/max only (no
gathers, no data-dependent control flow) so Mosaic vectorizes every
compare-exchange onto the VPU:

  * stage (k, j): rows viewed as (W/(2j), 2, j); partners (i, i^j) are
    the two slices of the middle axis; the ascending/descending
    direction depends only on the leading group index — a compile-time
    boolean vector.

On non-TPU backends the kernel runs in interpreter mode, which keeps CI
(CPU pytest) covering the exact kernel code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _lowering_dispatch(compiled_fn, interpret_fn, *args):
    """Pick the Mosaic-compiled kernel vs interpret mode AT LOWERING
    TIME (``lax.platform_dependent``), not from the process default
    backend: a function traced for a CPU device on a TPU-default host
    (e.g. a config pinned to ``inc_pallas`` jitted onto a CPU device)
    must get the interpretable lowering — ``jax.default_backend()``
    sees the host default, not the trace target.  Both branches are
    traced; only the branch matching each lowering platform is
    compiled, so the selection costs nothing at runtime.

    One guard ahead of the platform cond: current jax lowers BOTH
    ``platform_dependent`` branches even for a single-platform lowering
    (no dead-branch elimination in cond), so the Mosaic branch bricks a
    CPU-only process with "Only interpret mode is supported on CPU
    backend" (pinned by tests/test_pallas_median.py's dispatch test).
    A process with no TPU backend at all can never legitimately reach
    the compiled branch, so it is dropped before tracing; hosts that DO
    have a TPU keep the full lowering-time selection."""
    try:
        tpu_present = bool(jax.devices("tpu"))
    except RuntimeError:
        tpu_present = False
    if not tpu_present:
        return interpret_fn(*args)
    return jax.lax.platform_dependent(
        *args, tpu=compiled_fn, default=interpret_fn
    )


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _bitonic_sort_rows(x: jax.Array) -> jax.Array:
    """Ascending bitonic sort along axis 0 (static power-of-2 length)."""
    w = x.shape[0]
    assert w & (w - 1) == 0, "bitonic network needs power-of-2 rows"
    tail = x.shape[1:]
    k = 2
    while k <= w:
        j = k // 2
        while j >= 1:
            g = w // (2 * j)
            v = x.reshape((g, 2, j) + tail)
            a, b = v[:, 0], v[:, 1]
            lo = jnp.minimum(a, b)
            hi = jnp.maximum(a, b)
            # bit k of row index i = (i // (2j)) // (k // (2j)) & 1 —
            # constant per leading group; iota keeps it kernel-local
            # (pallas_call rejects captured host constants).
            gshape = (g, 1) + (1,) * len(tail)
            gidx = jax.lax.broadcasted_iota(jnp.int32, gshape, 0)
            asc = (gidx // max(k // (2 * j), 1)) % 2 == 0
            new_a = jnp.where(asc, lo, hi)
            new_b = jnp.where(asc, hi, lo)
            x = jnp.concatenate([new_a[:, None], new_b[:, None]], axis=1).reshape((w,) + tail)
            j //= 2
        k *= 2
    return x


def _pad_beam_tiles(x: jax.Array, block_beams: int, interpret: bool):
    """Shared beam-axis tiling rule of the median entry points: pick the
    tile width (>= one lane group on hardware, clamped to the data in
    interpret mode) and +inf-pad the minor axis to a tile multiple.
    Returns (padded array, tile width)."""
    b = x.shape[-1]
    tb = min(block_beams, _next_pow2(max(b, _LANES)))
    tb = max(tb, _LANES) if not interpret else min(tb, max(b, 1))
    b_pad = ((b + tb - 1) // tb) * tb
    if b_pad != b:
        x = jnp.pad(
            x, ((0, 0),) * (x.ndim - 1) + ((0, b_pad - b),), constant_values=jnp.inf
        )
    return x, tb


def _pick_lower_median(s: jax.Array, nvalid: jax.Array, w: int) -> jax.Array:
    """(rows, TB) ALREADY-SORTED columns + per-lane finite count ->
    (TB,) lower median.  The one kernel-side definition of the median
    rule (gather-free select-by-iota; all-inf lanes stay +inf), shared
    by the sort kernels (_median_select) and the fused sorted_replace
    kernel — the host-side jnp twin is ops/filters.median_from_sorted."""
    pick = jnp.clip((nvalid - 1) // 2, 0, w - 1)                # (TB,)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    med = jnp.sum(jnp.where(rows == pick[None, :], s, 0.0), axis=0)
    return jnp.where(nvalid > 0, med, jnp.inf)


def _median_select(win: jax.Array, w: int) -> jax.Array:
    """(>=W, TB) window -> (TB,) lower median of the finite values.

    Shared by the streaming (_median_kernel) and fused
    (_sliding_median_kernel) kernels: rows beyond ``w`` must be +inf
    padding (they sort to the tail and cannot shift the lower median)."""
    w_pad = _next_pow2(max(w, 2))
    nvalid = jnp.sum(jnp.isfinite(win[:w]), axis=0)             # (TB,)
    if win.shape[0] != w_pad:
        win = jnp.concatenate(
            [win, jnp.full((w_pad - win.shape[0], win.shape[1]), jnp.inf, win.dtype)]
        )
    s = _bitonic_sort_rows(win)                                 # inf sorts last
    return _pick_lower_median(s, nvalid, w)


def _median_kernel(win_ref, out_ref):
    """One (W, TB) tile: sort rows, pick the lower median of finite values."""
    win = win_ref[:]
    out_ref[:] = _median_select(win, win.shape[0])[None, :]


@functools.partial(jax.jit, static_argnames=("block_beams", "interpret"))
def _median_call(window: jax.Array, block_beams: int, interpret: bool) -> jax.Array:
    w, b = window.shape
    grid = (b // block_beams,)
    return pl.pallas_call(
        _median_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((w, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM)
        ],
        # 2-D (1, TB) output blocks: a bare 1-D f32 output hits an XLA/Mosaic
        # tiled-layout mismatch (T(1024) vs T(512)) on v5e.
        out_specs=pl.BlockSpec((1, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.float32),
        interpret=interpret,
    )(window)[0]


def _sliding_median_kernel(w: int, k: int, ext_ref, out_ref):
    """One (W+K, TB) history stripe -> (K, TB) sliding medians.

    Step i's window is rows [i+1, i+1+W) of the stripe (the W most
    recent rows after appending scan i — ops/filters.compact_filter_scan
    builds the stripe as [previous ring in age order] ++ [new rows]).
    Each stripe is read into VMEM once; the K windows are overlapping
    VMEM slices, so nothing is re-fetched from HBM and the (K, W, B)
    gather the XLA path materializes never exists.

    Mosaic only accepts sublane-aligned dynamic slice starts (multiples
    of 8 in dim 0), so steps are processed in groups of 8: one aligned
    (W+8, TB) load per group, the 8 windows inside it are static slices
    of the loaded value.  Requires k % 8 == 0 (caller pads)."""

    def body(g, _):
        blk = ext_ref[pl.ds(8 * g, w + 8), :]
        meds = [
            _median_select(blk[j + 1 : j + 1 + w], w)[None, :] for j in range(8)
        ]
        out_ref[pl.ds(8 * g, 8), :] = jnp.concatenate(meds, axis=0)
        return 0

    jax.lax.fori_loop(0, k // 8, body, 0)


@functools.partial(jax.jit, static_argnames=("w", "block_beams", "interpret"))
def _sliding_median_call(
    ext: jax.Array, w: int, block_beams: int, interpret: bool
) -> jax.Array:
    wk, b = ext.shape
    k = wk - w
    grid = (b // block_beams,)
    return pl.pallas_call(
        functools.partial(_sliding_median_kernel, w, k),
        grid=grid,
        in_specs=[
            pl.BlockSpec((wk, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM)
        ],
        out_specs=pl.BlockSpec(
            (k, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((k, b), jnp.float32),
        interpret=interpret,
    )(ext)


def sliding_median_pallas(
    ext: jax.Array,
    window: int,
    *,
    block_beams: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """K sliding per-beam medians over an extended history — Pallas backend.

    ``ext`` is (window + K, B): the previous ring in age order followed by
    K new rows; returns (K, B) where row i is the per-beam lower median
    over ``ext[i+1 : i+1+window]`` (exactly what K successive
    :func:`ops.filters.temporal_median` calls on the advancing ring would
    produce).  Non-power-of-two windows are padded with +inf rows inside
    the kernel (they sort to the tail without shifting the lower median).

    ``interpret=None`` (default) resolves per LOWERING platform
    (``_lowering_dispatch``), so the same traced function is correct on
    a TPU target and a CPU target alike."""
    wk, b = ext.shape
    w = window
    k = wk - w
    ext = ext.astype(jnp.float32)

    # group-of-8 alignment (see _sliding_median_kernel): pad the stripe
    # with trailing +inf rows; the extra outputs are sliced off
    k_pad = ((k + 7) // 8) * 8
    if k_pad != k:
        ext = jnp.pad(ext, ((0, k_pad - k), (0, 0)), constant_values=jnp.inf)

    def _impl(ext, interpret):
        # beam-tile padding sits inside the per-lowering branch: the
        # tile rule differs by mode, but the sliced output shape matches
        padded, tb = _pad_beam_tiles(ext, block_beams, interpret)
        return _sliding_median_call(padded, w, tb, interpret)[:k, :b]

    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_impl, interpret=False),
            functools.partial(_impl, interpret=True),
            ext,
        )
    return _impl(ext, interpret)


def _sorted_replace_kernel(w: int, s_ref, old_ref, new_ref, out_ref, med_ref):
    """One (Wp, TB) tile of the sorted window: delete old, insert new,
    emit the updated tile AND its lower median in one VMEM pass.

    Same multiset algebra as ops/filters.sorted_replace (delete/insert
    shift each element by at most one slot, so the result is a 3-way
    select over {left-neighbor, self, right-neighbor}) — but executed
    entirely in VMEM: the O(W) formulation loses to the bitonic network
    on TPU at W=64 ONLY because its ~6 small XLA ops each round-trip
    HBM; fused into one kernel the work is two (W, TB) streams and a
    handful of VPU passes.  Rows >= w are +inf padding: the delete slot
    d and insert slot p both land in [0, w), so pads never shift (see
    sorted_replace_pallas).
    """
    s = s_ref[:]                                           # (Wp, TB)
    old = old_ref[0, :]
    new = new_ref[0, :]
    wp = s.shape[0]
    iota = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    # first slot holding old (ties: any occurrence is the same value)
    d = jnp.min(jnp.where(s == old[None, :], iota, wp), axis=0)
    # insertion index in the W-1 multiset without old ("insert after
    # equals": stable, matches sorted_replace exactly)
    p = (
        jnp.sum((s < new[None, :]).astype(jnp.int32), axis=0)
        - (old < new).astype(jnp.int32)
    )
    left = jnp.concatenate([s[:1], s[:-1]], axis=0)        # left[i]=s[i-1]
    right = jnp.concatenate([s[1:], s[-1:]], axis=0)       # right[i]=s[i+1]
    d_, p_ = d[None, :], p[None, :]
    shift_l = (d_ < p_) & (iota >= d_) & (iota < p_)
    shift_r = (d_ > p_) & (iota > p_) & (iota <= d_)
    out = jnp.where(shift_l, right, jnp.where(shift_r, left, s))
    out = jnp.where(iota == p_, new[None, :], out)
    out_ref[:] = out
    # lower median of the finite values (pads are +inf: excluded by
    # isfinite, and pick < w keeps the selection inside the real rows)
    nvalid = jnp.sum(jnp.isfinite(out) & (iota < w), axis=0)
    med_ref[:] = _pick_lower_median(out, nvalid, w)[None, :]


@functools.partial(
    jax.jit, static_argnames=("w", "block_beams", "interpret")
)
def _sorted_replace_call(s, old, new, w, block_beams, interpret):
    wp, b = s.shape
    grid = (b // block_beams,)
    return pl.pallas_call(
        functools.partial(_sorted_replace_kernel, w),
        grid=grid,
        in_specs=[
            pl.BlockSpec(
                (wp, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=[
            pl.BlockSpec(
                (wp, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct((wp, b), jnp.float32),
            jax.ShapeDtypeStruct((1, b), jnp.float32),
        ],
        interpret=interpret,
    )(s, old, new)


def sorted_replace_pallas(
    sorted_w: jax.Array,
    old_v: jax.Array,
    new_v: jax.Array,
    *,
    block_beams: int = 512,
    interpret: bool | None = None,
) -> tuple[jax.Array, jax.Array]:
    """Fused multiset update + median of the per-beam sorted window.

    Drop-in for ``sorted_replace(...)`` followed by
    ``median_from_sorted(...)`` (ops/filters) — bit-exact (parity suite
    in tests/test_pallas_median.py) — with the whole update running in
    one VMEM pass per beam tile.  Same contract: ``sorted_w`` (W, B)
    ascending per column, ``old_v`` (B,) present in each column (exact
    float equality — guaranteed when it came from the same ring),
    +inf participates like any value.  Returns (updated (W, B), median
    (B,)).

    Row padding to the sublane multiple (and +inf beam-tile padding) is
    safe: pads sort to the tail, the delete slot is the FIRST
    occurrence of old (a real row whenever the contract holds — for
    old=+inf the sorted order puts a real +inf before the pads), and
    the insert slot p <= W-1 (p counts strictly-smaller survivors of a
    W-1 multiset), so no shift or insert ever touches a pad row.

    ``interpret=None`` (default) resolves per LOWERING platform
    (``_lowering_dispatch``): a config pinned to ``inc_pallas`` but
    traced for a CPU device on a TPU-default host still compiles.
    """
    w, b = sorted_w.shape
    s = sorted_w.astype(jnp.float32)
    # pad rows unconditionally (not just on hardware): the pad-row
    # algebra is the kernel's trickiest branch, and interpret-mode CI
    # must exercise the same code path TPU runs
    wp = ((w + 7) // 8) * 8
    if wp != w:
        s = jnp.pad(s, ((0, wp - w), (0, 0)), constant_values=jnp.inf)

    def _impl(s, old_v, new_v, interpret):
        s, tb = _pad_beam_tiles(s, block_beams, interpret)
        bp = s.shape[1]
        old = old_v.astype(jnp.float32)[None, :]
        new = new_v.astype(jnp.float32)[None, :]
        if bp != b:
            old = jnp.pad(old, ((0, 0), (0, bp - b)), constant_values=jnp.inf)
            new = jnp.pad(new, ((0, 0), (0, bp - b)), constant_values=jnp.inf)
        out, med = _sorted_replace_call(s, old, new, w, tb, interpret)
        return out[:w, :b], med[0, :b]

    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_impl, interpret=False),
            functools.partial(_impl, interpret=True),
            s, old_v, new_v,
        )
    return _impl(s, old_v, new_v, interpret)


def temporal_median_pallas(
    window: jax.Array,
    *,
    block_beams: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-beam lower median over the (W, B) ring — Pallas backend.

    Drop-in equivalent of :func:`ops.filters.temporal_median` (+inf marks
    missing returns / unfilled slots; all-inf beams stay +inf).  W is
    padded to the next power of two with +inf (sorts to the tail, does
    not shift the lower median); B is padded to the beam-tile multiple.

    ``interpret=None`` (default) resolves per LOWERING platform
    (``_lowering_dispatch``), so the same traced function is correct on
    a TPU target and a CPU target alike.
    """
    w, b = window.shape
    window = window.astype(jnp.float32)

    w_pad = _next_pow2(max(w, 2))
    if w_pad != w:
        window = jnp.pad(window, ((0, w_pad - w), (0, 0)), constant_values=jnp.inf)

    def _impl(window, interpret):
        padded, tb = _pad_beam_tiles(window, block_beams, interpret)
        return _median_call(padded, tb, interpret)[:b]

    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_impl, interpret=False),
            functools.partial(_impl, interpret=True),
            window,
        )
    return _impl(window, interpret)
