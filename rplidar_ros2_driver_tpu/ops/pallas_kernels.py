"""Pallas TPU kernels for the filter chain's hot ops.

The rolling temporal median is the chain's dominant cost (SURVEY.md §7
"hard parts": a W x B median per revolution).  The XLA path sorts the
whole (W, B) window in HBM via ``jnp.sort``; this kernel instead runs a
fully vectorized bitonic sorting network over the window axis inside
VMEM, tiled over beams, so each (W, TB) tile is read from HBM exactly
once and the median selection fuses with the sort.

The network is expressed with static reshapes + min/max only (no
gathers, no data-dependent control flow) so Mosaic vectorizes every
compare-exchange onto the VPU:

  * stage (k, j): rows viewed as (W/(2j), 2, j); partners (i, i^j) are
    the two slices of the middle axis; the ascending/descending
    direction depends only on the leading group index — a compile-time
    boolean vector.

On non-TPU backends the kernel runs in interpreter mode, which keeps CI
(CPU pytest) covering the exact kernel code path.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

_LANES = 128


def _next_pow2(n: int) -> int:
    p = 1
    while p < n:
        p *= 2
    return p


def _bitonic_sort_rows(x: jax.Array) -> jax.Array:
    """Ascending bitonic sort along axis 0 (static power-of-2 length)."""
    w = x.shape[0]
    assert w & (w - 1) == 0, "bitonic network needs power-of-2 rows"
    tail = x.shape[1:]
    k = 2
    while k <= w:
        j = k // 2
        while j >= 1:
            g = w // (2 * j)
            v = x.reshape((g, 2, j) + tail)
            a, b = v[:, 0], v[:, 1]
            lo = jnp.minimum(a, b)
            hi = jnp.maximum(a, b)
            # bit k of row index i = (i // (2j)) // (k // (2j)) & 1 —
            # constant per leading group; iota keeps it kernel-local
            # (pallas_call rejects captured host constants).
            gshape = (g, 1) + (1,) * len(tail)
            gidx = jax.lax.broadcasted_iota(jnp.int32, gshape, 0)
            asc = (gidx // max(k // (2 * j), 1)) % 2 == 0
            new_a = jnp.where(asc, lo, hi)
            new_b = jnp.where(asc, hi, lo)
            x = jnp.concatenate([new_a[:, None], new_b[:, None]], axis=1).reshape((w,) + tail)
            j //= 2
        k *= 2
    return x


def _median_kernel(win_ref, out_ref):
    """One (W, TB) tile: sort rows, pick the lower median of finite values."""
    win = win_ref[:]
    w = win.shape[0]
    nvalid = jnp.sum(jnp.isfinite(win), axis=0)                 # (TB,)
    s = _bitonic_sort_rows(win)                                 # inf sorts last
    pick = jnp.clip((nvalid - 1) // 2, 0, w - 1)                # (TB,)
    rows = jax.lax.broadcasted_iota(jnp.int32, s.shape, 0)
    med = jnp.sum(jnp.where(rows == pick[None, :], s, 0.0), axis=0)
    out_ref[:] = jnp.where(nvalid > 0, med, jnp.inf)[None, :]


@functools.partial(jax.jit, static_argnames=("block_beams", "interpret"))
def _median_call(window: jax.Array, block_beams: int, interpret: bool) -> jax.Array:
    w, b = window.shape
    grid = (b // block_beams,)
    return pl.pallas_call(
        _median_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((w, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM)
        ],
        # 2-D (1, TB) output blocks: a bare 1-D f32 output hits an XLA/Mosaic
        # tiled-layout mismatch (T(1024) vs T(512)) on v5e.
        out_specs=pl.BlockSpec((1, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((1, b), jnp.float32),
        interpret=interpret,
    )(window)[0]


def temporal_median_pallas(
    window: jax.Array,
    *,
    block_beams: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """Per-beam lower median over the (W, B) ring — Pallas backend.

    Drop-in equivalent of :func:`ops.filters.temporal_median` (+inf marks
    missing returns / unfilled slots; all-inf beams stay +inf).  W is
    padded to the next power of two with +inf (sorts to the tail, does
    not shift the lower median); B is padded to the beam-tile multiple.
    """
    if interpret is None:
        interpret = jax.default_backend() != "tpu"
    w, b = window.shape
    window = window.astype(jnp.float32)

    w_pad = _next_pow2(max(w, 2))
    if w_pad != w:
        window = jnp.pad(window, ((0, w_pad - w), (0, 0)), constant_values=jnp.inf)

    tb = min(block_beams, _next_pow2(max(b, _LANES)))
    tb = max(tb, _LANES) if not interpret else min(tb, max(b, 1))
    b_pad = ((b + tb - 1) // tb) * tb
    if b_pad != b:
        window = jnp.pad(window, ((0, 0), (0, b_pad - b)), constant_values=jnp.inf)

    out = _median_call(window, tb, interpret)
    return out[:b]
