"""Fused device-resident ingest: raw frame bytes -> filter output, ONE dispatch.

The host ingest path (driver/decode.py + driver/assembly.py + the chain's
packed upload) makes two device round-trips per capsule batch: the unpack
kernels run pinned to the CPU backend, NumPy materializes on the host, a
Python loop splits revolutions at sync positions, and the completed
revolution is re-packed and ``device_put`` into the filter step.  The
device-resident filter core sustains ~33k scans/s in-jit while the live
end-to-end path manages ~780 scans/s — the gap IS that host assembly
round-trip (the "caching-aware sweep reconstruction" bottleneck of
SR-LIO++, arXiv:2503.22926; the FPGA 2-D SLAM accelerator of
arXiv:2006.01050 fuses the same decode-to-map dataflow in hardware).

This module closes it in XLA: one jitted program per answer type runs

  1. **unpack** — the vectorized kernels of ops/unpack.py, NOT pinned to
     the CPU backend, with the prev-frame / sync-edge / smoothing carries
     threaded as device scalars (ops/unpack_ref.py stays the scalar golden
     model; driver/decode.py stays the host golden path);
  2. **validity compaction + revolution segmentation** — the flag-bit0
     sync split of driver/assembly.ScanAssembler.push_nodes.  Formulated
     WITHOUT element-wise scatters (XLA lowers those to a µs-per-element
     loop on CPU, and they are no better on TPU): frame validity is
     row-uniform in every wire format, so a stable 1-row-per-frame argsort
     compacts valid frames to the front, two ``dynamic_update_slice`` ops
     append the compacted nodes to the carried partial revolution in one
     contiguous buffer, ``searchsorted`` over the sync-bit cumsum finds
     each revolution's start offset, and each completed revolution is a
     single contiguous ``dynamic_slice`` — wrap/overflow-cap semantics
     identical to the assembler (data before the first sync dropped;
     ``max_nodes`` overflow cap, head-keep; completed segments beyond
     ``max_revs`` per batch dropped oldest-first, counted in
     ``revs_dropped``);
  3. **the filter step** — ``_filter_step_impl`` statically unrolled over
     the ``max_revs`` revolution slots, each gated by a ``lax.cond`` on
     ``slot < n_completed``, so a batch that completes no revolution
     takes every false branch and pays no filter compute, and the donated
     FilterState advances exactly one step per completed revolution (same
     trajectory as the host chain).

Node values are clamped exactly like the host wire pack
(ops/filters._pack_compact_rows: dist 18 bits, quality 8, flag 6) so the
filter sees bit-identical inputs on both paths; bit-exactness of the
whole bytes->revolution pipeline against BatchScanDecoder+ScanAssembler
is enforced by tests/test_fused_ingest.py.

Timestamps ride as float32 offsets from a PER-DISPATCH base (the
batch's first rx stamp, kept host-side in f64): each dispatch re-bases
the carried partial revolution's offsets by the base delta (one scalar
add over the partial plane), so on-device offsets stay bounded by the
span of one revolution — microsecond-exact in f32 — for arbitrarily
long sessions (a single session-epoch anchor would drift to ~ms ulp
after hours).  The host adds the base back after the fetch.  The
reference-exact per-sample back-dating (protocol/timing.py) is applied
in-kernel — delay(0) and the per-sample slope are compile-time
constants of the ingest config.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES, ScanBatch
from rplidar_ros2_driver_tpu.ops import deskew as deskewmod
from rplidar_ros2_driver_tpu.ops.deskew import RECON_EMPTY, DeskewConfig
from rplidar_ros2_driver_tpu.ops.scan_match import (
    MapConfig,
    MapState,
    _map_match_step_impl,
)
from rplidar_ros2_driver_tpu.ops.filters import (
    FilterConfig,
    FilterOutput,
    FilterState,
    _filter_step_impl,
    _pack_output_wire,
    unpack_output_wire,
    wire_output_len,
)
from rplidar_ros2_driver_tpu.driver.decode import _PAIRED_NODES
from rplidar_ros2_driver_tpu.protocol import timing as timingmod
from rplidar_ros2_driver_tpu.protocol.constants import ANS_PAYLOAD_BYTES, Ans

# nodes per decoded row (pair for the capsule formats, frame otherwise)
# and the paired-format set come from the canonical tables
# (protocol/timing.SAMPLES_PER_FRAME, driver/decode._PAIRED_NODES) —
# the fused geometry must never drift from the host golden path's
_NPTS = timingmod.SAMPLES_PER_FRAME
_PAIRED = frozenset(_PAIRED_NODES)


@dataclasses.dataclass(frozen=True)
class IngestConfig:
    """Static (compile-time) configuration of one fused ingest program."""

    ans_type: int
    frame_bytes: int
    npts: int
    paired: bool
    grouped: bool            # per-sample grouping delay applies (timing)
    sample_duration_us: int  # rounded, as the decode kernels take it
    delay0_us: int           # back-dating of sample 0 (protocol/timing.py)
    max_nodes: int           # revolution overflow cap (head-keep)
    max_revs: int            # completed revolutions per dispatch (newest win)
    emit_nodes: bool         # debug/parity: assembled node buffers returned
    filter: FilterConfig
    # per-revolution slot lowering: "auto" | "cond" | "fori" (bit-exact
    # either way — see _slot_impl_for; pinnable for A/B and parity tests)
    slot_impl: str = "auto"
    # fixed-point de-skew + sweep reconstruction (ops/deskew.py): None
    # keeps the core byte-identical to the pre-deskew program (no extra
    # state planes, no extra outputs)
    deskew: Optional[DeskewConfig] = None
    # in-program SLAM front-end (ops/scan_match.py): when set, every
    # tick's reconstructed sweep is matched against the stream's
    # log-odds map and the map updated INSIDE this program — the
    # MapState rides the ingest carry, so bytes -> decode -> de-skewed
    # sweep -> pose -> map update is one dispatch (and one scan carry
    # through the super-tick).  Requires ``deskew`` — the reconstructed
    # sweep IS the mapper feed.
    mapping: Optional[MapConfig] = None

    def __post_init__(self):
        _check_mapping_geometry(self.mapping, self.deskew)


def _check_mapping_geometry(mapping, deskew) -> None:
    """Shared ingest-config invariant: the in-program mapper consumes
    the reconstructed sweep, so it needs the de-skew/reconstruction
    stage AND the same beam grid the sweep is rasterized on."""
    if mapping is None:
        return
    if deskew is None:
        raise ValueError(
            "ingest mapping requires the de-skew/reconstruction stage "
            "(cfg.deskew): the reconstructed sweep is the mapper feed"
        )
    if mapping.beams != deskew.recon_beams:
        raise ValueError(
            f"ingest mapping beam grid ({mapping.beams}) must equal the "
            f"reconstruction beam grid ({deskew.recon_beams})"
        )


def ingest_config_for(
    ans_type: int,
    timing: timingmod.TimingDesc,
    filter_cfg: FilterConfig,
    *,
    max_nodes: int = MAX_SCAN_NODES,
    max_revs: int = 2,
    emit_nodes: bool = False,
    slot_impl: str = "auto",
    deskew: Optional[DeskewConfig] = None,
    mapping: Optional[MapConfig] = None,
) -> IngestConfig:
    """Build the static config for one (answer type, timing desc, chain)."""
    at = Ans(ans_type)
    return IngestConfig(
        ans_type=int(at),
        frame_bytes=ANS_PAYLOAD_BYTES[at],
        npts=_NPTS[at],
        paired=at in _PAIRED,
        grouped=at in timingmod._GROUPED_FORMATS,
        sample_duration_us=timing.sample_duration_int_us,
        delay0_us=timingmod.sample_delay_us(at, timing, 0),
        max_nodes=max_nodes,
        max_revs=max_revs,
        emit_nodes=emit_nodes,
        filter=filter_cfg,
        slot_impl=slot_impl,
        deskew=deskew,
        mapping=mapping,
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class IngestState:
    """Device-resident streaming state threaded through the fused step."""

    filter: FilterState
    partial: jax.Array        # (max_nodes, 4) int32 current partial revolution
    partial_ts: jax.Array     # (max_nodes,) f32 offsets from the LAST base
    partial_len: jax.Array    # int32 (capped at max_nodes, like the assembler)
    seen_sync: jax.Array      # bool — data before the first sync is dropped
    sync_carry: jax.Array     # int32 — dense/ultra-dense edge-filter carry
    dist_carry: jax.Array     # int32 — ultra-dense smoothing carry
    prev_frame: jax.Array     # (frame_bytes,) uint8 — paired-format prev
    have_prev: jax.Array      # bool
    scans_completed: jax.Array  # int32, cumulative
    revs_dropped: jax.Array     # int32, cumulative (max_revs overflow drops)
    # de-skew + sweep-reconstruction planes (cfg.deskew; None otherwise —
    # a None pytree leaf is an empty subtree, so the state structure
    # stays jit/donation-stable per compiled config, like FilterState's
    # median_sorted).  See ops/deskew.py.
    recon_ring: Optional[jax.Array] = None    # (K, B) int32 sub-sweep ring
    recon_pos: Optional[jax.Array] = None     # int32 cumulative push count
    deskew_prof: Optional[jax.Array] = None   # (D,) int32 prev-rev profile
    deskew_motion: Optional[jax.Array] = None  # (3,) int32 [dx,dy,dθ_q16]
    # in-program SLAM front-end planes (cfg.mapping; None otherwise) —
    # the MapState of ops/scan_match.py flattened into the ingest carry
    # so the map update rides the same donated scan state the decode
    # carries do (key names mirror MapState's fields behind the "map_"
    # prefix: the per-stream snapshot transport rekeys them 1:1)
    map_log_odds: Optional[jax.Array] = None   # (G, G) int32 Q10
    map_pose: Optional[jax.Array] = None       # (3,) int32 [tx, ty, θidx]
    map_origin_xy: Optional[jax.Array] = None  # (2,) float32
    map_revision: Optional[jax.Array] = None   # () int32


def create_ingest_state(
    cfg: IngestConfig, filter_state: Optional[FilterState] = None
) -> IngestState:
    """Fresh stream state; ``filter_state`` carries the rolling window
    across scan-mode switches (the host path's chain survives an answer-
    type change too — only decode/assembly state resets)."""
    dsk = cfg.deskew
    return IngestState(
        filter=filter_state
        if filter_state is not None
        else FilterState.for_config(cfg.filter),
        partial=jnp.zeros((cfg.max_nodes, 4), jnp.int32),
        partial_ts=jnp.zeros((cfg.max_nodes,), jnp.float32),
        partial_len=jnp.asarray(0, jnp.int32),
        seen_sync=jnp.asarray(False),
        sync_carry=jnp.asarray(0, jnp.int32),
        dist_carry=jnp.asarray(0, jnp.int32),
        prev_frame=jnp.zeros((cfg.frame_bytes,), jnp.uint8),
        have_prev=jnp.asarray(False),
        scans_completed=jnp.asarray(0, jnp.int32),
        revs_dropped=jnp.asarray(0, jnp.int32),
        # RECON_EMPTY (not zero) marks fresh ring/profile planes: a
        # zero cell would decode as a live dist-0 return
        recon_ring=(
            jnp.full(
                (dsk.recon_window, dsk.recon_beams), RECON_EMPTY, jnp.int32
            ) if dsk is not None else None
        ),
        recon_pos=jnp.asarray(0, jnp.int32) if dsk is not None else None,
        deskew_prof=(
            jnp.full((dsk.profile_beams,), RECON_EMPTY, jnp.int32)
            if dsk is not None else None
        ),
        deskew_motion=(
            jnp.zeros((3,), jnp.int32) if dsk is not None else None
        ),
        **_fresh_map_leaves(cfg.mapping),
    )


def _fresh_map_leaves(mcfg: Optional[MapConfig], streams: int = 0) -> dict:
    """Fresh in-carry MapState leaves (MapState.create's exact values —
    all zeros), stream-batched when ``streams`` > 0; all-None when the
    in-program mapper is off (the state structure stays jit/donation-
    stable per compiled config, like the de-skew planes)."""
    if mcfg is None:
        return dict(
            map_log_odds=None, map_pose=None,
            map_origin_xy=None, map_revision=None,
        )
    lead = (streams,) if streams else ()
    return dict(
        map_log_odds=jnp.zeros(lead + (mcfg.grid, mcfg.grid), jnp.int32),
        map_pose=jnp.zeros(lead + (3,), jnp.int32),
        map_origin_xy=jnp.zeros(lead + (2,), jnp.float32),
        map_revision=jnp.zeros(lead, jnp.int32),
    )


# ---------------------------------------------------------------------------
# result layout (one small meta fetch per dispatched batch; the per-slot
# filter-output wires ride as a separate (max_revs, wire_output_len) array
# that the host only touches when meta says revolutions completed)
# ---------------------------------------------------------------------------
#
#   meta (float32, _META + 3*max_revs [+ _DESKEW_META]):
#     [0] n_completed  [1] revs_dropped_this_step  [2] syncs_in_batch
#     [3] nodes_appended
#     [4 : 4+R]        per-slot node counts          (R = max_revs)
#     [.. : ..+R]      per-slot ts0 epoch offsets
#     [.. : ..+R]      per-slot end_ts epoch offsets
#     (cfg.deskew only, appended) [recon_pushed, recon_valid_beams,
#       motion_dx_q2, motion_dy_q2, motion_dθ_q16]
#   out_wires: (R, wire_output_len(filter)) float32
#   (cfg.deskew only) recon_plane (B,) int32 + recon_pts (B, 3) f32
#   (cfg.mapping only) map_wire (7,) int32:
#     [live, tx_sub, ty_sub, theta_idx, score, n_valid, revision]
#   (emit_nodes only) nodes (R, max_nodes, 4) f32 + node_ts (R, max_nodes)

_META = 4
_DESKEW_META = 5


def ingest_meta_len(cfg: IngestConfig) -> int:
    return (
        _META + 3 * cfg.max_revs
        + (_DESKEW_META if cfg.deskew is not None else 0)
    )


@dataclasses.dataclass
class IngestBatchResult:
    """Host-side parse of one fused-step result."""

    n_completed: int
    revs_dropped: int
    syncs: int
    nodes_appended: int
    counts: np.ndarray          # (n_completed,)
    ts0: np.ndarray             # (n_completed,) epoch offsets (float32)
    end_ts: np.ndarray          # (n_completed,)
    outputs: list               # n_completed FilterOutput (numpy-backed)
    nodes: Optional[np.ndarray] = None      # (n_completed, max_nodes, 4)
    node_ts: Optional[np.ndarray] = None    # (n_completed, max_nodes)
    # de-skew + reconstruction surface (cfg.deskew only)
    recon_plane: Optional[np.ndarray] = None  # (B,) int32 packed sweep
    recon_pts: Optional[np.ndarray] = None    # (B, 3) f32 [x, y, mask]
    recon_pushed: bool = False       # this dispatch appended a sub-sweep
    recon_valid: int = 0             # beams carrying a return in the sweep
    deskew_motion: Optional[np.ndarray] = None  # (3,) int32 estimate
    # in-program mapping surface (cfg.mapping only): the tick's map
    # wire [live, tx_sub, ty_sub, theta_idx, score, n_valid, revision]
    map_wire: Optional[np.ndarray] = None  # (7,) int32


def unpack_ingest_result(res, cfg: IngestConfig) -> IngestBatchResult:
    """Host-side parse of the fused step's returned arrays (everything
    after the advanced state): ``(meta, out_wires[, nodes, node_ts])``.

    Only ``meta`` (a handful of floats) is always materialized; the
    per-slot output wires are touched exclusively for slots that actually
    completed, so a mid-revolution batch costs one tiny fetch.
    """
    meta = np.asarray(res[0])
    if meta.size != ingest_meta_len(cfg):
        raise ValueError(
            f"ingest meta of {meta.size} floats does not match cfg "
            f"(expected {ingest_meta_len(cfg)})"
        )
    r = cfg.max_revs
    n = int(meta[0])
    off = _META
    # graftlint: policed — slot counts ride the f32 meta plane by wire
    # contract: small non-negative ints (<= max_nodes), exact in f32
    counts = meta[off : off + r].astype(np.int32)
    ts0 = meta[off + r : off + 2 * r].copy()
    end_ts = meta[off + 2 * r : off + 3 * r].copy()
    outputs = []
    if n > 0:
        w = np.asarray(res[1])
        outputs = [unpack_output_wire(w[k], cfg.filter) for k in range(n)]
    idx = 2
    recon_plane = recon_pts = motion = None
    recon_pushed = False
    recon_valid = 0
    if cfg.deskew is not None:
        doff = _META + 3 * r
        recon_pushed = bool(meta[doff] > 0.5)
        recon_valid = int(meta[doff + 1])
        # graftlint: policed — deskew meta rides the f32 plane by wire
        # contract: pushed flag, beam count (<= recon_beams) and the
        # clamped motion components (|dx|,|dy| <= 2^11, |dθ| <= 2^13)
        # are all exact in f32
        motion = meta[doff + 2 : doff + 5].astype(np.int32)
        recon_plane = np.asarray(res[idx])
        recon_pts = np.asarray(res[idx + 1])
        idx += 2
    map_wire = None
    if cfg.mapping is not None:
        map_wire = np.asarray(res[idx])
        idx += 1
    nodes = node_ts = None
    if cfg.emit_nodes:
        # graftlint: policed — debug node planes ride f32 by wire
        # contract; the widest field (18-bit clamped dist) is exact
        nodes = np.asarray(res[idx]).astype(np.int32)[:n]
        node_ts = np.asarray(res[idx + 1])[:n]
    return IngestBatchResult(
        n_completed=n,
        revs_dropped=int(meta[1]),
        syncs=int(meta[2]),
        nodes_appended=int(meta[3]),
        counts=counts[:n],
        ts0=ts0[:n],
        end_ts=end_ts[:n],
        outputs=outputs,
        nodes=nodes,
        node_ts=node_ts,
        recon_plane=recon_plane,
        recon_pts=recon_pts,
        recon_pushed=recon_pushed,
        recon_valid=recon_valid,
        deskew_motion=motion,
        map_wire=map_wire,
    )


# ---------------------------------------------------------------------------
# the fused step
# ---------------------------------------------------------------------------


def _decode(cfg: IngestConfig, state: IngestState, frames, crc_ok):
    """Dispatch to the right ops/unpack.py kernel, prev frame prepended for
    the paired formats and the edge/smoothing carries threaded as traced
    device scalars (driver/decode.py threads the same carries as host ints).

    LOCKSTEP NOTE: the fleet lowering's :func:`_fleet_branch` carries this
    same decode+carry logic at fleet input geometry (guarded for m==0
    lanes, padded to the common sample width) — a semantic change here
    must land there too; both are pinned bit-exact against the host
    golden path by their parity suites."""
    from rplidar_ros2_driver_tpu.ops import unpack

    at = cfg.ans_type
    if at == Ans.MEASUREMENT:
        return unpack.unpack_normal_nodes(frames)
    if at == Ans.MEASUREMENT_HQ:
        return unpack.unpack_hq_capsules(frames, crc_ok)
    fr = jnp.concatenate([state.prev_frame[None, :], frames], axis=0)
    if at == Ans.MEASUREMENT_CAPSULED:
        return unpack.unpack_capsules(fr)
    if at == Ans.MEASUREMENT_CAPSULED_ULTRA:
        return unpack.unpack_ultra_capsules(fr)
    if at == Ans.MEASUREMENT_DENSE_CAPSULED:
        return unpack.unpack_dense_capsules(
            fr, state.sync_carry, sample_duration_us=cfg.sample_duration_us
        )
    if at == Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED:
        return unpack.unpack_ultra_dense_capsules(
            fr, state.sync_carry, state.dist_carry,
            sample_duration_us=cfg.sample_duration_us,
        )
    raise ValueError(f"unsupported ans type {at:#x}")


def _slot_impl_for(cfg: IngestConfig) -> str:
    """Static choice of the per-revolution slot lowering (both are
    bit-identical in output; the choice only moves XLA:CPU carry-copy
    cost for skipped slots).  ``cond`` executes only the taken branch but
    copies the FilterState through every conditional — right when the
    state is small.  ``fori`` aliases its while-loop carries in place, so
    skipped slots are free even with a multi-MB state, at the price of a
    slightly less fusible loop body.  The crossover sits around a few
    hundred KB of carried state; below we approximate the state footprint
    by its dominant planes (median window + voxel accumulator)."""
    if cfg.slot_impl != "auto":
        return cfg.slot_impl
    f = cfg.filter
    state_elems = f.window * f.beams * 3 + f.grid * f.grid
    return "cond" if state_elems <= (1 << 18) else "fori"


def _wire_clamp(angle, dist, quality, flag):
    """The host wire pack's exact clamps (ops/filters._pack_compact_rows:
    dist saturates at 18 bits — a 'negative' int32 bit pattern from the HQ
    u32 field saturates too, matching the uint32 host math — quality masks
    to 8 bits, flag to 6, angle to u16), applied pre-segmentation so the
    filter sees bit-identical node values on both ingest backends."""
    angle = angle & 0xFFFF
    dist = jnp.where(dist < 0, 0x3FFFF, jnp.minimum(dist, 0x3FFFF))
    quality = quality & 0xFF
    flag = flag & 0x3F
    return angle, dist, quality, flag


class _CoreResult(NamedTuple):
    """What the shared segmentation/filter tail hands back to its caller
    (the single-stream step or one fleet lane): the advanced stream-state
    planes, the per-dispatch counters, and the result arrays."""

    filter: object            # advanced FilterState
    partial: jax.Array
    partial_ts: jax.Array
    partial_len: jax.Array
    seen_sync: jax.Array
    n_completed: jax.Array
    drop_head: jax.Array
    meta: jax.Array
    out_wires: jax.Array
    nodes: Optional[jax.Array]
    node_ts: Optional[jax.Array]
    # de-skew + reconstruction (cfg.deskew only; None otherwise)
    recon_ring: Optional[jax.Array] = None
    recon_pos: Optional[jax.Array] = None
    deskew_prof: Optional[jax.Array] = None
    deskew_motion: Optional[jax.Array] = None
    recon_plane: Optional[jax.Array] = None
    recon_pts: Optional[jax.Array] = None
    recon_pushed: Optional[jax.Array] = None  # bool — sub-sweep appended


def _segment_filter_core(cfg, state, batch4, ts_c, nv, base_shift) -> _CoreResult:
    """The shared tail of the fused ingest step: append the compacted
    node stream to the carried partial revolution, segment at the sync
    bits, and run the donated per-revolution filter slots.

    ``batch4``/``ts_c`` are the validity-compacted (n, 4)/(n,) node
    stream (valid nodes first, original order preserved — the callers'
    stable sorts guarantee it) and ``nv`` the live node count.  Both the
    single-stream step (row-compacted — validity is row-uniform in every
    wire format) and the fleet lowering (node-compacted — the fleet's
    common sample width pads narrower formats with dead columns) reduce
    to this one formulation, so bytes->revolution bit-exactness against
    the host assembler is pinned in exactly one place.

    ``cfg`` needs only the shared fields (max_nodes/max_revs/filter/
    slot_impl/emit_nodes): IngestConfig and FleetIngestConfig both
    satisfy it.
    """
    mn = cfg.max_nodes
    n = batch4.shape[0]

    # -- append to the carried partial: one contiguous stream buffer,
    # allocated ONCE at (2*mn + n): [0, mn) the carried partial zone, the
    # batch appended at partial_len, and a trailing mn of zeros so every
    # fixed-length revolution slice below stays in bounds (a concat-pad
    # here cost two full-buffer copies per dispatch on the CPU backend)
    z0 = jnp.asarray(0, jnp.int32)
    full4 = jnp.zeros((2 * mn + n, 4), jnp.int32)
    full4 = jax.lax.dynamic_update_slice(full4, state.partial, (z0, z0))
    full4 = jax.lax.dynamic_update_slice(full4, batch4, (state.partial_len, z0))
    fullts = jnp.zeros((2 * mn + n,), jnp.float32)
    # the carried offsets were relative to the PREVIOUS dispatch's base:
    # one scalar add re-bases them, so on-device stamps stay bounded by
    # one revolution's span for arbitrarily long sessions (dead lanes
    # pick up base_shift too, but every consumer below masks by count)
    fullts = jax.lax.dynamic_update_slice(
        fullts, state.partial_ts + base_shift, (z0,)
    )
    fullts = jax.lax.dynamic_update_slice(fullts, ts_c, (state.partial_len,))
    total = state.partial_len + nv  # live stream length in full4/fullts
    flag_c = batch4[:, 3]

    # -- de-skew + sweep reconstruction (cfg.deskew, ops/deskew.py):
    # the tick's freshly appended nodes — de-skewed by their phase
    # fraction with the CARRIED motion estimate — become one sub-sweep
    # segment pushed into the device-resident ring, and the ring's
    # newest-wins overlay is the reconstructed sweep emitted EVERY tick
    # (cached segments reused across overlapping windows, never
    # recomputed).  Deliberately decoupled from the revolution
    # bookkeeping below: every valid node of the tick enters the cache,
    # pre-first-sync and overflow-capped data included — it is real
    # measurement data and the host twin sees the identical stream.
    dsk = cfg.deskew
    recon_ring = state.recon_ring
    recon_pos = state.recon_pos
    recon_plane = recon_pts = recon_pushed = None
    if dsk is not None:
        jb = jnp.arange(n, dtype=jnp.int32)
        live_b = jb < nv
        a_ds, d_ds = deskewmod.apply_deskew(
            batch4[:, 0], batch4[:, 1], live_b, state.deskew_motion, dsk
        )
        seg = deskewmod.rasterize_subsweep(
            a_ds, d_ds, batch4[:, 2], live_b, dsk
        )
        recon_pushed = nv > 0
        recon_ring, recon_pos = deskewmod.push_ring(
            state.recon_ring, state.recon_pos, seg, recon_pushed
        )
        recon_plane = deskewmod.combine_ring(recon_ring, recon_pos)
        _rr, rxy, rmask = deskewmod.recon_points(recon_plane)
        recon_pts = jnp.concatenate(
            [rxy, rmask.astype(jnp.float32)[:, None]], axis=1
        )

    # -- revolution segmentation: sync-bit cumsum + searchsorted starts --
    j = jnp.arange(n, dtype=jnp.int32)
    s_c = (j < nv) & ((flag_c & 1) == 1)
    psum = jnp.cumsum(s_c.astype(jnp.int32))  # syncs at-or-before node j
    syncs = psum[-1]

    seen = state.seen_sync
    k0 = jnp.where(seen, 0, 1)           # first completable segment id
    n_completed_raw = jnp.maximum(syncs - k0, 0)
    drop_head = jnp.maximum(n_completed_raw - cfg.max_revs, 0)
    n_completed = jnp.minimum(n_completed_raw, cfg.max_revs)

    # segment q's start offset in the stream buffer: position of the q-th
    # sync (which OPENS segment q); segment 0 starts at the stream head
    q = k0 + drop_head + jnp.arange(cfg.max_revs + 1, dtype=jnp.int32)
    qs = jnp.concatenate([q, syncs[None]])
    jq = jnp.searchsorted(psum, qs, side="left").astype(jnp.int32)
    starts = jnp.where(qs == 0, 0, state.partial_len + jq)
    seg_start = starts[: cfg.max_revs + 1]   # slots 0..R-1 (+1 for ends)
    open_start = starts[-1]                  # the still-open segment

    slot = jnp.arange(cfg.max_revs, dtype=jnp.int32)
    live_slot = slot < n_completed
    counts = jnp.where(
        live_slot, jnp.minimum(seg_start[1:] - seg_start[:-1], mn), 0
    )
    # ts0 = first node of the slot (0.0 for an empty revolution, matching
    # an untouched buffer); end_ts = the sync node CLOSING the slot — the
    # opening node of the next segment (assembler: _close_partial stamp)
    ts0 = jnp.where(counts > 0, fullts[seg_start[: cfg.max_revs]], 0.0)
    end_ts = jnp.where(live_slot, fullts[seg_start[1:]], 0.0)

    # -- the carried partial: the open segment's head (max_nodes cap) --
    keep_p = seen | (syncs > 0)          # pre-first-sync data is dropped
    cnt_p = jnp.where(keep_p, jnp.minimum(total - open_start, mn), 0)
    new_partial = jax.lax.dynamic_slice(full4, (open_start, z0), (mn, 4))
    new_partial_ts = jax.lax.dynamic_slice(fullts, (open_start,), (mn,))
    pmask = jnp.arange(mn, dtype=jnp.int32) < cnt_p
    new_partial = jnp.where(pmask[:, None], new_partial, 0)
    new_partial_ts = jnp.where(pmask, new_partial_ts, 0.0)

    # nodes that actually landed (stat parity with the host decoder):
    # valid, within the head-keep cap, in a kept segment
    last_sync_j = jax.lax.associative_scan(
        jnp.maximum, jnp.where(s_c, j, -1)
    )
    seg_begin_j = jnp.where(last_sync_j >= 0, state.partial_len + last_sync_j, 0)
    pos_j = state.partial_len + j - seg_begin_j
    rel = psum - k0 - drop_head
    kept = jnp.where(
        psum == syncs, keep_p, (rel >= 0) & (rel < n_completed)
    )
    nodes_appended = jnp.sum(
        ((j < nv) & (pos_j < mn) & kept).astype(jnp.int32)
    )

    # -- the filter: one donated step per completed revolution slot.
    # Two lowerings, picked statically per filter geometry (see
    # _slot_impl_for): cond-unrolled slots vs a fori_loop with traced
    # trip count.  Identical math either way — the choice only moves
    # where XLA:CPU pays carry copies for the skipped-slot case.
    fcfg = cfg.filter
    live_iota = jnp.arange(mn, dtype=jnp.int32)
    wire_len = wire_output_len(fcfg)

    def _slot_nodes(begin, cnt):
        nodes_r = jax.lax.dynamic_slice(full4, (begin, z0), (mn, 4))
        nts_r = jax.lax.dynamic_slice(fullts, (begin,), (mn,))
        lv = live_iota < cnt
        # zero the dead lanes: the host packed upload is zero-padded past
        # count, so bit-exactness requires the same dead-lane values
        return jnp.where(lv[:, None], nodes_r, 0), jnp.where(lv, nts_r, 0.0), lv

    def _slot_filter(carry, nodes_r, lv, cnt):
        """The shared slot tail: (optional) per-revolution de-skew, then
        the donated filter step.  ``carry`` is (FilterState, prof,
        motion) — the de-skew planes thread through the slot loop so a
        dispatch completing several revolutions estimates each one
        against its true predecessor (prof/motion are None-leaves when
        cfg.deskew is None and cost nothing)."""
        fstate, prof, motion = carry
        angle_r, dist_r = nodes_r[:, 0], nodes_r[:, 1]
        if dsk is not None:
            # per-revolution range-only de-skew: profile this revolution
            # RAW, estimate the rigid motion against the carried
            # predecessor profile, re-project every node to the
            # revolution's end pose by its phase fraction — the filter
            # consumes the corrected nodes on BOTH backends
            prof_r = deskewmod.profile_from_nodes(angle_r, dist_r, lv, dsk)
            motion = deskewmod.estimate_motion(prof, prof_r, dsk)
            prof = prof_r
            angle_r, dist_r = deskewmod.apply_deskew(
                angle_r, dist_r, lv, motion, dsk
            )
        batch = ScanBatch(
            angle_q14=angle_r,
            dist_q2=dist_r,
            quality=nodes_r[:, 2],
            flag=nodes_r[:, 3],
            valid=lv,
            count=cnt,
        )
        fstate, out = _filter_step_impl(fstate, batch, fcfg)
        return (fstate, prof, motion), _pack_output_wire(out)

    def _slot_step(r, carry):
        cnt = counts[r]
        nodes_r, _, lv = _slot_nodes(seg_start[r], cnt)
        return _slot_filter(carry, nodes_r, lv, cnt)

    def _slot_skip(carry):
        return carry, jnp.zeros((wire_len,), jnp.float32)

    carry0 = (state.filter, state.deskew_prof, state.deskew_motion)
    if _slot_impl_for(cfg) == "cond":
        # small filter state: per-slot lax.cond — only the taken branch
        # executes, the pass-through copy of the small state is cheap,
        # and a live slot runs the step inline with a static slot index
        # (NOTE: under vmap — the fleet lowering — a batched predicate
        # lowers to select-of-both-branches, so the fleet default is
        # "fori"; cond stays available for parity pinning)
        carry = carry0
        wire_rows = []
        for r in range(cfg.max_revs):
            carry, w = jax.lax.cond(
                r < n_completed,
                functools.partial(_slot_step, r),
                _slot_skip,
                carry,
            )
            wire_rows.append(w)
        out_wires = jnp.stack(wire_rows)
    else:
        # large filter state: fori_loop with traced trip count — XLA:CPU
        # aliases while-loop carries in place, so a zero-trip batch skips
        # the filter without round-tripping the multi-MB FilterState
        # (conditionals copy their carried operands per branch on CPU,
        # which measured ~3 ms/dispatch at the DenseBoost-64 geometry)
        def _slot_step_dyn(r, carry):
            cnt = jax.lax.dynamic_index_in_dim(counts, r, 0, keepdims=False)
            begin = jax.lax.dynamic_index_in_dim(
                seg_start, r, 0, keepdims=False
            )
            nodes_r, _, lv = _slot_nodes(begin, cnt)
            return _slot_filter(carry, nodes_r, lv, cnt)

        def _loop_body(r, loop_carry):
            carry, wires = loop_carry
            carry, w = _slot_step_dyn(r, carry)
            return carry, jax.lax.dynamic_update_index_in_dim(wires, w, r, 0)

        carry, out_wires = jax.lax.fori_loop(
            0,
            n_completed,
            _loop_body,
            (carry0, jnp.zeros((cfg.max_revs, wire_len), jnp.float32)),
        )
    fstate, new_prof, new_motion = carry

    meta = jnp.concatenate([
        jnp.stack([
            n_completed, drop_head, syncs, nodes_appended
        ]).astype(jnp.float32),
        counts.astype(jnp.float32),
        ts0,
        end_ts,
    ])
    if dsk is not None:
        recon_valid = jnp.sum(
            (recon_plane != RECON_EMPTY).astype(jnp.int32)
        )
        # graftlint: policed — deskew meta rides the f32 plane by wire
        # contract: pushed flag, beam count (<= recon_beams) and the
        # clamped motion components (|dx|,|dy| <= 2^11, |dθ| <= 2^13)
        # are small exact ints in f32
        meta = jnp.concatenate([
            meta,
            jnp.stack([
                recon_pushed.astype(jnp.int32), recon_valid,
                new_motion[0], new_motion[1], new_motion[2],
            ]).astype(jnp.float32),
        ])

    nodes_arr = ts_arr = None
    if cfg.emit_nodes:
        # debug/parity surface: the assembled node buffers per completed
        # slot (static unroll — max_revs slices of the stream buffer)
        node_rows, ts_rows = [], []
        for r in range(cfg.max_revs):
            nodes_r, nts_r, _ = _slot_nodes(seg_start[r], counts[r])
            node_rows.append(nodes_r)
            ts_rows.append(nts_r)
        nodes_arr = jnp.stack(node_rows).astype(jnp.float32)
        ts_arr = jnp.stack(ts_rows)

    return _CoreResult(
        filter=fstate,
        partial=new_partial,
        partial_ts=new_partial_ts,
        partial_len=cnt_p,
        seen_sync=seen | (syncs > 0),
        n_completed=n_completed,
        drop_head=drop_head,
        meta=meta,
        out_wires=out_wires,
        nodes=nodes_arr,
        node_ts=ts_arr,
        recon_ring=recon_ring,
        recon_pos=recon_pos,
        deskew_prof=new_prof,
        deskew_motion=new_motion,
        recon_plane=recon_plane,
        recon_pts=recon_pts,
        recon_pushed=recon_pushed,
    )


def _map_update_tick(cfg, state: IngestState, core: _CoreResult):
    """The in-program SLAM front-end tick (cfg.mapping): match the
    tick's reconstructed sweep against the stream's in-carry map and
    absorb it — ops/scan_match._map_match_step_impl, the SAME step the
    host-route FleetMapper dispatches separately, gated on this tick
    actually pushing a sub-sweep (``live = recon_pushed``, exactly the
    freshness contract of FleetFusedIngest.take_recon: an idle tick's
    map and pose pass through untouched, so the fused and host mapping
    routes land byte-identical MapState trajectories).  The Cartesian
    endpoints are ``core.recon_pts`` — the very planes the host route
    fetches and feeds back, decoded by the same jitted helpers — so the
    one f32 quantizing multiply downstream sees identical inputs on
    both routes.

    Returns the advanced MapState and the (7,) int32 map wire
    ``[live, tx_sub, ty_sub, theta_idx, score, n_valid, revision]``.
    """
    mstate = MapState(
        log_odds=state.map_log_odds,
        pose=state.map_pose,
        origin_xy=state.map_origin_xy,
        revision=state.map_revision,
    )
    live = core.recon_pushed.astype(jnp.int32)
    pts = core.recon_pts
    mstate, wire5 = _map_match_step_impl(
        mstate, pts[:, :2], pts[:, 2] > 0.5, live, cfg.mapping
    )
    map_wire = jnp.concatenate([
        live[None], wire5, mstate.revision[None]
    ]).astype(jnp.int32)
    return mstate, map_wire


def _map_state_leaves(mstate: Optional[MapState]) -> dict:
    """MapState -> the flat ``map_*`` IngestState leaves (all-None when
    the in-program mapper is off)."""
    if mstate is None:
        return dict(
            map_log_odds=None, map_pose=None,
            map_origin_xy=None, map_revision=None,
        )
    return dict(
        map_log_odds=mstate.log_odds,
        map_pose=mstate.pose,
        map_origin_xy=mstate.origin_xy,
        map_revision=mstate.revision,
    )


def _core_outputs(cfg, core: _CoreResult, map_wire=None) -> tuple:
    """The one result-arity rule, shared by the single-stream step and
    every fleet lane: ``(meta, out_wires[, recon_plane, recon_pts]
    [, map_wire][, nodes, node_ts])`` — reconstruction planes appear
    iff ``cfg.deskew``, the map wire iff ``cfg.mapping``, the debug
    node surface iff ``cfg.emit_nodes``.  The unpackers invert this
    ordering; keep them in lockstep."""
    out = [core.meta, core.out_wires]
    if cfg.deskew is not None:
        out += [core.recon_plane, core.recon_pts]
    if cfg.mapping is not None:
        out += [map_wire]
    if cfg.emit_nodes:
        out += [core.nodes, core.node_ts]
    return tuple(out)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def fused_ingest_step(
    state: IngestState, frames: jax.Array, aux: jax.Array, cfg: IngestConfig
) -> tuple:
    """One frame batch through unpack -> segment -> filter, in one program.

    ``frames`` is (M, frame_bytes) uint8, zero-padded past the live count;
    ``aux`` is (2M+2,) float32: per-frame rx offsets from THIS batch's
    base stamp, per-frame CRC verdicts (HQ only; CRC32 runs on the host
    like the host path), the previous base minus this base (the re-base
    shift applied to the carried partial's offsets), and the live frame
    count in the last slot.  Returns
    ``(state, meta, out_wires[, nodes, node_ts])`` — see the result-layout
    note above.
    """
    mb = frames.shape[0]
    rx = aux[:mb]
    crc_ok = aux[mb : 2 * mb] > 0.5
    base_shift = aux[-2]
    # graftlint: policed — the live frame count rides the f32 aux plane
    # by wire contract: a small non-negative int, exact in f32
    m = aux[-1].astype(jnp.int32)

    dec = _decode(cfg, state, frames, crc_ok)
    npts = cfg.npts
    rows = jnp.arange(mb, dtype=jnp.int32)
    if cfg.paired:
        # pair i = (fr[i], fr[i+1]) with the prev frame at fr[0]: a zeroed
        # prev fails the checksum, but the explicit mask also covers it
        row_live = (rows < m) & (state.have_prev | (rows > 0))
    else:
        row_live = rows < m

    angle = jnp.asarray(dec.angle_q14)[:mb]
    dist = jnp.asarray(dec.dist_q2)[:mb]
    quality = jnp.asarray(dec.quality)[:mb]
    flag = jnp.asarray(dec.flag)[:mb]
    # frame validity is row-uniform in every wire format (checksum / CRC /
    # sync-nibble verdicts apply to whole frames) — the row mask is the
    # whole story, which is what makes row-level compaction exact
    valid_row = jnp.asarray(dec.node_valid)[:mb, 0] & row_live

    # -- carries for the next batch (driver/decode.py:249-258 semantics) --
    new_sync_carry = state.sync_carry
    new_dist_carry = state.dist_carry
    if cfg.ans_type in (
        Ans.MEASUREMENT_DENSE_CAPSULED, Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED
    ):
        last_row_flag = jax.lax.dynamic_index_in_dim(
            flag, jnp.maximum(m - 1, 0), 0, keepdims=False
        )
        new_sync_carry = jnp.where(
            m > 0, last_row_flag[-1] & 1, state.sync_carry
        )
    if cfg.ans_type == Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED:
        d_flat = dist.reshape(-1)
        v_flat = jnp.repeat(valid_row, npts)
        vidx = jnp.where(v_flat, jnp.arange(d_flat.shape[0]), -1)
        li = jnp.max(vidx)
        new_dist_carry = jnp.where(
            li >= 0, d_flat[jnp.maximum(li, 0)], state.dist_carry
        )
    if cfg.paired:
        new_prev = jax.lax.dynamic_index_in_dim(
            frames, jnp.maximum(m - 1, 0), 0, keepdims=False
        )
        new_have_prev = state.have_prev | (m > 0)
    else:
        new_prev = state.prev_frame
        new_have_prev = state.have_prev

    # -- per-node timestamps (protocol/timing.frame_sample_times, f32) --
    first = rx - jnp.float32(cfg.delay0_us * 1e-6)
    step = jnp.float32(cfg.sample_duration_us * 1e-6 if cfg.grouped else 0.0)
    ts2 = first[:, None] + step * jnp.arange(npts, dtype=jnp.float32)[None, :]

    angle, dist, quality, flag = _wire_clamp(angle, dist, quality, flag)

    # -- validity compaction: stable row sort, valid frames first --
    # (NO element-wise scatter anywhere below: XLA lowers scatters to a
    # µs-per-element loop on CPU, which at production batch sizes cost
    # more than the whole filter step)
    order = jnp.argsort(jnp.logical_not(valid_row), stable=True)
    nvr = jnp.sum(valid_row.astype(jnp.int32))
    n = mb * npts
    nv = nvr * npts
    batch4 = jnp.stack(
        [angle[order], dist[order], quality[order], flag[order]], axis=-1
    ).reshape(n, 4)
    ts_c = ts2[order].reshape(n)

    core = _segment_filter_core(cfg, state, batch4, ts_c, nv, base_shift)
    map_wire = None
    mstate = None
    if cfg.mapping is not None:
        mstate, map_wire = _map_update_tick(cfg, state, core)
    new_state = IngestState(
        filter=core.filter,
        partial=core.partial,
        partial_ts=core.partial_ts,
        partial_len=core.partial_len,
        seen_sync=core.seen_sync,
        sync_carry=new_sync_carry,
        dist_carry=new_dist_carry,
        prev_frame=new_prev,
        have_prev=new_have_prev,
        scans_completed=state.scans_completed + core.n_completed,
        revs_dropped=state.revs_dropped + core.drop_head,
        recon_ring=core.recon_ring,
        recon_pos=core.recon_pos,
        deskew_prof=core.deskew_prof,
        deskew_motion=core.deskew_motion,
        **_map_state_leaves(mstate),
    )
    return (new_state,) + _core_outputs(cfg, core, map_wire)


# ---------------------------------------------------------------------------
# fleet-fused lowering: ONE dispatch per fleet tick, bytes in, N scans out
# ---------------------------------------------------------------------------
#
# The fleet service's host path pays N host decodes plus per-stream packing
# ahead of its one batched filter dispatch per tick.  This lowering stacks
# every stream's raw frame bytes into one (N, M, frame_bytes) buffer and
# runs the whole per-stream pipeline — unpack, validity compaction,
# sync-split revolution segmentation, the donated filter slots — vmapped
# over the stream axis inside ONE compiled program, with each stream's
# decode carries (prev frame, sync edge, smoothing, partial revolution,
# timestamp re-base) threaded as device state exactly like the
# single-stream step above.  Per-stream answer types ride as device
# scalars dispatched via ``lax.switch``, so a mixed fleet (or one stream
# switching scan modes mid-session) shares the one program.

# widest payload over every wire format: the per-stream prev-frame carry
# plane is allocated at this width so the carried state's SHAPE never
# depends on which formats a fleet happens to be streaming — a scan-mode
# change recompiles the program but never re-stages device state
_FLEET_PREV_BYTES = max(int(v) for v in ANS_PAYLOAD_BYTES.values())


@dataclasses.dataclass(frozen=True)
class FleetIngestConfig:
    """Static (compile-time) configuration of one fleet-fused program.

    ``formats`` is the tuple of answer types the program can decode; each
    stream selects its branch per dispatch via a device scalar in ``aux``
    (``lax.switch``), so per-stream format changes move an index, not the
    program.  Input geometry (``frame_bytes``/``npts``) is the max over
    ``formats``: a homogeneous fleet — the common case — compiles exactly
    its own format's shapes and pays no switch at all (the single-branch
    fast path in :func:`_fleet_stream_step`).
    """

    formats: tuple           # ans types, branch order
    frame_bytes: int         # input row width = max payload over formats
    npts: int                # common sample width = max over formats
    sample_duration_us: int
    delay0_us: tuple         # per-format back-dating of sample 0, formats order
    max_nodes: int
    max_revs: int
    emit_nodes: bool
    filter: FilterConfig
    # per-revolution slot lowering: the fleet default is "fori" — under
    # vmap a lax.cond slot's batched predicate lowers to select, which
    # executes BOTH branches per stream and inverts the cond lowering's
    # skip advantage; fori's batched while_loop runs max(n_completed)
    # iterations across the fleet (1 in steady state).
    slot_impl: str = "fori"
    # fixed-point de-skew + sweep reconstruction (ops/deskew.py); every
    # lane carries its own ring/profile/motion planes when set
    deskew: Optional[DeskewConfig] = None
    # in-program SLAM front-end (see IngestConfig.mapping): every lane
    # carries its own MapState planes and the per-tick map update runs
    # inside the one fleet program — one dispatch per (super-)tick per
    # shard covers ingest AND mapping.  Requires ``deskew``.
    mapping: Optional[MapConfig] = None

    def __post_init__(self):
        _check_mapping_geometry(self.mapping, self.deskew)


def fleet_ingest_config_for(
    formats,
    timing: timingmod.TimingDesc,
    filter_cfg: FilterConfig,
    *,
    max_nodes: int = MAX_SCAN_NODES,
    max_revs: int = 2,
    emit_nodes: bool = False,
    slot_impl: str = "fori",
    deskew: Optional[DeskewConfig] = None,
    mapping: Optional[MapConfig] = None,
) -> FleetIngestConfig:
    """Build the static config for one (format set, timing desc, chain)."""
    ats = tuple(Ans(a) for a in dict.fromkeys(formats))
    if not ats:
        raise ValueError("fleet ingest needs at least one wire format")
    return FleetIngestConfig(
        formats=tuple(int(a) for a in ats),
        frame_bytes=max(ANS_PAYLOAD_BYTES[a] for a in ats),
        npts=max(_NPTS[a] for a in ats),
        sample_duration_us=timing.sample_duration_int_us,
        delay0_us=tuple(timingmod.sample_delay_us(a, timing, 0) for a in ats),
        max_nodes=max_nodes,
        max_revs=max_revs,
        emit_nodes=emit_nodes,
        filter=filter_cfg,
        slot_impl=slot_impl,
        deskew=deskew,
        mapping=mapping,
    )


def create_fleet_ingest_state(
    cfg: FleetIngestConfig, streams: int, filter_state=None
) -> IngestState:
    """Stream-batched :class:`IngestState` — a leading ``(streams,)`` axis
    on every leaf (same pytree class; the fleet step vmaps over it).

    ``filter_state`` (stream-batched) carries the rolling windows across
    scan-mode switches, like the single-stream engine; the prev-frame
    plane is allocated at the global max payload width so this state's
    shape is independent of the config's format set.
    """
    if filter_state is None:
        per = FilterState.for_config(cfg.filter)
        filter_state = jax.tree_util.tree_map(
            lambda x: jnp.tile(x[None], (streams,) + (1,) * x.ndim), per
        )
    dsk = cfg.deskew
    return IngestState(
        filter=filter_state,
        partial=jnp.zeros((streams, cfg.max_nodes, 4), jnp.int32),
        partial_ts=jnp.zeros((streams, cfg.max_nodes), jnp.float32),
        partial_len=jnp.zeros((streams,), jnp.int32),
        seen_sync=jnp.zeros((streams,), bool),
        sync_carry=jnp.zeros((streams,), jnp.int32),
        dist_carry=jnp.zeros((streams,), jnp.int32),
        prev_frame=jnp.zeros((streams, _FLEET_PREV_BYTES), jnp.uint8),
        have_prev=jnp.zeros((streams,), bool),
        scans_completed=jnp.zeros((streams,), jnp.int32),
        revs_dropped=jnp.zeros((streams,), jnp.int32),
        recon_ring=(
            jnp.full(
                (streams, dsk.recon_window, dsk.recon_beams),
                RECON_EMPTY, jnp.int32,
            ) if dsk is not None else None
        ),
        recon_pos=(
            jnp.zeros((streams,), jnp.int32) if dsk is not None else None
        ),
        deskew_prof=(
            jnp.full((streams, dsk.profile_beams), RECON_EMPTY, jnp.int32)
            if dsk is not None else None
        ),
        deskew_motion=(
            jnp.zeros((streams, 3), jnp.int32) if dsk is not None else None
        ),
        **_fresh_map_leaves(cfg.mapping, streams),
    )


def fleet_aux_len(max_frames: int) -> int:
    """Per-stream aux row length for a ``max_frames`` bucket: rx offsets,
    CRC verdicts, then [base_shift, m, branch, reset]."""
    return 2 * max_frames + 4


def _reset_stream_decode(state: IngestState, reset) -> IngestState:
    """Zero one stream's decode/assembly carries (scan-mode change or an
    engine-level stream reset) while the rolling filter window — and the
    cumulative stream stats — survive: the device-side analog of the
    single-stream engine's ``_activate`` building a fresh ingest state
    around the carried FilterState."""
    def rz(a):
        return jnp.where(reset, jnp.zeros_like(a), a)

    def re(a):
        # sub-sweep ring / motion profile reset to the EMPTY sentinel
        # (a zero cell would decode as a live dist-0 return): the cache
        # restarts with the decode carries — a format switch or
        # quarantine rejoin must never stitch reconstructed sweeps
        # across the discontinuity
        return None if a is None else jnp.where(
            reset, jnp.full_like(a, RECON_EMPTY), a
        )

    def rz_opt(a):
        return None if a is None else rz(a)

    return dataclasses.replace(
        state,
        partial=rz(state.partial),
        partial_ts=rz(state.partial_ts),
        partial_len=rz(state.partial_len),
        seen_sync=state.seen_sync & ~reset,
        sync_carry=rz(state.sync_carry),
        dist_carry=rz(state.dist_carry),
        prev_frame=rz(state.prev_frame),
        have_prev=state.have_prev & ~reset,
        recon_ring=re(state.recon_ring),
        recon_pos=rz_opt(state.recon_pos),
        deskew_prof=re(state.deskew_prof),
        deskew_motion=rz_opt(state.deskew_motion),
    )


def _fleet_branch(cfg: FleetIngestConfig, k: int, state, frames, rx, crc_ok, m):
    """One format's decode+carry step at fleet input geometry: slice the
    stream's frame rows to this format's payload width, run the exact
    single-stream decode (prev frame prepended for the paired formats,
    edge/smoothing carries as traced scalars), back-date per-sample
    stamps, and pad the per-frame sample planes to the fleet's common
    width (pad columns are dead: valid=False, stamp 0).  An ``m == 0``
    lane (idle stream, or a lane executing a non-selected switch branch)
    passes every carry through unchanged — unlike the single-stream step,
    which never dispatches empty batches."""
    from rplidar_ros2_driver_tpu.ops import unpack

    at = Ans(cfg.formats[k])
    fb = ANS_PAYLOAD_BYTES[at]
    npts = _NPTS[at]
    paired = at in _PAIRED
    mb = frames.shape[0]
    fr = frames[:, :fb]
    rows = jnp.arange(mb, dtype=jnp.int32)

    if at == Ans.MEASUREMENT:
        dec = unpack.unpack_normal_nodes(fr)
    elif at == Ans.MEASUREMENT_HQ:
        dec = unpack.unpack_hq_capsules(fr, crc_ok)
    else:
        frp = jnp.concatenate([state.prev_frame[None, :fb], fr], axis=0)
        if at == Ans.MEASUREMENT_CAPSULED:
            dec = unpack.unpack_capsules(frp)
        elif at == Ans.MEASUREMENT_CAPSULED_ULTRA:
            dec = unpack.unpack_ultra_capsules(frp)
        elif at == Ans.MEASUREMENT_DENSE_CAPSULED:
            dec = unpack.unpack_dense_capsules(
                frp, state.sync_carry, sample_duration_us=cfg.sample_duration_us
            )
        elif at == Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED:
            dec = unpack.unpack_ultra_dense_capsules(
                frp, state.sync_carry, state.dist_carry,
                sample_duration_us=cfg.sample_duration_us,
            )
        else:  # pragma: no cover - config_for validates formats
            raise ValueError(f"unsupported ans type {int(at):#x}")

    if paired:
        # pair i = (fr[i], fr[i+1]) with the prev frame at fr[0]: a zeroed
        # prev fails the checksum, but the explicit mask also covers it
        row_live = (rows < m) & (state.have_prev | (rows > 0))
    else:
        row_live = rows < m
    angle = jnp.asarray(dec.angle_q14)[:mb]
    dist = jnp.asarray(dec.dist_q2)[:mb]
    quality = jnp.asarray(dec.quality)[:mb]
    flag = jnp.asarray(dec.flag)[:mb]
    valid_row = jnp.asarray(dec.node_valid)[:mb, 0] & row_live

    # -- carries for the next dispatch (single-stream step semantics,
    # guarded so an empty lane cannot clobber them) --
    new_sync = state.sync_carry
    new_dist = state.dist_carry
    if at in (
        Ans.MEASUREMENT_DENSE_CAPSULED, Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED
    ):
        last_row_flag = jax.lax.dynamic_index_in_dim(
            flag, jnp.maximum(m - 1, 0), 0, keepdims=False
        )
        new_sync = jnp.where(m > 0, last_row_flag[-1] & 1, state.sync_carry)
    if at == Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED:
        d_flat = dist.reshape(-1)
        v_flat = jnp.repeat(valid_row, npts)
        vidx = jnp.where(v_flat, jnp.arange(d_flat.shape[0]), -1)
        li = jnp.max(vidx)
        new_dist = jnp.where(
            li >= 0, d_flat[jnp.maximum(li, 0)], state.dist_carry
        )
    if paired:
        last = jax.lax.dynamic_index_in_dim(
            frames, jnp.maximum(m - 1, 0), 0, keepdims=False
        )
        lastp = jnp.zeros((_FLEET_PREV_BYTES,), jnp.uint8)
        lastp = jax.lax.dynamic_update_slice(lastp, last, (0,))
        new_prev = jnp.where(m > 0, lastp, state.prev_frame)
        new_have = state.have_prev | (m > 0)
    else:
        new_prev = state.prev_frame
        new_have = state.have_prev

    # -- per-node timestamps (protocol/timing.frame_sample_times, f32) --
    first = rx - jnp.float32(cfg.delay0_us[k] * 1e-6)
    step = jnp.float32(
        cfg.sample_duration_us * 1e-6
        if at in timingmod._GROUPED_FORMATS else 0.0
    )
    ts2 = first[:, None] + step * jnp.arange(npts, dtype=jnp.float32)[None, :]

    P = cfg.npts
    valid2 = valid_row[:, None] & (
        jnp.arange(P, dtype=jnp.int32)[None, :] < npts
    )

    def pad(a):
        if a.shape[1] == P:
            return a
        return jnp.pad(a, ((0, 0), (0, P - a.shape[1])))

    return (
        pad(angle), pad(dist), pad(quality), pad(flag),
        valid2, pad(ts2),
        new_sync, new_dist, new_prev, new_have,
    )


def _fleet_stream_step(cfg: FleetIngestConfig, state: IngestState, frames, aux):
    """One stream's lane of the fleet step (vmapped over the stream axis):
    branch-dispatched decode, node-level validity compaction, then the
    shared segmentation/filter core."""
    mb = frames.shape[0]
    rx = aux[:mb]
    crc_ok = aux[mb : 2 * mb] > 0.5
    base_shift = aux[2 * mb]
    # graftlint: policed — frame count and branch index ride the f32 aux
    # plane by wire contract: small non-negative ints, exact in f32
    m = aux[2 * mb + 1].astype(jnp.int32)
    # graftlint: policed — see above
    branch = aux[2 * mb + 2].astype(jnp.int32)
    reset = aux[2 * mb + 3] > 0.5
    state = _reset_stream_decode(state, reset)

    if len(cfg.formats) == 1:
        dec = _fleet_branch(cfg, 0, state, frames, rx, crc_ok, m)
    else:
        dec = jax.lax.switch(
            jnp.clip(branch, 0, len(cfg.formats) - 1),
            [
                functools.partial(_fleet_branch, cfg, k)
                for k in range(len(cfg.formats))
            ],
            state, frames, rx, crc_ok, m,
        )
    (angle, dist, quality, flag, valid2, ts2,
     new_sync, new_dist, new_prev, new_have) = dec
    angle, dist, quality, flag = _wire_clamp(angle, dist, quality, flag)

    # -- node-level validity compaction: frame validity is row-uniform in
    # every wire format, but at fleet width the narrower formats' padded
    # sample columns break row uniformity — a stable flat argsort on the
    # node mask reduces EXACTLY to the single-stream row compaction when
    # rows are uniform (valid rows in order, each row's nodes contiguous),
    # so the two paths stay bit-identical through the shared core
    v = valid2.reshape(-1)
    order = jnp.argsort(jnp.logical_not(v), stable=True)
    nv = jnp.sum(v.astype(jnp.int32))
    batch4 = jnp.stack(
        [angle, dist, quality, flag], axis=-1
    ).reshape(-1, 4)[order]
    ts_c = ts2.reshape(-1)[order]

    core = _segment_filter_core(cfg, state, batch4, ts_c, nv, base_shift)
    map_wire = None
    mstate = None
    if cfg.mapping is not None:
        mstate, map_wire = _map_update_tick(cfg, state, core)
    new_state = IngestState(
        filter=core.filter,
        partial=core.partial,
        partial_ts=core.partial_ts,
        partial_len=core.partial_len,
        seen_sync=core.seen_sync,
        sync_carry=new_sync,
        dist_carry=new_dist,
        prev_frame=new_prev,
        have_prev=new_have,
        scans_completed=state.scans_completed + core.n_completed,
        revs_dropped=state.revs_dropped + core.drop_head,
        recon_ring=core.recon_ring,
        recon_pos=core.recon_pos,
        deskew_prof=core.deskew_prof,
        deskew_motion=core.deskew_motion,
        **_map_state_leaves(mstate),
    )
    return (new_state,) + _core_outputs(cfg, core, map_wire)


def _fleet_tick(cfg: FleetIngestConfig, state: IngestState, frames, aux):
    """The un-jitted fleet-tick body (every stream's lane vmapped over
    the stream axis) — shared verbatim by the per-tick program
    (:func:`fleet_fused_ingest_step`) and the T-tick super-step
    (:func:`super_fleet_ingest_step`'s ``lax.scan`` body), so the two
    lowerings can never drift semantically."""
    return jax.vmap(functools.partial(_fleet_stream_step, cfg))(
        state, frames, aux
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def fleet_fused_ingest_step(
    state: IngestState, frames: jax.Array, aux: jax.Array,
    cfg: FleetIngestConfig,
) -> tuple:
    """One fleet tick through the whole ingest pipeline in ONE program.

    ``state`` is the stream-batched :func:`create_fleet_ingest_state`
    pytree (donated); ``frames`` is (streams, M, frame_bytes) uint8 —
    every stream's raw frame bytes for this tick, zero-padded past each
    stream's live count and past each narrower format's payload width;
    ``aux`` is (streams, 2M+4) float32 per :func:`fleet_aux_len`:
    per-frame rx offsets from the STREAM's own base stamp, per-frame CRC
    verdicts (HQ only), then [previous-base-minus-base re-base shift,
    live frame count, format branch index, decode-state reset flag].

    Returns ``(state, meta, out_wires[, nodes, node_ts])`` with a leading
    stream axis on every result — the single-stream result layout per
    stream row (see the layout note above) — so a fleet tick is one
    dispatch and at most one meta fetch + one wire fetch, independent of
    fleet size.
    """
    return _fleet_tick(cfg, state, frames, aux)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def super_fleet_ingest_step(
    state: IngestState, frames: jax.Array, aux: jax.Array,
    cfg: FleetIngestConfig,
) -> tuple:
    """T fleet ticks through the whole ingest pipeline in ONE program —
    the temporal counterpart of the fleet lowering's spatial fusion
    (chunk -> fleet tick -> T ticks, the third rung of the
    dispatch-amortization ladder).

    ``frames`` is (T, streams, M, frame_bytes) uint8 and ``aux``
    (T, streams, 2M+4) float32 — T per-tick staging planes with the
    per-tick layout of :func:`fleet_fused_ingest_step` — and the whole
    stream state (decode carries, partial revolutions, timestamp
    re-bases, rolling filter windows) threads through a ``lax.scan``
    over the tick axis as donated scan carries.  The scan body IS
    :func:`_fleet_tick`, so a T-step super-tick is bit-exact against T
    sequential per-tick dispatches (pinned by tests/test_super_tick.py);
    the per-revolution slot lowering stays the fleet default ``fori``,
    whose while-loop carries alias in place — no cond-induced copies of
    the FilterState ride the scan.

    An all-idle tick plane (every stream m=0, reset=0, base_shift=0)
    passes every carry through unchanged and emits an all-zero meta row,
    so callers can pad a short backlog up to a fixed T and keep ONE
    compiled executable per (T, bucket) instead of one per backlog
    length.

    Returns ``(state, meta, out_wires[, nodes, node_ts])`` with a
    leading (T, streams) axis pair on every result — one dispatch and
    one meta fetch per T ticks, independent of both T and fleet size.
    """

    def body(st, xs):
        fr, ax = xs
        res = _fleet_tick(cfg, st, fr, ax)
        return res[0], tuple(res[1:])

    state, stacked = jax.lax.scan(body, state, (frames, aux))
    return (state,) + tuple(stacked)


def _parse_fleet_rows(
    meta, wires, nodes_all, ts_all, cfg, recon_all=None, rpts_all=None,
    map_all=None,
) -> list:
    """One :class:`IngestBatchResult` per stream row of one tick's
    materialized result planes (the shared tail of the fleet and
    super-step unpackers)."""
    r = cfg.max_revs
    doff = _META + 3 * r
    out = []
    for i in range(meta.shape[0]):
        mrow = meta[i]
        n = int(mrow[0])
        off = _META
        # graftlint: policed — slot counts ride the f32 meta plane by
        # wire contract (unpack_ingest_result note): exact small ints
        counts = mrow[off : off + r].astype(np.int32)
        ts0 = mrow[off + r : off + 2 * r].copy()
        end_ts = mrow[off + 2 * r : off + 3 * r].copy()
        outputs = [
            unpack_output_wire(wires[i, k], cfg.filter) for k in range(n)
        ]
        recon_kw = {}
        if cfg.deskew is not None:
            recon_kw = {
                "recon_pushed": bool(mrow[doff] > 0.5),
                "recon_valid": int(mrow[doff + 1]),
                # graftlint: policed — deskew meta rides the f32 plane
                # by wire contract (unpack_ingest_result note)
                "deskew_motion": mrow[doff + 2 : doff + 5].astype(np.int32),
                "recon_plane": (
                    recon_all[i] if recon_all is not None else None
                ),
                "recon_pts": rpts_all[i] if rpts_all is not None else None,
            }
        if cfg.mapping is not None and map_all is not None:
            recon_kw["map_wire"] = np.asarray(map_all[i], np.int32)
        out.append(IngestBatchResult(
            n_completed=n,
            revs_dropped=int(mrow[1]),
            syncs=int(mrow[2]),
            nodes_appended=int(mrow[3]),
            counts=counts[:n],
            ts0=ts0[:n],
            end_ts=end_ts[:n],
            outputs=outputs,
            nodes=(
                # graftlint: policed — debug node planes ride f32 by
                # wire contract; 18-bit clamped dist is exact
                nodes_all[i].astype(np.int32)[:n]
                if nodes_all is not None else None
            ),
            node_ts=ts_all[i][:n] if ts_all is not None else None,
            **recon_kw,
        ))
    return out


def unpack_fleet_ingest_result(res, cfg: FleetIngestConfig) -> list:
    """Host-side parse of one fleet step's result arrays: one
    :class:`IngestBatchResult` per stream.  The meta plane (streams x a
    handful of floats) is always materialized — ONE fetch per tick; the
    stream-batched wire plane is touched once, and only when at least one
    stream completed a revolution, so an all-mid-revolution tick costs
    one tiny fetch regardless of fleet size."""
    meta = np.asarray(res[0])
    if meta.ndim != 2 or meta.shape[1] != ingest_meta_len(cfg):
        raise ValueError(
            f"fleet ingest meta of shape {meta.shape} does not match cfg "
            f"(expected (streams, {ingest_meta_len(cfg)}))"
        )
    wires = None
    if (meta[:, 0] > 0).any():
        wires = np.asarray(res[1])
    idx = 2
    recon_all = rpts_all = None
    if cfg.deskew is not None:
        # the reconstruction planes are the every-tick surface (the
        # mapper feed), so they materialize unconditionally — still one
        # fetch per array per tick, independent of fleet size
        recon_all = np.asarray(res[idx])
        rpts_all = np.asarray(res[idx + 1])
        idx += 2
    map_all = None
    if cfg.mapping is not None:
        # the in-program mapping surface (one small (streams, 7) int32
        # plane — the pose/score wires the host route used to fetch
        # from its separate mapper dispatch)
        map_all = np.asarray(res[idx])
        idx += 1
    nodes_all = ts_all = None
    if cfg.emit_nodes:
        nodes_all = np.asarray(res[idx])
        ts_all = np.asarray(res[idx + 1])
    return _parse_fleet_rows(
        meta, wires, nodes_all, ts_all, cfg, recon_all, rpts_all, map_all
    )


def unpack_super_fleet_ingest_result(res, cfg: FleetIngestConfig) -> list:
    """Host-side parse of one super-step's result arrays: a list over
    the T tick planes, each a list of per-stream
    :class:`IngestBatchResult` (the :func:`unpack_fleet_ingest_result`
    layout per tick).  The (T, streams) meta plane is ONE fetch per
    super-step; the stacked wire plane is touched once, and only when
    at least one revolution completed anywhere in the super-step."""
    meta = np.asarray(res[0])
    if meta.ndim != 3 or meta.shape[2] != ingest_meta_len(cfg):
        raise ValueError(
            f"super-tick ingest meta of shape {meta.shape} does not match "
            f"cfg (expected (T, streams, {ingest_meta_len(cfg)}))"
        )
    wires = None
    if (meta[:, :, 0] > 0).any():
        wires = np.asarray(res[1])
    idx = 2
    recon_all = rpts_all = None
    if cfg.deskew is not None:
        recon_all = np.asarray(res[idx])
        rpts_all = np.asarray(res[idx + 1])
        idx += 2
    map_all = None
    if cfg.mapping is not None:
        map_all = np.asarray(res[idx])
        idx += 1
    nodes_all = ts_all = None
    if cfg.emit_nodes:
        nodes_all = np.asarray(res[idx])
        ts_all = np.asarray(res[idx + 1])
    return [
        _parse_fleet_rows(
            meta[t],
            wires[t] if wires is not None else None,
            nodes_all[t] if nodes_all is not None else None,
            ts_all[t] if ts_all is not None else None,
            cfg,
            recon_all[t] if recon_all is not None else None,
            rpts_all[t] if rpts_all is not None else None,
            map_all[t] if map_all is not None else None,
        )
        for t in range(meta.shape[0])
    ]
