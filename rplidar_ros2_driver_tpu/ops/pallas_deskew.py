"""Pallas TPU kernels for the de-skew/reconstruction hot loops.

The PR 13 fusion (mapping threaded through the ingest carry) makes the
de-skew stage's two dense loops the ingest program's exposed hot spots:

  * the **sub-sweep rasterizer / profile beam-min** — a per-beam
    masked min over every node of the tick (ops/deskew.
    rasterize_subsweep and profile_from_nodes share the formulation):
    the XLA arm materializes (block, n) compare planes per beam block
    in HBM; this kernel tiles the beam axis over VMEM and streams the
    node planes through in chunks, so each (TB, n) compare never exists
    outside the vector unit — the same VMEM-residency move as the PR 8
    matcher kernels (ops/pallas_scan_match.py);
  * the **de-skew shift search** — the (C, D) circular-shift SAD score
    of ops/deskew.estimate_motion: one VMEM pass computes every
    candidate's clamped mean-|Δ| score (the rolls are cheap static
    slices and stay in shared jnp code so the candidate set cannot
    drift between backends).

EXACTNESS: both kernels are int32 min/sum/compare end to end — any
evaluation order is bit-identical, so the Pallas arms are byte-equal to
the XLA arms and the NumPy twins (ops/deskew_ref.py) by construction;
tests/test_pallas_deskew.py pins all three.  ``DeskewConfig.backend``
selects the lowering; every entry point rides ``_lowering_dispatch``
(compiled on TPU, interpret mode off-TPU — CPU CI smokes the exact
kernel code path), the GL010 discipline.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from rplidar_ros2_driver_tpu.ops.filters import _INT_INF
from rplidar_ros2_driver_tpu.ops.pallas_kernels import _lowering_dispatch

_LANES = 128
_EMPTY = _INT_INF  # == ops/deskew.RECON_EMPTY (aliased, not re-declared)


def _pad_to(n: int, m: int) -> int:
    return ((n + m - 1) // m) * m


# ---------------------------------------------------------------------------
# beam-min: per-beam masked min over one tick's node stream
# ---------------------------------------------------------------------------


def _beam_min_kernel(chunk: int, beam_ref, val_ref, out_ref):
    """One (TB,) beam tile: min over every node whose beam index lands
    in the tile.  The node planes ride VMEM whole (two int32 rows); the
    (TB, chunk) compare lives only in registers/VPU per chunk."""
    tb = out_ref.shape[1]
    i = pl.program_id(0)
    bt = i * tb + jax.lax.broadcasted_iota(jnp.int32, (tb, 1), 0)
    n_pad = beam_ref.shape[1]

    def body(k, acc):
        b = beam_ref[0, pl.ds(k * chunk, chunk)]
        v = val_ref[0, pl.ds(k * chunk, chunk)]
        m = jnp.where(b[None, :] == bt, v[None, :], _EMPTY)
        return jnp.minimum(acc, jnp.min(m, axis=1))

    acc = jax.lax.fori_loop(
        0, n_pad // chunk, body,
        jnp.full((tb,), _EMPTY, jnp.int32),
    )
    out_ref[0, :] = acc


@functools.partial(
    jax.jit, static_argnames=("nbeams", "block_beams", "chunk", "interpret")
)
def _beam_min_call(beam, values, nbeams, block_beams, chunk, interpret):
    n_pad = beam.shape[1]
    grid = (nbeams // block_beams,)
    return pl.pallas_call(
        functools.partial(_beam_min_kernel, chunk),
        grid=grid,
        in_specs=[
            # constant index maps: the node planes load into VMEM once
            # and stay resident across every beam tile (the PR 8
            # fine-stage trick)
            pl.BlockSpec(
                (1, n_pad), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
            pl.BlockSpec(
                (1, n_pad), lambda i: (0, 0), memory_space=pltpu.VMEM
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, block_beams), lambda i: (0, i), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((1, nbeams), jnp.int32),
        interpret=interpret,
    )(beam, values)[0]


def beam_min_pallas(
    beam: jax.Array,
    values: jax.Array,
    nbeams: int,
    *,
    block_beams: int = 256,
    chunk: int = 512,
    interpret: bool | None = None,
) -> jax.Array:
    """(nbeams,) int32 per-beam min of ``values`` grouped by ``beam``
    (RECON_EMPTY where no node touched a beam) — the Pallas twin of the
    dense tiled min in ops/deskew.rasterize_subsweep /
    profile_from_nodes.  ``beam`` is (n,) int32 in [0, nbeams) and
    ``values`` (n,) int32 with RECON_EMPTY already marking dropped
    nodes (min is order-independent over int32, so any tiling is
    bit-identical).

    ``interpret=None`` (default) resolves per LOWERING platform
    (``_lowering_dispatch``), so the same traced function is correct on
    a TPU target and a CPU target alike."""
    n = beam.shape[0]
    # node padding: beam -1 never matches a tile row, value EMPTY is
    # the min identity — either alone suffices, both keep it obvious
    n_pad = max(_pad_to(n, chunk), chunk)
    b2 = jnp.full((1, n_pad), -1, jnp.int32)
    b2 = jax.lax.dynamic_update_slice(b2, beam.astype(jnp.int32)[None, :], (0, 0))
    v2 = jnp.full((1, n_pad), _EMPTY, jnp.int32)
    v2 = jax.lax.dynamic_update_slice(v2, values.astype(jnp.int32)[None, :], (0, 0))

    def _impl(b2, v2, interpret):
        tb = min(block_beams, nbeams) if interpret else max(
            min(block_beams, nbeams), _LANES
        )
        bp = _pad_to(nbeams, tb)
        out = _beam_min_call(b2, v2, bp, tb, chunk, interpret)
        return out[:nbeams]

    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_impl, interpret=False),
            functools.partial(_impl, interpret=True),
            b2, v2,
        )
    return _impl(b2, v2, interpret)


# ---------------------------------------------------------------------------
# shift search: the (C, D) circular-shift SAD score plane
# ---------------------------------------------------------------------------


def _shift_sad_kernel(min_valid: int, max_trans: int, prev_ref, rolled_ref,
                      out_ref):
    """All candidates in one VMEM pass: per row, the clamped mean-|Δ|
    score over beams valid in BOTH profiles (ops/deskew.estimate_motion
    `sad_of`, vectorized over the candidate axis)."""
    prev = prev_ref[0, :][None, :]                  # (1, D)
    rolled = rolled_ref[:]                          # (C, D)
    both = (prev != _EMPTY) & (rolled != _EMPTY)
    diff = jnp.clip(
        jnp.where(both, rolled - prev, 0), -max_trans, max_trans
    )
    sad = jnp.sum(jnp.abs(diff), axis=1, keepdims=True)       # (C, 1)
    cnt = jnp.sum(both.astype(jnp.int32), axis=1, keepdims=True)
    score = jnp.where(
        cnt >= min_valid, sad // jnp.maximum(cnt, 1), _EMPTY
    )
    out_ref[:] = jnp.broadcast_to(score, out_ref.shape)


@functools.partial(
    jax.jit, static_argnames=("min_valid", "max_trans", "interpret")
)
def _shift_sad_call(prev, rolled, min_valid, max_trans, interpret):
    cp, dp = rolled.shape
    return pl.pallas_call(
        functools.partial(_shift_sad_kernel, min_valid, max_trans),
        grid=(1,),
        in_specs=[
            pl.BlockSpec((1, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
            pl.BlockSpec((cp, dp), lambda i: (0, 0), memory_space=pltpu.VMEM),
        ],
        # lane-broadcast output: a (C, 1) int32 block trips the same
        # XLA/Mosaic tiled-layout mismatch the median kernels hit on
        # bare 1-D outputs, so the score broadcasts across one lane
        # group and the host reads column 0
        out_specs=pl.BlockSpec(
            (cp, _LANES), lambda i: (0, 0), memory_space=pltpu.VMEM
        ),
        out_shape=jax.ShapeDtypeStruct((cp, _LANES), jnp.int32),
        interpret=interpret,
    )(prev, rolled)


def shift_sad_pallas(
    prev_prof: jax.Array,
    rolled: jax.Array,
    min_valid: int,
    max_trans: int,
    *,
    interpret: bool | None = None,
) -> jax.Array:
    """(C,) int32 shift-candidate scores — the Pallas twin of the SAD
    stack in ops/deskew.estimate_motion.  ``rolled`` is the (C, D)
    plane of circularly shifted current profiles (the rolls are static
    slices built by the caller, so the |s|-ordered candidate set — and
    therefore first-min-wins tie-breaking — stays in shared code);
    RECON_EMPTY marks invalid beams in both inputs and is the returned
    "no estimate" score, exactly the XLA arm's convention."""
    c, d = rolled.shape
    # pad beams with EMPTY (invalid in `both` — contributes nothing)
    # and candidates with EMPTY rows (score EMPTY, sliced off)
    dp = _pad_to(max(d, _LANES), _LANES)
    cp = _pad_to(max(c, 8), 8)
    p2 = jnp.full((1, dp), _EMPTY, jnp.int32)
    p2 = jax.lax.dynamic_update_slice(
        p2, prev_prof.astype(jnp.int32)[None, :], (0, 0)
    )
    r2 = jnp.full((cp, dp), _EMPTY, jnp.int32)
    r2 = jax.lax.dynamic_update_slice(r2, rolled.astype(jnp.int32), (0, 0))

    def _impl(p2, r2, interpret):
        out = _shift_sad_call(p2, r2, min_valid, max_trans, interpret)
        return out[:c, 0]

    if interpret is None:
        return _lowering_dispatch(
            functools.partial(_impl, interpret=False),
            functools.partial(_impl, interpret=True),
            p2, r2,
        )
    return _impl(p2, r2, interpret)
