"""Fixed-point de-skew + caching-aware sweep reconstruction (ROADMAP 3).

A spinning 2-D lidar's revolution is not instantaneous: on a moving
platform every beam is measured from a slightly different pose, and at
fleet scale that intra-revolution skew is the dominant map-quality
error.  Following "Robust De-skewing Exclusively Relying on Range
Measurements" (range-only — no IMU, which matches our wire data: the
frames carry nothing but angle/dist/quality/flag) and SR-LIO++'s
caching-aware sweep reconstruction (both PAPERS.md), this module adds
two coupled stages that ride INSIDE the fused ingest core
(ops/ingest._segment_filter_core), so every lowering — single-stream,
fleet-vmapped, `lax.scan` super-tick — inherits them with zero extra
dispatches:

  1. **per-revolution range-only de-skew** — the per-revolution rigid
     motion (dx, dy, dθ) is estimated from CONSECUTIVE revolutions'
     beam-gridded range profiles (circular shift search for dθ, a
     diagonal least-squares radial fit for the translation), and every
     beam is re-projected to the revolution's END pose by its
     intra-revolution phase fraction (its wire angle: a node at angle a
     has (65536 - a)/65536 of the revolution's motion still ahead of
     it).  The whole datapath is int32 — the matcher's fixed-point
     rotation tables (ops/scan_match.rotation_table) supply cos/sin at
     2^14 scale, divisions are floor divisions, clamps are explicit —
     so the NumPy twin (ops/deskew_ref.py) is BIT-EXACT, not close.

  2. **caching-aware sweep reconstruction** — each tick's freshly
     arrived nodes (de-skewed with the carried motion estimate) are
     rasterized into a sub-sweep segment on the filter's beam grid and
     pushed into a device-resident ring of the last K segments; the
     reconstructed sweep emitted EVERY tick is the newest-wins overlay
     of the ring (cached segments are REUSED across overlapping
     windows, never recomputed — SR-LIO++'s cache discipline), turning
     one physical revolution into R >= 2 matcher/mapper updates at the
     same dispatch count.

EXACTNESS NOTES (the module is a graftlint GL004/GL005 bit-exact zone):
the only float arithmetic is (a) the clip predicate folded into the
sub-sweep rasterizer — a single f32 multiply + compares, mirroring
ops/filters._clip_ok, deterministic on every backend — and (b) the
reconstructed sweep's polar->Cartesian decode, which REUSES the filter
chain's jitted helpers (ops/filters._grid_decode / polar_to_cartesian)
so both ingest backends hand the mapper identical f32 planes (the same
elementwise-XLA argument the chain's own parity rests on).  Everything
that feeds state carries is integer.

Overflow discipline (int32 end to end): profile values are 18-bit wire
distances; per-beam diffs clamp to ±``max_trans_q2`` (<= 2^11) before
any product; cos/sin enter the normal equations pre-shifted to 7 bits
(|ΔR·c7| <= 2^18, summed over <= 2^10 profile beams < 2^28); the phase
products bound by 2^16 · 2^13 < 2^29.  ``DeskewConfig.__post_init__``
rejects geometries that break these bounds.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from rplidar_ros2_driver_tpu.ops.filters import _INT_INF
from rplidar_ros2_driver_tpu.ops.scan_match import ANG_BITS, rotation_table

# empty-beam sentinel shared by the motion profiles and the sub-sweep
# ring.  It MUST be ops/filters._INT_INF — combine_ring output feeds
# the chain's _grid_decode, whose miss test is `!= _INT_INF` — so it is
# aliased, not re-declared (a plain Python int either way: a
# module-scope jnp constant would initialize a backend at import time)
RECON_EMPTY = _INT_INF

# rotation-table resolution for per-node trig: 1024 rows of the
# matcher's int32 cos/sin table (2^14 scale) — the wire angle indexes it
# with one shift (65536 / 1024 = 64 angle units per row)
TABLE_DIVISIONS = 1024

# packed sub-sweep cell layout (dist << 8 | quality), the resampler's
# convention (ops/filters._resample_keys) minus the f32 decode
_QUAL_BITS = 8


@dataclasses.dataclass(frozen=True)
class DeskewConfig:
    """Static (compile-time) de-skew + reconstruction configuration."""

    recon_beams: int          # sub-sweep/reconstruction beam grid (= chain beams)
    profile_beams: int = 256  # motion-profile beam grid (power of two)
    shift_window: int = 8     # dθ search: ± profile-beam shifts
    recon_window: int = 4     # K sub-sweep segments kept per stream
    max_trans_q2: int = 2048  # per-revolution translation clamp (q2 units)
    min_valid: int = 16       # min overlapping profile beams for an estimate
    # clip fold for the sub-sweep rasterizer (the chain's _clip_ok
    # domain, so reconstructed sweeps see the same returns the filter
    # keeps); mirrored from FilterConfig by the factory — INCLUDING the
    # enable flag: a chain without the clip stage keeps out-of-range
    # returns, and the reconstruction must keep them too
    enable_clip: bool = True
    range_min_m: float = 0.15
    range_max_m: float = 40.0
    intensity_min: float = 0.0
    # kernel lowering of the two dense hot loops (the sub-sweep
    # rasterizer / profile beam-min and the shift-search SAD): "xla" =
    # the jnp arms below, "pallas" = the VMEM-tiled kernels
    # (ops/pallas_deskew.py, interpret mode off-TPU).  Bit-exact either
    # way — int32 min/sum are evaluation-order independent — so the
    # seam is purely a performance choice (resolve_deskew_backend
    # holds the auto mapping and its evidence bar).
    backend: str = "xla"

    def __post_init__(self):
        if self.backend not in ("xla", "pallas"):
            raise ValueError(
                "deskew backend must be 'xla' or 'pallas' once resolved "
                "(the 'auto' spelling resolves in resolve_deskew_backend "
                "before DeskewConfig is built)"
            )
        d = self.profile_beams
        if d < 64 or d > 1024 or d & (d - 1):
            raise ValueError(
                "deskew profile_beams must be a power of two in [64, 1024]"
            )
        if TABLE_DIVISIONS % d:
            raise ValueError(
                "deskew profile_beams must divide the trig table "
                f"({TABLE_DIVISIONS} rows)"
            )
        if not (1 <= self.shift_window <= d // 8):
            raise ValueError(
                "deskew shift_window must be within [1, profile_beams/8]"
            )
        if self.shift_window * (65536 // d) > (1 << 13):
            raise ValueError(
                "deskew shift window exceeds the 2^13 dθ overflow bound"
            )
        if not (2 <= self.recon_window <= 64):
            raise ValueError("sweep_reconstruct_window must be in [2, 64]")
        if not (8 <= self.recon_beams <= 8192):
            raise ValueError(
                "recon_beams must be in [8, 8192] (the declared GL011 "
                "reconstruction-sum bound)"
            )
        if not (0 < self.max_trans_q2 <= (1 << 11)):
            raise ValueError(
                "deskew max_trans_q2 must be in (0, 2^11] (the int32 "
                "normal-equation bound)"
            )
        if self.min_valid < 1:
            raise ValueError("deskew min_valid must be >= 1")


def resolve_deskew_backend(
    requested: str, platform: Optional[str] = None
) -> str:
    """Resolve the ``auto`` de-skew kernel lowering (mirrors
    mapping/mapper.resolve_match_backend; explicit requests pass
    through).  ``auto`` stays on the XLA arm until an on-chip artifact
    clears the standing decision bar — off-TPU the Pallas arm runs in
    INTERPRET mode (ops/pallas_kernels._lowering_dispatch), which
    measures the emulator, not the datapath, so CPU evidence can never
    flip this."""
    if requested != "auto":
        return requested
    del platform
    return "xla"


def deskew_config_from_params(
    params, beams: int, platform: Optional[str] = None
) -> Optional[DeskewConfig]:
    """The one params -> DeskewConfig mapping (None when disabled), so
    the engines, the service, replay and the bench cannot drift on
    geometry.  The clip fold mirrors the chain's clip params — the
    reconstructed sweep must keep exactly the returns the filter keeps."""
    if not getattr(params, "deskew_enable", False):
        return None
    return DeskewConfig(
        recon_beams=beams,
        profile_beams=int(getattr(params, "deskew_profile_beams", 256)),
        shift_window=int(getattr(params, "deskew_shift_window", 8)),
        recon_window=int(getattr(params, "sweep_reconstruct_window", 4)),
        enable_clip="clip" in tuple(params.filter_chain),
        range_min_m=float(params.range_clip_min_m),
        range_max_m=float(params.range_clip_max_m),
        intensity_min=float(params.intensity_min),
        backend=resolve_deskew_backend(
            getattr(params, "deskew_backend", "auto"), platform
        ),
    )


def shift_candidates(cfg: DeskewConfig) -> np.ndarray:
    """(2S+1,) int32 dθ shift candidates ordered by |s| (0, -1, 1, ...):
    the first-min-wins argmin then prefers the SMALLEST rotation on
    ties, so a featureless scene (every shift scores equally) estimates
    identity instead of the window edge.  Shared by both twins."""
    out = [0]
    for s in range(1, cfg.shift_window + 1):
        out.extend((-s, s))
    # graftlint: disable=GL001 — builds a compile-time candidate table
    # from Python ints (static per config); nothing traced reaches it
    return np.asarray(out, np.int32)


def profile_trig(cfg: DeskewConfig) -> np.ndarray:
    """(D, 2) int32 cos/sin at 2^14 scale for each profile beam's start
    angle — rows of the matcher's rotation table (numpy-built once,
    consumed verbatim by both twins, like ops/scan_match's)."""
    table = rotation_table(TABLE_DIVISIONS)
    step = TABLE_DIVISIONS // cfg.profile_beams
    return table[:: step]


def node_trig_table() -> np.ndarray:
    """(TABLE_DIVISIONS, 2) int32 cos/sin for per-node de-skew trig,
    indexed by ``angle >> 6`` (65536 / 1024 angle units per row)."""
    return rotation_table(TABLE_DIVISIONS)


# ---------------------------------------------------------------------------
# fixed-point building blocks (literal numpy mirrors in ops/deskew_ref.py
# — keep the two in lockstep, the parity suite pins them bit-exact)
# ---------------------------------------------------------------------------


def beam_of(angle, beams: int):
    """Wire angle -> beam cell, the chain resampler's exact convention
    (ops/filters._resample_keys: Q14 full turn == 65536)."""
    return jnp.clip((angle * beams) // 65536, 0, beams - 1)


def profile_from_nodes(angle, dist, valid, cfg: DeskewConfig, block: int = 64):
    """(D,) int32 min-range beam profile of one revolution's nodes
    (RECON_EMPTY where no return).  Dense tiled masked-min, the fused
    path's scatter-free formulation (ops/filters.grid_resample_batch):
    min is order-independent over int32, so any evaluation order — XLA,
    vmap, numpy, the Pallas kernel — lands the identical profile.
    ``cfg.backend`` routes the min through the VMEM-tiled kernel
    (ops/pallas_deskew.beam_min_pallas) or the jnp arm below."""
    d = cfg.profile_beams
    b = beam_of(angle, d)
    live = valid & (dist > 0)
    if cfg.backend == "pallas":
        from rplidar_ros2_driver_tpu.ops.pallas_deskew import (
            beam_min_pallas,
        )

        # a dead node contributes the EMPTY min-identity whatever its
        # beam — value masking is exactly the jnp arm's compare mask
        return beam_min_pallas(
            b, jnp.where(live, dist, RECON_EMPTY), d
        )
    outs = []
    for t0 in range(0, d, block):
        bt = jnp.arange(t0, min(t0 + block, d), dtype=jnp.int32)
        m = jnp.where(
            (b[None, :] == bt[:, None]) & live[None, :],
            dist[None, :], RECON_EMPTY,
        )
        outs.append(jnp.min(m, axis=1))
    return jnp.concatenate(outs)


def estimate_motion(prev_prof, cur_prof, cfg: DeskewConfig):
    """(3,) int32 [dx_q2, dy_q2, dθ_q16] rigid-motion estimate between
    two consecutive revolutions' range profiles — range-only, the
    de-skewing paper's premise.

    dθ: circular shift search — ``aligned_s = roll(cur, s)`` matches
    ``prev`` when s equals the inter-revolution rotation in beam units;
    the score is the mean absolute range difference over beams valid in
    BOTH profiles (diffs clamped to ±max_trans_q2 so one outlier beam
    cannot out-vote the consensus), candidates ordered by |s| so ties
    prefer identity.  (dx, dy): with the rotation taken out, a static
    point's range changes by the radial projection -(dx·cosφ + dy·sinφ)
    >> 14, so the translation drops out of one diagonal least-squares
    fit per axis (the off-diagonal Σcos·sin term vanishes over a full
    turn).  Fewer than ``min_valid`` overlapping beams — a fresh
    stream, an empty revolution — estimates exact zero: de-skew
    degrades to the identity, never to garbage."""
    d = cfg.profile_beams
    mt = cfg.max_trans_q2
    cands_np = shift_candidates(cfg)                             # (C,) host
    cands = jnp.asarray(cands_np)
    vp = prev_prof != RECON_EMPTY
    vc = cur_prof != RECON_EMPTY

    def sad_of(s):
        aligned = jnp.roll(cur_prof, s)
        both = vp & jnp.roll(vc, s)
        diff = jnp.clip(
            jnp.where(both, aligned - prev_prof, 0), -mt, mt
        )
        cnt = jnp.sum(both.astype(jnp.int32))
        sad = jnp.sum(jnp.abs(diff))
        return jnp.where(
            cnt >= cfg.min_valid, sad // jnp.maximum(cnt, 1), RECON_EMPTY
        )

    # static unroll over the (small) candidate set: scores in |s| order
    # (the rolls are static slices either way — building the (C, D)
    # rolled plane in shared code keeps the candidate order, and
    # therefore first-min-wins tie-breaking, backend-independent)
    if cfg.backend == "pallas":
        from rplidar_ros2_driver_tpu.ops.pallas_deskew import (
            shift_sad_pallas,
        )

        rolled = jnp.stack([
            jnp.roll(cur_prof, int(s)) for s in cands_np
        ])
        scores = shift_sad_pallas(prev_prof, rolled, cfg.min_valid, mt)
    else:
        scores = jnp.stack([sad_of(int(s)) for s in cands_np])
    k = jnp.argmin(scores).astype(jnp.int32)   # first-min-wins: ties -> s=0
    s_best = jnp.take(cands, k)
    usable = jnp.take(scores, k) != RECON_EMPTY

    aligned = jnp.roll(cur_prof, s_best)
    both = vp & jnp.roll(vc, s_best)
    diff = jnp.clip(jnp.where(both, aligned - prev_prof, 0), -mt, mt)
    trig = jnp.asarray(profile_trig(cfg))
    c7 = trig[:, 0] >> 7
    s7 = trig[:, 1] >> 7
    bi = both.astype(jnp.int32)
    num_x = jnp.sum(diff * c7 * bi)
    den_x = jnp.sum(c7 * c7 * bi)
    num_y = jnp.sum(diff * s7 * bi)
    den_y = jnp.sum(s7 * s7 * bi)
    dx = jnp.clip(-(num_x // jnp.maximum(den_x >> 7, 1)), -mt, mt)
    dy = jnp.clip(-(num_y // jnp.maximum(den_y >> 7, 1)), -mt, mt)
    # DeskewConfig.__post_init__ guarantees shift_window * (65536 // d)
    # <= 2^13, so the clip is a numeric no-op — but apply_deskew later
    # computes rem * motion[2] with rem up to 2^16, so motion[2] must be
    # BOUNDED, not merely bounded-in-practice, for that product to stay
    # inside int32.
    dth = jnp.clip(s_best * (65536 // d), -(1 << 13), 1 << 13)
    motion = jnp.stack([dx, dy, dth]).astype(jnp.int32)
    return jnp.where(usable, motion, jnp.zeros((3,), jnp.int32))


def apply_deskew(angle, dist, valid, motion, cfg: DeskewConfig):
    """Re-project nodes to the revolution's END pose by their phase
    fraction: a node at wire angle ``a`` still has ``(65536 - a)/65536``
    of the revolution's motion ahead of it, so its angle drifts by that
    fraction of -dθ and its range by the radial projection of the
    remaining translation.  Zero motion is the exact identity (every
    correction term multiplies by motion components).  Returns
    (angle', dist') with dist' clamped into the 18-bit wire domain and
    invalid/no-return nodes passed through untouched (a correction must
    never resurrect a dropped node)."""
    table = jnp.asarray(node_trig_table())
    rem = 65536 - angle                                         # (n,) 1..65536
    dang = (rem * motion[2]) >> 16
    angle2 = (angle - dang) & 0xFFFF
    idx = angle >> 6                                            # table row
    c = jnp.take(table[:, 0], idx)
    s = jnp.take(table[:, 1], idx)
    half = 1 << (ANG_BITS - 1)
    radial = (motion[0] * c + motion[1] * s + half) >> ANG_BITS  # q2 units
    corr = (radial * rem) >> 16
    dist2 = jnp.clip(dist - corr, 1, 0x3FFFF)
    live = valid & (dist > 0)
    return (
        jnp.where(live, angle2, angle),
        jnp.where(live, dist2, dist),
    )


def rasterize_subsweep(angle, dist, quality, valid, cfg: DeskewConfig,
                       block: int = 256):
    """(B,) int32 packed sub-sweep segment from one tick's (de-skewed)
    nodes: per-beam min of ``dist << 8 | quality`` (nearest return wins,
    carrying its intensity — the chain resampler's packing), RECON_EMPTY
    where the tick left a beam untouched.  The chain's clip predicate
    folds into the drop mask here (one f32 multiply + compares,
    ops/filters._clip_ok's exact domain) so the reconstructed sweep
    keeps exactly the returns the filter keeps."""
    b = cfg.recon_beams
    ok = valid & (dist > 0)
    if cfg.enable_clip:
        # THE one clip predicate (ops/filters._clip_ok), not a copy:
        # DeskewConfig carries the chain's range/intensity fields under
        # the same names, so the shared predicate applies directly — a
        # future change to the clip convention reaches the
        # reconstruction through this call (and breaks the NumPy twin's
        # parity suite loudly, forcing the mirror to follow)
        from rplidar_ros2_driver_tpu.core.types import ScanBatch
        from rplidar_ros2_driver_tpu.ops.filters import _clip_ok

        batch = ScanBatch(
            angle_q14=angle, dist_q2=dist, quality=quality,
            flag=jnp.zeros_like(angle), valid=valid,
            count=jnp.asarray(angle.shape[0], jnp.int32),
        )
        ok = ok & _clip_ok(batch, cfg)
    # packed-cell layout: the resampler's exact convention
    # (ops/filters._resample_keys — dist << 8 | 8-bit quality, nearest
    # return wins); _grid_decode inverts it downstream
    beam = beam_of(angle, b)
    packed = (dist << _QUAL_BITS) | jnp.clip(quality, 0, 255)
    packed = jnp.where(ok, packed, RECON_EMPTY)
    if cfg.backend == "pallas":
        from rplidar_ros2_driver_tpu.ops.pallas_deskew import (
            beam_min_pallas,
        )

        return beam_min_pallas(beam, packed, b)
    outs = []
    for t0 in range(0, b, block):
        bt = jnp.arange(t0, min(t0 + block, b), dtype=jnp.int32)
        m = jnp.where(
            beam[None, :] == bt[:, None], packed[None, :], RECON_EMPTY
        )
        outs.append(jnp.min(m, axis=1))
    return jnp.concatenate(outs)


def push_ring(ring, pos, seg, pushed):
    """Advance the sub-sweep ring by one segment when ``pushed`` (an
    idle tick leaves the ring untouched — the ring holds the last K
    NON-EMPTY sub-sweeps, so a stalled stream's cache does not expire
    under it).  ``pos`` counts pushes cumulatively; the write slot is
    ``pos % K``."""
    k = ring.shape[0]
    slot = jnp.remainder(pos, k)
    written = jax.lax.dynamic_update_index_in_dim(ring, seg, slot, 0)
    new_ring = jnp.where(pushed, written, ring)
    new_pos = pos + pushed.astype(jnp.int32)
    return new_ring, new_pos


def combine_ring(ring, pos):
    """(B,) int32 reconstructed sweep: newest-wins overlay of the ring's
    segments (a beam keeps the most recent segment that touched it —
    SR-LIO++'s cache reuse: segments rasterized once, reused across
    every overlapping window they appear in).  ``pos`` is the push
    count; the newest row is ``(pos - 1) % K``."""
    k = ring.shape[0]
    # age order, oldest first: rolling by -(pos % K) puts slot (pos % K)
    # — the OLDEST entry once the ring has wrapped, the first empty slot
    # before — at row 0 and the newest at row K-1
    aged = jnp.roll(ring, -jnp.remainder(pos, k), axis=0)
    combined = jnp.full(ring.shape[1:], RECON_EMPTY, jnp.int32)
    for i in range(k):
        combined = jnp.where(aged[i] != RECON_EMPTY, aged[i], combined)
    return combined


def recon_points(combined):
    """Reconstructed sweep -> ((B,) ranges, (B, 2) xy, (B,) mask): the
    chain's own decode + polar projection (ops/filters._grid_decode /
    polar_to_cartesian), so the mapper consumes reconstructed sweeps in
    exactly the representation the per-revolution path feeds it.  The
    f32 math here is the same elementwise-XLA code on every path —
    identical int planes in, identical f32 planes out."""
    from rplidar_ros2_driver_tpu.ops.filters import (
        _grid_decode,
        polar_to_cartesian,
    )

    ranges, _inten = _grid_decode(combined)
    xy, mask = polar_to_cartesian(ranges, combined.shape[0])
    return ranges, xy, mask
