"""NumPy golden reference for the de-skew + sweep-reconstruction stage
(ops/deskew.py) and the host-golden stream twin the parity suite drives.

The datapath is integer end to end (see the exactness notes in
ops/deskew.py), so every function here is BIT-EXACT against the jitted
/ vmapped / scanned lowerings — not "close", equal — which is what lets
tests/test_deskew.py pin the fused single-stream, fleet 1/3/8 and
super-tick T∈{1,2,8} paths byte-for-byte against this module.

Keep every function in literal lockstep with its ops/deskew.py twin; a
divergence is a bug in whichever side moved.

:class:`HostDeskewStream` is the per-stream state machine mirroring how
ops/ingest._segment_filter_core sequences the two stages per dispatch:
first the tick's freshly appended nodes are de-skewed with the CARRIED
motion estimate and rasterized into the sub-sweep ring (recon emits
every tick), then each revolution completed this tick re-estimates the
motion from consecutive profiles and de-skews its own nodes before they
enter the filter.  :class:`DeskewHostTwin` wraps the host golden decode
path (BatchScanDecoder + ScanAssembler) with a push_nodes tap so the
twin sees exactly the valid node stream the fused batch sees.
"""

from __future__ import annotations

import numpy as np

from rplidar_ros2_driver_tpu.ops.deskew import (
    RECON_EMPTY,
    DeskewConfig,
    node_trig_table,
    profile_trig,
    shift_candidates,
)
from rplidar_ros2_driver_tpu.ops.scan_match import ANG_BITS


def wire_clamp_np(angle, dist, quality, flag):
    """The wire clamps (ops/ingest._wire_clamp / the host pack's
    _pack_compact_rows domain) as int32 numpy — what both backends'
    node streams look like when they reach the de-skew stage."""
    angle = np.asarray(angle, np.int64) & 0xFFFF
    dist = np.asarray(dist, np.int64)
    dist = np.where(dist < 0, 0x3FFFF, np.minimum(dist, 0x3FFFF))
    quality = np.asarray(quality, np.int64) & 0xFF
    flag = np.asarray(flag, np.int64) & 0x3F
    return (
        angle.astype(np.int32), dist.astype(np.int32),
        quality.astype(np.int32), flag.astype(np.int32),
    )


def beam_of_np(angle, beams: int):
    return np.clip(
        (angle.astype(np.int64) * beams) // 65536, 0, beams - 1
    ).astype(np.int32)


def profile_from_nodes_np(angle, dist, valid, cfg: DeskewConfig):
    d = cfg.profile_beams
    b = beam_of_np(np.asarray(angle, np.int32), d)
    live = np.asarray(valid, bool) & (np.asarray(dist, np.int32) > 0)
    prof = np.full((d,), RECON_EMPTY, np.int32)
    # min is order-independent over int32: the scatter form here equals
    # the fused path's tiled masked-min exactly
    np.minimum.at(prof, b[live], np.asarray(dist, np.int32)[live])
    return prof


def estimate_motion_np(prev_prof, cur_prof, cfg: DeskewConfig):
    d = cfg.profile_beams
    mt = cfg.max_trans_q2
    cands = shift_candidates(cfg)
    vp = prev_prof != RECON_EMPTY
    vc = cur_prof != RECON_EMPTY

    scores = np.empty((len(cands),), np.int32)
    for i, s in enumerate(cands):
        aligned = np.roll(cur_prof, int(s))
        both = vp & np.roll(vc, int(s))
        diff = np.clip(np.where(both, aligned - prev_prof, 0), -mt, mt)
        cnt = int(both.sum())
        sad = int(np.abs(diff).sum())
        scores[i] = (
            sad // max(cnt, 1) if cnt >= cfg.min_valid else RECON_EMPTY
        )
    k = int(np.argmin(scores))  # first-min-wins: ties prefer s=0
    s_best = int(cands[k])
    if scores[k] == RECON_EMPTY:
        return np.zeros((3,), np.int32)

    aligned = np.roll(cur_prof, s_best)
    both = vp & np.roll(vc, s_best)
    diff = np.clip(np.where(both, aligned - prev_prof, 0), -mt, mt)
    trig = profile_trig(cfg)
    c7 = trig[:, 0] >> 7
    s7 = trig[:, 1] >> 7
    bi = both.astype(np.int32)
    num_x = int(np.sum(diff * c7 * bi))
    den_x = int(np.sum(c7 * c7 * bi))
    num_y = int(np.sum(diff * s7 * bi))
    den_y = int(np.sum(s7 * s7 * bi))
    dx = int(np.clip(-(num_x // max(den_x >> 7, 1)), -mt, mt))
    dy = int(np.clip(-(num_y // max(den_y >> 7, 1)), -mt, mt))
    dth = int(np.clip(s_best * (65536 // d), -(1 << 13), 1 << 13))
    return np.asarray([dx, dy, dth], np.int32)


def apply_deskew_np(angle, dist, valid, motion, cfg: DeskewConfig):
    del cfg  # geometry-independent, kept for twin-signature lockstep
    angle = np.asarray(angle, np.int32)
    dist = np.asarray(dist, np.int32)
    table = node_trig_table()
    rem = 65536 - angle
    dang = (rem * int(motion[2])) >> 16
    angle2 = (angle - dang) & 0xFFFF
    idx = angle >> 6
    c = table[idx, 0]
    s = table[idx, 1]
    half = 1 << (ANG_BITS - 1)
    radial = (int(motion[0]) * c + int(motion[1]) * s + half) >> ANG_BITS
    corr = (radial * rem) >> 16
    dist2 = np.clip(dist - corr, 1, 0x3FFFF)
    live = np.asarray(valid, bool) & (dist > 0)
    return (
        np.where(live, angle2, angle).astype(np.int32),
        np.where(live, dist2, dist).astype(np.int32),
    )


def rasterize_subsweep_np(angle, dist, quality, valid, cfg: DeskewConfig):
    b = cfg.recon_beams
    angle = np.asarray(angle, np.int32)
    dist = np.asarray(dist, np.int32)
    quality = np.asarray(quality, np.int32)
    ok = np.asarray(valid, bool) & (dist > 0)
    if cfg.enable_clip:
        # graftlint: policed — literal twin of the fused rasterizer's
        # one sanctioned float op: a single f32 multiply + compares
        # gating the integer drop mask (deterministic elementwise)
        dist_m = dist.astype(np.float32) * np.float32(1.0 / 4000.0)
        ok = (
            ok
            & (dist_m >= np.float32(cfg.range_min_m))
            & (dist_m <= np.float32(cfg.range_max_m))
            & (quality.astype(np.float32) >= np.float32(cfg.intensity_min))
        )
    beam = beam_of_np(angle, b)
    packed = (dist << 8) | np.clip(quality, 0, 255)
    seg = np.full((b,), RECON_EMPTY, np.int32)
    np.minimum.at(seg, beam[ok], packed[ok].astype(np.int32))
    return seg


def combine_ring_np(ring, pos):
    k = ring.shape[0]
    aged = np.roll(ring, -(int(pos) % k), axis=0)
    combined = np.full(ring.shape[1:], RECON_EMPTY, np.int32)
    for i in range(k):
        combined = np.where(aged[i] != RECON_EMPTY, aged[i], combined)
    return combined


class HostDeskewStream:
    """Per-stream host-golden twin of the fused core's de-skew +
    reconstruction state (the numpy analog of the four optional
    IngestState planes).  Drive it with the SAME per-dispatch node
    stream the fused path sees — :meth:`tick` first with everything the
    dispatch appended, then :meth:`revolution` for each revolution the
    dispatch completed, in order — and every returned plane is
    bit-exact against the fused lowerings."""

    def __init__(self, cfg: DeskewConfig) -> None:
        self.cfg = cfg
        self.reset()

    def reset(self) -> None:
        """The decode-carry reset (scan-mode switch, quarantine rejoin):
        the ring, profile and motion estimate restart with the engines
        — ops/ingest._reset_stream_decode's exact semantics."""
        cfg = self.cfg
        self.ring = np.full(
            (cfg.recon_window, cfg.recon_beams), RECON_EMPTY, np.int32
        )
        self.pos = 0
        self.prof = np.full((cfg.profile_beams,), RECON_EMPTY, np.int32)
        self.motion = np.zeros((3,), np.int32)

    def tick(self, angle, dist, quality, flag=None):
        """One dispatch's appended valid nodes (possibly none): de-skew
        with the CARRIED motion estimate, rasterize the sub-sweep, push
        it into the ring, and return ``(combined, pushed)`` — the
        reconstructed sweep emitted this tick and whether a segment was
        pushed (an empty tick re-emits the previous reconstruction)."""
        del flag
        angle = np.asarray(angle, np.int32)
        dist = np.asarray(dist, np.int32)
        quality = np.asarray(quality, np.int32)
        pushed = angle.size > 0
        if pushed:
            valid = np.ones(angle.shape, bool)
            a2, d2 = apply_deskew_np(
                angle, dist, valid, self.motion, self.cfg
            )
            seg = rasterize_subsweep_np(a2, d2, quality, valid, self.cfg)
            self.ring[self.pos % self.cfg.recon_window] = seg
            self.pos += 1
        return combine_ring_np(self.ring, self.pos), pushed

    def revolution(self, angle, dist, quality=None, flag=None):
        """One completed revolution's (wire-clamped) nodes: re-estimate
        the motion from the consecutive profiles, carry this
        revolution's raw profile for the next, and return the de-skewed
        ``(angle', dist')`` — what the filter consumes on both
        backends."""
        del quality, flag
        angle = np.asarray(angle, np.int32)
        dist = np.asarray(dist, np.int32)
        valid = np.ones(angle.shape, bool)
        prof = profile_from_nodes_np(angle, dist, valid, self.cfg)
        self.motion = estimate_motion_np(self.prof, prof, self.cfg)
        self.prof = prof
        return apply_deskew_np(angle, dist, valid, self.motion, self.cfg)


class DeskewHostTwin:
    """The host golden decode path (BatchScanDecoder + ScanAssembler)
    with the de-skew twin spliced in: feed it the same per-tick frame
    batches the fused engine gets and it yields, per tick, the
    reconstructed sweep plane and the de-skewed completed revolutions
    (ready for a golden ScanFilterChain).  The decoder's push_nodes
    stream IS the fused batch's compacted valid node stream (pinned by
    the existing ingest parity suites), so no second decode exists to
    drift."""

    def __init__(self, cfg: DeskewConfig, max_nodes=None) -> None:
        from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES
        from rplidar_ros2_driver_tpu.driver.assembly import ScanAssembler
        from rplidar_ros2_driver_tpu.driver.decode import BatchScanDecoder

        self.cfg = cfg
        self.stream = HostDeskewStream(cfg)
        self._tick_nodes: list = []
        self._completed: list = []
        twin = self

        class _TapAssembler(ScanAssembler):
            def push_nodes(self, angle_q14, dist_q2, quality, flag, ts=None):
                if len(angle_q14):
                    twin._tick_nodes.append(
                        wire_clamp_np(angle_q14, dist_q2, quality, flag)
                    )
                return super().push_nodes(
                    angle_q14, dist_q2, quality, flag, ts
                )

        self.assembler = _TapAssembler(
            max_nodes=max_nodes or MAX_SCAN_NODES,
            on_complete=lambda s: self._completed.append(dict(s)),
        )
        self.decoder = BatchScanDecoder(self.assembler)

    def reset(self) -> None:
        """Scan-mode switch: decoder + assembler + de-skew carries reset
        (the host path's _begin_streaming semantics; the filter window
        is the caller's to carry)."""
        self.decoder.reset()
        self.assembler.reset()
        self.stream.reset()
        self._tick_nodes.clear()
        self._completed.clear()

    def tick(self, ans_type: int, items: list):
        """One fused-dispatch-equivalent frame batch.  Returns
        ``(combined, pushed, revolutions)``: the reconstructed sweep
        plane, whether this tick pushed a segment, and a list of
        ``(angle', dist', scan_dict)`` de-skewed completed revolutions
        in completion order."""
        self._tick_nodes.clear()
        self._completed.clear()
        self.decoder.on_measurement_batch(int(ans_type), list(items))
        if self._tick_nodes:
            parts = list(zip(*self._tick_nodes))
            a, d, q = (np.concatenate(p) for p in parts[:3])
        else:
            a = d = q = np.zeros((0,), np.int32)
        combined, pushed = self.stream.tick(a, d, q)
        revs = []
        for scan in self._completed:
            ca, cd, cq, cf = wire_clamp_np(
                scan["angle_q14"], scan["dist_q2"],
                scan["quality"], scan["flag"],
            )
            a2, d2 = self.stream.revolution(ca, cd)
            revs.append((a2, d2, {**scan, "quality": cq, "flag": cf}))
        return combined, pushed, revs
