"""Correlative scan-to-map matching + log-odds occupancy mapping kernels.

The SLAM front-end the FPGA accelerator papers build custom hardware for
(arxiv 2103.09523, 2006.01050): dense multi-resolution correlative scan
matching against a persistent occupancy grid.  On TPU the same workload
is a natural ``jit``+``vmap`` dense-scoring problem: rotate/translate the
scan's Cartesian endpoints over a (dθ, dx, dy) pose lattice, gather
bilinear map lookups, argmax — one compiled program per revolution, with
a vmapped fleet lowering so N streams match against N maps in ONE
dispatch (mapping/mapper.FleetMapper).

EXACTNESS CONTRACT (the reason everything here is integer):

The mapper ships two backends — a NumPy host reference (the golden path,
ops/scan_match_ref.py) and this fused device path — and the fleet parity
suite pins them BIT-EXACT (tests/test_mapping.py, fleet sizes 1/3/8).
Float scoring cannot honor that bar: XLA and NumPy order reductions
differently and XLA:CPU fuses mul+add into FMA, so f32 scores drift by
ulps and argmax ties flip.  Instead the whole matcher datapath is
fixed-point — exactly the move the FPGA accelerator papers make for
their hardware scoring pipelines:

  * endpoints quantize to int32 SUBCELL coordinates (SUB=32 subcells per
    map cell; ONE f32 multiply + round-half-even, deterministic on every
    backend because a single IEEE op cannot be re-associated or fused);
  * rotations use a precomputed int32 cos/sin table at 2^14 scale
    (numpy-built once per config, shared verbatim by both backends — no
    in-kernel transcendentals to diverge between libms);
  * the "bilinear map lookup" is 4 integer gathers with 5-bit fractional
    weights (Σw = 1024), summed in int32;
  * the log-odds grid itself is int32 in Q10 (1/1024) units with integer
    hit/miss increments and clamping;
  * argmax over int32 scores, first-max-wins in C order (jnp.argmax and
    np.argmax agree).

Arithmetic bounds (so int32 never overflows): subcell coords are clamped
to ±(2^15 - 1) before rotation (|c·x - s·y| ≤ 2·2^15·2^14 = 2^30); map
values are clipped to [0, clamp_q] and right-shifted by ``quant_shift``,
chosen per config so (clamp_q >> quant_shift)·1024·beams < 2^31.

Because the datapath is int32 end to end — and int32 addition is
associative and commutative even at wrap-around — ANY evaluation order
produces bit-identical results.  That is what lets the matcher carry a
second lowering: ``MapConfig.match_backend`` routes the score volume
and the log-odds update through either the jnp arm in this module
("xla") or the VMEM-tiled Pallas kernels ("pallas",
ops/pallas_scan_match.py, interpret mode off-TPU), with the argmax and
accept/assemble epilogues shared so first-max-wins tie-breaking is
structurally backend-independent.  tests/test_pallas_scan_match.py pins
all three implementations (xla / pallas / numpy) byte-for-byte.

The occupancy update reuses the voxel-accumulation machinery's two
kernel shapes — a scatter-add histogram and the one-hot bf16 einsum with
f32 accumulation that rides the MXU (ops/filters.voxel_hits /
voxel_hits_matmul) — re-derived for integer cell indices, because the
float entry points would double-round the cell index the matcher's
fixed-point gathers use.  Both lowerings are exact and parity-tested;
``MapConfig.voxel_backend`` selects, via the same resolver as the filter
chain's.
"""

from __future__ import annotations

import dataclasses
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np

# fixed-point geometry (see module docstring for the overflow analysis)
SUB_BITS = 5
SUB = 1 << SUB_BITS            # subcells per map cell
ANG_BITS = 14
ANG = 1 << ANG_BITS            # rotation-table scale
LO_SCALE = 1024                # log-odds Q10 fixed point (1/1024 units)
W_SCALE = SUB * SUB            # bilinear weight denominator (Σw)
PQ_LIMIT = (1 << 15) - 1       # subcell clamp ahead of the int32 rotation

MAP_STATE_VERSION = 1          # checkpoint schema version of MapState


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class MapState:
    """Device-resident per-stream SLAM state, threaded functionally like
    FilterState.  ``log_odds`` is Q10 fixed point (int32, 1/1024 units);
    ``pose`` is (tx_sub, ty_sub, theta_idx) int32 — translation in
    subcells, heading as an index into the ``theta_divisions``-entry
    rotation table (so heading composition stays exact integer math and
    never needs an in-kernel transcendental)."""

    log_odds: jax.Array   # (G, G) int32, Q10 log-odds, [ix, iy] layout
    pose: jax.Array       # (3,) int32: tx_sub, ty_sub, theta_idx
    origin_xy: jax.Array  # (2,) float32 world coords of the grid centre
    revision: jax.Array   # () int32, revolutions absorbed

    @staticmethod
    def shapes(grid: int) -> dict[str, tuple[int, ...]]:
        """Array shapes for a map of this geometry — host-side, no
        allocation (checkpoint pre-validation, like FilterState.shapes)."""
        return {
            "log_odds": (grid, grid),
            "pose": (3,),
            "origin_xy": (2,),
            "revision": (),
        }

    @classmethod
    def create(cls, cfg: "MapConfig") -> "MapState":
        return cls(
            log_odds=jnp.zeros((cfg.grid, cfg.grid), jnp.int32),
            pose=jnp.zeros((3,), jnp.int32),
            origin_xy=jnp.zeros((2,), jnp.float32),
            revision=jnp.asarray(0, jnp.int32),
        )


@dataclasses.dataclass(frozen=True)
class MapConfig:
    """Static (compile-time) mapping + matcher configuration."""

    grid: int = 256            # cells per side of the log-odds grid
    cell_m: float = 0.05       # metres per cell
    beams: int = 2048          # points per scan (the chain's beam grid)
    hit_q: int = 922           # Q10 log-odds increment per endpoint hit
    miss_q: int = -410         # Q10 decrement per free-space pass
    clamp_q: int = 8192        # Q10 clamp (±) on the log-odds grid
    theta_divisions: int = 720 # rotation-table entries over a full turn
    theta_window: int = 6      # match search: ± table steps
    coarse: int = 4            # pyramid pool factor (power of two)
    window_cells: int = 2      # coarse translation radius (coarse cells)
    fine_radius: int = 4       # fine translation radius (cells)
    free_samples: int = 4      # ray samples for the free-space miss pass
    # Q10 per-revolution log-odds decay toward zero (dynamic scenes:
    # stale moving-obstacle cells fade even when no ray revisits them).
    # 0 disables — and the gate is STATIC Python, so a decay-off config
    # traces the byte-identical program the pre-decay tree compiled
    # (the deskew-plane discipline: an off feature costs nothing)
    decay_q: int = 0
    quant_shift: int = 4       # match-map right shift (int32 score bound)
    voxel_backend: str = "scatter"  # endpoint histogram: scatter | matmul
    # score-volume + log-odds-update lowering: "xla" (the jnp arm below)
    # or "pallas" (ops/pallas_scan_match VMEM-tiled kernels, interpret
    # mode off-TPU via _lowering_dispatch).  Bit-exact either way — the
    # int32 datapath makes evaluation order irrelevant — so the seam is
    # purely a performance choice (resolve_match_backend in
    # mapping/mapper.py holds the auto mapping and its evidence bar).
    match_backend: str = "xla"

    def __post_init__(self):
        if self.grid < 8 or self.grid > 1024:
            raise ValueError("map grid must be within [8, 1024]")
        if self.coarse < 1 or self.coarse & (self.coarse - 1):
            raise ValueError("coarse pool factor must be a power of two")
        if self.grid % self.coarse:
            raise ValueError("map grid must divide by the coarse factor")
        if self.cell_m <= 0:
            raise ValueError("map cell size must be positive")
        if self.hit_q <= 0 or self.miss_q >= 0 or self.clamp_q <= 0:
            raise ValueError(
                "log-odds increments must satisfy hit > 0 > miss, clamp > 0"
            )
        if self.clamp_q < self.hit_q:
            raise ValueError("log-odds clamp must be >= the hit increment")
        if self.decay_q < 0 or self.decay_q > self.clamp_q:
            raise ValueError(
                "log-odds decay must satisfy 0 <= decay_q <= clamp_q "
                "(0 disables; anything past the clamp is meaningless)"
            )
        if self.theta_window >= self.theta_divisions // 2:
            raise ValueError("theta window exceeds half a turn")
        if self.match_backend not in ("xla", "pallas"):
            raise ValueError(
                "match_backend must be 'xla' or 'pallas' once resolved "
                "(the 'auto' spelling resolves in mapping/mapper."
                "resolve_match_backend before MapConfig is built)"
            )
        # int32 score bound: per-point ≤ (clamp>>shift)·1024, summed over
        # beams — must stay under 2^31 (module docstring)
        if (self.clamp_q >> self.quant_shift) * W_SCALE * self.beams >= 2**31:
            raise ValueError(
                "match score can overflow int32: raise quant_shift "
                f"(clamp_q={self.clamp_q}, beams={self.beams})"
            )

    @property
    def sub_per_m(self) -> float:
        """The ONE metres -> subcells constant, materialized identically
        (f32) by both backends so the single quantizing multiply agrees."""
        return float(np.float32(SUB / self.cell_m))

    @property
    def t_limit_sub(self) -> int:
        """Pose translation clamp: the sensor stays inside the map."""
        return (self.grid // 2) * SUB


def min_quant_shift(clamp_q: int, beams: int) -> int:
    """Smallest match-map shift keeping the int32 score bound (shared by
    the config factory so defaults can't silently overflow)."""
    s = 0
    while (clamp_q >> s) * W_SCALE * beams >= 2**31:
        s += 1
    return s


@functools.lru_cache(maxsize=8)
def rotation_table(divisions: int) -> np.ndarray:
    """(divisions, 2) int32 [cos, sin] at ANG scale — numpy-built once
    and shared VERBATIM by the numpy reference and the jitted kernels
    (where it bakes in as a constant), so no backend ever evaluates a
    transcendental inside the parity-critical datapath."""
    # graftlint: disable=GL005 — deliberate f64 HOST-side table build;
    # both backends consume the resulting int32 table verbatim, so the
    # float math here can never reach the parity-critical datapath
    k = np.arange(divisions, dtype=np.float64) * (2.0 * np.pi / divisions)
    # graftlint: policed — |cos|,|sin| <= 1 so rint(· * 2^14) is within
    # ±2^14, exactly representable and in int32 range on every backend
    return np.stack(
        [np.rint(np.cos(k) * ANG), np.rint(np.sin(k) * ANG)], axis=1
    ).astype(np.int32)


def theta_offsets(cfg: MapConfig) -> np.ndarray:
    """(T,) int32 search offsets in rotation-table steps."""
    w = cfg.theta_window
    return np.arange(-w, w + 1, dtype=np.int32)


# ---------------------------------------------------------------------------
# fixed-point building blocks (each has a literal numpy mirror in
# ops/scan_match_ref.py — keep the two in lockstep, the parity suite
# pins them bit-exact)
# ---------------------------------------------------------------------------


def quantize_points(xy: jax.Array, mask: jax.Array, cfg: MapConfig):
    """f32 metres -> int32 subcell coords + validity.  The one f32 op of
    the datapath: a single multiply (deterministic — nothing to fuse or
    re-associate) then round-half-even.

    Range and finiteness are policed IN FLOAT SPACE, before the int
    cast: converting an out-of-range/NaN/inf f32 to int32 is
    implementation-defined and NumPy and XLA disagree on it, which
    would break the bit-exactness contract through the back door.  The
    cast only ever sees values clamped into ±PQ_LIMIT; points beyond
    that window (≥ 1023 cells from the sensor — off any permitted map)
    are invalidated (a NaN coordinate fails the <= compare on both
    backends)."""
    s = xy * jnp.float32(cfg.sub_per_m)
    lim = jnp.float32(PQ_LIMIT)
    ok = (
        mask
        & (jnp.abs(s[:, 0]) <= lim)
        & (jnp.abs(s[:, 1]) <= lim)
    )
    s = jnp.where(jnp.isfinite(s), s, 0.0)
    # graftlint: policed — the docstring's whole point: NaN/inf zeroed
    # and the value clamped into ±PQ_LIMIT in FLOAT space above, so the
    # cast never sees an implementation-defined conversion
    pq = jnp.round(jnp.clip(s, -lim, lim)).astype(jnp.int32)
    return pq, ok


def rotate_rows(x, y, cos_q, sin_q):
    """Fixed-point rotation of split x/y coordinate planes: (c·x - s·y)
    at ANG scale, rounded back to subcells.  THE one rotation core —
    `rotate_points` and the Pallas kernels both call it, so the rounding
    contract cannot drift between the matcher backends."""
    half = 1 << (ANG_BITS - 1)
    xr = (cos_q * x - sin_q * y + half) >> ANG_BITS
    yr = (sin_q * x + cos_q * y + half) >> ANG_BITS
    return xr, yr


def rotate_points(pq: jax.Array, cos_q, sin_q):
    """Fixed-point rotation of packed (…, 2) points — `rotate_rows` on
    the unpacked planes.  Broadcasts over leading axes of cos_q/sin_q."""
    return rotate_rows(pq[..., 0], pq[..., 1], cos_q, sin_q)


def _bilinear_gather(mf: jax.Array, gdim: int, ix, iy, fx, fy):
    """Integer bilinear lookup on a flattened [ix, iy] map: 4 gathers
    with 5-bit fractional weights (Σw = 1024); out-of-bounds corners
    contribute 0.  ``ix/iy`` are cell indices (any broadcastable int32
    shape), ``fx/fy`` the subcell fractions in [0, SUB)."""
    total = jnp.zeros(jnp.broadcast_shapes(ix.shape, fx.shape), jnp.int32)
    for dx_c, dy_c in ((0, 0), (1, 0), (0, 1), (1, 1)):
        cx, cy = ix + dx_c, iy + dy_c
        ok = (cx >= 0) & (cx < gdim) & (cy >= 0) & (cy < gdim)
        idx = jnp.clip(cx, 0, gdim - 1) * gdim + jnp.clip(cy, 0, gdim - 1)
        val = jnp.where(ok, jnp.take(mf, idx), 0)
        wx = SUB - fx if dx_c == 0 else fx
        wy = SUB - fy if dy_c == 0 else fy
        total = total + wx * wy * val
    return total


def cell_hits(cells_x, cells_y, inb, grid: int) -> jax.Array:
    """(G, G) int32 endpoint counts from integer cell indices — the
    scatter-add twin of ops/filters.voxel_hits (same flat-index drop
    trick), taking the fixed-point datapath's cells directly so the
    histogram and the matcher's gathers share ONE cell convention."""
    flat = jnp.where(inb, cells_x * grid + cells_y, grid * grid)
    counts = jnp.zeros((grid * grid,), jnp.int32).at[flat].add(1, mode="drop")
    return counts.reshape(grid, grid)


def cell_hits_matmul(cells_x, cells_y, inb, grid: int) -> jax.Array:
    """The MXU-riding twin (ops/filters.voxel_hits_matmul restated for
    integer cells): one-hot bf16 outer-product accumulation in f32 —
    exact to 2^24 hits per cell, bit-identical to :func:`cell_hits`."""
    cells = jnp.arange(grid, dtype=jnp.int32)
    ohx = ((cells_x[:, None] == cells[None, :]) & inb[:, None]).astype(
        jnp.bfloat16
    )
    ohy = (cells_y[:, None] == cells[None, :]).astype(jnp.bfloat16)
    # graftlint: disable=GL004 — the one sanctioned float accumulation
    # (ops/filters.voxel_hits_matmul note): 0/1 one-hot products are
    # exact and f32 accumulation is exact below 2^24 counts
    counts = jnp.einsum(
        "bi,bj->ij", ohx, ohy, preferred_element_type=jnp.float32
    )
    # graftlint: policed — exact small integers in f32 (see above)
    return counts.astype(jnp.int32)


def select_cell_hits(backend: str):
    """voxel_backend -> integer-cell histogram kernel (strict, like
    ops/filters.select_voxel_hits — a typo must fail loudly)."""
    try:
        return {"scatter": cell_hits, "matmul": cell_hits_matmul}[backend]
    except KeyError:
        raise ValueError(
            f"voxel_backend must be 'scatter' or 'matmul' once resolved, "
            f"got {backend!r}"
        ) from None


# ---------------------------------------------------------------------------
# matcher + map update
# ---------------------------------------------------------------------------


def _theta_trig(pose: jax.Array, cfg: MapConfig):
    """(T,) int32 cos/sin rotation-table rows of the θ search candidates
    around ``pose`` — the one place both matcher backends read the
    table, so the candidate set cannot drift between them."""
    table = jnp.asarray(rotation_table(cfg.theta_divisions))
    dth = jnp.asarray(theta_offsets(cfg))                       # (T,)
    th_idx = jnp.mod(pose[2] + dth, cfg.theta_divisions)
    return jnp.take(table[:, 0], th_idx), jnp.take(table[:, 1], th_idx)


def match_coarse_scores(
    log_odds: jax.Array, pose: jax.Array, pq: jax.Array, ok: jax.Array,
    cfg: MapConfig,
):
    """Coarse TRANSLATION-ONLY sweep at the predicted heading: the match
    map (positive log-odds, quantized) is max-pooled by ``cfg.coarse``
    and every coarse (dx, dy) candidate scored with bilinear gathers.
    The pooled map upper-bounds the fine map (the standard correlative
    pyramid), and rotation deliberately stays OUT of this stage: inside
    the search window a dθ of a few table steps displaces endpoints by
    well under one coarse cell, so a pooled map cannot discriminate θ —
    it can only mis-seed the refinement (a hazard the golden rotation
    tests pin).

    Returns ``(ctx, score_c)``: the (U, V) int32 coarse score plane and
    a backend-specific context tuple the fine stage reuses (quantized
    map forms and, on the XLA arm, the rotated candidate planes).  Both
    backends produce bit-identical ``score_c`` — int32 end to end."""
    g, c = cfg.grid, cfg.coarse
    gc = g // c
    clog = int(math.log2(c))
    center = (g // 2) * SUB
    cos_q, sin_q = _theta_trig(pose, cfg)                       # (T,)
    t_mid = cfg.theta_window                                    # the dθ=0 row
    w = cfg.window_cells

    if cfg.match_backend == "pallas":
        from rplidar_ros2_driver_tpu.ops.pallas_scan_match import (
            coarse_scores_pallas,
        )

        posec = pose[:2] + center
        mq, score_c = coarse_scores_pallas(
            log_odds, pq, ok, posec, cos_q[t_mid], sin_q[t_mid], cfg
        )
        return (mq,), score_c

    mq = jnp.clip(log_odds, 0, cfg.clamp_q) >> cfg.quant_shift
    mc = mq.reshape(gc, c, gc, c).max(axis=(1, 3))
    mq_f, mc_f = mq.reshape(-1), mc.reshape(-1)
    rx, ry = rotate_points(pq[None, :, :], cos_q[:, None], sin_q[:, None])
    bx = rx + pose[0] + center                                  # world subcells
    by = ry + pose[1] + center

    # -- coarse: predicted heading only; subcell coords at coarse scale
    # (SUB subcells per coarse cell), translations = whole coarse cells
    # so only the cell index shifts and the bilinear fraction is shared
    # across candidates
    scx, scy = bx[t_mid] >> clog, by[t_mid] >> clog             # (B,)
    ccx, ccy = scx >> SUB_BITS, scy >> SUB_BITS
    cfx, cfy = scx & (SUB - 1), scy & (SUB - 1)
    shifts = jnp.arange(-w, w + 1, dtype=jnp.int32)             # (U,)
    ix = ccx[:, None, None] + shifts[None, :, None]             # (B, U, 1)
    iy = ccy[:, None, None] + shifts[None, None, :]             # (B, 1, V)
    vals = _bilinear_gather(
        mc_f, gc, ix, iy, cfx[:, None, None], cfy[:, None, None]
    )                                                           # (B, U, V)
    score_c = jnp.sum(
        jnp.where(ok[:, None, None], vals, 0), axis=0
    )                                                           # (U, V)
    return (mq_f, bx, by), score_c


def match_fine_scores(
    ctx: tuple, pose: jax.Array, pq: jax.Array, ok: jax.Array,
    u_best: jax.Array, v_best: jax.Array, cfg: MapConfig,
):
    """Fine JOINT (dθ, dx, dy) stage at full resolution around the
    coarse winner: every θ candidate re-rotates the scan and scores a
    ±fine_radius cell window; the subcell bilinear fractions resolve
    the sub-cell endpoint shifts a single θ step causes.  Greedy
    single-seed refinement rather than the papers' full
    branch-and-bound — sufficient to recover lattice-resolution offsets
    (golden tests) at a fraction of the search.

    Returns the (T, F, F) int32 score volume in C order (θ, du, dv) —
    the layout both backends reproduce exactly, so the shared
    first-max-wins argmax downstream cannot diverge."""
    c = cfg.coarse
    r = cfg.fine_radius

    if cfg.match_backend == "pallas":
        from rplidar_ros2_driver_tpu.ops.pallas_scan_match import (
            fine_scores_pallas,
        )

        (mq,) = ctx
        center = (cfg.grid // 2) * SUB
        cos_q, sin_q = _theta_trig(pose, cfg)
        posec = pose[:2] + center
        return fine_scores_pallas(
            mq, pq, ok, posec, cos_q, sin_q, u_best, v_best, cfg
        )

    mq_f, bx, by = ctx
    fbx = bx + u_best * (c * SUB)                               # (T, B)
    fby = by + v_best * (c * SUB)
    fcx, fcy = fbx >> SUB_BITS, fby >> SUB_BITS
    ffx, ffy = fbx & (SUB - 1), fby & (SUB - 1)
    fsh = jnp.arange(-r, r + 1, dtype=jnp.int32)
    fix = fcx[:, :, None, None] + fsh[None, None, :, None]      # (T, B, F, 1)
    fiy = fcy[:, :, None, None] + fsh[None, None, None, :]      # (T, B, 1, F)
    fvals = _bilinear_gather(
        mq_f, cfg.grid, fix, fiy,
        ffx[:, :, None, None], ffy[:, :, None, None],
    )                                                           # (T, B, F, F)
    return jnp.sum(
        jnp.where(ok[None, :, None, None], fvals, 0), axis=1
    )                                                           # (T, F, F)


def match_scan_volumes(
    log_odds: jax.Array, pose: jax.Array, pq: jax.Array, ok: jax.Array,
    cfg: MapConfig,
):
    """The matcher's shared score-volume core: coarse translation sweep
    (:func:`match_coarse_scores`), first-max-wins argmax seed, joint
    full-resolution refinement (:func:`match_fine_scores`), raw-delta
    decode.  ``cfg.match_backend`` selects the lowering (XLA arm or the
    VMEM-tiled Pallas kernels); both arms land bit-identical volumes,
    and the argmaxes live HERE in shared code, so tie-breaking is
    structurally backend-independent.

    Returns ``(dpose_raw, best, minv)``: the UNGATED argmax delta
    ((3,) int32 [dx_sub, dy_sub, dθ_steps]), the best fine score, and
    the fine volume's minimum — the peak-contrast statistic the
    loop-closure gates consume (ops/loop_close.py); :func:`match_scan`
    applies the front-end accept epilogue on top."""
    c = cfg.coarse
    w = cfg.window_cells
    r = cfg.fine_radius
    dth = jnp.asarray(theta_offsets(cfg))                       # (T,)

    ctx, score_c = match_coarse_scores(log_odds, pose, pq, ok, cfg)

    nu = 2 * w + 1
    kbest = jnp.argmax(score_c.reshape(-1)).astype(jnp.int32)
    u_best = kbest // nu - w                                    # coarse cells
    v_best = kbest % nu - w

    score_f = match_fine_scores(ctx, pose, pq, ok, u_best, v_best, cfg)

    nf = 2 * r + 1
    fbest = jnp.argmax(score_f.reshape(-1)).astype(jnp.int32)
    t_best = fbest // (nf * nf)
    du = (fbest // nf) % nf - r
    dv = fbest % nf - r
    best = jnp.max(score_f)
    minv = jnp.min(score_f)

    dpose_raw = jnp.stack([
        (u_best * c + du) * SUB,
        (v_best * c + dv) * SUB,
        jnp.take(dth, t_best),
    ])
    return dpose_raw, best, minv


def match_scan(
    log_odds: jax.Array, pose: jax.Array, pq: jax.Array, ok: jax.Array,
    cfg: MapConfig,
):
    """Dense multi-resolution correlative match of one quantized scan
    against the map, searching a (dθ, dx, dy) lattice around ``pose``
    (:func:`match_scan_volumes`) with the front-end accept/assemble
    epilogue.

    Returns (dpose (3,) int32 [dx_sub, dy_sub, dθ_steps], score, n_valid).
    An empty or informationless window (best score ≤ 0 — e.g. a fresh
    map, or an all-invalid scan) yields the identity delta.
    """
    dpose_raw, best, _minv = match_scan_volumes(log_odds, pose, pq, ok, cfg)
    accept = best > 0
    dpose = jnp.where(accept, dpose_raw, jnp.zeros((3,), jnp.int32))
    n_valid = jnp.sum(ok.astype(jnp.int32))
    return dpose, jnp.where(accept, best, 0), n_valid


def update_map(
    log_odds: jax.Array, pose: jax.Array, pq: jax.Array, ok: jax.Array,
    cfg: MapConfig,
):
    """Log-odds occupancy update from one scan at ``pose``: endpoint
    cells get ``hit_q``, ray-sampled free cells ``miss_q`` (unless also
    hit this revolution), clamped to ±clamp_q.  The free pass samples
    each ray at integer fractions k/S (k < S, endpoint excluded) —
    the dense-sampling stand-in for exact ray tracing, one histogram per
    sample index, all inside the fused program.

    ``cfg.match_backend`` routes the whole update through the Pallas
    one-hot/matmul kernel (ops/pallas_scan_match.log_odds_update_pallas)
    or the jnp arm below; both are bit-identical to the NumPy reference
    (integer counts, integer increments — nothing order-sensitive).

    ``cfg.decay_q`` (when nonzero) first shrinks every cell toward zero
    by that Q10 amount — stale dynamic-obstacle evidence fades even in
    cells no ray revisits.  Applied BEFORE the backend branch so both
    arms inherit it identically; the gate is static Python, so the
    default decay_q=0 program is byte-identical to the pre-decay one."""
    g = cfg.grid
    center = (g // 2) * SUB
    if cfg.decay_q:
        mag = jnp.maximum(jnp.abs(log_odds) - cfg.decay_q, 0)
        log_odds = jnp.sign(log_odds) * mag
    table = jnp.asarray(rotation_table(cfg.theta_divisions))
    cos_q = jnp.take(table[:, 0], pose[2])
    sin_q = jnp.take(table[:, 1], pose[2])

    if cfg.match_backend == "pallas":
        from rplidar_ros2_driver_tpu.ops.pallas_scan_match import (
            log_odds_update_pallas,
        )

        posec = pose[:2] + center
        return log_odds_update_pallas(
            log_odds, pq, ok, posec, cos_q, sin_q, cfg
        )

    wx, wy = rotate_points(pq, cos_q, sin_q)
    wx, wy = wx + pose[0] + center, wy + pose[1] + center       # (B,)

    hits_fn = select_cell_hits(cfg.voxel_backend)
    cx, cy = wx >> SUB_BITS, wy >> SUB_BITS
    inb = ok & (cx >= 0) & (cx < g) & (cy >= 0) & (cy < g)
    hits = hits_fn(cx, cy, inb, g)

    if cfg.free_samples > 0:
        ox, oy = pose[0] + center, pose[1] + center             # sensor
        free = jnp.zeros((g, g), jnp.int32)
        for k in range(cfg.free_samples):
            sx = ox + ((wx - ox) * k) // cfg.free_samples
            sy = oy + ((wy - oy) * k) // cfg.free_samples
            fx_c, fy_c = sx >> SUB_BITS, sy >> SUB_BITS
            finb = ok & (fx_c >= 0) & (fx_c < g) & (fy_c >= 0) & (fy_c < g)
            free = free + hits_fn(fx_c, fy_c, finb, g)
        i_miss = (free > 0) & ~(hits > 0)
    else:
        i_miss = jnp.zeros((g, g), bool)

    delta = (
        jnp.where(hits > 0, cfg.hit_q, 0)
        + jnp.where(i_miss, cfg.miss_q, 0)
    )
    return jnp.clip(log_odds + delta, -cfg.clamp_q, cfg.clamp_q)


def _map_match_step_impl(
    state: MapState, points_xy: jax.Array, mask: jax.Array, live: jax.Array,
    cfg: MapConfig,
):
    """One revolution: match against the map built so far, compose the
    accepted delta into the pose, then absorb the scan at the new pose.
    ``live`` (int32 0/1) gates everything — an idle stream's state
    passes through untouched, which is what lets the fleet lowering run
    ragged fleets in lockstep."""
    pq, ok = quantize_points(points_xy, mask, cfg)
    ok = ok & (live > 0)
    dpose, score, n_valid = match_scan(state.log_odds, state.pose, pq, ok, cfg)
    lim = cfg.t_limit_sub
    pose = jnp.stack([
        jnp.clip(state.pose[0] + dpose[0], -lim, lim),
        jnp.clip(state.pose[1] + dpose[1], -lim, lim),
        jnp.mod(state.pose[2] + dpose[2], cfg.theta_divisions),
    ])
    log_odds = update_map(state.log_odds, pose, pq, ok, cfg)
    alive = live > 0
    new_state = MapState(
        log_odds=jnp.where(alive, log_odds, state.log_odds),
        pose=jnp.where(alive, pose, state.pose),
        origin_xy=state.origin_xy,
        revision=state.revision + live,
    )
    # single-fetch wire: pose + score + matched-point count, one int32 row
    wire = jnp.concatenate([
        new_state.pose, score[None], n_valid[None]
    ]).astype(jnp.int32)
    return new_state, wire


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def map_match_step(
    state: MapState, points_xy: jax.Array, mask: jax.Array, live: jax.Array,
    cfg: MapConfig,
):
    """Single-stream fused match+update: one donated dispatch per
    revolution, one (5,) int32 wire out [tx_sub, ty_sub, th_idx, score,
    n_valid]."""
    return _map_match_step_impl(state, points_xy, mask, live, cfg)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def fleet_map_match_step(
    states: MapState, points_xy: jax.Array, masks: jax.Array,
    live: jax.Array, cfg: MapConfig,
):
    """The fleet lowering: N streams match against N maps in ONE
    compiled vmapped dispatch (stream-stacked MapState donated in
    place).  Bit-exact vs N independent host-reference steps — integer
    datapath end to end, so vmap cannot perturb a single bit."""

    def one(st, p, m, lv):
        return _map_match_step_impl(st, p, m, lv, cfg)

    return jax.vmap(one)(states, points_xy, masks, live)


def unpack_wire(wire: np.ndarray) -> dict:
    """Host-side view of one stream's (5,) int32 wire row."""
    w = np.asarray(wire)
    return {
        "pose_q": w[:3].astype(np.int32),
        "score": int(w[3]),
        "n_valid": int(w[4]),
    }


def pose_to_metric(pose_q: np.ndarray, cfg: MapConfig) -> tuple:
    """(x_m, y_m, theta_rad) floats from the integer pose — reporting
    only, never part of the parity-critical datapath."""
    x = float(pose_q[0]) * (cfg.cell_m / SUB)
    y = float(pose_q[1]) * (cfg.cell_m / SUB)
    th = float(pose_q[2]) * (2.0 * np.pi / cfg.theta_divisions)
    if th > np.pi:
        th -= 2.0 * np.pi
    return x, y, th
