"""On-device loop closure: submap library + batched candidate matching.

The SLAM back-end's front half (ROADMAP item 2, after "A Universal
LiDAR SLAM Accelerator System on Low-cost FPGA"): the correlative
matcher (ops/scan_match.py) is a front-end only — pose drift is
unbounded — so every ``submap_revs`` revolutions a stream's MapState is
FINALIZED into a quantized submap plane (``clip(log_odds, 0, clamp) >>
quant_shift`` — the exact match-map form the matcher's score engines
consume, whose coarse max-pooled pyramid level the engines already
materialize in-kernel at ops/scan_match.py:384) and stored in a
device-resident library with its anchor pose.  A closure check then
matches the CURRENT scan window against the K nearest submaps in ONE
vmapped dispatch, reusing the matcher's score-volume engines verbatim —
``match_backend`` routes each candidate through either the XLA arm or
the PR 8 VMEM-tiled Pallas kernels (interpret mode on CPU), so the
candidate scorer inherits the kernel A/B for free.

Acceptance gates (all integer, all policed):

  * overlap   — ``n_valid >= min_points`` quantized endpoints entered;
  * absolute  — ``best >= n_valid * accept_q`` (a per-point score bar;
    ``accept_q * beams < 2^31`` is validated so the product is safe);
  * contrast  — ``best - min(volume) >= best >> peak_shift``: a
    saturated or featureless submap scores FLAT across the whole
    (dθ, dx, dy) volume, so peak-minus-floor contrast rejects the
    false-positive class an absolute bar cannot (the degenerate suite
    pins this).

An accepted match becomes an inter-pose constraint between the newest
submap anchor and the matched one (the transient current pose is
eliminated through the local odometry leg, so the graph lives over the
fixed submap node set), appended into the dense padded constraint
plane; the fixed-point pose-graph relaxation (ops/pose_graph.py) then
runs INSIDE THE SAME compiled program — a closure check costs exactly
one dispatch, matcher through solver.

Everything is int32 end to end in the established Q-format discipline
(subcell translations, 2^14 rotation tables, explicit overflow bounds),
so the NumPy twin (ops/loop_close_ref.py) is BIT-EXACT against the
single-stream and vmapped fleet lowerings — not close, byte-equal
(tests/test_loop_close.py, fleet sizes 1/3/8, snapshot/restore paths).
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from rplidar_ros2_driver_tpu.ops.pose_graph import (
    PoseGraphConfig,
    pose_compose,
    pose_relative,
    rel_inverse,
    solve_pose_graph_impl,
)
from rplidar_ros2_driver_tpu.ops.scan_match import (
    MapConfig,
    match_scan_volumes,
    quantize_points,
    rotation_table,
)

LOOP_STATE_VERSION = 1
ODOM_WEIGHT = 1                # odometry-chain constraint weight
WIRE_LEN = 9                   # per-stream closure-check wire row length


@dataclasses.dataclass(frozen=True)
class LoopConfig:
    """Static loop-closure configuration.  ``match`` is the DERIVED
    candidate-match MapConfig (quant_shift 0, clamp at the stored
    plane's ceiling — submap planes are pre-quantized at finalize, so
    the matcher's in-kernel ``clip >> shift`` is the identity on them);
    ``graph`` sizes the solver's dense padded planes."""

    match: MapConfig
    graph: PoseGraphConfig
    submap_revs: int = 8       # revolutions between submap finalizations
    max_submaps: int = 8       # library capacity (= pose-graph nodes)
    check_revs: int = 4        # revolutions between closure checks
    candidates: int = 2        # K nearest submaps scored per check
    max_constraints: int = 16  # loop-constraint plane capacity
    exclude_recent: int = 1    # newest submaps never offered as candidates
    min_points: int = 32       # overlap gate: quantized endpoints required
    accept_q: int = 60000      # absolute gate: per-point score bar
    peak_shift: int = 3        # contrast gate: best-minus-floor >= best>>s
    weight: int = 4            # loop-constraint weight (odometry is 1)
    reanchor: bool = False     # rewrite anchors/front-end pose on accept

    def __post_init__(self):
        if self.submap_revs < 1:
            raise ValueError("submap_revs must be >= 1")
        if self.max_submaps < 2:
            raise ValueError(
                "loop closure needs >= 2 submap slots (one to close "
                "against, one to close from)"
            )
        if self.check_revs < 1:
            raise ValueError("check_revs must be >= 1")
        if not (1 <= self.candidates <= self.max_submaps):
            raise ValueError(
                "candidates must be within [1, max_submaps]"
            )
        if self.exclude_recent < 1:
            raise ValueError(
                "exclude_recent must be >= 1 (a scan always matches the "
                "submap it was just absorbed into)"
            )
        if self.min_points < 1:
            raise ValueError("min_points must be >= 1")
        if self.accept_q < 1:
            raise ValueError("accept_q must be positive")
        # absolute-gate overflow bound: n_valid * accept_q in int32
        if self.accept_q * self.match.beams >= 2**31:
            raise ValueError(
                "accept gate can overflow int32: accept_q * beams "
                f"({self.accept_q} * {self.match.beams}) >= 2^31"
            )
        if not (0 <= self.peak_shift <= 30):
            raise ValueError("peak_shift must be within [0, 30]")
        if not (1 <= self.weight <= self.graph.weight_max):
            raise ValueError(
                "loop weight must be within [1, graph.weight_max]"
            )
        if self.max_constraints < 1:
            raise ValueError("max_constraints must be >= 1")
        if self.graph.max_nodes != self.max_submaps:
            raise ValueError(
                "pose-graph nodes must equal the submap capacity (the "
                "graph lives over the submap anchor set)"
            )
        if self.graph.max_constraints != self.max_submaps + self.max_constraints:
            raise ValueError(
                "graph.max_constraints must equal max_submaps + "
                "max_constraints (odometry chain rows + loop rows form "
                "one dense solver plane)"
            )
        if self.graph.theta_divisions != self.match.theta_divisions:
            raise ValueError(
                "solver and matcher must share one rotation table"
            )


def derive_match_config(
    map_cfg: MapConfig, *, theta_window: int, window_cells: int
) -> MapConfig:
    """The one base-map -> candidate-match MapConfig derivation: submap
    planes are stored ALREADY quantized (finalize applies ``clip(·, 0,
    clamp_q) >> quant_shift``), so the candidate config sets
    ``quant_shift=0`` with the clamp at the stored ceiling — the score
    engines' in-kernel quantization becomes the identity and the
    existing int32 score bound holds with the same margin.  The wider
    θ/translation windows are the loop-closure search radii (drift at
    re-visit time exceeds the front-end's per-revolution window)."""
    return dataclasses.replace(
        map_cfg,
        clamp_q=max(map_cfg.clamp_q >> map_cfg.quant_shift, 1),
        quant_shift=0,
        # unused by scoring, but MapConfig validates hit > 0 > miss and
        # clamp >= hit — the stored-plane ceiling can sit below the
        # base hit_q, so pin the increments to the minimal legal pair
        hit_q=1,
        miss_q=-1,
        theta_window=theta_window,
        window_cells=window_cells,
    )


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LoopState:
    """Device-resident per-stream loop-closure state, threaded
    functionally like MapState.  Dense padded planes throughout — one
    compiled program per (streams, max_submaps, max_constraints)
    bucket, whatever the live fill level."""

    planes: jax.Array   # (K, G, G) int32 quantized submap match planes
    anchors: jax.Array  # (K, 3) int32 anchor poses (tx_sub, ty_sub, θ_idx)
    odom: jax.Array     # (K, 3) int32 measured prev-anchor -> anchor
    valid: jax.Array    # (K,) int32 0/1 slot occupancy
    count: jax.Array    # () int32 submaps finalized
    cons: jax.Array     # (C, 6) int32 loop constraints [i,j,zx,zy,zθ,w]
    ncons: jax.Array    # () int32 appended loop constraints
    dropped: jax.Array  # () int32 accepts dropped at the C cap

    @staticmethod
    def shapes(cfg: "LoopConfig") -> dict[str, tuple[int, ...]]:
        """Array shapes — host-side, no allocation (checkpoint
        pre-validation, like MapState.shapes)."""
        k, g = cfg.max_submaps, cfg.match.grid
        c = cfg.max_constraints
        return {
            "planes": (k, g, g),
            "anchors": (k, 3),
            "odom": (k, 3),
            "valid": (k,),
            "count": (),
            "cons": (c, 6),
            "ncons": (),
            "dropped": (),
        }

    @classmethod
    def create(cls, cfg: "LoopConfig") -> "LoopState":
        shapes = cls.shapes(cfg)
        return cls(**{
            k: jnp.zeros(v, jnp.int32) for k, v in shapes.items()
        })


# ---------------------------------------------------------------------------
# submap install (finalize lands here; the quantize itself is host-side
# in mapping/submap.py so both backends share ONE finalization path)
# ---------------------------------------------------------------------------


def _install_submap_impl(state: LoopState, plane, anchor, cfg: LoopConfig):
    """Install one finalized submap into the next free slot: plane +
    anchor stored, the odometry leg from the previous anchor recorded
    (slot 0 records identity — node 0 is the gauge anchor).  A full
    library freezes (cap-and-hold): the graph's node indices must stay
    stable for the constraints that reference them."""
    k = cfg.max_submaps
    div = cfg.match.theta_divisions
    table = jnp.asarray(rotation_table(div))
    room = state.count < k
    slot = jnp.clip(state.count, 0, k - 1)
    prev = jnp.take(
        state.anchors, jnp.clip(state.count - 1, 0, k - 1), axis=0
    )
    first = state.count == 0
    odom_leg = jnp.where(
        first, jnp.zeros((3,), jnp.int32),
        pose_relative(prev, anchor, table, div),
    )
    sel = room

    def upd(arr, row):
        return jnp.where(sel, arr.at[slot].set(row), arr)

    return LoopState(
        planes=upd(state.planes, plane),
        anchors=upd(state.anchors, anchor),
        odom=upd(state.odom, odom_leg),
        valid=jnp.where(
            sel, state.valid.at[slot].set(1), state.valid
        ),
        count=state.count + sel,
        cons=state.cons,
        ncons=state.ncons,
        dropped=state.dropped,
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def fleet_install_submap(
    states: LoopState, idx, plane, anchor, cfg: LoopConfig
):
    """Install one stream's finalized submap into the stacked fleet
    state: row gather at device-scalar ``idx`` (one compiled program
    for every lane, utils/rowops discipline), the single-stream
    install, one dynamic-index row scatter (state donated)."""
    from jax import lax

    row = jax.tree_util.tree_map(
        lambda a: lax.dynamic_index_in_dim(a, idx, 0, keepdims=False),
        states,
    )
    row = _install_submap_impl(row, plane, anchor, cfg)
    return jax.tree_util.tree_map(
        lambda a, r: lax.dynamic_update_index_in_dim(a, r, idx, 0),
        states, row,
    )


# ---------------------------------------------------------------------------
# the closure check: batched candidate match -> gates -> constraint ->
# pose-graph relaxation, ONE program
# ---------------------------------------------------------------------------


def _loop_close_step_impl(
    state: LoopState, points_xy, mask, pose, cand_idx, check, cfg: LoopConfig,
):
    """One closure check for one stream.  ``cand_idx`` is the (Kc,)
    int32 host-selected candidate slot list (-1 = none — selection is a
    pure function of the anchor poses, host-side in both backends so it
    cannot diverge); ``check`` (int32 0/1) gates the whole step like
    the mapper's ``live``: a non-due stream's state passes through and
    its wire reads all-zero.

    Returns ``(new_state, wire, corrected)``: the threaded state, the
    (WIRE_LEN,) int32 wire row [accept, best_slot, best_score, n_valid,
    cur_x, cur_y, cur_θ, ncons, dropped] (cur_* = the pose-graph-
    corrected CURRENT pose), and the (K, 3) corrected anchor plane."""
    m = cfg.match
    k = cfg.max_submaps
    div = m.theta_divisions
    lim = m.t_limit_sub
    table = jnp.asarray(rotation_table(div))

    pq, ok = quantize_points(points_xy, mask, m)
    ok = ok & (check > 0)
    n_valid = jnp.sum(ok.astype(jnp.int32))

    # -- batched candidate matching: K nearest submaps, one vmap ------------
    slots = jnp.clip(cand_idx, 0, k - 1)
    cvalid = (cand_idx >= 0) & (jnp.take(state.valid, slots) > 0)
    planes = jnp.take(state.planes, slots, axis=0)              # (Kc, G, G)

    def one(plane):
        return match_scan_volumes(plane, pose, pq, ok, m)

    dposes, bests, minvs = jax.vmap(one)(planes)
    masked = jnp.where(cvalid, bests, jnp.int32(-(2**31) + 1))
    kc = jnp.argmax(masked).astype(jnp.int32)                   # first-max-wins
    best = jnp.take(masked, kc)
    dpose = jnp.take(dposes, kc, axis=0)
    minv = jnp.take(minvs, kc)
    best_slot = jnp.take(slots, kc)
    has_cand = jnp.any(cvalid)

    # -- acceptance gates (module docstring) --------------------------------
    accept = (
        (check > 0)
        & has_cand
        & (n_valid >= cfg.min_points)
        & (best > 0)
        & (best >= n_valid * cfg.accept_q)
        & ((best - minv) >= (best >> cfg.peak_shift))
    )

    # -- constraint emission: eliminate the transient current pose ----------
    # matched current pose in the submap's (world) frame
    p_m = jnp.stack([
        jnp.clip(pose[0] + dpose[0], -lim, lim),
        jnp.clip(pose[1] + dpose[1], -lim, lim),
        jnp.mod(pose[2] + dpose[2], div),
    ])
    last = jnp.clip(state.count - 1, 0, k - 1)
    a_last = jnp.take(state.anchors, last, axis=0)
    a_best = jnp.take(state.anchors, best_slot, axis=0)
    o_cur = pose_relative(a_last, pose, table, div)             # odometry leg
    z_jc = pose_relative(a_best, p_m, table, div)               # measured leg
    z_ij = pose_compose(                                        # last -> best
        o_cur, rel_inverse(z_jc, table, div), table, div
    )
    room = state.ncons < cfg.max_constraints
    do_append = accept & room
    row = jnp.concatenate([
        last[None], best_slot[None], z_ij,
        jnp.asarray([cfg.weight], jnp.int32),
    ]).astype(jnp.int32)
    slot_c = jnp.clip(state.ncons, 0, cfg.max_constraints - 1)
    cons = jnp.where(
        do_append, state.cons.at[slot_c].set(row), state.cons
    )
    ncons = state.ncons + do_append
    dropped = state.dropped + (accept & ~room)

    # -- pose-graph relaxation, same program --------------------------------
    ks = jnp.arange(k, dtype=jnp.int32)
    odom_w = ((ks >= 1) & (ks < state.count)).astype(jnp.int32) * ODOM_WEIGHT
    odom_rows = jnp.stack([
        jnp.maximum(ks - 1, 0), ks,
        state.odom[:, 0], state.odom[:, 1], state.odom[:, 2], odom_w,
    ], axis=1)                                                  # (K, 6)
    all_cons = jnp.concatenate([odom_rows, cons], axis=0)
    corrected = solve_pose_graph_impl(state.anchors, all_cons, cfg.graph)

    # corrected CURRENT pose: hang the local odometry leg off the
    # corrected newest anchor (identity when the library is empty)
    cur_c = pose_compose(
        jnp.take(corrected, last, axis=0), o_cur, table, div
    )
    cur_c = jnp.stack([
        jnp.clip(cur_c[0], -lim, lim),
        jnp.clip(cur_c[1], -lim, lim),
        cur_c[2],
    ])
    cur_c = jnp.where(state.count > 0, cur_c, pose)

    anchors = state.anchors
    if cfg.reanchor:
        # accepted closure rewrites the stored anchors to the corrected
        # solution (a warm start for the next solve — the constraint
        # set, not the initialization, determines the fixed point)
        anchors = jnp.where(accept, corrected, anchors)

    new_state = LoopState(
        planes=state.planes, anchors=anchors, odom=state.odom,
        valid=state.valid, count=state.count,
        cons=cons, ncons=ncons, dropped=dropped,
    )
    wire = jnp.concatenate([
        accept.astype(jnp.int32)[None],
        jnp.where(has_cand, best_slot, -1)[None],
        jnp.where(has_cand, jnp.maximum(best, 0), 0)[None],
        n_valid[None],
        cur_c,
        ncons[None],
        dropped[None],
    ]).astype(jnp.int32)
    return new_state, wire, corrected


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def loop_close_step(
    state: LoopState, points_xy, mask, pose, cand_idx, check,
    cfg: LoopConfig,
):
    """Single-stream fused closure check: one donated dispatch runs the
    batched candidate match, the gates, the constraint append and the
    pose-graph relaxation (tests' parity twin of the fleet lowering)."""
    return _loop_close_step_impl(
        state, points_xy, mask, pose, cand_idx, check, cfg
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def fleet_loop_close_step(
    states: LoopState, points_xy, masks, poses, cand_idx, check,
    cfg: LoopConfig,
):
    """The fleet lowering: N streams check N libraries in ONE compiled
    vmapped dispatch (stream-stacked LoopState donated in place) —
    candidate match through solver, bit-exact vs N independent host
    reference steps."""

    def one(st, p, mk, ps, ci, ck):
        return _loop_close_step_impl(st, p, mk, ps, ci, ck, cfg)

    return jax.vmap(one)(states, points_xy, masks, poses, cand_idx, check)
