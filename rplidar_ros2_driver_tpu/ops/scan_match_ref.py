"""NumPy golden reference for the SLAM front-end (ops/scan_match.py).

The mapper's ``map_backend=host`` path and the parity suite's oracle: a
literal transcription of the fused kernels into numpy, step for step.
The datapath is integer end to end (see the exactness contract in
ops/scan_match.py), so this reference is BIT-EXACT against the jitted
single-stream and vmapped fleet lowerings — not "close", equal — which
is what lets tests/test_mapping.py pin fleet sizes 1/3/8 byte-for-byte.

Keep every function here in literal lockstep with its ops/scan_match.py
twin; a divergence is a bug in whichever side moved.
"""

from __future__ import annotations

import math

import numpy as np

from rplidar_ros2_driver_tpu.ops.scan_match import (
    ANG_BITS,
    PQ_LIMIT,
    SUB,
    SUB_BITS,
    MapConfig,
    rotation_table,
    theta_offsets,
)


def create_map_state_np(cfg: MapConfig) -> dict:
    """Fresh host-side MapState as the snapshot dict layout."""
    return {
        "log_odds": np.zeros((cfg.grid, cfg.grid), np.int32),
        "pose": np.zeros((3,), np.int32),
        "origin_xy": np.zeros((2,), np.float32),
        "revision": np.int32(0),
    }


def quantize_points_np(xy, mask, cfg: MapConfig):
    s = np.asarray(xy, np.float32) * np.float32(cfg.sub_per_m)
    lim = np.float32(PQ_LIMIT)
    with np.errstate(invalid="ignore"):
        ok = (
            np.asarray(mask, bool)
            & (np.abs(s[:, 0]) <= lim)
            & (np.abs(s[:, 1]) <= lim)
        )
        s = np.where(np.isfinite(s), s, np.float32(0.0))
        # graftlint: policed — NaN/inf zeroed and clamped into ±PQ_LIMIT
        # in float space above (literal twin of ops/scan_match.py)
        pq = np.rint(np.clip(s, -lim, lim)).astype(np.int32)
    return pq, ok


def rotate_points_np(pq, cos_q, sin_q):
    x, y = pq[..., 0], pq[..., 1]
    half = 1 << (ANG_BITS - 1)
    xr = (cos_q * x - sin_q * y + half) >> ANG_BITS
    yr = (sin_q * x + cos_q * y + half) >> ANG_BITS
    return xr, yr


def _bilinear_gather_np(mf, gdim, ix, iy, fx, fy):
    total = np.zeros(np.broadcast(ix, fx).shape, np.int32)
    for dx_c, dy_c in ((0, 0), (1, 0), (0, 1), (1, 1)):
        cx, cy = ix + dx_c, iy + dy_c
        ok = (cx >= 0) & (cx < gdim) & (cy >= 0) & (cy < gdim)
        idx = np.clip(cx, 0, gdim - 1) * gdim + np.clip(cy, 0, gdim - 1)
        val = np.where(ok, mf[idx], 0).astype(np.int32)
        wx = SUB - fx if dx_c == 0 else fx
        wy = SUB - fy if dy_c == 0 else fy
        total = total + wx * wy * val
    return total


def cell_hits_np(cells_x, cells_y, inb, grid: int) -> np.ndarray:
    counts = np.zeros((grid * grid,), np.int32)
    flat = np.where(inb, cells_x * grid + cells_y, 0)
    np.add.at(counts, flat[inb], 1)
    return counts.reshape(grid, grid)


def match_scan_volumes_np(log_odds, pose, pq, ok, cfg: MapConfig):
    """Literal twin of ops/scan_match.match_scan_volumes: the shared
    score-volume core returning the UNGATED argmax delta, the best fine
    score and the fine volume's minimum (the loop-closure gates'
    peak-contrast statistic)."""
    g, c = cfg.grid, cfg.coarse
    gc = g // c
    clog = int(math.log2(c))
    center = (g // 2) * SUB

    mq = (np.clip(log_odds, 0, cfg.clamp_q) >> cfg.quant_shift).astype(
        np.int32
    )
    mc = mq.reshape(gc, c, gc, c).max(axis=(1, 3))
    mq_f, mc_f = mq.reshape(-1), mc.reshape(-1)

    table = rotation_table(cfg.theta_divisions)
    dth = theta_offsets(cfg)
    th_idx = np.mod(pose[2] + dth, cfg.theta_divisions)
    cos_q = table[:, 0][th_idx][:, None]
    sin_q = table[:, 1][th_idx][:, None]
    rx, ry = rotate_points_np(pq[None, :, :], cos_q, sin_q)
    bx = rx + pose[0] + center
    by = ry + pose[1] + center
    t_mid = cfg.theta_window  # the dθ=0 row

    # coarse: translation-only at the predicted heading
    scx, scy = bx[t_mid] >> clog, by[t_mid] >> clog
    ccx, ccy = scx >> SUB_BITS, scy >> SUB_BITS
    cfx, cfy = scx & (SUB - 1), scy & (SUB - 1)
    w = cfg.window_cells
    shifts = np.arange(-w, w + 1, dtype=np.int32)
    ix = ccx[:, None, None] + shifts[None, :, None]
    iy = ccy[:, None, None] + shifts[None, None, :]
    vals = _bilinear_gather_np(
        mc_f, gc, ix, iy, cfx[:, None, None], cfy[:, None, None]
    )
    score_c = np.sum(
        np.where(ok[:, None, None], vals, 0), axis=0, dtype=np.int32
    )

    nu = 2 * w + 1
    kbest = int(np.argmax(score_c.reshape(-1)))
    u_best = kbest // nu - w
    v_best = kbest % nu - w

    # fine: joint (θ, dx, dy) at full resolution around the winner
    fbx = bx + u_best * (c * SUB)
    fby = by + v_best * (c * SUB)
    fcx, fcy = fbx >> SUB_BITS, fby >> SUB_BITS
    ffx, ffy = fbx & (SUB - 1), fby & (SUB - 1)
    r = cfg.fine_radius
    fsh = np.arange(-r, r + 1, dtype=np.int32)
    fix = fcx[:, :, None, None] + fsh[None, None, :, None]
    fiy = fcy[:, :, None, None] + fsh[None, None, None, :]
    fvals = _bilinear_gather_np(
        mq_f, g, fix, fiy,
        ffx[:, :, None, None], ffy[:, :, None, None],
    )
    score_f = np.sum(
        np.where(ok[None, :, None, None], fvals, 0), axis=1, dtype=np.int32
    )

    nf = 2 * r + 1
    fbest = int(np.argmax(score_f.reshape(-1)))
    t_best = fbest // (nf * nf)
    du = (fbest // nf) % nf - r
    dv = fbest % nf - r
    best = int(np.max(score_f))
    minv = int(np.min(score_f))

    dpose_raw = np.asarray([
        (u_best * c + du) * SUB,
        (v_best * c + dv) * SUB,
        int(dth[t_best]),
    ], np.int32)
    return dpose_raw, np.int32(best), np.int32(minv)


def match_scan_np(log_odds, pose, pq, ok, cfg: MapConfig):
    dpose_raw, best, _minv = match_scan_volumes_np(log_odds, pose, pq, ok, cfg)
    if int(best) > 0:
        dpose, score = dpose_raw, int(best)
    else:
        dpose, score = np.zeros((3,), np.int32), 0
    return dpose, np.int32(score), np.int32(np.sum(ok))


def update_map_np(log_odds, pose, pq, ok, cfg: MapConfig):
    g = cfg.grid
    center = (g // 2) * SUB
    if cfg.decay_q:
        # literal twin of the static-gated decay in ops/scan_match.py:
        # shrink toward zero BEFORE the hit/miss pass
        mag = np.maximum(np.abs(log_odds) - cfg.decay_q, 0)
        log_odds = (np.sign(log_odds) * mag).astype(np.int32)
    table = rotation_table(cfg.theta_divisions)
    cos_q, sin_q = table[pose[2], 0], table[pose[2], 1]
    wx, wy = rotate_points_np(pq, cos_q, sin_q)
    wx, wy = wx + pose[0] + center, wy + pose[1] + center

    cx, cy = wx >> SUB_BITS, wy >> SUB_BITS
    inb = ok & (cx >= 0) & (cx < g) & (cy >= 0) & (cy < g)
    hits = cell_hits_np(cx, cy, inb, g)

    if cfg.free_samples > 0:
        ox, oy = pose[0] + center, pose[1] + center
        free = np.zeros((g, g), np.int32)
        for k in range(cfg.free_samples):
            sx = ox + ((wx - ox) * k) // cfg.free_samples
            sy = oy + ((wy - oy) * k) // cfg.free_samples
            fx_c, fy_c = sx >> SUB_BITS, sy >> SUB_BITS
            finb = ok & (fx_c >= 0) & (fx_c < g) & (fy_c >= 0) & (fy_c < g)
            free = free + cell_hits_np(fx_c, fy_c, finb, g)
        i_miss = (free > 0) & ~(hits > 0)
    else:
        i_miss = np.zeros((g, g), bool)

    delta = (
        np.where(hits > 0, cfg.hit_q, 0) + np.where(i_miss, cfg.miss_q, 0)
    ).astype(np.int32)
    return np.clip(log_odds + delta, -cfg.clamp_q, cfg.clamp_q).astype(
        np.int32
    )


def map_match_step_np(
    state: dict, points_xy, mask, live: int, cfg: MapConfig
):
    """One host-reference revolution — the literal twin of
    ops/scan_match._map_match_step_impl.  ``state`` is the snapshot-dict
    layout; returns (new state dict, (5,) int32 wire row)."""
    pq, ok = quantize_points_np(points_xy, mask, cfg)
    ok = ok & (int(live) > 0)
    dpose, score, n_valid = match_scan_np(
        state["log_odds"], state["pose"], pq, ok, cfg
    )
    lim = cfg.t_limit_sub
    pose = np.asarray([
        np.clip(state["pose"][0] + dpose[0], -lim, lim),
        np.clip(state["pose"][1] + dpose[1], -lim, lim),
        np.mod(state["pose"][2] + dpose[2], cfg.theta_divisions),
    ], np.int32)
    if int(live) > 0:
        log_odds = update_map_np(state["log_odds"], pose, pq, ok, cfg)
    else:
        log_odds, pose = state["log_odds"], state["pose"]
    new_state = {
        "log_odds": log_odds,
        "pose": pose,
        "origin_xy": state["origin_xy"],
        "revision": np.int32(state["revision"] + int(live)),
    }
    wire = np.concatenate([
        pose, np.asarray([score, n_valid], np.int32)
    ]).astype(np.int32)
    return new_state, wire
