"""Fixed-point 2-D pose-graph relaxation — the SLAM back-end solver.

The loop-closure subsystem (ops/loop_close.py) turns accepted submap
matches into inter-pose constraints; this module relaxes the resulting
graph ON DEVICE in the matcher's established int32/Q-format discipline
("An FPGA Acceleration and Optimization Techniques for 2D LiDAR SLAM
Algorithm" builds custom hardware for exactly this iterative relaxation
— on TPU it is a fixed-iteration ``lax.fori_loop`` over dense padded
constraint planes, one compiled program per (nodes, constraints)
bucket).

Representation (shared with ops/scan_match.py):

  * a NODE is a pose (tx_sub, ty_sub, theta_idx) int32 — translation in
    SUB-subcell units, heading an index into the ``theta_divisions``
    rotation table (2^14-scale int32 cos/sin, numpy-built once);
  * a CONSTRAINT row is (i, j, zx_sub, zy_sub, ztheta_steps, weight)
    int32 — "node j observed from node i at relative pose z", weight 0
    = padding (dense planes, so fleet graphs of any fill level share
    one compiled program).

The solver is damped Gauss–Newton relaxation with the rotation Jacobian
applied through the exact integer rotation core (rotate_rows): each
iteration predicts every constraint's node-j pose from node i, forms
the weighted residual, accumulates ± corrections per node with integer
scatter-adds (associative — ANY evaluation order is bit-identical),
and steps each node by the truncated half-mean correction.  Truncating
division toward zero (not floor) keeps the update bias-free around
zero: a ±1-subcell rounding residual must decay to a fixed point, not
walk the graph one subcell per iteration.

Node 0 is the gauge anchor and never moves; nodes touched by no
constraint have zero degree and zero accumulated correction, so
padding nodes pass through untouched by construction.

Arithmetic bounds (int32, explicit like the matcher's): translations
clamp to ±t_limit_sub <= 2^14 (grid <= 1024) and constraint z terms to
±2·t_limit_sub, so a residual is < 5·t_limit_sub, a weighted residual
< 5·t_limit_sub·weight_max, and a node's accumulator over every
constraint < 5·t_limit_sub·weight_max·max_constraints — the config
validates this product < 2^31.  The NumPy twin
(ops/pose_graph_ref.py) is BIT-EXACT, not close; the randomized-graph
parity suite (tests/test_loop_close.py) pins it byte-for-byte.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp

from rplidar_ros2_driver_tpu.ops.scan_match import (
    rotate_rows,
    rotation_table,
)

POSE_GRAPH_VERSION = 1


@dataclasses.dataclass(frozen=True)
class PoseGraphConfig:
    """Static (compile-time) solver configuration.  ``max_nodes`` /
    ``max_constraints`` are the dense padded plane sizes — one compiled
    program per bucket, whatever the live fill level."""

    max_nodes: int
    max_constraints: int
    iters: int = 96
    theta_divisions: int = 720
    t_limit_sub: int = 4096     # ± translation clamp (subcells)
    weight_max: int = 16        # constraint weight clamp

    def __post_init__(self):
        if self.max_nodes < 1:
            raise ValueError("pose graph needs at least one node")
        if self.max_constraints < 1:
            raise ValueError("pose graph needs a constraint plane")
        if self.iters < 1:
            raise ValueError("pose_graph_iters must be >= 1")
        if self.theta_divisions < 4:
            raise ValueError("theta_divisions must be >= 4")
        if self.t_limit_sub < 1:
            raise ValueError("t_limit_sub must be positive")
        if self.weight_max < 1:
            raise ValueError("weight_max must be >= 1")
        # int32 accumulator bound (module docstring): every node sums
        # <= max_constraints weighted residuals of < 5·t_limit each
        if 5 * self.t_limit_sub * self.weight_max * self.max_constraints >= 2**31:
            raise ValueError(
                "pose-graph accumulator can overflow int32: shrink "
                "max_constraints, weight_max or t_limit_sub "
                f"(5*{self.t_limit_sub}*{self.weight_max}"
                f"*{self.max_constraints} >= 2^31)"
            )


# ---------------------------------------------------------------------------
# exact SE(2) fixed-point composition helpers (each has a literal numpy
# mirror in ops/pose_graph_ref.py — keep them in lockstep)
# ---------------------------------------------------------------------------


def wrap_steps(d, div: int):
    """Wrap a rotation-table step delta into [-div/2, div/2)."""
    half = div // 2
    return jnp.mod(d + half, div) - half


def pose_compose(p, z, table, div: int):
    """p ∘ z: apply relative transform ``z`` in ``p``'s frame
    (t = t_p + R(θ_p)·z_t, θ = θ_p + z_θ mod div).  Broadcasts over
    leading axes; the rotation rides the shared integer core."""
    cos_q = jnp.take(table[:, 0], p[..., 2])
    sin_q = jnp.take(table[:, 1], p[..., 2])
    rx, ry = rotate_rows(z[..., 0], z[..., 1], cos_q, sin_q)
    return jnp.stack(
        [p[..., 0] + rx, p[..., 1] + ry, jnp.mod(p[..., 2] + z[..., 2], div)],
        axis=-1,
    )


def pose_relative(a, b, table, div: int):
    """b ⊖ a: the relative transform from ``a`` to ``b`` in ``a``'s
    frame (z_t = R(-θ_a)·(t_b - t_a), z_θ = θ_b - θ_a mod div) —
    R(-θ) is the same table row with the sine negated, so no second
    table is ever built."""
    cos_q = jnp.take(table[:, 0], a[..., 2])
    sin_q = jnp.take(table[:, 1], a[..., 2])
    rx, ry = rotate_rows(
        b[..., 0] - a[..., 0], b[..., 1] - a[..., 1], cos_q, -sin_q
    )
    return jnp.stack(
        [rx, ry, jnp.mod(b[..., 2] - a[..., 2], div)], axis=-1
    )


def rel_inverse(z, table, div: int):
    """z⁻¹ of a relative transform: (−R(−θ_z)·t_z, −θ_z)."""
    inv_th = jnp.mod(-z[..., 2], div)
    cos_q = jnp.take(table[:, 0], inv_th)
    sin_q = jnp.take(table[:, 1], inv_th)
    rx, ry = rotate_rows(z[..., 0], z[..., 1], cos_q, sin_q)
    return jnp.stack([-rx, -ry, inv_th], axis=-1)


# ---------------------------------------------------------------------------
# the relaxation core
# ---------------------------------------------------------------------------


def solve_pose_graph_impl(nodes0, cons, cfg: PoseGraphConfig):
    """Relax one graph: ``nodes0`` (M, 3) int32 initial poses, ``cons``
    (C, 6) int32 dense constraint plane (weight 0 = padding).  Returns
    the corrected (M, 3) int32 node poses after ``cfg.iters`` damped
    relaxation sweeps.  Pure function of its inputs — callers embed it
    in their own jitted programs (ops/loop_close.py runs it INSIDE the
    closure-check dispatch, so a check costs one dispatch total)."""
    m, div = cfg.max_nodes, cfg.theta_divisions
    table = jnp.asarray(rotation_table(div))
    lim = cfg.t_limit_sub
    ci = jnp.clip(cons[:, 0], 0, m - 1)
    cj = jnp.clip(cons[:, 1], 0, m - 1)
    wgt = jnp.clip(cons[:, 5], 0, cfg.weight_max)               # (C,)
    # z clamp: the residual bound the config validated assumes it
    zx = jnp.clip(cons[:, 2], -2 * lim, 2 * lim)
    zy = jnp.clip(cons[:, 3], -2 * lim, 2 * lim)
    zth = cons[:, 4]
    movable = (jnp.arange(m, dtype=jnp.int32) > 0)[:, None]     # gauge anchor

    def body(_, nodes):
        pi = jnp.take(nodes, ci, axis=0)                        # (C, 3)
        pj = jnp.take(nodes, cj, axis=0)
        cos_q = jnp.take(table[:, 0], pi[:, 2])
        sin_q = jnp.take(table[:, 1], pi[:, 2])
        rx, ry = rotate_rows(zx, zy, cos_q, sin_q)
        res = jnp.stack([
            (pi[:, 0] + rx - pj[:, 0]) * wgt,
            (pi[:, 1] + ry - pj[:, 1]) * wgt,
            wrap_steps(pi[:, 2] + zth - pj[:, 2], div) * wgt,
        ], axis=1)                                              # (C, 3)
        acc = (
            jnp.zeros((m, 3), jnp.int32)
            .at[cj].add(res, mode="drop")
            .at[ci].add(-res, mode="drop")
        )
        deg = (
            jnp.zeros((m,), jnp.int32)
            .at[cj].add(wgt, mode="drop")
            .at[ci].add(wgt, mode="drop")
        )
        den = 2 * jnp.maximum(deg, 1)                           # damping 1/2
        # truncating (toward-zero) division: bias-free around zero, so
        # ±1-subcell rounding residuals decay instead of walking
        corr = jnp.sign(acc) * (jnp.abs(acc) // den[:, None])
        nodes = jnp.where(movable, nodes + corr, nodes)
        return jnp.stack([
            jnp.clip(nodes[:, 0], -lim, lim),
            jnp.clip(nodes[:, 1], -lim, lim),
            jnp.mod(nodes[:, 2], div),
        ], axis=1)

    return jax.lax.fori_loop(0, cfg.iters, body, nodes0)


@functools.partial(jax.jit, static_argnames=("cfg",))
def solve_pose_graph(nodes0, cons, cfg: PoseGraphConfig):
    """Standalone jitted single-graph solve (tests and offline tools;
    the live path embeds :func:`solve_pose_graph_impl` in the fused
    closure-check program instead)."""
    return solve_pose_graph_impl(nodes0, cons, cfg)


@functools.partial(jax.jit, static_argnames=("cfg",))
def fleet_solve_pose_graph(nodes0, cons, cfg: PoseGraphConfig):
    """Fleet lowering: N graphs relax in ONE compiled vmapped dispatch
    ((N, M, 3) nodes, (N, C, 6) constraint planes)."""
    return jax.vmap(lambda n, c: solve_pose_graph_impl(n, c, cfg))(
        nodes0, cons
    )
