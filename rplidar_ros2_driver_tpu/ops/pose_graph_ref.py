"""NumPy golden reference for the pose-graph solver (ops/pose_graph.py).

A literal transcription of the jitted relaxation into numpy, step for
step — the datapath is integer end to end, so this reference is
BIT-EXACT against the jitted single-graph and vmapped fleet lowerings
(tests/test_loop_close.py pins randomized graphs byte-for-byte).

Keep every function here in literal lockstep with its ops/pose_graph.py
twin; a divergence is a bug in whichever side moved.
"""

from __future__ import annotations

import numpy as np

from rplidar_ros2_driver_tpu.ops.pose_graph import PoseGraphConfig
from rplidar_ros2_driver_tpu.ops.scan_match import rotation_table
from rplidar_ros2_driver_tpu.ops.scan_match_ref import rotate_points_np


def _rotate_np(x, y, cos_q, sin_q):
    """rotate_rows on split planes (the ref twin keeps the packed-point
    helper; restate it here for split coordinates)."""
    pq = np.stack([x, y], axis=-1)
    return rotate_points_np(pq, cos_q, sin_q)


def wrap_steps_np(d, div: int):
    half = div // 2
    return np.mod(d + half, div) - half


def pose_compose_np(p, z, table, div: int):
    p = np.asarray(p)
    z = np.asarray(z)
    cos_q = table[:, 0][p[..., 2]]
    sin_q = table[:, 1][p[..., 2]]
    rx, ry = _rotate_np(z[..., 0], z[..., 1], cos_q, sin_q)
    return np.stack(
        [p[..., 0] + rx, p[..., 1] + ry, np.mod(p[..., 2] + z[..., 2], div)],
        axis=-1,
    ).astype(np.int32)


def pose_relative_np(a, b, table, div: int):
    a = np.asarray(a)
    b = np.asarray(b)
    cos_q = table[:, 0][a[..., 2]]
    sin_q = table[:, 1][a[..., 2]]
    rx, ry = _rotate_np(
        b[..., 0] - a[..., 0], b[..., 1] - a[..., 1], cos_q, -sin_q
    )
    return np.stack(
        [rx, ry, np.mod(b[..., 2] - a[..., 2], div)], axis=-1
    ).astype(np.int32)


def rel_inverse_np(z, table, div: int):
    z = np.asarray(z)
    inv_th = np.mod(-z[..., 2], div)
    cos_q = table[:, 0][inv_th]
    sin_q = table[:, 1][inv_th]
    rx, ry = _rotate_np(z[..., 0], z[..., 1], cos_q, sin_q)
    return np.stack([-rx, -ry, inv_th], axis=-1).astype(np.int32)


def solve_pose_graph_np(nodes0, cons, cfg: PoseGraphConfig):
    """The literal twin of ops/pose_graph.solve_pose_graph_impl."""
    m, div = cfg.max_nodes, cfg.theta_divisions
    table = rotation_table(div)
    lim = cfg.t_limit_sub
    cons = np.asarray(cons, np.int32)
    ci = np.clip(cons[:, 0], 0, m - 1)
    cj = np.clip(cons[:, 1], 0, m - 1)
    wgt = np.clip(cons[:, 5], 0, cfg.weight_max)
    zx = np.clip(cons[:, 2], -2 * lim, 2 * lim)
    zy = np.clip(cons[:, 3], -2 * lim, 2 * lim)
    zth = cons[:, 4]
    movable = (np.arange(m, dtype=np.int32) > 0)[:, None]

    nodes = np.asarray(nodes0, np.int32).copy()
    for _ in range(cfg.iters):
        pi = nodes[ci]
        pj = nodes[cj]
        cos_q = table[:, 0][pi[:, 2]]
        sin_q = table[:, 1][pi[:, 2]]
        rx, ry = _rotate_np(zx, zy, cos_q, sin_q)
        res = np.stack([
            (pi[:, 0] + rx - pj[:, 0]) * wgt,
            (pi[:, 1] + ry - pj[:, 1]) * wgt,
            wrap_steps_np(pi[:, 2] + zth - pj[:, 2], div) * wgt,
        ], axis=1).astype(np.int32)
        acc = np.zeros((m, 3), dtype=np.int32)
        np.add.at(acc, cj, res)
        np.add.at(acc, ci, -res)
        deg = np.zeros((m,), dtype=np.int32)
        np.add.at(deg, cj, wgt)
        np.add.at(deg, ci, wgt)
        den = 2 * np.maximum(deg, 1)
        corr = (np.sign(acc) * (np.abs(acc) // den[:, None])).astype(
            np.int32
        )
        nodes = np.where(movable, nodes + corr, nodes)
        nodes = np.stack([
            np.clip(nodes[:, 0], -lim, lim),
            np.clip(nodes[:, 1], -lim, lim),
            np.mod(nodes[:, 2], div),
        ], axis=1).astype(np.int32)
    return nodes
