"""ScanBatch -> LaserScan conversion as a single jit kernel.

Array reformulation of the reference's ``publish_scan``
(src/rplidar_node.cpp:558-683): drop zero-distance nodes, Q14->radians,
Q2->metres, quality->intensity (legacy protocol shifts right by 2), wrap
angles, sort by angle, then either

  * Mode A (``scan_processing``): resample onto a fixed angular grid with
    min-range conflict resolution and REP-117 +inf padding (:632-662), or
  * Mode B: raw CW-reversed mapping (:663-680).

The reference's per-point loop + std::sort become a masked sort plus a
scatter-min.  Conflict resolution packs ``(dist_q2 << 8) | intensity`` into
one int32 so a single ``min``-scatter picks the winning range *and* its
intensity atomically (ties resolve to the lowest intensity rather than
first-seen — same distance either way).

Output arrays stay padded at the ScanBatch width; ``beam_count`` gives the
live prefix, and the host trims before serializing a ROS message.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp

from rplidar_ros2_driver_tpu.core.types import LaserScanMsg, ScanBatch

TWO_PI = 2.0 * jnp.pi


@functools.partial(
    jax.jit, static_argnames=("scan_processing", "inverted", "is_new_type")
)
def to_laserscan(
    batch: ScanBatch,
    scan_duration_s,
    max_range_m,
    *,
    scan_processing: bool = False,
    inverted: bool = False,
    is_new_type: bool = True,
) -> LaserScanMsg:
    n = batch.num_nodes
    valid = batch.valid & (batch.dist_q2 != 0)
    count = valid.sum().astype(jnp.int32)

    angle_deg = batch.angle_q14.astype(jnp.float32) * (90.0 / 16384.0)
    angle = angle_deg * (jnp.pi / 180.0)
    angle = jnp.where(angle < 0.0, angle + TWO_PI, angle)
    angle = jnp.where(angle >= TWO_PI, angle - TWO_PI, angle)
    dist_m = batch.dist_q2.astype(jnp.float32) * (1.0 / 4000.0)
    intensity = (
        batch.quality if is_new_type else (batch.quality >> 2)
    ).astype(jnp.float32)

    # masked sort by angle: invalid nodes to the tail
    key = jnp.where(valid, angle, jnp.inf)
    order = jnp.argsort(key)
    angle_s = key[order]
    dist_s = dist_m[order]
    dist_q2_s = batch.dist_q2[order]
    inten_s = intensity[order]
    qual_s = batch.quality[order] if is_new_type else (batch.quality[order] >> 2)
    valid_s = valid[order]

    countf = jnp.maximum(count, 1).astype(jnp.float32)
    scan_duration_s = jnp.asarray(scan_duration_s, jnp.float32)

    if scan_processing:
        # Mode A: fixed angular grid, one beam per valid point count
        angle_increment = TWO_PI / countf
        time_increment = scan_duration_s / countf
        a = angle_s
        if inverted:
            a = TWO_PI - a
            a = jnp.where(a >= TWO_PI, a - TWO_PI, a)
        index = (a / angle_increment).astype(jnp.int32)  # trunc, matches C cast
        in_range = valid_s & (index >= 0) & (index < count)
        index = jnp.clip(index, 0, n - 1)
        # pack (dist_q2, intensity byte) for atomic min-conflict resolution
        packed = (dist_q2_s << 8) | jnp.clip(qual_s, 0, 255)
        packed = jnp.where(in_range, packed, jnp.int32(0x7FFFFFFF))
        grid = jnp.full((n,), 0x7FFFFFFF, jnp.int32).at[index].min(
            packed, mode="drop"
        )
        hit = grid != 0x7FFFFFFF
        ranges = jnp.where(hit, (grid >> 8).astype(jnp.float32) * (1.0 / 4000.0), jnp.inf)
        intensities = jnp.where(hit, (grid & 0xFF).astype(jnp.float32), 0.0)
        beam_count = count
    else:
        # Mode B: raw mapping, rplidar turns CW so order is reversed unless
        # inverted (src/rplidar_node.cpp:672-678)
        denom = jnp.maximum(count - 1, 1).astype(jnp.float32)
        angle_increment = TWO_PI / denom
        time_increment = scan_duration_s / denom
        i = jnp.arange(n, dtype=jnp.int32)
        idx = jnp.where(inverted, i, count - 1 - i)
        # route invalid (padding) points out of bounds so mode="drop" skips them
        idx = jnp.where(valid_s, idx, n)
        ranges = jnp.full((n,), jnp.inf, jnp.float32).at[idx].set(
            dist_s, mode="drop"
        )
        intensities = jnp.zeros((n,), jnp.float32).at[idx].set(
            inten_s, mode="drop"
        )
        beam_count = count

    return LaserScanMsg(
        ranges=ranges,
        intensities=intensities,
        beam_count=beam_count,
        angle_min=jnp.float32(0.0),
        angle_max=jnp.float32(TWO_PI),
        angle_increment=angle_increment.astype(jnp.float32),
        time_increment=time_increment.astype(jnp.float32),
        scan_time=scan_duration_s,
        range_min=jnp.float32(0.15),
        range_max=jnp.asarray(max_range_m, jnp.float32),
    )
