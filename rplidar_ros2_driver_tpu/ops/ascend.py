"""Angle-compensation: synthesize angles for invalid points, then sort.

Equivalent of the reference's ``ascendScanData_``
(sl_lidar_driver.cpp:128-184), applied by the wrapper when
``angle_compensate`` is on (src/lidar_driver_wrapper.cpp:329).

The reference tunes the head backwards from the first valid point, tunes
the tail, then *overwrites every invalid index >= 1* with
``angle[0] + i * inc`` (so only the head-tuned ``angle[0]`` actually
survives), and finally sorts by angle.  The vectorized form computes
exactly that net effect:

  * ``angle[0]``   — first-valid angle walked back ``fv`` steps of
    ``360/count`` deg, floor-clamped at 0 (computed closed-form; the
    reference quantizes through u16 Q14 at each step, so synthesized
    angles of *invalid* points may differ by ~1 LSB — they carry no range
    data, dist == 0),
  * invalid ``i``  — ``angle[0] + i*inc`` with a single 360-wrap,
  * sort by (quantized) angle; invalid-count scans return ``ok=False``
    and the batch unchanged (the reference returns OPERATION_FAIL and the
    wrapper falls back to the raw scan).

Operates on the valid prefix of a padded ScanBatch; padding stays at the
tail (sort key +inf).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from rplidar_ros2_driver_tpu.core.types import ScanBatch


@jax.jit
def ascend_scan(batch: ScanBatch) -> tuple[ScanBatch, jax.Array]:
    n = batch.num_nodes
    live = batch.valid
    has_range = live & (batch.dist_q2 != 0)
    count = jnp.maximum(batch.count, 1)
    any_valid = has_range.any()

    angle_f = batch.angle_q14.astype(jnp.float32) * (90.0 / 16384.0)
    inc = 360.0 / count.astype(jnp.float32)

    idx = jnp.arange(n, dtype=jnp.int32)
    fv = jnp.argmax(has_range)  # first index with a real measurement
    a_fv = angle_f[fv]

    a0 = jnp.where(
        has_range[0], angle_f[0], jnp.maximum(a_fv - fv.astype(jnp.float32) * inc, 0.0)
    )
    synth = a0 + idx.astype(jnp.float32) * inc
    synth = jnp.where(synth > 360.0, synth - 360.0, synth)
    new_angle_f = jnp.where(has_range | (idx == 0), jnp.where(idx == 0, a0, angle_f), synth)
    new_q14 = (new_angle_f * (16384.0 / 90.0)).astype(jnp.int32)

    # keep original values when compensation cannot run
    q14_out = jnp.where(any_valid & live, new_q14, batch.angle_q14)

    sort_key = jnp.where(live, q14_out, jnp.int32(0x7FFFFFFF))
    order = jnp.argsort(sort_key)
    out = ScanBatch(
        angle_q14=q14_out[order],
        dist_q2=batch.dist_q2[order],
        quality=batch.quality[order],
        flag=batch.flag[order],
        valid=live[order],
        count=batch.count,
    )
    return out, any_valid


def apply_angle_compensation(batch: ScanBatch, enabled: bool) -> ScanBatch:
    """The single 'ascend if configured' policy point, shared by the driver
    grab path (RealLidarDriver.grab_scan_data_with_timestamp) and the
    node's raw publish path — keep the conditional here so the two layers
    cannot drift (reference: ascendScanData applied inside grab when
    angle_compensate, src/lidar_driver_wrapper.cpp:329)."""
    if not enabled:
        return batch
    out, _ = ascend_scan(batch)
    return out
