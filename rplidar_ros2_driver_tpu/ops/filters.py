"""ScanFilterChain kernels — the TPU north star (BASELINE.json).

Everything here is pure, jit-stable array math over padded ScanBatch /
gridded range images:

  * range/intensity clip        (elementwise validity update)
  * angular-grid resample       (scatter-min range image, B fixed beams)
  * rolling-window temporal median (lower median over a (W, B) device ring)
  * polar -> Cartesian          (for PointCloud output)
  * 2-D voxel occupancy         (scatter-add histogram, W-scan accumulation)

The rolling window and voxel accumulator are device-resident state
(:class:`FilterState`) threaded functionally through ``filter_step`` — the
checkpoint/restore surface of the framework (SURVEY.md §5 checkpoint note).
The reference has no analog: its pipeline is stateless per scan
(src/rplidar_node.cpp:558-683); this chain is the new capability layered
between the wrapper and the publisher.
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp

from rplidar_ros2_driver_tpu.core.types import ScanBatch

TWO_PI = 2.0 * jnp.pi
# plain Python int (not jnp.int32): a module-scope jnp constant would
# initialize a JAX backend at import time, defeating late platform selection
# (tests/conftest.py, __graft_entry__.dryrun_multichip)
_INT_INF = 0x7FFFFFFF


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class FilterState:
    """Device-resident rolling state for the filter chain."""

    range_window: jax.Array   # (W, B) float32, +inf = no return
    inten_window: jax.Array   # (W, B) float32
    hit_window: jax.Array     # (W, G, G) int32 per-scan voxel grids
    voxel_acc: jax.Array      # (G, G) int32 running sum over the window
    cursor: jax.Array         # int32 ring write position
    filled: jax.Array         # int32 number of scans pushed (saturates at W)
    # derived state for median_backend == "inc": the window's multiset
    # kept sorted ascending per beam (None for the other backends; a
    # None pytree leaf is an empty subtree, so state structure stays
    # jit/donation-stable per compiled config).  Invariant: always the
    # sorted view of range_window's multiset — maintained incrementally
    # by the step, recomputed wholesale by the fused path and restore.
    median_sorted: Optional[jax.Array] = None  # (W, B) float32

    @staticmethod
    def shapes(window: int, beams: int, grid: int) -> dict[str, tuple[int, ...]]:
        """Array shapes of a state with this geometry — host-side, no
        allocation (used to validate checkpoints before touching
        devices).  Derived fields (median_sorted) are not part of the
        checkpoint surface, so they don't appear here."""
        return {
            "range_window": (window, beams),
            "inten_window": (window, beams),
            "hit_window": (window, grid, grid),
            "voxel_acc": (grid, grid),
            "cursor": (),
            "filled": (),
        }

    @classmethod
    def for_config(cls, cfg: "FilterConfig") -> "FilterState":
        """The one config -> fresh-state mapping: backends that carry
        derived state (median_backend == "inc" needs the sorted window)
        get it here, so call sites can't forget the coupling."""
        return cls.create(
            cfg.window, cfg.beams, cfg.grid,
            with_sorted=cfg.median_backend.startswith("inc"),
        )

    @staticmethod
    def create(
        window: int, beams: int, grid: int, with_sorted: bool = False
    ) -> "FilterState":
        return FilterState(
            range_window=jnp.full((window, beams), jnp.inf, jnp.float32),
            inten_window=jnp.zeros((window, beams), jnp.float32),
            hit_window=jnp.zeros((window, grid, grid), jnp.int32),
            voxel_acc=jnp.zeros((grid, grid), jnp.int32),
            cursor=jnp.asarray(0, jnp.int32),
            filled=jnp.asarray(0, jnp.int32),
            # an all-inf ring is trivially sorted
            median_sorted=(
                jnp.full((window, beams), jnp.inf, jnp.float32)
                if with_sorted else None
            ),
        )


@dataclasses.dataclass(frozen=True)
class FilterConfig:
    """Static (compile-time) chain configuration."""

    window: int = 16
    beams: int = 2048
    grid: int = 256
    cell_m: float = 0.25
    range_min_m: float = 0.15
    range_max_m: float = 40.0
    intensity_min: float = 0.0
    enable_clip: bool = True
    enable_median: bool = True
    enable_voxel: bool = True
    # "xla" = jnp.sort path; "pallas" = VMEM bitonic-network kernel
    # (ops/pallas_kernels.temporal_median_pallas); "inc" = incremental
    # sliding median over a sorted-window carried state — O(W) per step,
    # auto-lowered per platform ("inc_pallas", the fused VMEM
    # sorted_replace kernel, on TPU; "inc_xla", the jnp formulation,
    # elsewhere — both pinnable for A/B).  inc* requires FilterState
    # created with with_sorted=True; the fused path computes inc* via
    # the xla windows and re-sorts the carried state per chunk.
    median_backend: str = "xla"
    # sharded-step voxel all-reduce over the beam axis: "psum" (XLA's
    # tuned all-reduce, default) or "ring" (explicit ppermute
    # rotate-accumulate) — parallel/sharding.py; ignored single-device
    voxel_reduce: str = "psum"
    # per-scan streaming-step resampler: "scatter" (jnp .at[].min) or
    # "dense" (the fused path's tiled masked-min, grid_resample_batch
    # with K=1 — scatter-min serializes on TPU).  Fused replay always
    # uses the dense tile regardless.
    resample_backend: str = "scatter"
    # voxel accumulation kernel: "scatter" (jnp .at[].add) or "matmul"
    # (one-hot bf16 einsum with f32 accumulation — exact counts, rides
    # the MXU; voxel_hits_matmul)
    voxel_backend: str = "scatter"


@dataclasses.dataclass(frozen=True)
class FilterOutput:
    """One step's outputs (all device arrays)."""

    ranges: jax.Array        # (B,) median-filtered (or raw gridded) ranges
    intensities: jax.Array   # (B,)
    points_xy: jax.Array     # (B, 2) Cartesian projection of `ranges`
    point_mask: jax.Array    # (B,) finite-range mask
    voxel: jax.Array         # (G, G) occupancy counts over the window


jax.tree_util.register_dataclass(
    FilterOutput,
    data_fields=["ranges", "intensities", "points_xy", "point_mask", "voxel"],
    meta_fields=[],
)


# ---------------------------------------------------------------------------
# individual kernels
# ---------------------------------------------------------------------------


def _clip_ok(batch: ScanBatch, cfg: FilterConfig) -> jax.Array:
    """The ONE clip predicate (returns inside [range_min, range_max] and
    at/above intensity_min), shared by the standalone clip_filter and
    the fused resample-key paths so the two cannot drift."""
    dist_m = batch.dist_q2.astype(jnp.float32) * jnp.float32(1.0 / 4000.0)
    return (
        (dist_m >= cfg.range_min_m)
        & (dist_m <= cfg.range_max_m)
        & (batch.quality.astype(jnp.float32) >= cfg.intensity_min)
    )


def clip_filter(batch: ScanBatch, cfg: FilterConfig) -> ScanBatch:
    """Drop returns outside [range_min, range_max] or below intensity_min.

    The standalone form; the step paths fold the same predicate
    (:func:`_clip_ok`) directly into the resample-key mask instead —
    bit-identical (a clipped point's zeroed dist is dropped by the key
    mask either way) with one fewer pass over the point arrays."""
    ok = batch.valid & (batch.dist_q2 != 0) & _clip_ok(batch, cfg)
    return dataclasses.replace(
        batch,
        dist_q2=jnp.where(ok, batch.dist_q2, 0),
        valid=batch.valid,  # node slots stay; zero dist marks the drop
        count=batch.count,
    )


def _resample_keys(batch: ScanBatch, beams: int, cfg: Optional[FilterConfig] = None):
    """Shared beam-index + packed-value computation of the resamplers:
    beam = angular cell, packed = dist<<8 | quality (so the per-beam min
    picks the nearest return and carries its intensity), _INT_INF marks
    dropped/invalid points.  With ``cfg`` given and clip enabled, the
    clip predicate folds into the drop mask here (bit-identical to a
    prior clip_filter pass, without materializing a clipped batch)."""
    ok = batch.valid & (batch.dist_q2 != 0)
    if cfg is not None and cfg.enable_clip:
        ok = ok & _clip_ok(batch, cfg)
    beam = (batch.angle_q14 * beams) // 65536  # Q14 full turn == 65536
    beam = jnp.clip(beam, 0, beams - 1)
    packed = (batch.dist_q2 << 8) | jnp.clip(batch.quality, 0, 255)
    packed = jnp.where(ok, packed, _INT_INF)
    return beam, packed


def _grid_decode(grid: jax.Array):
    """Per-beam packed min -> (ranges, intensities) with +inf / 0 misses."""
    hit = grid != _INT_INF
    ranges = jnp.where(
        hit, (grid >> 8).astype(jnp.float32) * jnp.float32(1.0 / 4000.0),
        jnp.inf,
    )
    inten = jnp.where(hit, (grid & 0xFF).astype(jnp.float32), 0.0)
    return ranges, inten


def grid_resample(batch: ScanBatch, beams: int, cfg: Optional[FilterConfig] = None):
    """Scatter-min a scan onto a fixed angular grid of ``beams`` cells.

    Returns (ranges (B,), intensities (B,)) with +inf where no return —
    the aligned representation the temporal window needs (scan point
    counts vary; the grid is the jit-stable common shape).  ``cfg``
    folds the clip predicate into the key mask (see _resample_keys).
    """
    beam, packed = _resample_keys(batch, beams, cfg)
    grid = jnp.full((beams,), _INT_INF, jnp.int32).at[beam].min(packed, mode="drop")
    return _grid_decode(grid)


def grid_resample_batch(beam: jax.Array, packed: jax.Array, beams: int, block: int = 256):
    """Per-beam min for a whole (K, P) batch of scans at once.

    A vmapped scatter-min serializes on TPU (~30 ms for 512 x 4096
    updates, measured r2); this instead evaluates the min as a dense
    masked reduction tiled over beam blocks — out[k, b] = min over p of
    where(beam[k, p] == b, packed[k, p], INF) — which XLA fuses into
    compare/select/min sweeps at ~2x the scatter's throughput with no
    ordering assumptions on the input.
    """
    outs = []
    for t0 in range(0, beams, block):
        bt = jnp.arange(t0, min(t0 + block, beams), dtype=jnp.int32)
        m = jnp.where(beam[:, None, :] == bt[None, :, None], packed[:, None, :], _INT_INF)
        outs.append(jnp.min(m, axis=2))
    return _grid_decode(jnp.concatenate(outs, axis=1))


def temporal_median(window: jax.Array) -> jax.Array:
    """Per-beam lower median over the (W, B) ring.

    +inf marks both missing returns and unfilled ring slots; they sort to
    the tail so the median is taken over actual returns only.  Beams with
    no return in the whole window stay +inf.  (Correctness depends on the
    ring being initialized to +inf — never seed it with finite values.)
    """
    w = window.shape[0]
    s = jnp.sort(window, axis=0)  # inf sorts last
    nvalid = jnp.sum(jnp.isfinite(window), axis=0)  # (B,)
    pick = jnp.clip((nvalid - 1) // 2, 0, w - 1)
    med = jnp.take_along_axis(s, pick[None, :], axis=0)[0]
    return jnp.where(nvalid > 0, med, jnp.inf)


def sorted_replace(
    sorted_w: jax.Array, old_v: jax.Array, new_v: jax.Array
) -> jax.Array:
    """Multiset update of a per-beam sorted window: delete one occurrence
    of ``old_v``, insert ``new_v``, keep it sorted — branch-free, O(W)
    elementwise work per beam instead of a fresh O(W log^2 W) sort.

    This is the sliding-window trick the streaming step's geometry
    invites: the ring evicts exactly one value per revolution
    (``range_window[cursor]``, bit-exactly the value inserted W steps
    ago), so between steps the sorted multiset changes by one delete and
    one insert.  The shift between the delete and insert positions is at
    most one slot per element, so the new array is a 3-way select over
    {left-neighbor, self, right-neighbor} — two rolls and a few compares
    on (W, B), no gather, no sort network.

    Args: sorted_w (W, B) ascending per column; old_v (B,) MUST be
    present in each column (exact float equality — guaranteed when it
    came from the same ring); new_v (B,).  +inf entries participate like
    any value (missing returns / unfilled slots).  Returns (W, B).
    """
    w = sorted_w.shape[0]
    iota = jnp.arange(w, dtype=jnp.int32)[:, None]                   # (W, 1)
    # d: first slot holding old_v (ties: any occurrence is the same value)
    d = jnp.argmax(sorted_w == old_v[None, :], axis=0).astype(jnp.int32)  # (B,)
    # p: insertion index of new_v in the W-1 multiset without old_v —
    # count of strictly-smaller survivors ("insert after equals": stable)
    p = (
        jnp.sum(sorted_w < new_v[None, :], axis=0)
        - (old_v < new_v).astype(jnp.int32)
    ).astype(jnp.int32)                                              # (B,)
    left = jnp.roll(sorted_w, 1, axis=0)    # left[i]  = s[i-1]
    right = jnp.roll(sorted_w, -1, axis=0)  # right[i] = s[i+1]
    # d < p: slots [d, p) close the gap from the right (take s[i+1]);
    # d > p: slots (p, d] make room from the left (take s[i-1]);
    # the wrap rows of the rolls are never selected (i<p<=W-1, i>p>=0)
    shift_l = (d[None, :] < p[None, :]) & (iota >= d[None, :]) & (iota < p[None, :])
    shift_r = (d[None, :] > p[None, :]) & (iota > p[None, :]) & (iota <= d[None, :])
    out = jnp.where(shift_l, right, jnp.where(shift_r, left, sorted_w))
    return jnp.where(iota == p[None, :], new_v[None, :], out)


def pin_inc_lowering(median: str, platform: Optional[str] = None) -> str:
    """The ONE platform -> inc-lowering mapping ("inc_pallas", the fused
    VMEM sorted_replace kernel, on TPU; "inc_xla", the jnp formulation,
    elsewhere), shared by chain.config_from_params (which pins while the
    target platform is known) and inc_median's in-jit fallback (which
    can only see the process default backend) so the two cannot drift.
    Non-"inc" values pass through."""
    if median != "inc":
        return median
    p = platform if platform is not None else jax.default_backend()
    return "inc_pallas" if p == "tpu" else "inc_xla"


def inc_median(
    range_window: jax.Array,
    cursor: jax.Array,
    median_sorted: Optional[jax.Array],
    new_ranges: jax.Array,
    backend: str = "inc",
) -> tuple[jax.Array, jax.Array]:
    """One incremental-median step, shared by the single-device and
    sharded step implementations so the two cannot drift: evict the
    PRE-update ring row at ``cursor`` from the carried sorted window,
    insert ``new_ranges``, return (updated sorted window, median).

    ``backend`` selects the lowering: "inc" auto-resolves per platform
    (the fused VMEM kernel on TPU — the jnp formulation's ~6 small ops
    each round-trip HBM there, which is the whole reason the O(W)
    update measured SLOWER than the O(W log^2 W) pallas sort at W=64);
    "inc_xla" / "inc_pallas" pin a lowering for A/B.  All lowerings are
    bit-exact (tests/test_pallas_median.py parity)."""
    if median_sorted is None:
        raise ValueError(
            "median_backend='inc' requires a state carrying the sorted "
            "window (FilterState.for_config / create_sharded_state "
            "provide it per config)"
        )
    old_v = jax.lax.dynamic_index_in_dim(
        range_window, cursor, 0, keepdims=False
    )
    backend = pin_inc_lowering(backend)
    if backend == "inc_pallas":
        from rplidar_ros2_driver_tpu.ops.pallas_kernels import (
            sorted_replace_pallas,
        )

        return sorted_replace_pallas(median_sorted, old_v, new_ranges)
    ms = sorted_replace(median_sorted, old_v, new_ranges)
    return ms, median_from_sorted(ms)


def recompute_median_sorted(range_window) -> jax.Array:
    """Rebuild the derived sorted window from the ring — the ONE
    restore/fused-boundary recompute, sorting along the window axis
    (axis=-2 covers both the (W, B) and (streams, W, B) layouts)."""
    return jnp.sort(jnp.asarray(range_window), axis=-2)


def median_from_sorted(sorted_w: jax.Array) -> jax.Array:
    """Per-beam lower median given the already-sorted (W, B) window —
    identical semantics to :func:`temporal_median` (+inf marks missing;
    all-inf beams stay +inf), minus the sort."""
    w = sorted_w.shape[0]
    nvalid = jnp.sum(jnp.isfinite(sorted_w), axis=0)
    pick = jnp.clip((nvalid - 1) // 2, 0, w - 1)
    med = jnp.take_along_axis(sorted_w, pick[None, :], axis=0)[0]
    return jnp.where(nvalid > 0, med, jnp.inf)


def polar_to_cartesian(ranges: jax.Array, beams: int):
    """Beam-grid ranges -> (B, 2) XY metres + finite mask."""
    theta = (
        jnp.arange(beams, dtype=jnp.float32) + jnp.float32(0.5)
    ) * jnp.float32(TWO_PI / beams)
    finite = jnp.isfinite(ranges)
    r = jnp.where(finite, ranges, 0.0)
    xy = jnp.stack([r * jnp.cos(theta), r * jnp.sin(theta)], axis=-1)
    return xy, finite


def _voxel_cells(
    xy: jax.Array, mask: jax.Array, grid: int, cell_m: float
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """(gx, gy, in_bounds) cell indices for one scan — the ONE place the
    grid-indexing convention (origin at the grid centre, floor
    semantics) lives, shared by both voxel kernels so their bit-parity
    contract cannot drift."""
    half = grid // 2
    # graftlint: policed — xy comes from the masked polar projection
    # (non-finite ranges project to r=0) and is bounded by range_max_m,
    # so the cast never sees NaN/inf/out-of-int32 values
    ij = jnp.floor(xy / cell_m).astype(jnp.int32) + half
    gx, gy = ij[:, 0], ij[:, 1]
    inb = mask & (gx >= 0) & (gx < grid) & (gy >= 0) & (gy < grid)
    return gx, gy, inb


def voxel_hits(xy: jax.Array, mask: jax.Array, grid: int, cell_m: float) -> jax.Array:
    """(G, G) occupancy counts for one scan, origin at the grid centre."""
    gx, gy, inb = _voxel_cells(xy, mask, grid, cell_m)
    flat = jnp.where(inb, gx * grid + gy, grid * grid)
    counts = jnp.zeros((grid * grid,), jnp.int32).at[flat].add(1, mode="drop")
    return counts.reshape(grid, grid)


def voxel_hits_matmul(
    xy: jax.Array, mask: jax.Array, grid: int, cell_m: float
) -> jax.Array:
    """(G, G) occupancy counts via a one-hot einsum — the MXU-riding
    alternative to :func:`voxel_hits`'s scatter-add (scatters serialize
    on TPU; a 0/1 outer-product accumulation is one (G, B) @ (B, G)
    matmul the systolic array eats whole).

    Exactness: the one-hots are exactly 0/1 in bf16, every product is
    exact, and the accumulation happens in f32
    (``preferred_element_type``) — integer counts are exact up to 2**24
    hits per cell (a scan contributes at most ``beams``).  Bit-identical
    to :func:`voxel_hits` (parity-tested); selected by
    ``FilterConfig.voxel_backend``.
    """
    gx, gy, inb = _voxel_cells(xy, mask, grid, cell_m)
    cells = jnp.arange(grid, dtype=jnp.int32)
    # mask folded into one side only: a dead/out-of-grid point is all-zero
    ohx = ((gx[:, None] == cells[None, :]) & inb[:, None]).astype(jnp.bfloat16)
    ohy = (gy[:, None] == cells[None, :]).astype(jnp.bfloat16)
    # graftlint: disable=GL004 — the one sanctioned float accumulation:
    # 0/1 one-hot products are exact in bf16 and the f32 accumulation is
    # exact for counts < 2^24, so order of reduction cannot matter
    counts = jnp.einsum(
        "bi,bj->ij", ohx, ohy, preferred_element_type=jnp.float32
    )
    # graftlint: policed — exact small integers in f32 (see above)
    return counts.astype(jnp.int32)


def select_voxel_hits(backend: str):
    """The one ``voxel_backend`` -> kernel mapping ("scatter" | "matmul").
    Strict: an unresolved "auto" or a typo must fail loudly, not silently
    run the scatter kernel under a mislabeled A/B."""
    try:
        return {"scatter": voxel_hits, "matmul": voxel_hits_matmul}[backend]
    except KeyError:
        raise ValueError(
            f"voxel_backend must be 'scatter' or 'matmul' once resolved, "
            f"got {backend!r}"
        ) from None


# ---------------------------------------------------------------------------
# fused chain step
# ---------------------------------------------------------------------------


# The ScanBatch-level debug/parity API stays non-donating on purpose:
# the filter suites call it repeatedly on the SAME input state for A/B
# trajectory comparison.  Every production wire entry below donates.
# graftlint: disable=GL003 — non-donating debug/parity API (see above)
@functools.partial(jax.jit, static_argnames=("cfg",))
def filter_step(
    state: FilterState, batch: ScanBatch, cfg: FilterConfig
) -> tuple[FilterState, FilterOutput]:
    return _filter_step_impl(state, batch, cfg)


def _filter_step_impl(
    state: FilterState, batch: ScanBatch, cfg: FilterConfig
) -> tuple[FilterState, FilterOutput]:
    """One revolution through the full chain; single fused XLA program.

    clip -> grid resample -> ring-buffer update -> temporal median ->
    polar->Cartesian -> voxel accumulate (incremental: add the new scan's
    hit grid, retire the one falling out of the window).  The clip
    stage folds into the resample-key mask (no clipped-batch
    materialization; the on-chip ablation priced the standalone pass at
    ~9 us/step of a ~30 us step).
    """
    if cfg.resample_backend == "dense":
        beam, packed = _resample_keys(batch, cfg.beams, cfg)
        ranges, inten = grid_resample_batch(beam[None], packed[None], cfg.beams)
        ranges, inten = ranges[0], inten[0]
    elif cfg.resample_backend == "scatter":
        ranges, inten = grid_resample(batch, cfg.beams, cfg)
    else:
        raise ValueError(
            f"resample_backend must be 'scatter' or 'dense', got "
            f"{cfg.resample_backend!r}"
        )

    rw = jax.lax.dynamic_update_index_in_dim(state.range_window, ranges, state.cursor, 0)
    iw = jax.lax.dynamic_update_index_in_dim(state.inten_window, inten, state.cursor, 0)
    filled = jnp.minimum(state.filled + 1, rw.shape[0])

    ms = state.median_sorted
    if cfg.enable_median:
        if cfg.median_backend.startswith("inc"):
            # incremental sliding median: the ring evicts exactly ONE
            # value per step, so the sorted multiset is maintained by a
            # delete+insert (O(W) elementwise) instead of re-sorted
            ms, med = inc_median(
                state.range_window, state.cursor, ms, ranges,
                backend=cfg.median_backend,
            )
        elif cfg.median_backend == "pallas":
            from rplidar_ros2_driver_tpu.ops.pallas_kernels import (
                temporal_median_pallas,
            )

            med = temporal_median_pallas(rw)
        else:
            med = temporal_median(rw)
    else:
        med = ranges
    xy, mask = polar_to_cartesian(med, cfg.beams)

    if cfg.enable_voxel:
        new_hits = select_voxel_hits(cfg.voxel_backend)(
            xy, mask, cfg.grid, cfg.cell_m
        )
        old_hits = jax.lax.dynamic_index_in_dim(
            state.hit_window, state.cursor, 0, keepdims=False
        )
        voxel_acc = state.voxel_acc + new_hits - old_hits
        hw = jax.lax.dynamic_update_index_in_dim(
            state.hit_window, new_hits, state.cursor, 0
        )
    else:
        voxel_acc = state.voxel_acc
        hw = state.hit_window

    new_state = FilterState(
        range_window=rw,
        inten_window=iw,
        hit_window=hw,
        voxel_acc=voxel_acc,
        cursor=(state.cursor + 1) % rw.shape[0],
        filled=filled,
        median_sorted=ms,
    )
    out = FilterOutput(
        ranges=med,
        intensities=inten,
        points_xy=xy,
        point_mask=mask,
        voxel=voxel_acc,
    )
    return new_state, out


# ---------------------------------------------------------------------------
# packed streaming ingest — the production host->device path
# ---------------------------------------------------------------------------
#
# Shipping a ScanBatch field-by-field costs one transfer dispatch per array;
# through a remote-attached TPU each dispatch carries link overhead (measured
# ~5 ms/scan on the axon tunnel).  The streaming path instead ships ONE
# (4, N) int32 array [angle_q14; dist_q2; quality; flag] plus a count scalar
# and rebuilds the ScanBatch inside the jitted program.  The state is donated
# so the rolling window updates in place (no HBM churn at W x B scale).

PACKED_FIELDS = 4  # rows: angle_q14, dist_q2, quality, flag


def pack_host_scan(
    angle_q14, dist_q2, quality, flag=None, n: int | None = None
):
    """Pack raw host arrays into the single (4, n) transfer buffer + count."""
    import numpy as np

    from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES

    n = n or MAX_SCAN_NODES
    count = int(len(angle_q14))
    if count > n:
        raise ValueError(f"scan of {count} nodes exceeds capacity {n}")
    buf = np.zeros((PACKED_FIELDS, n), np.int32)
    buf[0, :count] = angle_q14
    buf[1, :count] = dist_q2
    buf[2, :count] = quality
    if flag is not None:
        buf[3, :count] = flag
    return buf, count


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def packed_filter_step(
    state: FilterState, packed: jax.Array, count: jax.Array, cfg: FilterConfig
) -> tuple[FilterState, FilterOutput]:
    """filter_step over the single-buffer wire form (see module note above)."""
    i = jnp.arange(packed.shape[1], dtype=jnp.int32)
    live = i < count
    batch = ScanBatch(
        angle_q14=packed[0],
        dist_q2=packed[1],
        quality=packed[2],
        flag=packed[3],
        valid=live,
        count=count,
    )
    return _filter_step_impl(state, batch, cfg)


def _pack_compact_rows(buf, capacity: int, angle_q14, dist_q2, quality, flag) -> int:
    """Fill the leading columns of a (3, >=capacity) uint16 buffer with the
    bit-packed node stream; the one definition of the row layout shared by
    the compact and counted wire forms.  Returns the node count.

    Layout (6 bytes/point): row0 = angle_q14; row1 = dist_q2 low 16;
    row2 = dist_q2 bits 17:16 | quality<<2 | flag<<10.  Distance is
    clamped to 18 bits (2^18 q2 = 65.5 m — beyond any supported lidar;
    the reference's own max is 40 m) and flag to 6 bits (the wire flag
    uses 2: sync + inverse-sync), mirroring how malformed angles clamp
    into the edge beams rather than being dropped."""
    import numpy as np

    count = int(len(angle_q14))
    if count > capacity:
        raise ValueError(f"scan of {count} nodes exceeds capacity {capacity}")
    d = np.minimum(
        np.asarray(dist_q2, np.int64).astype(np.uint32), np.uint32(0x3FFFF)
    )
    buf[0, :count] = np.asarray(angle_q14, np.uint32).astype(np.uint16)
    buf[1, :count] = (d & 0xFFFF).astype(np.uint16)
    hi = (d >> 16).astype(np.uint16)
    hi |= ((np.asarray(quality, np.uint32) & 0xFF) << 2).astype(np.uint16)
    if flag is not None:
        hi |= ((np.asarray(flag, np.uint32) & 0x3F) << 10).astype(np.uint16)
    buf[2, :count] = hi
    return count


def pack_host_scan_compact(angle_q14, dist_q2, quality, flag=None, n: int | None = None):
    """Bit-packed wire form: (3, n) uint16, 6 bytes/point (see
    :func:`_pack_compact_rows` for the row layout and clamps).

    Over a remote-attached TPU the per-scan transfer is the pipeline
    bottleneck and its cost is size-dependent (~36 µs/KB marginal on the
    axon tunnel), so wire bytes matter more than device-side unpack
    arithmetic; 6 bytes/point cuts a DenseBoost revolution from 32 KB
    (the earlier (2, n) uint32 form) to 24 KB.
    """
    import numpy as np

    from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES

    n = n or MAX_SCAN_NODES
    buf = np.zeros((3, n), np.uint16)
    count = _pack_compact_rows(buf, n, angle_q14, dist_q2, quality, flag)
    return buf, count


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def compact_filter_step(
    state: FilterState, packed: jax.Array, count: jax.Array, cfg: FilterConfig
) -> tuple[FilterState, FilterOutput]:
    """filter_step over the compact (3, n) uint16 wire form."""
    return _filter_step_impl(state, _unpack_compact(packed, count), cfg)


def pack_host_scan_counted(angle_q14, dist_q2, quality, flag=None, n: int | None = None):
    """Count-embedded wire form: :func:`pack_host_scan_compact` plus one
    extra column whose angle-row slot holds the node count, so the hot
    path ships ONE ``(3, n + 1)`` array per revolution instead of buffer
    + count scalar.

    Through a remote-attached device every host->device transfer is a
    separate RPC enqueue; measured on the axon tunnel the second (scalar)
    put roughly doubles the paced per-scan dispatch latency (p99 ~2.2 ms
    -> ~1.3 ms with the count folded in).  The count slot is an *extra*
    column (6 wire bytes), not a reservation out of ``n``, so capacity-
    filling revolutions (the assembler truncates at MAX_SCAN_NODES,
    matching the reference's 8192-node cap) keep every node; the count
    (<= 8192) fits the u16 slot.
    """
    import numpy as np

    from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES

    n = n or MAX_SCAN_NODES
    if n >= 0x10000:
        # the count slot is u16: a larger capacity would silently wrap
        # the count and mask out most of the scan
        raise ValueError(f"counted wire form supports capacity < 65536, got {n}")
    buf = np.zeros((3, n + 1), np.uint16)
    count = _pack_compact_rows(buf, n, angle_q14, dist_q2, quality, flag)
    buf[0, -1] = count
    return buf


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def counted_filter_step(
    state: FilterState, packed: jax.Array, cfg: FilterConfig
) -> tuple[FilterState, FilterOutput]:
    """filter_step over the count-embedded wire form (one transfer/scan).

    The count slot sits at index ``n`` of a ``(3, n + 1)`` buffer and the
    count is at most ``n``, so the slot itself can never enter the
    ``i < count`` live mask.
    """
    count = packed[0, -1].astype(jnp.int32)
    return _filter_step_impl(state, _unpack_compact(packed, count), cfg)


def _unpack_compact(packed: jax.Array, count: jax.Array) -> ScanBatch:
    i = jnp.arange(packed.shape[1], dtype=jnp.int32)
    live = i < count
    hi = packed[2].astype(jnp.int32)
    return ScanBatch(
        angle_q14=packed[0].astype(jnp.int32),
        dist_q2=packed[1].astype(jnp.int32) | ((hi & 0x3) << 16),
        quality=(hi >> 2) & 0xFF,
        flag=(hi >> 10) & 0x3F,
        valid=live,
        count=count,
    )


# -- fused multi-scan sequence step ------------------------------------------
#
# Offline/replay throughput path: K scans advance the rolling window in ONE
# dispatch, amortizing the per-scan dispatch + transfer overhead that bounds
# the streaming path.  Returns the per-scan median-filtered range images and
# the final state (whose voxel_acc is the window accumulation after the last
# scan); the full per-scan FilterOutput is deliberately not materialized
# (K x ~300 KB would turn a throughput path into an HBM bandwidth test).
#
# The production implementation is PARALLEL, not a lax.scan: a sequential
# K-step loop costs ~80 us/scan of per-iteration overhead on TPU regardless
# of the body (measured r2 — shrinking window/grid doesn't move it), while
# none of the chain's data dependencies are actually sequential:
#   * unpack/clip/resample are per-scan independent -> one batched kernel;
#   * the rolling window after step i is, by construction, the W most
#     recent rows of [previous window in age order] ++ [new rows], so every
#     step's median is a sliding-window gather over one extended array —
#     K independent (W, B) medians in one sort;
#   * the voxel accumulator after the last step is the sum of the final
#     window's per-scan hit grids (the incremental add-new/retire-old of
#     the streaming step telescopes).
# The lax.scan form is kept as _compact_filter_scan_sequential: it is the
# semantic definition (exactly K compact_filter_step calls) that the
# parallel path is parity-tested against.


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def _compact_filter_scan_sequential(
    state: FilterState, packed_seq: jax.Array, counts: jax.Array, cfg: FilterConfig
) -> tuple[FilterState, jax.Array]:
    """Reference form: literally K successive compact_filter_step calls."""

    def body(st, xs):
        pk, ct = xs
        st, out = _filter_step_impl(st, _unpack_compact(pk, ct), cfg)
        return st, out.ranges

    state, ranges = jax.lax.scan(body, state, (packed_seq, counts))
    return state, ranges


def fused_scan_core(
    state: FilterState,
    packed_seq: jax.Array,
    counts: jax.Array,
    cfg: FilterConfig,
    *,
    keys_fn,
    polar_fn,
    hits_fn,
) -> tuple[FilterState, jax.Array]:
    """The one fused K-scan formulation, shared by the single-device path
    (:func:`compact_filter_scan`) and the sharded path
    (parallel/sharding._filter_scan_shard).  The callers inject the three
    partition-dependent primitives; every piece of boundary arithmetic —
    history stripe, sliding-median indexing, ring restore, telescoped
    hit-window merge — lives only here.

    * ``keys_fn(batch) -> (beam, packed)`` — resample keys (global beam
      indices, or shard-local with out-of-slice points carrying INF);
    * ``polar_fn(med_row) -> (xy, mask)`` — Cartesian projection for one
      range row (global or shard-offset beam angles);
    * ``hits_fn(xy, mask) -> (m, G, G)`` — per-scan occupancy grids for
      the batch, including any cross-shard reduction.
    """
    k = packed_seq.shape[0]
    w = state.range_window.shape[0]

    # 1. unpack + clip + resample every scan in parallel (dense tiled
    # min — a vmapped scatter would serialize, see grid_resample_batch)
    def keys_one(pk, ct):
        # clip folds into keys_fn's drop mask (see _resample_keys /
        # _resample_keys_shard) — no clipped-batch materialization
        return keys_fn(_unpack_compact(pk, ct))

    beam_k, packed_k = jax.vmap(keys_one)(packed_seq, counts)  # (K, P) each
    b_local = state.range_window.shape[1]
    new_r, new_i = grid_resample_batch(beam_k, packed_k, b_local)  # (K, B)

    # 2. extended history: previous ring in age order (oldest first), then
    # the new rows.  After step i the live window is ext[i+1 : i+1+W].
    prev_r = jnp.roll(state.range_window, -state.cursor, axis=0)
    ext_r = jnp.concatenate([prev_r, new_r], axis=0)  # (W+K, B)

    # 3. every step's median in one batched pass over the history stripe.
    # Pallas: sliding windows are overlapping VMEM slices of the stripe —
    # no gather, nothing re-fetched from HBM.  XLA: materialize the K
    # windows in (W, K, B) order and flatten, one (W, K*B) lane median.
    if cfg.enable_median:
        if cfg.median_backend == "pallas":
            from rplidar_ros2_driver_tpu.ops.pallas_kernels import (
                sliding_median_pallas,
            )

            med = sliding_median_pallas(ext_r, w)
        else:
            win_idx = jnp.arange(w)[:, None] + jnp.arange(1, k + 1)[None, :]  # (W, K)
            windows = ext_r[win_idx].reshape(w, k * b_local)
            med = temporal_median(windows).reshape(k, b_local)
    else:
        med = new_r

    # 4. final window state: the W most recent rows, restored to ring
    # layout (ring = roll(age-ordered, +cursor'))
    cursor2 = (state.cursor + jnp.asarray(k, state.cursor.dtype)) % w
    prev_i = jnp.roll(state.inten_window, -state.cursor, axis=0)
    ext_i = jnp.concatenate([prev_i, new_i], axis=0)
    range_window = jnp.roll(ext_r[k : k + w], cursor2, axis=0)
    inten_window = jnp.roll(ext_i[k : k + w], cursor2, axis=0)
    filled = jnp.minimum(state.filled + k, w)

    # 5. voxel: the accumulator after the last step is the sum of the
    # final window's hit grids (incremental add/retire telescopes); only
    # the last min(K, W) scans' grids survive, so the Cartesian
    # projection is restricted to those scans
    if cfg.enable_voxel:
        m = min(k, w)
        xy, mask = jax.vmap(polar_fn)(med[k - m :])
        new_hits = hits_fn(xy, mask)  # (m, G, G)
        if m < w:
            prev_h = jnp.roll(state.hit_window, -state.cursor, axis=0)
            ext_h = jnp.concatenate([prev_h[k:], new_hits], axis=0)  # (W,)
        else:
            ext_h = new_hits
        hit_window = jnp.roll(ext_h, cursor2, axis=0)
        voxel_acc = jnp.sum(ext_h, axis=0)
    else:
        hit_window = state.hit_window
        voxel_acc = state.voxel_acc

    final = FilterState(
        range_window=range_window,
        inten_window=inten_window,
        hit_window=hit_window,
        voxel_acc=voxel_acc,
        cursor=cursor2,
        filled=filled,
        # the fused path advances K scans at once, so the incremental
        # backend's derived state is re-sorted wholesale (one sort per
        # K-chunk, amortized) to restore the invariant
        median_sorted=(
            recompute_median_sorted(range_window)
            if state.median_sorted is not None else None
        ),
    )
    return final, med


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def compact_filter_scan(
    state: FilterState, packed_seq: jax.Array, counts: jax.Array, cfg: FilterConfig
) -> tuple[FilterState, jax.Array]:
    """Run the chain over a (K, 3, N) uint16 packed scan sequence.

    Semantically identical to K successive ``compact_filter_step`` calls
    (same state trajectory — tests/test_packed_ingest.py asserts equality
    against both the per-step calls and _compact_filter_scan_sequential);
    ``counts`` is (K,) int32.  Returns (final state, (K, beams) ranges).
    """
    return fused_scan_core(
        state,
        packed_seq,
        counts,
        cfg,
        keys_fn=lambda batch: _resample_keys(batch, cfg.beams, cfg),
        polar_fn=lambda row: polar_to_cartesian(row, cfg.beams),
        hits_fn=lambda xy, mask: jax.vmap(
            select_voxel_hits(cfg.voxel_backend), in_axes=(0, 0, None, None)
        )(xy, mask, cfg.grid, cfg.cell_m),
    )


def pack_host_scans_compact(scans, n: int | None = None):
    """Stack host scans into the (K, 3, n) sequence buffer + (K,) counts
    (the multi-scan form of :func:`pack_host_scan_compact`)."""
    import numpy as np

    from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES

    n = n or MAX_SCAN_NODES
    k = len(scans)
    seq = np.zeros((k, 3, n), np.uint16)
    counts = np.zeros((k,), np.int32)
    for i, s in enumerate(scans):
        seq[i], counts[i] = pack_host_scan_compact(
            s["angle_q14"], s["dist_q2"], s["quality"], s.get("flag"), n
        )
    return seq, counts


# -- fused single-fetch output -----------------------------------------------
#
# Pulling FilterOutput field-by-field costs one device->host round trip per
# array (5/scan); over a remote-attached TPU each trip is link RTT, which
# dwarfs the compute.  The wire variant concatenates every output into ONE
# flat float32 vector inside the jitted step, so the host pays exactly one
# fetch per revolution and slices it back apart locally.


def wire_output_len(cfg: FilterConfig) -> int:
    return 5 * cfg.beams + cfg.grid * cfg.grid


def _pack_output_wire(out: FilterOutput) -> jax.Array:
    """The one definition of the flat wire layout — ``unpack_output_wire``
    and ``wire_output_len`` are its host-side inverses; keep all three in
    lockstep."""
    return jnp.concatenate(
        [
            out.ranges,
            out.intensities,
            out.points_xy.reshape(-1),
            out.point_mask.astype(jnp.float32),
            out.voxel.reshape(-1).astype(jnp.float32),  # exact to 2^24 counts
        ]
    )


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def compact_filter_step_wire(
    state: FilterState, packed: jax.Array, count: jax.Array, cfg: FilterConfig
) -> tuple[FilterState, jax.Array]:
    """compact_filter_step returning the single-fetch flat output vector."""
    state, out = _filter_step_impl(state, _unpack_compact(packed, count), cfg)
    return state, _pack_output_wire(out)


@functools.partial(jax.jit, static_argnames=("cfg",), donate_argnums=(0,))
def counted_filter_step_wire(
    state: FilterState, packed: jax.Array, cfg: FilterConfig
) -> tuple[FilterState, jax.Array]:
    """compact_filter_step_wire over the count-embedded wire form: ONE
    transfer in, one donated dispatch, one flat fetch out — the minimal
    per-revolution host<->device traffic."""
    count = packed[0, -1].astype(jnp.int32)
    state, out = _filter_step_impl(state, _unpack_compact(packed, count), cfg)
    return state, _pack_output_wire(out)


def unpack_output_wire(wire, cfg: FilterConfig) -> FilterOutput:
    """Host-side inverse of the wire packing (numpy in, numpy out).

    Slices are copied: a view would pin the whole ~300 KB wire vector for
    as long as any published message (e.g. an 8 KB ranges array sitting in
    a subscriber queue) stays alive.
    """
    import numpy as np

    b, g = cfg.beams, cfg.grid
    w = np.asarray(wire)
    if w.size != wire_output_len(cfg):
        raise ValueError(
            f"wire vector of {w.size} floats does not match cfg "
            f"(expected {wire_output_len(cfg)})"
        )
    return FilterOutput(
        ranges=w[:b].copy(),
        intensities=w[b : 2 * b].copy(),
        points_xy=w[2 * b : 4 * b].reshape(b, 2).copy(),
        point_mask=w[4 * b : 5 * b] != 0.0,
        voxel=w[5 * b :].reshape(g, g).astype(np.int32),
    )
