"""Integer tile quantization + run-length ops — the world map's wire form.

The shared-world plane (mapping/worldmap.py) accumulates per-stream
submap log-odds as RAW int32 sums: integer addition is associative and
commutative even at wrap, so ANY merge order — per-stream, shuffled,
or sharded partial sums merged later — lands the bit-identical
accumulation.  This module holds the two halves of that plane's
arithmetic contract:

  * FUSION — ``fuse_accumulate`` / ``fuse_retract``: the device-
    resident merge and its exact inverse (int32 addition forms a
    group, so evicting a submap is a subtraction that restores the
    accumulation byte-for-byte to the sum of the survivors).  Jitted
    with the accumulation donated — a merge never copies the world
    plane — and warmed by ``WorldMap.precompile`` so a merge inside a
    guarded steady-state loop pays zero compiles.
  * SERVING QUANTIZATION — SR-LIO++-style int8/int4 level coding of
    the clamped accumulation plus nibble packing and run-length
    encoding, all pure integer (numpy is its own reference).  The
    round-trip error is BOUNDED by construction: a level reconstructs
    at its band midpoint, so occupied cells (level > 0) land within
    ``2^(shift-1)`` of the clamped value and empty-band cells (level
    0) within ``2^shift - 1`` — and level 0 reconstructs to exactly 0,
    so unknown space stays unknown instead of acquiring phantom
    occupancy (tests/test_world_map.py pins both bounds).

Quantization only ever runs at PUBLISH time, on the host, from an
explicitly fetched copy of the accumulation — the int32 sum is the
system of record and fusion never sees a quantization error.
"""

from __future__ import annotations

import functools

import jax
import numpy as np

TILE_QUANT_VERSION = 1

# serialized run cost: one int32 level is coded as a value byte (int8)
# or value nibble (int4) plus a 16-bit run length — the accounting the
# compression-ratio headline uses (bench --config 22)
RUN_LEN_BYTES = 2
RUN_LEN_MAX = (1 << (8 * RUN_LEN_BYTES)) - 1


def min_tile_shift(clamp_q: int, bits: int) -> int:
    """Smallest right shift putting ``[0, clamp_q]`` into ``bits``
    unsigned levels — the tile analog of scan_match.min_quant_shift
    (same derivation: the level count is the hard ceiling, the shift
    is whatever clears it)."""
    if clamp_q < 1:
        raise ValueError("clamp_q must be positive")
    if bits < 1:
        raise ValueError("bits must be >= 1")
    levels = (1 << bits) - 1
    shift = 0
    while (clamp_q >> shift) > levels:
        shift += 1
    return shift


def quant_error_bound(shift: int) -> int:
    """Worst-case |dequantize(quantize(v)) - clip(v)| for OCCUPIED
    cells (level > 0): the band-midpoint distance ``2^(shift-1)``.
    Level-0 cells reconstruct to exactly 0, so their bound is the band
    width minus one, ``2^shift - 1`` (both pinned by test)."""
    return (1 << shift) >> 1


def quantize_plane(plane, clamp_q: int, shift: int) -> np.ndarray:
    """Clamp an int32 log-odds plane to ``[0, clamp_q]`` and code each
    cell as its ``>> shift`` level (int32 holding small unsigned
    values; the wire layer narrows).  Pure integer — its own
    reference, like quantize_submap_plane."""
    lo = np.clip(np.asarray(plane, np.int32), 0, int(clamp_q))
    return (lo >> int(shift)).astype(np.int32)


def dequantize_plane(levels, shift: int) -> np.ndarray:
    """Reconstruct each level at its band midpoint; level 0 stays
    exactly 0 (unknown space must not acquire phantom occupancy)."""
    lv = np.asarray(levels, np.int32)
    half = (1 << int(shift)) >> 1
    return np.where(lv > 0, (lv << int(shift)) + half, 0).astype(np.int32)


def pack_nibbles(levels) -> np.ndarray:
    """Pack int4 levels (values in [0, 15]) two per byte, low nibble
    first; odd counts pad with a zero nibble."""
    lv = np.asarray(levels, np.int32).reshape(-1)
    if lv.size % 2:
        lv = np.concatenate([lv, np.zeros((1,), np.int32)])
    return ((lv[0::2] & 0xF) | ((lv[1::2] & 0xF) << 4)).astype(np.uint8)


def unpack_nibbles(packed, count: int) -> np.ndarray:
    """Inverse of :func:`pack_nibbles` — ``count`` trims the pad."""
    p = np.asarray(packed, np.uint8).astype(np.int32)
    lv = np.empty((p.size * 2,), np.int32)
    lv[0::2] = p & 0xF
    lv[1::2] = (p >> 4) & 0xF
    return lv[: int(count)]


def rle_encode(levels) -> tuple:
    """Run-length code a flat level array: ``(values, runs)`` int32,
    runs capped at ``RUN_LEN_MAX`` (a longer run splits — the 16-bit
    run field is the wire contract).  Deterministic and pure integer."""
    lv = np.asarray(levels, np.int32).reshape(-1)
    if lv.size == 0:
        return np.zeros((0,), np.int32), np.zeros((0,), np.int32)
    edges = np.flatnonzero(np.diff(lv)) + 1
    starts = np.concatenate([np.zeros((1,), np.int64), edges])
    ends = np.concatenate([edges, np.asarray([lv.size], np.int64)])
    values, runs = [], []
    for s, e in zip(starts, ends):
        n = int(e - s)
        v = int(lv[s])
        while n > RUN_LEN_MAX:
            values.append(v)
            runs.append(RUN_LEN_MAX)
            n -= RUN_LEN_MAX
        values.append(v)
        runs.append(n)
    return (
        np.asarray(values, np.int32),
        np.asarray(runs, np.int32),
    )


def rle_decode(values, runs) -> np.ndarray:
    """Inverse of :func:`rle_encode`."""
    return np.repeat(
        np.asarray(values, np.int32), np.asarray(runs, np.int64)
    ).astype(np.int32)


def rle_payload_bytes(n_runs: int, bits: int) -> int:
    """Serialized size of an RLE stream: one level (byte or packed
    nibble) plus a ``RUN_LEN_BYTES`` run count per run."""
    n = int(n_runs)
    if bits == 4:
        value_bytes = (n + 1) // 2
    else:
        value_bytes = n
    return value_bytes + RUN_LEN_BYTES * n


# ---------------------------------------------------------------------------
# device-resident fusion — the merge op and its exact inverse
# ---------------------------------------------------------------------------


@functools.partial(jax.jit, donate_argnums=(0,))
# graftlint: disable=GL011 — the accumulation is RAW int32 with wrap as
# the documented group contract (retract is the exact inverse); no bound
# exists to declare
def fuse_accumulate(acc, plane):
    """``acc + plane`` with the accumulation donated in place — the
    world merge op.  int32 addition is associative/commutative (wrap
    included), so any merge order is bit-identical; the numpy twin is
    the same expression (tests pin shuffled-order byte-equality)."""
    return acc + plane


@functools.partial(jax.jit, donate_argnums=(0,))
# graftlint: disable=GL011 — same wrap-group contract as fuse_accumulate
def fuse_retract(acc, plane):
    """``acc - plane`` with the accumulation donated — submap
    EVICTION.  Addition forms a group over int32, so retracting a
    member restores the accumulation byte-for-byte to the sum of the
    survivors (the bounded-resident-bytes contract's exactness half)."""
    return acc - plane


# graftlint: disable=GL011 — host twin of the wrap-group accumulation
def fuse_planes_np(planes) -> np.ndarray:
    """Host twin of an arbitrary-order fusion: the plain int32 sum of
    a sequence of planes (the shuffled-order oracle the bench and
    tests fold against the device accumulation)."""
    out = None
    for p in planes:
        arr = np.asarray(p, np.int32)
        out = arr.copy() if out is None else out + arr
    if out is None:
        raise ValueError("fuse_planes_np needs at least one plane")
    return out
