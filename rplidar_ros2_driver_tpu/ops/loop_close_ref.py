"""NumPy golden reference for the loop-closure back-end
(ops/loop_close.py).

The loop engine's ``loop_backend=host`` path and the parity suite's
oracle: a literal transcription of the fused closure-check program into
numpy — batched candidate match (ops/scan_match_ref.match_scan_volumes_np
per candidate), the integer acceptance gates, the constraint append and
the pose-graph relaxation (ops/pose_graph_ref.solve_pose_graph_np) —
step for step.  The datapath is int32 end to end, so this reference is
BIT-EXACT against the jitted single-stream and vmapped fleet lowerings
(tests/test_loop_close.py pins fleet sizes 1/3/8 byte-for-byte).

Keep every function here in literal lockstep with its ops/loop_close.py
twin; a divergence is a bug in whichever side moved.
"""

from __future__ import annotations

import numpy as np

from rplidar_ros2_driver_tpu.ops.loop_close import (
    ODOM_WEIGHT,
    WIRE_LEN,
    LoopConfig,
    LoopState,
)
from rplidar_ros2_driver_tpu.ops.pose_graph_ref import (
    pose_compose_np,
    pose_relative_np,
    rel_inverse_np,
    solve_pose_graph_np,
)
from rplidar_ros2_driver_tpu.ops.scan_match import rotation_table
from rplidar_ros2_driver_tpu.ops.scan_match_ref import (
    match_scan_volumes_np,
    quantize_points_np,
)

INT32_MIN1 = -(2**31) + 1


def create_loop_state_np(cfg: LoopConfig) -> dict:
    """Fresh host-side LoopState as the snapshot dict layout."""
    return {
        k: np.zeros(v, np.int32) for k, v in LoopState.shapes(cfg).items()
    }


def install_submap_np(state: dict, plane, anchor, cfg: LoopConfig) -> dict:
    """Literal twin of ops/loop_close._install_submap_impl."""
    k = cfg.max_submaps
    div = cfg.match.theta_divisions
    table = rotation_table(div)
    count = int(state["count"])
    if count >= k:                      # cap-and-hold: library frozen
        return state
    slot = count
    if count == 0:
        odom_leg = np.zeros((3,), np.int32)
    else:
        prev = state["anchors"][count - 1]
        odom_leg = pose_relative_np(prev, np.asarray(anchor), table, div)
    out = {key: np.asarray(v).copy() for key, v in state.items()}
    out["planes"][slot] = np.asarray(plane, np.int32)
    out["anchors"][slot] = np.asarray(anchor, np.int32)
    out["odom"][slot] = odom_leg
    out["valid"][slot] = 1
    out["count"] = np.int32(count + 1)
    return out


def loop_close_step_np(
    state: dict, points_xy, mask, pose, cand_idx, check: int,
    cfg: LoopConfig,
):
    """One host-reference closure check — the literal twin of
    ops/loop_close._loop_close_step_impl.  Returns (new state dict,
    (WIRE_LEN,) int32 wire row, (K, 3) corrected anchors)."""
    m = cfg.match
    k = cfg.max_submaps
    div = m.theta_divisions
    lim = m.t_limit_sub
    table = rotation_table(div)
    pose = np.asarray(pose, np.int32)
    cand_idx = np.asarray(cand_idx, np.int32)

    pq, ok = quantize_points_np(points_xy, mask, m)
    ok = ok & (int(check) > 0)
    n_valid = int(np.sum(ok))

    slots = np.clip(cand_idx, 0, k - 1)
    cvalid = (cand_idx >= 0) & (state["valid"][slots] > 0)
    bests = np.full(len(cand_idx), INT32_MIN1, dtype=np.int32)
    dposes = np.zeros((len(cand_idx), 3), dtype=np.int32)
    minvs = np.zeros((len(cand_idx),), dtype=np.int32)
    for c in range(len(cand_idx)):
        dp, b, mv = match_scan_volumes_np(
            state["planes"][slots[c]], pose, pq, ok, m
        )
        dposes[c], minvs[c] = dp, mv
        bests[c] = b if cvalid[c] else INT32_MIN1
    kc = int(np.argmax(bests))                                  # first-max-wins
    best = int(bests[kc])
    dpose = dposes[kc]
    minv = int(minvs[kc])
    best_slot = int(slots[kc])
    has_cand = bool(np.any(cvalid))

    accept = (
        int(check) > 0
        and has_cand
        and n_valid >= cfg.min_points
        and best > 0
        and best >= n_valid * cfg.accept_q
        and (best - minv) >= (best >> cfg.peak_shift)
    )

    p_m = np.asarray([
        np.clip(pose[0] + dpose[0], -lim, lim),
        np.clip(pose[1] + dpose[1], -lim, lim),
        np.mod(pose[2] + dpose[2], div),
    ], np.int32)
    count = int(state["count"])
    last = int(np.clip(count - 1, 0, k - 1))
    a_last = state["anchors"][last]
    a_best = state["anchors"][best_slot]
    o_cur = pose_relative_np(a_last, pose, table, div)
    z_jc = pose_relative_np(a_best, p_m, table, div)
    z_ij = pose_compose_np(
        o_cur, rel_inverse_np(z_jc, table, div), table, div
    )
    room = int(state["ncons"]) < cfg.max_constraints
    do_append = accept and room
    cons = state["cons"].copy()
    if do_append:
        cons[int(state["ncons"])] = np.concatenate([
            np.asarray([last, best_slot], np.int32), z_ij,
            np.asarray([cfg.weight], np.int32),
        ])
    ncons = np.int32(int(state["ncons"]) + int(do_append))
    dropped = np.int32(int(state["dropped"]) + int(accept and not room))

    ks = np.arange(k, dtype=np.int32)
    odom_w = ((ks >= 1) & (ks < count)).astype(np.int32) * ODOM_WEIGHT
    odom_rows = np.stack([
        np.maximum(ks - 1, 0), ks,
        state["odom"][:, 0], state["odom"][:, 1], state["odom"][:, 2],
        odom_w,
    ], axis=1).astype(np.int32)
    all_cons = np.concatenate([odom_rows, cons], axis=0)
    corrected = solve_pose_graph_np(state["anchors"], all_cons, cfg.graph)

    cur_c = pose_compose_np(corrected[last], o_cur, table, div)
    cur_c = np.asarray([
        np.clip(cur_c[0], -lim, lim),
        np.clip(cur_c[1], -lim, lim),
        cur_c[2],
    ], np.int32)
    if count == 0:
        cur_c = pose.copy()

    anchors = state["anchors"]
    if cfg.reanchor and accept:
        anchors = corrected.copy()

    new_state = {
        "planes": state["planes"], "anchors": anchors,
        "odom": state["odom"], "valid": state["valid"],
        "count": state["count"], "cons": cons,
        "ncons": ncons, "dropped": dropped,
    }
    wire = np.concatenate([
        np.asarray([
            int(accept),
            best_slot if has_cand else -1,
            max(best, 0) if has_cand else 0,
            n_valid,
        ], np.int32),
        cur_c,
        np.asarray([int(ncons), int(dropped)], np.int32),
    ]).astype(np.int32)
    assert wire.shape == (WIRE_LEN,)
    return new_state, wire, corrected
