"""Standalone entry point — equivalent of src/standalone_main.cpp.

The reference's main is rclcpp::init -> RPlidarNode -> executor spin
(src/standalone_main.cpp:6-17).  Here:

    python -m rplidar_ros2_driver_tpu run [--params FILE] [--dummy] [--duration S]
    python -m rplidar_ros2_driver_tpu view [--scans N] [--pgm PATH]
    python -m rplidar_ros2_driver_tpu udev [--install]
"""

from __future__ import annotations

import argparse
import logging
import sys
import time


def _cmd_run(args) -> int:
    from rplidar_ros2_driver_tpu.launch import launch_lifecycle
    from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState

    overrides = {}
    if args.dummy:
        overrides["dummy_mode"] = True
    node = launch_lifecycle(args.params, overrides=overrides or None)
    if node.lifecycle_state is not LifecycleState.ACTIVE:
        print("bringup failed (see log)", file=sys.stderr)
        return 1
    pub = node.publisher
    deadline = time.monotonic() + args.duration if args.duration else None
    try:
        while deadline is None or time.monotonic() < deadline:
            time.sleep(1.0)
            node._update_diagnostics()
            diag = pub.diagnostics[-1] if getattr(pub, "diagnostics", None) else None
            scans = getattr(pub, "scan_count", 0)
            state = diag.message if diag else "?"
            note = ""
            if scans == 0 and state == "Scanning":
                # healthy but nothing out yet: first revolutions pay the
                # device compile and (on remote-attached rigs) output
                # fetch round-trips
                note = " (first publish pending: device compile/fetch)"
            print(f"[{node.name}] scans={scans} state={state}{note}")
    except KeyboardInterrupt:
        pass
    finally:
        if node.lifecycle_state is LifecycleState.ACTIVE:
            node.deactivate()
        if node.lifecycle_state is LifecycleState.INACTIVE:
            node.cleanup()
        node.shutdown()
    if args.stats:
        import json

        print(json.dumps(node.tracer.summary(), indent=2))
    return 0


def _view_defaults(path=None) -> dict:
    """Load config/rplidar_view.yaml (the rviz-config analog); CLI flags win."""
    import os

    import yaml

    here = os.path.dirname(os.path.abspath(__file__))
    path = path or os.path.join(os.path.dirname(here), "config", "rplidar_view.yaml")
    defaults = {"size_px": 256, "view_range_m": 4.0, "ascii_width": 64, "point_weight": 255}
    try:
        with open(path) as f:
            doc = yaml.safe_load(f)
        view = doc.get("view") if isinstance(doc, dict) else None
        if isinstance(view, dict):
            defaults.update(view)
        elif doc is not None:
            print(f"warning: ignoring malformed view config {path}", file=sys.stderr)
    except OSError:
        pass
    except yaml.YAMLError as e:
        print(f"warning: unreadable view config {path}: {e}", file=sys.stderr)
    return defaults


def _cmd_view(args) -> int:
    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.node.node import RPlidarNode
    from rplidar_ros2_driver_tpu.tools.viz import ascii_preview, save_pgm, scan_to_image

    view_cfg = _view_defaults(args.view_config)
    params = DriverParams(dummy_mode=True)
    node = RPlidarNode(params)
    node.configure()
    node.activate()
    pub = node.publisher
    try:
        t0 = time.monotonic()
        while pub.scan_count < args.scans and time.monotonic() - t0 < 30:
            time.sleep(0.05)
    finally:
        node.deactivate()
        node.cleanup()
        node.shutdown()
    if not pub.scans:
        print("no scans captured", file=sys.stderr)
        return 1
    img = scan_to_image(
        pub.scans[-1],
        size_px=int(view_cfg["size_px"]),
        view_range_m=args.range_m if args.range_m is not None else float(view_cfg["view_range_m"]),
        point_weight=int(view_cfg["point_weight"]),
    )
    if args.pgm:
        save_pgm(img, args.pgm)
        print(f"wrote {args.pgm}")
    else:
        print(ascii_preview(img, width=int(view_cfg["ascii_width"])))
    return 0


def _cmd_replay(args) -> int:
    from rplidar_ros2_driver_tpu.protocol.constants import Ans
    from rplidar_ros2_driver_tpu.replay import decode_recording

    per_stream = []
    runs_per_path = []
    for path in args.recordings:
        dec = decode_recording(path)
        revs = dec.revolutions()
        per_stream.append(revs)
        runs_per_path.append(len(dec.runs))
        print(f"{path}: {dec.num_nodes} nodes, {len(revs)} complete revolutions")
        for ans_type, n_frames, n_nodes in dec.runs:
            try:
                name = Ans(ans_type).name
            except ValueError:
                name = f"0x{ans_type:02x}"
            print(f"  run: {name:34s} {n_frames:6d} frames -> {n_nodes:7d} nodes")
        if revs:
            pts = [len(r["angle_q14"]) for r in revs]
            print(f"  points/rev: min={min(pts)} median={sorted(pts)[len(pts)//2]} max={max(pts)}")
    if args.chain and not all(per_stream):
        empty = [p for p, revs in zip(args.recordings, per_stream) if not revs]
        print(
            f"  --chain skipped: no complete revolutions in {', '.join(empty)}"
        )
    if args.chain and all(per_stream):
        import time as _time

        import numpy as np

        from rplidar_ros2_driver_tpu.core.config import DriverParams

        params = DriverParams(
            filter_backend="cpu" if args.cpu else "tpu",
            filter_chain=("clip", "median", "voxel"),
        )
        t0 = _time.perf_counter()
        if len(per_stream) == 1:
            from rplidar_ros2_driver_tpu.replay import replay_through_chain

            ranges, state = replay_through_chain(per_stream[0], params)
            what = "fused multi-scan step"
        else:
            # N recordings = N streams through the (stream, beam) mesh
            # (replay_fleet's default mesh divides any stream count)
            from rplidar_ros2_driver_tpu.replay import replay_fleet

            n_streams = len(per_stream)
            k_min = min(len(r) for r in per_stream)
            if any(len(r) != k_min for r in per_stream):
                print(
                    f"  note: recordings differ in length — fleet replay "
                    f"truncates every stream to {k_min} revolutions"
                )
            ranges, state = replay_fleet(per_stream, params)
            what = f"sharded fleet replay ({n_streams} streams)"
        dt = _time.perf_counter() - t0
        occupancy = int(np.asarray(state.voxel_acc).sum())
        n_scans = int(np.prod(ranges.shape[:-1]))
        finite = np.isfinite(ranges)
        print(
            f"  chain: {n_scans} scans through the {what} in "
            f"{dt:.2f} s ({n_scans / dt:.0f} scans/s); "
            f"median range {np.median(ranges[finite]):.2f} m, "
            f"voxel occupancy {occupancy}"
        )
    if args.fused:
        _replay_fused_report(args, per_stream, runs_per_path)
    if args.map or args.loop_close:
        # --loop-close implies the map report (the back-end IS the
        # map/trajectory pipeline plus correction)
        _replay_map_report(args, per_stream)
    return 0


def _replay_map_report(args, per_stream) -> None:
    """The `replay --map` arm: each recording's revolutions through the
    chain + SLAM front-end (replay.replay_with_map) — trajectory + final
    log-odds map, inspectable without ROS (ASCII preview by default,
    PGM via --map-pgm)."""
    import numpy as np

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.replay import replay_with_map
    from rplidar_ros2_driver_tpu.tools.viz import (
        ascii_preview,
        draw_trajectory,
        map_to_image,
        save_pgm,
    )

    params = DriverParams(
        filter_backend="cpu" if args.cpu else "tpu",
        filter_chain=("clip", "median", "voxel"),
        map_enable=True,
        map_backend=args.map_backend,
        loop_enable=bool(args.loop_close),
    )
    for i, (path, revs) in enumerate(zip(args.recordings, per_stream)):
        if not revs:
            print(f"{path}: --map skipped (no complete revolutions)")
            continue
        corrected = engine = None
        if args.loop_close:
            from rplidar_ros2_driver_tpu.replay import (
                replay_with_loop_closure,
            )

            traj, corrected, scores, mapper, engine = (
                replay_with_loop_closure(revs, params)
            )
        else:
            traj, scores, mapper = replay_with_map(revs, params)
        snap = mapper.snapshot()
        occupied = int(np.sum(snap["log_odds"][0] > 0))
        matched = int(np.sum(scores > 0))
        x, y, th = traj[-1]
        print(
            f"{path}: mapped {len(revs)} revolutions "
            f"({mapper.backend} backend): {matched} matched, "
            f"{occupied} occupied cells, final pose "
            f"({x:+.3f} m, {y:+.3f} m, {np.degrees(th):+.2f} deg)"
        )
        img = draw_trajectory(
            map_to_image(snap["log_odds"][0], mapper.cfg.clamp_q),
            traj[:, :2], mapper.cfg.cell_m,
            value=200 if corrected is not None else 255,
        )
        if corrected is not None:
            st = engine.status()
            cx, cy, cth = corrected[-1]
            print(
                f"  loop closure ({engine.backend} backend): "
                f"{st['accepted']} accepted / {st['rejected']} rejected, "
                f"{st['submaps'][0]} submaps, corrected final pose "
                f"({cx:+.3f} m, {cy:+.3f} m, {np.degrees(cth):+.2f} deg)"
            )
            # corrected trajectory overlaid BRIGHTER than the raw one,
            # same grid/orientation conventions (raw 200, corrected 255)
            img = draw_trajectory(
                img, corrected[:, :2], mapper.cfg.cell_m, value=255
            )
        if args.map_pgm:
            out = (
                args.map_pgm if len(per_stream) == 1
                else f"{args.map_pgm}.{i}"
            )
            save_pgm(img, out)
            print(f"  wrote {out}")
        else:
            # threshold: occupied evidence past half clamp (or the
            # trajectory overlay) shows as '#', unknown/free as '.'
            print(ascii_preview((img >= 192).astype(np.uint8), width=64))


def _replay_fused_report(args, per_stream, runs_per_path) -> None:
    """The `replay --fused` arm: raw capture bytes -> filtered scans
    end-to-end on device (replay.replay_raw_fused, the T-tick super-step
    drain) vs the host chain over the revolutions `_cmd_replay` already
    decoded (no second decode pass), parity-checked, with a scans/s
    throughput report for both.  A capture that switches scan modes
    legitimately diverges (replay_raw_fused replays it with the LIVE
    engine's reset semantics — see its docstring), so parity is reported
    as skipped there rather than failed."""
    import time as _time

    import numpy as np

    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.replay import (
        replay_raw_fused,
        replay_through_chain,
    )

    params = DriverParams(
        filter_backend="cpu" if args.cpu else "tpu",
        filter_chain=("clip", "median", "voxel"),
    )
    for path, revs, n_runs in zip(
        args.recordings, per_stream, runs_per_path
    ):
        t0 = _time.perf_counter()
        ranges_h, state_h = replay_through_chain(revs, params)
        dt_host = _time.perf_counter() - t0
        t0 = _time.perf_counter()
        ranges_f, state_f, stats = replay_raw_fused(path, params)
        dt_fused = _time.perf_counter() - t0
        n = ranges_f.shape[0]
        host_sps = n / dt_host if dt_host > 0 else float("inf")
        fused_sps = n / dt_fused if dt_fused > 0 else float("inf")
        if n_runs > 1:
            verdict = f"parity skipped (capture switches modes: {n_runs} runs)"
            parity = True
        else:
            parity = ranges_f.shape == ranges_h.shape and np.array_equal(
                ranges_f, ranges_h
            ) and np.array_equal(
                np.asarray(state_f.voxel_acc), np.asarray(state_h.voxel_acc)
            )
            verdict = f"parity {'OK' if parity else 'MISMATCH'}"
        print(
            f"{path}: fused raw replay {n} scans in {dt_fused:.2f} s "
            f"({fused_sps:.0f} scans/s, {stats['dispatches']} dispatches "
            f"for {stats['ticks']} ticks at T={stats['super_tick']}); "
            f"host chain {dt_host:.2f} s ({host_sps:.0f} scans/s); "
            f"{verdict}"
        )
        if not parity:
            raise SystemExit(
                f"{path}: fused raw replay diverged from the host path"
            )


def _cmd_doctor(args) -> int:
    """Environment self-check: every row prints PASS/WARN/FAIL + detail.

    Exit code is 1 only on FAIL (WARN covers degraded-but-working
    states like the pure-Python transport fallback)."""
    results: list[tuple[str, str, str]] = []

    def check(name: str, fn) -> None:
        try:
            level, detail = fn()
        except Exception as e:  # noqa: BLE001 - a crashed probe IS the finding
            level, detail = "FAIL", f"{type(e).__name__}: {e}"
        results.append((name, level, detail))

    def deps():
        # informational: a genuinely MISSING jax/numpy fails at package
        # import, before this subcommand runs — this row reports what is
        # installed, it cannot catch absence
        import jax

        import numpy

        return "PASS", f"jax {jax.__version__}, numpy {numpy.__version__}"

    def native_lib():
        from rplidar_ros2_driver_tpu import native

        if native.available():
            return "PASS", "librpl_native.so loaded (C++ I/O plane active)"
        return "WARN", ("native library unavailable — pure-Python transport "
                        "fallback will be used (no SCHED_RR rx elevation)")

    def jax_backend():
        from rplidar_ros2_driver_tpu.utils.backend import (
            probe_jax_backend,
            probe_jax_backend_subprocess,
        )

        if args.cpu:
            # CPU backend init cannot hang, and the --cpu config update
            # (main()) only exists in THIS process — a subprocess child
            # would dial the device link the flag is trying to avoid
            ok, detail = probe_jax_backend(args.device_timeout)
        else:
            # two-stage guard (same as bench.py): a throwaway child takes
            # the wedge risk first, then THIS process's init runs under
            # the in-process hang guard — sim_roundtrip's decode must
            # never be the parent's first (unguarded) backend init, or a
            # link that drops between child exit and parent init hangs
            # the doctor despite --device-timeout
            ok, detail = probe_jax_backend_subprocess(args.device_timeout)
            if ok:
                ok, detail = probe_jax_backend(args.device_timeout)
        return ("PASS" if ok else "FAIL"), detail

    def sim_roundtrip():
        import time as _time

        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(channel_type="tcp", tcp_host="127.0.0.1",
                                  tcp_port=sim.port, motor_warmup_s=0.0)
            if not drv.connect("sim", 0, False):
                return "FAIL", "connect to loopback simulator failed"
            drv.detect_and_init_strategy()
            if not drv.start_motor("", 600):
                return "FAIL", "scan start failed"
            t0 = _time.monotonic()
            got = None
            while got is None and _time.monotonic() - t0 < 10:
                got = drv.grab_scan_host(2.0)
            from rplidar_ros2_driver_tpu.node.diagnostics import (
                rx_scheduling_label,
            )

            sched = rx_scheduling_label(drv.rx_scheduling_class())
            drv.stop_motor()
            drv.disconnect()
            if got is None:
                return "FAIL", "no revolution within 10 s"
            return "PASS", (f"full protocol round-trip: {len(got[0]['angle_q14'])} "
                            f"nodes/rev through channel->codec->decode->assembly; "
                            f"rx thread at {sched}")
        finally:
            sim.stop()

    def serial_port():
        import os

        port = args.port
        if os.path.exists(port):
            ok = os.access(port, os.R_OK | os.W_OK)
            return ("PASS" if ok else "WARN",
                    f"{port} present{'' if ok else ' but not read/writable (udev rules? dialout group?)'}")
        return "WARN", f"{port} not present (no device attached, or udev rule missing — see `udev` subcommand)"

    check("python deps", deps)
    check("native I/O library", native_lib)
    check("jax backend", jax_backend)
    if results[-1][1] == "PASS":
        check("loopback protocol round-trip", sim_roundtrip)
    else:
        # ANY first jax use (even CPU-pinned decode) initializes every
        # backend, so with the device link down the round-trip would hang
        results.append(("loopback protocol round-trip", "SKIP",
                        "skipped: jax backend unavailable (decode needs it); "
                        "re-run with --cpu to test the rest of the stack"))
    check("serial port", serial_port)

    worst = 0
    for name, level, detail in results:
        print(f"[{level:4s}] {name}: {detail}")
        worst = max(worst, {"PASS": 0, "WARN": 0, "SKIP": 0, "FAIL": 1}[level])
    return worst


def main(argv=None) -> int:
    logging.basicConfig(level=logging.INFO, format="%(levelname)s %(name)s: %(message)s")
    ap = argparse.ArgumentParser(prog="rplidar_ros2_driver_tpu")
    sub = ap.add_subparsers(dest="cmd")

    run = sub.add_parser("run", help="bring up the lifecycle node and spin")
    run.add_argument("--params", default=None, help="parameter YAML (default: param/rplidar.yaml)")
    run.add_argument("--dummy", action="store_true", help="force the synthetic backend")
    run.add_argument("--duration", type=float, default=0.0, help="seconds to run (0 = forever)")
    run.add_argument("--cpu", action="store_true", help="force the CPU JAX backend")
    run.add_argument("--stats", action="store_true",
                     help="print per-stage latency percentiles (JSON) at exit")

    view = sub.add_parser("view", help="capture dummy scans and render a top-down view")
    view.add_argument("--scans", type=int, default=3)
    view.add_argument("--range-m", type=float, default=None, help="overrides view config")
    view.add_argument("--pgm", default=None, help="write image here instead of ASCII preview")
    view.add_argument(
        "--view-config", default=None, help="view YAML (default: config/rplidar_view.yaml)"
    )
    view.add_argument("--cpu", action="store_true", help="force the CPU JAX backend")

    udev = sub.add_parser("udev", help="generate/install udev rules")
    udev.add_argument("--install", action="store_true")

    doctor = sub.add_parser("doctor", help="environment self-check (deps, "
                            "native lib, jax backend, protocol round-trip, port)")
    doctor.add_argument("--cpu", action="store_true", help="force the CPU JAX backend")
    doctor.add_argument("--port", default="/dev/rplidar", help="serial port to probe")
    doctor.add_argument("--device-timeout", type=float, default=60.0,
                        help="seconds to wait for jax backend init before declaring it down")

    replay = sub.add_parser("replay", help="batch-decode frame recording(s)")
    replay.add_argument(
        "recordings",
        nargs="+",
        help="capture file(s) (RealLidarDriver.start_recording); several "
        "recordings replay as one fleet over the (stream, beam) mesh",
    )
    replay.add_argument("--cpu", action="store_true", help="force the CPU JAX backend")
    replay.add_argument(
        "--chain",
        action="store_true",
        help="also run the decoded revolutions through the filter chain "
        "(fused multi-scan step)",
    )
    replay.add_argument(
        "--fused",
        action="store_true",
        help="also replay the RAW capture bytes end-to-end on device "
        "(replay_raw_fused: T-tick super-step drain, "
        "ceil(ticks/T) dispatches) and report scans/s vs the host "
        "decode path, parity-checked",
    )
    replay.add_argument(
        "--map",
        action="store_true",
        help="also run the decoded revolutions through the SLAM "
        "front-end (correlative scan-to-map matching + log-odds map, "
        "replay.replay_with_map): prints trajectory + map summary and "
        "an ASCII map preview",
    )
    replay.add_argument(
        "--loop-close",
        action="store_true",
        help="with --map: run the FULL SLAM back-end too (submap "
        "library + loop-closure candidate matching + fixed-point "
        "pose-graph relaxation, replay.replay_with_loop_closure) and "
        "write the corrected trajectory next to the raw one in the "
        "overlay (raw 200, corrected 255)",
    )
    replay.add_argument(
        "--map-pgm",
        default=None,
        metavar="PATH",
        help="write the --map log-odds map (trajectory overlaid) as a "
        "PGM instead of the ASCII preview",
    )
    replay.add_argument(
        "--map-backend",
        choices=("auto", "host", "fused"),
        default="auto",
        help="mapper backend for --map (auto resolves per the standing "
        "decision procedure; host is the NumPy golden reference)",
    )

    args = ap.parse_args(argv)
    if getattr(args, "cpu", False):
        # must run before the first jax backend init; the env var is not
        # enough on hosts whose site config pre-selects an accelerator
        import jax

        jax.config.update("jax_platforms", "cpu")
    if args.cmd == "run":
        return _cmd_run(args)
    if args.cmd == "view":
        return _cmd_view(args)
    if args.cmd == "replay":
        return _cmd_replay(args)
    if args.cmd == "doctor":
        return _cmd_doctor(args)
    if args.cmd == "udev":
        from rplidar_ros2_driver_tpu.tools import udev as udev_mod

        return udev_mod.main(["--install"] if args.install else [])
    ap.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
