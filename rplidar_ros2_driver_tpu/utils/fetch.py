"""Deadline-bounded device->host fetch.

The reference bounds every wait on the device (grab timeout 2000 ms
default, sl_lidar_driver.h:332; channel waits, sl_lidar_driver.h:171-238
take explicit timeouts).  JAX's host materialization (``np.asarray`` on
a device array) has no such bound, and a wedged remote-attach link can
block it indefinitely (observed >30 min on the measurement rig).  This
helper races the fetch against a deadline on a daemon thread so the
publish path can surface a TimeoutError to the FSM's transient-fault
recovery instead of hanging the stream.

An expired fetch's thread stays blocked until the link resolves or the
process exits; callers keep the un-materialized handle (re-stash) so
the data itself is not lost, and their recovery cadence — not the tick
rate — bounds how many threads one incident can strand.
"""

from __future__ import annotations

import threading
from typing import Callable, Optional, TypeVar

T = TypeVar("T")


class DeadlineExpired(TimeoutError):
    """Raised by :func:`bounded_fetch` when the DEADLINE expires — never
    by the wrapped ``fn`` — so layers that need to distinguish "the wait
    ran out" from "the fetch itself raised TimeoutError" can (see
    utils/backend.run_with_deadline).  A plain TimeoutError to every
    existing caller."""


def bounded_fetch(
    fn: Callable[[], T],
    timeout_s: Optional[float],
    what: str = "device->host fetch",
) -> T:
    """Run ``fn`` (a blocking fetch/materialize) with a deadline.

    ``timeout_s`` of None or 0 means unbounded: ``fn`` runs inline on
    the calling thread with zero overhead — the default, and always the
    right choice for a locally-attached device whose D2H is microseconds.
    """
    if not timeout_s:
        return fn()
    box: dict[str, object] = {}
    done = threading.Event()

    def run() -> None:
        try:
            box["out"] = fn()
        except BaseException as e:  # noqa: BLE001 - relayed to the caller
            box["err"] = e
        finally:
            done.set()

    threading.Thread(target=run, daemon=True, name="bounded-fetch").start()
    if not done.wait(timeout_s):
        raise DeadlineExpired(f"{what} exceeded {timeout_s} s")
    if "err" in box:
        raise box["err"]  # type: ignore[misc]
    return box["out"]  # type: ignore[return-value]
