"""Dynamic-index row gather/scatter over stream-batched pytrees.

The ONE builder behind every per-stream checkpoint surface
(FleetFusedIngest and FleetMapper quarantine/rejoin rows, and the
cross-host migration unit of ROADMAP item 1): jitted ``gather(state,
idx) -> row`` / ``scatter(state, row, idx) -> state`` pairs whose
stream index is a DEVICE scalar, so every lane shares a single
compiled program per direction — a Python-int index would bake one
executable per lane and recompile inside guarded steady-state loops
the first time each lane quarantines.

Row traffic is O(1/streams) of the fleet state; the whole-state host
round trip this replaces measured 0.73x healthy-lane throughput at
full geometry (bench --config 13, docs/BENCHMARKS.md).

``fixup(new_state, row, idx)`` lets a caller repair DERIVED state
inside the scatter jit (the ingest engine re-sorts the restored
window row's median view there); the scatter donates the old state.
"""

from __future__ import annotations

from typing import Callable, Optional


def make_row_ops(jax, *, fixup: Optional[Callable] = None) -> tuple:
    """Build the jitted (gather, scatter) pair.  ``jax`` is passed in
    (the engines import jax lazily); leaves that are ``None`` in the
    pytree are skipped by tree_map as usual."""
    from jax import lax

    def gather(state, idx):
        return jax.tree_util.tree_map(
            lambda a: lax.dynamic_index_in_dim(a, idx, 0, keepdims=False),
            state,
        )

    def scatter(state, row, idx):
        new = jax.tree_util.tree_map(
            lambda a, r: lax.dynamic_update_index_in_dim(a, r, idx, 0),
            state, row,
        )
        if fixup is not None:
            new = fixup(new, row, idx)
        return new

    # donate the full state only: row buffers are strictly smaller
    # than any output buffer, so donating them just warns
    return jax.jit(gather), jax.jit(scatter, donate_argnums=(0,))
