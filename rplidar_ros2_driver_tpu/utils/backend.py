"""JAX backend-init probe with a hang guard.

Through a remote-attached device a dead link makes the first
``jax.devices()`` block forever (observed: the relay died and every
backend init hung until killed).  Probing from a daemon thread with a
bounded wait turns that failure mode into a reportable result; bench.py
and the ``doctor`` CLI both use this single implementation.
"""

from __future__ import annotations

import subprocess
import sys
import time

# ---------------------------------------------------------------------------
# persistent compilation cache
# ---------------------------------------------------------------------------

_cache_enabled_dir: str | None = None
_cache_was_cold: bool = True  # dir empty/missing when the cache was enabled


def enable_compilation_cache(cache_dir: str) -> bool:
    """Enable the JAX persistent compilation cache at ``cache_dir``.

    Restart latency: the fused ingest programs cost hundreds of ms to
    multiple seconds of XLA compile each (one per bucket x format set),
    paid again on every process start — a fleet gateway restarting after
    a crash pays it while lidars stream into a dead pump.  The
    persistent cache turns every warm restart's compiles into disk
    loads.  Thresholds are zeroed so even the small CPU programs cache
    (the default 1 s floor would skip most of this framework's
    programs).

    Idempotent; safe to call after JAX is initialized (the cache is
    consulted per compile).  Returns whether the cache is enabled —
    False when this jax build lacks the config knobs (the knob set has
    moved across versions; a missing threshold knob downgrades the
    feature, never breaks the caller).
    """
    global _cache_enabled_dir, _cache_was_cold
    import os

    import jax

    try:
        was_cold = not os.path.isdir(cache_dir) or not os.listdir(cache_dir)
        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", str(cache_dir))
    except Exception:  # noqa: BLE001 - feature-gate, never break the caller
        return False
    for knob, val in (
        ("jax_persistent_cache_min_compile_time_secs", 0),
        ("jax_persistent_cache_min_entry_size_bytes", -1),
    ):
        try:
            jax.config.update(knob, val)
        except Exception:  # noqa: BLE001 - older jax: keep its defaults
            pass
    if _cache_enabled_dir != str(cache_dir):
        _cache_was_cold = was_cold
    _cache_enabled_dir = str(cache_dir)
    return True


def maybe_enable_compilation_cache(cache_dir: str | None) -> bool:
    """Config-flag seam: enable the persistent cache when the parameter
    (``DriverParams.compilation_cache_dir``) is set, no-op when None/empty.
    Every engine that compiles hot-path programs calls this at init."""
    if not cache_dir:
        return False
    return enable_compilation_cache(cache_dir)


def compilation_cache_status() -> dict:
    """What the bench meta records beside startup timings: whether the
    persistent cache is on, where, and whether THIS run found it cold
    (empty/missing dir at enable time tells warm restarts from first
    ones when reading cold-vs-warm startup numbers)."""
    import os

    if _cache_enabled_dir is None:
        return {"enabled": False}
    try:
        entries = len(os.listdir(_cache_enabled_dir))
    except OSError:
        entries = 0
    return {
        "enabled": True,
        "dir": _cache_enabled_dir,
        "entries": entries,
        "cold": _cache_was_cold,
    }


def _nonpositive_timeout_detail(timeout_s: float) -> str | None:
    """Probe timeouts arrive via env vars (``BENCH_PROBE_TIMEOUT_S``),
    where 0 is one typo away.  Both probe flavors validate up front and
    report a timeout-STYLE failure detail — letting the value reach
    :func:`run_with_deadline` would surface its ValueError as the probe
    diagnostic, reading like a code bug instead of a misconfiguration."""
    try:
        bad = not (timeout_s > 0)
    except TypeError:
        bad = True
    if bad:
        return (f"jax backend init not attempted: non-positive probe "
                f"timeout {timeout_s!r} (check BENCH_PROBE_TIMEOUT_S)")
    return None


def probe_jax_backend(timeout_s: float) -> tuple[bool, str]:
    """(ok, detail) — detail is the device list on success, and on
    failure distinguishes a hang (link down) from an init error; a
    daemon probe thread means a hung init never blocks process exit.
    """
    bad = _nonpositive_timeout_detail(timeout_s)
    if bad is not None:
        return False, bad
    import jax

    try:
        devices = run_with_deadline(
            lambda: list(jax.devices()), timeout_s, what="jax backend init"
        )
    except MeasurementWedgedError:
        return False, (f"jax backend init timed out after {timeout_s:.0f} s "
                       "(remote-attach tunnel unreachable)")
    except BaseException as e:  # report the real failure, not a timeout
        return False, f"{type(e).__name__}: {e}"
    return True, ", ".join(str(d) for d in devices)


def probe_jax_backend_subprocess(timeout_s: float) -> tuple[bool, str]:
    """Like :func:`probe_jax_backend`, but in a THROWAWAY subprocess.

    Backend init is once-per-process: after an in-process probe hangs,
    every later ``jax.devices()`` in the same process blocks on the same
    wedged init, so an in-process probe can never be retried.  A
    subprocess probe leaves this process's backend untouched until a
    probe has actually succeeded — and the remote link serves one client
    at a time, so the probe must fully exit (``subprocess.run`` waits)
    before the caller initializes its own backend.
    """
    bad = _nonpositive_timeout_detail(timeout_s)
    if bad is not None:
        return False, bad
    code = "import jax; print(', '.join(str(d) for d in jax.devices()))"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, (f"jax backend init timed out after {timeout_s:.0f} s "
                       "(remote-attach tunnel unreachable)")
    if r.returncode != 0:
        tail = (r.stderr.strip().splitlines() or ["backend init failed"])[-1]
        return False, tail
    return True, r.stdout.strip()


def guarded_backend_init(
    default_budget_s: float = 600.0,
    default_interval_s: float = 60.0,
    log=None,
) -> tuple[bool, str, bool]:
    """The two-stage backend guard shared by every measurement CLI
    (bench.py, scripts/step_ablation.py, scripts/deep_window_ab.py):
    budgeted subprocess probes first (retryable — an in-process probe
    that hangs wedges this process's backend for good), then THIS
    process's real init under the in-process hang guard (the link can
    drop between the child's exit and this init).

    Env-tunable: ``BENCH_PROBE_BUDGET_S`` (total retry budget),
    ``BENCH_PROBE_TIMEOUT_S`` (per probe), ``BENCH_PROBE_INTERVAL_S``.

    Returns ``(ok, detail, poisoned)`` — ``poisoned`` means the
    in-process init was attempted and hung, so this process's backend
    is unusable even for CPU fallback work (compute it in a fresh
    process, as bench.py's outage path does).
    """
    import os

    per_probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 240))
    ok, detail = probe_jax_backend_with_retry(
        total_budget_s=float(
            os.environ.get("BENCH_PROBE_BUDGET_S", default_budget_s)
        ),
        per_probe_s=per_probe_s,
        interval_s=float(
            os.environ.get("BENCH_PROBE_INTERVAL_S", default_interval_s)
        ),
        log=log,
    )
    poisoned = False
    if ok:
        ok, detail = probe_jax_backend(per_probe_s)
        poisoned = not ok
    return ok, detail, poisoned


class MeasurementWedgedError(RuntimeError):
    """A device round-trip blocked past its deadline mid-measurement.

    Init guards cannot catch this class of failure: the backend dialed
    fine, rounds were completing, and then one D2H fetch through the
    remote link never returned (observed: a deep-window A/B sat 25 min
    in ``wait_woken`` with zero CPU accumulation, and an e2e fetch once
    hung >30 min).  Once it happens the process's device is unusable —
    the blocked fetch never returns — so callers must emit whatever
    they already measured and exit rather than retry in-process.
    """


def run_with_deadline(fn, timeout_s: float, what: str = "device round-trip"):
    """Run ``fn()`` in a daemon thread, bounded by ``timeout_s``.

    The mid-run analog of :func:`probe_jax_backend`: a wedged device
    fetch blocks in native code holding no Python signal opportunity,
    so neither SIGALRM nor an exception can break it — but a daemon
    thread lets the caller walk away.  Raises
    :class:`MeasurementWedgedError` on timeout; exceptions from ``fn``
    propagate unchanged (including ``fn``'s own TimeoutErrors — only
    the deadline sentinel converts).  The abandoned thread keeps the
    wedged fetch (and the process's backend) hostage, so treat a wedge
    as terminal for device work in this process.

    Thin measurement-layer veneer over the production
    :func:`~rplidar_ros2_driver_tpu.utils.fetch.bounded_fetch` (one
    daemon-thread deadline implementation, two exception contracts).
    """
    from rplidar_ros2_driver_tpu.utils.fetch import (
        DeadlineExpired,
        bounded_fetch,
    )

    if not timeout_s or timeout_s <= 0:
        # bounded_fetch treats a falsy timeout as "run inline,
        # unbounded" — correct for a local-chip fetch, but here it
        # would silently remove the hang guard that is this function's
        # entire purpose (deadlines arrive via env vars, where 0 is one
        # typo away)
        raise ValueError(
            f"run_with_deadline requires a positive deadline, got "
            f"{timeout_s!r}"
        )

    def _captured():
        # fn's exceptions — including any DeadlineExpired from a NESTED
        # bounded_fetch (e.g. a chain collect with collect_timeout_s) —
        # come back as values, so a DeadlineExpired escaping the outer
        # bounded_fetch can only be ITS OWN wait expiring
        try:
            return True, fn()
        except BaseException as e:  # re-raised on the caller thread
            return False, e

    try:
        ok, value = bounded_fetch(_captured, timeout_s, what)
    except DeadlineExpired:
        raise MeasurementWedgedError(
            f"{what} blocked past {timeout_s:.0f} s (link wedged mid-run)"
        ) from None
    if not ok:
        raise value
    return value


def exit_skipping_destructors(code: int = 0) -> None:
    """Flush stdio and ``os._exit`` — the only safe exit after a wedge.

    A thread abandoned by :func:`run_with_deadline` (or a hung init
    probe) is still blocked inside native runtime code; normal
    interpreter teardown aborts on it ("FATAL: exception not
    rethrown"), which would turn an already-emitted artifact into a
    nonzero exit.  The flush matters: ``os._exit`` skips atexit AND
    stdio flushing, so without it the artifact this exit is protecting
    can be silently dropped.
    """
    import os

    sys.stdout.flush()
    sys.stderr.flush()
    os._exit(code)


def probe_jax_backend_with_retry(
    total_budget_s: float = 1200.0,
    per_probe_s: float = 240.0,
    interval_s: float = 120.0,
    log=None,
    _probe=None,
) -> tuple[bool, str]:
    """Probe with retry/backoff: a transient link outage (relay restart,
    tunnel hiccup) should cost minutes, not a round's artifact.

    Probes in subprocesses every ``interval_s`` for up to
    ``total_budget_s`` before giving up; returns the first success or
    (False, last-error) once the budget is spent.  ``log`` (if given)
    receives one progress line per failed attempt — callers whose stdout
    is a machine-read artifact should pass a stderr writer.
    """
    if _probe is None:
        # resolved at call time, not def time, so tests (and callers)
        # can substitute the subprocess probe via the module attribute
        _probe = probe_jax_backend_subprocess
    deadline = time.monotonic() + total_budget_s
    attempt = 0
    detail = "no probe attempted"
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        ok, detail = _probe(min(per_probe_s, max(remaining, 10.0)))
        if ok:
            return True, detail
        if log is not None:
            log(f"backend probe {attempt} failed ({detail}); "
                f"{max(deadline - time.monotonic(), 0):.0f} s of budget left")
        if time.monotonic() + interval_s >= deadline:
            return False, (f"backend unreachable after {attempt} probes "
                           f"over {total_budget_s:.0f} s: {detail}")
        time.sleep(interval_s)
