"""JAX backend-init probe with a hang guard.

Through a remote-attached device a dead link makes the first
``jax.devices()`` block forever (observed: the relay died and every
backend init hung until killed).  Probing from a daemon thread with a
bounded wait turns that failure mode into a reportable result; bench.py
and the ``doctor`` CLI both use this single implementation.
"""

from __future__ import annotations

import subprocess
import sys
import threading
import time


def probe_jax_backend(timeout_s: float) -> tuple[bool, str]:
    """(ok, detail) — detail is the device list on success, and on
    failure distinguishes a hang (link down) from an init error; a
    daemon probe thread means a hung init never blocks process exit.
    """
    import jax

    out: dict = {}
    done = threading.Event()

    def _probe() -> None:
        try:
            out["devices"] = list(jax.devices())
        except BaseException as e:  # report the real failure, not a timeout
            out["err"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    threading.Thread(target=_probe, daemon=True).start()
    if not done.wait(timeout_s):
        return False, (f"jax backend init timed out after {timeout_s:.0f} s "
                       "(remote-attach tunnel unreachable)")
    if "err" in out:
        return False, out["err"]
    return True, ", ".join(str(d) for d in out["devices"])


def probe_jax_backend_subprocess(timeout_s: float) -> tuple[bool, str]:
    """Like :func:`probe_jax_backend`, but in a THROWAWAY subprocess.

    Backend init is once-per-process: after an in-process probe hangs,
    every later ``jax.devices()`` in the same process blocks on the same
    wedged init, so an in-process probe can never be retried.  A
    subprocess probe leaves this process's backend untouched until a
    probe has actually succeeded — and the remote link serves one client
    at a time, so the probe must fully exit (``subprocess.run`` waits)
    before the caller initializes its own backend.
    """
    code = "import jax; print(', '.join(str(d) for d in jax.devices()))"
    try:
        r = subprocess.run(
            [sys.executable, "-c", code],
            capture_output=True, text=True, timeout=timeout_s,
        )
    except subprocess.TimeoutExpired:
        return False, (f"jax backend init timed out after {timeout_s:.0f} s "
                       "(remote-attach tunnel unreachable)")
    if r.returncode != 0:
        tail = (r.stderr.strip().splitlines() or ["backend init failed"])[-1]
        return False, tail
    return True, r.stdout.strip()


def guarded_backend_init(
    default_budget_s: float = 600.0,
    default_interval_s: float = 60.0,
    log=None,
) -> tuple[bool, str, bool]:
    """The two-stage backend guard shared by every measurement CLI
    (bench.py, scripts/step_ablation.py, scripts/deep_window_ab.py):
    budgeted subprocess probes first (retryable — an in-process probe
    that hangs wedges this process's backend for good), then THIS
    process's real init under the in-process hang guard (the link can
    drop between the child's exit and this init).

    Env-tunable: ``BENCH_PROBE_BUDGET_S`` (total retry budget),
    ``BENCH_PROBE_TIMEOUT_S`` (per probe), ``BENCH_PROBE_INTERVAL_S``.

    Returns ``(ok, detail, poisoned)`` — ``poisoned`` means the
    in-process init was attempted and hung, so this process's backend
    is unusable even for CPU fallback work (compute it in a fresh
    process, as bench.py's outage path does).
    """
    import os

    per_probe_s = float(os.environ.get("BENCH_PROBE_TIMEOUT_S", 240))
    ok, detail = probe_jax_backend_with_retry(
        total_budget_s=float(
            os.environ.get("BENCH_PROBE_BUDGET_S", default_budget_s)
        ),
        per_probe_s=per_probe_s,
        interval_s=float(
            os.environ.get("BENCH_PROBE_INTERVAL_S", default_interval_s)
        ),
        log=log,
    )
    poisoned = False
    if ok:
        ok, detail = probe_jax_backend(per_probe_s)
        poisoned = not ok
    return ok, detail, poisoned


def probe_jax_backend_with_retry(
    total_budget_s: float = 1200.0,
    per_probe_s: float = 240.0,
    interval_s: float = 120.0,
    log=None,
    _probe=None,
) -> tuple[bool, str]:
    """Probe with retry/backoff: a transient link outage (relay restart,
    tunnel hiccup) should cost minutes, not a round's artifact.

    Probes in subprocesses every ``interval_s`` for up to
    ``total_budget_s`` before giving up; returns the first success or
    (False, last-error) once the budget is spent.  ``log`` (if given)
    receives one progress line per failed attempt — callers whose stdout
    is a machine-read artifact should pass a stderr writer.
    """
    if _probe is None:
        # resolved at call time, not def time, so tests (and callers)
        # can substitute the subprocess probe via the module attribute
        _probe = probe_jax_backend_subprocess
    deadline = time.monotonic() + total_budget_s
    attempt = 0
    detail = "no probe attempted"
    while True:
        attempt += 1
        remaining = deadline - time.monotonic()
        ok, detail = _probe(min(per_probe_s, max(remaining, 10.0)))
        if ok:
            return True, detail
        if log is not None:
            log(f"backend probe {attempt} failed ({detail}); "
                f"{max(deadline - time.monotonic(), 0):.0f} s of budget left")
        if time.monotonic() + interval_s >= deadline:
            return False, (f"backend unreachable after {attempt} probes "
                           f"over {total_budget_s:.0f} s: {detail}")
        time.sleep(interval_s)
