"""JAX backend-init probe with a hang guard.

Through a remote-attached device a dead link makes the first
``jax.devices()`` block forever (observed: the relay died and every
backend init hung until killed).  Probing from a daemon thread with a
bounded wait turns that failure mode into a reportable result; bench.py
and the ``doctor`` CLI both use this single implementation.
"""

from __future__ import annotations

import threading


def probe_jax_backend(timeout_s: float) -> tuple[bool, str]:
    """(ok, detail) — detail is the device list on success, and on
    failure distinguishes a hang (link down) from an init error; a
    daemon probe thread means a hung init never blocks process exit.
    """
    import jax

    out: dict = {}
    done = threading.Event()

    def _probe() -> None:
        try:
            out["devices"] = list(jax.devices())
        except BaseException as e:  # report the real failure, not a timeout
            out["err"] = f"{type(e).__name__}: {e}"
        finally:
            done.set()

    threading.Thread(target=_probe, daemon=True).start()
    if not done.wait(timeout_s):
        return False, (f"jax backend init timed out after {timeout_s:.0f} s "
                       "(remote-attach tunnel unreachable)")
    if "err" in out:
        return False, out["err"]
    return True, ", ".join(str(d) for d in out["devices"])
