"""Orbax backend for sharded checkpoint/resume.

The npz checkpointer (utils/checkpoint.py) is the single-stream default:
host snapshot, one atomic file, no dependencies.  At fleet scale the
service's state is a *sharded* pytree over the ``(stream, beam)`` mesh,
and gathering it to one host buffer defeats the sharding; this backend
saves/restores the device arrays directly with Orbax (the JAX
ecosystem's standard checkpointer): each process writes exactly its
addressable shards, restore places shards straight onto the restoring
mesh — which may be a different mesh shape than the one that saved, as
long as the global array shapes match.

Durability matches the npz path's old-or-new contract: Orbax's own
``force=True`` overwrite deletes the previous checkpoint *before*
writing the new one, so a crash mid-save would lose both; instead the
save lands in a sibling ``.saving`` directory and is rotated in with
two renames (previous → ``.old``, new → final).  A crash between the
renames leaves the previous checkpoint at ``.old``, which
:func:`restore_sharded` falls back to.

Geometry safety matches the npz path too: restore goes through an
abstract template built from the target state, so a checkpoint of
incompatible window/beams/grid fails cleanly instead of corrupting the
compiled step.  Orbax is an *optional* dependency (``pip install
rplidar-ros2-driver-tpu[orbax]``); nothing imports it until these
functions run.
"""

from __future__ import annotations

import functools
import logging
import os
import shutil

import jax

log = logging.getLogger("rplidar_tpu.checkpoint")

_SAVING_SUFFIX = ".saving"
_OLD_SUFFIX = ".old"


@functools.lru_cache(maxsize=1)
def _checkpointer():
    """One process-wide checkpointer: constructing one per call tears
    down Orbax's async executor on GC, which breaks any later call with
    'cannot schedule new futures after shutdown'."""
    import orbax.checkpoint as ocp

    return ocp.StandardCheckpointer()


def _barrier(tag: str) -> None:
    """Cross-process sync point; free when single-process."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils

        multihost_utils.sync_global_devices(f"rpl_ckpt:{tag}")


def save_sharded(path: str, state) -> None:
    """Write a (possibly sharded) state pytree under ``path`` — a
    FilterState, a stream-stacked MapState (the SLAM front-end's
    checkpoint schema, mapping/mapper.FleetMapper.save_sharded), or any
    registered pytree of device arrays; the save/rotate machinery is
    schema-agnostic.

    Blocks until the write is finalized and rotated in, so on return the
    checkpoint at ``path`` is durable and a reader always finds either
    the previous checkpoint or the new one (see module docstring for the
    crash-window analysis).  Multi-process: every process calls this
    (Orbax's save is collective — each writes its shards); the
    filesystem rotation is performed by process 0 only, bracketed by
    barriers, mirroring how Orbax itself finalizes on the primary host.
    """
    path = os.path.abspath(path)
    tmp, old = path + _SAVING_SUFFIX, path + _OLD_SUFFIX
    primary = jax.process_index() == 0
    if primary:
        shutil.rmtree(tmp, ignore_errors=True)
    _barrier("pre-save")
    ck = _checkpointer()
    ck.save(tmp, state, force=True)  # force only ever clears a dead .saving
    ck.wait_until_finished()
    _barrier("post-save")
    if primary:
        shutil.rmtree(old, ignore_errors=True)
        if os.path.isdir(path):
            os.replace(path, old)
        os.replace(tmp, path)
        shutil.rmtree(old, ignore_errors=True)
    _barrier("post-rotate")


def restore_sharded(path: str, like):
    """Restore a state pytree shaped-and-sharded like ``like`` (same
    schema-agnostic contract as :func:`save_sharded` — FilterState,
    MapState, ...).

    ``like`` supplies the target geometry AND target shardings — pass
    :func:`~rplidar_ros2_driver_tpu.parallel.sharding.abstract_sharded_state`
    (allocation-free) or a concrete state: shards land directly on its
    mesh.  Returns None when the checkpoint is absent or its geometry
    does not match — the caller keeps its current state, mirroring
    ScanFilterChain.restore's reject-don't-crash contract.  When ``path``
    is missing but a rotation crash left ``path.old``, that previous
    checkpoint is restored instead.
    """
    path = os.path.abspath(path)
    if not os.path.isdir(path):
        old = path + _OLD_SUFFIX
        if not os.path.isdir(old):
            return None
        log.warning("checkpoint %s missing; recovering previous from %s", path, old)
        path = old
    template = jax.tree_util.tree_map(
        lambda x: jax.ShapeDtypeStruct(x.shape, x.dtype, sharding=x.sharding), like
    )
    try:
        if not _metadata_matches(path, template):
            return None
        return _checkpointer().restore(path, template)
    except (ValueError, KeyError, FileNotFoundError) as e:
        log.warning("rejecting orbax checkpoint %s: %s", path, e)
        return None


def _metadata_matches(path: str, template) -> bool:
    """Explicit saved-vs-template geometry check.  Orbax's restore does
    NOT reject a shape mismatch: given a template whose arrays are
    smaller than the checkpointed ones it silently returns
    template-shaped slices (observed on orbax 0.7.0), so a
    wrong-window/beams/grid checkpoint would restore as truncated
    garbage instead of failing cleanly.  The checkpoint's own metadata
    carries the saved shapes/dtypes — compare leaf-by-leaf (key set
    included) and reject on any drift, keeping the caller's state
    untouched (the npz path's reject-don't-crash contract)."""
    def norm(entries) -> str:
        # one spelling for dataclass attrs, dict keys and sequence
        # indices: the metadata tree comes back as name-keyed dicts
        # while the template is the live pytree (e.g. a FilterState
        # dataclass), so treedefs/keystr never compare equal even on a
        # matching checkpoint — the NAMES do
        parts = []
        for e in entries:
            for attr in ("name", "key", "idx"):
                v = getattr(e, attr, None)
                if v is not None:
                    parts.append(str(v))
                    break
            else:
                parts.append(str(e))
        return "/".join(parts)

    saved = _checkpointer().metadata(path)
    t_leaves, _ = jax.tree_util.tree_flatten_with_path(template)
    s_leaves, _ = jax.tree_util.tree_flatten_with_path(
        saved, is_leaf=lambda x: hasattr(x, "shape")
    )
    want = {norm(p): (tuple(t.shape), t.dtype) for p, t in t_leaves}
    got = {
        norm(p): (
            tuple(getattr(s, "shape", ()) or ()),
            getattr(s, "dtype", None),
        )
        for p, s in s_leaves
    }
    if set(want) != set(got):
        log.warning(
            "rejecting orbax checkpoint %s: leaf set %s != %s",
            path, sorted(got), sorted(want),
        )
        return False
    for name, (shape, dtype) in want.items():
        s_shape, s_dtype = got[name]
        if shape != s_shape or (s_dtype is not None and dtype != s_dtype):
            log.warning(
                "rejecting orbax checkpoint %s: leaf %s saved as %s/%s, "
                "want %s/%s", path, name, s_shape, s_dtype, shape, dtype,
            )
            return False
    return True
