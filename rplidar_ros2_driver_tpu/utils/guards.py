"""Runtime sentinels — turn "never happens in steady state" into raises.

The fused engines' performance contract has two structural halves the
bench decompositions assert per release but nothing enforced per RUN:

  * zero recompiles after warmup — every precompile() exists so the
    live loop never pays an in-loop XLA compile (~600 ms measured on a
    CPU rig when a commit-pattern mismatch sneaks in);
  * zero implicit transfers — the hot loops perform exactly their
    DECLARED device_put staging and wire fetches; an implicit
    numpy->jit upload or a stray mid-loop materialization is a silent
    per-tick link round-trip on a remote-attached device.

These context managers make both enforceable in tests (tier-1 pins all
four engines — tests/test_guards.py) and cheap to borrow in soak
tooling.  They are the RUNTIME complement of graftlint's static rules
(GL001/GL007 catch the patterns the AST can see; these catch whatever
it can't).

``assert_no_recompile`` listens for the compile-begin log record that
``jax_log_compiles`` surfaces ("Compiling <name> with global shapes…",
logged by jax._src.interpreters.pxla at DEBUG when the flag is off) via
a scoped handler, so no global config flip — and no log spam — leaks
out of the context.
"""

from __future__ import annotations

import contextlib
import logging

# the module that logs XLA compile begins in this jax lineage (0.4.x);
# kept in one place so a jax upgrade moving the logger is a one-line fix
_COMPILE_LOGGERS = ("jax._src.interpreters.pxla", "jax._src.dispatch")
_COMPILE_PREFIXES = ("Compiling ", "Finished XLA compilation")


class RecompileError(AssertionError):
    """An XLA compile started inside an assert_no_recompile scope."""


class _CompileRecorder(logging.Handler):
    def __init__(self) -> None:
        super().__init__(level=logging.DEBUG)
        self.compiles: list[str] = []

    def emit(self, record: logging.LogRecord) -> None:
        msg = record.getMessage()
        if msg.startswith("Compiling "):
            # "Compiling <name> with global shapes and types [...]"
            self.compiles.append(msg.split(" with global", 1)[0])


@contextlib.contextmanager
def assert_no_recompile(max_compiles: int = 0, tag: str = ""):
    """Raise :class:`RecompileError` if more than ``max_compiles`` XLA
    compilations START inside the context.  Zero-overhead on the hot
    path itself (a logging handler fires only when jax actually
    compiles); the recorder is yielded so callers can inspect
    ``recorder.compiles`` for diagnostics."""
    rec = _CompileRecorder()
    loggers = [logging.getLogger(n) for n in _COMPILE_LOGGERS]
    saved = [lg.level for lg in loggers]
    for lg in loggers:
        lg.addHandler(rec)
        lg.setLevel(logging.DEBUG)
    try:
        yield rec
    finally:
        for lg, lvl in zip(loggers, saved):
            lg.removeHandler(rec)
            lg.setLevel(lvl)
    if len(rec.compiles) > max_compiles:
        where = f" in {tag}" if tag else ""
        raise RecompileError(
            f"{len(rec.compiles)} XLA compile(s){where} after warmup "
            f"(allowed {max_compiles}): {', '.join(rec.compiles[:8])} — "
            "a precompile() is missing a shape/bucket/commit-pattern, or "
            "a static config changed mid-stream"
        )


@contextlib.contextmanager
def no_implicit_transfers():
    """``jax_transfer_guard="disallow"`` for the scope: any transfer not
    explicitly requested (``jax.device_put`` / ``jax.device_get``)
    raises inside jax — most importantly the implicit host->device copy
    of a numpy argument reaching a jitted call, the exact per-tick cost
    class the engines' explicit ``device_put`` staging exists to
    declare."""
    import jax

    with jax.transfer_guard("disallow"):
        yield


@contextlib.contextmanager
def steady_state(max_compiles: int = 0, tag: str = ""):
    """The post-warmup invariant, whole: zero recompiles AND zero
    implicit transfers.  Wrap the steady-state portion of any engine
    loop — after precompile()/warmup ticks — and every violation of the
    dispatch-amortization story becomes a raised error instead of a
    silent latency regression."""
    with assert_no_recompile(max_compiles, tag=tag) as rec:
        with no_implicit_transfers():
            yield rec
