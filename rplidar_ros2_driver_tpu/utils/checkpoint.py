"""Durable checkpoint/resume for the filter-chain state.

The reference is stateless streaming — its only "resume" surface is the
lifecycle state machine (SURVEY.md §5).  In this framework the rolling
scan window and voxel accumulator are real device-resident state, so they
get a real checkpoint format: an atomically-written ``.npz`` of the host
snapshot plus a JSON sidecar fingerprinting the chain geometry
(window/beams/grid), so a restore into a reconfigured chain is detected
and refused instead of crashing the compiled step.

Kept dependency-light (numpy only): the snapshots are a few MB at most,
and a single-file atomic rename is exactly the durability contract needed.
For multi-host meshes, each host saves its addressable shards under its
process index.
"""

from __future__ import annotations

import json
import logging
import os
import tempfile
import zipfile
import zlib
from typing import Any, Optional

import numpy as np

log = logging.getLogger("rplidar_tpu.checkpoint")

FORMAT_VERSION = 1


def _array_crc(v: np.ndarray) -> int:
    """CRC32 of an array's raw bytes (C-order contiguous view, so the
    checksum is layout-independent of how the caller built it)."""
    return zlib.crc32(np.ascontiguousarray(v).tobytes()) & 0xFFFFFFFF


def _fingerprint(snap: dict[str, np.ndarray]) -> dict[str, Any]:
    return {
        "version": FORMAT_VERSION,
        # shape/dtype pre-validate the restore; crc32 detects torn or
        # bit-flipped payloads that still parse (a truncated zip fails
        # earlier, but a corrupt-but-well-formed npz would otherwise
        # restore silent garbage into a compiled step)
        "arrays": {
            k: {
                "shape": list(v.shape),
                "dtype": str(v.dtype),
                "crc32": _array_crc(np.asarray(v)),
            }
            for k, v in snap.items()
        },
    }


def save_checkpoint(path: str, snap: dict[str, np.ndarray], extra: Optional[dict] = None) -> None:
    """Atomically write ``snap`` to ``path`` (an .npz file).

    Write-to-temp + rename in the destination directory, so a crash
    mid-save never leaves a torn checkpoint, and a concurrent reader sees
    either the old file or the new one.
    """
    d = os.path.dirname(os.path.abspath(path)) or "."
    os.makedirs(d, exist_ok=True)
    meta = _fingerprint(snap)
    if extra:
        meta["extra"] = extra
    fd, tmp = tempfile.mkstemp(dir=d, suffix=".npz.tmp")
    try:
        with os.fdopen(fd, "wb") as f:
            np.savez(f, __meta__=np.frombuffer(json.dumps(meta).encode(), np.uint8), **snap)
            f.flush()
            os.fsync(f.fileno())  # data durable before the rename is
        os.replace(tmp, path)
        # fsync the directory so the rename itself survives power loss —
        # best-effort: by now the checkpoint IS at its final path, so a
        # platform that can't fsync a directory must not fail the save
        try:
            dfd = os.open(d, os.O_RDONLY)
            try:
                os.fsync(dfd)
            finally:
                os.close(dfd)
        except OSError:
            pass
    except BaseException:
        try:
            os.unlink(tmp)
        except OSError:
            pass
        raise


def load_checkpoint(path: str) -> Optional[tuple[dict[str, np.ndarray], dict]]:
    """Read a checkpoint; None when absent, unreadable, torn, or failing
    its own CRC manifest — every rejection is a logged clean refusal,
    never a crash or a silent garbage restore."""
    if not os.path.exists(path):
        return None
    try:
        with np.load(path) as z:
            raw_meta = z["__meta__"].tobytes()
            meta = json.loads(raw_meta)
            if meta.get("version") != FORMAT_VERSION:
                log.warning(
                    "rejecting checkpoint %s: format version %s (want %d)",
                    path, meta.get("version"), FORMAT_VERSION,
                )
                return None
            snap = {k: z[k] for k in z.files if k != "__meta__"}
    except (OSError, EOFError, ValueError, KeyError, json.JSONDecodeError, zipfile.BadZipFile) as e:
        # EOFError: a zero-length / headerless torn file (np.load raises
        # it before the zip machinery ever sees the bytes)
        log.warning("rejecting unreadable/torn checkpoint %s: %s", path, e)
        return None
    # verify the payload matches its own manifest: shape/dtype (a
    # truncation guard) AND the per-array CRC32 (a corruption guard —
    # a bit-flipped npz can still unzip and parse).  Checkpoints
    # written before the crc32 field simply lack it and skip that leg.
    want = meta.get("arrays", {})
    for k, spec in want.items():
        if k not in snap or list(snap[k].shape) != spec["shape"] or str(snap[k].dtype) != spec["dtype"]:
            log.warning(
                "rejecting checkpoint %s: array %r missing or "
                "shape/dtype drifted from its manifest", path, k,
            )
            return None
        crc = spec.get("crc32")
        if crc is not None and _array_crc(snap[k]) != crc:
            log.warning(
                "rejecting checkpoint %s: array %r failed its CRC32 "
                "(torn or bit-flipped payload)", path, k,
            )
            return None
    return snap, meta
