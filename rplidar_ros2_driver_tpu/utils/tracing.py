"""Per-stage latency tracing.

The reference has no tracing subsystem (SURVEY.md §5); the p99 publish
latency north-star metric needs one.  Lightweight monotonic-clock stage
timers with streaming percentile estimation over a bounded ring.
"""

from __future__ import annotations

import contextlib
import threading
import time

import numpy as np


class StageTimer:
    """Thread-safe named-stage duration collector (seconds)."""

    def __init__(self, capacity: int = 4096) -> None:
        self._capacity = capacity
        self._lock = threading.Lock()
        self._samples: dict[str, list] = {}

    @contextlib.contextmanager
    def stage(self, name: str):
        t0 = time.perf_counter()
        try:
            yield
        finally:
            dt = time.perf_counter() - t0
            with self._lock:
                buf = self._samples.setdefault(name, [])
                buf.append(dt)
                if len(buf) > self._capacity:
                    del buf[: len(buf) - self._capacity]

    def record(self, name: str, seconds: float) -> None:
        with self._lock:
            buf = self._samples.setdefault(name, [])
            buf.append(seconds)
            if len(buf) > self._capacity:
                del buf[: len(buf) - self._capacity]

    def percentile(self, name: str, q: float) -> float:
        with self._lock:
            buf = self._samples.get(name)
            if not buf:
                return float("nan")
            return float(np.percentile(np.asarray(buf), q))

    def summary(self) -> dict[str, dict[str, float]]:
        with self._lock:
            out = {}
            for name, buf in self._samples.items():
                a = np.asarray(buf)
                if len(a) == 0:
                    continue
                out[name] = {
                    "n": int(len(a)),
                    "mean_ms": float(a.mean() * 1e3),
                    "p50_ms": float(np.percentile(a, 50) * 1e3),
                    "p99_ms": float(np.percentile(a, 99) * 1e3),
                    "max_ms": float(a.max() * 1e3),
                }
            return out

    def reset(self) -> None:
        with self._lock:
            self._samples.clear()


@contextlib.contextmanager
def profile_trace(log_dir: str):
    """Capture a jax.profiler device trace (viewable in TensorBoard /
    Perfetto) around a block — the real-tracing upgrade over the
    reference's printf packet dump (sl_async_transceiver.cpp:336-359)."""
    import jax

    jax.profiler.start_trace(log_dir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()
