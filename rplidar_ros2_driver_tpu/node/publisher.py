"""Publishing seam.

The reference publishes over DDS via rclcpp with configurable QoS
(src/rplidar_node.cpp:154-172).  Here publishing is an interface: the node
calls it, and deployments plug in a ROS 2 bridge, a zero-copy intra-process
queue, or the default in-memory collector (tests / bench).

QoS semantics carried over: ``best_effort`` drops when the subscriber lags
(bounded queue, newest wins), ``reliable`` blocks/keeps all.
"""

from __future__ import annotations

import collections
import threading
from typing import Any, Callable, Optional

from rplidar_ros2_driver_tpu.node.messages import (
    DiagnosticStatus,
    LaserScanHost,
    PointCloudHost,
    PoseHost,
    StaticTransform,
)


class PublisherBase:
    def publish_scan(self, msg: LaserScanHost) -> None: ...

    def publish_cloud(self, msg: PointCloudHost) -> None: ...

    def publish_pose(self, msg: PoseHost) -> None: ...

    def publish_tf_static(self, tf: StaticTransform) -> None: ...

    def publish_diagnostics(self, status: DiagnosticStatus) -> None: ...


class CollectingPublisher(PublisherBase):
    """Default sink: bounded deques, thread-safe; best_effort semantics."""

    def __init__(self, maxlen: int = 64, reliable: bool = False) -> None:
        self._lock = threading.Lock()
        self.reliable = reliable
        self.scans: collections.deque = collections.deque(maxlen=None if reliable else maxlen)
        self.clouds: collections.deque = collections.deque(maxlen=None if reliable else maxlen)
        self.poses: collections.deque = collections.deque(maxlen=None if reliable else maxlen)
        self.tf_static: list[StaticTransform] = []
        self.diagnostics: collections.deque = collections.deque(maxlen=256)
        self.scan_count = 0

    def publish_scan(self, msg: LaserScanHost) -> None:
        with self._lock:
            self.scans.append(msg)
            self.scan_count += 1

    def publish_cloud(self, msg: PointCloudHost) -> None:
        with self._lock:
            self.clouds.append(msg)

    def publish_pose(self, msg: PoseHost) -> None:
        with self._lock:
            self.poses.append(msg)

    def publish_tf_static(self, tf: StaticTransform) -> None:
        with self._lock:
            self.tf_static.append(tf)

    def publish_diagnostics(self, status: DiagnosticStatus) -> None:
        with self._lock:
            self.diagnostics.append(status)


class CallbackPublisher(PublisherBase):
    """Routes messages to user callbacks (ROS bridge adapter point)."""

    def __init__(
        self,
        on_scan: Optional[Callable[[LaserScanHost], Any]] = None,
        on_cloud: Optional[Callable[[PointCloudHost], Any]] = None,
        on_tf: Optional[Callable[[StaticTransform], Any]] = None,
        on_diag: Optional[Callable[[DiagnosticStatus], Any]] = None,
        on_pose: Optional[Callable[[PoseHost], Any]] = None,
    ) -> None:
        self._on_scan = on_scan
        self._on_cloud = on_cloud
        self._on_tf = on_tf
        self._on_diag = on_diag
        self._on_pose = on_pose

    def publish_scan(self, msg: LaserScanHost) -> None:
        if self._on_scan:
            self._on_scan(msg)

    def publish_cloud(self, msg: PointCloudHost) -> None:
        if self._on_cloud:
            self._on_cloud(msg)

    def publish_pose(self, msg: PoseHost) -> None:
        if self._on_pose:
            self._on_pose(msg)

    def publish_tf_static(self, tf: StaticTransform) -> None:
        if self._on_tf:
            self._on_tf(tf)

    def publish_diagnostics(self, status: DiagnosticStatus) -> None:
        if self._on_diag:
            self._on_diag(status)
