"""Managed-node lifecycle.

Mirror of the ROS 2 managed-node state machine the reference builds on
(rclcpp_lifecycle::LifecycleNode; transitions wired in
src/rplidar_node.cpp:116-262 and driven by launch/rplidar.launch.py:109-141):

    UNCONFIGURED --configure--> INACTIVE --activate--> ACTIVE
         ^                        |  ^                   |
         '-------cleanup----------'  '----deactivate-----'
    any --shutdown--> FINALIZED

Transition callbacks return bool; a False return leaves the state unchanged
(ERROR processing kept simple: failed configure stays UNCONFIGURED).
"""

from __future__ import annotations

import enum
import logging
import threading

log = logging.getLogger("rplidar_tpu.lifecycle")


class LifecycleState(enum.Enum):
    UNCONFIGURED = "unconfigured"
    INACTIVE = "inactive"
    ACTIVE = "active"
    FINALIZED = "finalized"


class LifecycleError(RuntimeError):
    pass


class LifecycleNode:
    """Base class enforcing legal transitions; subclasses override on_*."""

    def __init__(self, name: str) -> None:
        self.name = name
        self._state = LifecycleState.UNCONFIGURED
        self._lock = threading.RLock()

    @property
    def lifecycle_state(self) -> LifecycleState:
        with self._lock:
            return self._state

    def _transition(self, expected, target, callback) -> bool:
        with self._lock:
            if self._state not in expected:
                raise LifecycleError(
                    f"{self.name}: cannot go {self._state.value} -> {target.value}"
                )
            ok = bool(callback())
            if ok:
                self._state = target
                log.info("%s: lifecycle -> %s", self.name, target.value)
            else:
                log.error("%s: transition to %s failed", self.name, target.value)
            return ok

    def configure(self) -> bool:
        return self._transition(
            (LifecycleState.UNCONFIGURED,), LifecycleState.INACTIVE, self.on_configure
        )

    def activate(self) -> bool:
        return self._transition(
            (LifecycleState.INACTIVE,), LifecycleState.ACTIVE, self.on_activate
        )

    def deactivate(self) -> bool:
        return self._transition(
            (LifecycleState.ACTIVE,), LifecycleState.INACTIVE, self.on_deactivate
        )

    def cleanup(self) -> bool:
        return self._transition(
            (LifecycleState.INACTIVE,), LifecycleState.UNCONFIGURED, self.on_cleanup
        )

    def shutdown(self) -> bool:
        with self._lock:
            if self._state is LifecycleState.ACTIVE:
                self.on_deactivate()
            if self._state in (LifecycleState.ACTIVE, LifecycleState.INACTIVE):
                self.on_cleanup()
            ok = bool(self.on_shutdown())
            self._state = LifecycleState.FINALIZED
            return ok

    # subclass hooks
    def on_configure(self) -> bool:
        return True

    def on_activate(self) -> bool:
        return True

    def on_deactivate(self) -> bool:
        return True

    def on_cleanup(self) -> bool:
        return True

    def on_shutdown(self) -> bool:
        return True
