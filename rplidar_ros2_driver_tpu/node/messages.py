"""Host-side message types published by the node.

Array analogs of ``sensor_msgs/LaserScan``, ``sensor_msgs/PointCloud2``
(XY subset), ``tf2_msgs/TFMessage`` (static transform), and
``diagnostic_msgs/DiagnosticStatus`` — the four things the reference node
publishes (src/rplidar_node.cpp:154-208,490-545,558-683).  Kept free of any
ROS dependency; a rclpy bridge only needs to map fields 1:1.
"""

from __future__ import annotations

import dataclasses
import enum
from typing import Optional

import numpy as np


@dataclasses.dataclass
class LaserScanHost:
    stamp: float
    frame_id: str
    angle_min: float
    angle_max: float
    angle_increment: float
    time_increment: float
    scan_time: float
    range_min: float
    range_max: float
    ranges: np.ndarray       # (beam_count,) float32, +inf = no return
    intensities: np.ndarray  # (beam_count,)


@dataclasses.dataclass
class PointCloudHost:
    stamp: float
    frame_id: str
    points_xy: np.ndarray    # (N, 2) float32 metres
    voxel: Optional[np.ndarray] = None  # (G, G) occupancy counts


@dataclasses.dataclass
class PoseHost:
    """2-D pose estimate from the SLAM front-end — the array analog of
    ``geometry_msgs/PoseStamped`` (yaw-only; a rclpy bridge maps theta
    to a z-axis quaternion)."""

    stamp: float
    frame_id: str          # the map frame ("map")
    child_frame_id: str    # the sensor frame (params.frame_id)
    x_m: float
    y_m: float
    theta_rad: float
    score: int = 0         # raw correlation score (0 = match rejected)
    matched_points: int = 0
    map_revision: int = 0  # revolutions absorbed into the map


@dataclasses.dataclass
class StaticTransform:
    """base_link -> frame_id identity transform
    (src/rplidar_node.cpp:177-201)."""

    parent: str = "base_link"
    child: str = "laser"
    translation: tuple = (0.0, 0.0, 0.0)
    rotation_wxyz: tuple = (1.0, 0.0, 0.0, 0.0)


class DiagLevel(enum.IntEnum):
    OK = 0
    WARN = 1
    ERROR = 2
    STALE = 3


@dataclasses.dataclass
class DiagnosticStatus:
    level: DiagLevel
    name: str
    message: str
    hardware_id: str
    values: dict[str, str] = dataclasses.field(default_factory=dict)
