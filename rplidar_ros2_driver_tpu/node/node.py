"""RPlidarNode — the top-level lifecycle node.

Behavioral mirror of the reference node (src/rplidar_node.cpp):

  * on_configure  — load params, build the driver factory (dummy vs real),
    set up publishing + static TF + diagnostics, build the filter chain
    (:116-211)
  * on_activate   — spawn the scan-loop FSM thread (:213-225)
  * on_deactivate — stop the thread, stop the motor (:227-242)
  * on_cleanup    — drop driver + chain state (:244-256)
  * dynamic reconfigure — rpm / scan_processing / scan_mode at runtime
    (:689-774), rejected while disconnected

New capability (the north star): when ``filter_chain`` stages are
configured, each revolution runs through the TPU ScanFilterChain between
grab and publish; the LaserScan then carries the temporal-median ranges and
a PointCloud + voxel grid are published alongside.

Ingest seam (``ingest_backend``): ``host`` grabs assembled revolutions
from the driver and runs the chain here (the golden path above);
``fused`` hands the driver a FusedIngest sink instead — raw frame bytes
decode, segment into revolutions and filter in ONE device dispatch
(ops/ingest.py), and the FSM publishes the already-filtered outputs via
:meth:`RPlidarNode._on_filtered_output`.
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import numpy as np

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.types import ScanBatch
from rplidar_ros2_driver_tpu.driver.dummy import DummyLidarDriver
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.node.diagnostics import DiagnosticsUpdater
from rplidar_ros2_driver_tpu.node.fsm import DriverState, FsmTimings, ScanLoopFsm
from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleNode, LifecycleState
from rplidar_ros2_driver_tpu.node.messages import (
    LaserScanHost,
    PointCloudHost,
    StaticTransform,
)
from rplidar_ros2_driver_tpu.node.publisher import CollectingPublisher, PublisherBase
from rplidar_ros2_driver_tpu.ops.laserscan import to_laserscan
from rplidar_ros2_driver_tpu.utils.tracing import StageTimer

log = logging.getLogger("rplidar_tpu.node")


class RPlidarNode(LifecycleNode):
    def __init__(
        self,
        params: Optional[DriverParams] = None,
        publisher: Optional[PublisherBase] = None,
        *,
        driver_factory=None,
        fsm_timings: Optional[FsmTimings] = None,
        name: str = "rplidar_node",
    ) -> None:
        super().__init__(name)
        self.params = params or DriverParams()
        self.params.validate()
        self.publisher = publisher or CollectingPublisher()
        self._driver_factory = driver_factory
        self._fsm_timings = fsm_timings
        self.fsm: Optional[ScanLoopFsm] = None
        self.chain: Optional[ScanFilterChain] = None
        # fused ingest engine (ingest_backend="fused"): owns the filter
        # window in place of self.chain; survives FSM driver recreation
        # (each recreated driver gets the same sink re-attached)
        self.fused_ingest = None
        # SLAM front-end (map_enable): per-stream log-odds map +
        # correlative matcher fed from _publish_chain_output — the hook
        # every chain path (sync, pipelined, fused-ingest) funnels
        # through, so the mapper sees each revolution exactly once
        self.mapper = None
        self._mapper_snapshot = None
        # SLAM back-end (loop_enable): submap library + loop-closure
        # detection + pose-graph correction beside the mapper; observes
        # every mapper tick and republishes the corrected pose
        self.loop = None
        self._loop_snapshot = None
        self.diagnostics: Optional[DiagnosticsUpdater] = None
        self.tracer = StageTimer()
        self._param_lock = threading.Lock()
        self._chain_snapshot = None
        # (stamp, duration, max_range) of the revolution whose chain
        # output is still in flight when pipelined_publish is on
        self._pipeline_meta: Optional[tuple[float, float, float]] = None

    # ------------------------------------------------------------------
    # lifecycle
    # ------------------------------------------------------------------

    def _default_factory(self):
        if self.params.dummy_mode:
            return DummyLidarDriver()
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver

        return RealLidarDriver(
            channel_type=self.params.channel_type,
            tcp_host=self.params.tcp_host,
            tcp_port=self.params.tcp_port,
            udp_host=self.params.udp_host,
            udp_port=self.params.udp_port,
        )

    def _resolve_fused_ingest(self) -> bool:
        """Whether this configure builds the fused ingest seam.  Fused
        needs the filter chain AND a wire-streaming driver: the dummy
        driver synthesizes host scans above the protocol layer, so it
        falls back to the host backend with a notice."""
        from rplidar_ros2_driver_tpu.filters.chain import resolve_ingest_backend

        backend = resolve_ingest_backend(self.params.ingest_backend)
        if backend != "fused" or not self.params.filter_chain:
            if getattr(self.params, "deskew_enable", False):
                # the validator only sees the FIELDS; here the node
                # knows its ACTIVE seam resolved to host — refusing
                # beats silently publishing skewed scans with the
                # operator believing de-skew is on
                raise ValueError(
                    "deskew_enable requires this node's ingest seam to "
                    f"resolve fused (ingest_backend="
                    f"{self.params.ingest_backend!r} resolved "
                    f"{backend!r}) — de-skew/reconstruction runs inside "
                    "the fused ingest program only"
                )
            return False
        if self.params.dummy_mode and self._driver_factory is None:
            log.warning(
                "ingest_backend='fused' needs a wire-streaming driver; "
                "dummy_mode synthesizes scans above the protocol layer — "
                "falling back to the host ingest path"
            )
            return False
        return True

    def on_configure(self) -> bool:
        log.info("%s: configuring (port=%s)", self.name, self.params.serial_port)
        # persistent-compile-cache flag first, ahead of any engine/chain
        # construction that compiles hot-path programs: a warm restart of
        # a lifecycle node should load its programs from disk, not pay
        # seconds of XLA compile while the device streams into a dead pump
        from rplidar_ros2_driver_tpu.utils.backend import (
            maybe_enable_compilation_cache,
        )

        maybe_enable_compilation_cache(self.params.compilation_cache_dir)
        if self._driver_factory is None and not self.params.dummy_mode:
            # probe the native I/O library here, not inside the scan thread:
            # when it cannot be built/loaded the driver falls back to the
            # pure-Python transport (protocol/pytransport.py), which works
            # but loses the SCHED_RR rx elevation — worth one loud notice
            from rplidar_ros2_driver_tpu import native

            if not native.available():
                log.warning("native I/O library unavailable (see "
                            "native/Makefile); real driver will use the "
                            "pure-Python transport fallback")
        factory = self._driver_factory or self._default_factory
        fused = self._resolve_fused_ingest()
        if fused:
            from rplidar_ros2_driver_tpu.driver.ingest import FusedIngest

            self.fused_ingest = FusedIngest(self.params)
            base_factory = factory

            def factory():  # noqa: F811 - deliberate seam wrapper
                drv = base_factory()
                if not hasattr(drv, "set_ingest_sink"):
                    # a custom factory handed us a driver without the
                    # ingest seam: surface ONE clear configuration error
                    # instead of an AttributeError crash-looping the FSM
                    # through RESETTING on every driver recreation
                    raise RuntimeError(
                        "ingest_backend='fused' requires a driver with "
                        "set_ingest_sink (wire-streaming RealLidarDriver); "
                        f"{type(drv).__name__} has none — use "
                        "ingest_backend='host' with this driver factory"
                    )
                # re-attach the one engine (and its rolling filter
                # window) to every recreated driver, like the chain
                # survives FSM resets on the host path
                drv.set_ingest_sink(self.fused_ingest)
                return drv

        self.fsm = ScanLoopFsm(
            factory,
            self._on_scan,
            params=self.params,
            timings=self._fsm_timings,
            on_state_change=self._on_fsm_state,
            on_filtered=self._on_filtered_output if fused else None,
        )
        if self.params.filter_chain and not fused:
            self.chain = ScanFilterChain(self.params)
            if self._chain_snapshot is not None:
                if not self.chain.restore(self._chain_snapshot):
                    # geometry changed since the snapshot: drop it rather
                    # than re-trying (and re-warning) every configure
                    self._chain_snapshot = None
        if self.chain is None and not fused:
            # raw publish path: warm its jitted kernels now (the
            # publish-path analog of the chain/decoder precompiles) so
            # the first live revolution doesn't stall on an XLA compile
            self.precompile_publish_kernels()
        if self.params.map_enable and self.params.filter_chain:
            from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper

            self.mapper = FleetMapper(self.params, 1)
            if self._mapper_snapshot is not None:
                if not self.mapper.restore(self._mapper_snapshot):
                    # geometry/schema changed since the snapshot: drop it
                    # rather than re-warning every configure (the chain's
                    # stale-snapshot policy)
                    self._mapper_snapshot = None
            if self.params.loop_enable:
                from rplidar_ros2_driver_tpu.slam.loop import (
                    LoopClosureEngine,
                )

                self.loop = LoopClosureEngine(self.params, self.mapper)
                self.loop.precompile()
                if self._loop_snapshot is not None:
                    if not self.loop.restore(self._loop_snapshot):
                        self._loop_snapshot = None
        self.diagnostics = DiagnosticsUpdater(
            hardware_id=f"rplidar-{self.params.serial_port}",
            publisher=self.publisher,
        )
        if self.params.publish_tf:
            self.publisher.publish_tf_static(
                StaticTransform(child=self.params.frame_id)
            )
        self._update_diagnostics()
        return True

    def precompile_publish_kernels(self) -> None:
        """Warm the RAW publish path's jitted kernels — ascend_scan (via
        apply_angle_compensation) and to_laserscan — on a throwaway
        all-masked batch, both is_new_type lowerings.  Chain-path
        configs never reach these kernels (the chain publishes its own
        output), so this runs only when the raw path is live; the dummy
        batch is shape-identical to a live one (from_numpy pads to
        MAX_SCAN_NODES), so the first real revolution hits a warm jit
        cache."""
        import numpy as np

        from rplidar_ros2_driver_tpu.ops.ascend import (
            apply_angle_compensation,
        )

        z = np.zeros((0,), np.int32)
        batch = apply_angle_compensation(
            ScanBatch.from_numpy(z, z, z), self.params.angle_compensate
        )
        for is_new in (False, True):
            to_laserscan(
                batch,
                0.1,
                40.0,
                scan_processing=self.params.scan_processing,
                inverted=self.params.inverted,
                is_new_type=is_new,
            )

    def on_activate(self) -> bool:
        assert self.fsm is not None
        self.fsm.start()
        self._update_diagnostics()
        return True

    def _on_fsm_state(self, state) -> None:
        # leaving RUNNING (deactivate, hot-unplug, RESETTING): drain the
        # pipelined publish seam NOW — the chain (and its pending output)
        # survives driver recreation, and an output held across a
        # recovery gap would otherwise be published arbitrarily late
        # into the resumed stream
        if state is not DriverState.RUNNING:
            self._drain_pipeline()
        self._update_diagnostics()

    def _drain_pipeline(self) -> None:
        """Publish the pipelined seam's in-flight revolution, if any.

        Must never raise: it runs inside the FSM loop's error handler
        (leaving RUNNING on a fault), where an escaping exception —
        e.g. the flush fetch failing on the same broken device path that
        caused the fault — would unwind the scan thread and kill
        recovery.  The pending output is dropped in that case."""
        if self.chain is None or self._pipeline_meta is None:
            return
        meta, self._pipeline_meta = self._pipeline_meta, None
        try:
            out = self.chain.flush_pipelined()
            if out is not None:
                self._publish_chain_output(out, *meta)
        except Exception:
            # the meta is spent, so the re-stashed wire (flush re-stashes
            # on fetch faults/timeouts for retrying callers) must go too
            # — otherwise a resumed stream would fetch stale data
            self.chain.discard_pipelined()
            log.warning("dropping in-flight pipelined output (drain failed)",
                        exc_info=True)

    def on_deactivate(self) -> bool:
        if self.fsm:
            self.fsm.stop()
        # drain the pipelined publish seam: the last revolution's output
        # is still in flight when the scan thread stops
        self._drain_pipeline()
        # preserve the rolling window across deactivate/activate — the
        # framework's checkpoint surface (SURVEY.md §5)
        if self.chain is not None:
            self._chain_snapshot = self.chain.snapshot()
        if self.mapper is not None:
            self._mapper_snapshot = self.mapper.snapshot()
        if self.loop is not None:
            self._loop_snapshot = self.loop.snapshot()
        self._update_diagnostics()
        return True

    def on_cleanup(self) -> bool:
        self.fsm = None
        self.chain = None
        self.fused_ingest = None
        self.mapper = None
        self.loop = None
        # _chain_snapshot / _mapper_snapshot intentionally survive
        # cleanup: they are the checkpoint/resume surface (SURVEY.md §5)
        # — a later configure restores the rolling window and the map.
        # discard_checkpoint() drops them.
        return True

    def discard_checkpoint(self) -> None:
        """Forget the saved filter-window + map snapshots (next configure
        starts cold)."""
        self._chain_snapshot = None
        self._mapper_snapshot = None
        self._loop_snapshot = None

    # keys of the mapper's MapState inside the combined node checkpoint:
    # "mapper." prefixed, schema-versioned by the mapper's own "version"
    # entry (ops/scan_match.MAP_STATE_VERSION) so a mapper survives node
    # restarts across format revisions — a future-format checkpoint is
    # rejected at restore, never misread
    _MAPPER_KEY_PREFIX = "mapper."
    # the loop-closure engine's LoopState rides the same combined file
    # under "loop." keys, schema-versioned by its own "version" entry
    # (ops/loop_close.LOOP_STATE_VERSION)
    _LOOP_KEY_PREFIX = "loop."

    def _split_checkpoint(
        self, snap: dict
    ) -> tuple[dict, Optional[dict], Optional[dict]]:
        """(chain keys, mapper keys or None, loop keys or None) of a
        combined checkpoint."""
        mp, lp = self._MAPPER_KEY_PREFIX, self._LOOP_KEY_PREFIX
        chain = {
            k: v for k, v in snap.items()
            if not k.startswith(mp) and not k.startswith(lp)
        }
        mapper = {k[len(mp):]: v for k, v in snap.items() if k.startswith(mp)}
        loop = {k[len(lp):]: v for k, v in snap.items() if k.startswith(lp)}
        return chain, (mapper or None), (loop or None)

    def save_checkpoint(self, path: str) -> bool:
        """Persist the filter-chain state — and, when the mapper is
        enabled, its MapState under versioned ``mapper.*`` keys — to one
        atomic file (utils/checkpoint.py).

        Uses the live state when active/inactive, else the last
        deactivate-time snapshots.  Returns False when there is nothing
        to save (no chain configured and no snapshot held).
        """
        from rplidar_ros2_driver_tpu.utils.checkpoint import save_checkpoint

        snap = self.chain.snapshot() if self.chain is not None else self._chain_snapshot
        if snap is None:
            return False
        snap = dict(snap)
        mapper_snap = (
            self.mapper.snapshot() if self.mapper is not None
            else self._mapper_snapshot
        )
        if mapper_snap is not None:
            for k, v in mapper_snap.items():
                snap[self._MAPPER_KEY_PREFIX + k] = v
        loop_snap = (
            self.loop.snapshot() if self.loop is not None
            else self._loop_snapshot
        )
        if loop_snap is not None:
            for k, v in loop_snap.items():
                snap[self._LOOP_KEY_PREFIX + k] = v
        save_checkpoint(path, snap, extra={"node": self.name})
        return True

    def load_checkpoint(self, path: str) -> bool:
        """Stage an on-disk checkpoint for the next configure (or restore it
        immediately into an already-configured chain and mapper).

        Returns False — touching nothing — when the file is absent/torn
        or its geometry doesn't match the current chain parameters, so a
        True return means the state genuinely resumed (or will on the next
        configure).  Mapper keys are restored when present and compatible;
        an incompatible map (changed geometry/schema) is dropped with the
        chain still restored — the map is derived state, the window is
        not."""
        from rplidar_ros2_driver_tpu.mapping.mapper import FleetMapper
        from rplidar_ros2_driver_tpu.utils.checkpoint import load_checkpoint

        if not self.params.filter_chain:
            return False
        loaded = load_checkpoint(path)
        if loaded is None:
            return False
        snap, _meta = loaded
        snap, mapper_snap, loop_snap = self._split_checkpoint(snap)

        def stage_mapper() -> None:
            if mapper_snap is not None:
                if self.mapper is not None:
                    if self.mapper.restore(mapper_snap):
                        self._mapper_snapshot = mapper_snap
                elif FleetMapper.snapshot_compatible(self.params, mapper_snap):
                    self._mapper_snapshot = mapper_snap
            if loop_snap is not None:
                if self.loop is not None:
                    if self.loop.restore(loop_snap):
                        self._loop_snapshot = loop_snap
                else:
                    # no live engine yet: stage for the next configure,
                    # whose restore() validates geometry/schema (derived
                    # state — an incompatible library is dropped there
                    # with the chain/map still restored)
                    self._loop_snapshot = loop_snap

        if self.chain is not None:
            if not self.chain.restore(snap):  # rejects mismatch untouched
                return False
            self._chain_snapshot = snap
            stage_mapper()
            return True
        # no live chain yet: validate host-side against the geometry the
        # next configure will build (no device transfers)
        if not ScanFilterChain.snapshot_compatible(self.params, snap):
            return False
        self._chain_snapshot = snap
        stage_mapper()
        return True

    def on_shutdown(self) -> bool:
        return True

    # ------------------------------------------------------------------
    # hot path: one revolution
    # ------------------------------------------------------------------

    def _on_scan(self, scan: dict, start_time: float, duration: float) -> None:
        """One revolution, as raw host arrays (angle_q14/dist_q2/quality/
        flag numpy).  Chain path: one bit-packed transfer + one dispatch.
        Raw path: ScanBatch conversion + optional angle compensation +
        the to_laserscan kernel (publish_scan, src/rplidar_node.cpp:558-683)."""
        params = self.params
        max_range = self.fsm.cached_max_range or 40.0
        is_new = True
        if self.fsm.driver is not None:
            is_new = self.fsm.driver.is_new_type()

        with self.tracer.stage("filter"):
            out = None
            if self.chain is not None:
                if params.pipelined_publish:
                    # publish revolution N-1 while N computes: the fetch
                    # below touches an already-finished step, so the
                    # publish never waits on device compute (one
                    # revolution of declared staleness; the stamp below
                    # is N-1's own)
                    out = self.chain.process_raw_pipelined(
                        scan["angle_q14"], scan["dist_q2"], scan["quality"],
                        scan.get("flag"),
                    )
                    # max_range travels with the revolution too: a
                    # scan-mode hot-swap between N-1 and N must not pair
                    # N-1's ranges with N's range_max in the header
                    meta, self._pipeline_meta = (
                        self._pipeline_meta, (start_time, duration, max_range)
                    )
                    if out is None or meta is None:
                        return  # first revolution of the stream: nothing pending
                    start_time, duration, max_range = meta
                else:
                    if self._pipeline_meta is not None:
                        # pipelined_publish was toggled off mid-stream:
                        # the in-flight revolution would otherwise sit
                        # pending until the next FSM transition and then
                        # publish arbitrarily late — drain it now, in
                        # order, before this revolution's blocking step
                        self._drain_pipeline()
                    out = self.chain.process_raw(
                        scan["angle_q14"], scan["dist_q2"], scan["quality"],
                        scan.get("flag"),
                    )

        if out is not None:
            self._publish_chain_output(out, start_time, duration, max_range)
            return

        with self.tracer.stage("convert"):
            from rplidar_ros2_driver_tpu.ops.ascend import (
                apply_angle_compensation,
            )

            batch = apply_angle_compensation(
                ScanBatch.from_numpy(
                    scan["angle_q14"], scan["dist_q2"], scan["quality"],
                    scan.get("flag"),
                ),
                params.angle_compensate,
            )
            ls = to_laserscan(
                batch,
                duration,
                max_range,
                scan_processing=params.scan_processing,
                inverted=params.inverted,
                is_new_type=is_new,
            )
            bc = int(ls.beam_count)
            if bc == 0:
                return
            msg = LaserScanHost(
                stamp=start_time,
                frame_id=params.frame_id,
                angle_min=float(ls.angle_min),
                angle_max=float(ls.angle_max),
                angle_increment=float(ls.angle_increment),
                time_increment=float(ls.time_increment),
                scan_time=float(ls.scan_time),
                range_min=float(ls.range_min),
                range_max=float(ls.range_max),
                ranges=np.asarray(ls.ranges)[:bc],
                intensities=np.asarray(ls.intensities)[:bc],
            )

        with self.tracer.stage("publish"):
            self.publisher.publish_scan(msg)

    def _on_filtered_output(self, out, ts0: float, duration: float) -> None:
        """Fused-ingest publish hook (FSM RUNNING loop): the revolution
        arrived decoded, assembled and filtered on-device — straight to
        the shared chain-output publisher."""
        with self.tracer.stage("filter"):
            pass  # device work already done inside the fused dispatch
        self._publish_chain_output(out, ts0, duration)

    def _publish_chain_output(
        self, out, stamp: float, duration: float, max_range: Optional[float] = None
    ) -> None:
        """Convert + publish one chain FilterOutput (shared by the
        synchronous path, the pipelined path, the deactivate-time
        pipeline drain, and the fused-ingest hook).  The output is
        already on the fixed angular grid."""
        params = self.params
        if max_range is None:
            max_range = (self.fsm.cached_max_range if self.fsm else None) or 40.0
        with self.tracer.stage("convert"):
            # beams from the output itself: the fused path has no
            # self.chain, and the grid width is intrinsic to the output
            beams = int(np.asarray(out.ranges).shape[0])
            msg = LaserScanHost(
                stamp=stamp,
                frame_id=params.frame_id,
                angle_min=0.0,
                angle_max=2.0 * np.pi,
                angle_increment=2.0 * np.pi / beams,
                time_increment=duration / beams,
                scan_time=duration,
                range_min=params.range_clip_min_m,
                range_max=max_range,
                ranges=np.asarray(out.ranges),
                intensities=np.asarray(out.intensities),
            )
        with self.tracer.stage("publish"):
            self.publisher.publish_scan(msg)
            self.publisher.publish_cloud(
                PointCloudHost(
                    stamp=stamp,
                    frame_id=params.frame_id,
                    points_xy=np.asarray(out.points_xy)[np.asarray(out.point_mask)],
                    voxel=np.asarray(out.voxel),
                )
            )
        if self.mapper is not None:
            with self.tracer.stage("map"):
                est = self.mapper.submit([out])[0]
                if self.loop is not None:
                    # the back-end observes every mapper tick: submap
                    # finalization + (when due) ONE closure-check
                    # dispatch; the published pose below becomes the
                    # pose-graph-corrected one
                    self.loop.observe([est])
            if est is not None:
                from rplidar_ros2_driver_tpu.node.messages import PoseHost

                x_m, y_m, theta_rad = est.x_m, est.y_m, est.theta_rad
                if self.loop is not None:
                    from rplidar_ros2_driver_tpu.ops.scan_match import (
                        pose_to_metric,
                    )

                    x_m, y_m, theta_rad = pose_to_metric(
                        self.loop.corrected_pose_q(0, est.pose_q),
                        self.mapper.cfg,
                    )
                self.publisher.publish_pose(PoseHost(
                    stamp=stamp,
                    frame_id="map",
                    child_frame_id=params.frame_id,
                    x_m=x_m,
                    y_m=y_m,
                    theta_rad=theta_rad,
                    score=est.score,
                    matched_points=est.matched_points,
                    map_revision=est.revision,
                ))

    # ------------------------------------------------------------------
    # diagnostics (src/rplidar_node.cpp:490-545)
    # ------------------------------------------------------------------

    def _update_diagnostics(self) -> None:
        if self.diagnostics is None:
            return
        lc = self.lifecycle_state
        fsm_state = self.fsm.state if self.fsm else None
        lat = {}
        for stage in ("filter", "convert", "publish", "map"):
            p = self.tracer.percentile(stage, 99.0)
            if p > 0:
                lat[stage] = 1e3 * p
        driver = self.fsm.driver if self.fsm else None
        rx_sched = driver.rx_scheduling_class() if driver is not None else None
        map_status = None
        if self.mapper is not None:
            est = self.mapper.last_estimates[0]
            map_status = {"backend": self.mapper.backend}
            if est is not None:
                map_status.update(
                    pose=(est.x_m, est.y_m, est.theta_rad),
                    score=est.score,
                    revision=est.revision,
                )
        reconnect = None
        if self.fsm is not None and (
            self.fsm.connect_attempts or self.fsm.reconnect_backoff_s
        ):
            reconnect = {
                "attempts": self.fsm.connect_attempts,
                "backoff_s": self.fsm.reconnect_backoff_s,
            }
            drv_failures = getattr(driver, "connect_failures", None)
            if drv_failures:
                reconnect["driver_failures"] = drv_failures
        self.diagnostics.update(
            lifecycle=lc,
            fsm_state=fsm_state,
            port=self.params.serial_port,
            rpm=self.params.rpm,
            device_info=self.fsm.cached_device_info if self.fsm else "",
            latency_p99_ms=lat or None,
            rx_scheduling=rx_sched,
            map_status=map_status,
            loop_status=self.loop.status() if self.loop is not None else None,
            reconnect=reconnect,
        )

    # ------------------------------------------------------------------
    # dynamic reconfigure (src/rplidar_node.cpp:689-774)
    # ------------------------------------------------------------------

    def set_parameters(self, updates: dict) -> tuple[bool, str]:
        """Runtime parameter updates; returns (successful, reason)."""
        with self._param_lock:
            if self.fsm is None or self.fsm.driver is None:
                return False, "Driver not ready"
            with self.fsm.driver_mutex:
                if not self.fsm.driver.is_connected():
                    return False, "Driver not ready"
                for key, value in updates.items():
                    if key == "rpm":
                        if not isinstance(value, int) or not (0 <= value <= 1200):
                            return False, f"rpm {value} out of range [0, 1200]"
                        if not self.fsm.driver.set_motor_speed(value):
                            return False, "failed to apply motor speed"
                        self.params.rpm = value
                    elif key == "scan_processing":
                        self.params.scan_processing = bool(value)
                    elif key == "scan_mode":
                        ok = self._hot_swap_scan_mode(str(value))
                        if not ok:
                            return False, f"scan mode '{value}' rejected"
                    else:
                        return False, f"parameter '{key}' is not runtime-mutable"
            self._update_diagnostics()
            return True, "success"

    def _hot_swap_scan_mode(self, mode: str) -> bool:
        """stop motor -> 500 ms -> restart in new mode; fall back to auto
        on failure (src/rplidar_node.cpp:740-770)."""
        drv = self.fsm.driver
        drv.stop_motor()
        time.sleep(0.5 if self._fsm_timings is None else self._fsm_timings.idle_tick_s)
        if drv.start_motor(mode, self.params.rpm):
            self.params.scan_mode = mode
            return True
        log.error("scan mode '%s' failed; falling back to auto", mode)
        drv.start_motor("", self.params.rpm)
        self.params.scan_mode = ""
        return False


def make_node_from_yaml(path: str, **kwargs) -> RPlidarNode:
    """Launch-file equivalent: YAML is the single source of truth
    (launch/rplidar.launch.py:86-93)."""
    return RPlidarNode(DriverParams.from_yaml(path), **kwargs)


def launch(node: RPlidarNode) -> RPlidarNode:
    """Auto lifecycle bringup: CONFIGURE on start, ACTIVATE once inactive
    (launch/rplidar.launch.py:109-141)."""
    if node.lifecycle_state is LifecycleState.UNCONFIGURED:
        if not node.configure():
            return node
    if node.lifecycle_state is LifecycleState.INACTIVE:
        node.activate()
    return node
