"""The 5-state fault-tolerant scan loop FSM.

Behavioral mirror of the reference's ``scan_loop``
(src/rplidar_node.cpp:304-484):

    CONNECTING -> CHECK_HEALTH -> WARMUP -> RUNNING
         ^------------- RESETTING <-- (errors) --'

  * CONNECTING   — (re)create driver (dummy vs real factory), retry connect
    every 1 s, detect model strategy, cache device-info string
  * CHECK_HEALTH — gate on health (OK/WARNING pass; ERROR -> disconnect,
    1 s, back to CONNECTING)
  * WARMUP       — start motor + scan mode; failure -> RESETTING
  * RUNNING      — grab + publish; consecutive failures > max_retries ->
    RESETTING (1 ms between retries)
  * RESETTING    — destroy and recreate the driver object, 2 s backoff

Timings are injected (FsmTimings) so tests run the same logic at speed;
defaults match the reference constants (:336,:438,:468,:479).
"""

from __future__ import annotations

import dataclasses
import enum
import logging
import threading
import time
from typing import Callable, Optional

from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.driver.interface import LidarDriverInterface

log = logging.getLogger("rplidar_tpu.fsm")


class DriverState(enum.Enum):
    CONNECTING = "connecting"
    CHECK_HEALTH = "check_health"
    WARMUP = "warmup"
    RUNNING = "running"
    RESETTING = "resetting"


@dataclasses.dataclass
class FsmTimings:
    connect_retry_s: float = 1.0
    health_retry_s: float = 1.0
    reset_backoff_s: float = 2.0
    idle_tick_s: float = 0.01
    grab_retry_s: float = 0.001
    grab_timeout_s: float = 2.0
    warmup_motor_s: float = 0.0  # motor warm-up handled inside drivers
    # ceiling of the CONNECTING retry backoff: the flat 1 s retry is the
    # FIRST delay (connect_retry_s = the base), then capped exponential
    # growth via driver/health.BackoffPolicy — a dead port costs
    # seconds-apart probes, not a tight 1 Hz reconnect storm forever
    connect_backoff_max_s: float = 10.0

    @classmethod
    def fast(cls) -> "FsmTimings":
        """Millisecond-scale variant for tests."""
        return cls(0.01, 0.01, 0.02, 0.001, 0.0005, 0.25,
                   connect_backoff_max_s=0.08)


class ScanLoopFsm:
    """Runs the fault-tolerant acquisition loop on a dedicated thread.

    The node supplies the driver factory, the scan consumer callback and
    (optionally) a state-change hook for diagnostics.  The driver mutex
    serializes grabs against dynamic reconfigure, exactly like the
    reference's ``driver_mutex_`` (include/rplidar_node.hpp:322) — and we
    hold it in CONNECTING/WARMUP too, closing the reference's documented
    race (SURVEY.md §5 race notes).
    """

    def __init__(
        self,
        driver_factory: Callable[[], LidarDriverInterface],
        on_scan: Callable[[dict, float, float], None],
        *,
        params,
        timings: Optional[FsmTimings] = None,
        on_state_change: Optional[Callable[[DriverState], None]] = None,
        on_connected: Optional[Callable[[LidarDriverInterface], None]] = None,
        on_filtered: Optional[Callable] = None,
    ) -> None:
        self._factory = driver_factory
        self._on_scan = on_scan
        # fused-ingest consumer (ingest_backend="fused"): called once per
        # completed revolution with (FilterOutput, ts0, duration) — the
        # revolution was decoded, assembled AND filtered on-device, so
        # there is no host scan dict to hand to on_scan
        self._on_filtered = on_filtered
        self._params = params
        self._t = timings or FsmTimings()
        self._on_state_change = on_state_change
        self._on_connected = on_connected

        self.driver: Optional[LidarDriverInterface] = None
        self.driver_mutex = threading.RLock()
        self._state = DriverState.CONNECTING
        self._state_lock = threading.Lock()
        self._running = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self.cached_device_info = ""
        self.cached_max_range = 0.0
        self.error_count = 0
        self.reset_count = 0
        # CONNECTING retry discipline: capped exponential backoff
        # (driver/health.BackoffPolicy) instead of the reference's flat
        # 1 s loop, with the attempt count surfaced in /diagnostics
        from rplidar_ros2_driver_tpu.driver.health import BackoffPolicy

        self._connect_backoff = BackoffPolicy(
            self._t.connect_retry_s,
            max(self._t.connect_backoff_max_s, self._t.connect_retry_s),
            jitter=0.1,
        )
        # cumulative connect attempts this session (successes included —
        # the driver-level connect_failures counter carries the failures,
        # so the two diagnostics values read consistently)
        self.connect_attempts = 0

    # -- state accessors ----------------------------------------------------

    @property
    def state(self) -> DriverState:
        with self._state_lock:
            return self._state

    def _set_state(self, s: DriverState) -> None:
        with self._state_lock:
            if s is self._state:
                return
            self._state = s
        log.info("[FSM] -> %s", s.value)
        if self._on_state_change:
            self._on_state_change(s)

    @property
    def is_scanning(self) -> bool:
        return self._running.is_set()

    @property
    def reconnect_backoff_s(self) -> float:
        """The CONNECTING retry delay most recently slept (0 when the
        last connect succeeded) — /diagnostics observability."""
        return self._connect_backoff.last_delay_s

    # -- thread lifecycle ---------------------------------------------------

    def start(self) -> None:
        if self._thread and self._thread.is_alive():
            return
        self._running.set()
        self._thread = threading.Thread(target=self._loop, name="scan_loop", daemon=True)
        self._thread.start()

    def stop(self, join_timeout_s: float = 5.0) -> None:
        self._running.clear()
        if self._thread:
            self._thread.join(join_timeout_s)
            self._thread = None
        with self.driver_mutex:
            if self.driver is not None:
                try:
                    self.driver.stop_motor()
                    self.driver.disconnect()
                except Exception:
                    log.exception("driver shutdown failed")

    # -- the loop -----------------------------------------------------------

    def _interruptible_sleep(self, seconds: float) -> None:
        deadline = time.monotonic() + seconds
        while self._running.is_set():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return
            time.sleep(min(remaining, 0.05))

    def _loop(self) -> None:
        self._set_state(DriverState.CONNECTING)
        self.error_count = 0
        log.info("[FSM] Scan loop started.")
        while self._running.is_set():
            state = self.state
            try:
                if state is DriverState.CONNECTING:
                    self._do_connecting()
                elif state is DriverState.CHECK_HEALTH:
                    self._do_check_health()
                elif state is DriverState.WARMUP:
                    self._do_warmup()
                elif state is DriverState.RUNNING:
                    self._do_running()
                elif state is DriverState.RESETTING:
                    self._do_resetting()
            except Exception:
                # A raising driver (or factory) must never kill the loop —
                # that would defeat the whole recovery design.  Treat it as a
                # hardware fault and go through RESETTING like any other.
                log.exception("[FSM] Unhandled error in state %s; resetting", state.value)
                if state is DriverState.RESETTING:
                    # factory itself is failing: back off before retrying
                    self._interruptible_sleep(self._t.reset_backoff_s)
                self._set_state(DriverState.RESETTING)
            if self.state is not DriverState.RUNNING:
                self._interruptible_sleep(self._t.idle_tick_s)
        log.info("[FSM] Scan loop terminated.")

    def _do_connecting(self) -> None:
        with self.driver_mutex:
            if self.driver is None:
                self.driver = self._factory()
            if not self.driver.is_connected():
                self.connect_attempts += 1
                ok = self.driver.connect(
                    self._params.serial_port,
                    self._params.serial_baudrate,
                    self._params.angle_compensate,
                )
                if not ok:
                    delay = self._connect_backoff.next_delay()
                    log.warning(
                        "[FSM] Connection failed (attempt %d). Retrying "
                        "in %.2f s...", self.connect_attempts, delay,
                    )
                    self._interruptible_sleep(delay)
                    return
                self._connect_backoff.reset()
                log.info("[FSM] Connection established.")
            self.driver.detect_and_init_strategy()
            self.cached_device_info = self.driver.get_device_info_str()
            log.info("[Hardware Detail] %s", self.cached_device_info)
            if self._on_connected:
                self._on_connected(self.driver)
        self._set_state(DriverState.CHECK_HEALTH)

    def _do_check_health(self) -> None:
        with self.driver_mutex:
            health = self.driver.get_health()
        if health in (DeviceHealth.OK, DeviceHealth.WARNING):
            self._set_state(DriverState.WARMUP)
        else:
            log.error("[FSM] Health error: %d. Disconnecting...", int(health))
            with self.driver_mutex:
                self.driver.disconnect()
            self._interruptible_sleep(self._t.health_retry_s)
            self._set_state(DriverState.CONNECTING)

    def _do_warmup(self) -> None:
        log.info("[FSM] Starting motor...")
        with self.driver_mutex:
            ok = self.driver.start_motor(self._params.scan_mode, self._params.rpm)
            if ok:
                self.driver.print_summary()
                hw_limit = self.driver.get_hw_max_distance()
                if self._params.max_distance > 0.0:
                    self.cached_max_range = min(self._params.max_distance, hw_limit)
                else:
                    self.cached_max_range = hw_limit
        if ok:
            log.info("[Config] Max Range: %.2f m", self.cached_max_range)
            self.error_count = 0
            self._set_state(DriverState.RUNNING)
        else:
            log.error("[FSM] Failed to start motor.")
            self._set_state(DriverState.RESETTING)

    def _do_running(self) -> None:
        # on_filtered is only wired when the node resolved the fused
        # ingest seam (node.on_configure via resolve_ingest_backend) —
        # re-deriving the backend from the raw param string here would
        # diverge the moment "auto" resolves to fused
        if self._on_filtered is not None:
            self._do_running_fused()
            return
        start_time = time.monotonic()
        scan: Optional[dict] = None
        ts0 = duration = None
        with self.driver_mutex:
            if self.driver is not None and self.driver.is_connected():
                # host-native timestamped grab (back-dated revolution begin,
                # grabScanDataHqWithTimeStamp parity): raw numpy arrays, so
                # the consumer controls the one host->device transfer
                got = self.driver.grab_scan_host(self._t.grab_timeout_s)
                if got is not None:
                    scan, ts0, duration = got
        if scan is None:
            self.error_count += 1
            if self.error_count > self._params.max_retries:
                log.error(
                    "[FSM] Hardware unresponsive (Over %d errors). Resetting...",
                    self._params.max_retries,
                )
                self._set_state(DriverState.RESETTING)
            else:
                self._interruptible_sleep(self._t.grab_retry_s)
            return
        self.error_count = 0
        if ts0 is None or duration is None or duration <= 0:
            ts0 = start_time
            duration = time.monotonic() - start_time
        self._on_scan(scan, ts0, duration)

    def _do_running_fused(self) -> None:
        """RUNNING step for the fused ingest backend: one dispatched
        frame batch's completed revolutions per iteration, already
        filtered on-device.  A timeout (None) walks the same
        error-count -> RESETTING path as a failed host grab; an empty
        list (mid-revolution batch) is healthy progress."""
        outs = None
        with self.driver_mutex:
            if self.driver is not None and self.driver.is_connected():
                grab = getattr(self.driver, "grab_filtered", None)
                if grab is not None:
                    outs = grab(self._t.grab_timeout_s)
        if outs is None:
            self.error_count += 1
            if self.error_count > self._params.max_retries:
                log.error(
                    "[FSM] Hardware unresponsive (Over %d errors). Resetting...",
                    self._params.max_retries,
                )
                self._set_state(DriverState.RESETTING)
            else:
                self._interruptible_sleep(self._t.grab_retry_s)
            return
        self.error_count = 0
        for out, ts0, duration in outs:
            self._on_filtered(out, ts0, duration)

    def _do_resetting(self) -> None:
        log.warning("[FSM] Performing hardware reset (recreating driver)...")
        with self.driver_mutex:
            if self.driver is not None:
                try:
                    self.driver.disconnect()
                except Exception:
                    pass
            self.driver = self._factory()
        self.reset_count += 1
        self._interruptible_sleep(self._t.reset_backoff_s)
        self._set_state(DriverState.CONNECTING)
        self.error_count = 0
