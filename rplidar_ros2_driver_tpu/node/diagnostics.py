"""Diagnostics updater.

Equivalent of the reference's diagnostic_updater wiring
(src/rplidar_node.cpp:206-208, 490-545): hardware id ``rplidar-<port>``,
a lifecycle-gated summary level and message, and key/value details (port,
target RPM, cached device info).
"""

from __future__ import annotations

from typing import Optional

from rplidar_ros2_driver_tpu.node.fsm import DriverState
from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
from rplidar_ros2_driver_tpu.node.messages import DiagLevel, DiagnosticStatus


def summarize(
    lifecycle: LifecycleState, fsm_state: Optional[DriverState]
) -> tuple[DiagLevel, str]:
    """Level/message table mirroring update_diagnostics
    (src/rplidar_node.cpp:497-520)."""
    if lifecycle is not LifecycleState.ACTIVE:
        return DiagLevel.OK, "Node Inactive (Lifecycle)"
    if fsm_state is DriverState.RUNNING:
        return DiagLevel.OK, "Scanning"
    if fsm_state is DriverState.WARMUP:
        return DiagLevel.WARN, "Warming Up"
    if fsm_state in (DriverState.CONNECTING, DriverState.CHECK_HEALTH):
        return DiagLevel.WARN, "Connecting"
    if fsm_state is DriverState.RESETTING:
        return DiagLevel.ERROR, "Resetting Hardware"
    return DiagLevel.WARN, "Unknown"


def rx_scheduling_label(code: int) -> str:
    """Human label for a driver rx_scheduling_class code — the ONE
    mapping, shared by /diagnostics and the doctor CLI."""
    return {
        2: "SCHED_RR",
        1: "nice boost",
        0: "default",
        -1: "no elevation",
    }.get(code, "n/a")


class DiagnosticsUpdater:
    def __init__(self, hardware_id: str, publisher) -> None:
        self.hardware_id = hardware_id
        self._publisher = publisher
        self.last: Optional[DiagnosticStatus] = None

    # graftlint: read-path
    def update(
        self,
        lifecycle: LifecycleState,
        fsm_state: Optional[DriverState],
        port: str,
        rpm: int,
        device_info: str,
        latency_p99_ms: Optional[dict[str, float]] = None,
        rx_scheduling: Optional[int] = None,
        map_status: Optional[dict] = None,
        loop_status: Optional[dict] = None,
        reconnect: Optional[dict] = None,
        stream_health: Optional[list] = None,
        shard_topology: Optional[dict] = None,
        scheduler: Optional[dict] = None,
        pod: Optional[dict] = None,
        world_map: Optional[dict] = None,
    ) -> DiagnosticStatus:
        level, message = summarize(lifecycle, fsm_state)
        values = {
            "Serial Port": port,
            "Target RPM": str(rpm),
            "Device Info": device_info,
            "FSM State": fsm_state.value if fsm_state else "n/a",
            "Lifecycle": lifecycle.value,
        }
        if rx_scheduling is not None:
            # the reference's PRIORITY_HIGH rx/decoder contract, observable
            values["RX Scheduling"] = rx_scheduling_label(rx_scheduling)
        # per-stage p99 latencies (utils/tracing.py) — the observability for
        # the <10 ms added-p99 publish-latency north star (BASELINE.md)
        if latency_p99_ms:
            for stage, ms in sorted(latency_p99_ms.items()):
                values[f"p99 {stage} (ms)"] = f"{ms:.3f}"
        # SLAM front-end observability (mapping/mapper.FleetMapper): the
        # matcher's pose/score/map-revision, mirroring how latencies ride
        # the same status message
        if map_status:
            values["Map Backend"] = str(map_status.get("backend", "?"))
            pose = map_status.get("pose")
            if pose is not None:
                x, y, th = pose
                values["Map Pose"] = f"{x:+.3f} {y:+.3f} {th:+.4f}"
                values["Map Match Score"] = str(map_status.get("score", 0))
                values["Map Revision"] = str(map_status.get("revision", 0))
        # SLAM back-end drift/loop-closure observability (slam/loop.
        # LoopClosureEngine.status()): accepted/rejected closures, the
        # per-stream submap library fill, the tick of the last accepted
        # closure, and the standing pose-correction magnitude — the
        # drift-bounded-or-not view at a glance (tests/test_loop_close.py
        # pins the rendering, like the shard-topology group)
        if loop_status:
            values["Loop Closures"] = (
                f"{loop_status.get('accepted', 0)} accepted / "
                f"{loop_status.get('rejected', 0)} rejected"
            )
            values["Loop Submaps"] = ",".join(
                str(c) for c in loop_status.get("submaps", [])
            )
            values["Loop Constraints"] = str(
                loop_status.get("constraints", 0)
            )
            last = loop_status.get("last_closure_tick")
            values["Last Closure Tick"] = (
                "n/a" if last is None else str(last)
            )
            corr = loop_status.get("correction_m")
            if corr is not None:
                cx, cy, cth = corr
                values["Pose Correction"] = (
                    f"{cx:+.3f} {cy:+.3f} {cth:+.4f}"
                )
        # reconnect observability (scan-loop FSM capped backoff +
        # driver-level connect counters): how hard the node is having to
        # fight for its link, and how long until the next attempt
        if reconnect:
            values["Connect Attempts"] = str(reconnect.get("attempts", 0))
            backoff = reconnect.get("backoff_s")
            if backoff:
                values["Reconnect Backoff (s)"] = f"{backoff:.2f}"
            drv_fail = reconnect.get("driver_failures")
            if drv_fail is not None:
                values["Driver Connect Failures"] = str(drv_fail)
        # per-stream health FSM states: FLEET deployments (which own a
        # ShardedFilterService rather than the single-stream node) feed
        # ``service.health_status()`` through this parameter — one
        # compact "state (reason)" value per stream
        # (tests/test_chaos.py pins the rendering)
        if stream_health:
            for i, st in enumerate(stream_health):
                state = st.get("state", "?")
                reason = st.get("reason") or ""
                values[f"Stream {i} Health"] = (
                    f"{state} ({reason})" if reason else state
                )
        # elastic-fleet shard topology + migration counters: pod
        # deployments (parallel/service.ElasticFleetService) feed
        # ``service.failover_status()`` through this parameter — one
        # compact "state [streams] (reason)" value per shard plus the
        # pod-level evacuation/migration counters
        # (tests/test_failover.py pins the rendering)
        if shard_topology:
            for i, sh in enumerate(shard_topology.get("shards", [])):
                state = sh.get("state", "?")
                hosted = ",".join(str(s) for s in sh.get("streams", []))
                reason = sh.get("reason") or ""
                val = f"{state} [{hosted}]"
                if reason:
                    val = f"{val} ({reason})"
                values[f"Shard {i}"] = val
            values["Evacuations"] = str(
                shard_topology.get("evacuations", 0)
            )
            values["Stream Migrations"] = str(
                shard_topology.get("migrations", 0)
            )
            values["Shard Readmissions"] = str(
                shard_topology.get("readmits", 0)
            )
            last = shard_topology.get("last_migration_tick")
            values["Last Migration Tick"] = (
                "n/a" if last is None else str(last)
            )
        # traffic-shaping scheduler (parallel/scheduler.TrafficShaper
        # via service.scheduler_status()): the current drain rung(s),
        # per-stream backlog depth + admission-shed counters (the
        # bounded-backlog contract at a glance), per-rung compiled-
        # dispatch accounting and the byte-rate placement weights —
        # mirroring the shard-topology group (tests/test_scheduler.py
        # pins the rendering)
        if scheduler:
            values["Sched Rung"] = ",".join(
                str(r) for r in scheduler.get("rungs", [])
            )
            values["Sched Backlog"] = ",".join(
                str(b) for b in scheduler.get("backlog", [])
            )
            values["Admission Drops"] = ",".join(
                str(d) for d in scheduler.get("admission_drops", [])
            )
            values["Admission Shed Total"] = str(
                scheduler.get("shed_total", 0)
            )
            rung_d = scheduler.get("rung_dispatches") or {}
            values["Rung Dispatches"] = " ".join(
                f"T{r}:{rung_d[r]}" for r in sorted(rung_d)
            ) or "n/a"
            weights = scheduler.get("weights")
            if weights is not None:
                values["Placement Weights"] = ",".join(
                    f"{w:.2f}" for w in weights
                )
            # link-latency hiding (PR 16): the measured per-(rung,
            # bucket) cost table steering the deadline cap, the bucket
            # ladder's picks, and the double buffer's overlap hit
            # count — only rendered once the model has keys / the
            # ladder is configured (a plain rung-only shaper keeps the
            # PR 14 group unchanged)
            model = scheduler.get("latency_model")
            if model:
                values["Latency Model ms"] = " ".join(
                    f"{k}:{model[k]}" for k in sorted(model)
                )
            buckets = scheduler.get("active_buckets")
            if buckets is not None:
                values["Active Bucket"] = ",".join(
                    str(b) for b in buckets
                )
                values["Bucket Switches"] = str(
                    scheduler.get("bucket_switches", 0)
                )
            hits = scheduler.get("staging_overlap_hits")
            if hits is not None:
                values["Staging Overlap Hits"] = str(hits)
        # pod-of-pods group (parallel/service.ElasticFleetService via
        # service.pod_status()): per-host shard states (PARKED marks a
        # shard the autoscaler spun down — engine released, membership
        # intact), the steal counters, the scale counters, and the
        # autoscaler's hysteresis state — mirroring the scheduler and
        # shard-topology groups (tests/test_scheduler.py pins the
        # rendering)
        if pod:
            for h in pod.get("per_host", []):
                states = " ".join(
                    f"{sh['shard']}:{sh['state']}[{sh['streams']}]"
                    for sh in h.get("shards", [])
                )
                values[f"Pod Host {h.get('host', '?')}"] = states or "n/a"
            values["Steals"] = str(pod.get("steals", 0))
            values["Steal Ticks"] = str(pod.get("steal_ticks", 0))
            values["Scale-Downs"] = str(pod.get("scale_downs", 0))
            values["Scale-Ups"] = str(pod.get("scale_ups", 0))
            auto = pod.get("autoscaler")
            if auto:
                occ = auto.get("occupancy")
                occ_s = "n/a" if occ is None else f"{occ:.3f}"
                values["Autoscaler"] = (
                    f"{auto.get('state', '?')} (occ {occ_s})"
                )
        if world_map:
            # the shared-world serving plane (mapping/worldmap.status())
            values["World Map"] = (
                f"{world_map.get('backend', '?')} "
                f"v{world_map.get('serving_version', 0)}"
            )
            values["World Tiles"] = str(world_map.get("tiles", 0))
            values["World Resident Bytes"] = str(
                world_map.get("resident_bytes", 0)
            )
            ratio = world_map.get("compression_ratio", 0.0)
            values["World Compression"] = f"{ratio:.2f}x"
            values["World Merges"] = str(world_map.get("merges", 0))
            values["World Evictions"] = str(
                world_map.get("evictions", 0)
            )
        status = DiagnosticStatus(
            level=level,
            name="rplidar_node: Device Status",
            message=message,
            hardware_id=self.hardware_id,
            values=values,
        )
        self.last = status
        self._publisher.publish_diagnostics(status)
        return status
