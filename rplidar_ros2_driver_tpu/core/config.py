"""Parameter surface.

Mirrors the reference's 13 ROS 2 parameters (declared in the node
constructor and ``init_parameters``, src/rplidar_node.cpp:80-90,268-289;
defaults shipped in param/rplidar.yaml) and adds the TPU filter-chain
parameters that are this framework's north star (BASELINE.json).

Three tiers, like the reference:
  * static params (read once at configure time),
  * runtime-mutable params (rpm / scan_processing / scan_mode,
    src/rplidar_node.cpp:689-774) — see node/node.py set_parameters,
  * device-side config (the GET/SET_LIDAR_CONF key space) — see
    protocol/conf.py.
"""

from __future__ import annotations

import dataclasses
from typing import Any

# Baud-rate table shipped in param/rplidar.yaml:9-15.
MODEL_BAUD_TABLE = {
    "A1": 115200,
    "A2M8": 115200,
    "A2M7": 256000,
    "A2M12": 256000,
    "A3": 256000,
    "S1": 256000,
    "C1": 460800,
    "S2": 1000000,
    "S3": 1000000,
}

RUNTIME_MUTABLE = ("rpm", "scan_processing", "scan_mode")

VALID_QOS = ("reliable", "best_effort")
VALID_BACKENDS = ("cpu", "tpu")
VALID_CHANNELS = ("serial", "tcp", "udp", "dummy")
# "polar" is accepted for symmetry with the BASELINE graded configs but
# the Cartesian projection is always computed inside the fused step (its
# output feeds voxelization); the other three stages toggle real work.
VALID_FILTER_STAGES = ("clip", "polar", "median", "voxel")


@dataclasses.dataclass
class DriverParams:
    """All tunables, defaults matching param/rplidar.yaml."""

    # -- connection (param/rplidar.yaml:5-15) --
    serial_port: str = "/dev/rplidar"
    serial_baudrate: int = 1000000
    channel_type: str = "serial"      # serial | tcp | udp (sl channel factories)
    tcp_host: str = "192.168.0.7"
    tcp_port: int = 20108
    udp_host: str = "192.168.11.2"
    udp_port: int = 8089

    # -- frame / geometry (param/rplidar.yaml:17-33) --
    frame_id: str = "laser"
    inverted: bool = False
    angle_compensate: bool = True

    # -- processing (param/rplidar.yaml:35-57) --
    scan_processing: bool = False
    scan_mode: str = ""               # "" => auto (DenseBoost > Sensitivity)
    rpm: int = 0                      # 0 => device default (600)
    max_distance: float = 0.0         # 0 => hardware limit

    # -- simulation / recovery (param/rplidar.yaml:59-88) --
    dummy_mode: bool = False
    max_retries: int = 3

    # -- publishing (param/rplidar.yaml:73-80) --
    publish_tf: bool = True
    qos_reliability: str = "best_effort"

    # -- TPU filter chain (new; BASELINE.json north star) --
    filter_backend: str = "tpu"       # cpu | tpu
    filter_window: int = 16           # rolling scans kept on device (<= 64 typical)
    # empty = raw passthrough (reference-parity default); enable stages for
    # the TPU pipeline, e.g. ("clip", "polar", "median", "voxel")
    filter_chain: tuple = ()
    range_clip_min_m: float = 0.15
    range_clip_max_m: float = 40.0
    intensity_min: float = 0.0
    voxel_grid_size: int = 256        # cells per side of the 2-D occupancy grid
    voxel_cell_m: float = 0.25        # metres per cell
    # temporal-median implementation: "xla" (jnp.sort), "pallas" (VMEM
    # bitonic-network kernel, ops/pallas_kernels.py), "inc" (incremental
    # sliding median over a sorted-window carried state), or "auto" —
    # pallas on TPU, inc on CPU, xla elsewhere.  Evidence behind the
    # mapping (docs/BENCHMARKS.md): pallas 2.14x over xla at W=64 and
    # 2.1-2.5x at W=256/512 (RTT-adaptive device-resident rounds,
    # 2026-07-31); inc 3.8x on the CPU full step.
    median_backend: str = "auto"
    # per-scan streaming-step resampler: "scatter" (jnp .at[].min),
    # "dense" (the fused path's tiled masked-min at K=1; bit-identical,
    # parity-tested), or "auto" — resolved per device platform from the
    # streaming-step ablation evidence (scripts/step_ablation.py;
    # resolve_resample_backend in filters/chain.py holds the mapping and
    # its provenance).  The fused replay path always uses the dense tile.
    resample_backend: str = "auto"
    # voxel accumulation kernel: "scatter" (.at[].add histogram),
    # "matmul" (one-hot einsum on the MXU, exact counts), or "auto" —
    # resolved per platform from the step-ablation evidence
    # (resolve_voxel_backend in filters/chain.py)
    voxel_backend: str = "auto"
    # ingest backend seam: "host" = the golden path (CPU-pinned batch
    # decode -> Python revolution assembly -> packed per-revolution
    # upload into the chain); "fused" = device-resident single-dispatch
    # ingest (raw frame bytes staged once, unpack + revolution
    # segmentation + the donated filter step in ONE compiled program —
    # ops/ingest.py / driver/ingest.FusedIngest; bit-exact vs host,
    # tests/test_fused_ingest.py).  "auto" resolves per the standing
    # decision procedure (filters/chain.resolve_ingest_backend —
    # currently host).  Fused requires the filter chain and a wire-
    # streaming driver (real/sim); it drops the RawNodeHolder interval
    # tap and the chain checkpoint surface.
    ingest_backend: str = "host"
    # fleet ingest backend seam (parallel/service.py submit_bytes*):
    # "host" = per-stream host decode (BatchScanDecoder + ScanAssembler,
    # newest revolution per stream) feeding the one batched sharded
    # filter dispatch per tick — the golden fleet path; "fused" = the
    # fleet-fused single-dispatch path (ops/ingest.fleet_fused_ingest_step
    # via driver/ingest.FleetFusedIngest: every stream's raw frame bytes
    # staged into ONE buffer, unpack + segmentation + per-stream filter
    # steps in ONE compiled vmapped program per tick — O(1) dispatches
    # and transfers per tick, independent of fleet size; bit-exact vs N
    # independent host paths, tests/test_fleet_fused_ingest.py).  "auto"
    # resolves per the standing decision procedure
    # (filters/chain.resolve_fleet_ingest_backend — host until an
    # on-chip artifact clears the bar; scripts/decide_backends.py flips
    # it from `fleet_ingest_ab` evidence).
    fleet_ingest_backend: str = "auto"
    # T-tick super-step lowering (ops/ingest.super_fleet_ingest_step via
    # driver/ingest.FleetFusedIngest): when a backlog of fleet ticks is
    # queued (link stall, slow consumer — submit_backlog /
    # ShardedFilterService.submit_bytes_backlog) or one tick splits
    # across bucket slices, up to this many ticks drain in ONE compiled
    # dispatch instead of T (lax.scan over the fleet tick, carries as
    # donated scan state — bit-exact vs T sequential ticks,
    # tests/test_super_tick.py).  1 disables the lowering (per-tick
    # dispatches only); each (T, bucket) pair costs one extra program
    # compile, warmed by FleetFusedIngest.precompile.
    super_tick_max: int = 1
    # persistent XLA compilation cache (utils/backend.
    # enable_compilation_cache): a directory path enables it (the fused
    # ingest programs cost seconds of compile per bucket x format set,
    # paid on every restart; the cache turns warm restarts into disk
    # loads — bench records cold-vs-warm startup in its meta).  None/""
    # disables (default: process-lifetime jit cache only).
    compilation_cache_dir: str | None = None
    # -- SLAM front-end (mapping/mapper.FleetMapper + ops/scan_match) --
    # enable the per-stream log-odds mapper + correlative scan matcher:
    # each revolution's chain output is matched against a persistent
    # occupancy map and the estimated pose published alongside the scan.
    # Requires filter_chain stages (the mapper consumes the chain's
    # Cartesian endpoint output).
    map_enable: bool = False
    # mapper backend seam: "host" = the NumPy golden reference (one
    # per-stream step on the host — the bit-exact oracle); "fused" = the
    # device path (N streams match N maps in ONE compiled vmapped
    # dispatch per fleet tick, ops/scan_match.fleet_map_match_step —
    # bit-exact vs N host steps, tests/test_mapping.py); "auto" resolves
    # per the standing decision procedure (mapping/mapper.
    # resolve_map_backend — host until an on-chip config-12 artifact
    # clears the bar; scripts/decide_backends.py reads `mapping_ab`).
    map_backend: str = "auto"
    # correlative-matcher kernel lowering (MapConfig.match_backend):
    # "xla" = the jnp score-volume + log-odds-update arm in
    # ops/scan_match.py; "pallas" = the VMEM-tiled Pallas kernels
    # (ops/pallas_scan_match.py — match map resident in VMEM across the
    # whole (dθ,dx,dy) candidate grid, scatter-free one-hot/matmul
    # log-odds update; interpret mode off-TPU so CPU configs stay
    # runnable).  Bit-exact either way (the int32 datapath makes
    # evaluation order irrelevant; tests/test_pallas_scan_match.py).
    # "auto" resolves per the standing decision procedure
    # (mapping/mapper.resolve_match_backend — xla until an on-chip
    # config-14 artifact clears the bar; scripts/decide_backends.py
    # reads `pallas_match_ab`, TPU records only, interpret-mode runs
    # carry no weight).
    match_backend: str = "auto"
    # fused mapping route (PR 13 "one dispatch for the whole stack"):
    # "fused" threads the per-stream MapState through the fused ingest
    # carry (ops/ingest cfg.mapping) so bytes -> decode -> de-skewed
    # sweep -> pose -> map update is ONE compiled program per
    # (super-)tick per shard — T ticks of ingest+mapping collapse from
    # T+T dispatches to 1; "host" keeps the two-dispatch golden
    # reference (the ingest dispatch plus a separate FleetMapper
    # dispatch fed from take_recon()); "auto" resolves per the standing
    # decision procedure (mapping/mapper.resolve_fused_mapping_backend
    # — host until an on-chip config-18 artifact clears the bar;
    # scripts/decide_backends.py reads `fused_mapping_ab`, TPU records
    # only).  Requires map_enable + deskew_enable + the fused fleet
    # ingest seam (the in-program mapper consumes the reconstructed
    # sweep; both routes are byte-identical — tests/test_fused_mapping
    # pins trajectories, wires and final MapState across T x fleet x
    # matcher-backend arms).
    fused_mapping_backend: str = "auto"
    map_grid: int = 256               # cells per side of the log-odds map
    map_cell_m: float = 0.05          # metres per map cell
    map_match_window: float = 0.4     # translation search radius (m)
    # log-odds parameters (probability units; quantized to Q10 fixed
    # point once, in mapping/mapper.map_config_from_params)
    map_log_odds_hit: float = 0.9     # increment per endpoint hit
    map_log_odds_miss: float = -0.4   # decrement per free-space pass
    map_log_odds_clamp: float = 8.0   # saturation bound (±)
    # per-revolution decay of every cell toward zero (dynamic scenes:
    # stale moving-obstacle evidence fades even when no ray revisits
    # it).  0.0 disables — and the gate is static, so the default traces
    # the byte-identical mapping program the pre-decay tree compiled
    map_decay: float = 0.0
    # -- SLAM back-end: loop closure + pose graph (slam/loop.
    # LoopClosureEngine + ops/loop_close.py + ops/pose_graph.py) --
    # attach the loop-closure engine beside the mapper: every
    # loop_submap_revs revolutions the stream's MapState finalizes into
    # a quantized submap (library capped at loop_max_submaps,
    # cap-and-hold); every loop_check_revs revolutions the current scan
    # is matched against the loop_candidates nearest submaps in ONE
    # dispatch (candidate scoring reuses the correlative matcher's
    # score-volume engines, so match_backend routes it through the XLA
    # or Pallas kernels), and accepted closures feed the fixed-point
    # pose-graph relaxation whose corrected pose is republished.
    # Requires map_enable (the back-end closes the front-end's loop).
    loop_enable: bool = False
    # loop backend seam: "host" = the NumPy golden reference
    # (ops/loop_close_ref.py — the bit-exact oracle); "fused" = the
    # device path (N streams check N submap libraries in ONE compiled
    # vmapped dispatch, ops/loop_close.fleet_loop_close_step); "auto"
    # resolves per the standing decision procedure (slam/loop.
    # resolve_loop_backend — host until an on-chip config-17 artifact
    # clears the bar; scripts/decide_backends.py reads `loop_close_ab`).
    loop_backend: str = "auto"
    loop_submap_revs: int = 8         # revolutions between finalizations
    loop_max_submaps: int = 8         # submap library capacity (= graph nodes)
    loop_check_revs: int = 4          # revolutions between closure checks
    loop_candidates: int = 2          # nearest submaps scored per check
    loop_window_cells: int = 4        # candidate coarse search radius (coarse cells)
    loop_theta_window: int = 8        # candidate search: ± rotation-table steps
    loop_min_points: int = 32         # overlap gate: valid endpoints required
    # absolute acceptance gate as a right shift of the per-point score
    # CEILING (clamp_q-after-quantization x the bilinear weight sum):
    # accept needs a mean per-point score above ceiling >> shift — 3 =
    # 1/8 of a perfectly saturated perfectly aligned match.  A shift
    # keeps the bar geometry-independent (the raw score scale moves
    # with quant_shift, which min_quant_shift derives from beams).
    loop_accept_shift: int = 3
    loop_peak_shift: int = 3          # contrast gate: peak-floor >= best>>s
    loop_weight: int = 4              # loop-constraint weight (odometry = 1)
    # rewrite the submap anchors AND the front-end pose to the corrected
    # solution on an accepted closure (map re-anchoring): subsequent
    # revolutions rasterize in the corrected frame.  Off by default —
    # corrected poses are republished either way; re-anchoring
    # additionally mutates the front-end trajectory.
    loop_reanchor: bool = False
    # fixed relaxation sweeps per solve (compile-time constant: the
    # solver is a lax.fori_loop, one program per graph bucket)
    pose_graph_iters: int = 96
    # loop-constraint plane capacity (dense padded; the solver plane is
    # loop_max_submaps odometry rows + this many loop rows)
    pose_graph_max_constraints: int = 16
    # -- shared-world mapping plane (mapping/worldmap.WorldMap +
    # mapping/tiles.py + ops/tile_quant.py) --
    # attach the fleet-wide world map: finalized per-stream submaps are
    # aligned against a fixed reference (the matcher's bit-exact host
    # twin, loop-closure search radii), fused into ONE device-resident
    # int32 accumulation (associative addition — any merge order is
    # byte-identical; eviction subtracts exactly), and served as
    # versioned quantized run-length tile snapshots published on the
    # idle staging half (a map read adds zero dispatches to a drain).
    # Requires map_enable (the world is made of the mapper's submaps).
    world_map_enable: bool = False
    # tile serving backend seam: "raw" = dense int32 tiles (lossless —
    # the A/B baseline arm); "int8"/"int4" = SR-LIO++-style quantized
    # levels + run-length coding (mapping/tiles.resolve_map_tile_backend
    # — bounded band-midpoint error, tests pin it); "auto" = int8
    # (capacity feature with a validated error bound, so auto does not
    # wait on on-chip evidence; the `map_serving_ab` decide_backends
    # key governs only the serving-latency claim, TPU records only).
    map_tile_backend: str = "auto"
    world_tile_cells: int = 8         # tile edge (cells; must divide map_grid)
    world_max_submaps: int = 16       # world membership cap (= graph nodes)
    world_merge_revs: int = 4         # revolutions between cross-stream merges
    world_publish_ticks: int = 8      # drain ticks between tile publications
    # -- de-skew + sweep reconstruction (ops/deskew.py, fused ingest) --
    # per-revolution range-only de-skew + caching-aware sweep
    # reconstruction INSIDE the fused ingest core
    # (ops/ingest._segment_filter_core — rides the single-stream,
    # fleet-vmapped and super-tick lowerings with zero extra
    # dispatches): the per-revolution rigid motion is estimated from
    # consecutive revolutions' range profiles (no IMU — the wire
    # carries none) and every beam re-projected to the revolution's end
    # pose by its phase fraction, int32 end to end so the NumPy host
    # twin (ops/deskew_ref.py) stays bit-exact; each tick's nodes also
    # land in a device-resident ring of the last K sub-sweep segments
    # whose newest-wins overlay is emitted EVERY tick as a
    # reconstructed sweep — the mapper seam consumes it for R >= 2
    # matcher/mapper updates per physical revolution at the same
    # dispatch count (bench --config 16; scripts/decide_backends.py
    # `deskew_ab` key gates the default flip on on-chip evidence).
    # Requires a fused ingest seam (the host service path has no
    # per-tick device residency to cache sub-sweeps in).
    deskew_enable: bool = False
    # K: sub-sweep segments cached per stream; the reconstruction
    # window (and the cache-expiry horizon — data older than K data
    # ticks ages out of the ring)
    sweep_reconstruct_window: int = 4
    # motion-profile beam grid (power of two in [64, 1024]) and the
    # ± dθ search radius in profile-beam steps
    deskew_profile_beams: int = 256
    deskew_shift_window: int = 8
    # de-skew kernel lowering (ops/deskew.DeskewConfig.backend): "xla"
    # = the jnp dense tiled-min / shift-search arms; "pallas" = the
    # VMEM-tiled kernels (ops/pallas_deskew.py — the sub-sweep
    # rasterizer's beam-min and the profile shift search, the two
    # intra-program hot loops the PR 13 fusion exposes; interpret mode
    # off-TPU so CPU configs stay runnable).  Bit-exact either way
    # (int32 min/sum are evaluation-order independent;
    # tests/test_pallas_deskew.py).  "auto" resolves per the standing
    # decision procedure (ops/deskew.resolve_deskew_backend — xla
    # until on-chip evidence; CPU interpret-mode runs carry no weight).
    deskew_backend: str = "auto"
    # -- fleet fault tolerance (driver/health.py + parallel/service.py) --
    # attach the per-stream health FSM supervisor to the fleet byte-tick
    # seams (ShardedFilterService.submit_bytes*): HEALTHY -> SUSPECT ->
    # QUARANTINED -> RECOVERING per stream, driven by corrupt-frame
    # ratio and tick-starvation age.  Quarantined streams are masked
    # onto the existing idle padding lanes (same compiled program, zero
    # recompiles — graftlint/guards enforced), their filter+map state
    # checkpointed at quarantine and restored at rejoin.  Off by
    # default: single-node deployments already have the scan-loop FSM.
    health_enable: bool = False
    health_window_ticks: int = 8      # sliding observation window (ticks)
    health_corrupt_ratio: float = 0.5  # malformed/total over window -> bad
    health_starvation_ticks: int = 16  # ticks w/o a revolution -> bad
    health_suspect_ticks: int = 4     # consecutive bad ticks -> quarantine
    health_probation_ticks: int = 4   # consecutive clean ticks -> healthy
    # capped exponential backoff on quarantine release / reconnect
    # probing: min(base * 2**attempt, max) * (1 + jitter * u)
    health_backoff_base_s: float = 0.5
    health_backoff_max_s: float = 30.0
    health_backoff_jitter: float = 0.1
    # -- elastic fleet / shard failover (parallel/service.
    # ElasticFleetService + driver/health.ShardHealth) --
    # number of shards in the fleet-of-fleets pod: each shard is one
    # fused engine pair (FleetFusedIngest + FleetMapper) hosting
    # `shard_lanes` stream lanes; streams are placed onto shards by
    # parallel/sharding.FleetTopology and migrate between them with
    # zero recompiles (membership changes relabel lanes, never shapes).
    # 1 = single-shard (no failover headroom — nowhere to evacuate to).
    shard_count: int = 1
    # stream lanes compiled per shard: 0 = auto, the smallest count
    # that survives one full shard loss ((shards-1)*lanes >= streams).
    # The idle lanes are the evacuation headroom AND the padding lanes
    # quarantined streams already ride.
    shard_lanes: int = 0
    # shard health FSM thresholds (UP -> SUSPECT -> LOST ->
    # READMITTING): fleet-wide tick starvation walks a shard to LOST;
    # a raised dispatch or a chaos kill is LOST immediately.
    shard_starvation_ticks: int = 8   # all-lane dry ticks -> bad
    shard_suspect_ticks: int = 4      # consecutive bad ticks -> LOST
    shard_probation_ticks: int = 4    # productive readmitting ticks -> UP
    # capped exponential backoff + probe gate on shard re-admission
    shard_backoff_base_s: float = 1.0
    shard_backoff_max_s: float = 60.0
    shard_backoff_jitter: float = 0.1
    # cadence of the per-stream snapshot pulls that feed the evacuation
    # store (row-sized gather + host fetch per stream, every N ticks):
    # on shard loss, each victim restores from its LAST pulled snapshot
    # — ticks since it are lost, so the cadence bounds the loss window.
    # 0 disables pulls (victims restore as fresh streams).
    failover_snapshot_ticks: int = 8
    # -- traffic-shaped elastic serving (parallel/scheduler.py) --
    # precompiled super-tick RUNG ladder for backlog drains: every
    # listed depth T gets its own pre-warmed (T, bucket) executable at
    # FleetFusedIngest.precompile, and the scheduler picks the rung per
    # drain from measured backlog depth — a burst is swallowed in one
    # deep dispatch, steady traffic stays on the low-latency shallow
    # rungs, and a mid-run rung switch is a compile-cache hit by
    # construction (zero recompiles, guards-pinned).  Must start at 1
    # (the per-tick program is the floor the scheduler can always fall
    # to) and ascend; each extra rung costs one compile per bucket at
    # warmup.  The ladder is inert until a TrafficShaper is attached
    # (ShardedFilterService.attach_scheduler / ElasticFleetService).
    sched_rungs: tuple = (1, 2, 4, 8)
    # consecutive drains at or below a LOWER rung's depth before the
    # scheduler steps down one rung (stepping UP is immediate — a burst
    # must be swallowed now, but easing back waits out the echo so a
    # sawtooth backlog doesn't thrash the rung choice)
    sched_hysteresis_ticks: int = 2
    # per-shard drain deadline budget (ms): the rung choice is capped
    # so the PREDICTED drain wall time (EWMA per-tick drain cost x
    # rung depth) stays inside the budget — the SLO feeding the rung
    # choice.  0 disables the cap (backlog depth alone picks the rung).
    sched_deadline_ms: float = 0.0
    # EWMA weight for the per-stream byte-rate estimate that feeds
    # byte-rate-weighted placement (FleetTopology weights) and the
    # /diagnostics scheduler group
    sched_byte_rate_alpha: float = 0.2
    # per-stream admission bound: a stream's queued backlog never
    # exceeds this many ticks — beyond it the OLDEST queued tick is
    # shed (counted per stream, surfaced on /diagnostics), never
    # unbounded growth.  The SLO-aware admission policy's hard edge.
    admission_max_backlog_ticks: int = 32
    # -- link-latency hiding (PR 16) --
    # double-buffered async H2D staging: within a multi-group drain the
    # NEXT group's staging planes are filled and device_put while the
    # previous group's compute is in flight, so the host->device link
    # transfer of drain t+1 hides under the compute of drain t (d2h
    # already overlaps via async dispatch).  Staging order is
    # unchanged — byte-equal trajectories by construction; off
    # reproduces the serialized stage->compute order exactly (the
    # bench --config 20 A/B arm).
    staging_double_buffer: bool = True
    # adaptive padding-bucket LADDER for the frame-run bucket M: every
    # listed bucket is pre-warmed per rung at precompile (one compiled
    # program per (rung, bucket)), and the scheduler's live-lane
    # occupancy EWMA picks the ACTIVE slicing cap with hysteresis —
    # occupancy collapse (many idle/quarantined lanes) drops dispatches
    # to a cheaper executable with zero recompiles; recovery steps back
    # up.  Must be strictly ascending when set; empty disables the
    # ladder (the static largest-bucket cap — pre-PR 16 behavior).
    # Inert until a TrafficShaper is attached.
    bucket_rungs: tuple = ()
    # EWMA weight of the live-lane occupancy estimate feeding the
    # bucket ladder (deliberately separate from sched_byte_rate_alpha:
    # retuning placement responsiveness must not silently retune the
    # bucket choice, or vice versa)
    occupancy_alpha: float = 0.2
    # -- pod of pods (PR 17): two-level placement, stealing, autoscale --
    # number of HOSTS the pod's shards split across (two-level
    # (host, shard, lane) coordinates): shards partition into
    # contiguous equal blocks, one host-local StagingPool per block,
    # and placement/evacuation/rebalance prefer same-host moves.
    # Must divide shard_count; 1 = the single-level pod (byte-
    # identical placement to pre-PR-17).
    pod_hosts: int = 1
    # cross-shard work stealing: when a shard's queued backlog depth
    # exceeds this many ticks and a sibling has idle lanes plus
    # deadline headroom, the sibling drains whole per-stream QUEUES
    # borrowed for that drain only (row snapshot -> restore onto the
    # taker's idle lane, decode carries intact, copied home after —
    # placement never moves).  Byte-equal to the no-steal schedule by
    # construction: admission and tick order are untouched, the policy
    # picks WHERE, never what.  0 disables stealing.
    steal_threshold_ticks: int = 0
    # reserve (ms) subtracted from sched_deadline_ms when pricing a
    # prospective taker's post-steal drain with the measured latency
    # model — the taker must finish the borrowed depth inside
    # (deadline - headroom).  With sched_deadline_ms=0 this is the
    # absolute budget; 0 disables the time gate (idle lanes alone
    # gate the steal).  Must stay below sched_deadline_ms when both
    # are set.
    steal_headroom_ms: float = 0.0
    # byte-rate autoscale seam: sustained fleet-wide thin occupancy
    # (live streams per active lane below the low watermark for
    # autoscale_hysteresis_ticks straight) gracefully drains one shard
    # out of the pod (live row moves, engine released); sustained
    # pressure above the high watermark re-admits one.  Hysteresis
    # mirrors the rung/bucket ladders: the watermark gap is the dead
    # zone a sawtooth cannot thrash across, and every scale event is
    # recompile-free (surviving shards' (rung, bucket) programs are
    # already warmed).  Scheduled seam only.
    autoscale_enable: bool = False
    autoscale_low_watermark: float = 0.25
    autoscale_high_watermark: float = 0.75
    autoscale_hysteresis_ticks: int = 8
    # the pod never scales below this many active shards
    autoscale_min_shards: int = 1
    # byte-rate EWMA floor (bytes/tick) above which a stream counts as
    # LIVE for occupancy: the EWMA decays toward zero but never
    # reaches it, so a zero floor would count every stream ever seen
    # as live forever
    autoscale_rate_floor: float = 256.0
    # pipelined publish seam: publish revolution N-1's chain output while
    # revolution N computes on the device (one revolution of bounded
    # staleness; the publish never waits on device compute).  Off by
    # default — the reference publishes synchronously.
    pipelined_publish: bool = False
    # bound on the pipelined collect's device->host fetch, mirroring the
    # reference's bounded grab (every wait in its SDK carries a timeout,
    # 2000 ms default — sl_lidar_driver.h:332).  A wedged remote-attach
    # link can otherwise block the publish path indefinitely (observed
    # >30 min on this rig).  On expiry the revolution is re-stashed and
    # the fault surfaces to the FSM like any transient device error.
    # 0/None = unbounded (a locally-attached chip's D2H is microseconds).
    collect_timeout_s: float | None = None

    def validate(self) -> None:
        if self.qos_reliability not in VALID_QOS:
            raise ValueError(f"qos_reliability must be one of {VALID_QOS}")
        if self.serial_baudrate <= 0:
            raise ValueError("serial_baudrate must be positive")
        if not (0 < self.tcp_port <= 0xFFFF) or not (0 < self.udp_port <= 0xFFFF):
            raise ValueError("tcp_port/udp_port must be within [1, 65535]")
        if self.max_distance < 0:
            raise ValueError("max_distance must be >= 0 (0 = hardware limit)")
        if not (0 <= self.range_clip_min_m < self.range_clip_max_m):
            raise ValueError(
                "range clip must satisfy 0 <= range_clip_min_m < "
                "range_clip_max_m"
            )
        if self.intensity_min < 0:
            raise ValueError("intensity_min must be >= 0")
        if self.filter_backend not in VALID_BACKENDS:
            raise ValueError(f"filter_backend must be one of {VALID_BACKENDS}")
        if self.channel_type not in VALID_CHANNELS:
            raise ValueError(f"channel_type must be one of {VALID_CHANNELS}")
        if not (0 <= self.rpm <= 1200):
            # same bound the dynamic-param path enforces (src/rplidar_node.cpp:713)
            raise ValueError("rpm must be within [0, 1200]")
        if self.max_retries < 0:
            raise ValueError("max_retries must be >= 0")
        if self.filter_window < 1:
            raise ValueError("filter_window must be >= 1")
        bad = set(self.filter_chain) - set(VALID_FILTER_STAGES)
        if bad:
            raise ValueError(
                f"unknown filter_chain stages {sorted(bad)}; valid: {VALID_FILTER_STAGES}"
            )
        if self.voxel_grid_size < 1 or self.voxel_cell_m <= 0:
            raise ValueError("invalid voxel grid configuration")
        if self.median_backend not in (
            "auto", "xla", "pallas", "inc", "inc_xla", "inc_pallas"
        ):
            raise ValueError(
                "median_backend must be 'auto', 'xla', 'pallas', 'inc', "
                "'inc_xla' or 'inc_pallas'"
            )
        if self.resample_backend not in ("auto", "scatter", "dense"):
            raise ValueError(
                "resample_backend must be 'auto', 'scatter' or 'dense'"
            )
        if self.voxel_backend not in ("auto", "scatter", "matmul"):
            raise ValueError(
                "voxel_backend must be 'auto', 'scatter' or 'matmul'"
            )
        if self.collect_timeout_s is not None and self.collect_timeout_s < 0:
            raise ValueError("collect_timeout_s must be >= 0 (or None)")
        if self.health_window_ticks < 1:
            raise ValueError("health_window_ticks must be >= 1")
        if not (0.0 < self.health_corrupt_ratio <= 1.0):
            raise ValueError("health_corrupt_ratio must be within (0, 1]")
        if self.health_starvation_ticks < 1:
            raise ValueError("health_starvation_ticks must be >= 1")
        if self.health_suspect_ticks < 1:
            raise ValueError("health_suspect_ticks must be >= 1")
        if self.health_probation_ticks < 1:
            raise ValueError("health_probation_ticks must be >= 1")
        if self.health_backoff_base_s <= 0:
            raise ValueError("health_backoff_base_s must be positive")
        if self.health_backoff_max_s < self.health_backoff_base_s:
            raise ValueError(
                "health_backoff_max_s must be >= health_backoff_base_s "
                "(the cap bounds the exponential, it cannot undercut it)"
            )
        if not (0.0 <= self.health_backoff_jitter <= 1.0):
            raise ValueError("health_backoff_jitter must be within [0, 1]")
        if self.shard_count < 1:
            raise ValueError("shard_count must be >= 1")
        if self.shard_lanes < 0:
            raise ValueError("shard_lanes must be >= 0 (0 = auto)")
        if self.shard_starvation_ticks < 1:
            raise ValueError("shard_starvation_ticks must be >= 1")
        if self.shard_suspect_ticks < 1:
            raise ValueError("shard_suspect_ticks must be >= 1")
        if self.shard_probation_ticks < 1:
            raise ValueError("shard_probation_ticks must be >= 1")
        if self.shard_backoff_base_s <= 0:
            raise ValueError("shard_backoff_base_s must be positive")
        if self.shard_backoff_max_s < self.shard_backoff_base_s:
            raise ValueError(
                "shard_backoff_max_s must be >= shard_backoff_base_s "
                "(the cap bounds the exponential, it cannot undercut it)"
            )
        if not (0.0 <= self.shard_backoff_jitter <= 1.0):
            raise ValueError("shard_backoff_jitter must be within [0, 1]")
        if self.failover_snapshot_ticks < 0:
            raise ValueError(
                "failover_snapshot_ticks must be >= 0 (0 disables the "
                "periodic snapshot pulls)"
            )
        if self.ingest_backend not in ("auto", "host", "fused"):
            raise ValueError(
                "ingest_backend must be 'auto', 'host' or 'fused'"
            )
        if self.ingest_backend == "fused" and not self.filter_chain:
            raise ValueError(
                "ingest_backend='fused' requires filter_chain stages (the "
                "fused program ends in the filter step; raw passthrough "
                "has no device-side consumer)"
            )
        if self.fleet_ingest_backend not in ("auto", "host", "fused"):
            raise ValueError(
                "fleet_ingest_backend must be 'auto', 'host' or 'fused'"
            )
        if self.fleet_ingest_backend == "fused" and not self.filter_chain:
            raise ValueError(
                "fleet_ingest_backend='fused' requires filter_chain stages "
                "(the fleet-fused program ends in the per-stream filter "
                "steps; raw passthrough has no device-side consumer)"
            )
        if self.super_tick_max < 1:
            raise ValueError("super_tick_max must be >= 1 (1 disables)")
        if not (2 <= self.sweep_reconstruct_window <= 64):
            raise ValueError(
                "sweep_reconstruct_window must be within [2, 64] (a "
                "1-deep ring cannot reconstruct across ticks)"
            )
        d = self.deskew_profile_beams
        if d < 64 or d > 1024 or d & (d - 1):
            raise ValueError(
                "deskew_profile_beams must be a power of two in [64, 1024]"
            )
        if not (1 <= self.deskew_shift_window <= d // 8):
            raise ValueError(
                "deskew_shift_window must be within [1, "
                "deskew_profile_beams/8]"
            )
        if self.deskew_enable:
            if not self.filter_chain:
                raise ValueError(
                    "deskew_enable requires filter_chain stages (the "
                    "de-skewed revolutions feed the fused filter step)"
                )
            if "fused" not in (
                self.ingest_backend, self.fleet_ingest_backend
            ):
                raise ValueError(
                    "deskew_enable requires a fused ingest seam "
                    "(ingest_backend='fused' or fleet_ingest_backend="
                    "'fused'): the sub-sweep cache lives inside the "
                    "fused program's device state — the host decode "
                    "path has nowhere to keep it"
                )
        if self.deskew_backend not in ("auto", "xla", "pallas"):
            raise ValueError(
                "deskew_backend must be 'auto', 'xla' or 'pallas'"
            )
        if self.map_backend not in ("auto", "host", "fused"):
            raise ValueError(
                "map_backend must be 'auto', 'host' or 'fused'"
            )
        if self.fused_mapping_backend not in ("auto", "host", "fused"):
            raise ValueError(
                "fused_mapping_backend must be 'auto', 'host' or 'fused'"
            )
        if self.fused_mapping_backend == "fused":
            if not self.map_enable:
                raise ValueError(
                    "fused_mapping_backend='fused' requires map_enable "
                    "(there is no map to thread through the carry)"
                )
            if not self.deskew_enable:
                raise ValueError(
                    "fused_mapping_backend='fused' requires deskew_enable "
                    "(the in-program mapper consumes the reconstructed "
                    "sweep the de-skew stage emits every tick)"
                )
            if self.fleet_ingest_backend != "fused":
                # the map rides the FLEET engine's carry: the
                # single-stream fused seam satisfies the deskew check
                # above but never builds cfg.mapping, so an 'auto' (or
                # host) fleet seam here would silently run with no
                # in-program map anywhere
                raise ValueError(
                    "fused_mapping_backend='fused' requires "
                    "fleet_ingest_backend='fused' (spelled, not 'auto' "
                    "— the MapState rides the fleet ingest carry, and "
                    "only that engine builds the in-program mapper)"
                )
        if self.match_backend not in ("auto", "xla", "pallas"):
            raise ValueError(
                "match_backend must be 'auto', 'xla' or 'pallas'"
            )
        if self.map_enable and not self.filter_chain:
            raise ValueError(
                "map_enable requires filter_chain stages (the mapper "
                "consumes the chain's Cartesian endpoint output)"
            )
        if not (8 <= self.map_grid <= 1024) or self.map_grid % 4:
            raise ValueError(
                "map_grid must be within [8, 1024] and divide by 4 "
                "(the matcher's coarse pyramid factor)"
            )
        if self.map_cell_m <= 0:
            raise ValueError("map_cell_m must be positive")
        if self.map_match_window <= 0:
            raise ValueError("map_match_window must be positive")
        if self.map_log_odds_hit <= 0:
            raise ValueError("map_log_odds_hit must be positive")
        if self.map_log_odds_miss >= 0:
            raise ValueError("map_log_odds_miss must be negative")
        if self.map_log_odds_clamp < self.map_log_odds_hit:
            raise ValueError(
                "map_log_odds_clamp must be >= map_log_odds_hit (a clamp "
                "below one hit increment can never mark a cell occupied)"
            )
        if self.map_decay < 0 or self.map_decay > self.map_log_odds_clamp:
            raise ValueError(
                "map_decay must be within [0, map_log_odds_clamp] "
                "(0 disables; decaying past the clamp is meaningless)"
            )
        if self.loop_backend not in ("auto", "host", "fused"):
            raise ValueError(
                "loop_backend must be 'auto', 'host' or 'fused'"
            )
        if self.loop_enable and not self.map_enable:
            raise ValueError(
                "loop_enable requires map_enable (the loop-closure "
                "back-end closes the front-end mapper's loop — there is "
                "no trajectory to correct without it)"
            )
        if self.loop_submap_revs < 1:
            raise ValueError("loop_submap_revs must be >= 1")
        if not (2 <= self.loop_max_submaps <= 64):
            raise ValueError(
                "loop_max_submaps must be within [2, 64] (one submap to "
                "close against, one to close from; the cap sizes the "
                "pose graph)"
            )
        if self.loop_check_revs < 1:
            raise ValueError("loop_check_revs must be >= 1")
        if not (1 <= self.loop_candidates <= self.loop_max_submaps):
            raise ValueError(
                "loop_candidates must be within [1, loop_max_submaps]"
            )
        if self.loop_window_cells < 1:
            raise ValueError("loop_window_cells must be >= 1")
        if self.loop_theta_window < 1:
            raise ValueError("loop_theta_window must be >= 1")
        if self.loop_min_points < 1:
            raise ValueError("loop_min_points must be >= 1")
        if not (0 <= self.loop_accept_shift <= 20):
            raise ValueError("loop_accept_shift must be within [0, 20]")
        if not (0 <= self.loop_peak_shift <= 30):
            raise ValueError("loop_peak_shift must be within [0, 30]")
        if not (1 <= self.loop_weight <= 16):
            raise ValueError(
                "loop_weight must be within [1, 16] (the pose-graph "
                "weight clamp — and the int32 accumulator bound)"
            )
        if self.pose_graph_iters < 1:
            raise ValueError("pose_graph_iters must be >= 1")
        if self.map_tile_backend not in ("auto", "raw", "int8", "int4"):
            raise ValueError(
                "map_tile_backend must be 'auto', 'raw', 'int8' or "
                "'int4'"
            )
        if self.world_map_enable and not self.map_enable:
            raise ValueError(
                "world_map_enable requires map_enable (the shared "
                "world is fused from the mapper's finalized submaps)"
            )
        if self.world_tile_cells < 1:
            raise ValueError("world_tile_cells must be >= 1")
        if self.map_grid % self.world_tile_cells != 0:
            raise ValueError(
                "world_tile_cells must divide map_grid (partial edge "
                "tiles would give one cell two serving addresses)"
            )
        if not (2 <= self.world_max_submaps <= 64):
            raise ValueError(
                "world_max_submaps must be within [2, 64] (a reference "
                "plus at least one member; the cap sizes the "
                "inter-stream pose graph)"
            )
        if self.world_merge_revs < 1:
            raise ValueError("world_merge_revs must be >= 1")
        if self.world_publish_ticks < 1:
            raise ValueError("world_publish_ticks must be >= 1")
        rungs = tuple(self.sched_rungs)
        if not rungs or any(
            not isinstance(r, int) or isinstance(r, bool) for r in rungs
        ):
            raise ValueError("sched_rungs must be a non-empty tuple of ints")
        if rungs[0] != 1:
            raise ValueError(
                "sched_rungs must start at 1 (the per-tick program is "
                "the floor the scheduler can always fall to)"
            )
        if any(b <= a for a, b in zip(rungs, rungs[1:])):
            raise ValueError("sched_rungs must be strictly ascending")
        if rungs[-1] > 64:
            raise ValueError(
                "sched_rungs depths must be <= 64 (every rung is one "
                "more compiled super-step program per padding bucket)"
            )
        if self.sched_hysteresis_ticks < 1:
            raise ValueError("sched_hysteresis_ticks must be >= 1")
        if self.sched_deadline_ms < 0:
            raise ValueError(
                "sched_deadline_ms must be >= 0 (0 disables the "
                "deadline cap on the rung choice)"
            )
        if not (0.0 < self.sched_byte_rate_alpha <= 1.0):
            raise ValueError(
                "sched_byte_rate_alpha must be within (0, 1]"
            )
        if self.admission_max_backlog_ticks < 1:
            raise ValueError(
                "admission_max_backlog_ticks must be >= 1 (the per-"
                "stream backlog is BOUNDED by contract — unbounded "
                "growth is the failure mode this knob exists to forbid)"
            )
        if not isinstance(self.staging_double_buffer, bool):
            raise ValueError(
                "staging_double_buffer must be a bool (the ping/pong "
                "staging pair is on or off — there is no depth knob; "
                "two halves fully overlap one in-flight drain)"
            )
        buckets = tuple(self.bucket_rungs)
        if any(
            not isinstance(b, int) or isinstance(b, bool) for b in buckets
        ):
            raise ValueError("bucket_rungs must be a tuple of ints")
        if buckets:
            if min(buckets) < 1:
                raise ValueError("bucket_rungs buckets must be >= 1")
            if any(b <= a for a, b in zip(buckets, buckets[1:])):
                raise ValueError(
                    "bucket_rungs must be strictly ascending (the "
                    "bucket ladder steps between pre-warmed padding "
                    "buckets)"
                )
        if not (0.0 < self.occupancy_alpha <= 1.0):
            raise ValueError("occupancy_alpha must be within (0, 1]")
        if self.pod_hosts < 1:
            raise ValueError("pod_hosts must be >= 1")
        if self.shard_count % self.pod_hosts != 0:
            raise ValueError(
                f"pod_hosts must divide shard_count ({self.shard_count} "
                f"shards cannot split evenly across {self.pod_hosts} "
                "hosts — the two-level topology uses contiguous equal "
                "blocks)"
            )
        if self.steal_threshold_ticks < 0:
            raise ValueError(
                "steal_threshold_ticks must be >= 0 (0 disables "
                "work stealing)"
            )
        if self.steal_headroom_ms < 0:
            raise ValueError("steal_headroom_ms must be >= 0")
        if (
            self.sched_deadline_ms > 0
            and self.steal_headroom_ms >= self.sched_deadline_ms
        ):
            raise ValueError(
                "steal_headroom_ms must leave part of sched_deadline_ms "
                "as the taker's drain budget"
            )
        if not isinstance(self.autoscale_enable, bool):
            raise ValueError("autoscale_enable must be a bool")
        if not (
            0.0 < self.autoscale_low_watermark
            < self.autoscale_high_watermark <= 1.0
        ):
            raise ValueError(
                "autoscale watermarks must satisfy 0 < low < high <= 1 "
                "(the gap between them is the hysteresis dead zone)"
            )
        if self.autoscale_hysteresis_ticks < 1:
            raise ValueError("autoscale_hysteresis_ticks must be >= 1")
        if self.autoscale_min_shards < 1:
            raise ValueError("autoscale_min_shards must be >= 1")
        if self.autoscale_rate_floor <= 0:
            raise ValueError(
                "autoscale_rate_floor must be > 0 (the byte-rate EWMA "
                "decays toward zero but never reaches it, so a zero "
                "floor would count every stream ever seen as live "
                "forever)"
            )
        if not (1 <= self.pose_graph_max_constraints <= 256):
            raise ValueError(
                "pose_graph_max_constraints must be within [1, 256]"
            )

    @classmethod
    def from_dict(cls, d: dict[str, Any]) -> "DriverParams":
        known = {f.name for f in dataclasses.fields(cls)}
        unknown = set(d) - known
        if unknown:
            raise KeyError(f"unknown parameters: {sorted(unknown)}")
        p = cls(**{k: v for k, v in d.items() if k in known})
        if isinstance(p.filter_chain, list):
            p.filter_chain = tuple(p.filter_chain)
        if isinstance(p.sched_rungs, list):
            p.sched_rungs = tuple(p.sched_rungs)
        if isinstance(p.bucket_rungs, list):
            p.bucket_rungs = tuple(p.bucket_rungs)
        p.validate()
        return p

    @classmethod
    def from_yaml(cls, path: str) -> "DriverParams":
        """Load a ROS-style YAML (node -> ros__parameters -> dict)."""
        import yaml  # baked into the image via other deps

        with open(path) as f:
            doc = yaml.safe_load(f)
        # unwrap ros2 param file nesting if present
        if isinstance(doc, dict) and len(doc) == 1:
            (inner,) = doc.values()
            if isinstance(inner, dict) and "ros__parameters" in inner:
                doc = inner["ros__parameters"]
        return cls.from_dict(doc or {})
