from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.results import DeviceHealth, Result, is_fail, is_ok
from rplidar_ros2_driver_tpu.core.types import MAX_SCAN_NODES, LaserScanMsg, ScanBatch

__all__ = [
    "DeviceHealth",
    "DriverParams",
    "LaserScanMsg",
    "MAX_SCAN_NODES",
    "Result",
    "ScanBatch",
    "is_fail",
    "is_ok",
]
