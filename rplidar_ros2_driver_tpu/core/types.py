"""Core array data model for the TPU-native RPLIDAR framework.

The reference driver moves scans around as
``std::vector<sl_lidar_response_measurement_node_hq_t>`` — an
array-of-structs of ``{angle_z_q14:u16, dist_mm_q2:u32, quality:u8, flag:u8}``
(reference: src/sdk/include/sl_lidar_cmd.h:272-278).  On TPU the same
information lives as a struct-of-arrays with a *fixed padded shape* so that
every downstream kernel compiles once: variable point counts (the reference's
``count`` out-parameter, src/sdk/include/sl_lidar_driver.h:427-435) become a
``count`` scalar plus a validity mask.

All fields are int32: TPU vector units have no efficient u8/u16 lanes, and
the fixed-point unpack arithmetic (ops/unpack_*.py) needs 32-bit integer
semantics anyway.
"""

from __future__ import annotations

import dataclasses
from typing import Any

import jax
import jax.numpy as jnp
import numpy as np

# The reference caps a complete scan at 8192 nodes
# (src/sdk/src/sl_lidar_driver.cpp:378, src/lidar_driver_wrapper.cpp:316).
# We keep the same cap as the padded static width of every scan array.
MAX_SCAN_NODES = 8192

# HQ node flag bits (sl_lidar_cmd.h:175-181).
FLAG_SYNCBIT = 0x1

# Angle is Q14 "Z-angle": 16384 units == 90 degrees => 65536 == 360 degrees.
ANGLE_Q14_FULL_TURN = 1 << 16
# Distance is millimetres in Q2 (quarter-millimetre resolution).
DIST_Q2_PER_METER = 4000.0  # dist_mm_q2 / 4000 == metres (src/rplidar_node.cpp:588)


def _field(**kw: Any) -> Any:
    return dataclasses.field(**kw)


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class ScanBatch:
    """A fixed-width batch of measurement nodes (one complete revolution).

    Mirrors the information content of the reference's HQ node vector plus
    its ``count``.  Shapes: all arrays are ``(..., MAX_SCAN_NODES)``; leading
    batch dims are allowed (vmap/shard friendly).

    ``valid`` is the padding mask; ``count`` is the number of valid nodes
    (== valid.sum() along the node axis when constructed correctly).
    """

    angle_q14: jax.Array  # int32, 0..65535 (Q14 z-angle; 65536 == 360 deg)
    dist_q2: jax.Array    # int32, quarter-mm; 0 == invalid measurement
    quality: jax.Array    # int32, 0..255
    flag: jax.Array       # int32, bit0 = scan-start sync
    valid: jax.Array      # bool padding mask
    count: jax.Array      # int32 scalar (or batch of scalars)

    @property
    def num_nodes(self) -> int:
        return self.angle_q14.shape[-1]

    @staticmethod
    def empty(n: int = MAX_SCAN_NODES, batch: tuple = ()) -> "ScanBatch":
        shape = batch + (n,)
        z = jnp.zeros(shape, jnp.int32)
        return ScanBatch(
            angle_q14=z,
            dist_q2=z,
            quality=z,
            flag=z,
            valid=jnp.zeros(shape, bool),
            count=jnp.zeros(batch, jnp.int32),
        )

    @staticmethod
    def from_numpy(
        angle_q14: np.ndarray,
        dist_q2: np.ndarray,
        quality: np.ndarray,
        flag: np.ndarray | None = None,
        n: int = MAX_SCAN_NODES,
    ) -> "ScanBatch":
        """Pad host arrays of length ``count <= n`` into a ScanBatch."""
        count = int(angle_q14.shape[0])
        if count > n:
            raise ValueError(f"scan of {count} nodes exceeds capacity {n}")

        def pad(a: np.ndarray) -> jnp.ndarray:
            out = np.zeros((n,), np.int32)
            out[:count] = a.astype(np.int32)
            return jnp.asarray(out)

        if flag is None:
            flag = np.zeros((count,), np.int32)
        valid = np.zeros((n,), bool)
        valid[:count] = True
        return ScanBatch(
            angle_q14=pad(angle_q14),
            dist_q2=pad(dist_q2),
            quality=pad(quality),
            flag=pad(flag),
            valid=jnp.asarray(valid),
            count=jnp.asarray(count, jnp.int32),
        )

    def to_host(self) -> dict[str, np.ndarray]:
        """Device → host, trimmed to the valid prefix (assumes prefix layout)."""
        c = int(self.count)
        return {
            "angle_q14": np.asarray(self.angle_q14)[..., :c],
            "dist_q2": np.asarray(self.dist_q2)[..., :c],
            "quality": np.asarray(self.quality)[..., :c],
            "flag": np.asarray(self.flag)[..., :c],
        }


@jax.tree_util.register_dataclass
@dataclasses.dataclass(frozen=True)
class LaserScanMsg:
    """Array analog of ``sensor_msgs/LaserScan`` (fixed-width, padded).

    ``ranges``/``intensities`` are padded to a static width; ``beam_count``
    gives the number of live beams.  angle_min/angle_max/range_min/range_max/
    scan_time/time_increment/angle_increment are scalars, matching the
    message fields filled by the reference (src/rplidar_node.cpp:617-643).
    """

    ranges: jax.Array          # float32 (n,) padded with +inf
    intensities: jax.Array     # float32 (n,)
    beam_count: jax.Array      # int32 scalar
    angle_min: jax.Array       # float32 scalar
    angle_max: jax.Array       # float32 scalar
    angle_increment: jax.Array # float32 scalar
    time_increment: jax.Array  # float32 scalar
    scan_time: jax.Array       # float32 scalar
    range_min: jax.Array       # float32 scalar
    range_max: jax.Array       # float32 scalar
