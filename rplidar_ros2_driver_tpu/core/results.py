"""Result / status codes.

The reference threads ``u_result`` codes through every SDK call
(src/sdk/src/hal/types.h:83-130).  We keep an enum-shaped equivalent for the
host-side runtime; array kernels signal failure through masks instead.
"""

from __future__ import annotations

import enum


class Result(enum.IntEnum):
    OK = 0
    FAIL_BIT = 0x80000000
    ALREADY_DONE = 0x20
    INVALID_DATA = 0x8000 | 0x80000000
    OPERATION_FAIL = 0x8001 | 0x80000000
    OPERATION_TIMEOUT = 0x8002 | 0x80000000
    OPERATION_STOP = 0x8003 | 0x80000000
    OPERATION_NOT_SUPPORT = 0x8004 | 0x80000000
    FORMAT_NOT_SUPPORT = 0x8005 | 0x80000000
    INSUFFICIENT_MEMORY = 0x8006 | 0x80000000


def is_ok(res: int) -> bool:
    return (int(res) & 0x80000000) == 0


def is_fail(res: int) -> bool:
    return (int(res) & 0x80000000) != 0


class DeviceHealth(enum.IntEnum):
    """Health levels as the node sees them (src/lidar_driver_wrapper.cpp:390-405)."""

    OK = 0
    WARNING = 1
    ERROR = 2
