"""Fixed-point Q-format helpers shared by the unpack kernels.

The Slamtec wire formats speak in Q2/Q3/Q6/Q8/Q14/Q16 fixed point
(e.g. handler_capsules.cpp:206-266).  These helpers centralize the exact
int32 semantics so the JAX kernels and the numpy reference implementations
agree bit-for-bit with the C++ arithmetic.
"""

from __future__ import annotations

import jax.numpy as jnp

FULL_TURN_Q6 = 360 << 6
FULL_TURN_Q8 = 360 << 8
FULL_TURN_Q16 = 360 << 16


def angle_q6_to_q14(angle_q6):
    """(angle_q6 << 8) / 90 with C integer division semantics (non-negative)."""
    return (angle_q6 << 8) // 90


def wrap_angle_q6(angle_q6):
    """Wrap into [0, 360<<6) the way the handlers do (single add/sub)."""
    a = jnp.where(angle_q6 < 0, angle_q6 + FULL_TURN_Q6, angle_q6)
    return jnp.where(a >= FULL_TURN_Q6, a - FULL_TURN_Q6, a)


def diff_start_angle_q8(prev_q6: jnp.ndarray, cur_q6: jnp.ndarray) -> jnp.ndarray:
    """Angular distance between consecutive capsule start angles in Q8.

    Matches handler_capsules.cpp:210-217: mask the sync bit, promote Q6→Q8,
    and add a full turn when the angle wrapped.
    """
    cur_q8 = (cur_q6 & 0x7FFF) << 2
    prev_q8 = (prev_q6 & 0x7FFF) << 2
    diff = cur_q8 - prev_q8
    return jnp.where(prev_q8 > cur_q8, diff + FULL_TURN_Q8, diff)


def angle_q14_to_rad(angle_q14):
    """Q14 z-angle → radians (float32). 16384 == 90 deg."""
    deg = angle_q14.astype(jnp.float32) * (90.0 / 16384.0)
    return deg * (jnp.pi / 180.0)


def dist_q2_to_m(dist_q2):
    """Quarter-millimetres → metres (float32)."""
    return dist_q2.astype(jnp.float32) * (1.0 / 4000.0)
