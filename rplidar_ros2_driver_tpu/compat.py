"""Legacy compatibility shim.

The reference keeps a facade, ``rp::standalone::rplidar::RPlidarDriver``,
that forwards every call to the modern ``sl::ILidarDriver``
(src/sdk/src/rplidar_driver.cpp:47-199), plus alias headers mapping old
``RPLIDAR_*`` macro names onto ``SL_LIDAR_*`` values (rplidar_cmd.h:42-70,
rplidar_protocol.h, rptypes.h).  This module is the same seam for users
migrating old scripts: a camelCase ``RPlidarDriver`` facade over
:class:`~rplidar_ros2_driver_tpu.driver.interface.LidarDriverInterface`,
and the old constant names bound to the modern protocol enums.
"""

from __future__ import annotations

import warnings
from typing import Optional

from rplidar_ros2_driver_tpu.core.results import DeviceHealth
from rplidar_ros2_driver_tpu.core.types import ScanBatch
from rplidar_ros2_driver_tpu.driver.interface import LidarDriverInterface
from rplidar_ros2_driver_tpu.protocol import constants as c

# ---------------------------------------------------------------------------
# RPLIDAR_* aliases (rplidar_cmd.h:42-70, rplidar_protocol.h:44-52)
# ---------------------------------------------------------------------------

RPLIDAR_CMD_SYNC_BYTE = c.CMD_SYNC_BYTE
RPLIDAR_CMDFLAG_HAS_PAYLOAD = c.CMDFLAG_HAS_PAYLOAD
RPLIDAR_ANS_PKTFLAG_LOOP = c.ANS_PKTFLAG_LOOP

RPLIDAR_CMD_STOP = int(c.Cmd.STOP)
RPLIDAR_CMD_SCAN = int(c.Cmd.SCAN)
RPLIDAR_CMD_FORCE_SCAN = int(c.Cmd.FORCE_SCAN)
RPLIDAR_CMD_RESET = int(c.Cmd.RESET)
RPLIDAR_CMD_EXPRESS_SCAN = int(c.Cmd.EXPRESS_SCAN)
RPLIDAR_CMD_HQ_SCAN = int(c.Cmd.HQ_SCAN)
RPLIDAR_CMD_GET_DEVICE_INFO = int(c.Cmd.GET_DEVICE_INFO)
RPLIDAR_CMD_GET_DEVICE_HEALTH = int(c.Cmd.GET_DEVICE_HEALTH)
RPLIDAR_CMD_GET_SAMPLERATE = int(c.Cmd.GET_SAMPLERATE)
RPLIDAR_CMD_HQ_MOTOR_SPEED_CTRL = int(c.Cmd.HQ_MOTOR_SPEED_CTRL)
RPLIDAR_CMD_GET_LIDAR_CONF = int(c.Cmd.GET_LIDAR_CONF)
RPLIDAR_CMD_SET_LIDAR_CONF = int(c.Cmd.SET_LIDAR_CONF)
RPLIDAR_CMD_SET_MOTOR_PWM = int(c.Cmd.SET_MOTOR_PWM)
RPLIDAR_CMD_GET_ACC_BOARD_FLAG = int(c.Cmd.GET_ACC_BOARD_FLAG)

RPLIDAR_ANS_TYPE_DEVINFO = int(c.Ans.DEVINFO)
RPLIDAR_ANS_TYPE_DEVHEALTH = int(c.Ans.DEVHEALTH)
RPLIDAR_ANS_TYPE_SAMPLE_RATE = int(c.Ans.SAMPLE_RATE)
RPLIDAR_ANS_TYPE_MEASUREMENT = int(c.Ans.MEASUREMENT)
RPLIDAR_ANS_TYPE_MEASUREMENT_CAPSULED = int(c.Ans.MEASUREMENT_CAPSULED)
RPLIDAR_ANS_TYPE_MEASUREMENT_HQ = int(c.Ans.MEASUREMENT_HQ)
RPLIDAR_ANS_TYPE_MEASUREMENT_CAPSULED_ULTRA = int(c.Ans.MEASUREMENT_CAPSULED_ULTRA)
RPLIDAR_ANS_TYPE_MEASUREMENT_DENSE_CAPSULED = int(c.Ans.MEASUREMENT_DENSE_CAPSULED)
RPLIDAR_ANS_TYPE_ACC_BOARD_FLAG = int(c.Ans.ACC_BOARD_FLAG)

RPLIDAR_STATUS_OK = int(c.HealthStatus.OK)
RPLIDAR_STATUS_WARNING = int(c.HealthStatus.WARNING)
RPLIDAR_STATUS_ERROR = int(c.HealthStatus.ERROR)

RPLIDAR_CONF_SCAN_MODE_COUNT = int(c.ConfKey.SCAN_MODE_COUNT)
RPLIDAR_CONF_SCAN_MODE_US_PER_SAMPLE = int(c.ConfKey.SCAN_MODE_US_PER_SAMPLE)
RPLIDAR_CONF_SCAN_MODE_MAX_DISTANCE = int(c.ConfKey.SCAN_MODE_MAX_DISTANCE)
RPLIDAR_CONF_SCAN_MODE_ANS_TYPE = int(c.ConfKey.SCAN_MODE_ANS_TYPE)
RPLIDAR_CONF_SCAN_MODE_TYPICAL = int(c.ConfKey.SCAN_MODE_TYPICAL)
RPLIDAR_CONF_SCAN_MODE_NAME = int(c.ConfKey.SCAN_MODE_NAME)

# legacy measurement bit layout (rplidar_cmd.h node struct)
RPLIDAR_RESP_MEASUREMENT_SYNCBIT = c.MEASUREMENT_SYNCBIT
RPLIDAR_RESP_MEASUREMENT_QUALITY_SHIFT = c.MEASUREMENT_QUALITY_SHIFT
RPLIDAR_RESP_MEASUREMENT_CHECKBIT = c.MEASUREMENT_CHECKBIT
RPLIDAR_RESP_MEASUREMENT_ANGLE_SHIFT = c.MEASUREMENT_ANGLE_SHIFT

MAX_SCAN_NODES = 8192  # sl_lidar_driver.cpp:378


def _deprecated(old: str, new: str) -> None:
    warnings.warn(
        f"{old} is the legacy API; prefer {new}", DeprecationWarning, stacklevel=3
    )


class RPlidarDriver:
    """CamelCase facade forwarding to a modern driver instance.

    Mirrors the delegation pattern of rplidar_driver.cpp:67-197: every
    method is a one-line forward.  Construct with :meth:`CreateDriver` (the
    legacy factory name) or wrap an existing driver.
    """

    def __init__(self, impl: Optional[LidarDriverInterface] = None, **real_kwargs) -> None:
        if impl is None:
            from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver

            impl = RealLidarDriver(**real_kwargs)
        self._impl = impl

    # -- legacy factory pair (rplidar_driver.h CreateDriver/DisposeDriver) --
    @classmethod
    def CreateDriver(cls, **kwargs) -> "RPlidarDriver":
        _deprecated("RPlidarDriver.CreateDriver", "RealLidarDriver()")
        return cls(**kwargs)

    @staticmethod
    def DisposeDriver(drv: "RPlidarDriver") -> None:
        drv.disconnect()

    # -- connection ---------------------------------------------------------
    def connect(self, port: str, baudrate: int, flag: int = 0) -> bool:
        if flag:
            # the legacy flag argument was already unused by the reference
            # shim (rplidar_driver.cpp connect forwards it nowhere); modern
            # geometric compensation is always on here
            import warnings

            warnings.warn(
                f"RPlidarDriver.connect flag={flag:#x} is ignored "
                "(use RealLidarDriver.connect(use_geometric_compensation=...))",
                RuntimeWarning,
                stacklevel=2,
            )
        return self._impl.connect(port, baudrate, True)

    def disconnect(self) -> None:
        self._impl.disconnect()

    def isConnected(self) -> bool:
        return self._impl.is_connected()

    def reset(self) -> None:
        self._impl.reset()

    # -- info / health ------------------------------------------------------
    def getDeviceInfo(self) -> str:
        return self._impl.get_device_info_str()

    def getHealth(self) -> DeviceHealth:
        return self._impl.get_health()

    # -- motor --------------------------------------------------------------
    def startMotor(self, rpm: int = 0) -> bool:
        return self._impl.set_motor_speed(rpm if rpm else 600)

    def stopMotor(self) -> None:
        self._impl.stop_motor()

    def setMotorSpeed(self, rpm: int) -> bool:
        return self._impl.set_motor_speed(rpm)

    # -- scanning -----------------------------------------------------------
    def startScan(self, force: bool = False, use_typical: bool = True) -> bool:
        """Legacy auto-start: detect + start in the preferred mode.

        ``force`` maps to FORCE_SCAN 0x21 (scan despite a failed health
        check) on backends that support it (RealLidarDriver.force_scan);
        elsewhere it warns and falls back to the health-gated path.
        """
        if force:
            if self._impl.force_scan():
                return True
            warnings.warn(
                "startScan(force=True): this backend has no FORCE_SCAN; "
                "starting with the normal health-gated path",
                RuntimeWarning,
                stacklevel=2,
            )
        self._impl.detect_and_init_strategy()
        return self._impl.start_motor("", 0)

    def startScanExpress(self, fixed_angle: bool, scan_mode: str, rpm: int = 0) -> bool:
        if fixed_angle:
            warnings.warn(
                "startScanExpress(fixed_angle=True) is not supported and is ignored",
                RuntimeWarning,
                stacklevel=2,
            )
        return self._impl.start_motor(scan_mode, rpm)

    def stop(self) -> None:
        self._impl.stop_motor()

    def grabScanDataHq(self, timeout_ms: int = 2000) -> Optional[ScanBatch]:
        return self._impl.grab_scan_data(timeout_ms / 1000.0)

    def ascendScanData(self, batch: ScanBatch) -> ScanBatch:
        from rplidar_ros2_driver_tpu.ops.ascend import ascend_scan

        out, _ = ascend_scan(batch)
        return out

    # escape hatch, mirroring how the facade exposes the sl driver
    @property
    def impl(self) -> LidarDriverInterface:
        return self._impl
