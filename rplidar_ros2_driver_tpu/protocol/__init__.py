from rplidar_ros2_driver_tpu.protocol.codec import (
    AnsHeader,
    ResponseDecoder,
    encode_command,
)
from rplidar_ros2_driver_tpu.protocol.constants import Ans, Cmd, ConfKey, HealthStatus
from rplidar_ros2_driver_tpu.protocol.crc import crc32_padded

__all__ = [
    "Ans",
    "AnsHeader",
    "Cmd",
    "ConfKey",
    "HealthStatus",
    "ResponseDecoder",
    "crc32_padded",
    "encode_command",
]
