"""Slamtec wire-protocol constants.

Semantics documented against the reference headers
(src/sdk/include/sl_lidar_cmd.h, sl_lidar_protocol.h); values are protocol
facts fixed by the device firmware, re-stated here — the framing/decoding
machinery around them is new.
"""

from __future__ import annotations

import enum

# ---- request framing (sl_lidar_protocol.h:44-45) ----
CMD_SYNC_BYTE = 0xA5
CMDFLAG_HAS_PAYLOAD = 0x80

# ---- response framing (sl_lidar_protocol.h:47-53) ----
ANS_SYNC_BYTE1 = 0xA5
ANS_SYNC_BYTE2 = 0x5A
ANS_PKTFLAG_LOOP = 0x1
ANS_HEADER_SIZE_MASK = 0x3FFFFFFF
ANS_HEADER_SUBTYPE_SHIFT = 30
ANS_HEADER_LEN = 7  # sync1 + sync2 + u32 size/subtype + type


class Cmd(enum.IntEnum):
    """Request opcodes (sl_lidar_cmd.h:47-74)."""

    STOP = 0x25
    SCAN = 0x20
    FORCE_SCAN = 0x21
    RESET = 0x40
    NEW_BAUDRATE_CONFIRM = 0x90
    GET_DEVICE_INFO = 0x50
    GET_DEVICE_HEALTH = 0x52
    GET_SAMPLERATE = 0x59
    HQ_MOTOR_SPEED_CTRL = 0xA8
    EXPRESS_SCAN = 0x82
    HQ_SCAN = 0x83
    GET_LIDAR_CONF = 0x84
    SET_LIDAR_CONF = 0x85
    SET_MOTOR_PWM = 0xF0
    GET_ACC_BOARD_FLAG = 0xFF


AUTOBAUD_MAGICBYTE = 0x41
# NEW_BAUDRATE_CONFIRM payload flag (sl_lidar_cmd.h:133-137)
AUTOBAUD_CONFIRM_FLAG = 0x5F5F
# ACC_BOARD_FLAG answer bit 0: accessory board drives the motor via PWM
# (sl_lidar_cmd.h acc_board_flag response + checkMotorCtrlSupport,
# sl_lidar_driver.cpp:833-878)
ACC_BOARD_FLAG_MOTOR_CTRL_SUPPORT_MASK = 0x1


class Ans(enum.IntEnum):
    """Response type bytes (sl_lidar_cmd.h:141-162)."""

    DEVINFO = 0x04
    DEVHEALTH = 0x06
    SAMPLE_RATE = 0x15
    GET_LIDAR_CONF = 0x20
    SET_LIDAR_CONF = 0x21
    MEASUREMENT = 0x81
    MEASUREMENT_CAPSULED = 0x82
    MEASUREMENT_HQ = 0x83
    MEASUREMENT_CAPSULED_ULTRA = 0x84
    MEASUREMENT_DENSE_CAPSULED = 0x85
    MEASUREMENT_ULTRA_DENSE_CAPSULED = 0x86
    ACC_BOARD_FLAG = 0xFF


# Measurement answer types that stream in loop mode.
SCAN_ANS_TYPES = frozenset(
    {
        Ans.MEASUREMENT,
        Ans.MEASUREMENT_CAPSULED,
        Ans.MEASUREMENT_HQ,
        Ans.MEASUREMENT_CAPSULED_ULTRA,
        Ans.MEASUREMENT_DENSE_CAPSULED,
        Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED,
    }
)

# ---- wire frame geometry (sl_lidar_cmd.h struct layouts) ----
# All little-endian, packed.
NORMAL_NODE_BYTES = 5          # sync_quality u8, angle_q6_checkbit u16, distance_q2 u16
CAPSULE_BYTES = 84             # 2 checksum nibbles + u16 start angle + 16 cabins x 5B
CAPSULE_CABINS = 16            # 2 points per cabin -> 32 points
DENSE_CAPSULE_BYTES = 84       # 2 + 2 + 40 cabins x u16
DENSE_CABINS = 40              # 1 point per cabin
ULTRA_CAPSULE_BYTES = 132      # 2 + 2 + 32 cabins x u32
ULTRA_CABINS = 32              # 3 points per cabin -> 96 points
ULTRA_DENSE_CAPSULE_BYTES = 170  # 2 + u32 ts + u16 status + u16 angle + 32 cabins x 5B
ULTRA_DENSE_CABINS = 32        # 2 points per cabin -> 64 points
HQ_CAPSULE_BYTES = 1 + 8 + 96 * 8 + 4  # sync + u64 ts + 96 HQ nodes + crc32
HQ_NODES_PER_CAPSULE = 96
HQ_NODE_BYTES = 8              # u16 angle_z_q14, u32 dist_mm_q2, u8 quality, u8 flag

# Express sync nibbles (sl_lidar_cmd.h:208-211).
EXP_SYNC_1 = 0xA
EXP_SYNC_2 = 0x5
HQ_SYNC = 0xA5
EXP_SYNCBIT = 0x1 << 15

# Measurement node bit fields (sl_lidar_cmd.h:175-181).
MEASUREMENT_SYNCBIT = 0x1
MEASUREMENT_QUALITY_SHIFT = 2
MEASUREMENT_CHECKBIT = 0x1
MEASUREMENT_ANGLE_SHIFT = 1

# Express scan working flags (sl_lidar_cmd.h:86-91).
EXPRESS_FLAG_BOOST = 0x0001
EXPRESS_FLAG_SUNLIGHT_REJECTION = 0x0002

# Varbitscale encoding (sl_lidar_cmd.h:364-372) used by the ultra capsule.
VARBITSCALE_X2_SRC_BIT = 9
VARBITSCALE_X4_SRC_BIT = 11
VARBITSCALE_X8_SRC_BIT = 12
VARBITSCALE_X16_SRC_BIT = 14
VARBITSCALE_X2_DEST_VAL = 512
VARBITSCALE_X4_DEST_VAL = 1280
VARBITSCALE_X8_DEST_VAL = 1792
VARBITSCALE_X16_DEST_VAL = 3328


class ConfKey(enum.IntEnum):
    """GET/SET_LIDAR_CONF key space (sl_lidar_cmd.h:296-317)."""

    ANGLE_RANGE = 0x00000000
    DESIRED_ROT_FREQ = 0x00000001
    SCAN_COMMAND_BITMAP = 0x00000002
    MIN_ROT_FREQ = 0x00000004
    MAX_ROT_FREQ = 0x00000005
    MAX_DISTANCE = 0x00000060
    SCAN_MODE_COUNT = 0x00000070
    SCAN_MODE_US_PER_SAMPLE = 0x00000071
    SCAN_MODE_MAX_DISTANCE = 0x00000074
    SCAN_MODE_ANS_TYPE = 0x00000075
    LIDAR_MAC_ADDR = 0x00000079
    SCAN_MODE_TYPICAL = 0x0000007C
    SCAN_MODE_NAME = 0x0000007F
    MODEL_REVISION_ID = 0x00000080
    MODEL_NAME_ALIAS = 0x00000081
    DETECTED_SERIAL_BPS = 0x000000A1
    LIDAR_STATIC_IP_ADDR = 0x0001CCC0


# Scan-command mode ids shared by the conf protocol and the EXPRESS_SCAN
# request (SL_LIDAR_CONF_SCAN_COMMAND_STD/EXPRESS, sl_lidar_cmd.h:289-290).
# EXPRESS is also the hardwired typical-mode fallback for old triangle
# lidars whose firmware predates the conf protocol (getTypicalScanMode,
# sl_lidar_driver.cpp:577-580).
SCAN_COMMAND_STD = 0
SCAN_COMMAND_EXPRESS = 1


class HealthStatus(enum.IntEnum):
    """Device-side health byte (sl_lidar_cmd.h:171-173)."""

    OK = 0x0
    WARNING = 0x1
    ERROR = 0x2


ANS_PAYLOAD_BYTES = {
    Ans.MEASUREMENT: NORMAL_NODE_BYTES,
    Ans.MEASUREMENT_CAPSULED: CAPSULE_BYTES,
    Ans.MEASUREMENT_HQ: HQ_CAPSULE_BYTES,
    Ans.MEASUREMENT_CAPSULED_ULTRA: ULTRA_CAPSULE_BYTES,
    Ans.MEASUREMENT_DENSE_CAPSULED: DENSE_CAPSULE_BYTES,
    Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: ULTRA_DENSE_CAPSULE_BYTES,
}
