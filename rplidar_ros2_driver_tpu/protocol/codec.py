"""Request/response framing codec.

Re-implements the behavioral contract of the reference codec
(src/sdk/src/sl_lidarprotocol_codec.cpp):

  * requests: ``A5 | cmd [| size | payload... | xor-checksum]`` — checksum
    covers every preceding byte including sync (codec onEncodeData
    :78-130);
  * responses: ``A5 5A | u32le size(30b)+subtype(2b) | type | payload`` with
    *loop mode*: when subtype bit0 is set the codec keeps re-emitting
    fixed-``size`` payloads without new headers until reset (:205-228).

Unlike the reference's byte-at-a-time switch statement, this decoder works
on whole buffers with ``bytes.find`` / slicing — the Python hot path hands
off entire capsule streams at once, and the per-byte scan-sync hunting lives
in the vectorized unpackers (ops/unpack.py) or the C++ runtime (native/).
"""

from __future__ import annotations

import dataclasses
from typing import Callable, Optional

from rplidar_ros2_driver_tpu.protocol.constants import (
    ANS_HEADER_LEN,
    ANS_HEADER_SIZE_MASK,
    ANS_HEADER_SUBTYPE_SHIFT,
    ANS_PKTFLAG_LOOP,
    ANS_SYNC_BYTE1,
    ANS_SYNC_BYTE2,
    CMD_SYNC_BYTE,
    CMDFLAG_HAS_PAYLOAD,
)


def encode_command(cmd: int, payload: bytes = b"") -> bytes:
    """Build a request packet.

    Commands without the HAS_PAYLOAD flag are 2 bytes; with it, the size
    byte and trailing XOR checksum are appended (checksum folds in the sync
    and cmd bytes too, matching RPLidarProtocolCodec::onEncodeData).
    """
    if cmd & CMDFLAG_HAS_PAYLOAD:
        if len(payload) > 0xFF:
            raise ValueError("payload too large for 1-byte size field")
        body = bytes([CMD_SYNC_BYTE, cmd & 0xFF, len(payload)]) + payload
        checksum = 0
        for b in body:
            checksum ^= b
        return body + bytes([checksum])
    if payload:
        raise ValueError(f"cmd {cmd:#x} does not carry a payload")
    return bytes([CMD_SYNC_BYTE, cmd & 0xFF])


@dataclasses.dataclass(frozen=True)
class AnsHeader:
    """Decoded response descriptor."""

    ans_type: int
    payload_len: int
    is_loop: bool

    def encode(self) -> bytes:
        word = (self.payload_len & ANS_HEADER_SIZE_MASK) | (
            (ANS_PKTFLAG_LOOP if self.is_loop else 0) << ANS_HEADER_SUBTYPE_SHIFT
        )
        return bytes([ANS_SYNC_BYTE1, ANS_SYNC_BYTE2]) + word.to_bytes(4, "little") + bytes(
            [self.ans_type & 0xFF]
        )


# message callback: (ans_type, payload bytes, is_loop)
MessageListener = Callable[[int, bytes, bool], None]

# Largest real payload is the HQ capsule (777 bytes); anything near the
# 30-bit field limit is a corrupted header (e.g. wrong-baud noise that
# happened to contain A5 5A) and must trigger a resync instead of
# swallowing the stream into a giant pending payload.  Matches the native
# codec's kMaxSanePayload (native/src/codec.cc).
MAX_SANE_PAYLOAD = 8192


class ResponseDecoder:
    """Streaming response decoder with loop-mode support.

    Feed arbitrary chunks via :meth:`feed`; complete messages are delivered
    to the listener.  In loop mode every subsequent ``payload_len`` bytes is
    one message with the same header until :meth:`exit_loop_mode` (the
    equivalent of the reference's exitLoopMode decode reset).
    """

    def __init__(self, listener: Optional[MessageListener] = None) -> None:
        self._listener = listener
        self._buf = bytearray()
        self._header: Optional[AnsHeader] = None
        self._in_loop = False
        self.messages: list[tuple[int, bytes, bool]] = []  # kept if no listener

    def set_listener(self, listener: MessageListener) -> None:
        self._listener = listener

    def reset(self) -> None:
        self._buf.clear()
        self._header = None
        self._in_loop = False

    # exitLoopMode == decode reset (sl_lidarprotocol_codec.cpp:66-68)
    exit_loop_mode = reset

    def _emit(self, payload: bytes) -> None:
        assert self._header is not None
        msg = (self._header.ans_type, payload, self._header.is_loop)
        if self._listener is not None:
            self._listener(*msg)
        else:
            self.messages.append(msg)

    def feed(self, data: bytes) -> None:
        self._buf.extend(data)
        while True:
            if self._header is None:
                # hunt for the A5 5A sync pair
                idx = self._buf.find(bytes([ANS_SYNC_BYTE1, ANS_SYNC_BYTE2]))
                if idx < 0:
                    # keep a trailing lone A5 in case 5A arrives next chunk
                    if self._buf and self._buf[-1] == ANS_SYNC_BYTE1:
                        del self._buf[:-1]
                    else:
                        self._buf.clear()
                    return
                if len(self._buf) - idx < ANS_HEADER_LEN:
                    del self._buf[:idx]
                    return
                word = int.from_bytes(self._buf[idx + 2 : idx + 6], "little")
                payload_len = word & ANS_HEADER_SIZE_MASK
                if payload_len > MAX_SANE_PAYLOAD:
                    # corrupted header: skip the false sync byte and rescan.
                    # Both codecs REJECT such frames (codec.cc resyncs on
                    # implausible sizes too); recovery differs benignly —
                    # the byte-at-a-time native decoder has already consumed
                    # the 7 header bytes, while this buffered decoder can
                    # rescan from sync+1 and so recovers a real packet that
                    # starts inside the corrupt header.
                    del self._buf[: idx + 1]
                    continue
                self._header = AnsHeader(
                    ans_type=self._buf[idx + 6],
                    payload_len=payload_len,
                    is_loop=bool((word >> ANS_HEADER_SUBTYPE_SHIFT) & ANS_PKTFLAG_LOOP),
                )
                del self._buf[: idx + ANS_HEADER_LEN]
                self._in_loop = self._header.is_loop
                if self._header.payload_len == 0:
                    # zero-payload packet: header-only (codec :196-199)
                    self._emit(b"")
                    self._header = None
                    continue
            # collecting payload(s)
            n = self._header.payload_len
            if len(self._buf) < n:
                return
            payload = bytes(self._buf[:n])
            del self._buf[:n]
            self._emit(payload)
            if not self._in_loop:
                self._header = None

    def drain_loop_payloads(self, data: bytes) -> list[bytes]:
        """Convenience: feed data, return payloads accumulated (no listener)."""
        self.feed(data)
        out = [p for (_, p, _) in self.messages]
        self.messages.clear()
        return out
