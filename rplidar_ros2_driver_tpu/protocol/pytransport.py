"""Pure-Python transport fallback: channels + transceiver without the
native library.

The production I/O plane is C++ (native/src/channel.cc + transceiver.cc,
the analog of the reference's arch layer + AsyncTransceiver).  This
module is its dependency-free twin — the same duck-typed contracts
(``NativeChannel`` / ``TransceiverLike``) over ``os``/``socket``/
``termios`` and the pure-Python :class:`~.codec.ResponseDecoder` — so
the real driver still runs where a C++ toolchain is unavailable
(``driver/real.py`` falls back here automatically, with a log notice).

Serial parity notes (vs channel.cc):

  * arbitrary baud uses the same termios2 ``BOTHER`` ioctl
    (``TCGETS2``/``TCSETS2``), raw 8N1, no flow control — 256000 baud
    (A2M7/A3/S1) has no ``Bxxx`` constant, so this is required, not an
    optimization;
  * DTR motor control via ``TIOCMBIS``/``TIOCMBIC``;
  * blocking reads use ``select`` over the fd plus a self-pipe so
    ``cancel()``/``close()`` unblocks a parked reader immediately (the
    reference's self-pipe trick, arch/linux/net_serial.cpp:204-223).

The rx thread runs at default priority (the native transceiver elevates
to SCHED_RR best-effort; Python offers no portable equivalent without
privileges — one more reason the native plane is the default).
"""

from __future__ import annotations

import errno
import fcntl
import logging
import os
import queue
import select
import socket
import struct
import threading
import time
from typing import Optional

from rplidar_ros2_driver_tpu.protocol.codec import ResponseDecoder

# the engine's pump catches exactly this class; importing it does not load
# the shared library (native.runtime only dlopens lazily inside load())
from rplidar_ros2_driver_tpu.native.runtime import ChannelError

log = logging.getLogger("rplidar_tpu.pytransport")


# Linux termios2 (asm-generic/ioctls.h, asm-generic/termbits.h)
_TCGETS2 = 0x802C542A
_TCSETS2 = 0x402C542B
_BOTHER = 0o010000
_CBAUD = 0o010017
_CSIZE = 0o000060
_CS8 = 0o000060
_PARENB = 0o000400
_CSTOPB = 0o000100
_CRTSCTS = 0o20000000000
_CREAD = 0o000200
_CLOCAL = 0o004000
_TCFLSH = 0x540B
_TCIOFLUSH = 2
_TIOCMBIS = 0x5416
_TIOCMBIC = 0x5417
_TIOCM_DTR = 0x002
# struct termios2: 4 tcflag_t, c_line, c_cc[19], 2 speed_t  (44 bytes)
_TERMIOS2_FMT = "<IIII20BII"


def _serial_configure_raw(fd: int, baud: int) -> None:
    """termios2 BOTHER raw-8N1 setup, mirroring rpl_channel::OpenSerial."""
    buf = bytearray(struct.calcsize(_TERMIOS2_FMT))
    fcntl.ioctl(fd, _TCGETS2, buf)
    fields = list(struct.unpack(_TERMIOS2_FMT, buf))
    cflag = fields[2]
    cflag &= ~(_CBAUD | _CSIZE | _PARENB | _CSTOPB | _CRTSCTS)
    cflag |= _BOTHER | _CS8 | _CREAD | _CLOCAL
    fields[0] = 0  # c_iflag
    fields[1] = 0  # c_oflag
    fields[2] = cflag
    fields[3] = 0  # c_lflag
    fields[5 + 6] = 0  # c_cc[VMIN=6]
    fields[5 + 5] = 0  # c_cc[VTIME=5]
    fields[-2] = baud  # c_ispeed
    fields[-1] = baud  # c_ospeed
    fcntl.ioctl(fd, _TCSETS2, struct.pack(_TERMIOS2_FMT, *fields))
    fcntl.ioctl(fd, _TCFLSH, _TCIOFLUSH)


class PyChannel:
    """serial | tcp | udp byte transport (NativeChannel's duck-type twin)."""

    def __init__(self, kind: str, target: str, *, baud: int = 0, port: int = 0) -> None:
        if kind not in ("serial", "tcp", "udp"):
            raise ValueError(f"unknown channel kind {kind!r}")
        self.kind = kind
        self._target = target
        self._baud = baud
        self._port = port
        self._fd: Optional[int] = None       # serial
        self._sock: Optional[socket.socket] = None
        self._cancel_r, self._cancel_w = -1, -1
        self._lock = threading.Lock()

    # -- lifecycle -----------------------------------------------------------

    def open(self) -> bool:
        self.close()
        try:
            if self.kind == "serial":
                fd = os.open(self._target, os.O_RDWR | os.O_NOCTTY | os.O_NONBLOCK)
                try:
                    _serial_configure_raw(fd, self._baud or 115200)
                except OSError:
                    os.close(fd)
                    return False
                self._fd = fd
            elif self.kind == "tcp":
                self._sock = socket.create_connection(
                    (self._target, self._port), timeout=5.0
                )
                # many tiny request packets, each awaited synchronously:
                # Nagle would serialize them behind delayed ACKs
                # (native parity: channel.cc sets TCP_NODELAY too)
                self._sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY, 1)
                self._sock.setblocking(False)
            else:  # udp: connected pair, like sl_udp_channel.cpp:53-58
                self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
                self._sock.connect((self._target, self._port))
                self._sock.setblocking(False)
        except OSError as e:
            log.debug("open(%s %s) failed: %s", self.kind, self._target, e)
            return False
        self._cancel_r, self._cancel_w = os.pipe()
        return True

    def close(self) -> None:
        with self._lock:
            if self._fd is not None:
                try:
                    os.close(self._fd)
                except OSError:
                    pass
                self._fd = None
            if self._sock is not None:
                try:
                    self._sock.close()
                except OSError:
                    pass
                self._sock = None
            for a in ("_cancel_r", "_cancel_w"):
                fd = getattr(self, a)
                if fd >= 0:
                    try:
                        os.close(fd)
                    except OSError:
                        pass
                    setattr(self, a, -1)

    @property
    def is_open(self) -> bool:
        return self._fd is not None or self._sock is not None

    def _read_fd(self) -> int:
        if self._fd is not None:
            return self._fd
        if self._sock is not None:
            return self._sock.fileno()
        return -1

    # -- I/O -----------------------------------------------------------------

    def write(self, data: bytes) -> int:
        """-1 on error or on 1 s without progress (native parity:
        rpl_channel_write gives up when its 1 s select makes none)."""
        try:
            if self._fd is not None:
                total = 0
                view = memoryview(data)
                while total < len(data):
                    try:
                        total += os.write(self._fd, view[total:])
                    except BlockingIOError:
                        _, w, _ = select.select([], [self._fd], [], 1.0)
                        if not w:
                            return -1  # wedged adapter: no progress in 1 s
                return total
            if self._sock is not None:
                self._sock.settimeout(1.0)
                try:
                    self._sock.sendall(data)
                except socket.timeout:
                    return -1  # stalled peer: no progress in 1 s
                finally:
                    self._sock.setblocking(False)
                return len(data)
        except OSError:
            return -1
        return -1

    def read(self, max_bytes: int = 4096, timeout_ms: int = 1000) -> Optional[bytes]:
        """None on timeout; b'' on closed/cancelled; bytes otherwise."""
        fd = self._read_fd()
        if fd < 0:
            return b""
        try:
            r, _, _ = select.select([fd, self._cancel_r], [], [], timeout_ms / 1000.0)
        except (OSError, ValueError):
            return b""
        if self._cancel_r in r:
            return b""
        if not r:
            return None
        try:
            if self._fd is not None:
                return os.read(self._fd, max_bytes)  # b'' at EOF (unplugged pty)
            assert self._sock is not None
            return self._sock.recv(max_bytes)  # b'' on peer close
        except BlockingIOError:
            return None
        except OSError as e:
            if e.errno in (errno.EAGAIN, errno.EWOULDBLOCK):
                return None
            return b""  # EIO on yanked adapter, ECONNRESET, ...

    def set_dtr(self, level: bool) -> bool:
        if self._fd is None:
            return False
        try:
            fcntl.ioctl(
                self._fd,
                _TIOCMBIS if level else _TIOCMBIC,
                struct.pack("I", _TIOCM_DTR),
            )
            return True
        except OSError:
            return False

    def cancel(self) -> None:
        if self._cancel_w >= 0:
            try:
                os.write(self._cancel_w, b"\x01")
            except OSError:
                pass


class PyTransceiver:
    """rx thread + decoded-message queue over a PyChannel (TransceiverLike).

    Same shape as the native transceiver: one reader thread feeds the
    streaming decoder and enqueues complete messages with their
    rx-thread arrival stamps (the anchor for per-node timestamp
    back-dating); a channel failure surfaces as ChannelError from
    ``wait_message``.
    """

    _SENTINEL = object()

    def __init__(self, channel: PyChannel) -> None:
        self.channel = channel
        self._q: queue.Queue = queue.Queue(maxsize=4096)
        self._dec_lock = threading.Lock()
        self._rx_ts = 0.0
        self._decoder = ResponseDecoder(self._on_message)
        self._thread: Optional[threading.Thread] = None
        self._running = threading.Event()
        self._error = threading.Event()

    # -- lifecycle -----------------------------------------------------------

    def start(self) -> bool:
        if not self.channel.is_open and not self.channel.open():
            return False
        self._error.clear()
        with self._dec_lock:
            self._decoder.reset()
        self._drain_queue()
        self._running.set()
        self._thread = threading.Thread(
            target=self._rx_loop, name="rpl_py_rx", daemon=True
        )
        self._thread.start()
        return True

    def stop(self) -> None:
        self._running.clear()
        self.channel.cancel()
        if self._thread is not None:
            self._thread.join(5.0)
            self._thread = None
        self.channel.close()

    # -- TransceiverLike -----------------------------------------------------

    def send(self, packet: bytes) -> bool:
        return self.channel.write(packet) == len(packet)

    def wait_message(self, timeout_ms: int = 1000) -> Optional[tuple[int, bytes, bool]]:
        got = self.wait_message_ts(timeout_ms)
        return got[:3] if got is not None else None

    def wait_message_ts(
        self, timeout_ms: int = 1000
    ) -> Optional[tuple[int, bytes, bool, float]]:
        try:
            m = self._q.get(timeout=timeout_ms / 1000.0)
        except queue.Empty:
            if self._error.is_set():
                raise ChannelError("channel closed or errored")
            return None
        if m is self._SENTINEL:
            raise ChannelError("channel closed or errored")
        return m

    def reset_decoder(self) -> None:
        with self._dec_lock:
            self._decoder.reset()

    @property
    def had_error(self) -> bool:
        return self._error.is_set()

    # -- internals -----------------------------------------------------------

    def _on_message(self, ans_type: int, payload: bytes, is_loop: bool) -> None:
        try:
            self._q.put_nowait((ans_type, payload, is_loop, self._rx_ts))
        except queue.Full:
            log.warning("rx queue full: dropping ans %#x", ans_type)

    def _drain_queue(self) -> None:
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                return

    def _rx_loop(self) -> None:
        while self._running.is_set():
            data = self.channel.read(4096, timeout_ms=200)
            if data is None:
                continue  # timeout: poll the running flag
            if data == b"":
                if self._running.is_set():
                    self._error.set()
                    try:
                        self._q.put_nowait(self._SENTINEL)
                    except queue.Full:
                        pass
                return
            self._rx_ts = time.monotonic()
            with self._dec_lock:
                self._decoder.feed(data)
