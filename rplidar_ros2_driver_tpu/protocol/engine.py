"""Command/response engine over an async transceiver.

Equivalent of the reference driver's send paths
(`_sendCommandWithoutResponse` sl_lidar_driver.cpp:1600-1610,
`_sendCommandWithResponse` :1612-1641) and its listener routing
(:1655-1672): measurement (loop-mode) messages flow to the scan handler;
anything else completes the pending request if the answer type matches.

The reference parks the requester on a ``Waiter`` signalled from the decoder
thread; here a pump thread drains the transceiver's message queue and hands
responses over a one-slot queue.  One operation lock serializes requests
(the recursive op-lock of sl_lidar_driver.cpp:401).
"""

from __future__ import annotations

import logging
import queue
import threading
import time
from typing import Callable, Optional, Protocol

from rplidar_ros2_driver_tpu.protocol.codec import encode_command
from rplidar_ros2_driver_tpu.protocol.constants import SCAN_ANS_TYPES

log = logging.getLogger("rplidar_tpu.engine")


class TransceiverLike(Protocol):
    """Duck-typed transceiver contract (NativeTransceiver or a test fake)."""

    def start(self) -> bool: ...
    def stop(self) -> None: ...
    def send(self, packet: bytes) -> bool: ...
    def wait_message(self, timeout_ms: int = 1000) -> Optional[tuple[int, bytes, bool]]: ...
    def reset_decoder(self) -> None: ...
    @property
    def had_error(self) -> bool: ...


# measurement callbacks: per-payload (ans_type, payload) or batched
# (ans_type, [(payload, rx_monotonic_ts), ...]) — the batched form is the
# production decode path: the pump drains every already-decoded message in
# one go so the vectorized unpackers see whole frame runs (natural batching,
# zero added latency — nothing ever *waits* for a batch to fill).
MeasurementHandler = Callable[[int, bytes], None]
MeasurementBatchHandler = Callable[[int, list], None]

# Upper bound on one delivered measurement run: bounds decode-batch memory
# and keeps request/response answers flowing between runs under sustained
# streaming.
_MAX_MEASUREMENT_BATCH = 64


class CommandEngine:
    def __init__(
        self,
        transceiver: TransceiverLike,
        on_measurement: Optional[MeasurementHandler] = None,
        on_measurement_batch: Optional[MeasurementBatchHandler] = None,
    ) -> None:
        self._tx = transceiver
        self._on_measurement = on_measurement
        self._on_measurement_batch = on_measurement_batch
        self._op_lock = threading.RLock()
        self._pending_lock = threading.Lock()
        self._pending_ans: Optional[int] = None
        self._pending_q: Optional[queue.Queue] = None
        # answers still owed by timed-out requests, per ans type: a late
        # answer must not complete the NEXT request of the same type (the
        # conf protocol reuses one ans type for every per-mode query, and
        # the echoed key alone cannot distinguish modes).  Maps ans_type ->
        # monotonic expiry; an answer arriving before expiry is dropped
        # once, after expiry flows normally (so a device that stays silent
        # can only cost one extra timeout, never a permanent drop loop).
        self._stale: dict[int, float] = {}
        self._pump: Optional[threading.Thread] = None
        self._running = threading.Event()
        self.link_error = threading.Event()

    # -- lifecycle ----------------------------------------------------------

    def start(self) -> bool:
        if not self._tx.start():
            return False
        self.link_error.clear()
        self._running.set()
        self._pump = threading.Thread(target=self._pump_loop, name="rpl_pump", daemon=True)
        self._pump.start()
        return True

    def stop(self) -> None:
        self._running.clear()
        self._tx.stop()  # unblocks wait_message via channel close
        if self._pump:
            self._pump.join(5.0)
            self._pump = None

    @property
    def healthy(self) -> bool:
        return self._running.is_set() and not self.link_error.is_set()

    @property
    def rx_priority(self) -> int:
        """Scheduling class the rx thread achieved, when the transceiver
        reports it (native: 2 = SCHED_RR, 1 = nice boost, 0 = default);
        -1 for transports without elevation (pure-Python fallback)."""
        return int(getattr(self._tx, "rx_priority", -1))

    @property
    def channel(self):
        """Underlying byte channel, when the transceiver exposes one (the
        raw-access escape hatch for DTR motor control and autobaud)."""
        return getattr(self._tx, "channel", None)

    # -- request API --------------------------------------------------------

    def send_only(self, cmd: int, payload: bytes = b"") -> bool:
        """Fire-and-forget (ref :1600-1610)."""
        with self._op_lock:
            return self._tx.send(encode_command(cmd, payload))

    def request(
        self, cmd: int, ans_type: int, payload: bytes = b"", timeout_s: float = 1.0
    ) -> Optional[bytes]:
        """Send and block for the matching answer; None on timeout/error."""
        with self._op_lock:
            slot: queue.Queue = queue.Queue(maxsize=1)
            with self._pending_lock:
                self._pending_ans = ans_type
                self._pending_q = slot
            try:
                if not self._tx.send(encode_command(cmd, payload)):
                    return None
                try:
                    return slot.get(timeout=timeout_s)
                except queue.Empty:
                    log.debug("request %#x timed out waiting for ans %#x", cmd, ans_type)
                    with self._pending_lock:
                        # the device may still answer later: discard one
                        # message of this type if it lands within another
                        # timeout window
                        self._stale[ans_type] = time.monotonic() + timeout_s
                    return None
            finally:
                with self._pending_lock:
                    self._pending_ans = None
                    self._pending_q = None

    def reset_decoder(self) -> None:
        self._tx.reset_decoder()

    # -- pump ---------------------------------------------------------------

    def _pump_loop(self) -> None:
        from rplidar_ros2_driver_tpu.native.runtime import ChannelError

        # prefer the rx-thread-stamped receive API: frame arrival times then
        # come from the native rx thread (CLOCK_MONOTONIC), immune to the
        # drain latency of this pump — a run of frames popped back-to-back
        # keeps its true inter-frame spacing for timestamp back-dating
        wait_ts = getattr(self._tx, "wait_message_ts", None)

        def recv(timeout_ms: int):
            if wait_ts is not None:
                return wait_ts(timeout_ms=timeout_ms)
            m = self._tx.wait_message(timeout_ms=timeout_ms)
            return None if m is None else (*m, time.monotonic())

        batch_type: Optional[int] = None
        batch: list = []  # [(payload, rx_ts)] of consecutive same-type frames

        def flush() -> None:
            nonlocal batch_type, batch
            if not batch:
                return
            try:
                if self._on_measurement_batch is not None:
                    self._on_measurement_batch(batch_type, batch)
                elif self._on_measurement is not None:
                    for data, _ts in batch:
                        self._on_measurement(batch_type, data)
            except Exception:
                log.exception("measurement handler failed")
            batch_type = None
            batch = []

        while self._running.is_set():
            # first message: block; then drain whatever else is already
            # decoded (timeout 0) so sustained streams deliver in runs
            timeout_ms = 200
            while True:
                try:
                    m = recv(timeout_ms)
                except ChannelError:
                    flush()
                    if self._running.is_set():
                        log.warning("channel error detected by pump (hot-unplug?)")
                        self.link_error.set()
                    return
                if m is None:
                    break  # queue drained (or idle timeout): deliver the run
                timeout_ms = 0
                ans_type, data, is_loop, rx_ts = m
                if is_loop or ans_type in SCAN_ANS_TYPES:
                    if ans_type != batch_type:
                        flush()
                        batch_type = ans_type
                    batch.append((data, rx_ts))
                    if len(batch) >= _MAX_MEASUREMENT_BATCH:
                        flush()
                    continue
                self._route_response(ans_type, data)
            flush()

    def _route_response(self, ans_type: int, data: bytes) -> None:
        with self._pending_lock:
            stale_until = self._stale.pop(ans_type, None)
            # the deadline itself is INSIDE the stale window (<=, not <):
            # an answer landing exactly at the expiry instant still
            # belongs to the timed-out request — delivering it would
            # hand request N-1's answer to request N (the conf protocol
            # reuses one ans type across per-mode queries, so a
            # boundary-delivered answer is silently WRONG data, not
            # just late data)
            if stale_until is not None and time.monotonic() <= stale_until:
                log.debug("dropping stale ans %#x (%d bytes)", ans_type, len(data))
            elif self._pending_ans == ans_type and self._pending_q is not None:
                try:
                    self._pending_q.put_nowait(data)
                except queue.Full:
                    pass
            else:
                log.debug("dropping unexpected ans %#x (%d bytes)", ans_type, len(data))
