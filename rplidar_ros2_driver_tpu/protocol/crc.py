"""CRC32 for HQ capsules.

The device uses the standard reflected CRC-32 (poly 0x04C11DB7, init/xorout
0xFFFFFFFF) over the capsule bytes zero-padded to a multiple of 4
(reference behavior: src/sdk/src/sl_crc.cpp:38-101,
handler_hqnode.cpp:124-141).  Implemented here with a numpy table — the CRC
guards frame integrity on the host side; it never needs to run on TPU.
"""

from __future__ import annotations

import numpy as np

_POLY_REFLECTED = 0xEDB88320  # bit-reversed 0x04C11DB7


def _make_table() -> np.ndarray:
    table = np.empty(256, np.uint32)
    for i in range(256):
        c = i
        for _ in range(8):
            c = (c >> 1) ^ _POLY_REFLECTED if (c & 1) else (c >> 1)
        table[i] = c
    return table


_TABLE = _make_table()


def crc32_padded(data: bytes | np.ndarray) -> int:
    """CRC32 with zero padding of ``4 - (len & 3)`` bytes.

    Note the device convention appends a full 4 zero bytes when the input is
    already 4-aligned (sl_crc.cpp:76 computes ``leftBytes = 4 - (len & 3)``,
    which is never 0) — we must match to stay frame-compatible.
    """
    buf = np.frombuffer(bytes(data), np.uint8)
    pad = 4 - (len(buf) & 3)
    buf = np.concatenate([buf, np.zeros(pad, np.uint8)])
    crc = np.uint32(0xFFFFFFFF)
    for b in buf:
        crc = _TABLE[(crc ^ b) & 0xFF] ^ (crc >> np.uint32(8))
    return int(crc ^ np.uint32(0xFFFFFFFF))


def frame_crc_ok(payload: bytes) -> bool:
    """Whole-frame HQ-capsule CRC verdict: the trailing little-endian u32
    against :func:`crc32_padded` over everything before it.  The ONE
    implementation of the wire CRC check — the host decoder and both
    fused ingest engines (single-stream and fleet) call this, so the
    framing can never drift between the parity-locked paths."""
    return crc32_padded(payload[:-4]) == int.from_bytes(payload[-4:], "little")
