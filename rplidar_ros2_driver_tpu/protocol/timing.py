"""Timestamp back-dating — the reference's per-sample delay models.

The reference stamps every decoded node with ``rx_time − delay(idx)`` where
``delay`` models how long sample ``idx`` of the frame took to reach the
host (handler_normalnode.cpp:51-68, handler_capsules.cpp:55-76, 272-293,
586-607, 796-817, handler_hqnode.cpp:54-73):

    delay(idx) = sample_filter_delay            # 1 sample duration
               + sample_delay                   # dur >> 1 (sample center)
               + transmission_delay             # frame bytes on the UART at
                                                #   the device's NATIVE baud,
                                                #   or a fixed 100 us dummy
                                                #   for ethernet links
               + linkage_delay                  # device-provided; the
                                                #   reference sets 0
                                                #   (_updateTimingDesc,
                                                #   sl_lidar_driver.cpp:1547)
               + grouping_delay(idx)            # (N-1-idx) * dur for the
                                                #   capsule formats; 0 for
                                                #   normal/HQ nodes

All arithmetic is integer microseconds, exactly like the reference's _u64
math (sample_duration is rounded once, ``+ 0.5``, sl_lidar_driver.cpp:1543).
Within one frame the delay is linear in ``idx`` with slope ``-dur``, so a
whole frame's back-dated timestamps are ``first + idx*dur`` — but *across*
frames the anchor is each frame's own arrival time, which is what keeps
node timestamps exact during RPM transients.

The per-mode sample duration arrives via a timing descriptor the driver
pushes into the decoder on scan start (``_updateTimingDesc``,
sl_lidar_driver.cpp:1538-1554).
"""

from __future__ import annotations

import dataclasses

import numpy as np

from rplidar_ros2_driver_tpu.protocol.constants import (
    ANS_PAYLOAD_BYTES,
    Ans,
)

# Fallback native baud per wire format when the device's native baud is
# unknown — the reference's per-handler "guess channel baudrate" defaults
# (handler_normalnode.cpp:53, handler_capsules.cpp:60,277,592,802).
_FORMAT_DEFAULT_BAUD = {
    Ans.MEASUREMENT: 115200,
    Ans.MEASUREMENT_CAPSULED: 115200,
    Ans.MEASUREMENT_CAPSULED_ULTRA: 256000,
    Ans.MEASUREMENT_DENSE_CAPSULED: 256000,
    Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: 1000000,
    Ans.MEASUREMENT_HQ: 1000000,
}

# Fixed transmission-delay stand-in for non-serial links (the reference's
# "100; //dummy value" ethernet branch in every handler).
ETHERNET_DUMMY_TRANSMISSION_US = 100

# Samples carried per frame of each streaming format (sl_lidar_cmd.h wire
# structs; SURVEY.md §2.2 handler table).
SAMPLES_PER_FRAME = {
    Ans.MEASUREMENT: 1,
    Ans.MEASUREMENT_CAPSULED: 32,
    Ans.MEASUREMENT_CAPSULED_ULTRA: 96,
    Ans.MEASUREMENT_DENSE_CAPSULED: 40,
    Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: 64,
    Ans.MEASUREMENT_HQ: 96,
}

# Formats whose delay model HAS a per-sample grouping term.  Normal nodes
# carry one sample; HQ capsules are pre-formatted device-side and the
# reference applies no grouping delay to them (handler_hqnode.cpp:54-73).
_GROUPED_FORMATS = frozenset(
    {
        Ans.MEASUREMENT_CAPSULED,
        Ans.MEASUREMENT_CAPSULED_ULTRA,
        Ans.MEASUREMENT_DENSE_CAPSULED,
        Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED,
    }
)

LEGACY_SAMPLE_DURATION_US = 476.0  # old A-series (sl_lidar_driver.cpp:1559)


@dataclasses.dataclass(frozen=True)
class TimingDesc:
    """What the driver knows about the active link + scan mode.

    Mirrors the reference's ``SlamtecLidarTimingDesc``: the *native* baud
    of the device model (not necessarily the negotiated link baud) drives
    the transmission-delay estimate, and ``linkage_delay_us`` is a
    device-provided hook the reference currently always sets to 0.
    """

    sample_duration_us: float = LEGACY_SAMPLE_DURATION_US
    native_baudrate: int = 0   # 0: unknown -> per-format default baud
    is_serial: bool = True     # False: ethernet dummy transmission delay
    linkage_delay_us: int = 0  # ref: _timing_desc.linkage_delay_uS = 0

    @property
    def sample_duration_int_us(self) -> int:
        """Rounded integer duration, as the reference stores it
        (``(_u64)(selectedSampleDuration + 0.5f)``, sl_lidar_driver.cpp:1543)."""
        return int(self.sample_duration_us + 0.5)

    def transmission_us(self, ans_type: int) -> int:
        """UART time for one frame of this format: 10 bits/byte (8N1) at
        the device's native baud; fixed dummy for network links."""
        if not self.is_serial:
            return ETHERNET_DUMMY_TRANSMISSION_US
        try:
            at = Ans(ans_type)
        except ValueError:
            return 0
        frame_bytes = ANS_PAYLOAD_BYTES.get(at)
        if frame_bytes is None:
            return 0
        baud = self.native_baudrate or _FORMAT_DEFAULT_BAUD.get(at, 115200)
        return frame_bytes * 10 * 1_000_000 // baud


def sample_delay_us(ans_type: int, timing: TimingDesc, sample_idx: int = 0) -> int:
    """Reference-exact age (integer µs) of sample ``sample_idx`` of a frame
    at the moment the frame is fully received."""
    try:
        at = Ans(ans_type)
    except ValueError:
        return 0
    n = SAMPLES_PER_FRAME.get(at)
    if n is None:
        return 0
    dur = timing.sample_duration_int_us
    grouping = (n - 1 - sample_idx) * dur if at in _GROUPED_FORMATS else 0
    return dur + (dur >> 1) + timing.transmission_us(at) + timing.linkage_delay_us + grouping


def frame_rx_delay_us(ans_type: int, timing: TimingDesc) -> float:
    """Age of the frame's FIRST sample at frame-receive time (the scalar
    per-frame approximation used where per-node stamps are not needed)."""
    return float(sample_delay_us(ans_type, timing, 0))


def frame_sample_times(
    ans_type: int, timing: TimingDesc, rx_ts, n_samples: int | None = None
) -> np.ndarray:
    """Back-dated measurement times (seconds, float64) of every sample of a
    frame received at ``rx_ts``: ``rx_ts − delay(idx)`` for each idx.

    Delay is linear in idx with slope −sample_duration, so this is
    ``(rx_ts − delay(0)) + idx*dur`` — bit-identical to evaluating
    :func:`sample_delay_us` per index (all terms are integer µs).

    ``rx_ts`` may be a scalar (one frame, returns ``(n_samples,)``) or an
    ``(m,)`` array of per-frame anchors (returns ``(m, n_samples)``) — the
    one timestamp formula for both the live decoder and the tests.
    """
    if n_samples is None:
        try:
            n_samples = SAMPLES_PER_FRAME[Ans(ans_type)]
        except (ValueError, KeyError):
            n_samples = 1
    rx = np.asarray(rx_ts, np.float64)
    first = rx - 1e-6 * sample_delay_us(ans_type, timing, 0)
    try:
        grouped = Ans(ans_type) in _GROUPED_FORMATS
    except ValueError:
        grouped = False
    step = 1e-6 * timing.sample_duration_int_us if grouped else 0.0
    return first[..., None] + step * np.arange(n_samples, dtype=np.float64)
