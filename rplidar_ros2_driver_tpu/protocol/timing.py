"""Timestamp back-dating — the reference's per-format delay models.

The reference stamps every decoded node with ``now − delay`` where the
delay models how long the sample took to reach the host: UART
transmission time of the frame, the device-side sample/filter latency,
and (for capsule formats) the grouping delay of samples measured earlier
in the frame (handler_normalnode.cpp:51-68, handler_capsules.cpp:55-76,
272-293, 586-607, 796-817, handler_hqnode.cpp:54-73).  The per-mode
sample duration arrives via a timing descriptor the driver pushes into
the unpackers on scan start (``_updateTimingDesc``,
sl_lidar_driver.cpp:1538-1554).

Here the same model is computed once per received frame (not per node):
the returned delay dates the *first* sample in the frame; downstream
per-node times are ``begin + i * us_per_sample`` (the LaserScan
``time_increment`` contract, ops/laserscan.py).
"""

from __future__ import annotations

import dataclasses

from rplidar_ros2_driver_tpu.protocol.constants import (
    ANS_PAYLOAD_BYTES,
    Ans,
)

# Conservative device-side latency between a sample being measured and it
# entering the UART FIFO (filter + packetization), matching the reference's
# fixed per-format constants.
_LINKAGE_DELAY_US = {
    Ans.MEASUREMENT: 20,
    Ans.MEASUREMENT_CAPSULED: 45,
    Ans.MEASUREMENT_CAPSULED_ULTRA: 45,
    Ans.MEASUREMENT_DENSE_CAPSULED: 45,
    Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: 45,
    Ans.MEASUREMENT_HQ: 45,
}

# Samples carried per frame of each streaming format (sl_lidar_cmd.h wire
# structs; SURVEY.md §2.2 handler table).
SAMPLES_PER_FRAME = {
    Ans.MEASUREMENT: 1,
    Ans.MEASUREMENT_CAPSULED: 32,
    Ans.MEASUREMENT_CAPSULED_ULTRA: 96,
    Ans.MEASUREMENT_DENSE_CAPSULED: 40,
    Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: 64,
    Ans.MEASUREMENT_HQ: 96,
}

LEGACY_SAMPLE_DURATION_US = 476.0  # old A-series (sl_lidar_driver.cpp:1559)


@dataclasses.dataclass(frozen=True)
class TimingDesc:
    """What the driver knows about the active link + scan mode."""

    sample_duration_us: float = LEGACY_SAMPLE_DURATION_US
    baudrate: int = 0          # 0: non-serial link (TCP/UDP) -> no UART delay
    is_serial: bool = True

    def transmission_us(self, frame_bytes: int) -> float:
        """UART time for the frame: 10 bits/byte (8N1) at the link baud."""
        if not self.is_serial or self.baudrate <= 0:
            return 0.0
        return frame_bytes * 10.0 * 1e6 / self.baudrate


def frame_rx_delay_us(ans_type: int, timing: TimingDesc) -> float:
    """Age of the frame's FIRST sample at the moment the frame is fully
    received: all samples in the frame were measured before it could be
    sent, so the first one is (n_samples × sample_duration) old, plus the
    wire time and the fixed linkage latency."""
    try:
        at = Ans(ans_type)
    except ValueError:
        return 0.0
    n = SAMPLES_PER_FRAME.get(at)
    if n is None:
        return 0.0
    frame_bytes = ANS_PAYLOAD_BYTES.get(at, 0)
    grouping_us = n * timing.sample_duration_us
    return timing.transmission_us(frame_bytes) + grouping_us + _LINKAGE_DELAY_US.get(at, 0)
