"""Device-side configuration protocol (GET/SET_LIDAR_CONF).

The typed key space of the reference (sl_lidar_cmd.h:289-317; getLidarConf
sl_lidar_driver.cpp:1261-1304, setLidarConf :1215-1259) and the derived
scan-mode getters (:1199-1379): a GET request carries ``u32 key [+ extra]``
and the answer echoes the key followed by the data; scan-mode metadata is
keyed by ``u16 mode`` appended as the payload.
"""

from __future__ import annotations

import dataclasses
import struct
from typing import Optional

from rplidar_ros2_driver_tpu.models.tables import ScanMode
from rplidar_ros2_driver_tpu.protocol.constants import Ans, Cmd, ConfKey
from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine


def get_conf(
    engine: CommandEngine, key: int, extra: bytes = b"", timeout_s: float = 1.0
) -> Optional[bytes]:
    """Raw GET_LIDAR_CONF: returns the data after the echoed key, or None."""
    payload = struct.pack("<I", key) + extra
    ans = engine.request(Cmd.GET_LIDAR_CONF, Ans.GET_LIDAR_CONF, payload, timeout_s)
    if ans is None or len(ans) < 4:
        return None
    echoed = struct.unpack_from("<I", ans)[0]
    if echoed != key:
        return None
    return ans[4:]


def set_conf(
    engine: CommandEngine, key: int, data: bytes = b"", timeout_s: float = 1.0
) -> bool:
    """SET_LIDAR_CONF; answer is ``u32 result`` (0 == ok)."""
    payload = struct.pack("<I", key) + data
    ans = engine.request(Cmd.SET_LIDAR_CONF, Ans.SET_LIDAR_CONF, payload, timeout_s)
    if ans is None or len(ans) < 4:
        return False
    return struct.unpack_from("<I", ans)[0] == 0


def _mode_extra(mode_id: int) -> bytes:
    return struct.pack("<H", mode_id)


def get_scan_mode_count(engine: CommandEngine) -> Optional[int]:
    data = get_conf(engine, ConfKey.SCAN_MODE_COUNT)
    return struct.unpack_from("<H", data)[0] if data and len(data) >= 2 else None


def get_typical_mode(engine: CommandEngine) -> Optional[int]:
    data = get_conf(engine, ConfKey.SCAN_MODE_TYPICAL)
    return struct.unpack_from("<H", data)[0] if data and len(data) >= 2 else None


def get_mode_us_per_sample(engine: CommandEngine, mode_id: int) -> Optional[float]:
    # u32 Q8 fixed point (ref :1317-1331)
    data = get_conf(engine, ConfKey.SCAN_MODE_US_PER_SAMPLE, _mode_extra(mode_id))
    return struct.unpack_from("<I", data)[0] / 256.0 if data and len(data) >= 4 else None


def get_mode_max_distance(engine: CommandEngine, mode_id: int) -> Optional[float]:
    # u32 Q8 metres (ref :1333-1347)
    data = get_conf(engine, ConfKey.SCAN_MODE_MAX_DISTANCE, _mode_extra(mode_id))
    return struct.unpack_from("<I", data)[0] / 256.0 if data and len(data) >= 4 else None


def get_mode_ans_type(engine: CommandEngine, mode_id: int) -> Optional[int]:
    data = get_conf(engine, ConfKey.SCAN_MODE_ANS_TYPE, _mode_extra(mode_id))
    return data[0] if data else None


def get_mode_name(engine: CommandEngine, mode_id: int) -> Optional[str]:
    data = get_conf(engine, ConfKey.SCAN_MODE_NAME, _mode_extra(mode_id))
    return data.split(b"\x00", 1)[0].decode("ascii", "replace") if data else None


# ---------------------------------------------------------------------------
# motor / network conf getters (sl_lidar_driver.cpp:887-955, 1023-1056,
# 1163-1174)
# ---------------------------------------------------------------------------


@dataclasses.dataclass(frozen=True)
class MotorInfo:
    """LidarMotorInfo analog (min/max/desired rotation speed)."""

    min_speed: int
    max_speed: int
    desired_speed: int


@dataclasses.dataclass(frozen=True)
class IpConf:
    """Static-IP configuration triple (sl_lidar_ip_conf_t: 3 x 4 bytes)."""

    ip: tuple[int, int, int, int]
    netmask: tuple[int, int, int, int]
    gateway: tuple[int, int, int, int]

    def to_payload(self) -> bytes:
        return bytes(self.ip) + bytes(self.netmask) + bytes(self.gateway)

    @staticmethod
    def from_payload(data: bytes) -> "IpConf":
        if len(data) < 12:
            raise ValueError(f"ip conf payload too short: {len(data)}")
        return IpConf(tuple(data[0:4]), tuple(data[4:8]), tuple(data[8:12]))


def get_desired_speed(engine: CommandEngine) -> Optional[tuple[int, int]]:
    """(rpm, pwm_ref) from DESIRED_ROT_FREQ (getDesiredSpeed :1163-1174)."""
    data = get_conf(engine, ConfKey.DESIRED_ROT_FREQ)
    if data is None or len(data) < 4:
        return None
    return struct.unpack_from("<HH", data)


def get_motor_info(engine: CommandEngine, pwm_ctrl: bool = False) -> Optional[MotorInfo]:
    """min/max/desired rotation speed (getMotorInfo :1023-1056); the desired
    field is the PWM reference when the motor is PWM-driven."""
    lo = get_conf(engine, ConfKey.MIN_ROT_FREQ)
    hi = get_conf(engine, ConfKey.MAX_ROT_FREQ)
    desired = get_desired_speed(engine)
    if lo is None or hi is None or desired is None or len(lo) < 2 or len(hi) < 2:
        return None
    rpm, pwm_ref = desired
    return MotorInfo(
        min_speed=struct.unpack_from("<H", lo)[0],
        max_speed=struct.unpack_from("<H", hi)[0],
        desired_speed=pwm_ref if pwm_ctrl else rpm,
    )


def get_mac_addr(engine: CommandEngine) -> Optional[bytes]:
    """6-byte MAC (getDeviceMacAddr :937-955)."""
    data = get_conf(engine, ConfKey.LIDAR_MAC_ADDR)
    return data[:6] if data and len(data) >= 6 else None


def get_ip_conf(engine: CommandEngine) -> Optional[IpConf]:
    """Static IP/netmask/gateway; the GET carries a 2-byte reserved extra
    for backward compatibility (getLidarIpConf :896-913)."""
    data = get_conf(engine, ConfKey.LIDAR_STATIC_IP_ADDR, extra=b"\x00\x00")
    if data is None or len(data) < 12:
        return None
    return IpConf.from_payload(data)


def set_ip_conf(engine: CommandEngine, conf: IpConf) -> bool:
    """SET_LIDAR_CONF of the static-IP key (setLidarIpConf :887-894)."""
    return set_conf(engine, ConfKey.LIDAR_STATIC_IP_ADDR, conf.to_payload())


def get_mode_metadata(engine: CommandEngine, mode_id: int) -> Optional[ScanMode]:
    """Full metadata for ONE mode id — the four-getter query block shared
    by getAllSupportedScanModes (sl_lidar_driver.cpp:529-549) and
    startScanExpress's single-mode lookup (:702-715).  None when any
    field is missing."""
    us = get_mode_us_per_sample(engine, mode_id)
    dist = get_mode_max_distance(engine, mode_id)
    ans = get_mode_ans_type(engine, mode_id)
    name = get_mode_name(engine, mode_id)
    if None in (us, dist, ans, name):
        return None
    return ScanMode(
        id=mode_id, us_per_sample=us, max_distance=dist, ans_type=ans, name=name
    )


def enumerate_scan_modes(engine: CommandEngine) -> list[ScanMode]:
    """All supported modes with metadata (ref getAllSupportedScanModes
    sl_lidar_driver.cpp:518-554)."""
    count = get_scan_mode_count(engine)
    if count is None:
        return []
    modes: list[ScanMode] = []
    for mode_id in range(count):
        mode = get_mode_metadata(engine, mode_id)
        if mode is not None:
            modes.append(mode)
    return modes
