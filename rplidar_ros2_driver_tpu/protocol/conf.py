"""Device-side configuration protocol (GET/SET_LIDAR_CONF).

The typed key space of the reference (sl_lidar_cmd.h:289-317; getLidarConf
sl_lidar_driver.cpp:1261-1304, setLidarConf :1215-1259) and the derived
scan-mode getters (:1199-1379): a GET request carries ``u32 key [+ extra]``
and the answer echoes the key followed by the data; scan-mode metadata is
keyed by ``u16 mode`` appended as the payload.
"""

from __future__ import annotations

import struct
from typing import Optional

from rplidar_ros2_driver_tpu.models.tables import ScanMode
from rplidar_ros2_driver_tpu.protocol.constants import Ans, Cmd, ConfKey
from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine


def get_conf(
    engine: CommandEngine, key: int, extra: bytes = b"", timeout_s: float = 1.0
) -> Optional[bytes]:
    """Raw GET_LIDAR_CONF: returns the data after the echoed key, or None."""
    payload = struct.pack("<I", key) + extra
    ans = engine.request(Cmd.GET_LIDAR_CONF, Ans.GET_LIDAR_CONF, payload, timeout_s)
    if ans is None or len(ans) < 4:
        return None
    echoed = struct.unpack_from("<I", ans)[0]
    if echoed != key:
        return None
    return ans[4:]


def set_conf(
    engine: CommandEngine, key: int, data: bytes = b"", timeout_s: float = 1.0
) -> bool:
    """SET_LIDAR_CONF; answer is ``u32 result`` (0 == ok)."""
    payload = struct.pack("<I", key) + data
    ans = engine.request(Cmd.SET_LIDAR_CONF, Ans.SET_LIDAR_CONF, payload, timeout_s)
    if ans is None or len(ans) < 4:
        return False
    return struct.unpack_from("<I", ans)[0] == 0


def _mode_extra(mode_id: int) -> bytes:
    return struct.pack("<H", mode_id)


def get_scan_mode_count(engine: CommandEngine) -> Optional[int]:
    data = get_conf(engine, ConfKey.SCAN_MODE_COUNT)
    return struct.unpack_from("<H", data)[0] if data and len(data) >= 2 else None


def get_typical_mode(engine: CommandEngine) -> Optional[int]:
    data = get_conf(engine, ConfKey.SCAN_MODE_TYPICAL)
    return struct.unpack_from("<H", data)[0] if data and len(data) >= 2 else None


def get_mode_us_per_sample(engine: CommandEngine, mode_id: int) -> Optional[float]:
    # u32 Q8 fixed point (ref :1317-1331)
    data = get_conf(engine, ConfKey.SCAN_MODE_US_PER_SAMPLE, _mode_extra(mode_id))
    return struct.unpack_from("<I", data)[0] / 256.0 if data and len(data) >= 4 else None


def get_mode_max_distance(engine: CommandEngine, mode_id: int) -> Optional[float]:
    # u32 Q8 metres (ref :1333-1347)
    data = get_conf(engine, ConfKey.SCAN_MODE_MAX_DISTANCE, _mode_extra(mode_id))
    return struct.unpack_from("<I", data)[0] / 256.0 if data and len(data) >= 4 else None


def get_mode_ans_type(engine: CommandEngine, mode_id: int) -> Optional[int]:
    data = get_conf(engine, ConfKey.SCAN_MODE_ANS_TYPE, _mode_extra(mode_id))
    return data[0] if data else None


def get_mode_name(engine: CommandEngine, mode_id: int) -> Optional[str]:
    data = get_conf(engine, ConfKey.SCAN_MODE_NAME, _mode_extra(mode_id))
    return data.split(b"\x00", 1)[0].decode("ascii", "replace") if data else None


def enumerate_scan_modes(engine: CommandEngine) -> list[ScanMode]:
    """All supported modes with metadata (ref getAllSupportedScanModes
    sl_lidar_driver.cpp:518-554)."""
    count = get_scan_mode_count(engine)
    if count is None:
        return []
    modes: list[ScanMode] = []
    for mode_id in range(count):
        us = get_mode_us_per_sample(engine, mode_id)
        dist = get_mode_max_distance(engine, mode_id)
        ans = get_mode_ans_type(engine, mode_id)
        name = get_mode_name(engine, mode_id)
        if None in (us, dist, ans, name):
            continue
        modes.append(
            ScanMode(id=mode_id, us_per_sample=us, max_distance=dist, ans_type=ans, name=name)
        )
    return modes
