"""Pluggable ScanFilterChain — the seam between wrapper and publisher.

The BASELINE.json north star: a filter chain inserted between the driver
wrapper and the ``/scan`` publisher, backend-selected via the parameter
surface (``filter_backend: cpu | tpu``).  ``cpu``/``tpu`` pick the JAX
backend the fused ``filter_step`` program runs on; the host FSM and
publishing stay identical either way.

Also owns the framework's checkpoint surface: the rolling window and voxel
accumulator are real state (unlike the reference's stateless pipeline), so
``snapshot``/``restore`` let a lifecycle deactivate/activate cycle — or a
RESETTING recovery — either preserve or deterministically reset the window
(SURVEY.md §5 checkpoint/resume note).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Optional

import jax
import jax.numpy as jnp
import numpy as np

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.types import ScanBatch
from rplidar_ros2_driver_tpu.utils.fetch import bounded_fetch
from rplidar_ros2_driver_tpu.ops.filters import (
    FilterConfig,
    FilterOutput,
    FilterState,
    counted_filter_step_wire,
    filter_step,
    pack_host_scan_counted,
    pin_inc_lowering,
    recompute_median_sorted,
    unpack_output_wire,
)


def pick_device(backend: str):
    # local_devices, not devices: in a multi-controller job the global
    # list starts with process 0's devices, and device_put to another
    # process's device raises "Cannot copy array to non-addressable
    # device" — the single-stream chain is a per-host object.  Shared
    # with the fused ingest engine (driver/ingest.py) so both backends
    # resolve the same device from the same parameter.
    if backend == "cpu":
        return jax.local_devices(backend="cpu")[0]
    # "tpu": first local accelerator if present, else fall back to host
    for d in jax.local_devices():
        if d.platform != "cpu":
            return d
    return jax.local_devices()[0]


_pick_device = pick_device  # compatibility alias (pre-seam internal name)


DEFAULT_BEAMS = 2048


def resolve_median_backend(
    requested: str,
    platform: Optional[str] = None,
    window: Optional[int] = None,
) -> str:
    """Resolve the ``auto`` median backend for a device platform and
    window length.  Explicit requests — including "inc", the
    incremental sliding median (sorted-window carried state, O(W) per
    revolution) — pass through.

    The mapping is evidence-gated on committed measurement artifacts
    (docs/BENCHMARKS.md "standing decision procedure"), one bar for
    every entry:

    - TPU: pallas bitonic network (device-resident A/B 2.17x over xla
      at W=64; 2.1-2.5x at deeper windows).  Window-aware because the
      O(W) incremental arm CLOSES with depth on-chip — 0.29x of pallas
      at W=64 but 0.95x at W=256 (2026-07-31 three-arm) — so the
      crossover, if the W=512 artifact confirms it, lands here as a
      window threshold; until that artifact exists, pallas at every
      depth.
    - CPU: inc (3.8x over the sort on the full W=64 step, 2026-07-31;
      bit-exact parity suite in tests/test_filters.py).
    - anything else (GPU): xla sort until it has its own measurement.
    """
    if requested != "auto":
        return requested
    if platform is None:
        platform = jax.default_backend()
    del window  # no measured crossover yet — threshold lands here
    if platform == "tpu":
        return "pallas"
    return "inc" if platform == "cpu" else "xla"


def resolve_ingest_backend(requested: str, platform: Optional[str] = None) -> str:
    """Resolve the ``auto`` ingest backend (mirrors the sibling
    resolvers; explicit requests pass through).

    ``host`` is the golden path: BatchScanDecoder (CPU-pinned unpack) +
    ScanAssembler + the chain's packed one-transfer upload.  ``fused``
    is the device-resident single-dispatch path (ops/ingest.py +
    driver/ingest.FusedIngest) — bit-exact against the host path
    (tests/test_fused_ingest.py), with the ingest-overhead A/B recorded
    per rig by ``bench.py --config 9`` (artifacts/ingest_ab_cpu.json,
    docs/BENCHMARKS.md: on a linkless CPU rig the shared chain step
    dominates both arms and the ratio sits near 1; the structural win is
    per link round-trip, so it materializes on-device), but without the
    RawNodeHolder interval tap or the chain's checkpoint surface.
    ``auto`` stays host until an on-chip artifact clears the standing
    decision bar for the TPU mapping."""
    if requested != "auto":
        return requested
    del platform
    return "host"


def resolve_fleet_ingest_backend(
    requested: str, platform: Optional[str] = None
) -> str:
    """Resolve the ``auto`` FLEET ingest backend (mirrors
    :func:`resolve_ingest_backend`; explicit requests pass through).

    ``host`` is the golden fleet path: per-stream host decode + newest-
    revolution stacking ahead of the one batched sharded filter dispatch
    per tick — N host decodes per tick.  ``fused`` is the fleet-fused
    single-dispatch path (driver/ingest.FleetFusedIngest): bytes from
    every stream to N filter outputs in ONE compiled dispatch per tick,
    O(1) dispatches/transfers independent of fleet size (bit-exact vs N
    independent host paths, tests/test_fleet_fused_ingest.py; structural
    counts asserted by ``bench.py --smoke-fleet-ingest``).  ``auto``
    stays host until an on-chip `fleet_ingest_ab` artifact clears the
    standing decision bar (docs/BENCHMARKS.md); scripts/decide_backends.py
    reads that evidence and recommends the flip mechanically — on a
    linkless CPU rig the shared batched filter tick dominates both arms
    and the wall-time ratio sits near 1 (artifacts/fleet_ingest_ab_cpu
    .json), so the CPU artifact can never clear the bar by itself."""
    if requested != "auto":
        return requested
    del platform
    return "host"


def resolve_resample_backend(requested: str, platform: Optional[str] = None) -> str:
    """Resolve the ``auto`` streaming-step resampler per device platform
    (mirrors :func:`resolve_median_backend`; explicit requests pass
    through).  Evidence source: scripts/step_ablation.py's full_scatter
    vs full_dense A/B on the real counted step.  CPU: scatter (the dense
    one-hot tile materializes a beams x capacity mask per scan, which the
    host backend pays for).  TPU: scatter until the on-chip ablation
    artifact decides otherwise — the ~2x dense win measured so far is
    from the FUSED replay path (K scans amortize the tile), not the
    K=1 streaming step (docs/BENCHMARKS.md)."""
    if requested != "auto":
        return requested
    return "scatter"


def resolve_voxel_backend(requested: str, platform: Optional[str] = None) -> str:
    """Resolve the ``auto`` voxel-accumulation kernel per device platform
    (mirrors :func:`resolve_resample_backend`).  "scatter" is the
    jnp ``.at[].add`` histogram; "matmul" is the one-hot bf16 einsum
    with f32 accumulation (exact counts — ops/filters.voxel_hits_matmul)
    that rides the MXU where scatters serialize.  CPU: scatter (the
    einsum materializes two beams x grid one-hots the host pays for).
    TPU: scatter until the on-chip ablation artifact
    (scripts/step_ablation.py, full_voxel_matmul case) decides
    otherwise — same evidence bar the other two backends met."""
    if requested != "auto":
        return requested
    return "scatter"


def config_from_params(
    params: DriverParams,
    beams: int = DEFAULT_BEAMS,
    platform: Optional[str] = None,
) -> FilterConfig:
    """The one params -> FilterConfig mapping, shared by the single-stream
    chain and the multi-stream sharded service so their filtering behavior
    (and checkpoint layouts) cannot drift.  ``platform`` resolves the
    ``auto`` median backend (defaults to the default JAX backend)."""
    chain = set(params.filter_chain)
    return FilterConfig(
        window=params.filter_window,
        beams=beams,
        grid=params.voxel_grid_size,
        cell_m=params.voxel_cell_m,
        range_min_m=params.range_clip_min_m,
        range_max_m=params.range_clip_max_m,
        intensity_min=params.intensity_min,
        enable_clip="clip" in chain,
        enable_median="median" in chain,
        enable_voxel="voxel" in chain,
        # the lowering is pinned HERE, while the target platform is
        # known: inside jit, inc_median's fallback can only consult the
        # process default backend — wrong for an explicit CPU chain/mesh
        # on a TPU-default host (the same hazard replay.py re-resolves
        # "auto" against the mesh platform to avoid)
        median_backend=pin_inc_lowering(
            resolve_median_backend(
                params.median_backend, platform, window=params.filter_window
            ),
            platform,
        ),
        resample_backend=resolve_resample_backend(
            params.resample_backend, platform
        ),
        voxel_backend=resolve_voxel_backend(params.voxel_backend, platform),
    )


class ScanFilterChain:
    """Stateful host wrapper around the fused filter_step program.

    Thread-safety: the hot-path step DONATES the state buffers (they are
    deleted the moment a step is dispatched), so a concurrent
    ``snapshot()`` — e.g. a checkpoint requested while the scan thread
    streams — would read deleted arrays and raise.  Every method that
    reads or swaps the state (process/process_raw/snapshot/restore)
    serializes on one lock, uncontended in steady state (one scan
    thread).  The ``state`` property is the one unsynchronized accessor
    (debug/tests); see its docstring.
    """

    def __init__(
        self,
        params: DriverParams,
        beams: int = DEFAULT_BEAMS,
        *,
        warmup: bool = True,
        capacity: Optional[int] = None,
    ) -> None:
        from rplidar_ros2_driver_tpu.utils.backend import (
            maybe_enable_compilation_cache,
        )

        maybe_enable_compilation_cache(
            getattr(params, "compilation_cache_dir", None)
        )
        self.device = _pick_device(params.filter_backend)
        self.cfg = config_from_params(params, beams, platform=self.device.platform)
        self.backend = params.filter_backend
        # wire capacity (nodes per packed upload): MAX_SCAN_NODES holds any
        # revolution; a device whose densest mode is known smaller (S2
        # DenseBoost <= ~3300 nodes/rev at 600 RPM) can halve the per-scan
        # transfer by passing e.g. 4096.  An oversized revolution (e.g.
        # the motor slowed while the sample rate held) is truncated
        # head-keep like the assembler's 8192-node overflow cap, never
        # raised — a crash would take down the scan thread mid-stream.
        self.capacity = capacity
        # bound on the pipelined collect's device->host fetch (see
        # _collect); 0/None = unbounded
        self.collect_timeout_s = params.collect_timeout_s
        self._overflow_warned = False
        self._lock = threading.Lock()
        self._state = jax.device_put(
            FilterState.for_config(self.cfg), self.device
        )
        # double-buffered publish seam: the not-yet-fetched wire output of
        # the newest dispatched step (process_raw_pipelined); _epoch
        # advances on restore/reset so a failed dispatch cannot re-stash
        # a pre-restore output
        self._pending_wire: Optional[jax.Array] = None
        self._epoch = 0
        # seconds the newest pipelined collect spent blocking on the
        # pending output's D2H copy (diagnostic for latency artifacts)
        self.last_collect_wait_s = 0.0
        # seconds the newest pipelined tick spent in device_put + step
        # dispatch: through a remote link the upload alone can cost ms
        # (link_put_ms has measured 1-8), so the latency artifact can
        # split the residual tail into link-priced upload/dispatch vs
        # pure host-side pack time
        self.last_upload_dispatch_s = 0.0
        if warmup:
            self.precompile()

    def precompile(self) -> None:
        """Compile the hot-path program now (≈1.4 s on a TPU) so the first
        real revolution doesn't pay it — the chain's analog of the decode
        engine's bucket precompile during motor warm-up.  Runs one
        zero-count step through the production wire program: on a FRESH
        state the all-masked scan writes only values the state already
        holds (+inf range row, zero intensities/hits), and the
        cursor/filled advance is rolled back, so state is exactly as if
        this never ran.  On a state that has already absorbed scans the
        warmup step would overwrite the current ring row, so it is
        skipped — the program is necessarily compiled by then anyway."""
        with self._lock:
            if int(np.asarray(self._state.filled)) != 0:
                return
            zeros = np.zeros(0, np.int32)
            buf = pack_host_scan_counted(zeros, zeros, zeros, None, self.capacity)
            packed = jax.device_put(buf, self.device)
            state, _ = counted_filter_step_wire(self._state, packed, self.cfg)
            # the step donates its state argument: rebuild from the stepped
            # arrays with the cursor/filled advance undone
            self._state = FilterState(
                range_window=state.range_window,
                inten_window=state.inten_window,
                hit_window=state.hit_window,
                voxel_acc=state.voxel_acc,
                cursor=state.cursor * 0,
                filled=state.filled * 0,
                # the zero-count warmup replaced an all-inf ring row with
                # an all-inf row, so the stepped sorted window is still
                # the sorted view of the rolled-back ring
                median_sorted=state.median_sorted,
            )

    def _pack_capped(self, angle_q14, dist_q2, quality, flag):
        """Pack one scan at ``self.capacity``, truncating an oversized
        revolution head-keep (the assembler's overflow policy) with a
        one-time warning instead of raising out of the scan thread."""
        n = self.capacity
        if n is not None and len(angle_q14) > n:
            if not self._overflow_warned:
                logging.getLogger("rplidar_tpu.chain").warning(
                    "revolution of %d nodes exceeds wire capacity %d; "
                    "truncating (head-keep) — raise the chain's capacity "
                    "if this device/mode can legitimately exceed it",
                    len(angle_q14), n,
                )
                self._overflow_warned = True
            angle_q14, dist_q2, quality = angle_q14[:n], dist_q2[:n], quality[:n]
            flag = flag[:n] if flag is not None else None
        return pack_host_scan_counted(angle_q14, dist_q2, quality, flag, n)

    def process(self, batch: ScanBatch) -> FilterOutput:
        batch = jax.device_put(batch, self.device)
        with self._lock:
            self._state, out = filter_step(self._state, batch, self.cfg)
        return out

    def process_raw(self, angle_q14, dist_q2, quality, flag=None) -> FilterOutput:
        """Streaming ingest of raw host arrays via the packed one-transfer path.

        This is the production hot path: per revolution, exactly one
        host->device transfer (bit-packed (3, N) uint16 with the node
        count folded into the reserved last slot — 6 bytes/point, no
        separate count scalar), one donated step dispatch, and one
        device->host fetch (the fused flat output vector).  Returns a
        numpy-backed FilterOutput.
        """
        buf = self._pack_capped(angle_q14, dist_q2, quality, flag)
        packed = jax.device_put(buf, self.device)
        with self._lock:
            self._state, wire = counted_filter_step_wire(self._state, packed, self.cfg)
        # bounded like the pipelined collect: the synchronous publish is
        # this framework's analog of the reference's timed grab
        return self._collect(wire)

    def process_raw_pipelined(
        self, angle_q14, dist_q2, quality, flag=None
    ) -> Optional[FilterOutput]:
        """Pipelined publish seam: dispatch THIS revolution's step, then
        fetch and return the PREVIOUS revolution's output — one revolution
        of bounded staleness in exchange for never waiting on device
        compute at publish time (the device-side mirror of the reference's
        double-buffered ScanDataHolder, sl_lidar_driver.cpp:237-371).

        The returned output's step finished — and its device->host copy
        was STARTED (``copy_to_host_async``) — during the previous
        inter-revolution gap, so by the time this call collects it the
        bytes are host-side and the publish pays neither device compute
        nor a blocking transfer round-trip (through a remote-attached
        device the blocking-fetch RTT alone can exceed the whole latency
        budget; the async copy buys it back).  The pending output is
        collected BEFORE this revolution's upload/dispatch: publishing
        N-1 needs nothing from N, and issuing fresh host->device traffic
        first would race the landing D2H bytes on a single-channel
        remote link.  Returns None on the first call after a start/reset
        (nothing pending); :meth:`flush_pipelined` drains the final
        pending output when the stream stops.
        """
        buf = self._pack_capped(angle_q14, dist_q2, quality, flag)
        # not flush_pipelined(): the wire handle must stay reachable so a
        # failed upload/dispatch below can re-stash it for the drain
        with self._lock:
            pending, self._pending_wire = self._pending_wire, None
            epoch = self._epoch
        out = None
        self.last_collect_wait_s = 0.0
        if pending is not None:
            t_collect = time.perf_counter()
            try:
                out = self._collect(pending)
                # how long the collect blocked waiting for the async
                # D2H copy to land: ~0 when the copy beat the
                # inter-revolution gap (local chip: always), up to one
                # link RTT when it didn't (remote-attach tunnel on a
                # bad day) — recorded so latency artifacts can separate
                # framework time from link weather
                self.last_collect_wait_s = time.perf_counter() - t_collect
            except Exception:
                # the device->host fetch of N-1 itself failed (same
                # transient-link fault class as the dispatch path below):
                # re-stash the wire so flush_pipelined can retry the
                # fetch, instead of losing the revolution
                self._restash_pending(pending, epoch)
                raise
        # reset before the attempt (like last_collect_wait_s above): a
        # failed upload/dispatch must not leave the previous tick's
        # duration attributed to this one
        self.last_upload_dispatch_s = 0.0
        t_dispatch = time.perf_counter()
        try:
            packed = jax.device_put(buf, self.device)
            with self._lock:
                self._state, wire = counted_filter_step_wire(
                    self._state, packed, self.cfg
                )
                try:
                    wire.copy_to_host_async()
                except Exception:
                    pass  # backend without async D2H: the later fetch blocks
                self._pending_wire = wire
            self.last_upload_dispatch_s = time.perf_counter() - t_dispatch
        except Exception:
            # upload/dispatch of N failed AFTER N-1 was popped: re-stash
            # the wire so the caller's drain (flush_pipelined) can still
            # publish N-1 instead of silently losing it
            if pending is not None:
                self._restash_pending(pending, epoch)
            raise
        with self._lock:
            if self._epoch != epoch:
                # a restore/reset raced in after the pop: the popped
                # output is pre-restore and must not be published
                out = None
        return out

    def _restash_pending(self, pending, epoch: int) -> None:
        """Put a popped-but-unpublished wire back for the drain — unless a
        restore/reset moved the epoch meanwhile (pre-restore outputs must
        stay dropped) or a newer dispatch already stashed its own."""
        with self._lock:
            if self._pending_wire is None and self._epoch == epoch:
                self._pending_wire = pending

    def _collect(self, wire) -> FilterOutput:
        """Fetch + unpack one wire output, bounded by
        ``collect_timeout_s`` when set (utils/fetch.bounded_fetch) —
        the analog of the reference's timed grab
        (sl_lidar_driver.h:332).  On expiry a TimeoutError surfaces to
        the caller's existing transient-fault path (re-stash + raise ->
        FSM recovery, which drains once and then resets), so a wedged
        link costs at most one stranded fetch thread per recovery
        cycle, not per tick."""
        return bounded_fetch(
            lambda: unpack_output_wire(wire, self.cfg),
            self.collect_timeout_s,
            "publish collect (device->host)",
        )

    def discard_pipelined(self) -> None:
        """Drop the pending pipelined output without fetching it.

        For callers whose failure policy is drop-not-retry (the node's
        drain): flush_pipelined re-stashes on a fetch fault/timeout so
        that retrying callers don't lose the revolution, but a caller
        that has already consumed its publish metadata must discard the
        orphaned wire or it would linger (and a resumed stream would
        spend a fetch materializing stale data)."""
        with self._lock:
            self._pending_wire = None

    def flush_pipelined(self) -> Optional[FilterOutput]:
        """Fetch the last dispatched step's output (the one revolution
        still in flight when the stream stops), or None.  Bounded by
        ``collect_timeout_s`` when set; on expiry the wire is re-stashed
        (same contract as the streaming collect) so a later drain can
        retry, and the TimeoutError surfaces to the caller."""
        with self._lock:
            pending, self._pending_wire = self._pending_wire, None
            epoch = self._epoch
        if pending is None:
            return None
        try:
            return self._collect(pending)
        except Exception:
            self._restash_pending(pending, epoch)
            raise

    # -- checkpoint surface -------------------------------------------------

    def snapshot(self) -> dict[str, np.ndarray]:
        """Host copy of the rolling window + accumulator.

        Safe against the streaming thread: a device-side copy is taken
        under the lock (cheap — on-device), then the lock is released
        before the host gather, so a checkpoint never stalls the hot
        path for the duration of a device->host fetch."""
        with self._lock:
            state = jax.tree_util.tree_map(jnp.copy, self._state)
        # median_sorted is DERIVED state (the sorted view of
        # range_window) — excluded so the snapshot format is identical
        # across median backends and restore recomputes it as needed
        return {
            k: np.asarray(v)
            for k, v in vars(state).items()
            if k != "median_sorted"
        }

    @staticmethod
    def _shape_mismatch(
        snap: dict[str, np.ndarray], window: int, beams: int, grid: int
    ) -> Optional[tuple[dict, dict]]:
        """(got, expected) when incompatible, None when compatible.
        Host-side — no device transfer.  The derived median_sorted key
        (present in no current snapshot, tolerated for forward compat)
        is ignored."""
        expected = FilterState.shapes(window, beams, grid)
        got = {
            k: tuple(np.asarray(v).shape)
            for k, v in snap.items()
            if k != "median_sorted"
        }
        return None if expected == got else (got, expected)

    @classmethod
    def snapshot_compatible(
        cls, params: DriverParams, snap: dict[str, np.ndarray], beams: Optional[int] = None
    ) -> bool:
        """Would a chain built from ``params`` accept this snapshot?  The
        single source of truth for pre-validation (node.load_checkpoint)."""
        return (
            cls._shape_mismatch(
                snap,
                params.filter_window,
                beams if beams is not None else DEFAULT_BEAMS,
                params.voxel_grid_size,
            )
            is None
        )

    def compatible(self, snap: dict[str, np.ndarray]) -> bool:
        return (
            self._shape_mismatch(snap, self.cfg.window, self.cfg.beams, self.cfg.grid)
            is None
        )

    def restore(self, snap: Optional[dict[str, np.ndarray]]) -> bool:
        """Restore a snapshot, or reset deterministically when None.

        A snapshot taken under different chain parameters (window/beams/
        grid changed across a cleanup->configure cycle) is incompatible
        with the compiled step; restoring it would crash the hot path, so
        it is rejected with a warning — the chain's CURRENT state is left
        untouched.  Returns True when the snapshot was restored, False
        when it wasn't (cold reset for None, or rejected mismatch).
        """
        if snap is not None:
            mismatch = self._shape_mismatch(
                snap, self.cfg.window, self.cfg.beams, self.cfg.grid
            )
            if mismatch is not None:
                got, expected = mismatch
                logging.getLogger("rplidar_tpu.chain").warning(
                    "rejecting incompatible filter snapshot (%s != %s)", got, expected
                )
                return False
        # build the new device state OUTSIDE the lock (the H2D upload is
        # several MB at default geometry); only the reference swap — O(1)
        # — holds the streaming lock
        with_sorted = self.cfg.median_backend.startswith("inc")
        if snap is None:
            fresh = jax.device_put(
                FilterState.for_config(self.cfg), self.device
            )
            with self._lock:
                self._state = fresh
                self._pending_wire = None  # pre-reset output: never publish
                self._epoch += 1
            return False
        core = {k: v for k, v in snap.items() if k != "median_sorted"}
        restored = jax.device_put(
            FilterState(
                **core,
                # derived state: recompute from the restored ring so any
                # snapshot (legacy, cross-backend) restores under "inc"
                median_sorted=(
                    recompute_median_sorted(core["range_window"])
                    if with_sorted else None
                ),
            ),
            self.device,
        )
        with self._lock:
            self._state = restored
            self._pending_wire = None
            self._epoch += 1
        return True

    def reset(self) -> None:
        self.restore(None)

    @property
    def state(self) -> FilterState:
        """The live device state — UNSYNCHRONIZED debug/test accessor.

        The arrays returned are the ones the next (donating) step will
        consume; reading them concurrently with streaming can observe
        deleted buffers.  Use :meth:`snapshot` from any thread that does
        not own the streaming loop."""
        return self._state
