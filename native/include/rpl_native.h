/* C API of the native runtime for the TPU lidar framework.
 *
 * Native equivalents of the reference's I/O stack, redesigned rather than
 * translated (behavioral contracts cited per function):
 *   - request/response protocol codec   (ref: src/sdk/src/sl_lidarprotocol_codec.cpp)
 *   - serial channel, termios2 BOTHER   (ref: src/sdk/src/arch/linux/net_serial.cpp)
 *   - TCP / UDP channels                (ref: src/sdk/src/sl_tcp_channel.cpp, sl_udp_channel.cpp)
 *   - async transceiver (rx thread + decoded-message queue)
 *                                       (ref: src/sdk/src/sl_async_transceiver.cpp)
 *
 * Everything is exposed through a flat extern "C" surface so the Python side
 * binds with ctypes (no pybind11 in this image).
 */

#ifndef RPL_NATIVE_H_
#define RPL_NATIVE_H_

#include <stddef.h>
#include <stdint.h>

#ifdef __cplusplus
extern "C" {
#endif

/* ---------------- result codes ---------------- */
enum {
  RPL_OK = 0,
  RPL_TIMEOUT = -1,
  RPL_ERR = -2,
  RPL_CLOSED = -3,
  RPL_TOOSMALL = -4,
};

/* ---------------- codec ---------------- */

/* Encode a request: A5 | cmd [| size | payload | xor-checksum].
 * Returns packet length, or RPL_TOOSMALL / RPL_ERR. */
int rpl_encode_command(uint8_t cmd, const uint8_t* payload, size_t payload_len,
                       uint8_t* out, size_t out_cap);

typedef struct rpl_decoder rpl_decoder;

rpl_decoder* rpl_decoder_create(void);
void rpl_decoder_destroy(rpl_decoder* d);
/* Reset decode state == exitLoopMode (ref codec :66-68). */
void rpl_decoder_reset(rpl_decoder* d);
/* Feed a chunk of rx bytes; decoded messages queue internally. */
void rpl_decoder_feed(rpl_decoder* d, const uint8_t* data, size_t len);
/* Number of complete messages waiting. */
size_t rpl_decoder_pending(const rpl_decoder* d);
/* Pop the oldest message.  Returns payload length (>= 0), RPL_TIMEOUT if
 * none pending, RPL_TOOSMALL if cap is insufficient (message stays queued). */
int rpl_decoder_pop(rpl_decoder* d, uint8_t* ans_type, int* is_loop,
                    uint8_t* payload, size_t cap);

/* ---------------- channels ---------------- */

typedef struct rpl_channel rpl_channel;

rpl_channel* rpl_serial_channel_create(const char* device, uint32_t baudrate);
rpl_channel* rpl_tcp_channel_create(const char* host, int port);
rpl_channel* rpl_udp_channel_create(const char* host, int port);

int rpl_channel_open(rpl_channel* c);
void rpl_channel_close(rpl_channel* c);
int rpl_channel_is_open(const rpl_channel* c);
/* Write all bytes; returns count written or RPL_ERR. */
int rpl_channel_write(rpl_channel* c, const uint8_t* data, size_t len);
/* Wait up to timeout_ms for data, then read at most cap bytes.
 * Returns bytes read (> 0), RPL_TIMEOUT, RPL_CLOSED or RPL_ERR. */
int rpl_channel_read(rpl_channel* c, uint8_t* out, size_t cap, int timeout_ms);
/* DTR line (serial only; motor control on A-series).  RPL_ERR otherwise. */
int rpl_channel_set_dtr(rpl_channel* c, int level);
/* Unblock a pending read from another thread (self-pipe). */
void rpl_channel_cancel(rpl_channel* c);
void rpl_channel_destroy(rpl_channel* c);

/* ---------------- async transceiver ---------------- */

typedef struct rpl_transceiver rpl_transceiver;

/* Borrows the channel (caller keeps ownership; destroy transceiver first). */
rpl_transceiver* rpl_transceiver_create(rpl_channel* ch);
void rpl_transceiver_destroy(rpl_transceiver* t);
/* Opens the channel and spawns the rx thread. */
int rpl_transceiver_start(rpl_transceiver* t);
/* Joins the rx thread and closes the channel. */
void rpl_transceiver_stop(rpl_transceiver* t);
/* Synchronous encoded-packet send (ref sendMessage :261-297). */
int rpl_transceiver_send(rpl_transceiver* t, const uint8_t* pkt, size_t len);
/* Block up to timeout_ms for one decoded message.  Returns payload length,
 * RPL_TIMEOUT, RPL_CLOSED (rx thread gone / channel error), RPL_TOOSMALL. */
int rpl_transceiver_wait_message(rpl_transceiver* t, int timeout_ms,
                                 uint8_t* ans_type, int* is_loop,
                                 uint8_t* payload, size_t cap);
/* Same, plus the frame's arrival time (steady-clock seconds, captured in
 * the rx thread at the read that completed the frame — immune to consumer
 * queue-drain latency; feeds the per-node timestamp back-dating). */
int rpl_transceiver_wait_message_ts(rpl_transceiver* t, int timeout_ms,
                                    uint8_t* ans_type, int* is_loop,
                                    double* rx_ts,
                                    uint8_t* payload, size_t cap);
/* Drop queued messages and reset decode state (scan-mode changes). */
void rpl_transceiver_reset_decoder(rpl_transceiver* t);
/* Nonzero once the rx thread observed a channel error (hot-unplug). */
int rpl_transceiver_error(const rpl_transceiver* t);
/* Scheduling class the rx thread achieved (best-effort PRIORITY_HIGH,
 * ref arch/linux/thread.hpp:64-120): 2 = SCHED_RR, 1 = nice boost,
 * 0 = default (unprivileged), -1 = rx thread not started yet. */
int rpl_transceiver_rx_priority(const rpl_transceiver* t);

#ifdef __cplusplus
}
#endif

#endif /* RPL_NATIVE_H_ */
