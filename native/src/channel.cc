// Byte-transport channels: serial (termios2 arbitrary baud), TCP, UDP.
//
// Native re-design of the reference's channel stack (behavioral contracts:
// serial open with termios2 BOTHER and non-blocking fd —
// src/sdk/src/arch/linux/net_serial.cpp:153-186; select-based waitfordata
// with FIONREAD — :300-386; self-pipe cancellation — :204-223,422-428; DTR
// ioctls — :397-411; TCP/UDP connected-pair semantics —
// src/sdk/src/sl_tcp_channel.cpp, sl_udp_channel.cpp).  One polymorphic
// struct with per-kind open logic replaces the reference's three class
// hierarchies; all reads share a single select()+self-pipe wait.

#include "rpl_native.h"

#include <arpa/inet.h>
#include <asm/termbits.h>  // termios2 + BOTHER (no <termios.h>: conflicts)
#include <errno.h>
#include <fcntl.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <string.h>
#include <sys/ioctl.h>
#include <sys/select.h>
#include <sys/socket.h>
#include <sys/types.h>
#include <unistd.h>

#include <string>

extern "C" int ioctl(int fd, unsigned long request, ...);

namespace {

enum class Kind { kSerial, kTcp, kUdp };

}  // namespace

struct rpl_channel {
  Kind kind;
  std::string target;  // device path or host
  uint32_t baud = 0;
  int port = 0;
  int fd = -1;
  int cancel_pipe[2] = {-1, -1};  // [read, write] self-pipe

  bool OpenSerial();
  bool OpenTcp();
  bool OpenUdp();
};

namespace {

bool SetNonblock(int fd) {
  int flags = fcntl(fd, F_GETFL, 0);
  return flags >= 0 && fcntl(fd, F_SETFL, flags | O_NONBLOCK) == 0;
}

}  // namespace

bool rpl_channel::OpenSerial() {
  fd = ::open(target.c_str(), O_RDWR | O_NOCTTY | O_NONBLOCK);
  if (fd < 0) return false;

  // termios2 with BOTHER: arbitrary baud (256000/460800/1000000 are not all
  // in the Bxxx table), raw 8N1, no flow control.
  struct termios2 tio;
  if (ioctl(fd, TCGETS2, &tio) < 0) {
    ::close(fd);
    fd = -1;
    return false;
  }
  tio.c_cflag &= ~(CBAUD | CSIZE | PARENB | CSTOPB | CRTSCTS);
  tio.c_cflag |= BOTHER | CS8 | CREAD | CLOCAL;
  tio.c_iflag = 0;
  tio.c_oflag = 0;
  tio.c_lflag = 0;
  tio.c_ispeed = baud;
  tio.c_ospeed = baud;
  tio.c_cc[VMIN] = 0;
  tio.c_cc[VTIME] = 0;
  if (ioctl(fd, TCSETS2, &tio) < 0) {
    ::close(fd);
    fd = -1;
    return false;
  }
  ioctl(fd, TCFLSH, TCIOFLUSH);
  return true;
}

bool rpl_channel::OpenTcp() {
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_STREAM;
  struct addrinfo* res = nullptr;
  const std::string port_s = std::to_string(port);
  if (getaddrinfo(target.c_str(), port_s.c_str(), &hints, &res) != 0) return false;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return false;
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  return SetNonblock(fd);
}

bool rpl_channel::OpenUdp() {
  struct addrinfo hints = {};
  hints.ai_family = AF_UNSPEC;
  hints.ai_socktype = SOCK_DGRAM;
  struct addrinfo* res = nullptr;
  const std::string port_s = std::to_string(port);
  if (getaddrinfo(target.c_str(), port_s.c_str(), &hints, &res) != 0) return false;
  for (struct addrinfo* ai = res; ai; ai = ai->ai_next) {
    fd = socket(ai->ai_family, ai->ai_socktype, ai->ai_protocol);
    if (fd < 0) continue;
    // connected-pair semantics like the reference UDP channel
    if (connect(fd, ai->ai_addr, ai->ai_addrlen) == 0) break;
    ::close(fd);
    fd = -1;
  }
  freeaddrinfo(res);
  if (fd < 0) return false;
  return SetNonblock(fd);
}

extern "C" {

static rpl_channel* NewChannel(Kind kind, const char* target, uint32_t baud,
                               int port) {
  rpl_channel* c = new rpl_channel();
  c->kind = kind;
  c->target = target ? target : "";
  c->baud = baud;
  c->port = port;
  return c;
}

rpl_channel* rpl_serial_channel_create(const char* device, uint32_t baudrate) {
  return NewChannel(Kind::kSerial, device, baudrate, 0);
}

rpl_channel* rpl_tcp_channel_create(const char* host, int port) {
  return NewChannel(Kind::kTcp, host, 0, port);
}

rpl_channel* rpl_udp_channel_create(const char* host, int port) {
  return NewChannel(Kind::kUdp, host, 0, port);
}

int rpl_channel_open(rpl_channel* c) {
  if (!c) return RPL_ERR;
  if (c->fd >= 0) return RPL_OK;
  bool ok = false;
  switch (c->kind) {
    case Kind::kSerial: ok = c->OpenSerial(); break;
    case Kind::kTcp: ok = c->OpenTcp(); break;
    case Kind::kUdp: ok = c->OpenUdp(); break;
  }
  if (!ok) return RPL_ERR;
  if (pipe(c->cancel_pipe) != 0) {
    ::close(c->fd);
    c->fd = -1;
    return RPL_ERR;
  }
  SetNonblock(c->cancel_pipe[0]);
  return RPL_OK;
}

void rpl_channel_close(rpl_channel* c) {
  if (!c) return;
  if (c->fd >= 0) {
    ::close(c->fd);
    c->fd = -1;
  }
  for (int i = 0; i < 2; ++i) {
    if (c->cancel_pipe[i] >= 0) {
      ::close(c->cancel_pipe[i]);
      c->cancel_pipe[i] = -1;
    }
  }
}

int rpl_channel_is_open(const rpl_channel* c) {
  return (c && c->fd >= 0) ? 1 : 0;
}

int rpl_channel_write(rpl_channel* c, const uint8_t* data, size_t len) {
  if (!c || c->fd < 0) return RPL_ERR;
  size_t sent = 0;
  while (sent < len) {
    ssize_t n = ::write(c->fd, data + sent, len - sent);
    if (n < 0) {
      if (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR) {
        fd_set wfds;
        FD_ZERO(&wfds);
        FD_SET(c->fd, &wfds);
        struct timeval tv = {1, 0};
        if (select(c->fd + 1, nullptr, &wfds, nullptr, &tv) <= 0) return RPL_ERR;
        continue;
      }
      return RPL_ERR;
    }
    sent += static_cast<size_t>(n);
  }
  return static_cast<int>(sent);
}

int rpl_channel_read(rpl_channel* c, uint8_t* out, size_t cap, int timeout_ms) {
  if (!c || c->fd < 0) return RPL_CLOSED;
  fd_set rfds;
  FD_ZERO(&rfds);
  FD_SET(c->fd, &rfds);
  FD_SET(c->cancel_pipe[0], &rfds);
  const int maxfd = (c->fd > c->cancel_pipe[0] ? c->fd : c->cancel_pipe[0]) + 1;
  struct timeval tv;
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  int rc = select(maxfd, &rfds, nullptr, nullptr, timeout_ms < 0 ? nullptr : &tv);
  if (rc == 0) return RPL_TIMEOUT;
  if (rc < 0) return (errno == EINTR) ? RPL_TIMEOUT : RPL_ERR;
  if (FD_ISSET(c->cancel_pipe[0], &rfds)) {
    uint8_t sink[64];
    while (::read(c->cancel_pipe[0], sink, sizeof(sink)) > 0) {
    }
    return RPL_CLOSED;  // cancelled from another thread
  }
  ssize_t n = ::read(c->fd, out, cap);
  if (n == 0) return RPL_CLOSED;  // EOF: peer closed / device unplugged
  if (n < 0) {
    return (errno == EAGAIN || errno == EWOULDBLOCK || errno == EINTR)
               ? RPL_TIMEOUT
               : RPL_ERR;
  }
  return static_cast<int>(n);
}

int rpl_channel_set_dtr(rpl_channel* c, int level) {
  if (!c || c->kind != Kind::kSerial || c->fd < 0) return RPL_ERR;
  int flag = TIOCM_DTR;
  return ioctl(c->fd, level ? TIOCMBIS : TIOCMBIC, &flag) == 0 ? RPL_OK : RPL_ERR;
}

void rpl_channel_cancel(rpl_channel* c) {
  if (c && c->cancel_pipe[1] >= 0) {
    const uint8_t b = 1;
    ssize_t ignored = ::write(c->cancel_pipe[1], &b, 1);
    (void)ignored;
  }
}

void rpl_channel_destroy(rpl_channel* c) {
  if (!c) return;
  rpl_channel_close(c);
  delete c;
}

}  // extern "C"
