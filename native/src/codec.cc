// Protocol codec: request encoder + streaming response decoder.
//
// Behavioral contract from the reference codec
// (src/sdk/src/sl_lidarprotocol_codec.cpp): requests are
// A5 | cmd [| size | payload | xor-checksum] where the checksum covers every
// preceding byte (:78-130); responses are A5 5A | u32le size(30b)+subtype(2b)
// | type | payload, and when subtype bit0 (loop flag) is set the decoder
// keeps re-emitting fixed-size payloads without new headers until reset
// (:142-233).  This implementation is a fresh state machine over whole
// buffers with an internal message queue (the reference delivers through a
// listener callback from its decoder thread; here the queue decouples the
// decoder from any threading model so the same codec serves both the
// transceiver's rx thread and offline unit tests).

#include "rpl_native.h"

#include <cstring>
#include <deque>
#include <vector>

namespace {

constexpr uint8_t kCmdSync = 0xA5;
constexpr uint8_t kAnsSync1 = 0xA5;
constexpr uint8_t kAnsSync2 = 0x5A;
constexpr uint8_t kCmdFlagHasPayload = 0x80;
constexpr uint32_t kSizeMask = 0x3FFFFFFFu;
constexpr int kSubtypeShift = 30;
constexpr uint32_t kPktFlagLoop = 0x1;
// Sanity cap on the 30-bit wire size field.  The largest real frame is the
// HQ capsule (777 bytes); anything near the 1 GiB field limit is a corrupted
// header (e.g. wrong-baud noise that happened to contain A5 5A) and must
// trigger a resync instead of swallowing the stream into a giant payload.
constexpr uint32_t kMaxSanePayload = 8192;

struct Message {
  uint8_t ans_type;
  bool is_loop;
  std::vector<uint8_t> payload;
};

}  // namespace

extern "C" int rpl_encode_command(uint8_t cmd, const uint8_t* payload,
                                  size_t payload_len, uint8_t* out,
                                  size_t out_cap) {
  if (cmd & kCmdFlagHasPayload) {
    if (payload_len > 0xFF) return RPL_ERR;
    const size_t total = 3 + payload_len + 1;
    if (out_cap < total) return RPL_TOOSMALL;
    out[0] = kCmdSync;
    out[1] = cmd;
    out[2] = static_cast<uint8_t>(payload_len);
    if (payload_len) std::memcpy(out + 3, payload, payload_len);
    uint8_t checksum = 0;
    for (size_t i = 0; i < total - 1; ++i) checksum ^= out[i];
    out[total - 1] = checksum;
    return static_cast<int>(total);
  }
  if (payload_len) return RPL_ERR;  // plain commands carry no payload
  if (out_cap < 2) return RPL_TOOSMALL;
  out[0] = kCmdSync;
  out[1] = cmd;
  return 2;
}

struct rpl_decoder {
  enum class State { kSync1, kSync2, kHeader, kPayload } state = State::kSync1;
  uint8_t header[5];  // u32 size/subtype + type byte
  size_t header_got = 0;
  uint8_t ans_type = 0;
  uint32_t payload_len = 0;
  bool in_loop = false;
  std::vector<uint8_t> payload;
  std::deque<Message> queue;

  void Reset() {
    state = State::kSync1;
    header_got = 0;
    payload.clear();
    in_loop = false;
  }

  void Emit() {
    Message m;
    m.ans_type = ans_type;
    m.is_loop = in_loop;
    m.payload = std::move(payload);
    payload.clear();
    queue.push_back(std::move(m));
  }

  void Feed(const uint8_t* data, size_t len) {
    for (size_t i = 0; i < len; ++i) {
      const uint8_t b = data[i];
      switch (state) {
        case State::kSync1:
          if (b == kAnsSync1) state = State::kSync2;
          break;
        case State::kSync2:
          if (b == kAnsSync2) {
            state = State::kHeader;
            header_got = 0;
          } else if (b != kAnsSync1) {
            // A5 A5 5A must still sync (second A5 restarts the hunt)
            state = State::kSync1;
          }
          break;
        case State::kHeader:
          header[header_got++] = b;
          if (header_got == sizeof(header)) {
            uint32_t word;
            std::memcpy(&word, header, 4);  // wire is little-endian
            payload_len = word & kSizeMask;
            in_loop = ((word >> kSubtypeShift) & kPktFlagLoop) != 0;
            ans_type = header[4];
            payload.clear();
            if (payload_len > kMaxSanePayload) {
              state = State::kSync1;  // corrupted header: resync
              break;
            }
            if (payload_len == 0) {
              // header-only packet (ref :196-199)
              Emit();
              state = State::kSync1;
            } else {
              state = State::kPayload;
            }
          }
          break;
        case State::kPayload:
          payload.push_back(b);
          if (payload.size() == payload_len) {
            Emit();
            // loop mode: same header keeps producing payloads (ref :205-228)
            state = in_loop ? State::kPayload : State::kSync1;
          }
          break;
      }
    }
  }
};

extern "C" {

rpl_decoder* rpl_decoder_create(void) { return new rpl_decoder(); }

void rpl_decoder_destroy(rpl_decoder* d) { delete d; }

void rpl_decoder_reset(rpl_decoder* d) {
  d->Reset();
  d->queue.clear();
}

void rpl_decoder_feed(rpl_decoder* d, const uint8_t* data, size_t len) {
  d->Feed(data, len);
}

size_t rpl_decoder_pending(const rpl_decoder* d) { return d->queue.size(); }

int rpl_decoder_pop(rpl_decoder* d, uint8_t* ans_type, int* is_loop,
                    uint8_t* payload, size_t cap) {
  if (d->queue.empty()) return RPL_TIMEOUT;
  const Message& m = d->queue.front();
  if (m.payload.size() > cap) return RPL_TOOSMALL;
  *ans_type = m.ans_type;
  *is_loop = m.is_loop ? 1 : 0;
  if (!m.payload.empty()) std::memcpy(payload, m.payload.data(), m.payload.size());
  const int n = static_cast<int>(m.payload.size());
  d->queue.pop_front();
  return n;
}

}  // extern "C"
