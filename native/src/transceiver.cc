// Async transceiver: rx thread feeding the codec, condvar-signaled queue.
//
// Native re-design of the reference's two-thread AsyncTransceiver
// (src/sdk/src/sl_async_transceiver.cpp:299-409: rx thread reads into a
// queue, a second decoder thread drains it through the codec).  Here one
// thread reads AND decodes — the decode is a trivial state machine that
// never blocks, so a second thread only adds a hand-off — and completed
// messages land in a mutex+condvar queue the consumer pops with a timeout
// (the Waiter role, hal/waiter.h).  Channel errors set an error flag the
// driver's FSM polls for hot-unplug detection (ref :311-321,340-347).

#include "rpl_native.h"

#include <sched.h>
#include <sys/resource.h>
#include <sys/syscall.h>
#include <unistd.h>

#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdlib>
#include <cstring>
#include <deque>
#include <mutex>
#include <thread>
#include <vector>

namespace {

struct Message {
  uint8_t ans_type;
  bool is_loop;
  double rx_ts;  // steady-clock seconds at the read that completed the frame
  std::vector<uint8_t> payload;
};

double SteadyNowSeconds() {
  // CLOCK_MONOTONIC explicitly (not steady_clock) so the value is directly
  // comparable with Python's time.monotonic() on the consumer side
  timespec ts;
  clock_gettime(CLOCK_MONOTONIC, &ts);
  return static_cast<double>(ts.tv_sec) + 1e-9 * static_cast<double>(ts.tv_nsec);
}

constexpr size_t kReadChunk = 4096;
constexpr size_t kMaxQueued = 8192;  // bound memory if the consumer stalls

// Best-effort elevation of the calling thread to the reference's
// PRIORITY_HIGH: SCHED_RR at the minimum RR priority, SCHED_RESET_ON_FORK
// so children do not inherit it (Thread::SetSelfPriority,
// arch/linux/thread.hpp:64-120).  Unprivileged processes get EPERM; fall
// back silently to a negative nice (also usually EPERM) and finally to the
// default policy — latency under host load degrades gracefully instead of
// failing startup.  Returns 2 (SCHED_RR), 1 (nice boost) or 0 (default).
//
// RPL_RX_NO_ELEVATE=1 skips the elevation entirely (returns 0): the
// measurement knob for the RR-vs-default A/B under host load — without
// it the elevation's value can never be isolated on a rig where it
// succeeds.
int ElevateSelfToHighPriority() {
  if (const char* no = std::getenv("RPL_RX_NO_ELEVATE")) {
    if (*no && *no != '0') return 0;
  }
  const pid_t tid = static_cast<pid_t>(syscall(SYS_gettid));
  sched_param param{};
  param.sched_priority = sched_get_priority_min(SCHED_RR);
  if (sched_setscheduler(tid, SCHED_RR | SCHED_RESET_ON_FORK, &param) == 0) {
    return 2;
  }
  if (setpriority(PRIO_PROCESS, tid, -10) == 0) {
    return 1;
  }
  return 0;
}

}  // namespace

struct rpl_transceiver {
  rpl_channel* channel = nullptr;  // borrowed
  rpl_decoder* decoder = nullptr;
  std::thread rx_thread;
  std::atomic<bool> running{false};
  std::atomic<bool> channel_error{false};

  std::mutex mu;
  std::condition_variable cv;
  std::deque<Message> queue;
  bool reset_requested = false;
  std::atomic<int> rx_priority{-1};  // -1 until the rx thread reports

  void RxLoop();
};

void rpl_transceiver::RxLoop() {
  rx_priority.store(ElevateSelfToHighPriority(), std::memory_order_relaxed);
  std::vector<uint8_t> buf(kReadChunk);
  std::vector<uint8_t> payload(64 * 1024);
  while (running.load(std::memory_order_relaxed)) {
    int n = rpl_channel_read(channel, buf.data(), buf.size(), 1000);
    // arrival anchor for every frame completed by this read: taken HERE,
    // in the rx thread, so consumer-side queue draining cannot compress
    // inter-frame spacing (the timestamp back-dating models depend on it)
    const double rx_ts = SteadyNowSeconds();
    if (n == RPL_TIMEOUT) continue;
    if (n <= 0) {
      if (!running.load(std::memory_order_relaxed)) break;
      channel_error.store(true, std::memory_order_relaxed);
      std::lock_guard<std::mutex> lk(mu);
      cv.notify_all();
      break;
    }
    {
      std::lock_guard<std::mutex> lk(mu);
      if (reset_requested) {
        rpl_decoder_reset(decoder);
        queue.clear();
        reset_requested = false;
      }
      rpl_decoder_feed(decoder, buf.data(), static_cast<size_t>(n));
      bool pushed = false;
      for (;;) {
        uint8_t ans_type;
        int is_loop;
        int plen = rpl_decoder_pop(decoder, &ans_type, &is_loop, payload.data(),
                                   payload.size());
        if (plen == RPL_TOOSMALL) {
          // a message bigger than our pop buffer can only come from a
          // corrupted stream (codec caps frames well below this); drop the
          // decoder's queue + state rather than wedging the pipeline on a
          // permanently stuck head message
          rpl_decoder_reset(decoder);
          break;
        }
        if (plen < 0) break;
        if (queue.size() >= kMaxQueued) queue.pop_front();  // drop oldest
        Message m;
        m.ans_type = ans_type;
        m.is_loop = is_loop != 0;
        m.rx_ts = rx_ts;
        m.payload.assign(payload.begin(), payload.begin() + plen);
        queue.push_back(std::move(m));
        pushed = true;
      }
      if (pushed) cv.notify_all();
    }
  }
}

extern "C" {

rpl_transceiver* rpl_transceiver_create(rpl_channel* ch) {
  if (!ch) return nullptr;
  rpl_transceiver* t = new rpl_transceiver();
  t->channel = ch;
  t->decoder = rpl_decoder_create();
  return t;
}

void rpl_transceiver_destroy(rpl_transceiver* t) {
  if (!t) return;
  rpl_transceiver_stop(t);
  rpl_decoder_destroy(t->decoder);
  delete t;
}

int rpl_transceiver_start(rpl_transceiver* t) {
  if (!t) return RPL_ERR;
  if (t->running.load()) return RPL_OK;
  if (rpl_channel_open(t->channel) != RPL_OK) return RPL_ERR;
  t->channel_error.store(false);
  t->running.store(true);
  t->rx_thread = std::thread(&rpl_transceiver::RxLoop, t);
  return RPL_OK;
}

void rpl_transceiver_stop(rpl_transceiver* t) {
  if (!t) return;
  if (t->running.exchange(false)) {
    rpl_channel_cancel(t->channel);  // unblock the select()
    if (t->rx_thread.joinable()) t->rx_thread.join();
  }
  rpl_channel_close(t->channel);
  std::lock_guard<std::mutex> lk(t->mu);
  t->queue.clear();
  rpl_decoder_reset(t->decoder);
}

int rpl_transceiver_send(rpl_transceiver* t, const uint8_t* pkt, size_t len) {
  if (!t || !t->running.load()) return RPL_ERR;
  return rpl_channel_write(t->channel, pkt, len);
}

int rpl_transceiver_wait_message_ts(rpl_transceiver* t, int timeout_ms,
                                    uint8_t* ans_type, int* is_loop,
                                    double* rx_ts,
                                    uint8_t* payload, size_t cap) {
  if (!t) return RPL_ERR;
  std::unique_lock<std::mutex> lk(t->mu);
  if (t->queue.empty()) {
    auto pred = [&] { return !t->queue.empty() || t->channel_error.load(); };
    if (timeout_ms < 0) {
      t->cv.wait(lk, pred);
    } else if (!t->cv.wait_for(lk, std::chrono::milliseconds(timeout_ms), pred)) {
      return RPL_TIMEOUT;
    }
  }
  if (t->queue.empty()) {
    return t->channel_error.load() ? RPL_CLOSED : RPL_TIMEOUT;
  }
  const Message& m = t->queue.front();
  if (m.payload.size() > cap) return RPL_TOOSMALL;
  *ans_type = m.ans_type;
  *is_loop = m.is_loop ? 1 : 0;
  if (rx_ts) *rx_ts = m.rx_ts;
  if (!m.payload.empty()) std::memcpy(payload, m.payload.data(), m.payload.size());
  const int n = static_cast<int>(m.payload.size());
  t->queue.pop_front();
  return n;
}

int rpl_transceiver_wait_message(rpl_transceiver* t, int timeout_ms,
                                 uint8_t* ans_type, int* is_loop,
                                 uint8_t* payload, size_t cap) {
  return rpl_transceiver_wait_message_ts(t, timeout_ms, ans_type, is_loop,
                                         nullptr, payload, cap);
}

void rpl_transceiver_reset_decoder(rpl_transceiver* t) {
  if (!t) return;
  std::lock_guard<std::mutex> lk(t->mu);
  t->queue.clear();
  t->reset_requested = true;  // applied by the rx thread before next feed
}

int rpl_transceiver_error(const rpl_transceiver* t) {
  return (t && t->channel_error.load()) ? 1 : 0;
}

int rpl_transceiver_rx_priority(const rpl_transceiver* t) {
  return t ? t->rx_priority.load(std::memory_order_relaxed) : -1;
}

}  // extern "C"
