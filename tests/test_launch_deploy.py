"""Deployment-layer tests: param YAML, lifecycle launch, composition
container + intra-process bus, udev generator, viz renderer, CLI.

Covers the reference's L0 layer (launch/rplidar.launch.py,
launch/composition.launch.py, param/rplidar.yaml,
scripts/create_udev_rules.sh, config/rplidar.rviz).
"""

from __future__ import annotations

import subprocess
import sys
import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.launch import (
    IntraProcessBus,
    NodeContainer,
    default_params_path,
    launch_lifecycle,
)
from rplidar_ros2_driver_tpu.node.lifecycle import LifecycleState
from rplidar_ros2_driver_tpu.tools import udev, viz


def test_shipped_param_yaml_matches_defaults():
    """param/rplidar.yaml must parse and agree with DriverParams defaults."""
    p = DriverParams.from_yaml(default_params_path())
    assert p == DriverParams()


def test_launch_lifecycle_brings_node_to_active():
    node = launch_lifecycle(overrides={"dummy_mode": True})
    try:
        assert node.lifecycle_state is LifecycleState.ACTIVE
        deadline = time.monotonic() + 10
        while node.publisher.scan_count == 0 and time.monotonic() < deadline:
            time.sleep(0.02)
        assert node.publisher.scan_count > 0
    finally:
        node.deactivate()
        node.cleanup()
        node.shutdown()


def test_launch_no_auto_activate():
    node = launch_lifecycle(overrides={"dummy_mode": True}, auto_activate=False)
    try:
        assert node.lifecycle_state is LifecycleState.INACTIVE
    finally:
        node.cleanup()
        node.shutdown()


class TestIntraProcessBus:
    def test_zero_copy_delivery(self):
        bus = IntraProcessBus()
        got = []
        bus.subscribe("/scan", got.append)
        msg = object()
        n = bus.publish("/scan", msg)
        assert n == 1
        assert got[0] is msg  # same object, no serialization

    def test_best_effort_bounded_newest_wins(self):
        bus = IntraProcessBus()
        sub = bus.subscribe("/scan", maxlen=2)
        for k in range(5):
            bus.publish("/scan", k)
        assert sub.drain() == [3, 4]

    def test_reliable_keeps_all(self):
        bus = IntraProcessBus()
        sub = bus.subscribe("/scan", reliable=True, maxlen=2)
        for k in range(5):
            bus.publish("/scan", k)
        assert sub.drain() == [0, 1, 2, 3, 4]

    def test_latched_topic_replays_to_late_subscriber(self):
        """/tf_static transient-local behaviour."""
        bus = IntraProcessBus()
        bus.publish("/tf_static", "tf0", latched=True)
        sub = bus.subscribe("/tf_static")
        assert sub.drain() == ["tf0"]

    def test_latched_replay_callback_may_reenter_bus(self):
        """Replay is delivered outside the bus lock: a callback that
        republishes or subscribes must not deadlock."""
        bus = IntraProcessBus()
        bus.publish("/tf_static", "tf0", latched=True)
        got = []

        def reenter(msg):
            got.append(msg)
            bus.publish("/echo", msg)  # re-enters the bus
            bus.topic_names()

        echo = bus.subscribe("/echo")
        bus.subscribe("/tf_static", reenter)  # would deadlock pre-fix
        assert got == ["tf0"]
        assert echo.drain() == ["tf0"]

    def test_stale_replay_dropped_after_newer_publish(self):
        """A latched replay that lost the race to a newer publish must not
        overwrite the newer message (delivered outside the lock)."""
        from rplidar_ros2_driver_tpu.launch.bus import _Subscription

        sub = _Subscription(None, reliable=True, maxlen=8)
        sub.deliver("m2", 2)               # live publish won the race
        sub.deliver("m1", 1, replay=True)  # stale replay arrives late
        assert sub.drain() == ["m2"]

    def test_racing_live_publishes_never_dropped(self):
        from rplidar_ros2_driver_tpu.launch.bus import _Subscription

        sub = _Subscription(None, reliable=True, maxlen=8)
        sub.deliver("m2", 2)
        sub.deliver("m1", 1)  # out-of-order live delivery: kept (reliable)
        assert sub.drain() == ["m2", "m1"]


def test_container_composition_end_to_end():
    """Two composed nodes publish on namespaced topics over one bus."""
    with NodeContainer() as cont:
        cont.add_node("lidar_a", DriverParams(dummy_mode=True))
        cont.add_node("lidar_b", DriverParams(dummy_mode=True))
        sub_a = cont.bus.subscribe("/lidar_a/scan")
        sub_b = cont.bus.subscribe("/lidar_b/scan")
        assert cont.configure_all()
        assert cont.activate_all()
        deadline = time.monotonic() + 10
        while time.monotonic() < deadline:
            if sub_a.drain() and sub_b.drain():
                break
            time.sleep(0.02)
        else:
            pytest.fail("composed nodes did not both publish")
    assert not cont.nodes  # shutdown_all unloaded them


def test_container_duplicate_name_rejected():
    cont = NodeContainer()
    cont.add_node("x", DriverParams(dummy_mode=True))
    with pytest.raises(ValueError):
        cont.add_node("x", DriverParams(dummy_mode=True))
    cont.shutdown_all()


def test_udev_rules_text():
    text = udev.udev_rules_text()
    assert '"10c4"' in text and '"ea60"' in text
    assert 'SYMLINK+="rplidar"' in text
    assert 'MODE:="0666"' in text
    assert 'GROUP:="dialout"' in text


def test_udev_install_requires_root(tmp_path):
    import os

    if os.geteuid() == 0:
        path = tmp_path / "99-rplidar.rules"
        udev.install(str(path), symlink="lidar2", reload_udev=False)
        text = path.read_text()
        assert "10c4" in text
        assert 'SYMLINK+="lidar2"' in text  # --symlink honored by install
    else:
        with pytest.raises(PermissionError):
            udev.install(str(tmp_path / "r.rules"), reload_udev=False)


def _fake_scan(n=360, r=2.0):
    from rplidar_ros2_driver_tpu.node.messages import LaserScanHost

    inc = 2 * np.pi / n
    return LaserScanHost(
        stamp=0.0,
        frame_id="laser",
        angle_min=-np.pi,
        angle_max=np.pi - inc,
        angle_increment=inc,
        time_increment=0.0,
        scan_time=0.1,
        range_min=0.15,
        range_max=12.0,
        ranges=np.full(n, r, np.float32),
        intensities=np.full(n, 47.0, np.float32),
    )


def test_viz_renders_ring(tmp_path):
    img = viz.scan_to_image(_fake_scan(), size_px=128, view_range_m=4.0)
    assert img.shape == (128, 128)
    assert img.sum() > 0
    # a constant-radius ring leaves the center empty
    assert img[60:68, 60:68].sum() == 0
    pgm = tmp_path / "scan.pgm"
    viz.save_pgm(img, str(pgm))
    head = pgm.read_bytes()[:15]
    assert head.startswith(b"P5\n128 128\n255")
    txt = viz.ascii_preview(img, width=32)
    assert "#" in txt


def test_viz_drops_nonfinite_points():
    scan = _fake_scan()
    scan.ranges[:180] = np.inf
    img = viz.scan_to_image(scan, size_px=64, view_range_m=4.0)
    assert img.sum() > 0


def test_cli_view_subcommand():
    """Standalone main equivalent: `python -m ... view` runs end-to-end."""
    out = subprocess.run(
        [sys.executable, "-m", "rplidar_ros2_driver_tpu", "view", "--scans", "1", "--cpu"],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "#" in out.stdout


def test_cli_doctor():
    """Self-check subcommand: all probes run, loopback round-trip passes,
    missing hardware port is a WARN (not FAIL) so exit code is 0."""
    out = subprocess.run(
        [sys.executable, "-m", "rplidar_ros2_driver_tpu", "doctor", "--cpu",
         "--port", "/dev/definitely_not_a_lidar"],
        capture_output=True,
        text=True,
        timeout=180,
    )
    assert out.returncode == 0, (out.stdout, out.stderr)
    assert "[PASS] loopback protocol round-trip" in out.stdout
    assert "[WARN] serial port" in out.stdout


def test_cli_run_duration():
    out = subprocess.run(
        [
            sys.executable,
            "-m",
            "rplidar_ros2_driver_tpu",
            "run",
            "--dummy",
            "--duration",
            "2",
            "--cpu",
            "--stats",
        ],
        capture_output=True,
        text=True,
        timeout=120,
    )
    assert out.returncode == 0, out.stderr
    assert "scans=" in out.stdout
    # --stats appends a JSON per-stage latency summary after shutdown
    import json
    import re

    brace = out.stdout.find("{")
    assert brace != -1, f"no stats JSON in output: {out.stdout!r}"
    summary = json.loads(out.stdout[brace:])
    # stage entries exist only for scans that actually published; on a
    # loaded host the whole duration can go to the first jit compile
    scan_counts = [int(m) for m in re.findall(r"scans=(\d+)", out.stdout)]
    if scan_counts and scan_counts[-1] > 0:
        assert "publish" in summary and "p99_ms" in summary["publish"]


def test_raising_callback_does_not_wedge_subscription_or_publisher():
    """A raising subscriber must neither stop later delivery NOR propagate
    into the publisher's thread (one bad consumer cannot degrade the node
    hot path into an FSM reset loop — rclcpp intra-process delivery does
    not crash the publisher either)."""
    bus = IntraProcessBus()
    got = []
    calls = {"n": 0}

    def flaky(msg):
        calls["n"] += 1
        if calls["n"] == 1:
            raise RuntimeError("boom")
        got.append(msg)

    bus.subscribe("/t", flaky)
    bus.publish("/t", "m1")  # exception contained, logged
    bus.publish("/t", "m2")  # must still be delivered
    assert calls["n"] == 2
    assert got == ["m2"]
