"""Scenario-foundry suite (scenarios/ + the SimConfig.scene seam).

The contracts under test:

  * DETERMINISM — a scene is a pure function of its spec: byte-equal
    range streams across rebuilds AND across arbitrary query chunkings
    (the SimConfig.scene provider contract that lets six wire formats
    share one world).
  * GOLDEN — the vectorized raycaster exactly equals a scalar
    per-segment brute-force twin, ray by ray.
  * UNITS — the accuracy metrics mean what they claim: a pose offset of
    exactly k lattice cells scores exactly k; a perfect map scores
    F1 1.0 and an empty one 0.0.
  * TRAJECTORIES — the loop script genuinely returns to its start pose
    (what PR 11 loop closure needs) and organic drift never out-turns
    the matcher's theta window.
  * DECAY — the new log-odds decay param validates at every layer and
    is byte-invisible when off: the decay-0 jaxpr is equation-for-
    equation the pre-decay program.
  * WIRE — the sim's beam->(theta, rev) contract is pinned, the default
    ring stays byte-identical to the pre-scene tree on all six wire
    formats, and a foundry scene streams deterministically through
    the same seam.
"""

from __future__ import annotations

import math

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.mapping.mapper import map_config_from_params
from rplidar_ros2_driver_tpu.ops.scan_match import (
    SUB,
    MapConfig,
    update_map,
)
from rplidar_ros2_driver_tpu.ops.scan_match_ref import update_map_np
from rplidar_ros2_driver_tpu.scenarios.foundry import (
    SCENE_KINDS,
    FoundryScene,
    SceneSpec,
    build_scene,
    raycast_brute,
)
from rplidar_ros2_driver_tpu.scenarios.metrics import (
    end_pose_error_cells,
    map_f1,
    pose_to_lattice,
    scan_points_xy,
    visible_truth_occupancy,
)
from rplidar_ros2_driver_tpu.scenarios.trajectory import (
    organic,
    scripted_line,
    scripted_loop,
    scripted_waypoints,
)


# ----------------------------------------------------------------------
# foundry determinism + raycaster goldens
# ----------------------------------------------------------------------

@pytest.mark.parametrize("kind", SCENE_KINDS)
def test_foundry_byte_determinism_across_chunkings(kind):
    """Same spec => byte-equal streams, however the queries are
    chunked — the provider contract the sim's frame loops rely on
    (one frame never aligns with one revolution)."""
    spec = SceneSpec(kind=kind, seed=77, n_revs=8, dropout_rate=0.1)
    a = build_scene(spec)
    b = build_scene(spec)  # fresh build: no shared state

    thetas = np.linspace(0.0, 360.0, 200, endpoint=False)
    thetas = np.tile(thetas, 2)
    revs = np.repeat(np.arange(2, dtype=np.int64), 200)

    whole = a.dist_mm(thetas, revs)
    parts = [
        b.dist_mm(thetas[i:i + 63], revs[i:i + 63])
        for i in range(0, len(thetas), 63)
    ]
    assert whole.tobytes() == np.concatenate(parts).tobytes()
    # a third chunking, point by point, over the REBUILT scene
    single = np.array([
        float(b.dist_mm(thetas[i:i + 1], revs[i:i + 1])[0])
        for i in range(0, len(thetas), 17)
    ])
    assert single.tobytes() == whole[::17].tobytes()


@pytest.mark.parametrize("kind", SCENE_KINDS)
def test_foundry_spec_validation_and_coverage(kind):
    spec = SceneSpec(kind=kind, seed=3, n_revs=8)
    scene = build_scene(spec)
    assert isinstance(scene, FoundryScene)
    assert scene.segments.shape[0] >= 2  # corridor is two bare walls
    # waypoint programs (decay) derive their own length; others honor it
    assert scene.traj.n_revs >= 5
    thetas = np.linspace(0.0, 360.0, 360, endpoint=False)
    d = scene.dist_mm(thetas, np.zeros(360, np.int64))
    assert np.all(d >= 0.0)
    assert np.any(d > 0.0)  # the world is visible from the start pose


def test_scene_spec_rejects_malformed():
    with pytest.raises(ValueError):
        SceneSpec(kind="escher")
    with pytest.raises(ValueError):
        SceneSpec(kind="rooms", n_revs=2)
    with pytest.raises(ValueError):
        SceneSpec(kind="rooms", dropout_rate=0.9)
    with pytest.raises(ValueError):
        SceneSpec(kind="rooms", max_range_m=0.1)
    with pytest.raises(ValueError):
        SceneSpec(kind="rooms", theta_table=1000)  # not a multiple of 360


def test_raycast_matches_scalar_brute_twin():
    """The vectorized (rays x segments) raycaster must EXACTLY equal
    the scalar per-segment loop — same float64 formulas, same
    first-min-wins tie rule — including moving-box overlays."""
    for kind in ("rooms", "decay"):  # decay exercises the moving box
        scene = build_scene(SceneSpec(kind=kind, seed=11, n_revs=8))
        x0, y0 = scene.traj.x_m[0], scene.traj.y_m[0]
        angs = np.linspace(0.0, 2.0 * math.pi, 64, endpoint=False)
        dx, dy = np.cos(angs), np.sin(angs)
        for rev in (0, scene.traj.n_revs - 1):
            t_vec, m_vec = scene.raycast(
                np.full(64, x0), np.full(64, y0), dx, dy,
                np.full(64, rev, np.int64),
            )
            for i in range(64):
                t_ref, m_ref = raycast_brute(
                    scene, x0, y0, float(dx[i]), float(dy[i]), rev
                )
                assert float(t_vec[i]) == t_ref, (kind, rev, i)
                assert int(m_vec[i]) == m_ref, (kind, rev, i)


# ----------------------------------------------------------------------
# metric units
# ----------------------------------------------------------------------

def test_end_pose_error_exact_cells():
    cfg = MapConfig(grid=64, cell_m=0.1, beams=128)
    truth = pose_to_lattice(0.0, 0.0, 0.0, cfg)
    for k in (1, 3, 7):
        est = pose_to_lattice(k * cfg.cell_m, 0.0, 0.0, cfg)
        assert est[0] == k * SUB  # the lattice quantization is exact
        assert end_pose_error_cells(est, truth) == float(k)
    # Euclidean, not Manhattan: a (3, 4)-cell offset is exactly 5
    est = pose_to_lattice(3 * cfg.cell_m, 4 * cfg.cell_m, 0.0, cfg)
    assert end_pose_error_cells(est, truth) == 5.0


def test_map_f1_endpoints():
    truth = np.zeros((16, 16), bool)
    truth[4:8, 4:8] = True
    perfect = np.where(truth, 1000, -1000).astype(np.int32)
    assert map_f1(perfect, truth) == 1.0
    empty = np.full((16, 16), -1000, np.int32)
    assert map_f1(empty, truth) == 0.0
    # empty prediction against empty truth is vacuously perfect
    assert map_f1(empty, np.zeros((16, 16), bool)) == 1.0


def test_visible_truth_occupancy_reachable_by_perfect_mapper():
    """F1 against the visible raster must be attainable: replaying the
    clean truth scans through the mapper's own update at the truth
    poses scores F1 1.0 on hit cells."""
    from rplidar_ros2_driver_tpu.ops.scan_match_ref import (
        create_map_state_np,
        quantize_points_np,
    )

    cfg = MapConfig(grid=64, cell_m=0.1, beams=180, free_samples=0)
    scene = build_scene(SceneSpec(kind="rooms", seed=5, n_revs=6))
    thetas = np.linspace(0.0, 360.0, cfg.beams, endpoint=False)
    rel = scene.traj.relative_poses()
    revs = list(range(scene.traj.n_revs))
    truth_q = np.stack([
        pose_to_lattice(rel[k, 0], rel[k, 1], rel[k, 2], cfg) for k in revs
    ])
    occ = visible_truth_occupancy(scene, thetas, revs, truth_q, cfg)
    assert occ.any()
    state = create_map_state_np(cfg)
    log_odds = state["log_odds"]
    for i, rev in enumerate(revs):
        d = scene.truth_dist_mm(
            thetas, np.full(cfg.beams, rev, np.int64)
        )
        xy, mask = scan_points_xy(thetas, d)
        pq, ok = quantize_points_np(xy, mask, cfg)
        log_odds = update_map_np(log_odds, truth_q[i], pq, ok, cfg)
    assert map_f1(log_odds, occ) == 1.0


# ----------------------------------------------------------------------
# trajectories
# ----------------------------------------------------------------------

def test_scripted_loop_returns_to_start():
    traj = scripted_loop(24, center_xy=(0.5, -0.25), radius_m=1.5)
    assert traj.is_loop()
    assert traj.x_m[-1] == traj.x_m[0] and traj.y_m[-1] == traj.y_m[0]
    rel = traj.relative_poses()
    assert rel[0, 0] == 0.0 and rel[0, 1] == 0.0 and rel[0, 2] == 0.0
    assert rel[-1, 0] == 0.0 and rel[-1, 1] == 0.0
    with pytest.raises(ValueError):
        scripted_loop(4)


def test_scripted_line_and_waypoints():
    traj = scripted_line(10, start_xy=(1.0, 2.0), speed_m=0.25)
    assert traj.n_revs == 10
    assert np.allclose(np.diff(traj.x_m), 0.25)
    assert not traj.is_loop()
    wp = scripted_waypoints([(0.0, 0.0), (1.0, 0.0)], [3, 3], speed_m=0.5)
    assert wp.x_m[0] == 0.0 and wp.x_m[-1] == 1.0
    assert np.sum(wp.x_m == 0.0) == 3  # first dwell parked 3 revs
    with pytest.raises(ValueError):
        scripted_waypoints([(0.0, 0.0)], [1, 2])


def test_organic_is_seeded_bounded_and_trackable():
    bounds = (-1.0, 1.0, -1.0, 1.0)
    a = organic(200, seed=9, speed_m=0.1, bounds=bounds)
    b = organic(200, seed=9, speed_m=0.1, bounds=bounds)
    c = organic(200, seed=10, speed_m=0.1, bounds=bounds)
    assert a.poses.tobytes() == b.poses.tobytes()  # pure in the seed
    assert a.poses.tobytes() != c.poses.tobytes()
    assert np.all(a.x_m >= -1.0) and np.all(a.x_m <= 1.0)
    assert np.all(a.y_m >= -1.0) and np.all(a.y_m <= 1.0)
    # every per-rev heading change stays inside the matcher's theta
    # window (0.05 rad ~ 2.9 deg < the +-3 deg search) — the wall
    # steering must never reflect
    dh = np.abs(np.diff(a.heading))
    assert float(dh.max()) <= 0.05 + 1e-12


# ----------------------------------------------------------------------
# log-odds decay: validation + default-off byte identity
# ----------------------------------------------------------------------

def test_decay_param_validation():
    with pytest.raises(ValueError):
        MapConfig(decay_q=-1)
    with pytest.raises(ValueError):
        MapConfig(decay_q=9000)  # past the default clamp_q=8192
    chain = ("clip", "median", "voxel")
    with pytest.raises(ValueError):
        DriverParams(
            map_enable=True, filter_chain=chain, map_decay=-0.1
        ).validate()
    with pytest.raises(ValueError):
        DriverParams(
            map_enable=True, filter_chain=chain, map_decay=99.0
        ).validate()
    # Q10 derivation through the mapper seam
    p = DriverParams(map_enable=True, filter_chain=chain, map_decay=0.4)
    p.validate()
    assert map_config_from_params(p).decay_q == 410  # round(0.4 * 1024)
    assert map_config_from_params(
        DriverParams(map_enable=True, filter_chain=chain)
    ).decay_q == 0


def test_decay_off_is_the_same_program():
    """decay_q=0 must trace the byte-identical XLA program the
    pre-decay tree compiled — equation for equation, not 'mostly'.
    (The gate is static Python; a traced `where` would survive into
    the decay-off jaxpr and break this.)"""
    import jax
    import jax.numpy as jnp

    cfg0 = MapConfig(grid=32, cell_m=0.1, beams=64, free_samples=2)
    cfg_off = MapConfig(
        grid=32, cell_m=0.1, beams=64, free_samples=2, decay_q=0
    )
    cfg_on = MapConfig(
        grid=32, cell_m=0.1, beams=64, free_samples=2, decay_q=410
    )
    lo = jnp.zeros((32, 32), jnp.int32)
    pose = jnp.zeros((3,), jnp.int32)
    pq = jnp.zeros((64, 2), jnp.int32)
    ok = jnp.zeros((64,), bool)

    def eqns(cfg):
        return len(jax.make_jaxpr(
            lambda l, p, q, o: update_map(l, p, q, o, cfg)
        )(lo, pose, pq, ok).eqns)

    assert eqns(cfg_off) == eqns(cfg0)
    assert eqns(cfg_on) > eqns(cfg0)


def test_decay_fades_and_twins_agree():
    rng = np.random.default_rng(4)
    cfg_on = MapConfig(grid=32, cell_m=0.1, beams=64, decay_q=410)
    cfg_off = MapConfig(grid=32, cell_m=0.1, beams=64)
    lo = rng.integers(-8192, 8193, (32, 32), dtype=np.int32)
    pose = np.zeros((3,), np.int32)
    pq = np.zeros((64, 2), np.int32)
    ok = np.zeros((64,), bool)  # no rays: isolate the decay term
    out_on = update_map_np(lo, pose, pq, ok, cfg_on)
    out_off = update_map_np(lo, pose, pq, ok, cfg_off)
    want = np.sign(lo) * np.maximum(np.abs(lo) - 410, 0)
    assert np.array_equal(out_on, want.astype(np.int32))
    assert np.array_equal(out_off, lo)  # off = untouched (no rays)
    # jnp arm is bit-exact against the reference, decay on AND off
    import jax.numpy as jnp

    for cfg, ref in ((cfg_on, out_on), (cfg_off, out_off)):
        dev = update_map(
            jnp.asarray(lo), jnp.asarray(pose), jnp.asarray(pq),
            jnp.asarray(ok), cfg,
        )
        assert np.array_equal(np.asarray(dev), ref)


# ----------------------------------------------------------------------
# the sim wire seam
# ----------------------------------------------------------------------

def _capture_frames(dev, mode, n):
    """Run the stream loop in-thread against a fake transport until n
    measurement frames land; returns them (header frame skipped)."""
    frames = []

    def fake_send(data):
        frames.append(bytes(data))
        if len(frames) >= n + 1:
            dev._streaming.clear()
        return True

    dev._send = fake_send
    dev._streaming.set()
    dev._running.set()
    dev._stream_loop(mode)
    return frames[1:]


def test_sim_beam_rev_contract_golden():
    """The ONE beam->(theta, rev) contract: theta = 360*(p % ppr)/ppr,
    rev = p // ppr, each beam at its OWN revolution even mid-frame."""
    from rplidar_ros2_driver_tpu.driver.sim_device import (
        SimConfig,
        SimulatedDevice,
    )

    queries = []

    class Recorder:
        def dist_mm(self, thetas, revs):
            queries.append((np.asarray(thetas), np.asarray(revs)))
            return np.full(len(np.asarray(thetas)), 1500.0)

    dev = SimulatedDevice(SimConfig(points_per_rev=50, scene=Recorder()))
    pts = np.arange(30, 130)  # global indices straddling rev 1 -> 2
    out = dev._scene_dists(pts)
    assert out.shape == (100,)
    thetas, revs = queries[-1]
    assert np.array_equal(revs, pts // 50)
    assert np.array_equal(thetas, 360.0 * (pts % 50) / 50)


def _pr18_frame(ans, idx, first, c):
    """Inline re-implementation of the PR 18 stream-loop encoders (the
    per-beam scalar sinusoid ring, rev fixed per FRAME START was never
    true — each beam always carried its own rev; this is that exact
    math) — the byte-identity oracle for the refactored seam."""
    from rplidar_ros2_driver_tpu.ops import unpack_ref, wire

    ppr = c.points_per_rev

    def old_dist(theta, rev):
        return c.dist_base_mm + c.dist_amp_mm * math.sin(
            math.radians(theta) + 0.1 * rev
        )

    rev, pos = divmod(idx, ppr)
    theta = 360.0 * pos / ppr
    start_q6 = int(theta * 64) & 0x7FFF
    if ans == 0x81:
        d = old_dist(theta, rev)
        return bytes(wire.encode_normal_node(
            int(theta * 64), int(d * 4), 0x2F, syncbit=(pos == 0)
        ))
    if ans == 0x85:
        pts = np.arange(40) + idx
        dists = np.array([
            old_dist(360.0 * (p % ppr) / ppr, p // ppr) for p in pts
        ])
        return bytes(wire.encode_dense_capsule(
            start_q6, first, dists.astype(int)
        ))
    if ans == 0x82:
        pts = np.arange(32) + idx
        dists = np.array([
            old_dist(360.0 * (p % ppr) / ppr, p // ppr) for p in pts
        ])
        dq2 = (dists.astype(int) * 4) & ~0x3
        return bytes(wire.encode_capsule(
            start_q6, first, dq2.reshape(16, 2), np.zeros((16, 2), int)
        ))
    if ans == 0x84:
        pts = np.arange(97) + idx
        mm = np.array([
            int(old_dist(360.0 * (p % ppr) / ppr, p // ppr)) for p in pts
        ])
        bases = mm[0::3]
        majors = np.array(
            [wire.varbitscale_encode(int(v)) for v in bases]
        )
        dec = [unpack_ref.varbitscale_decode(int(m)) for m in majors]
        p1 = np.empty(32, np.int64)
        p2 = np.empty(32, np.int64)
        for cab in range(32):
            b1, l1 = dec[cab]
            b2, l2 = dec[cab + 1]
            p1[cab] = np.clip((mm[3 * cab + 1] - b1) >> l1, -511, 510)
            p2[cab] = np.clip((mm[3 * cab + 2] - b2) >> l2, -511, 510)
        return bytes(wire.encode_ultra_capsule(
            start_q6, first, majors[:32], p1, p2
        ))
    if ans == 0x86:
        pts = np.arange(64) + idx
        words = np.array([
            wire.ultra_dense_encode_sample(
                int(old_dist(360.0 * (p % ppr) / ppr, p // ppr)), 0x2F
            )
            for p in pts
        ])
        return bytes(wire.encode_ultra_dense_capsule(start_q6, first, words))
    assert ans == 0x83
    pts = np.arange(96) + idx
    thetas = 360.0 * (pts % ppr) / ppr
    dq2 = np.array([
        int(old_dist(360.0 * (p % ppr) / ppr, p // ppr)) for p in pts
    ]) * 4
    flags = np.where(pts % ppr == 0, 1, 2)
    return bytes(wire.encode_hq_capsule(
        (thetas * (65536.0 / 360.0)).astype(int),
        dq2,
        np.full(96, 0x2F, int),
        flags,
        timestamp=idx,
    ))


def test_sim_default_ring_byte_identical_all_formats():
    """No scene configured => every wire format emits the EXACT bytes
    the pre-scene tree emitted.  ppr=50 puts rev boundaries mid-frame
    for every capsule format, so per-frame rev mixing is exercised."""
    from rplidar_ros2_driver_tpu.driver.sim_device import (
        DEFAULT_MODES,
        SimConfig,
        SimulatedDevice,
    )

    for mode in DEFAULT_MODES:
        cfg = SimConfig(points_per_rev=50, frame_rate_hz=1e6)
        dev = SimulatedDevice(cfg)
        _, pts_per_frame = dev.STREAMABLE[mode.ans_type]
        got = _capture_frames(dev, mode, 4)
        idx, first = 0, True
        for frame in got:
            want = _pr18_frame(mode.ans_type, idx, first, cfg)
            assert frame == want, (mode.name, idx)
            idx += pts_per_frame
            first = False


def test_sim_foundry_scene_streams_deterministically():
    """A foundry scene through the seam: two independently built
    devices emit byte-equal frames on every format, and the frames
    differ from the default ring (the scene really is on the wire)."""
    from rplidar_ros2_driver_tpu.driver.sim_device import (
        DEFAULT_MODES,
        SimConfig,
        SimulatedDevice,
    )

    spec = SceneSpec(kind="rooms", seed=21, n_revs=8, dropout_rate=0.05)
    for mode in DEFAULT_MODES:
        devs = [
            SimulatedDevice(SimConfig(
                points_per_rev=50, frame_rate_hz=1e6,
                scene=build_scene(spec),
            ))
            for _ in range(2)
        ]
        a, b = (_capture_frames(d, mode, 3) for d in devs)
        assert a == b, mode.name
        ring = _capture_frames(
            SimulatedDevice(SimConfig(points_per_rev=50, frame_rate_hz=1e6)),
            mode, 3,
        )
        assert a != ring, mode.name
