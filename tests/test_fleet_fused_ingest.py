"""Fleet-fused ingest vs N independent host golden paths — parity suite.

The fleet backend (ops/ingest.fleet_fused_ingest_step +
driver/ingest.FleetFusedIngest) replaces per-stream BatchScanDecoder ->
ScanAssembler -> ScanFilterChain pipelines with ONE compiled vmapped
program per fleet tick.  This suite pins the contract that makes it
shippable: **bit-exact** filter outputs against N independent host
paths on identical per-stream wire streams, across

  * fleets of 1, 3, and 8 streams (the acceptance matrix),
  * mixed answer types within one tick (per-stream lax.switch dispatch),
  * idle and straggler streams (empty byte slices, late joiners, early
    stoppers),
  * corrupt/resync streams in the middle of a healthy fleet,
  * per-stream chunk-boundary carries surviving across ticks (two
    different tick chunkings produce identical outputs),
  * per-stream answer-type switches (decode state resets, filter window
    survives),
  * snapshot/restore of the whole per-stream carry state mid-stream,
  * the ShardedFilterService.submit_bytes seam (host and fused).

Timestamps ride as f32 per-stream epoch offsets on the fused path (the
host path is f64), so ts0/duration compare to tolerance; node values and
filter outputs ARE exact (same contract as tests/test_fused_ingest.py).
"""

from __future__ import annotations

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.driver.assembly import ScanAssembler
from rplidar_ros2_driver_tpu.driver.decode import BatchScanDecoder
from rplidar_ros2_driver_tpu.driver.ingest import FleetFusedIngest
from rplidar_ros2_driver_tpu.filters.chain import (
    ScanFilterChain,
    resolve_fleet_ingest_backend,
)
from rplidar_ros2_driver_tpu.protocol.constants import Ans

from test_fused_ingest import BEAMS, TS_TOL, _params
from test_live_decode import _make_stream, _rng

DENSE = int(Ans.MEASUREMENT_DENSE_CAPSULED)


def _mk_ticks(streams_frames, rng, idle_prob: float = 0.25):
    """Random per-tick chunking of each stream's frame list: 0..4 frames
    per stream per tick (0 = idle this tick), independent per stream —
    the fleet gateway's real arrival pattern."""
    s = len(streams_frames)
    t = [100.0 + 50.0 * i for i in range(s)]
    pos = [0] * s
    ticks = []
    while any(pos[i] < len(streams_frames[i][1]) for i in range(s)):
        tick = []
        for i in range(s):
            ans, frames = streams_frames[i]
            k = int(rng.integers(0, 5))
            if pos[i] >= len(frames) or (k == 0 and rng.random() < idle_prob):
                tick.append(None)
                continue
            k = max(k, 1)
            batch = []
            for f in frames[pos[i] : pos[i] + k]:
                t[i] += 0.002
                batch.append((f, t[i]))
            pos[i] += k
            tick.append((int(ans), batch))
        ticks.append(tick)
    return ticks


def _host_reference(ticks, s, params=None):
    """N INDEPENDENT decoder+assembler+chain paths over the same ticks —
    the golden reference the acceptance criteria name."""
    params = params or _params()
    host = []
    for i in range(s):
        completed = []
        asm = ScanAssembler(
            on_complete=lambda sc, c=completed: c.append(dict(sc))
        )
        dec = BatchScanDecoder(asm)
        for tick in ticks:
            if tick[i]:
                dec.on_measurement_batch(tick[i][0], list(tick[i][1]))
        chain = ScanFilterChain(params, beams=BEAMS, warmup=False)
        host.append([
            (
                chain.process_raw(
                    sc["angle_q14"], sc["dist_q2"], sc["quality"], sc["flag"]
                ),
                sc["ts0"],
                sc["duration"],
            )
            for sc in completed
        ])
    return host


def _run_fleet(ticks, s, params=None, *, pipelined=True, **kw):
    kw.setdefault("max_revs", 6)
    kw.setdefault("buckets", (4,))
    fleet = FleetFusedIngest(params or _params(), s, beams=BEAMS, **kw)
    outs = [[] for _ in range(s)]
    for tick in ticks:
        got = fleet.submit_pipelined(tick) if pipelined else fleet.submit(tick)
        for i, o in enumerate(got):
            outs[i].extend(o)
    for i, o in enumerate(fleet.flush()):
        outs[i].extend(o)
    return outs, fleet


def _assert_fleet_outputs_equal(host, fused, min_revs: int = 1):
    assert len(host) == len(fused)
    for i, (h_outs, f_outs) in enumerate(zip(host, fused)):
        assert len(h_outs) == len(f_outs), (
            f"stream {i}: host {len(h_outs)} revs vs fused {len(f_outs)}"
        )
        for k, ((ho, hts0, hdur), (fo, fts0, fdur)) in enumerate(
            zip(h_outs, f_outs)
        ):
            for field in (
                "ranges", "intensities", "points_xy", "point_mask", "voxel"
            ):
                h = np.asarray(getattr(ho, field))
                f = np.asarray(getattr(fo, field))
                assert np.array_equal(h, f), f"stream {i} rev {k}: {field}"
            assert abs(hts0 - fts0) < TS_TOL, (i, k, hts0, fts0)
            assert abs(hdur - fdur) < TS_TOL, (i, k, hdur, fdur)
    assert sum(len(h) for h in host) >= min_revs, "fixture closed no revs"


class TestFleetParity:
    """The acceptance matrix: fleets of 1, 3, 8 on the virtual mesh,
    bit-exact against N independent host paths, idle ticks included."""

    @pytest.mark.parametrize("streams", [1, 3, 8])
    def test_fleet_sizes_bit_exact(self, streams):
        sf = [
            (DENSE, _make_stream(
                Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(),
                syncs=(0, 10 + i, 25),
            ))
            for i in range(streams)
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(streams))
        host = _host_reference(ticks, streams)
        fused, fleet = _run_fleet(ticks, streams)
        _assert_fleet_outputs_equal(host, fused, min_revs=streams)
        # the structural O(1) claim at test scale: one dispatch per tick
        # slice and two staged transfers per dispatch, whatever N is
        assert fleet.dispatch_count <= len(ticks)
        assert fleet.h2d_transfers == 2 * fleet.dispatch_count
        assert fleet.revs_dropped == 0 and fleet.wires_dropped == 0

    def test_mixed_ans_types_per_tick(self):
        """Three formats live in ONE tick: per-stream lax.switch branch
        dispatch, each stream bit-exact against its own host path."""
        sf = [
            (int(a), _make_stream(a, 36, _rng(), syncs=(0, 9, 18, 27)))
            for a in (
                Ans.MEASUREMENT_DENSE_CAPSULED,
                Ans.MEASUREMENT_HQ,
                Ans.MEASUREMENT,
            )
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(11))
        host = _host_reference(ticks, 3)
        fused, _ = _run_fleet(ticks, 3)
        _assert_fleet_outputs_equal(host, fused, min_revs=4)

    def test_straggler_and_silent_streams(self):
        """A late joiner, an early stopper, and a stream that never sends
        a byte: the silent stream's state must stay untouched while its
        neighbors' revolutions stay bit-exact."""
        frames = _make_stream(
            Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(), syncs=(0, 10, 25)
        )
        base = _mk_ticks(
            [(DENSE, frames), (DENSE, frames)], np.random.default_rng(5)
        )
        n = len(base)
        ticks = []
        for j, tick in enumerate(base):
            late = tick[0] if j >= n // 2 else None      # joins mid-run
            early = tick[1] if j < n // 2 else None      # stops mid-run
            ticks.append([late, early, None])            # stream 2: silent
        host = _host_reference(ticks, 3)
        fused, fleet = _run_fleet(ticks, 3)
        _assert_fleet_outputs_equal(host, fused)
        assert host[2] == [] and fused[2] == []
        snap = fleet.snapshot()
        assert snap["formats"][2] == -1  # never activated

    def test_corrupt_resync_mid_fleet(self):
        """Checksum faults (and the resync they force) on ONE stream in
        the middle of a healthy fleet stay bit-exact on every stream —
        fault isolation is per-stream state, not fleet state."""
        a = Ans.MEASUREMENT_DENSE_CAPSULED
        healthy = _make_stream(a, 40, _rng(), syncs=(0, 10, 25))
        corrupt = _make_stream(
            a, 40, _rng(), syncs=(0,), corrupt=(7, 8, 19, 30)
        )
        sf = [(DENSE, healthy), (DENSE, corrupt), (DENSE, healthy)]
        ticks = _mk_ticks(sf, np.random.default_rng(9))
        host = _host_reference(ticks, 3)
        fused, _ = _run_fleet(ticks, 3)
        _assert_fleet_outputs_equal(host, fused, min_revs=3)


class TestCarryAndSwitchSemantics:
    def test_tick_boundaries_do_not_matter(self):
        """Two different random tick chunkings of the same per-stream
        byte streams produce identical outputs: every per-stream carry
        (prev frame, sync edge, partial revolution, timestamp re-base)
        survives arbitrary tick boundaries."""
        sf = [
            (DENSE, _make_stream(
                Ans.MEASUREMENT_DENSE_CAPSULED, 36, _rng(), syncs=(0,)
            ))
            for i in range(2)
        ]

        def run(seed):
            ticks = _mk_ticks(sf, np.random.default_rng(seed))
            outs, _ = _run_fleet(ticks, 2)
            return outs

        a, b = run(1), run(2)
        for i in range(2):
            assert len(a[i]) == len(b[i]) >= 1, i
            for (oa, ta, da), (ob, tb, db) in zip(a[i], b[i]):
                assert np.array_equal(
                    np.asarray(oa.ranges), np.asarray(ob.ranges)
                )
                assert np.array_equal(
                    np.asarray(oa.voxel), np.asarray(ob.voxel)
                )
                assert abs(ta - tb) < TS_TOL and abs(da - db) < TS_TOL

    def test_ans_type_switch_resets_stream_keeps_window(self):
        """One stream switches scan modes mid-run: that stream's decode
        state resets (host semantics) while its rolling filter window —
        and every other stream — carries straight through."""
        a1, a2 = Ans.MEASUREMENT_DENSE_CAPSULED, Ans.MEASUREMENT_HQ
        s0_first = _make_stream(a1, 24, _rng(), syncs=(0, 8, 16))
        s0_second = _make_stream(a2, 20, _rng(), syncs=(0, 5, 10, 15))
        s1 = _make_stream(a1, 44, _rng(), syncs=(0, 11, 22, 33))
        rng = np.random.default_rng(13)
        t1 = _mk_ticks([(int(a1), s0_first), (DENSE, s1[:22])], rng)
        t2 = _mk_ticks([(int(a2), s0_second), (DENSE, s1[22:])], rng)
        # keep stream 1's stream continuous across the two phases: shift
        # phase-2 stamps after phase 1 and re-feed as one tick sequence
        ticks = t1 + t2
        # host reference needs the SAME per-stream byte order; feed the
        # tick list as-is (the host decoder resets itself on the type
        # change, and stream 1's frames keep their carries through it)
        host = _host_reference(ticks, 2)
        fused, _ = _run_fleet(ticks, 2)
        _assert_fleet_outputs_equal(host, fused, min_revs=4)

    def test_max_revs_overflow_drops_oldest(self):
        """More completions in one dispatch than max_revs: oldest drop,
        counted per stream, survivors are the newest (the single-stream
        engine's assembler-double-buffer semantics, per lane)."""
        ans = Ans.MEASUREMENT  # 1 node/frame: syncs land densely
        frames = _make_stream(ans, 16, _rng(), syncs=tuple(range(0, 16, 2)))
        ticks = []
        t = 50.0
        for i in range(0, len(frames), 4):
            batch = []
            for f in frames[i : i + 4]:
                t += 0.002
                batch.append((f, t))
            ticks.append([(int(ans), list(batch)), (int(ans), list(batch))])
        fused, fleet = _run_fleet(ticks, 2, max_revs=1, pipelined=False)
        assert fleet.revs_dropped > 0
        host = _host_reference(ticks, 2)
        for i in range(2):
            assert len(fused[i]) < len(host[i])
            host_ts0 = np.array([h[1] for h in host[i]])
            for _, ts0, _ in fused[i]:
                assert np.min(np.abs(host_ts0 - ts0)) < TS_TOL


class TestSnapshotRestore:
    def test_snapshot_restore_mid_stream(self):
        """Snapshot mid-stream, restore into a FRESH engine, continue the
        byte stream: the restored fleet's outputs are identical to the
        uninterrupted run's — per-stream partial revolutions, decode
        carries, filter windows, formats and timestamp bases all make the
        round trip."""
        sf = [
            (DENSE, _make_stream(
                Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(), syncs=(0,)
            ))
            for i in range(2)
        ]
        ticks = _mk_ticks(sf, np.random.default_rng(17))
        cut = len(ticks) // 2
        params = _params()

        # uninterrupted run
        ref, _ = _run_fleet(ticks, 2, params, pipelined=False)

        # run half, snapshot, restore into a fresh engine, run the rest
        a = FleetFusedIngest(params, 2, beams=BEAMS, max_revs=6, buckets=(4,))
        outs = [[] for _ in range(2)]
        for tick in ticks[:cut]:
            for i, o in enumerate(a.submit(tick)):
                outs[i].extend(o)
        snap = a.snapshot()
        b = FleetFusedIngest(params, 2, beams=BEAMS, max_revs=6, buckets=(4,))
        assert b.restore(snap)
        for tick in ticks[cut:]:
            for i, o in enumerate(b.submit(tick)):
                outs[i].extend(o)
        for i, o in enumerate(b.flush()):
            outs[i].extend(o)

        for i in range(2):
            assert len(outs[i]) == len(ref[i]) >= 1, i
            for (oa, ta, da), (ob, tb, db) in zip(outs[i], ref[i]):
                for field in ("ranges", "voxel"):
                    assert np.array_equal(
                        np.asarray(getattr(oa, field)),
                        np.asarray(getattr(ob, field)),
                    ), (i, field)
                assert abs(ta - tb) < TS_TOL and abs(da - db) < TS_TOL

    def test_restore_rejects_wrong_geometry(self):
        params = _params()
        a = FleetFusedIngest(params, 2, beams=BEAMS, buckets=(4,))
        snap = a.snapshot()
        b = FleetFusedIngest(params, 3, beams=BEAMS, buckets=(4,))
        assert not b.restore(snap)
        assert not b.restore({"bogus": np.zeros(3)})


class TestServiceSeam:
    def test_resolver_and_validation(self):
        assert resolve_fleet_ingest_backend("auto") == "host"
        assert resolve_fleet_ingest_backend("auto", "tpu") == "host"
        assert resolve_fleet_ingest_backend("fused") == "fused"
        with pytest.raises(ValueError):
            DriverParams(fleet_ingest_backend="warp").validate()
        with pytest.raises(ValueError):
            DriverParams(fleet_ingest_backend="fused").validate()
        _params(fleet_ingest_backend="fused").validate()

    def test_submit_bytes_both_backends(self):
        """The service's raw-bytes tick seam: the fused backend returns
        each stream's newest completed revolution (bit-exact vs the
        independent-chain reference), the host backend feeds the lockstep
        batched tick; both accept the same per-stream byte runs."""
        from rplidar_ros2_driver_tpu.parallel.service import (
            ShardedFilterService,
        )

        frames = _make_stream(
            Ans.MEASUREMENT_DENSE_CAPSULED, 40, _rng(), syncs=(0, 10, 25)
        )
        sf = [(DENSE, frames), (DENSE, frames)]
        ticks = _mk_ticks(sf, np.random.default_rng(23), idle_prob=0.0)

        svc_f = ShardedFilterService(
            _params(fleet_ingest_backend="fused"), 2, beams=BEAMS,
            fleet_ingest_buckets=(4,),
        )
        got_f = []
        for tick in ticks:
            got_f.append(svc_f.submit_bytes(tick))
        assert svc_f.fleet_ingest is not None
        newest_f = [
            [r[i] for r in got_f if r[i] is not None] for i in range(2)
        ]
        host = _host_reference(ticks, 2)
        for i in range(2):
            assert len(newest_f[i]) >= 1
            # the service returns newest-per-tick; with <= max_revs
            # completions per tick every host revolution surfaces
            assert len(newest_f[i]) == len(host[i])
            for out, (ho, _, _) in zip(newest_f[i], host[i]):
                assert np.array_equal(
                    np.asarray(out.ranges), np.asarray(ho.ranges)
                )

        svc_h = ShardedFilterService(
            _params(fleet_ingest_backend="host"), 2, beams=BEAMS
        )
        svc_h.precompile()
        got_h = []
        for tick in ticks:
            got_h.append(svc_h.submit_bytes(tick))
        published = sum(
            r is not None for tick_out in got_h for r in tick_out
        )
        assert published >= 2  # both streams published through the seam
