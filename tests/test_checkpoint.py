"""Durable checkpoint/resume tests (utils/checkpoint.py + node wiring).

The reference has no checkpointing (stateless streaming, SURVEY.md §5);
this framework's rolling window + voxel accumulator are real state, so
snapshot/save/load/restore must round-trip bit-exactly and refuse
geometry mismatches.
"""

from __future__ import annotations

import os
import time

import numpy as np

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.node.node import RPlidarNode
from rplidar_ros2_driver_tpu.utils.checkpoint import load_checkpoint, save_checkpoint


def _params(**kw) -> DriverParams:
    base = dict(
        dummy_mode=True,
        filter_chain=("clip", "median", "voxel"),
        filter_window=4,
        voxel_grid_size=32,
    )
    base.update(kw)
    return DriverParams(**base)


def _fill_chain(chain: ScanFilterChain, n: int = 6) -> None:
    rng = np.random.default_rng(7)
    for k in range(n):
        pts = 180
        chain.process_raw(
            ((np.arange(pts) * 65536) // pts).astype(np.int32),
            (rng.uniform(1000, 9000, pts)).astype(np.int32),
            np.full(pts, 150, np.int32),
        )


class TestFileFormat:
    def test_roundtrip_bit_exact(self, tmp_path):
        snap = {
            "window": np.arange(24, dtype=np.float32).reshape(2, 3, 4),
            "cursor": np.asarray(5, np.int32),
        }
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, snap, extra={"node": "x"})
        loaded = load_checkpoint(p)
        assert loaded is not None
        got, meta = loaded
        assert set(got) == set(snap)
        for k in snap:
            np.testing.assert_array_equal(got[k], snap[k])
        assert meta["extra"]["node"] == "x"

    def test_missing_file(self, tmp_path):
        assert load_checkpoint(str(tmp_path / "nope.npz")) is None

    def test_torn_file_rejected(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, {"a": np.zeros(64, np.float32)})
        raw = open(p, "rb").read()
        with open(p, "wb") as f:
            f.write(raw[: len(raw) // 2])  # simulate crash mid-write of a NON-atomic writer
        assert load_checkpoint(p) is None

    def test_no_tmp_residue(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, {"a": np.zeros(4, np.float32)})
        assert [f for f in os.listdir(tmp_path)] == ["ck.npz"]

    def test_truncated_at_every_cut_rejected(self, tmp_path):
        """A torn write of ANY length (power loss through a non-atomic
        copy of the file) is a clean None, never a crash."""
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, {"a": np.arange(64, dtype=np.int32)})
        raw = open(p, "rb").read()
        for cut in range(0, len(raw), max(1, len(raw) // 23)):
            with open(p, "wb") as f:
                f.write(raw[:cut])
            assert load_checkpoint(p) is None, cut

    def test_crc_mismatch_rejected(self, tmp_path):
        """A corrupt-but-well-formed npz (unzips, parses, matches the
        manifest's shape/dtype — e.g. storage-layer corruption, or a
        buggy writer pairing a stale payload with a fresh manifest) is
        caught ONLY by the per-array CRC32 leg."""
        import json
        import zipfile

        p = str(tmp_path / "ck.npz")
        arr = np.arange(64, dtype=np.int32)
        save_checkpoint(p, {"a": arr, "b": np.ones(3, np.float32)})
        with np.load(p) as z:
            meta_raw = z["__meta__"]
        bad = arr.copy()
        bad[17] ^= 1  # one flipped bit, same shape/dtype
        with open(p, "wb") as f:
            np.savez(f, __meta__=meta_raw, a=bad,
                     b=np.ones(3, np.float32))
        with zipfile.ZipFile(p) as z:  # well-formed as a zip...
            assert z.testzip() is None
        meta = json.loads(meta_raw.tobytes())
        assert meta["arrays"]["a"]["shape"] == [64]  # ...and manifest
        assert load_checkpoint(p) is None  # only the CRC catches it

    def test_manifest_array_missing_rejected(self, tmp_path):
        p = str(tmp_path / "ck.npz")
        save_checkpoint(p, {"a": np.arange(8, dtype=np.int32),
                            "b": np.zeros(2, np.float32)})
        with np.load(p) as z:
            meta_raw, b = z["__meta__"], z["b"]
        with open(p, "wb") as f:
            np.savez(f, __meta__=meta_raw, b=b)  # "a" vanished
        assert load_checkpoint(p) is None

    def test_pre_crc_checkpoint_still_loads(self, tmp_path):
        """Checkpoints written before the crc32 manifest field carry
        shape/dtype only; they must keep loading (the CRC leg is
        skipped, not required)."""
        import json

        p = str(tmp_path / "ck.npz")
        arr = np.arange(16, dtype=np.int32)
        save_checkpoint(p, {"a": arr})
        with np.load(p) as z:
            meta = json.loads(z["__meta__"].tobytes())
        for spec in meta["arrays"].values():
            del spec["crc32"]
        with open(p, "wb") as f:
            np.savez(f, __meta__=np.frombuffer(
                json.dumps(meta).encode(), np.uint8
            ), a=arr)
        loaded = load_checkpoint(p)
        assert loaded is not None
        np.testing.assert_array_equal(loaded[0]["a"], arr)


class TestChainResume:
    def test_chain_state_survives_disk_roundtrip(self, tmp_path):
        params = _params()
        chain = ScanFilterChain(params, beams=256)
        _fill_chain(chain)
        snap = chain.snapshot()
        p = str(tmp_path / "chain.npz")
        save_checkpoint(p, snap)
        snap2, _ = load_checkpoint(p)

        chain2 = ScanFilterChain(params, beams=256)
        chain2.restore(snap2)
        for k, v in chain.snapshot().items():
            np.testing.assert_array_equal(np.asarray(v), np.asarray(chain2.snapshot()[k]))

    def test_snapshot_format_identical_across_median_backends(self):
        # median_sorted is derived state and must not leak into the
        # checkpoint surface: an "inc" chain's snapshot restores into an
        # "xla" chain and vice versa, bit-exactly
        chains = {
            b: ScanFilterChain(_params(median_backend=b), beams=256)
            for b in ("xla", "inc")
        }
        for c in chains.values():
            _fill_chain(c)
        snaps = {b: c.snapshot() for b, c in chains.items()}
        assert set(snaps["xla"]) == set(snaps["inc"])
        assert "median_sorted" not in snaps["inc"]
        # cross-restore both directions; continued medians stay in parity
        chains["xla"].restore(snaps["inc"])
        chains["inc"].restore(snaps["xla"])
        # the inc chain recomputed its sorted window on restore
        ms = np.asarray(chains["inc"].state.median_sorted)
        np.testing.assert_array_equal(
            ms, np.sort(np.asarray(chains["inc"].state.range_window), axis=0)
        )
        rng = np.random.default_rng(9)
        pts = 180
        angle = ((np.arange(pts) * 65536) // pts).astype(np.int32)
        dist = (rng.uniform(1000, 9000, pts)).astype(np.int32)
        qual = np.full(pts, 150, np.int32)
        outs = {b: c.process_raw(angle, dist, qual) for b, c in chains.items()}
        # both chains now hold the SAME history (swapped snapshots came
        # from identically-filled chains), so outputs must agree
        np.testing.assert_array_equal(outs["xla"].ranges, outs["inc"].ranges)

    def test_rejected_restore_leaves_live_state_untouched(self, tmp_path):
        """A bad restore must not cold-reset a populated chain."""
        chain = ScanFilterChain(_params(), beams=256)
        _fill_chain(chain)
        before = chain.snapshot()
        bad = ScanFilterChain(_params(filter_window=8), beams=256)
        _fill_chain(bad, n=2)
        assert not bad.restore(before)  # mismatch rejected...
        after = bad.snapshot()
        populated = ScanFilterChain(_params(filter_window=8), beams=256)
        _fill_chain(populated, n=2)
        for k in after:  # ...and bad's own accumulated state survived
            np.testing.assert_array_equal(after[k], populated.snapshot()[k])

    def test_geometry_mismatch_starts_cold(self, tmp_path):
        chain = ScanFilterChain(_params(), beams=256)
        _fill_chain(chain)
        p = str(tmp_path / "chain.npz")
        save_checkpoint(p, chain.snapshot())
        snap, _ = load_checkpoint(p)
        bigger = ScanFilterChain(_params(filter_window=8), beams=256)
        assert not bigger.restore(snap)  # incompatible -> rejected, no crash
        cold = ScanFilterChain(_params(filter_window=8), beams=256)
        for k, v in vars(cold.state).items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(vars(bigger.state)[k])
            )


class TestNodeWiring:
    def _run_node(self, params, scans=2, timeout=10.0):
        node = RPlidarNode(params)
        assert node.configure() and node.activate()
        t0 = time.monotonic()
        while node.publisher.scan_count < scans and time.monotonic() - t0 < timeout:
            time.sleep(0.02)
        node.deactivate()
        return node

    def test_node_save_load_resume(self, tmp_path):
        p = str(tmp_path / "node.npz")
        node = self._run_node(_params())
        assert node.save_checkpoint(p)
        ref = node._chain_snapshot
        node.cleanup()
        node.shutdown()

        node2 = RPlidarNode(_params())
        assert node2.load_checkpoint(p)
        assert node2.configure()
        for k, v in ref.items():
            np.testing.assert_array_equal(
                np.asarray(v), np.asarray(node2.chain.snapshot()[k])
            )
        node2.cleanup()
        node2.shutdown()

    def test_save_without_chain_is_false(self, tmp_path):
        node = RPlidarNode(DriverParams(dummy_mode=True))  # no filter chain
        assert not node.save_checkpoint(str(tmp_path / "x.npz"))

    def test_load_missing_is_false(self, tmp_path):
        node = RPlidarNode(_params())
        assert not node.load_checkpoint(str(tmp_path / "absent.npz"))

    def test_load_incompatible_geometry_is_false(self, tmp_path):
        """A saved window=4 checkpoint must not claim to resume into a
        window=8 node, nor stay staged for later configures."""
        p = str(tmp_path / "node.npz")
        node = self._run_node(_params(filter_window=4))
        assert node.save_checkpoint(p)
        node.cleanup()
        node.shutdown()

        node2 = RPlidarNode(_params(filter_window=8))
        assert not node2.load_checkpoint(p)
        assert node2._chain_snapshot is None

    def test_load_without_filter_chain_is_false(self, tmp_path):
        p = str(tmp_path / "node.npz")
        node = self._run_node(_params())
        assert node.save_checkpoint(p)
        node.cleanup()
        node.shutdown()
        plain = RPlidarNode(DriverParams(dummy_mode=True))
        assert not plain.load_checkpoint(p)
