"""Unit tests for the per-stage latency tracer (utils/tracing.py) — the
node's `--stats` output and the bench's stage decompositions both read
through this surface, so its ring-capacity and percentile behavior are
load-bearing."""

import threading

import numpy as np

from rplidar_ros2_driver_tpu.utils.tracing import StageTimer


def test_stage_and_record_accumulate():
    t = StageTimer()
    with t.stage("a"):
        pass
    t.record("a", 0.010)
    t.record("b", 0.500)
    s = t.summary()
    assert s["a"]["n"] == 2
    assert s["b"]["p50_ms"] == 500.0
    assert s["b"]["max_ms"] == 500.0
    assert np.isfinite(s["a"]["p99_ms"])


def test_ring_capacity_keeps_newest():
    t = StageTimer(capacity=8)
    for k in range(100):
        t.record("x", float(k))
    s = t.summary()["x"]
    assert s["n"] == 8
    # oldest samples were evicted: the minimum surviving value is 92
    assert t.percentile("x", 0) == 92.0
    assert s["max_ms"] == 99.0 * 1e3


def test_percentile_of_unknown_stage_is_nan():
    t = StageTimer()
    assert np.isnan(t.percentile("nope", 99))
    assert t.summary() == {}


def test_reset_clears():
    t = StageTimer()
    t.record("a", 1.0)
    t.reset()
    assert t.summary() == {}


def test_concurrent_recording_is_safe():
    t = StageTimer(capacity=1024)
    errors = []

    def worker(name):
        try:
            for k in range(500):
                t.record(name, k * 1e-6)
                if k % 50 == 0:
                    t.summary()
        except BaseException as e:  # noqa: BLE001
            errors.append(e)

    threads = [
        threading.Thread(target=worker, args=(f"s{i % 3}",)) for i in range(6)
    ]
    for th in threads:
        th.start()
    for th in threads:
        th.join(10.0)
        assert not th.is_alive()
    assert not errors, errors
    total = sum(v["n"] for v in t.summary().values())
    assert total == 6 * 500  # capacity 1024 per stage, 2 threads/stage
