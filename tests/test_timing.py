"""Timestamp back-dating tests (protocol/timing.py + assembler/driver wiring).

The reference dates every node ``now − (uart transmission + sample +
grouping delay)`` (handler_normalnode.cpp:51-68, handler_capsules.cpp:55-76)
and exposes per-scan begin timestamps via grabScanDataHqWithTimeStamp
(sl_lidar_driver.cpp:783-806).
"""

from __future__ import annotations

import time

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.driver.assembly import ScanAssembler
from rplidar_ros2_driver_tpu.protocol.constants import (
    ANS_PAYLOAD_BYTES,
    Ans,
)
from rplidar_ros2_driver_tpu.protocol.timing import (
    ETHERNET_DUMMY_TRANSMISSION_US,
    LEGACY_SAMPLE_DURATION_US,
    SAMPLES_PER_FRAME,
    TimingDesc,
    frame_rx_delay_us,
    frame_sample_times,
    sample_delay_us,
)


def _ref_delay_us(ans: Ans, timing: TimingDesc, idx: int) -> int:
    """Independent scalar transcription of the reference's per-handler
    delay functions (_getSampleDelayOffsetIn{LegacyMode,ExpressMode,
    UltraBoostMode,DenseMode,UltraDenseMode,HQMode}; handler_normalnode.cpp:
    51-68, handler_capsules.cpp:55-76,272-293,586-607,796-817,
    handler_hqnode.cpp:54-73).  All-integer u64 math, per-format default
    bauds, ethernet 100 µs dummy; grouping (N-1-idx)*dur for the capsule
    formats only."""
    defaults = {
        Ans.MEASUREMENT: 115200,
        Ans.MEASUREMENT_CAPSULED: 115200,
        Ans.MEASUREMENT_CAPSULED_ULTRA: 256000,
        Ans.MEASUREMENT_DENSE_CAPSULED: 256000,
        Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: 1000000,
        Ans.MEASUREMENT_HQ: 1000000,
    }
    dur = int(timing.sample_duration_us + 0.5)
    if not timing.is_serial:
        trans = 100
    else:
        baud = timing.native_baudrate or defaults[ans]
        trans = 1_000_000 * ANS_PAYLOAD_BYTES[ans] * 10 // baud
    sample_delay = dur >> 1
    sample_filter_delay = dur
    grouping = {
        Ans.MEASUREMENT: 0,
        Ans.MEASUREMENT_HQ: 0,
        Ans.MEASUREMENT_CAPSULED: (32 - 1 - idx) * dur,
        Ans.MEASUREMENT_CAPSULED_ULTRA: (32 * 3 - 1 - idx) * dur,
        Ans.MEASUREMENT_DENSE_CAPSULED: (40 - 1 - idx) * dur,
        Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED: (32 * 2 - 1 - idx) * dur,
    }[ans]
    return sample_filter_delay + sample_delay + trans + timing.linkage_delay_us + grouping


class TestDelayModel:
    def test_transmission_time_matches_8n1_at_native_baud(self):
        t = TimingDesc(sample_duration_us=65.0, native_baudrate=1_000_000)
        # 84-byte capsule at 1 Mbaud: 84*10 bits / 1e6 = 840 us
        assert t.transmission_us(Ans.MEASUREMENT_CAPSULED) == 840

    def test_network_link_uses_ethernet_dummy(self):
        """Non-serial links get the reference's fixed 100 µs stand-in
        (the "dummy value" ethernet branch in every handler)."""
        t = TimingDesc(sample_duration_us=65.0, is_serial=False)
        assert t.transmission_us(Ans.MEASUREMENT_CAPSULED) == ETHERNET_DUMMY_TRANSMISSION_US

    def test_unknown_native_baud_falls_back_per_format(self):
        t = TimingDesc(sample_duration_us=65.0, native_baudrate=0)
        # express guesses 115200, ultra-dense guesses 1 Mbaud (handlers)
        assert t.transmission_us(Ans.MEASUREMENT_CAPSULED) == 84 * 10 * 1_000_000 // 115200
        assert t.transmission_us(Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED) == (
            ANS_PAYLOAD_BYTES[Ans.MEASUREMENT_ULTRA_DENSE_CAPSULED] * 10
        )

    def test_frame_delay_orders_by_density(self):
        """Denser frames carry older first samples (more grouping delay)."""
        t = TimingDesc(sample_duration_us=65.0, native_baudrate=256000)
        d_norm = frame_rx_delay_us(Ans.MEASUREMENT, t)
        d_caps = frame_rx_delay_us(Ans.MEASUREMENT_CAPSULED, t)
        d_ultra = frame_rx_delay_us(Ans.MEASUREMENT_CAPSULED_ULTRA, t)
        assert d_norm < d_caps < d_ultra

    @pytest.mark.parametrize("ans", sorted(SAMPLES_PER_FRAME, key=int))
    @pytest.mark.parametrize("dur", [31.25, 65.0, 476.0])
    def test_per_sample_delay_matches_reference_model(self, ans, dur):
        """All 6 formats, every sample index: reference-exact parity."""
        for timing in (
            TimingDesc(sample_duration_us=dur, native_baudrate=0),
            TimingDesc(sample_duration_us=dur, native_baudrate=256000),
            TimingDesc(sample_duration_us=dur, is_serial=False),
        ):
            for idx in range(SAMPLES_PER_FRAME[ans]):
                assert sample_delay_us(ans, timing, idx) == _ref_delay_us(
                    ans, timing, idx
                ), (ans, timing, idx)

    @pytest.mark.parametrize("ans", sorted(SAMPLES_PER_FRAME, key=int))
    def test_frame_sample_times_equal_per_index_evaluation(self, ans):
        """The vectorized per-frame stamps are exactly rx − delay(idx)."""
        timing = TimingDesc(sample_duration_us=65.0, native_baudrate=256000)
        rx = 1234.5
        times = frame_sample_times(ans, timing, rx)
        assert times.shape == (SAMPLES_PER_FRAME[ans],)
        for idx in range(SAMPLES_PER_FRAME[ans]):
            assert times[idx] == pytest.approx(
                rx - 1e-6 * sample_delay_us(ans, timing, idx), abs=1e-9
            )

    def test_unknown_ans_type_is_zero(self):
        assert frame_rx_delay_us(0x42, TimingDesc()) == 0.0
        assert frame_rx_delay_us(int(Ans.DEVINFO), TimingDesc()) == 0.0

    def test_legacy_default(self):
        assert TimingDesc().sample_duration_us == LEGACY_SAMPLE_DURATION_US


def _push_rev(asm: ScanAssembler, n: int, ts: float, sync_first=True) -> None:
    flag = np.zeros(n, np.int32)
    if sync_first:
        flag[0] = 1
    asm.push_nodes(
        ((np.arange(n) * 65536) // n).astype(np.int32),
        np.full(n, 4000, np.int32),
        np.full(n, 200, np.int32),
        flag,
        ts=ts,
    )


class TestAssemblerTimestamps:
    def test_begin_ts_and_duration(self):
        asm = ScanAssembler()
        _push_rev(asm, 90, ts=100.0)   # opens rev @100
        _push_rev(asm, 90, ts=100.1)   # closes rev -> duration 0.1, opens @100.1
        got = asm.wait_and_grab_with_timestamp(0.1)
        assert got is not None
        batch, ts0, dur = got
        assert ts0 == pytest.approx(100.0)
        assert dur == pytest.approx(0.1)
        assert int(batch.count) == 90

    def test_default_ts_is_now(self):
        asm = ScanAssembler()
        t0 = time.monotonic()
        _push_rev(asm, 10, ts=None)
        _push_rev(asm, 10, ts=None)
        _, ts0, dur = asm.wait_and_grab_with_timestamp(0.1)
        assert abs(ts0 - t0) < 1.0
        assert dur >= 0

    def test_wait_and_grab_still_returns_batch_only(self):
        asm = ScanAssembler()
        _push_rev(asm, 10, ts=1.0)
        _push_rev(asm, 10, ts=2.0)
        batch = asm.wait_and_grab(0.1)
        assert int(batch.count) == 10


class TestDriverWiring:
    def test_decoder_backdates_against_sim(self):
        """End-to-end: driver + protocol simulator; revolution begin
        timestamps must trail wall clock (back-dated) and durations must
        approximate the simulated spin period."""
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="tcp",
                tcp_host="127.0.0.1",
                tcp_port=sim.port,
                motor_warmup_s=0.0,
            )
            assert drv.connect("sim", 0, True)
            assert drv.start_motor("", 600)
            got = drv.grab_scan_data_with_timestamp(5.0)
            assert got is not None
            batch, ts0, dur = got
            assert int(batch.count) > 0
            assert ts0 <= time.monotonic()
            assert dur > 0
            # timing desc was pushed on scan start
            assert drv._scan_decoder.timing.sample_duration_us > 0
            assert not drv._scan_decoder.timing.is_serial  # tcp link
            drv.stop_motor()
            drv.disconnect()
        finally:
            sim.stop()


class TestFrequencyAndDiag:
    def test_get_frequency_from_sim(self):
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice

        sim = SimulatedDevice().start()
        try:
            drv = RealLidarDriver(
                channel_type="tcp", tcp_host="127.0.0.1", tcp_port=sim.port,
                motor_warmup_s=0.0,
            )
            assert drv.connect("sim", 0, True)
            assert drv.get_frequency(1000) is None  # not scanning yet
            assert drv.start_motor("", 600)
            f = drv.get_frequency(1000)
            assert f is not None and f > 0
            us = drv._scan_decoder.timing.sample_duration_us
            assert f == pytest.approx(1e6 / (us * 1000))
            drv.stop_motor()
            drv.disconnect()
        finally:
            sim.stop()

    def test_diagnostics_carry_latency_p99(self):
        import time as _time

        from rplidar_ros2_driver_tpu.core.config import DriverParams
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode

        node = RPlidarNode(DriverParams(dummy_mode=True))
        assert node.configure() and node.activate()
        t0 = _time.monotonic()
        while node.publisher.scan_count < 2 and _time.monotonic() - t0 < 10:
            _time.sleep(0.02)
        node._update_diagnostics()
        d = node.diagnostics.last
        assert any(k.startswith("p99 ") for k in d.values), d.values
        # dummy driver has no rx thread: the scheduling field is omitted
        assert "RX Scheduling" not in d.values
        node.deactivate(); node.cleanup(); node.shutdown()

    def test_diagnostics_carry_rx_scheduling_for_real_driver(self):
        """Against the protocol sim, /diagnostics surfaces the scheduling
        class the rx thread achieved (the observable for the reference's
        PRIORITY_HIGH contract, sl_async_transceiver.cpp:299-409)."""
        from rplidar_ros2_driver_tpu.core.config import DriverParams
        from rplidar_ros2_driver_tpu.driver.real import RealLidarDriver
        from rplidar_ros2_driver_tpu.driver.sim_device import SimulatedDevice
        from rplidar_ros2_driver_tpu.node.node import RPlidarNode, launch

        sim = SimulatedDevice().start()
        node = None
        try:
            node = RPlidarNode(
                DriverParams(channel_type="tcp"),
                driver_factory=lambda: RealLidarDriver(
                    channel_type="tcp", tcp_host="127.0.0.1",
                    tcp_port=sim.port, motor_warmup_s=0.0,
                ),
            )
            launch(node)
            import time as _time
            t0 = _time.monotonic()
            while node.publisher.scan_count < 1 and _time.monotonic() - t0 < 10:
                _time.sleep(0.02)
            node._update_diagnostics()
            d = node.diagnostics.last
            assert d.values.get("RX Scheduling") in (
                # "no elevation" is the pure-Python transport's report
                # (rx_sched_class -1) on hosts without the native library
                "SCHED_RR", "nice boost", "default", "no elevation", "n/a"
            ), d.values
        finally:
            if node is not None:
                node.shutdown()
            sim.stop()
