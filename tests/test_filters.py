"""Filter-chain kernels: clip, grid resample, temporal median, voxel
occupancy, state ring semantics, checkpoint/restore, and the LaserScan /
ascend kernels against numpy oracles."""

import dataclasses

import jax.numpy as jnp
import numpy as np
import pytest

from rplidar_ros2_driver_tpu.core.config import DriverParams
from rplidar_ros2_driver_tpu.core.types import ScanBatch
from rplidar_ros2_driver_tpu.driver.dummy import synth_scan
from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
from rplidar_ros2_driver_tpu.ops import filters
from rplidar_ros2_driver_tpu.ops.ascend import ascend_scan
from rplidar_ros2_driver_tpu.ops.laserscan import to_laserscan


def make_batch(angles_deg, dists_m, quality=200, n=1024):
    angles_q14 = (np.asarray(angles_deg) * 16384.0 / 90.0).astype(np.int64)
    dist_q2 = (np.asarray(dists_m) * 4000.0).astype(np.int64)
    q = np.full(len(angles_deg), quality, np.int64)
    return ScanBatch.from_numpy(angles_q14, dist_q2, q, n=n)


CFG = filters.FilterConfig(window=4, beams=256, grid=64, cell_m=0.25)


class TestClip:
    def test_out_of_range_zeroed(self):
        b = make_batch([0, 10, 20, 30], [0.05, 1.0, 50.0, 2.0])
        cfg = dataclasses.replace(CFG, range_max_m=40.0)
        out = filters.clip_filter(b, cfg)
        d = np.asarray(out.dist_q2)[:4]
        assert d[0] == 0       # below 0.15 m
        assert d[1] == 4000
        assert d[2] == 0       # above 40 m
        assert d[3] == 8000


class TestClipFusion:
    def test_fused_clip_identical_to_standalone_pass(self):
        """The step folds the clip predicate into the resample-key mask;
        it must be bit-identical to the standalone clip_filter pass:
        step(raw, clip enabled) == step(clip_filter(raw), clip
        disabled), for both resample backends."""
        rng = np.random.default_rng(5)
        n = 300
        b = make_batch(
            np.sort(rng.uniform(0, 360, n)),
            rng.uniform(0.01, 60.0, n),          # spans both clip bounds
            quality=rng.integers(0, 255, n),
        )
        for backend in ("scatter", "dense"):
            cfg = dataclasses.replace(
                CFG, range_max_m=40.0, intensity_min=20.0,
                resample_backend=backend,
            )
            cfg_noclip = dataclasses.replace(cfg, enable_clip=False)
            s1 = filters.FilterState.for_config(cfg)
            s2 = filters.FilterState.for_config(cfg_noclip)
            _, out_fused = filters.filter_step(s1, b, cfg)
            _, out_two_pass = filters.filter_step(
                s2, filters.clip_filter(b, cfg), cfg_noclip
            )
            np.testing.assert_array_equal(
                np.asarray(out_fused.ranges), np.asarray(out_two_pass.ranges)
            )
            np.testing.assert_array_equal(
                np.asarray(out_fused.intensities),
                np.asarray(out_two_pass.intensities),
            )
            np.testing.assert_array_equal(
                np.asarray(out_fused.voxel), np.asarray(out_two_pass.voxel)
            )


class TestGridResample:
    def test_min_range_wins_per_beam(self):
        # two points in the same beam: nearer one wins
        b = make_batch([10.0, 10.4, 100.0], [3.0, 2.0, 5.0])
        ranges, inten = filters.grid_resample(b, 256)
        ranges = np.asarray(ranges)
        beam = int((10.0 * 65536 / 360) * 256 // 65536)
        assert ranges[beam] == pytest.approx(2.0)
        assert np.isfinite(ranges).sum() == 2

    def test_empty_beams_are_inf(self):
        b = make_batch([0.0], [1.0])
        ranges, _ = filters.grid_resample(b, 64)
        assert np.isinf(np.asarray(ranges)).sum() == 63


class TestTemporalMedian:
    def test_median_ignores_missing(self):
        w = jnp.asarray(
            np.array(
                [
                    [1.0, np.inf],
                    [3.0, np.inf],
                    [2.0, 5.0],
                    [np.inf, np.inf],
                ],
                np.float32,
            )
        )
        med = np.asarray(filters.temporal_median(w))
        assert med[0] == pytest.approx(2.0)  # lower median of {1,2,3}
        assert med[1] == pytest.approx(5.0)
        empty = filters.temporal_median(jnp.full((4, 1), jnp.inf))
        assert np.isinf(np.asarray(empty)[0])

    def test_median_denoises_outlier(self):
        state = filters.FilterState.create(CFG.window, CFG.beams, CFG.grid)
        clean = make_batch(np.arange(0, 360, 1.5), np.full(240, 2.0), n=1024)
        spiky = make_batch(np.arange(0, 360, 1.5), np.full(240, 9.0), n=1024)
        for b in (clean, clean, spiky, clean):
            state, out = filters.filter_step(state, b, CFG)
        med = np.asarray(out.ranges)
        finite = med[np.isfinite(med)]
        assert np.allclose(finite, 2.0)  # the 9 m spike scan is voted out


class TestIncrementalMedian:
    def test_sorted_replace_matches_resort(self):
        rng = np.random.default_rng(3)
        W, B = 16, 64
        ring = np.full((W, B), np.inf, np.float32)
        sor = np.sort(ring, axis=0)
        cursor = 0
        for step in range(120):
            new = rng.uniform(0.1, 40.0, B).astype(np.float32)
            new[rng.random(B) < 0.25] = np.inf        # missing returns
            if step % 7 == 0:
                new[:] = new[0]                        # heavy ties
            old = ring[cursor].copy()
            sor = np.asarray(
                filters.sorted_replace(
                    jnp.asarray(sor), jnp.asarray(old), jnp.asarray(new)
                )
            )
            ring[cursor] = new
            cursor = (cursor + 1) % W
            np.testing.assert_array_equal(sor, np.sort(ring, axis=0))

    def test_full_step_parity_inc_vs_xla(self):
        # medians (and therefore every downstream output) must be
        # bit-identical between the sort path and the incremental path,
        # through unfilled windows AND full wraparound
        cfgs = {
            b: filters.FilterConfig(
                window=6, beams=CFG.beams, grid=32, cell_m=0.25,
                median_backend=b,
            )
            for b in ("xla", "inc")
        }
        states = {
            b: filters.FilterState.create(
                c.window, c.beams, c.grid, with_sorted=(b == "inc")
            )
            for b, c in cfgs.items()
        }
        rng = np.random.default_rng(11)
        for k in range(15):  # > 2 full window wraps
            dist = np.full(240, 2.0 + 0.2 * k) + rng.normal(0, 0.05, 240)
            b = make_batch(np.arange(0, 360, 1.5), dist, n=1024)
            outs = {}
            for name in cfgs:
                states[name], outs[name] = filters.filter_step(
                    states[name], b, cfgs[name]
                )
            np.testing.assert_array_equal(
                np.asarray(outs["xla"].ranges), np.asarray(outs["inc"].ranges)
            )
            np.testing.assert_array_equal(
                np.asarray(outs["xla"].voxel), np.asarray(outs["inc"].voxel)
            )

    def test_fused_chunk_restores_inc_invariant(self):
        # the fused path re-sorts the carried state per chunk; streaming
        # steps after a fused chunk must continue bit-exactly
        cfg = filters.FilterConfig(
            window=4, beams=CFG.beams, grid=32, cell_m=0.25,
            median_backend="inc",
        )
        state = filters.FilterState.create(
            cfg.window, cfg.beams, cfg.grid, with_sorted=True
        )
        scans = [
            make_batch(np.arange(0, 360, 1.5), np.full(240, 2.0 + 0.3 * k), n=1024)
            for k in range(6)
        ]
        packed, counts = filters.pack_host_scans_compact(
            [
                {
                    "angle_q14": np.asarray(s.angle_q14),
                    "dist_q2": np.asarray(s.dist_q2),
                    "quality": np.asarray(s.quality),
                    "flag": None,
                }
                for s in scans
            ]
        )
        state, _ = filters.compact_filter_scan(
            state, jnp.asarray(packed), jnp.asarray(counts), cfg
        )
        assert state.median_sorted is not None
        np.testing.assert_array_equal(
            np.asarray(state.median_sorted),
            np.sort(np.asarray(state.range_window), axis=0),
        )
        # one more streaming step keeps parity with the xla path run
        # over the same full history
        nxt = make_batch(np.arange(0, 360, 1.5), np.full(240, 5.0), n=1024)
        state, out = filters.filter_step(state, nxt, cfg)
        np.testing.assert_array_equal(
            np.asarray(state.median_sorted),
            np.sort(np.asarray(state.range_window), axis=0),
        )

    def test_inc_requires_sorted_state(self):
        cfg = filters.FilterConfig(
            window=4, beams=CFG.beams, grid=32, cell_m=0.25,
            median_backend="inc",
        )
        state = filters.FilterState.create(cfg.window, cfg.beams, cfg.grid)
        b = make_batch(np.arange(0, 360, 1.5), np.full(240, 2.0), n=1024)
        with pytest.raises(ValueError, match="sorted window"):
            filters.filter_step(state, b, cfg)


class TestVoxel:
    def test_hits_land_in_cells(self):
        xy = jnp.asarray(np.array([[0.3, 0.3], [-0.3, 0.3], [100.0, 0.0]], np.float32))
        mask = jnp.asarray([True, True, True])
        grid = np.asarray(filters.voxel_hits(xy, mask, 64, 0.25))
        assert grid.sum() == 2  # out-of-grid point dropped
        assert grid[32 + 1, 32 + 1] == 1
        assert grid[32 - 2, 32 + 1] == 1

    def test_matmul_backend_bit_identical_to_scatter(self):
        # voxel_hits_matmul's contract is exactness: 0/1 bf16 products
        # accumulated in f32 are exact integers, so the MXU formulation
        # must match the scatter histogram bit for bit — including
        # masked and out-of-grid points
        rng = np.random.default_rng(7)
        xy = jnp.asarray(rng.uniform(-12, 12, (2048, 2)).astype(np.float32))
        mask = jnp.asarray(rng.random(2048) < 0.8)
        a = np.asarray(filters.voxel_hits(xy, mask, 64, 0.25))
        b = np.asarray(filters.voxel_hits_matmul(xy, mask, 64, 0.25))
        assert a.dtype == b.dtype == np.int32
        np.testing.assert_array_equal(a, b)
        # many points into ONE cell: accumulation exactness beyond 256
        # (where bf16 would saturate integer representation)
        xy1 = jnp.zeros((2048, 2), jnp.float32) + 0.1
        all_on = jnp.ones(2048, bool)
        m = np.asarray(filters.voxel_hits_matmul(xy1, all_on, 64, 0.25))
        assert m.sum() == 2048 and m.max() == 2048

    def test_full_step_parity_across_voxel_backends(self):
        outs = {}
        for backend in ("scatter", "matmul"):
            cfg = filters.FilterConfig(
                window=4, beams=CFG.beams, grid=32, cell_m=0.25,
                voxel_backend=backend,
            )
            state = filters.FilterState.create(cfg.window, cfg.beams, cfg.grid)
            for k in range(6):
                b = make_batch(
                    np.arange(0, 360, 1.5), np.full(240, 2.0 + 0.1 * k), n=1024
                )
                state, out = filters.filter_step(state, b, cfg)
            outs[backend] = np.asarray(out.voxel)
        np.testing.assert_array_equal(outs["scatter"], outs["matmul"])

    def test_window_accumulation_retires_old_scans(self):
        state = filters.FilterState.create(CFG.window, CFG.beams, CFG.grid)
        b = make_batch(np.arange(0, 360, 1.5), np.full(240, 2.0), n=1024)
        sums = []
        for _ in range(CFG.window + 3):
            state, out = filters.filter_step(state, b, CFG)
            sums.append(int(np.asarray(out.voxel).sum()))
        per_scan = sums[0]
        # grows until the ring is full, then plateaus at window * per-scan
        assert sums[CFG.window - 1] == CFG.window * per_scan
        assert sums[-1] == CFG.window * per_scan
        assert (np.asarray(state.voxel_acc) >= 0).all()


class TestChainHost:
    def _params(self, **kw):
        return DriverParams(
            dummy_mode=True,
            filter_backend="cpu",
            filter_window=4,
            filter_chain=("clip", "polar", "median", "voxel"),
            voxel_grid_size=64,
            **kw,
        )

    def test_process_and_snapshot_roundtrip(self):
        chain = ScanFilterChain(self._params(), beams=256)
        b = synth_scan(jnp.float32(0.0), count=360, capacity=8192)
        out1 = chain.process(b)
        snap = chain.snapshot()
        assert int(np.asarray(chain.state.filled)) == 1
        chain.reset()
        assert int(np.asarray(chain.state.filled)) == 0
        chain.restore(snap)
        assert int(np.asarray(chain.state.filled)) == 1
        out2 = chain.process(b)
        assert np.isfinite(np.asarray(out2.ranges)).sum() > 0
        assert np.asarray(out1.voxel).sum() > 0


class TestLaserScanKernel:
    """to_laserscan vs a direct numpy transliteration of publish_scan."""

    def _numpy_oracle(self, batch, duration, max_range, scan_processing, inverted, is_new):
        # float32 at every step, mirroring both the kernel and the C++
        # reference's all-float arithmetic (src/rplidar_node.cpp:586-603)
        angle = (
            np.asarray(batch.angle_q14).astype(np.float32) * np.float32(90.0 / 16384.0)
        ) * np.float32(np.pi / 180.0)
        dist = np.asarray(batch.dist_q2).astype(np.float32) * np.float32(1.0 / 4000.0)
        qual = np.asarray(batch.quality)
        valid = np.asarray(batch.valid) & (np.asarray(batch.dist_q2) != 0)
        inten = qual if is_new else (qual >> 2)
        a_v, d_v, q_v = angle[valid] % np.float32(2 * np.pi), dist[valid], inten[valid]
        # stable sort by angle alone — ties keep stream order, matching the
        # kernel (the reference's std::sort is unstable; tie order is free)
        order = np.argsort(a_v, kind="stable")
        pts = list(zip(a_v[order], d_v[order], q_v[order].astype(float)))
        count = len(pts)
        if scan_processing:
            # float32 throughout: both the kernel and the C++ reference
            # compute the beam index in single precision
            inc = np.float32(2 * np.pi) / np.float32(count)
            ranges = np.full(count, np.inf, np.float32)
            intens = np.zeros(count, np.float32)
            for a, d, q in pts:
                a = np.float32(a)
                if inverted:
                    a = np.float32(2 * np.pi) - a
                    if a >= np.float32(2 * np.pi):
                        a -= np.float32(2 * np.pi)
                idx = int(np.float32(a) / inc)
                if 0 <= idx < count and d < ranges[idx]:
                    ranges[idx] = d
                    intens[idx] = q
        else:
            ranges = np.zeros(count, np.float32)
            intens = np.zeros(count, np.float32)
            for i, (a, d, q) in enumerate(pts):
                idx = i if inverted else count - 1 - i
                ranges[idx] = d
                intens[idx] = q
        return ranges, intens, count

    @pytest.mark.parametrize("scan_processing", [False, True])
    @pytest.mark.parametrize("inverted", [False, True])
    @pytest.mark.parametrize("is_new", [False, True])
    def test_matches_oracle(self, scan_processing, inverted, is_new):
        rng = np.random.default_rng(7)
        n = 400
        angles_deg = np.sort(rng.uniform(0, 360, n))
        dists = rng.uniform(0.2, 10.0, n)
        dists[rng.random(n) < 0.1] = 0.0  # invalid points dropped
        b = make_batch(angles_deg, dists, quality=180, n=1024)
        msg = to_laserscan(
            b, 0.1, 12.0,
            scan_processing=scan_processing, inverted=inverted, is_new_type=is_new,
        )
        bc = int(msg.beam_count)
        ranges = np.asarray(msg.ranges)[:bc]
        intens = np.asarray(msg.intensities)[:bc]
        oracle_r, oracle_i, oracle_c = self._numpy_oracle(
            b, 0.1, 12.0, scan_processing, inverted, is_new
        )
        assert bc == oracle_c
        np.testing.assert_allclose(ranges, oracle_r, rtol=1e-6)
        np.testing.assert_allclose(intens, oracle_i, rtol=1e-6)

    def test_empty_scan(self):
        b = make_batch([10.0], [0.0])
        msg = to_laserscan(b, 0.1, 12.0)
        assert int(msg.beam_count) == 0

    @pytest.mark.parametrize("scan_processing", [False, True])
    def test_header_fields(self, scan_processing):
        """The ROS header contract (src/rplidar_node.cpp:614-631): full
        circle, increments derived from the valid point count, duration
        carried through, REP-117 bounds."""
        n_valid = 360
        angles_deg = np.linspace(0, 359, n_valid)
        b = make_batch(angles_deg, np.full(n_valid, 2.0), n=512)
        duration = 0.125
        msg = to_laserscan(b, duration, 12.0, scan_processing=scan_processing)
        count = int(msg.beam_count)
        assert count == n_valid
        assert float(msg.angle_min) == 0.0
        assert float(msg.angle_max) == pytest.approx(2 * np.pi)
        denom = count if scan_processing else count - 1
        assert float(msg.angle_increment) == pytest.approx(2 * np.pi / denom, rel=1e-6)
        assert float(msg.time_increment) == pytest.approx(duration / denom, rel=1e-6)
        assert float(msg.scan_time) == pytest.approx(duration)
        assert float(msg.range_min) == pytest.approx(0.15)
        assert float(msg.range_max) == pytest.approx(12.0)


class TestAscend:
    def test_invalid_angles_interpolated_and_sorted(self):
        angles = np.array([350.0, 10.0, 20.0, 30.0, 40.0])
        dists = np.array([0.0, 1.0, 0.0, 1.0, 1.0])
        b = make_batch(angles, dists, n=16)
        out, ok = ascend_scan(b)
        assert bool(ok)
        a = np.asarray(out.angle_q14)[:5] * 90.0 / 16384.0
        assert (np.diff(a) >= 0).all()  # sorted
        d = np.asarray(out.dist_q2)[:5]
        assert (d >= 0).all()

    def test_all_invalid_returns_not_ok(self):
        b = make_batch([10.0, 20.0], [0.0, 0.0], n=16)
        _, ok = ascend_scan(b)
        assert not bool(ok)


class TestBackendResolution:
    def test_auto_resolves_per_platform(self):
        from rplidar_ros2_driver_tpu.filters.chain import resolve_median_backend

        assert resolve_median_backend("auto", "tpu") == "pallas"
        # CPU: the incremental sliding median (3.8x full-step on the
        # CPU ablation; bit-exact vs the sort path); GPU keeps the sort
        # until it has its own measurement
        assert resolve_median_backend("auto", "cpu") == "inc"
        assert resolve_median_backend("auto", "gpu") == "xla"
        # explicit choices pass through regardless of platform
        assert resolve_median_backend("xla", "tpu") == "xla"
        assert resolve_median_backend("pallas", "cpu") == "pallas"
        # window-aware signature: no measured crossover yet, so depth
        # does not change the TPU mapping (the W=512 three-arm artifact
        # is what would move this — docs/BENCHMARKS.md decision table)
        assert resolve_median_backend("auto", "tpu", window=512) == "pallas"
        assert resolve_median_backend("inc", "tpu", window=64) == "inc"

    def test_resample_auto_resolves_per_platform(self):
        from rplidar_ros2_driver_tpu.filters.chain import (
            resolve_resample_backend,
        )

        # scatter everywhere pending an on-chip streaming-step ablation
        # artifact (the fused-path dense win does not transfer at K=1)
        assert resolve_resample_backend("auto", "cpu") == "scatter"
        assert resolve_resample_backend("auto", "tpu") == "scatter"
        assert resolve_resample_backend("dense", "cpu") == "dense"
        assert resolve_resample_backend("scatter", "tpu") == "scatter"

    def test_config_from_params_resolves_both_autos(self):
        from rplidar_ros2_driver_tpu.core.config import DriverParams
        from rplidar_ros2_driver_tpu.filters.chain import config_from_params

        cfg = config_from_params(DriverParams(), platform="tpu")
        assert cfg.median_backend == "pallas"
        assert cfg.resample_backend in ("scatter", "dense")  # resolved
        cfg = config_from_params(DriverParams(), platform="cpu")
        # CPU auto -> inc, pinned to the jnp lowering while the target
        # platform is known (inc_median's in-jit fallback can only see
        # the process default backend)
        assert cfg.median_backend == "inc_xla"
        assert cfg.resample_backend == "scatter"
        # explicit "inc" also gets pinned per platform
        cfg = config_from_params(
            DriverParams(median_backend="inc"), platform="tpu"
        )
        assert cfg.median_backend == "inc_pallas"
