"""REAL multi-process distributed run: 2 jax.distributed processes.

The other multihost tests exercise topology arithmetic in-process; this
one actually launches two controller processes (2 virtual CPU devices
each), joins them via `parallel.multihost.initialize`, builds the global
(2, 2) `(stream, beam)` mesh spanning both, and runs the fused sharded
fleet replay with the voxel all-reduce crossing the process boundary
(gloo-backed CPU collectives — the stand-in for ICI/DCN).  Each process
verifies the gathered result against a locally computed single-device
reference, so the test proves the cross-host program is bit-identical
to the single-chip math — the framework's analog of validating an
NCCL/MPI backend against the serial implementation.
"""

import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)

    from rplidar_ros2_driver_tpu.parallel import multihost
    assert multihost.is_configured()
    assert multihost.initialize()
    assert jax.process_count() == 2 and jax.device_count() == 4

    import numpy as np
    import jax.numpy as jnp
    from jax.sharding import NamedSharding
    from rplidar_ros2_driver_tpu.ops.filters import (
        FilterConfig, FilterState, compact_filter_scan, pack_host_scans_compact,
    )
    from rplidar_ros2_driver_tpu.parallel import sharding as sh

    # stream=1 deliberately: the BEAM axis must span both processes so
    # the voxel all-reduce genuinely crosses the process boundary (a
    # stream-major (2, 2) mesh would keep each stream's psum inside one
    # process and the test would pass with zero inter-process bytes)
    mesh = multihost.make_global_mesh(stream=1)
    assert dict(mesh.shape) == {"stream": 1, "beam": 4}

    cfg = FilterConfig(window=4, beams=64, grid=16, cell_m=0.5)
    streams, k, cap = 2, 6, 128

    # identical data on both controllers (SPMD contract)
    rng = np.random.default_rng(0)
    per_stream = []
    for s in range(streams):
        revs = []
        for j in range(k):
            n = 40 + 3 * j + s
            revs.append({
                "angle_q14": ((np.arange(n) * 65536) // n).astype(np.int32),
                "dist_q2": (rng.uniform(0.3, 6.0, n) * 4000).astype(np.int32),
                "quality": np.full(n, 180, np.int32),
            })
        per_stream.append(revs)
    seqs, counts = zip(*[pack_host_scans_compact(r, cap) for r in per_stream])
    seq_np = np.stack(seqs); counts_np = np.stack(counts).astype(np.int32)

    scan_fn = sh.build_sharded_scan(mesh, cfg)
    state = sh.create_sharded_state(mesh, cfg, streams)
    seq = jax.device_put(seq_np, NamedSharding(mesh, sh.SEQ_SPEC))
    cts = jax.device_put(counts_np, NamedSharding(mesh, sh.COUNTS_SPEC))
    state, ranges = scan_fn(state, seq, cts)

    # reassemble this process's addressable beam columns (half the beam
    # axis lives here; the other half only on the peer)
    got = np.full((streams, k, cfg.beams), np.nan, np.float32)
    cols = np.zeros(cfg.beams, bool)
    for shard in ranges.addressable_shards:
        idx = shard.index  # (stream slice, scan slice, beam slice)
        got[:, :, idx[2]] = np.asarray(shard.data)
        cols[idx[2]] = True
    assert cols.sum() == cfg.beams // 2, cols.sum()  # strictly half
    # voxel_acc is replicated over beam, and its VALUE depends on hit
    # grids from beams this process does NOT hold — equality with the
    # local reference proves the cross-process all-reduce delivered
    vox = np.asarray(state.voxel_acc.addressable_shards[0].data)

    for s in range(streams):
        st = FilterState.create(cfg.window, cfg.beams, cfg.grid)
        st, ref = compact_filter_scan(
            st, jnp.asarray(seq_np[s]), jnp.asarray(counts_np[s]), cfg
        )
        np.testing.assert_array_equal(
            got[s][:, cols], np.asarray(ref)[:, cols]
        )
        np.testing.assert_array_equal(vox[s], np.asarray(st.voxel_acc))
    print(f"proc {pid}: cross-process fleet replay bit-exact", flush=True)

    # --- streaming service, multi-controller: each process feeds ONLY
    # its own stream over the production stream-major mesh --------------
    from rplidar_ros2_driver_tpu.core.config import DriverParams
    from rplidar_ros2_driver_tpu.filters.chain import ScanFilterChain
    from rplidar_ros2_driver_tpu.parallel.service import ShardedFilterService

    params = DriverParams(
        filter_backend="cpu", filter_window=4,
        filter_chain=("clip", "median", "voxel"), voxel_grid_size=16,
    )
    mesh2 = multihost.make_global_mesh(stream=2)  # rows align to processes
    svc = ShardedFilterService(params, streams=2, mesh=mesh2, beams=64,
                               capacity=cap)
    ref_chain = ScanFilterChain(params, beams=64)
    wants = []
    for j in range(k):
        scan = per_stream[pid][j]  # this process's OWN stream only
        outs = svc.submit_local([scan])
        want = ref_chain.process_raw(
            scan["angle_q14"], scan["dist_q2"], scan["quality"]
        )
        wants.append(want)
        np.testing.assert_array_equal(
            outs[0].ranges, np.asarray(want.ranges)
        )
        np.testing.assert_array_equal(outs[0].voxel, np.asarray(want.voxel))
    print(f"proc {pid}: multi-controller service ticks bit-exact", flush=True)

    # --- pipelined multi-controller ticks: same stream, outputs shifted
    # by exactly one tick, flush drains the last one.  Both processes run
    # the pipelined variant together (mixed fleets would deadlock) -------
    svc_p = ShardedFilterService(params, streams=2, mesh=mesh2, beams=64,
                                 capacity=cap)
    prevs = []
    for j in range(k):
        scan = per_stream[pid][j]
        outs_p = svc_p.submit_local_pipelined([scan])
        prevs.append(outs_p[0])
    tail = svc_p.flush_pipelined()
    assert prevs[0] is None
    for j in range(1, k):
        np.testing.assert_array_equal(
            prevs[j].ranges, np.asarray(wants[j - 1].ranges)
        )
        np.testing.assert_array_equal(
            prevs[j].voxel, np.asarray(wants[j - 1].voxel)
        )
    np.testing.assert_array_equal(tail[0].ranges, np.asarray(wants[-1].ranges))
    assert svc_p.flush_pipelined() is None
    print(f"proc {pid}: pipelined local ticks bit-exact one tick late",
          flush=True)
    """
)


# First-class CPU CI arm (PR 17): the same two-process jax.distributed
# launch, but with NO cross-process collective execution — coordinator
# join, GLOBAL mesh construction, the local-stream ownership split, and
# a two-host FleetTopology relabel cycle are all capability-independent
# host/compiler-metadata work, so this arm must PASS wherever the
# coordination service runs (the collective-backed replay above keeps
# its capability probe).
_TOPOLOGY_WORKER = textwrap.dedent(
    """
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax
    jax.config.update("jax_platforms", "cpu")

    port, pid = sys.argv[1], int(sys.argv[2])
    os.environ["JAX_COORDINATOR_ADDRESS"] = f"127.0.0.1:{port}"
    os.environ["JAX_NUM_PROCESSES"] = "2"
    os.environ["JAX_PROCESS_ID"] = str(pid)

    from rplidar_ros2_driver_tpu.parallel import multihost
    assert multihost.is_configured()
    assert multihost.initialize()
    assert jax.process_count() == 2 and jax.device_count() == 4

    # global mesh spans both processes; no program is dispatched over
    # it here — construction + axis bookkeeping only
    mesh = multihost.make_global_mesh(stream=1)
    assert dict(mesh.shape) == {"stream": 1, "beam": 4}
    mesh2 = multihost.make_global_mesh(stream=2)
    assert dict(mesh2.shape) == {"stream": 2, "beam": 2}
    assert multihost.local_stream_slice(4) == (
        slice(0, 2) if pid == 0 else slice(2, 4)
    )
    print(f"proc {pid}: global mesh spans both processes", flush=True)

    # two-host pod relabel cycle: each jax process models one HOST of
    # a 4-shard pod.  Every move below is a live-lane relabel in the
    # shared topology — both processes compute the identical placement
    # (SPMD control plane), which is what lets a real pod-of-pods keep
    # one placement view without a coordinator round trip.
    from rplidar_ros2_driver_tpu.parallel.sharding import FleetTopology

    topo = FleetTopology(6, 4, 3, hosts=2)
    assert topo.hosts == 2 and topo.shards_per_host == 2
    assert [topo.host_of(s) for s in range(4)] == [0, 0, 1, 1]
    assert topo.shards_on_host(pid) == ([0, 1] if pid == 0 else [2, 3])
    before = {i: topo.coordinate(i) for i in range(6)}
    assert all(c is not None for c in before.values())

    # lose host 0's shard 0: victims must land on the same-host
    # sibling (shard 1) first — cross-host moves only on overflow
    victims = topo.streams_on(0)
    plan = topo.evacuate(0)
    assert {p[0] for p in plan} == set(victims)
    # the same-host sibling fills before any victim crosses hosts
    if any(topo.host_of(dst) != 0 for _v, dst, _l in plan):
        assert len(topo.streams_on(1)) == 3
    assert any(topo.host_of(dst) == 0 for _v, dst, _l in plan)
    # re-admit: movers rebalance back, no stream left unhosted
    moves = topo.rebalance_into(0)
    assert topo.unhosted() == []
    assert len(topo.streams_on(0)) > 0
    loads = [len(topo.streams_on(s)) for s in range(4)]
    assert max(loads) - min(loads) <= 1
    print(f"proc {pid}: two-host relabel cycle consistent", flush=True)
    """
)


def _free_port() -> int:
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _launch_once(port: int, worker: str = _WORKER):
    procs = [
        subprocess.Popen(
            [sys.executable, "-c", worker, str(port), str(i)],
            stdout=subprocess.PIPE,
            stderr=subprocess.STDOUT,
            text=True,
        )
        for i in range(2)
    ]
    outs = []
    try:
        for p in procs:
            try:
                out, _ = p.communicate(timeout=240)
            except subprocess.TimeoutExpired:
                # a stolen coordinator port leaves the non-coordinator
                # blocked in initialize(): kill and let the caller retry
                p.kill()
                out, _ = p.communicate()
            outs.append(out)
    finally:
        for p in procs:
            if p.poll() is None:
                p.kill()
    return procs, outs


def test_two_process_distributed_fleet_replay():
    # the free-port probe races against other processes binding it; one
    # retry with a fresh port covers the TOCTOU window on busy CI hosts
    for attempt in range(2):
        procs, outs = _launch_once(_free_port())
        if all(p.returncode == 0 for p in procs) or attempt == 1:
            break
    if any(p.returncode != 0 for p in procs) and any(
        "Multiprocess computations aren't implemented on the CPU backend"
        in out
        for out in outs
    ):
        # capability probe: this jaxlib's CPU backend has no
        # cross-process collective runtime (gloo path unavailable), so
        # the distributed replay CANNOT run here — the launch above IS
        # the probe, and only this exact signature downgrades to a
        # skip; any other failure still fails loudly
        pytest.skip(
            "CPU backend lacks multiprocess collectives "
            "(\"Multiprocess computations aren't implemented on the "
            "CPU backend\") — distributed replay needs a device "
            "runtime with cross-process support"
        )
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "fleet replay bit-exact" in out, out[-1000:]
        assert "service ticks bit-exact" in out, out[-1000:]
        assert "pipelined local ticks bit-exact one tick late" in out, out[-1000:]


def test_two_process_global_mesh_and_pod_topology():
    """First-class CPU CI arm: a real two-process jax.distributed
    launch (coordinator on localhost) that joins the process group,
    builds the GLOBAL (stream, beam) mesh spanning both processes, and
    runs the two-host FleetTopology relabel cycle — no cross-process
    collective is dispatched, so this must pass on any backend whose
    coordination service runs; there is no rig-weather skip here."""
    for attempt in range(2):
        procs, outs = _launch_once(_free_port(), worker=_TOPOLOGY_WORKER)
        if all(p.returncode == 0 for p in procs) or attempt == 1:
            break
    for i, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {i} failed:\n{out[-3000:]}"
        assert "global mesh spans both processes" in out, out[-1000:]
        assert "two-host relabel cycle consistent" in out, out[-1000:]
