"""Protocol layer: command encoding, response framing, loop mode, CRC."""

import zlib

import numpy as np
import pytest

from rplidar_ros2_driver_tpu.protocol import (
    Ans,
    AnsHeader,
    Cmd,
    ResponseDecoder,
    crc32_padded,
    encode_command,
)


class TestEncodeCommand:
    def test_simple_command_is_two_bytes(self):
        assert encode_command(Cmd.STOP) == bytes([0xA5, 0x25])
        assert encode_command(Cmd.SCAN) == bytes([0xA5, 0x20])
        assert encode_command(Cmd.RESET) == bytes([0xA5, 0x40])

    def test_payload_command_has_size_and_checksum(self):
        payload = bytes([0x00, 0x01, 0x00, 0x00, 0x00])
        pkt = encode_command(Cmd.EXPRESS_SCAN, payload)
        assert pkt[0] == 0xA5
        assert pkt[1] == 0x82
        assert pkt[2] == len(payload)
        checksum = 0
        for b in pkt[:-1]:
            checksum ^= b
        assert pkt[-1] == checksum

    def test_payload_on_payloadless_command_rejected(self):
        with pytest.raises(ValueError):
            encode_command(Cmd.STOP, b"\x01")


class TestResponseDecoder:
    def _header(self, ans, n, loop=False):
        return AnsHeader(ans_type=int(ans), payload_len=n, is_loop=loop).encode()

    def test_single_response(self):
        dec = ResponseDecoder()
        payload = bytes(range(20))
        dec.feed(self._header(Ans.DEVINFO, 20) + payload)
        assert dec.messages == [(int(Ans.DEVINFO), payload, False)]

    def test_split_across_chunks(self):
        dec = ResponseDecoder()
        buf = self._header(Ans.DEVHEALTH, 3) + b"\x00\x01\x02"
        for i in range(len(buf)):
            dec.feed(buf[i : i + 1])
        assert dec.messages == [(int(Ans.DEVHEALTH), b"\x00\x01\x02", False)]

    def test_loop_mode_reemits_payloads(self):
        dec = ResponseDecoder()
        dec.feed(self._header(Ans.MEASUREMENT, 5, loop=True))
        dec.feed(bytes(15))  # 3 complete 5-byte nodes
        assert len(dec.messages) == 3
        assert all(loop for (_, _, loop) in dec.messages)
        # loop mode persists until reset
        dec.feed(bytes(5))
        assert len(dec.messages) == 4
        dec.exit_loop_mode()
        dec.feed(bytes(5))  # garbage, no header
        assert len(dec.messages) == 4

    def test_garbage_before_sync_is_skipped(self):
        dec = ResponseDecoder()
        dec.feed(b"\xff\x00\xa5" + self._header(Ans.DEVINFO, 1) + b"\x42")
        assert dec.messages == [(int(Ans.DEVINFO), b"\x42", False)]

    def test_lone_sync_byte_straddling_chunks(self):
        dec = ResponseDecoder()
        hdr = self._header(Ans.DEVINFO, 2)
        dec.feed(b"\x00" + hdr[:1])
        dec.feed(hdr[1:] + b"\xaa\xbb")
        assert dec.messages == [(int(Ans.DEVINFO), b"\xaa\xbb", False)]

    def test_zero_payload_header(self):
        dec = ResponseDecoder()
        dec.feed(self._header(Ans.SET_LIDAR_CONF, 0))
        assert dec.messages == [(int(Ans.SET_LIDAR_CONF), b"", False)]

    def test_corrupt_size_resyncs(self):
        """A header claiming an implausibly large payload (wrong-baud noise
        containing A5 5A) must trigger a resync, not swallow the stream.
        Same rejection rule as the native codec (codec.cc kMaxSanePayload);
        this buffered decoder additionally recovers packets that begin
        inside the corrupt header (rescan from sync+1)."""
        import struct

        from rplidar_ros2_driver_tpu.protocol.codec import MAX_SANE_PAYLOAD

        dec = ResponseDecoder()
        corrupt = b"\xa5\x5a" + struct.pack("<I", MAX_SANE_PAYLOAD + 1) + b"\x04"
        good_payload = bytes(range(20))
        dec.feed(corrupt + self._header(Ans.DEVINFO, 20) + good_payload)
        assert dec.messages == [(int(Ans.DEVINFO), good_payload, False)]

    def test_max_sane_payload_accepted(self):
        """The cap itself is a legal size (boundary pins the > comparison)."""
        from rplidar_ros2_driver_tpu.protocol.codec import MAX_SANE_PAYLOAD

        dec = ResponseDecoder()
        payload = bytes(MAX_SANE_PAYLOAD)
        dec.feed(self._header(Ans.DEVINFO, MAX_SANE_PAYLOAD) + payload)
        assert dec.messages == [(int(Ans.DEVINFO), payload, False)]


from conftest import ScriptedTransceiver as _ScriptedTransceiver, wait_for


class TestStaleAnswerGuard:
    """A request that timed out leaves an answer 'owed'; the late answer
    must not complete the NEXT request of the same type (the conf protocol
    reuses one ans type for every per-mode query) — but exactly one is
    dropped, so a silent device costs one extra timeout, never a permanent
    drop loop (protocol/engine.py stale bookkeeping)."""

    def _engine(self):
        from rplidar_ros2_driver_tpu.protocol.engine import CommandEngine

        tx = _ScriptedTransceiver()
        eng = CommandEngine(tx)
        assert eng.start()
        return eng, tx

    def _background_request(self, eng, tx, timeout_s=5.0):
        """Start a request on a thread and wait (via the send the engine
        performs AFTER registering its pending slot) until it is in flight
        — deterministic sequencing, no bare sleeps."""
        import threading

        sends_before = len(tx.sent)
        result = {}

        def req():
            result["ans"] = eng.request(
                Cmd.GET_LIDAR_CONF, Ans.GET_LIDAR_CONF, timeout_s=timeout_s
            )

        t = threading.Thread(target=req)
        t.start()
        assert wait_for(lambda: len(tx.sent) > sends_before, 5.0)
        return t, result

    def test_late_answer_dropped_once(self):
        eng, tx = self._engine()
        try:
            # request 1: device stays silent -> timeout marks the type
            # stale for a window equal to the timeout (generous: 2 s, so
            # CI scheduling jitter cannot expire it mid-test)
            assert eng.request(Cmd.GET_LIDAR_CONF, Ans.GET_LIDAR_CONF,
                               timeout_s=2.0) is None
            # request 2 in flight; the LATE answer to request 1 lands first,
            # then the real answer — the engine must hand back the second
            t, result = self._background_request(eng, tx)
            tx.q.put((int(Ans.GET_LIDAR_CONF), b"LATE", False))   # dropped
            tx.q.put((int(Ans.GET_LIDAR_CONF), b"FRESH", False))  # completes
            t.join(10.0)
            assert result["ans"] == b"FRESH"
        finally:
            eng.stop()

    def test_boundary_answer_is_still_stale(self, monkeypatch):
        """Regression: a late answer arriving EXACTLY at the stale
        deadline used to slip through (`<` vs `<=`) and complete the
        next caller's request with the previous request's data.  Pin
        the boundary by routing a response at a monotonic clock frozen
        to the recorded deadline."""
        import time as time_mod

        eng, tx = self._engine()
        try:
            assert eng.request(Cmd.GET_LIDAR_CONF, Ans.GET_LIDAR_CONF,
                               timeout_s=0.05) is None
            deadline = eng._stale[int(Ans.GET_LIDAR_CONF)]
            # request 2 pending; the late answer lands at t == deadline
            t, result = self._background_request(eng, tx, timeout_s=2.0)
            from rplidar_ros2_driver_tpu.protocol import engine as engine_mod

            real_monotonic = time_mod.monotonic
            monkeypatch.setattr(
                engine_mod.time, "monotonic", lambda: deadline
            )
            try:
                eng._route_response(int(Ans.GET_LIDAR_CONF), b"LATE")
            finally:
                monkeypatch.setattr(
                    engine_mod.time, "monotonic", real_monotonic
                )
            # the boundary answer must have been dropped as stale; the
            # genuine answer then completes request 2
            tx.q.put((int(Ans.GET_LIDAR_CONF), b"FRESH", False))
            t.join(10.0)
            assert result["ans"] == b"FRESH"
        finally:
            eng.stop()

    def test_stale_window_expires(self):
        import time

        eng, tx = self._engine()
        try:
            assert eng.request(Cmd.GET_LIDAR_CONF, Ans.GET_LIDAR_CONF,
                               timeout_s=0.05) is None
            time.sleep(0.2)  # stale window (== timeout, 50 ms) elapses
            # an answer arriving after expiry flows normally
            t, result = self._background_request(eng, tx)
            tx.q.put((int(Ans.GET_LIDAR_CONF), b"OK", False))
            t.join(10.0)
            assert result["ans"] == b"OK"
        finally:
            eng.stop()


class TestCrc:
    def test_matches_zlib_with_device_padding(self):
        rng = np.random.default_rng(0)
        for n in (1, 3, 4, 7, 16, 773):
            data = rng.integers(0, 256, n, dtype=np.uint8).tobytes()
            pad = 4 - (n & 3)
            assert crc32_padded(data) == zlib.crc32(data + b"\x00" * pad)
